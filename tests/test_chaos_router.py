"""Chaos tier for the fleet router (ISSUE 19): the two acceptance
gates — a zero-drop rolling deploy under live Poisson traffic with a
SIGTERM mid-stream (every handle terminal, completed results bitwise-
equal to the sequential reference, the relaunched replica rejoining
with ExecutableStore hits == program count and ZERO recompiles) and
the breaker gate (injected consecutive admission failures trip a
replica OPEN within the threshold while traffic completes on the
survivors with zero caller-visible errors, then the half-open probe
restores it) — plus the wedged-replica faults composing with the
router's pressure signals."""
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flight_recorder
from paddle_tpu.distributed.resilience import GracefulShutdown
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.jit.compile_cache import ExecutableStore
from paddle_tpu.models.gpt import gpt
from paddle_tpu.serving import (FleetRouter, InProcessFleet,
                                RequestStatus, ServingEngine)
from paddle_tpu.serving.router import BREAKER_CLOSED, BREAKER_OPEN
from paddle_tpu.utils import fault_injection

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


def _spec():
    return [paddle.to_tensor(np.zeros((2, 12), np.int32))]


def _config(m, **serving_kw):
    cfg = (Config().from_layer(m, _spec())
           .enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                              max_batch=1))
    cfg.enable_serving(**serving_kw)
    return cfg


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    """ONE ExecutableStore shared by every engine and every relaunch in
    this module: the first build compiles the program set, siblings and
    rejoins deserialize — the warm-rejoin gate diffs its stats."""
    return ExecutableStore(str(tmp_path_factory.mktemp("chaos_exe")))


@pytest.fixture(scope="module")
def reference(tiny_gpt):
    pred = create_predictor(_config(tiny_gpt))
    return lambda p: pred.generate([p], max_new_tokens=8)[0]


def _factory(tiny_gpt, store, **kw):
    kw.setdefault("max_queue", 8)
    kw.setdefault("drain_timeout_s", 60.0)
    def build(name):
        return ServingEngine(_config(tiny_gpt, **kw), poll_every=1,
                             executable_store=store)
    return build


# ------------------------------------------------ rolling-deploy gate


def test_rolling_deploy_zero_drop(tiny_gpt, store, reference):
    """THE deploy gate: 3 in-process replicas, Poisson-bursty arrivals,
    SIGTERM mid-stream, one replica drained + relaunched under the
    live queue — zero dropped requests, every handle terminal, results
    bitwise-equal to the sequential reference, and the rejoin pays
    hits == program count / misses == 0 against the shared store."""
    fleet = InProcessFleet(_factory(tiny_gpt, store), n=3,
                           router_kw=dict(seed=0))
    flight_recorder.configure(capacity=512, on=True)
    try:
        rng = np.random.RandomState(19)
        prompts = [rng.randint(0, 512, 3 + int(rng.poisson(3.0)))
                   .astype(np.int32) for i in range(6)]
        killer = fault_injection.KillAfter(4, signal.SIGTERM)
        with GracefulShutdown(exit_on_save=False) as gs:
            handles = []
            for p in prompts:               # Poisson burst arrival: the
                handles.append(fleet.router.submit(p))   # queue is LIVE
                killer.step()               # SIGTERM mid-stream
            assert killer.fired and gs.preempted
            # the deploy rides the preemption: drain the replica the
            # signal doomed WHILE its queue holds work, relaunch it
            victim = handles[0].replica
            assert any(h.replica == victim and not h.done()
                       for h in handles)
            h0, m0 = store.stats["hits"], store.stats["misses"]
            fresh = fleet.rolling_deploy(victim)
            # warm rejoin: every program deserialized, ZERO compiles
            assert store.stats["hits"] - h0 == len(fresh._exes)
            assert store.stats["misses"] - m0 == 0
            assert len(fresh._exes) >= 3
            # the fleet keeps admitting after the deploy — including
            # onto the relaunched replica
            prompts += [rng.randint(0, 512, 4 + i).astype(np.int32)
                        for i in range(3)]
            handles += [fleet.router.submit(p) for p in prompts[6:]]
        # zero-drop: EVERY handle terminal and COMPLETED, bitwise equal
        for h, p in zip(handles, prompts):
            out = h.result(timeout=180)
            np.testing.assert_array_equal(out, reference(p))
            assert h.status is RequestStatus.COMPLETED
        stats = fleet.router.stats
        assert stats["rehomed"] >= 1        # the drain re-homed work
        assert stats["rejected"] == 0       # ...and nobody saw it
        kinds = [k for _, k, _ in flight_recorder.events()]
        assert "serve.router.drain" in kinds
        assert "serve.router.rejoin" in kinds
        assert "serve.router.reroute" in kinds
        assert fresh.stats["completed"] >= 0  # rejoined and serviceable
        probe = fleet.router.submit([7, 7, 7])
        assert probe.result(timeout=120).size == 8
    finally:
        flight_recorder.configure(
            capacity=flight_recorder.DEFAULT_CAPACITY, on=True)
        fleet.shutdown()


# ------------------------------------------------------- breaker gate


def test_breaker_gate_survives_admission_failures(tiny_gpt, store,
                                                  reference):
    """THE breaker gate: consecutive injected admission failures trip
    the victim OPEN within the threshold, every request completes on
    the survivor with zero caller-visible errors, and after the
    backoff the half-open probe restores the replica."""
    # base_s huge on purpose: the breaker must stay provably OPEN for
    # the whole survivor phase (a realistic 10ms backoff expires inside
    # one CPU decode and the replica self-heals before we can assert)
    fleet = InProcessFleet(_factory(tiny_gpt, store), n=2,
                           router_kw=dict(breaker_threshold=2,
                                          breaker_base_s=30.0,
                                          breaker_cap_s=60.0, seed=7))
    flight_recorder.configure(capacity=512, on=True)
    try:
        router = fleet.router
        victim = fleet["r0"]
        rec = router._replicas["r0"]
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, 512, 3 + i).astype(np.int32)
                   for i in range(6)]
        with fault_injection.fail_admission(victim, n=2) as fault:
            h0 = router.submit(prompts[0])
            # both injected failures burn on r0 (re-placed once while
            # the breaker is still counting), the second trips OPEN,
            # and the request completes on the survivor
            np.testing.assert_array_equal(h0.result(timeout=120),
                                          reference(prompts[0]))
            assert fault.triggered == 2
        assert rec.breaker.state == BREAKER_OPEN
        assert rec.breaker.trips == 1              # within threshold
        assert h0.replica == "r1" and h0.reroutes == 2
        # traffic keeps completing on the survivor: the OPEN replica
        # is provably out of rotation, zero caller-visible errors
        handles = [router.submit(p) for p in prompts[1:]]
        assert all(h.replica == "r1" for h in handles)
        for h, p in zip(handles, prompts[1:]):
            np.testing.assert_array_equal(h.result(timeout=120),
                                          reference(p))
        assert rec.breaker.state == BREAKER_OPEN   # still out
        stats = router.stats
        assert stats["breaker_trips"] == 1
        assert stats["rejected"] == 0
        reroutes = [f for _, k, f in flight_recorder.events()
                    if k == "serve.router.reroute"]
        assert len([f for f in reroutes
                    if f["reason"] == "admission_error"]) == 2
        assert all(h.status is RequestStatus.COMPLETED for h in handles)
        # serve the backoff (rewind it: no 30s sleep in CI), then the
        # single half-open probe lands on r0 and closes the breaker
        rec.breaker.open_until = time.monotonic() - 0.001
        probe = router.submit([9, 9])
        assert probe.replica == "r0"
        assert probe.result(timeout=120).size == 8
        assert rec.breaker.state == BREAKER_CLOSED
        kinds = [k for _, k, _ in flight_recorder.events()]
        assert "serve.router.breaker_open" in kinds
        assert "serve.router.breaker_probe" in kinds
        assert "serve.router.breaker_close" in kinds
    finally:
        flight_recorder.configure(
            capacity=flight_recorder.DEFAULT_CAPACITY, on=True)
        fleet.shutdown()


# ------------------------------------------------------ wedged replica


def test_wedge_replica_standalone(tiny_gpt, store):
    """wedge_replica suspends the poll loop: the handle's inline pump
    goes inert (result() times out instead of hanging forever), and
    release() restores service with no state lost."""
    eng = ServingEngine(_config(tiny_gpt), poll_every=1,
                        executable_store=store)
    try:
        h = eng.submit([1, 2, 3])
        with fault_injection.wedge_replica(eng):
            with pytest.raises(TimeoutError):
                h.result(timeout=0.3)
            assert not h.done()
        assert h.result(timeout=120).size == 8     # released: completes
    finally:
        eng.shutdown()


def test_wedge_replica_router_routes_around(tiny_gpt, store):
    """A wedged replica stops consuming its queue; once the queue hits
    its bound the health document flips not-ready and the router sends
    new traffic to the survivor — no new work lands on the wedge."""
    a = ServingEngine(_config(tiny_gpt, max_queue=1), poll_every=1,
                      executable_store=store)
    b = ServingEngine(_config(tiny_gpt, max_queue=4), poll_every=1,
                      executable_store=store)
    router = FleetRouter({"a": a, "b": b}, seed=0)
    try:
        wedge = fault_injection.wedge_replica(a)
        wedge.wedge()
        stuck = a.submit([1, 2, 3])     # fills a's queue at its bound
        assert not a.health()["ready"]
        routed = [router.submit([4, 5]), router.submit([6, 7, 8])]
        assert all(rr.replica == "b" for rr in routed)
        for rr in routed:
            assert rr.result(timeout=120).size == 8
        assert a.health()["queue_depth"] == 1      # untouched wedge
        wedge.release()
        assert stuck.result(timeout=120).size == 8
    finally:
        router.shutdown()
        a.shutdown()
        b.shutdown()
