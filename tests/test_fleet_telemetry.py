"""Fleet observability plane (ISSUE 15): delta-encoded snapshot
protocol, publisher/aggregator over the TCPStore, /fleet/metrics +
/fleet/healthz live HTTP (the 4-process acceptance gate: kill a rank
-> stale within the deadline, survivors keep scraping clean), the
concurrent-scrape hammer, clock-aligned trace merge, and the 3-process
chaos post-mortem."""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flight_recorder, metrics, monitor
from paddle_tpu.core.telemetry_server import (TelemetryServer,
                                              prometheus_text)
from paddle_tpu.distributed import fleet_telemetry as ft
from paddle_tpu.distributed.store import TCPStore
from tests.test_telemetry import parse_prometheus

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.disable()
    metrics.reset()
    yield
    metrics.disable()
    metrics.reset()


@pytest.fixture()
def store():
    s = TCPStore("127.0.0.1", 0, is_master=True)
    yield s
    s.shutdown_server()


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ------------------------------------------------------ delta protocol


class TestSnapshotDelta:
    def test_full_then_delta_roundtrip(self):
        metrics.enable()
        metrics.counter("t.c", kind="a").inc(3)
        metrics.gauge("t.g").set(2.5)
        h = metrics.histogram("t.h", bounds=(1.0, 10.0))
        h.observe(0.5)
        state, delta = metrics.snapshot_delta(None)
        assert delta["full"]
        mirror = metrics.apply_delta({}, delta)
        assert mirror["t.c{kind=a}"]["value"] == 3
        assert mirror["t.g"]["value"] == 2.5
        assert mirror["t.h"]["count"] == 1

        metrics.counter("t.c", kind="a").inc(2)
        h.observe(5.0)
        state2, d2 = metrics.snapshot_delta(state)
        assert not d2["full"]
        # unchanged metrics are omitted (the delta-encoding point)
        assert "t.g" not in d2["metrics"]
        assert d2["metrics"]["t.c{kind=a}"] == {"kind": "counter",
                                                "d": 2}
        metrics.apply_delta(mirror, d2)
        assert mirror["t.c{kind=a}"]["value"] == 5
        assert mirror["t.h"]["count"] == 2
        assert mirror["t.h"]["counts"] == \
            metrics._metric_state(h)["counts"]

    def test_reset_rebaselines_absolute(self):
        metrics.enable()
        c = metrics.counter("t.reset")
        c.inc(5)
        state, _ = metrics.snapshot_delta(None)
        c.reset()
        c.inc(1)
        _, delta = metrics.snapshot_delta(state)
        rec = delta["metrics"]["t.reset"]
        assert "d" not in rec and rec["value"] == 1  # absolute re-send
        mirror = metrics.apply_delta(
            {"t.reset": {"kind": "counter", "value": 5}}, delta)
        assert mirror["t.reset"]["value"] == 1

    def test_delta_for_unseen_metric_dropped(self):
        # a delta record arriving without its absolute baseline (missed
        # payload) must not corrupt the state — it is dropped, resync
        # re-sends absolute
        mirror = metrics.apply_delta(
            {}, {"full": False,
                 "metrics": {"t.x": {"kind": "counter", "d": 4}}})
        assert "t.x" not in mirror

    def test_quiet_registry_publishes_empty_delta(self):
        metrics.enable()
        metrics.counter("t.q").inc()
        state, _ = metrics.snapshot_delta(None)
        _, delta = metrics.snapshot_delta(state)
        assert delta == {"full": False, "metrics": {}}


# ----------------------------------------------- publisher + aggregator


class TestPublisherAggregator:
    def test_merge_labels_and_staleness(self, store):
        metrics.enable()
        monitor.record_serve_request("completed")
        monitor.record_serve_ttft(0.01)
        pub = ft.MetricsPublisher(store, period_s=0.2)
        agg = ft.FleetAggregator(store, period_s=0.2,
                                 stale_after_s=0.6, expected_ranks=1)
        pub.publish_now()
        agg.poll()
        reg = agg.fleet_registry()
        key = ("serve.requests{incarnation=0,rank=0,replica=0,"
               "status=completed}")
        assert key in reg and reg[key].value == 1
        # the merged histogram is a real Histogram the renderer accepts
        hkeys = [k for k in reg if k.startswith("serve.ttft{")]
        assert len(hkeys) == 1 and reg[hkeys[0]].count == 1
        assert reg["fleet.ranks_total"].value == 1
        assert reg["fleet.ranks_stale"].value == 0
        roll = agg.healthz()
        assert roll["ready"] and roll["ranks"]["0"]["ready"]
        # second publish is a DELTA; re-polling the same seq twice is
        # idempotent
        monitor.record_serve_request("completed")
        pub.publish_now()
        agg.poll()
        agg.poll()
        reg = agg.fleet_registry()
        assert reg[key].value == 2
        # silence past the deadline -> stale, MARKED not dropped
        time.sleep(0.8)
        agg.poll()
        roll = agg.healthz()
        assert not roll["ready"]
        assert roll["ranks_stale"] == 1
        assert roll["ranks"]["0"]["stale"] and \
            roll["ranks"]["0"]["reason"] == "stale"
        reg = agg.fleet_registry()
        assert reg[key].value == 2           # series survive staleness
        up_key = "fleet.rank_up{incarnation=0,rank=0}"
        assert reg[up_key].value == 0.0
        # ...and a fresh publish revives the rank
        pub.publish_now()
        agg.poll()
        assert agg.healthz()["ranks"]["0"]["stale"] is False

    def test_seq_gap_triggers_resync(self, store):
        metrics.enable()
        c = metrics.counter("t.gap")
        c.inc()
        pub = ft.MetricsPublisher(store, period_s=0.2)
        agg = ft.FleetAggregator(store, period_s=0.2)
        pub.publish_now()
        agg.poll()
        # two publishes between polls: the aggregator misses seq 1
        c.inc()
        pub.publish_now()
        c.inc()
        pub.publish_now()
        agg.poll()      # gap detected -> resync requested, not applied
        key = "t.gap{incarnation=0,rank=0,replica=0}"
        assert agg.fleet_registry()[key].value == 1
        pub.publish_now()   # answers the resync with a FULL snapshot
        agg.poll()
        assert agg.fleet_registry()[key].value == 3

    def test_new_incarnation_replaces_stream(self, store, monkeypatch):
        metrics.enable()
        metrics.counter("t.inc").inc(7)
        ft.MetricsPublisher(store, period_s=0.2).publish_now()
        agg = ft.FleetAggregator(store, period_s=0.2)
        agg.poll()
        # relaunched rank: new incarnation, counters restart
        metrics.reset()
        metrics.counter("t.inc").inc(1)
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "1")
        ft.MetricsPublisher(store, period_s=0.2).publish_now()
        agg.poll()
        reg = agg.fleet_registry()
        assert reg["t.inc{incarnation=1,rank=0,replica=0}"].value == 1
        assert not any("incarnation=0" in k for k in reg)
        assert agg.healthz()["ranks"]["0"]["incarnation"] == 1

    def test_publisher_excludes_fleet_meta_plane(self, store):
        metrics.enable()
        monitor.record_fleet_ranks(3, 1)    # aggregator-side series
        metrics.counter("t.mine").inc()
        payload = ft.MetricsPublisher(store, period_s=0.2).publish_now()
        names = list(payload["delta"]["metrics"])
        assert "t.mine" in names
        assert not any(n.startswith("fleet.") for n in names)

    def test_health_fn_failure_is_not_fatal(self, store):
        metrics.enable()

        def boom():
            raise RuntimeError("injected")

        pub = ft.MetricsPublisher(store, period_s=0.2, health_fn=boom)
        payload = pub.publish_now()
        assert payload["health"]["ready"] is False

    def test_failed_publish_never_loses_a_window(self, store,
                                                 monkeypatch):
        """A store blip mid-publish must not lose that window's
        deltas: the baseline commits only after the payload write
        succeeds, so the retry re-covers the window under the same
        seq."""
        metrics.enable()
        c = metrics.counter("t.blip")
        pub = ft.MetricsPublisher(store, period_s=0.2)
        agg = ft.FleetAggregator(store, period_s=0.2)
        c.inc()
        pub.publish_now()            # seq 0, full, value 1
        agg.poll()
        orig_set = store.set
        armed = {"on": True}

        def flaky_set(key, value):
            if armed["on"] and "/m/" in key:
                armed["on"] = False
                raise RuntimeError("injected store blip")
            return orig_set(key, value)

        monkeypatch.setattr(store, "set", flaky_set)
        c.inc()
        with pytest.raises(RuntimeError, match="injected"):
            pub.publish_now()        # window {+1} NOT committed
        c.inc()
        payload = pub.publish_now()  # retry covers BOTH increments
        assert payload["seq"] == 1
        assert payload["delta"]["metrics"]["t.blip"]["d"] == 2
        agg.poll()
        key = "t.blip{incarnation=0,rank=0,replica=0}"
        assert agg.fleet_registry()[key].value == 3

    def test_rank_collision_is_observable(self, store):
        """Two live processes publishing one (rank, incarnation)
        stream (hand-joined replicas without distinct replica ids):
        never a silent flap — errors.swallowed names the collision."""
        metrics.enable()
        ident_a = ft.FleetIdentity(rank=0, world_size=1, incarnation=0,
                                   replica="0", pid=111)
        ident_b = ft.FleetIdentity(rank=0, world_size=1, incarnation=0,
                                   replica="0", pid=222)
        agg = ft.FleetAggregator(store, period_s=0.2)
        ft.MetricsPublisher(store, identity=ident_a,
                            period_s=0.2).publish_now()
        agg.poll()
        ft.MetricsPublisher(store, identity=ident_b,
                            period_s=0.2).publish_now()
        agg.poll()
        assert metrics.snapshot()[
            "errors.swallowed{where=fleet.rank_collision}"][
            "value"] >= 1

    def test_numeric_replica_id_doubles_as_rank(self, monkeypatch):
        """Hand-joined replicas (no launcher): a numeric
        PADDLE_REPLICA_ID becomes the fleet rank so N replicas never
        clobber one stream."""
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        monkeypatch.setenv("PADDLE_REPLICA_ID", "5")
        ident = ft.local_identity()
        assert ident.rank == 5 and ident.replica == "5"
        monkeypatch.setenv("PADDLE_REPLICA_ID", "pod-a")   # label only
        assert ft.local_identity().rank == 0
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")       # launcher wins
        monkeypatch.setenv("PADDLE_REPLICA_ID", "5")
        assert ft.local_identity().rank == 2

    def test_refresh_never_blocks_behind_a_wedged_poll(self, store):
        """A store outage mid-poll must not wedge the scrape path:
        refresh() skips when another thread holds the poll round, and
        the view lock is never held across store I/O."""
        metrics.enable()
        ft.MetricsPublisher(store, period_s=0.2).publish_now()
        agg = ft.FleetAggregator(store, period_s=0.2)
        agg.poll()
        agg._last_poll = float("-inf")    # due for a refresh
        with agg._poll_lock:              # a poll round is "in flight"
            t0 = time.monotonic()
            agg.refresh()                 # returns immediately
            assert time.monotonic() - t0 < 0.5
            assert agg._last_poll == float("-inf")
            # the merged view stays readable while the poll is wedged
            assert agg.fleet_registry()["fleet.ranks_total"].value == 1
            assert agg.healthz()["ranks_total"] == 1

    def test_clock_handshake_records_offset(self, store):
        metrics.enable()
        pub = ft.MetricsPublisher(store, period_s=0.2)
        offset, rtt = pub.sync_clock()
        # same process as the store server: offset is sub-second, rtt
        # positive; the dump metadata carries the same number
        assert abs(offset) < 1e9 and rtt > 0
        assert flight_recorder.clock_offset_ns() == offset
        kinds = [k for _, k, _ in flight_recorder.events()]
        assert "fleet.clock_sync" in kinds


# ------------------------------------------------------ /fleet endpoints


class TestFleetEndpoints:
    def test_fleet_endpoints_404_without_aggregator(self):
        server = TelemetryServer(port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            for path in ("/fleet/metrics", "/fleet/healthz"):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _get(base + path)
                assert e.value.code == 404
        finally:
            server.stop()

    def test_fleet_metrics_and_healthz_over_http(self, store):
        metrics.enable()
        monitor.record_serve_request("completed")
        pub = ft.MetricsPublisher(store, period_s=0.2)
        pub.publish_now()
        agg = ft.FleetAggregator(store, period_s=0.2,
                                 stale_after_s=5.0, expected_ranks=1)
        server = TelemetryServer(port=0).start().attach_aggregator(agg)
        try:
            base = f"http://127.0.0.1:{server.port}"
            code, text = _get(base + "/fleet/metrics")
            assert code == 200
            parsed = parse_prometheus(text)
            assert parsed["samples"][
                ("serve_requests",
                 frozenset({("rank", "0"), ("replica", "0"),
                            ("incarnation", "0"),
                            ("status", "completed")}))] == 1
            assert parsed["samples"][("fleet_ranks_total",
                                      frozenset())] == 1
            # scrape hygiene rides on the fleet render too
            assert ("process_uptime_seconds", frozenset()) in \
                parsed["samples"]
            code, body = _get(base + "/fleet/healthz")
            roll = json.loads(body)
            assert code == 200 and roll["ready"] and \
                roll["ranks"]["0"]["ready"]
        finally:
            server.stop()


# ------------------------------------------- concurrent-scrape hammer


class TestScrapeHammer:
    def test_four_threads_against_mutating_registry(self, store):
        """Satellite: 4 threads hammering /metrics + /fleet/metrics
        while the registry and the aggregator mutate underneath — no
        exception, every render parseable, histogram cumulatives
        monotone."""
        metrics.enable()
        pub = ft.MetricsPublisher(store, period_s=0.05)
        agg = ft.FleetAggregator(store, period_s=0.05,
                                 stale_after_s=5.0)
        server = TelemetryServer(port=0).start().attach_aggregator(agg)
        pub.start()
        agg.start()
        stop = threading.Event()
        errors = []

        def mutate():
            i = 0
            while not stop.is_set():
                monitor.record_serve_request("completed")
                monitor.record_serve_ttft(0.001 * (1 + i % 50))
                monitor.record_serve_queue_depth(i % 7)
                i += 1
                time.sleep(0.0005)

        def scrape():
            base = f"http://127.0.0.1:{server.port}"
            try:
                for n in range(12):
                    for path in ("/metrics", "/fleet/metrics"):
                        code, text = _get(base + path)
                        assert code == 200
                        parsed = parse_prometheus(text)
                        buckets = sorted(
                            ((dict(k[1]).get("le"), v)
                             for k, v in parsed["samples"].items()
                             if k[0] == "serve_ttft_bucket"
                             and dict(k[1]).get("rank", "0") == "0"),
                            key=lambda kv: float("inf")
                            if kv[0] == "+Inf" else float(kv[0]))
                        vals = [v for _, v in buckets]
                        assert vals == sorted(vals), \
                            f"non-monotone cumulatives on {path}"
            except Exception as e:  # surfaced on the main thread
                errors.append(e)

        mut = threading.Thread(target=mutate, daemon=True)
        mut.start()
        scrapers = [threading.Thread(target=scrape, daemon=True)
                    for _ in range(4)]
        try:
            for t in scrapers:
                t.start()
            for t in scrapers:
                t.join(timeout=60)
                assert not t.is_alive(), "scraper wedged"
        finally:
            stop.set()
            mut.join(timeout=5)
            pub.stop(final_publish=False)
            agg.stop()
            server.stop()
        assert not errors, errors


# ------------------------------------------------ engine fleet wiring


class TestEngineFleetWiring:
    def test_engine_joins_fleet_from_env(self, store, monkeypatch):
        """PADDLE_FLEET_STORE on a ServingEngine: the replica
        publishes its health + serve.* series, and (as rank 0) its
        telemetry server grows the /fleet/* endpoints."""
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        monkeypatch.setenv("PADDLE_FLEET_STORE",
                           f"127.0.0.1:{store.port}")
        monkeypatch.setenv("PADDLE_JOB_ID", "engwire")
        monkeypatch.setenv("PADDLE_FLEET_METRICS_PERIOD_S", "0.2")
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=2,
                                  prefill_buckets=(16,), max_batch=1)
               .enable_serving(telemetry_port=0))
        eng = ServingEngine(cfg, poll_every=1)
        try:
            assert eng.fleet is not None
            assert eng.fleet.aggregator is not None   # rank 0 elected
            assert eng.telemetry.aggregator is eng.fleet.aggregator
            eng.submit(np.arange(1, 5, dtype=np.int32)).result(
                timeout=60)
            eng.fleet.publisher.publish_now()
            base = f"http://127.0.0.1:{eng.telemetry.port}"

            # the plane is eventually consistent: the constructor-time
            # publishes predate warmup (ready=False), and a seq gap
            # between the background publisher and aggregator threads
            # resolves via resync within a period or two — retry
            def rank0_ready():
                roll = json.loads(_get(base + "/fleet/healthz")[1])
                return roll["ranks"]["0"]["ready"]

            _wait_until(rank0_ready, 30,
                        "rank 0 ready in /fleet/healthz")
            roll = json.loads(_get(base + "/fleet/healthz")[1])
            assert "queue_depth" in roll["ranks"]["0"]

            key = ("serve_requests",
                   frozenset({("rank", "0"), ("replica", "0"),
                              ("incarnation", "0"),
                              ("status", "completed")}))

            def completed_visible():
                parsed = parse_prometheus(
                    _get(base + "/fleet/metrics")[1])
                return parsed["samples"].get(key, 0) >= 1

            _wait_until(completed_visible, 15,
                        "completed request in /fleet/metrics")
        finally:
            eng.shutdown()
        assert eng.fleet is None


# ----------------------------------------------------- 4-process e2e


_WORKER = """\
import os, sys, time
from paddle_tpu.core import metrics
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed import fleet_telemetry as ft

host, port = sys.argv[1], int(sys.argv[2])
store = TCPStore(host, port, timeout=30.0)
member = ft.start(store, aggregate=False, period_s=0.25)
while True:
    metrics.counter("gen.tokens").inc(1)
    time.sleep(0.05)
"""


def _spawn_worker(script, store_port, rank, world, extra_env=None,
                  args=()):
    env = dict(os.environ)
    env.update({"PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO_ROOT +
                os.pathsep + env.get("PYTHONPATH", "")})
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, script, "127.0.0.1", str(store_port), *args],
        env=env, cwd=REPO_ROOT)


def _wait_until(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


class TestFleetE2E:
    def test_four_process_job_one_pane_kill_one_rank(
            self, store, tmp_path, monkeypatch):
        """THE acceptance gate: a 4-process TCPStore job serves ONE
        /fleet/metrics with per-rank labeled series and a
        /fleet/healthz rollup over live HTTP; killing a rank flips it
        stale within the publish deadline while the remaining ranks
        keep scraping clean. Zero jax cross-process collectives."""
        monkeypatch.setenv("PADDLE_JOB_ID", "e2e4")
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        period, stale_after = 0.25, 1.0
        agg = ft.FleetAggregator(store, period_s=period,
                                 stale_after_s=stale_after,
                                 expected_ranks=4,
                                 namespace="__fleet/e2e4").start()
        server = TelemetryServer(port=0).start().attach_aggregator(agg)
        base = f"http://127.0.0.1:{server.port}"
        procs = [_spawn_worker(str(script), store.port, r, 4,
                               extra_env={"PADDLE_JOB_ID": "e2e4"})
                 for r in range(4)]
        try:
            def roll():
                return json.loads(_get(base + "/fleet/healthz")[1])

            _wait_until(
                lambda: roll()["ranks_total"] == 4
                and roll()["ranks_stale"] == 0, 30,
                "all 4 ranks publishing")
            assert roll()["ready"]

            code, text = _get(base + "/fleet/metrics")
            assert code == 200
            parsed = parse_prometheus(text)

            def tokens(snapshot, rank):
                return snapshot["samples"].get(
                    ("gen_tokens",
                     frozenset({("rank", str(rank)),
                                ("replica", str(rank)),
                                ("incarnation", "0")})), 0)

            for r in range(4):
                assert tokens(parsed, r) >= 1, f"rank {r} series missing"

            # SIGKILL rank 2: no graceful anything — the hard case
            procs[2].kill()
            procs[2].wait(timeout=10)
            t_kill = time.monotonic()
            _wait_until(lambda: roll()["ranks"]["2"]["stale"],
                        stale_after + 4 * period + 5.0,
                        "killed rank marked stale")
            flip_s = time.monotonic() - t_kill
            r = roll()
            assert not r["ready"] and r["ranks_stale"] == 1
            # survivors untouched — and still scraping clean
            assert not any(r["ranks"][str(k)]["stale"]
                           for k in (0, 1, 3))
            code, text2 = _get(base + "/fleet/metrics")
            assert code == 200
            parsed2 = parse_prometheus(text2)
            # the dead rank's series are STILL THERE (marked, not
            # dropped) and the survivors' counters kept advancing
            assert tokens(parsed2, 2) >= tokens(parsed, 2) > 0
            assert parsed2["samples"][
                ("fleet_rank_up",
                 frozenset({("rank", "2"), ("incarnation", "0")}))] == 0
            assert any(tokens(parsed2, k) > tokens(parsed, k)
                       for k in (0, 1, 3))
            # the flip honored the deadline (generous slack for a
            # loaded CI box: deadline + a few aggregation periods)
            assert flip_s < stale_after + 4 * period + 5.0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            agg.stop()
            server.stop()


# --------------------------------------------------------- trace merge


class TestTraceMerge:
    @staticmethod
    def _dump(rank, pid, offset_ns, events, restart=0):
        """Synthetic dump: anchor_perf=0 so ts µs IS local wall ns/1000
        above the 1s epoch."""
        anchor_wall = 1_000_000_000
        te = []
        for name, master_ns, args in events:
            local_wall = master_ns + offset_ns      # skewed local clock
            te.append({"name": name, "ph": "i", "s": "p",
                       "cat": "flight",
                       "ts": (local_wall - anchor_wall) / 1000.0
                       + anchor_wall / 1000.0,
                       "pid": pid, "tid": 0, "args": args})
        return {"traceEvents": te,
                "metadata": {"rank": rank, "restart_count": restart,
                             "pid": pid, "clock_offset_ns": offset_ns,
                             "anchor_wall_ns": anchor_wall,
                             "anchor_perf_ns": anchor_wall,
                             "reason": "test",
                             "dropped_events": 0}}

    def test_offset_adjustment_fixes_cross_rank_ordering(self):
        from tools.trace_merge import merge
        s = 1_000_000_000     # events sit 1s past the epoch anchor
        # victim (rank 1): clock runs 50ms AHEAD of the master; its
        # SIGTERM lands at master t=100ms. Peer (rank 0, clock true)
        # detects at master t=110ms. On RAW local clocks the victim's
        # event looks LATER (150ms vs 110ms) — the inversion the
        # offset adjustment must fix.
        victim = self._dump(1, 111, 50_000_000,
                            [("resilience.preemption",
                              s + 100_000_000, {"source": "signal"})])
        peer = self._dump(0, 222, 0,
                          [("resilience.preemption",
                            s + 110_000_000, {"source": "store"})])
        raw = {e["args"]["source"]: e["ts"]
               for e in victim["traceEvents"] + peer["traceEvents"]}
        assert raw["signal"] > raw["store"]          # inverted raw
        merged = merge([victim, peer])
        assert merged["metadata"]["clock_aligned"]
        ts = {e["args"]["source"]: e["ts"]
              for e in merged["traceEvents"] if e.get("ph") == "i"}
        assert ts["signal"] < ts["store"]            # fixed
        assert ts["store"] - ts["signal"] == pytest.approx(10_000.0)
        names = {e["args"]["name"] for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names == {"rank0.0 (pid 222, test)",
                         "rank1.0 (pid 111, test)"}

    def test_real_dumps_round_trip(self, tmp_path, monkeypatch):
        """Two live recorder dumps (different env identities +
        offsets) merge into one valid trace with one track each, and
        the filenames embed (rank, restart, pid)."""
        from tools.trace_merge import merge_paths
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        for rank, offset in ((0, 0), (1, 25_000_000)):
            monkeypatch.setenv("PADDLE_TRAINER_ID", str(rank))
            monkeypatch.setenv("PADDLE_RESTART_COUNT", "0")
            flight_recorder.configure(capacity=64, on=True)
            flight_recorder.set_clock_offset_ns(offset)
            flight_recorder.record("checkpoint.commit", step=rank)
            path = flight_recorder.dump(reason="postmortem")
            name = os.path.basename(path)
            assert name.startswith(
                f"flightrecorder_postmortem_r{rank}i0_p{os.getpid()}")
        flight_recorder.set_clock_offset_ns(0)
        merged = merge_paths([str(tmp_path)])
        assert set(merged["metadata"]["merged_tracks"]) == \
            {"rank0.0", "rank1.0"}
        assert merged["metadata"]["clock_aligned"]
        instants = [e for e in merged["traceEvents"]
                    if e.get("ph") == "i"
                    and e["name"] == "checkpoint.commit"]
        assert len(instants) == 2
        for e in merged["traceEvents"]:
            assert "name" in e and "ph" in e and "pid" in e

    def test_duplicate_track_from_two_jobs_rejected(self):
        from tools.trace_merge import merge
        a = self._dump(0, 111, 0, [("checkpoint.commit",
                                    1_100_000_000, {})])
        b = self._dump(0, 222, 0, [("checkpoint.commit",
                                    1_100_000_000, {})])
        with pytest.raises(ValueError, match="two different jobs"):
            merge([a, b])

    def test_two_dumps_of_one_process_dedupe_ring_overlap(self):
        """One process can dump twice (preemption auto-dump, then a
        later manual/crash dump): the shared ring prefix renders ONCE
        on the track, the later dump's new events still merge."""
        from tools.trace_merge import merge
        s = 1_000_000_000
        first = self._dump(0, 111, 0,
                           [("resilience.preemption",
                             s + 100_000_000, {"source": "signal"})])
        first["metadata"]["reason"] = "preemption"
        second = self._dump(0, 111, 0,
                            [("resilience.preemption",
                              s + 100_000_000, {"source": "signal"}),
                             ("checkpoint.commit",
                              s + 200_000_000, {"step": 7})])
        second["metadata"]["reason"] = "manual"
        merged = merge([first, second])
        instants = [e for e in merged["traceEvents"]
                    if e.get("ph") == "i"]
        assert len(instants) == 2      # overlap deduped, new event kept
        assert {e["name"] for e in instants} == \
            {"resilience.preemption", "checkpoint.commit"}
        track = merged["metadata"]["merged_tracks"]["rank0.0"]
        assert track["events"] == 2
        assert track["reason"] == "preemption+manual"


# ----------------------------------------------- chaos: fleet post-mortem


_CHAOS_WORKER = """\
import os, sys, time
from paddle_tpu.core import flight_recorder, goodput
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed import fleet_telemetry as ft
from paddle_tpu.distributed.resilience import GracefulShutdown
from paddle_tpu.utils.fault_injection import KillAfter

host, port = sys.argv[1], int(sys.argv[2])
rank = int(os.environ["PADDLE_TRAINER_ID"])
victim = rank == 1
store = TCPStore(host, port, timeout=30.0)
member = ft.start(store, aggregate=False, period_s=0.2)
member.publisher.sync_clock()
killer = KillAfter(6) if victim else None
ledger = goodput.GoodputLedger("train")
with GracefulShutdown(store=store, exit_on_save=victim,
                      store_poll_interval=0.05) as gs:
    with ledger:
        for step in range(500):
            time.sleep(0.02)                     # the "work"
            ledger.charge("compute", 0.02)
            if killer is not None:
                killer.step()
            if gs.check(step):   # victim exits inside check();
                #                  survivors detect via the store flag
                with ledger.timed("preemption_recovery"):
                    time.sleep(0.1)              # elastic re-rendezvous
                break
    snap = ledger.snapshot()
# only survivors reach here
store.set("__result/%d" % rank, snap)
time.sleep(4.0)    # stay live (publishing) while the test asserts
member.stop()
"""


@pytest.mark.chaos
class TestFleetPostMortem:
    def test_three_process_kill_one_post_mortem(self, store, tmp_path,
                                                monkeypatch):
        """Satellite chaos gate: 3-process TCPStore job, SIGTERM kills
        rank 1 mid-run (KillAfter). Assert (a) the aggregator marks
        the victim stale while the survivors stay live (never
        dropped), (b) the merged trace carries the victim's preemption
        event (source=signal) ordered before the peers' detection
        events (source=store), (c) the survivors' recovery wall time
        landed in their preemption_recovery goodput bucket, with the
        ledger invariant holding."""
        monkeypatch.setenv("PADDLE_JOB_ID", "chaos3")
        dump_dir = tmp_path / "dumps"
        dump_dir.mkdir()
        script = tmp_path / "worker.py"
        script.write_text(_CHAOS_WORKER)
        agg = ft.FleetAggregator(store, period_s=0.2,
                                 stale_after_s=0.8, expected_ranks=3,
                                 namespace="__fleet/chaos3").start()
        procs = [_spawn_worker(
            str(script), store.port, r, 3,
            extra_env={"PADDLE_JOB_ID": "chaos3",
                       "PADDLE_FLIGHT_RECORDER_DIR": str(dump_dir)})
            for r in range(3)]
        try:
            # victim exits with the elastic code once check() ran its
            # emergency path
            assert procs[1].wait(timeout=60) == 101
            _wait_until(
                lambda: (lambda h: h["ranks_total"] == 3
                         and h["ranks"]["1"]["stale"]
                         and not h["ranks"]["0"]["stale"]
                         and not h["ranks"]["2"]["stale"])(
                    (agg.poll(), agg.healthz())[1]),
                15, "victim stale beside live survivors")
            # stale is MARKED, not dropped: the victim's series remain
            reg = agg.fleet_registry()
            assert any("rank=1" in k for k in reg)
            for p in (procs[0], procs[2]):
                assert p.wait(timeout=60) == 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
            agg.stop()

        # ---- (b) the merged post-mortem: every rank auto-dumped on
        # preemption; one clock-aligned timeline orders the SIGTERM
        # before the detections
        from tools.trace_merge import merge_paths
        merged = merge_paths([str(dump_dir)])
        assert merged["metadata"]["clock_aligned"]
        tracks = merged["metadata"]["merged_tracks"]
        assert set(tracks) == {"rank0.0", "rank1.0", "rank2.0"}
        pre = [(e["pid"], e["ts"], e["args"]["source"])
               for e in merged["traceEvents"]
               if e.get("name") == "resilience.preemption"]
        by_source = {}
        for _, ts, source in pre:
            by_source.setdefault(source, []).append(ts)
        assert len(by_source["signal"]) == 1      # the victim
        assert len(by_source["store"]) == 2       # both peers detected
        assert by_source["signal"][0] < min(by_source["store"])
        # the victim's dump was the preemption auto-dump, identity in
        # the filename
        victim_dumps = [f for f in os.listdir(dump_dir)
                        if f.startswith("flightrecorder_preemption_r1i0")]
        assert victim_dumps, os.listdir(dump_dir)

        # ---- (c) survivors' goodput: recovery landed in its bucket,
        # buckets sum to wall
        for r in (0, 2):
            snap = store.get(f"__result/{r}", timeout=5.0)
            buckets = snap["buckets"]
            assert buckets["preemption_recovery"] >= 0.09, snap
            assert sum(buckets.values()) == \
                pytest.approx(snap["wall_s"], rel=0.05)
