"""Auto-parallel (ProcessMesh/shard_tensor/Engine) tests on the 8-device
CPU mesh (≈ unittests/auto_parallel/: completion/partition tests run
device-free on ProgramDesc; here annotations compile+run on the virtual
mesh, XLA SPMD doing completion/partition/reshard)."""
import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import auto_parallel as ap


def test_process_mesh_shapes():
    m = ap.ProcessMesh([2, 4], dim_names=["dp", "mp"])
    assert m.shape == (2, 4)
    assert m.jax_mesh.axis_names == ("dp", "mp")
    m1 = ap.ProcessMesh(list(range(8)), dim_names=["dp"])
    assert m1.shape == (8,)
    # [0] with one dim name is device id 0, NOT an empty shape-(0,) mesh
    m0 = ap.ProcessMesh([0], dim_names=["dp"])
    assert m0.shape == (1,)


def test_shard_tensor_places_array():
    mesh = ap.ProcessMesh([2, 4], dim_names=["x", "y"])
    t = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
    out = ap.shard_tensor(t, mesh, ["x", None])
    assert out.dist_attr["shard_spec"] == ["x", None]
    shard = out._data.sharding
    assert shard.spec[0] == "x"
    # value unchanged
    np.testing.assert_allclose(np.asarray(out._data),
                               np.arange(32).reshape(8, 4))


def test_shard_tensor_in_mesh_context():
    with ap.ProcessMesh([8], dim_names=["dp"]) as mesh:
        t = paddle.to_tensor(np.ones((8, 2), np.float32))
        out = ap.shard_tensor(t, shard_spec=["dp", None])
        assert out.dist_attr["process_mesh"] is mesh


def test_engine_fit_converges_dp():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    w = rng.randn(8, 1).astype(np.float32)
    y = x @ w

    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    mesh = ap.ProcessMesh([8], dim_names=["dp"])
    engine = ap.Engine(model=model,
                       loss=lambda out, lab: ((out - lab) ** 2).mean(),
                       optimizer=optimizer.Adam(learning_rate=0.01),
                       process_mesh=mesh)
    hist = engine.fit((x, y), epochs=8, batch_size=32, verbose=0)
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.5

    ev = engine.evaluate((x, y), batch_size=32)
    assert ev["eval_loss"] == pytest.approx(hist[-1]["loss"], rel=2.0)

    preds = engine.predict((x,), batch_size=32)
    assert preds[0].shape == (32, 1)


def test_engine_tp_annotation_matches_serial():
    """Column-sharded weight over mp axis == replicated math."""
    paddle.seed(1)
    mesh = ap.ProcessMesh([2, 4], dim_names=["dp", "mp"])
    model = nn.Linear(8, 8)
    # annotate: shard weight's output dim over mp
    ap.shard_tensor(model.weight, mesh, [None, "mp"])
    serial = model.weight.numpy().copy()

    x = np.random.RandomState(1).randn(4, 8).astype(np.float32)
    out = model(paddle.to_tensor(x)).numpy()
    ref = x @ serial + model.bias.numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_shard_layer_default_replicates():
    mesh = ap.ProcessMesh([8], dim_names=["dp"])
    model = nn.Linear(4, 4)
    ap.shard_layer(model, mesh)
    for _, p in model.named_parameters():
        assert p.dist_attr["shard_spec"] == [None] * len(p.shape)


def test_engine_save_load(tmp_path):
    paddle.seed(2)
    model = nn.Linear(4, 2)
    mesh = ap.ProcessMesh([8], dim_names=["dp"])
    eng = ap.Engine(model=model,
                    loss=lambda o, l: ((o - l) ** 2).mean(),
                    optimizer=optimizer.SGD(learning_rate=0.1),
                    process_mesh=mesh)
    x = np.ones((8, 4), np.float32)
    y = np.zeros((8, 2), np.float32)
    eng.fit((x, y), epochs=2, verbose=0)
    path = str(tmp_path / "ckpt")
    eng.save(path)

    model2 = nn.Linear(4, 2)
    eng2 = ap.Engine(model=model2, loss=eng.loss_fn,
                     optimizer=optimizer.SGD(learning_rate=0.1),
                     process_mesh=mesh)
    eng2.load(path)
    np.testing.assert_allclose(model2.weight.numpy(),
                               model.weight.numpy())


def test_estimate_cost():
    import jax.numpy as jnp

    def f(a, b):
        return jnp.matmul(a, b)

    a = jnp.ones((128, 256), jnp.float32)
    b = jnp.ones((256, 64), jnp.float32)
    cost = ap.estimate_cost(f, a, b)
    # 2*M*N*K flops
    assert cost["flops"] == pytest.approx(2 * 128 * 256 * 64, rel=0.5)


def test_process_mesh_flat_list_semantics():
    """1-D list is a shape iff dim_names covers every entry; otherwise
    process ids (reference semantics). Never depends on device count."""
    m = ap.ProcessMesh([2, 4], dim_names=["dp", "mp"])   # shape
    assert m.shape == (2, 4)
    m2 = ap.ProcessMesh([2, 4], dim_names=["x"])          # ids {2,4}
    assert m2.shape == (2,)
    assert list(np.asarray(m2.process_ids)) == [2, 4]
    with pytest.raises(ValueError):          # duplicate ids
        ap.ProcessMesh([[0, 1], [1, 2]], dim_names=["a", "b"])
    with pytest.raises(ValueError):          # out-of-range ids
        ap.ProcessMesh(list(range(16)), dim_names=["dp"])


def test_engine_empty_epoch_warns_not_crashes():
    paddle.seed(0)
    model = nn.Linear(4, 2)
    eng = ap.Engine(model=model,
                    loss=lambda o, l: ((o - l) ** 2).mean(),
                    optimizer=optimizer.SGD(learning_rate=0.1),
                    process_mesh=ap.ProcessMesh([8], dim_names=["dp"]))
    x = np.ones((4, 4), np.float32)   # 4 samples < batch_size 16
    y = np.zeros((4, 2), np.float32)
    with pytest.warns(UserWarning):
        hist = eng.fit((x, y), batch_size=16, epochs=1, verbose=0)
    assert hist[0]["steps"] == 0 and hist[0]["loss"] is None


def test_engine_predict_tuple_outputs_and_partial_batch():
    paddle.seed(0)

    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 2)
            self.b = nn.Linear(4, 3)

        def forward(self, x):
            return self.a(x), self.b(x)

    eng = ap.Engine(model=TwoHead(),
                    process_mesh=ap.ProcessMesh([8], dim_names=["dp"]))
    x = np.ones((10, 4), np.float32)  # 10 = 8 + partial 2
    outs = eng.predict((x,), batch_size=8)
    assert len(outs) == 2              # full + partial batch, none dropped
    a0, b0 = outs[0]
    assert a0.shape == (8, 2) and b0.shape == (8, 3)
    a1, b1 = outs[1]
    assert a1.shape == (2, 2) and b1.shape == (2, 3)
