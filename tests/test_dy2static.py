"""dy2static AST-conversion tests (reference:
unittests/dygraph_to_static/test_ifelse.py, test_loop.py analogs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import (convert_ifelse, convert_to_static,
                                      convert_while_loop, declarative)


# --------------------------------------------------------- runtime helpers
def test_convert_ifelse_eager_python_bool():
    out = convert_ifelse(True, lambda: (1,), lambda: (2,))
    assert out == (1,)
    out = convert_ifelse(paddle.to_tensor(0.0) > 1.0,
                         lambda: (paddle.ones([2]),),
                         lambda: (paddle.zeros([2]),))
    np.testing.assert_allclose(out[0].numpy(), 0.0)


def test_convert_while_eager():
    out = convert_while_loop(
        lambda i, s: i < 5,
        lambda i, s: (i + 1, s + i),
        (paddle.to_tensor(0), paddle.to_tensor(0)))
    assert int(out[1]) == 0 + 1 + 2 + 3 + 4


# -------------------------------------------------------------- converted
def test_declarative_if_traces_under_jit():
    import jax

    @declarative
    def f(x):
        if (x.sum() > 0.0):
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(f(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(f(neg).numpy(), [-2.0, -3.0])

    # under jax.jit the same function traces to ONE program w/ lax.cond
    traced = paddle.jit.to_static(f)
    np.testing.assert_allclose(traced(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(traced(neg).numpy(), [-2.0, -3.0])


def test_declarative_while_traces():
    @declarative
    def cumsum_until(x, limit):
        total = paddle.zeros([])
        i = paddle.zeros([], "int32")
        while total < limit:
            total = total + x[i]
            i = i + 1
        return total, i

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
    total, i = cumsum_until(x, paddle.to_tensor(5.0))
    assert float(total) == 6.0 and int(i) == 3

    traced = paddle.jit.to_static(cumsum_until)
    total2, i2 = traced(x, paddle.to_tensor(5.0))
    assert float(total2) == 6.0 and int(i2) == 3


def test_python_if_untouched():
    @declarative
    def f(x, flag):
        if flag:  # plain python bool stays python
            return x + 1.0
        return x - 1.0

    x = paddle.to_tensor(np.array([0.0], np.float32))
    np.testing.assert_allclose(f(x, True).numpy(), [1.0])
    np.testing.assert_allclose(f(x, False).numpy(), [-1.0])


def test_if_with_return_left_to_python():
    # returns inside branches can't cross lax.cond: stays python and
    # still works eagerly
    @declarative
    def f(x):
        if float(x.sum()) > 0:
            return x * 10.0
        return x

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.array([1.0], np.float32))).numpy(),
        [10.0])


def test_nested_if_in_while():
    @declarative
    def f(n):
        i = paddle.zeros([], "int32")
        acc = paddle.zeros([])
        while i < n:
            if (i % 2) == 0:
                acc = acc + 1.0
            else:
                acc = acc + 10.0
            i = i + 1
        return acc

    out = f(paddle.to_tensor(np.int32(4)))
    assert float(out) == 22.0  # 1 + 10 + 1 + 10
    traced = paddle.jit.to_static(f)
    assert float(traced(paddle.to_tensor(np.int32(4)))) == 22.0


def test_closure_function_converts():
    scale = 3.0

    @declarative
    def f(x):
        if (x.sum() > 0.0):
            y = x * scale
        else:
            y = x
        return y

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.array([2.0], np.float32))).numpy(),
        [6.0])


def test_unbound_var_raises_on_use():
    # a carried var the taken branch never binds must raise NameError
    # on later use, not silently bind an internal sentinel
    @declarative
    def f(t):
        if float(t.sum()) < 0:  # eager predicate
            z = t * 2
        return z

    with pytest.raises(NameError):
        f(paddle.ones([2]))


def test_unbound_var_in_untaken_branch_is_fine():
    @declarative
    def g(t):
        if float(t.sum()) < 0:
            z = t * 2
        return 1

    assert g(paddle.ones([2])) == 1


def test_nested_if_var_first_bound_inside_loop():
    # inner converted `if` first binds y inside a converted while body:
    # the cleanup must not delete a name the generated loop body still
    # returns (regression: UnboundLocalError at __jst_body's return)
    @declarative
    def f():
        i = 0
        while i < 3:
            if i > 1:
                y = 5
            i = i + 1
        return y

    assert f() == 5


# ---- round-2: for-loop + break/continue transforms (VERDICT Next #7) --

def test_for_over_tensor():
    @declarative
    def f(t):
        acc = paddle.zeros([])
        for row in t:
            acc = acc + row.sum()
        return acc

    x = np.arange(6, dtype=np.float32).reshape(3, 2)
    assert float(f(paddle.to_tensor(x))) == x.sum()


def test_for_range_static():
    @declarative
    def f(t):
        acc = t
        for i in range(3):
            acc = acc + i
        return acc

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.zeros(2, np.float32))).numpy(), [3.0, 3.0])


def test_for_range_traced_trip_count():
    # dynamic trip count: n is a traced scalar -> ONE lax.while_loop
    @declarative
    def f(x, n):
        acc = x
        i0 = paddle.zeros([], "int32")
        for i in range(n):
            acc = acc + 1.0
        return acc

    traced = paddle.jit.to_static(f)
    out = traced(paddle.zeros([2]), paddle.to_tensor(np.int32(5)))
    np.testing.assert_allclose(out.numpy(), [5.0, 5.0])
    out2 = traced(paddle.zeros([2]), paddle.to_tensor(np.int32(2)))
    np.testing.assert_allclose(out2.numpy(), [2.0, 2.0])


def test_while_with_break():
    @declarative
    def f(t):
        i = paddle.zeros([], "int32")
        acc = paddle.zeros([])
        while i < 100:
            if i >= t:
                break
            acc = acc + 2.0
            i = i + 1
        return acc

    assert float(f(paddle.to_tensor(np.int32(4)))) == 8.0
    traced = paddle.jit.to_static(f)
    assert float(traced(paddle.to_tensor(np.int32(4)))) == 8.0


def test_while_with_continue():
    @declarative
    def f(t):
        i = paddle.zeros([], "int32")
        acc = paddle.zeros([])
        while i < t:
            i = i + 1
            if (i % 2) == 0:
                continue
            acc = acc + 1.0
        return acc

    # odds in 1..6 -> 3
    assert float(f(paddle.to_tensor(np.int32(6)))) == 3.0
    traced = paddle.jit.to_static(f)
    assert float(traced(paddle.to_tensor(np.int32(6)))) == 3.0


def test_for_with_break_continue():
    @declarative
    def f(t):
        acc = paddle.zeros([])
        for row in t:
            if row.sum() < 0:
                continue
            if row.sum() > 90:
                break
            acc = acc + row.sum()
        return acc

    x = np.array([[1.0], [-5.0], [2.0], [100.0], [7.0]], np.float32)
    assert float(f(paddle.to_tensor(x))) == 3.0


def test_for_generator_falls_back():
    @declarative
    def f(t):
        acc = t
        for v in (x * 2 for x in [1, 2, 3]):
            acc = acc + v
        return acc

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.zeros(1, np.float32))).numpy(), [12.0])


def test_nested_for_loops():
    @declarative
    def f(t):
        acc = paddle.zeros([])
        for i in range(2):
            for j in range(3):
                acc = acc + t.sum()
        return acc

    assert float(f(paddle.ones([1]))) == 6.0


def test_break_under_with_falls_back_to_python():
    # a break inside `with` can't move into a generated function;
    # the loop must stay plain Python (regression: SyntaxError)
    import contextlib

    @declarative
    def f(t):
        i = 0
        while i < 5:
            with contextlib.nullcontext():
                break
        return t + i

    np.testing.assert_allclose(f(paddle.zeros([1])).numpy(), [0.0])


def test_for_over_python_list_traces():
    # python-sequence loops stay Python and unroll under tracing
    # (regression: desugar made the index a tracer, list[i] crashed)
    @declarative
    def f(t):
        acc = t
        for v in [1.0, 2.0, 3.0]:
            acc = acc + v
        return acc

    traced = paddle.jit.to_static(f)
    np.testing.assert_allclose(
        traced(paddle.zeros([2])).numpy(), [6.0, 6.0])


def test_static_range_loop_indexes_python_list():
    # static trip count keeps the Python loop: body may index python
    # containers with the concrete counter even under tracing
    @declarative
    def f(t):
        ws = [1.0, 10.0, 100.0]
        acc = t
        for i in range(3):
            acc = acc + ws[i]
        return acc

    traced = paddle.jit.to_static(f)
    np.testing.assert_allclose(
        traced(paddle.zeros([1])).numpy(), [111.0])
