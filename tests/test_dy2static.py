"""dy2static AST-conversion tests (reference:
unittests/dygraph_to_static/test_ifelse.py, test_loop.py analogs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import (convert_ifelse, convert_to_static,
                                      convert_while_loop, declarative)


# --------------------------------------------------------- runtime helpers
def test_convert_ifelse_eager_python_bool():
    out = convert_ifelse(True, lambda: (1,), lambda: (2,))
    assert out == (1,)
    out = convert_ifelse(paddle.to_tensor(0.0) > 1.0,
                         lambda: (paddle.ones([2]),),
                         lambda: (paddle.zeros([2]),))
    np.testing.assert_allclose(out[0].numpy(), 0.0)


def test_convert_while_eager():
    out = convert_while_loop(
        lambda i, s: i < 5,
        lambda i, s: (i + 1, s + i),
        (paddle.to_tensor(0), paddle.to_tensor(0)))
    assert int(out[1]) == 0 + 1 + 2 + 3 + 4


# -------------------------------------------------------------- converted
def test_declarative_if_traces_under_jit():
    import jax

    @declarative
    def f(x):
        if (x.sum() > 0.0):
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    pos = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    neg = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    np.testing.assert_allclose(f(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(f(neg).numpy(), [-2.0, -3.0])

    # under jax.jit the same function traces to ONE program w/ lax.cond
    traced = paddle.jit.to_static(f)
    np.testing.assert_allclose(traced(pos).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(traced(neg).numpy(), [-2.0, -3.0])


def test_declarative_while_traces():
    @declarative
    def cumsum_until(x, limit):
        total = paddle.zeros([])
        i = paddle.zeros([], "int32")
        while total < limit:
            total = total + x[i]
            i = i + 1
        return total, i

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
    total, i = cumsum_until(x, paddle.to_tensor(5.0))
    assert float(total) == 6.0 and int(i) == 3

    traced = paddle.jit.to_static(cumsum_until)
    total2, i2 = traced(x, paddle.to_tensor(5.0))
    assert float(total2) == 6.0 and int(i2) == 3


def test_python_if_untouched():
    @declarative
    def f(x, flag):
        if flag:  # plain python bool stays python
            return x + 1.0
        return x - 1.0

    x = paddle.to_tensor(np.array([0.0], np.float32))
    np.testing.assert_allclose(f(x, True).numpy(), [1.0])
    np.testing.assert_allclose(f(x, False).numpy(), [-1.0])


def test_if_with_return_left_to_python():
    # returns inside branches can't cross lax.cond: stays python and
    # still works eagerly
    @declarative
    def f(x):
        if float(x.sum()) > 0:
            return x * 10.0
        return x

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.array([1.0], np.float32))).numpy(),
        [10.0])


def test_nested_if_in_while():
    @declarative
    def f(n):
        i = paddle.zeros([], "int32")
        acc = paddle.zeros([])
        while i < n:
            if (i % 2) == 0:
                acc = acc + 1.0
            else:
                acc = acc + 10.0
            i = i + 1
        return acc

    out = f(paddle.to_tensor(np.int32(4)))
    assert float(out) == 22.0  # 1 + 10 + 1 + 10
    traced = paddle.jit.to_static(f)
    assert float(traced(paddle.to_tensor(np.int32(4)))) == 22.0


def test_closure_function_converts():
    scale = 3.0

    @declarative
    def f(x):
        if (x.sum() > 0.0):
            y = x * scale
        else:
            y = x
        return y

    np.testing.assert_allclose(
        f(paddle.to_tensor(np.array([2.0], np.float32))).numpy(),
        [6.0])


def test_unbound_var_raises_on_use():
    # a carried var the taken branch never binds must raise NameError
    # on later use, not silently bind an internal sentinel
    @declarative
    def f(t):
        if float(t.sum()) < 0:  # eager predicate
            z = t * 2
        return z

    with pytest.raises(NameError):
        f(paddle.ones([2]))


def test_unbound_var_in_untaken_branch_is_fine():
    @declarative
    def g(t):
        if float(t.sum()) < 0:
            z = t * 2
        return 1

    assert g(paddle.ones([2])) == 1


def test_nested_if_var_first_bound_inside_loop():
    # inner converted `if` first binds y inside a converted while body:
    # the cleanup must not delete a name the generated loop body still
    # returns (regression: UnboundLocalError at __jst_body's return)
    @declarative
    def f():
        i = 0
        while i < 3:
            if i > 1:
                y = 5
            i = i + 1
        return y

    assert f() == 5
