"""Calling parity for paddle.static.nn (VERDICT r2 Next #6): every name
in the frozen reference list is INVOKED, not just hasattr-checked.
Gated names are enumerated explicitly with their reason class; the gate
list is restricted to genuinely ragged/parameter-server APIs."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn

# name -> reason class. Only ragged (LoD-structure-mutating) and
# parameter-server APIs may be gated; everything else must run.
GATED = {
    "sequence_concat": "ragged",     # interleaves ragged rows: output
    "sequence_conv": "ragged",       # context windows cross ragged rows
    "sequence_enumerate": "ragged",  # emits ragged win_size ids
    "sequence_reshape": "ragged",    # redistributes ragged boundaries
    "sequence_scatter": "ragged",    # scatter into ragged offsets
    "sequence_slice": "ragged",      # per-seq dynamic-length slices
    "sparse_embedding": "parameter-server",
    "multi_box_head": "parameter-server-era SSD assembly",
}


def _r(*shape, seed=0, dtype="float32"):
    return paddle.to_tensor(
        np.random.RandomState(seed).randn(*shape).astype(dtype))


def _lengths():
    return paddle.to_tensor(np.array([2, 3, 1], np.int32))


SMOKES = {
    "fc": lambda: snn.fc(_r(2, 6), size=4),
    "embedding": lambda: snn.embedding(
        paddle.to_tensor(np.array([[1, 2]], np.int64)), size=(10, 4)),
    "conv2d": lambda: snn.conv2d(_r(1, 3, 8, 8), 4, 3),
    "conv2d_transpose": lambda: snn.conv2d_transpose(
        _r(1, 3, 8, 8), 4, filter_size=3),
    "conv3d": lambda: snn.conv3d(_r(1, 3, 4, 8, 8), 4, 3),
    "conv3d_transpose": lambda: snn.conv3d_transpose(
        _r(1, 3, 4, 8, 8), 4, filter_size=3),
    "batch_norm": lambda: snn.batch_norm(_r(2, 3, 8, 8)),
    "layer_norm": lambda: snn.layer_norm(_r(2, 6)),
    "group_norm": lambda: snn.group_norm(_r(2, 4, 8, 8), groups=2),
    "instance_norm": lambda: snn.instance_norm(_r(2, 3, 8, 8)),
    "data_norm": lambda: snn.data_norm(_r(8, 4)),
    "prelu": lambda: snn.prelu(_r(2, 3, 8, 8)),
    "spectral_norm": lambda: snn.spectral_norm(_r(6, 4)),
    "bilinear_tensor_product": lambda: snn.bilinear_tensor_product(
        _r(2, 3), _r(2, 5), size=4),
    "row_conv": lambda: snn.row_conv(_r(2, 5, 4), future_context_size=2),
    "crf_decoding": lambda: snn.crf_decoding(
        _r(1, 3, 4), None,
        length=paddle.to_tensor(np.array([3], np.int64)),
        transition=_r(6, 4, seed=2)),
    "py_func": lambda: snn.py_func(
        func=lambda a: np.asarray(a) * 2, x=_r(2, 2), out=_r(2, 2)),
    "nce": lambda: snn.nce(
        _r(4, 8), paddle.to_tensor(np.array([[1], [2], [3], [0]],
                                            np.int64)),
        num_total_classes=10),
    "case": lambda: snn.case(
        [(paddle.to_tensor(np.array(True)), lambda: _r(2))],
        default=lambda: _r(2, seed=1)),
    "switch_case": lambda: snn.switch_case(
        paddle.to_tensor(np.array(0, np.int32)),
        {0: lambda: _r(2), 1: lambda: _r(2, seed=1)}),
    "cond": lambda: paddle.static.nn.cond(
        paddle.to_tensor(np.array(True)), lambda: _r(2),
        lambda: _r(2, seed=1)),
    "while_loop": lambda: paddle.static.nn.while_loop(
        lambda i: i < 3, lambda i: [i + 1],
        [paddle.to_tensor(np.array(0, np.int64))]),
    "deform_conv2d": lambda: snn.deform_conv2d(
        _r(1, 3, 6, 6), paddle.zeros([1, 18, 6, 6]), None, 4, 3,
        padding=1),
    "sequence_pad": lambda: snn.sequence_pad(
        _r(6, 2), 0.0, length=_lengths()),
    "sequence_unpad": lambda: snn.sequence_unpad(
        _r(3, 3, 2), _lengths()),
    "sequence_reverse": lambda: snn.sequence_reverse(
        _r(6, 2), _lengths()),
    "sequence_first_step": lambda: snn.sequence_first_step(
        _r(6, 2), _lengths()),
    "sequence_last_step": lambda: snn.sequence_last_step(
        _r(6, 2), _lengths()),
    "sequence_pool": lambda: snn.sequence_pool(_r(6, 2), "max",
                                               length=_lengths()),
    "sequence_softmax": lambda: snn.sequence_softmax(_r(6), _lengths()),
    "sequence_expand": lambda: snn.sequence_expand(
        _r(6, 2), None, x_length=_lengths(), y_length=[1, 2, 0]),
    "sequence_expand_as": lambda: snn.sequence_expand_as(
        _r(3, 2), None, y_length=[2, 1, 3]),
}


def _static_rnn_smoke():
    x = _r(2, 4, 3)
    rnn = snn.StaticRNN()
    with rnn.step():
        w = rnn.step_input(x)
        prev = rnn.memory(shape=[3], batch_ref=x)
        h = paddle.tanh(w + prev)
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    return rnn()


SMOKES["StaticRNN"] = _static_rnn_smoke

ALL_NAMES = sorted(open(
    __file__.rsplit("/", 1)[0] + "/data_ref_static_nn_all.txt"
).read().split())


@pytest.mark.parametrize("name", ALL_NAMES)
def test_static_nn_name_callable(name):
    """Each reference static.nn name either RUNS (smoke invocation
    returns a value) or is an enumerated ragged/PS gate that raises
    NotImplementedError with a docstring'd reason."""
    if name in GATED:
        with pytest.raises(NotImplementedError):
            getattr(snn, name)()
        return
    assert name in SMOKES, f"no smoke invocation for {name}"
    out = SMOKES[name]()
    assert out is not None


def test_gate_list_is_bounded():
    # the honest-parity contract: gates only for ragged/PS names
    assert set(GATED) <= {
        "sequence_concat", "sequence_conv", "sequence_enumerate",
        "sequence_reshape", "sequence_scatter", "sequence_slice",
        "sparse_embedding", "multi_box_head"}


def test_static_rnn_matches_manual_scan():
    b, t, d = 3, 5, 4
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(b, t, d).astype(np.float32))
    rnn = snn.StaticRNN()
    with rnn.step():
        word = rnn.step_input(x)
        prev = rnn.memory(shape=[d], batch_ref=x)
        h = paddle.tanh(word + prev)
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    out = rnn()
    xv = np.asarray(x.data)
    m = np.zeros((b, d), np.float32)
    ref = []
    for ti in range(t):
        m = np.tanh(xv[:, ti] + m)
        ref.append(m)
    np.testing.assert_allclose(np.asarray(out.data),
                               np.stack(ref, axis=1), rtol=1e-3,
                               atol=1e-5)


def test_sequence_ops_golden():
    """Dense sequence ops vs hand-computed expectations on the packed
    (data, lengths) contract (reference fluid/layers/sequence_lod.py
    semantics with LoD replaced by the explicit lengths vector)."""
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(6, 2))
    ln = _lengths()
    out, length = snn.sequence_pad(x, -1.0, length=ln)
    assert out.shape == [3, 3, 2]
    np.testing.assert_allclose(np.asarray(out.data)[0],
                               [[0, 1], [2, 3], [-1, -1]])
    np.testing.assert_allclose(np.asarray(length.data), [2, 3, 1])
    np.testing.assert_allclose(
        np.asarray(snn.sequence_unpad(out, ln).data), np.asarray(x.data))
    np.testing.assert_allclose(
        np.asarray(snn.sequence_reverse(x, ln).data),
        [[2, 3], [0, 1], [8, 9], [6, 7], [4, 5], [10, 11]])
    np.testing.assert_allclose(
        np.asarray(snn.sequence_first_step(x, ln).data),
        [[0, 1], [4, 5], [10, 11]])
    np.testing.assert_allclose(
        np.asarray(snn.sequence_last_step(x, ln).data),
        [[2, 3], [8, 9], [10, 11]])
    np.testing.assert_allclose(
        np.asarray(snn.sequence_pool(x, "sum", length=ln).data),
        [[2, 4], [18, 21], [10, 11]])
    np.testing.assert_allclose(
        np.asarray(snn.sequence_pool(x, "average", length=ln).data),
        [[1, 2], [6, 7], [10, 11]])
    sm = np.asarray(snn.sequence_softmax(
        paddle.to_tensor(np.array([1., 2., 1., 1., 1., 9.],
                                  np.float32)), ln).data)
    np.testing.assert_allclose(
        [sm[:2].sum(), sm[2:5].sum(), sm[5]], [1, 1, 1], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(snn.sequence_expand(
            x, None, x_length=ln, y_length=[2, 0, 3]).data),
        [[0, 1], [2, 3], [0, 1], [2, 3],
         [10, 11], [10, 11], [10, 11]])
    np.testing.assert_allclose(
        np.asarray(snn.sequence_expand_as(
            paddle.to_tensor(np.array([[1.], [2.]], np.float32)), None,
            y_length=[2, 3]).data).ravel(),
        [1, 1, 2, 2, 2])


def test_bitwise_dunders():
    """__and__/__or__/__xor__/__invert__ (reference tensor/__init__.py
    magic_method_func) — restored to the frozen tensor-method list."""
    a = paddle.to_tensor(np.array([5, 3], np.int32))
    b = paddle.to_tensor(np.array([3, 1], np.int32))
    assert np.asarray((a & b).data).tolist() == [1, 1]
    assert np.asarray((a | b).data).tolist() == [7, 3]
    assert np.asarray((a ^ b).data).tolist() == [6, 2]
    assert np.asarray((~a).data).tolist() == [-6, -4]
    bt = paddle.to_tensor(np.array([True, False]))
    assert np.asarray((~bt).data).tolist() == [False, True]
    assert np.asarray((5 & b).data).tolist() == [1, 1]  # reflected


def test_static_rnn_sees_live_parameter_updates():
    """Replay must read CURRENT parameter values (optimizer steps
    between record and call), not build-time snapshots."""
    from paddle_tpu import nn
    paddle.seed(0)
    lin = nn.Linear(3, 3)
    x = _r(2, 4, 3)
    rnn = snn.StaticRNN()
    with rnn.step():
        w = rnn.step_input(x)
        h = rnn.memory(shape=[3], batch_ref=x)
        nh = paddle.tanh(lin(w) + h)
        rnn.update_memory(h, nh)
        rnn.step_output(nh)
    o1 = np.asarray(rnn().data)
    lin.weight.set_value(np.zeros((3, 3), np.float32))
    lin.bias.set_value(np.zeros(3, np.float32))
    o2 = np.asarray(rnn().data)
    assert not np.allclose(o1, o2)
    np.testing.assert_allclose(o2, 0.0)


def test_sequence_pool_requires_length():
    with pytest.raises(ValueError, match="length"):
        snn.sequence_pool(_r(6, 2), "sum")
