"""Cross-framework golden checks: paddle_tpu ops vs torch CPU — an
INDEPENDENT oracle (the registry sweep's finite-difference grads verify
internal consistency; these verify the semantics themselves match the
ecosystem's reference implementations). Reference analog: the OpTest
corpus's comparisons against authoritative kernels."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402

rng = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def _close(ours, theirs, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(
        np.asarray(ours.data if hasattr(ours, "data") else ours),
        theirs.detach().numpy(), rtol=rtol, atol=atol)


class TestConvPoolVsTorch:
    def test_conv2d(self):
        x = rng.randn(2, 3, 9, 9).astype(np.float32)
        w = rng.randn(5, 3, 3, 3).astype(np.float32)
        b = rng.randn(5).astype(np.float32)
        ours = F.conv2d(_t(x), _t(w), _t(b), stride=2, padding=1,
                        dilation=1)
        theirs = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=2, padding=1)
        _close(ours, theirs)

    def test_conv2d_grouped_dilated(self):
        x = rng.randn(1, 4, 8, 8).astype(np.float32)
        w = rng.randn(6, 2, 3, 3).astype(np.float32)
        ours = F.conv2d(_t(x), _t(w), groups=2, dilation=2, padding=2)
        theirs = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), groups=2, dilation=2,
            padding=2)
        _close(ours, theirs)

    def test_conv2d_transpose(self):
        x = rng.randn(1, 3, 5, 5).astype(np.float32)
        w = rng.randn(3, 4, 3, 3).astype(np.float32)
        ours = F.conv2d_transpose(_t(x), _t(w), stride=2, padding=1,
                                  output_padding=1)
        theirs = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1,
            output_padding=1)
        _close(ours, theirs)

    def test_pools(self):
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        _close(F.max_pool2d(_t(x), 2),
               torch.nn.functional.max_pool2d(torch.tensor(x), 2))
        _close(F.avg_pool2d(_t(x), 2, stride=2, padding=1),
               torch.nn.functional.avg_pool2d(
                   torch.tensor(x), 2, stride=2, padding=1,
                   count_include_pad=False))
        _close(F.adaptive_avg_pool2d(_t(x), 3),
               torch.nn.functional.adaptive_avg_pool2d(
                   torch.tensor(x), 3))

    def test_grid_sample(self):
        x = rng.randn(1, 2, 6, 6).astype(np.float32)
        g = (rng.rand(1, 4, 4, 2).astype(np.float32) * 2 - 1)
        ours = F.grid_sample(_t(x), _t(g), align_corners=True)
        theirs = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(g), align_corners=True)
        _close(ours, theirs, rtol=1e-4, atol=1e-4)


class TestLossesVsTorch:
    def test_cross_entropy_and_grad(self):
        logits = rng.randn(6, 5).astype(np.float32)
        labels = rng.randint(0, 5, 6).astype(np.int64)
        lt = _t(logits)
        lt.stop_gradient = False
        ours = F.cross_entropy(lt, _t(labels))
        ours.backward()
        tt = torch.tensor(logits, requires_grad=True)
        theirs = torch.nn.functional.cross_entropy(
            tt, torch.tensor(labels))
        theirs.backward()
        _close(ours, theirs)
        np.testing.assert_allclose(np.asarray(lt.grad.data),
                                   tt.grad.numpy(), rtol=1e-4,
                                   atol=1e-6)

    def test_nll_kl_bce(self):
        p = np.log(np.abs(rng.randn(4, 5)) + 0.2).astype(np.float32)
        lab = rng.randint(0, 5, 4).astype(np.int64)
        _close(F.nll_loss(_t(p), _t(lab)),
               torch.nn.functional.nll_loss(torch.tensor(p),
                                            torch.tensor(lab)))
        a = np.log(rng.rand(3, 4).astype(np.float32) + 0.1)
        b = rng.rand(3, 4).astype(np.float32)
        _close(F.kl_div(_t(a), _t(b), reduction="batchmean"),
               torch.nn.functional.kl_div(torch.tensor(a),
                                          torch.tensor(b),
                                          reduction="batchmean"))
        x = rng.randn(4, 3).astype(np.float32)
        y = rng.rand(4, 3).astype(np.float32)
        _close(F.binary_cross_entropy_with_logits(_t(x), _t(y)),
               torch.nn.functional.binary_cross_entropy_with_logits(
                   torch.tensor(x), torch.tensor(y)))

    def test_ctc_loss(self):
        T, B, C = 6, 2, 5
        logp = torch.log_softmax(torch.tensor(
            rng.randn(T, B, C).astype(np.float32)), dim=-1)
        targets = torch.tensor(
            rng.randint(1, C, (B, 3)).astype(np.int64))
        ilen = torch.tensor([T, T])
        tlen = torch.tensor([3, 2])
        theirs = torch.nn.functional.ctc_loss(
            logp, targets, ilen, tlen, blank=0, reduction="mean",
            zero_infinity=False)
        ours = F.ctc_loss(_t(logp.numpy()), _t(targets.numpy()),
                          _t(ilen.numpy()), _t(tlen.numpy()),
                          blank=0, reduction="mean")
        _close(ours, theirs, rtol=1e-4)

    def test_margin_and_triplet(self):
        a = rng.randn(4, 6).astype(np.float32)
        p = rng.randn(4, 6).astype(np.float32)
        n = rng.randn(4, 6).astype(np.float32)
        _close(F.triplet_margin_loss(_t(a), _t(p), _t(n), margin=0.7),
               torch.nn.functional.triplet_margin_loss(
                   torch.tensor(a), torch.tensor(p), torch.tensor(n),
                   margin=0.7))
        x1 = rng.randn(5).astype(np.float32)
        x2 = rng.randn(5).astype(np.float32)
        y = np.sign(rng.randn(5)).astype(np.float32)
        _close(F.margin_ranking_loss(_t(x1), _t(x2), _t(y),
                                     margin=0.2),
               torch.nn.functional.margin_ranking_loss(
                   torch.tensor(x1), torch.tensor(x2),
                   torch.tensor(y), margin=0.2))


class TestNormActivationsVsTorch:
    def test_layer_norm_and_grad(self):
        x = rng.randn(4, 6).astype(np.float32)
        w = rng.randn(6).astype(np.float32)
        b = rng.randn(6).astype(np.float32)
        xt = _t(x)
        xt.stop_gradient = False
        ours = F.layer_norm(xt, [6], _t(w), _t(b))
        ours.sum().backward()
        tt = torch.tensor(x, requires_grad=True)
        theirs = torch.nn.functional.layer_norm(
            tt, [6], torch.tensor(w), torch.tensor(b))
        theirs.sum().backward()
        _close(ours, theirs)
        np.testing.assert_allclose(np.asarray(xt.grad.data),
                                   tt.grad.numpy(), rtol=1e-3,
                                   atol=1e-5)

    def test_batch_group_instance_norm(self):
        x = rng.randn(3, 4, 5, 5).astype(np.float32)
        _close(F.batch_norm(_t(x), _t(np.zeros(4, np.float32)),
                            _t(np.ones(4, np.float32)),
                            training=True),
               torch.nn.functional.batch_norm(
                   torch.tensor(x), torch.zeros(4), torch.ones(4),
                   training=True), rtol=1e-3, atol=1e-4)
        _close(F.group_norm(_t(x), 2),
               torch.nn.functional.group_norm(torch.tensor(x), 2),
               rtol=1e-3, atol=1e-4)
        _close(F.instance_norm(_t(x)),
               torch.nn.functional.instance_norm(torch.tensor(x)),
               rtol=1e-3, atol=1e-4)

    def test_activations(self):
        x = rng.randn(3, 7).astype(np.float32)
        pairs = [
            (F.gelu(_t(x)), torch.nn.functional.gelu(
                torch.tensor(x))),
            (F.silu(_t(x)), torch.nn.functional.silu(
                torch.tensor(x))),
            (F.mish(_t(x)), torch.nn.functional.mish(
                torch.tensor(x))),
            (F.softplus(_t(x)), torch.nn.functional.softplus(
                torch.tensor(x))),
            (F.elu(_t(x), alpha=0.7), torch.nn.functional.elu(
                torch.tensor(x), alpha=0.7)),
            (F.hardswish(_t(x)), torch.nn.functional.hardswish(
                torch.tensor(x))),
            (F.log_softmax(_t(x), axis=-1),
             torch.nn.functional.log_softmax(torch.tensor(x),
                                             dim=-1)),
        ]
        for ours, theirs in pairs:
            _close(ours, theirs, rtol=1e-4, atol=1e-5)


class TestLinalgVsTorch:
    def test_solve_cholesky_det(self):
        m = rng.randn(4, 4).astype(np.float32)
        spd = (m @ m.T + 4 * np.eye(4)).astype(np.float32)
        b = rng.randn(4, 2).astype(np.float32)
        _close(paddle.linalg.solve(_t(spd), _t(b)),
               torch.linalg.solve(torch.tensor(spd),
                                  torch.tensor(b)), rtol=1e-3,
               atol=1e-4)
        _close(paddle.linalg.cholesky(_t(spd)),
               torch.linalg.cholesky(torch.tensor(spd)), rtol=1e-3,
               atol=1e-4)
        _close(paddle.linalg.det(_t(spd)),
               torch.linalg.det(torch.tensor(spd)), rtol=1e-3)

    def test_matrix_ops(self):
        a = rng.randn(3, 4).astype(np.float32)
        _close(paddle.linalg.pinv(_t(a)),
               torch.linalg.pinv(torch.tensor(a)), rtol=1e-3,
               atol=1e-4)
        sym = (lambda m: (m + m.T) / 2)(
            rng.randn(4, 4)).astype(np.float32)
        ours = paddle.linalg.eigvalsh(_t(sym))
        theirs = torch.linalg.eigvalsh(torch.tensor(sym))
        _close(ours, theirs, rtol=1e-3, atol=1e-4)
