"""Static HBM planner coverage (analysis.memory / ISSUE 14): exact-byte
golden fixtures (including the int8-cache + bf16-sidecar and
int4-packed-weight quant geometries), the donation credit, the
``mem.budget`` gate (audit kwarg + ``PADDLE_HBM_BUDGET``) with a seeded
undonated-cache regression proving it non-vacuous, predicted-vs-
measured slack on the CPU test-tiny decode and engine programs, the
ServingEngine budget fail-fast + health() headroom, and
``cross_check_memory``.

Documented CPU slack (asserted below): the plan never under-counts the
program's RESIDENT set (inputs held live + outputs produced), and it
over-predicts by at most ``_SLACK``x — the gap is transient
temporaries XLA materializes and frees between the live-array polls the
CPU backend's ``max_memory_allocated`` fallback can see.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import analysis, device, optimizer
from paddle_tpu.analysis import Severity
from paddle_tpu.profiler import metrics

# predicted peak within [1x, _SLACK x] of the measured resident set on
# the CPU test-tiny decode/engine programs (see module docstring)
_SLACK = 2.0


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.disable()
    metrics.reset()
    yield
    metrics.disable()
    metrics.reset()


def _tiny_gpt():
    from paddle_tpu.models.gpt import gpt
    paddle.seed(0)
    return gpt("test-tiny")


def _bytes_of(tree) -> int:
    return sum(
        int(np.prod(l.shape, dtype=np.int64))
        * np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(tree)
        if hasattr(l, "shape"))


# ------------------------------------------------------- byte arithmetic


class TestParseBytes:
    def test_suffixes_and_plain(self):
        assert analysis.parse_bytes(12345) == 12345
        assert analysis.parse_bytes("16GiB") == 16 << 30
        assert analysis.parse_bytes("16G") == 16 << 30
        assert analysis.parse_bytes("512M") == 512 << 20
        assert analysis.parse_bytes("1.5k") == 1536
        assert analysis.parse_bytes(" 64 KiB ") == 64 << 10

    def test_garbage_and_nonpositive_raise(self):
        # 'inf'/nan overflow int() with OverflowError — must fold into
        # ValueError or every swallow path built on it crashes instead
        for bad in ("lots", "", "-1G", 0, -5, "inf", "1e500",
                    float("inf"), float("nan")):
            with pytest.raises(ValueError):
                analysis.parse_bytes(bad)

    def test_env_resolution(self, monkeypatch):
        monkeypatch.delenv("PADDLE_HBM_BUDGET", raising=False)
        assert analysis.resolve_hbm_budget() is None
        assert analysis.resolve_hbm_budget("1M") == 1 << 20
        monkeypatch.setenv("PADDLE_HBM_BUDGET", "2MiB")
        assert analysis.resolve_hbm_budget() == 2 << 20
        assert analysis.resolve_hbm_budget("1M") == 1 << 20  # explicit wins
        monkeypatch.setenv("PADDLE_HBM_BUDGET", "off")
        assert analysis.resolve_hbm_budget() is None


# ------------------------------------------------------- golden fixtures


def _fixture_donated_update(p, x):
    return p - 0.1 * x.sum(), x * 2


class TestPlanGoldenFixtures:
    """Exact-byte assertions on minimal programs — the 8MiB
    baked-const precedent applied to the liveness scan."""

    def test_donation_credited_at_last_use(self):
        p = jnp.zeros((256, 256), jnp.float32)   # 262144 B
        x = jnp.ones((64, 64), jnp.float32)      # 16384 B
        don = analysis.audit(_fixture_donated_update, p, x, donate=(0,))
        und = analysis.audit(_fixture_donated_update, p, x)
        # undonated: old p + new p coexist — exactly one extra buffer
        assert und.memory.peak_bytes - don.memory.peak_bytes == 262144
        assert don.memory.arg_bytes == [262144, 16384]
        assert don.memory.donated_bytes == 262144
        # the peak live set names the buffers with provenance
        assert don.memory.top[0]["nbytes"] == 262144
        assert any("test_memory_plan.py" in t["source"]
                   for t in don.memory.top if t["source"])

    def test_consts_resident_whole_program(self):
        big = np.ones((512, 512), np.float32)    # 1 MiB baked const

        def prog(x):
            return x @ jnp.asarray(big)

        rep = analysis.audit(prog, jnp.ones((4, 512)),
                             const_budget_bytes=4 << 20)
        assert rep.memory.consts_bytes == 512 * 512 * 4
        assert rep.memory.phases["consts"] == 512 * 512 * 4

    def test_int8_cache_with_bf16_sidecars_exact_bytes(self):
        """The quant-geometry golden fixture: int8 K/V pages with
        per-(position, head) bf16 scale sidecars. Itemsize-based byte
        math must hold exactly, and the (shape, dtype) donation
        pairing must keep the int8 values and the bf16 sidecars in
        SEPARATE slots — a sidecar can never be credited against a
        value buffer."""
        L, B, T, H, D = 2, 2, 32, 2, 8
        kv_bytes = L * B * T * H * D * 1          # int8: 1 B/elem
        sc_bytes = L * B * T * H * 2              # bf16: 2 B/elem

        def update(k, v, ks, vs, nk, nv):
            k = k.at[:, :, 0].set(nk)
            v = v.at[:, :, 0].set(nv)
            ks = ks.at[:, :, 0].set(jnp.bfloat16(1.0))
            vs = vs.at[:, :, 0].set(jnp.bfloat16(1.0))
            return k, v, ks, vs

        sds = jax.ShapeDtypeStruct
        args = (sds((L, B, T, H, D), jnp.int8),
                sds((L, B, T, H, D), jnp.int8),
                sds((L, B, T, H), jnp.bfloat16),
                sds((L, B, T, H), jnp.bfloat16),
                sds((L, B, H, D), jnp.int8),
                sds((L, B, H, D), jnp.int8))
        und = analysis.audit(update, *args,
                             checks=("donation", "memory"),
                             min_donation_bytes=64)
        misses = und.by_check("donation.miss")
        assert sorted(f.data["bytes"] for f in misses) == \
            sorted([kv_bytes, kv_bytes, sc_bytes, sc_bytes])
        # per-operand byte totals are pure itemsize arithmetic
        assert und.memory.arg_bytes == [
            kv_bytes, kv_bytes, sc_bytes, sc_bytes,
            L * B * H * D, L * B * H * D]
        # donating everything repairs coverage AND halves the peak's
        # cache contribution (in-place update, no second copy)
        don = analysis.audit(update, *args, donate=(0, 1, 2, 3),
                             checks=("donation", "memory"),
                             min_donation_bytes=64)
        assert don.donation_coverage == 1.0
        assert und.memory.peak_bytes - don.memory.peak_bytes == \
            2 * kv_bytes + 2 * sc_bytes

    def test_repeated_inlined_subjaxpr_buffers_stay_distinct(self):
        """jax caches traced sub-jaxprs, so two call equations of the
        same jitted subfunction share Var OBJECTS — the scan must
        scope each invocation or it under-counts (an optimistic plan
        is the one failure mode a budget gate cannot have)."""
        g = jax.jit(lambda x: x + 1.0)

        def prog(x):
            return g(x), g(x)

        nb = 256 * 256 * 4
        rep = analysis.audit(prog, jnp.zeros((256, 256), jnp.float32),
                             checks=("memory",))
        assert rep.memory.out_bytes == 2 * nb
        # input + both (distinct) outputs resident at exit
        assert rep.memory.peak_bytes >= 3 * nb

    def test_repeated_subjaxpr_consts_counted_once(self):
        """The flip side of invocation scoping: a cached sub-jaxpr's
        BAKED consts exist once in the executable however many call
        sites reuse it — double-counting would raise false mem.budget
        ERRORs on programs reusing a jitted block with weights."""
        big = np.ones((512, 512), np.float32)            # 1 MiB
        g = jax.jit(lambda x: x @ jnp.asarray(big))

        def prog(x):
            return g(x), g(x) + 1.0

        rep = analysis.audit(prog, jnp.zeros((4, 512), jnp.float32),
                             checks=("memory",))
        assert rep.memory.consts_bytes == 512 * 512 * 4  # once, not 2x

    def test_int4_packed_weight_operand_exact_bytes(self):
        """int4 weights travel as two-nibbles-per-int8: the plan must
        count the PACKED bytes (in/2 x out x 1B), not the logical
        in x out."""
        IN, OUT = 64, 32

        def matmul(wp, scale, x):
            w = wp.astype(jnp.float32) * scale    # stands in for unpack
            return x @ w

        sds = jax.ShapeDtypeStruct
        rep = analysis.audit(
            matmul, sds((IN // 2, OUT), jnp.int8),
            sds((OUT,), jnp.float32), sds((4, IN // 2), jnp.float32),
            checks=("memory",))
        assert rep.memory.arg_bytes[0] == (IN // 2) * OUT * 1
        assert rep.memory.arg_bytes[1] == OUT * 4


# ----------------------------------------------------------- budget gate


class TestBudgetGate:
    def test_audit_kwarg_over_budget_is_error(self):
        p = jnp.zeros((256, 256), jnp.float32)
        x = jnp.ones((64, 64), jnp.float32)
        rep = analysis.audit(_fixture_donated_update, p, x,
                             hbm_budget=1024)
        hits = rep.by_check("mem.budget")
        assert hits and hits[0].severity == Severity.ERROR
        assert hits[0].data["budget_bytes"] == 1024
        assert hits[0].data["over_bytes"] == \
            rep.memory.peak_bytes - 1024
        with pytest.raises(analysis.AuditError, match="mem.budget"):
            rep.raise_on_error()
        # a budget above the peak passes and reports headroom
        ok = analysis.audit(_fixture_donated_update, p, x,
                            hbm_budget="1MiB")
        assert not ok.by_check("mem.budget")
        assert ok.memory.headroom_bytes == \
            (1 << 20) - ok.memory.peak_bytes

    def test_env_budget_gates_every_audit(self, monkeypatch):
        monkeypatch.setenv("PADDLE_HBM_BUDGET", "1KiB")
        rep = analysis.audit(_fixture_donated_update,
                             jnp.zeros((64, 64)), jnp.ones((8, 8)))
        assert rep.by_check("mem.budget")
        monkeypatch.setenv("PADDLE_HBM_BUDGET", "nonsense")
        rep = analysis.audit(_fixture_donated_update,
                             jnp.zeros((64, 64)), jnp.ones((8, 8)))
        bad = rep.by_check("mem.budget_invalid")
        assert bad and bad[0].severity == Severity.WARNING
        assert not rep.by_check("mem.budget")  # NOT silently enforced

    def test_undonated_cache_regression_is_caught(self):
        """THE seeded regression: dropping the decode program's cache
        donation grows the predicted peak by one full cache copy, and
        a budget sized between the two plans turns exactly that drop
        into an AuditError — the gate is not vacuous."""
        from paddle_tpu.generation.api import GenerationSession
        model = _tiny_gpt()
        sess = GenerationSession(model)
        _, donated = sess.audit(2, 16, 128)
        _, undonated = sess.audit(2, 16, 128, donate=())
        cache_bytes = _bytes_of(
            jax.tree_util.tree_leaves(donated.out_shape)[1:-1])
        grown = undonated.memory.peak_bytes - donated.memory.peak_bytes
        # the regression costs at least one K or V cache copy
        assert grown >= cache_bytes // 2
        budget = donated.memory.peak_bytes + grown // 2
        _, ok = sess.audit(2, 16, 128, hbm_budget=budget)
        ok.raise_on_error()
        with pytest.raises(analysis.AuditError, match="mem.budget"):
            sess.audit(2, 16, 128, donate=(),
                       hbm_budget=budget)[1].raise_on_error()

    def test_peak_gauge_and_violation_counter(self):
        metrics.enable()
        analysis.audit(_fixture_donated_update, jnp.zeros((64, 64)),
                       jnp.ones((8, 8)), hbm_budget=1024,
                       name="fixture")
        snap = metrics.snapshot()
        assert snap["analysis.mem.peak_bytes{program=fixture}"][
            "value"] > 1024
        assert snap["analysis.mem.budget_violations{program=fixture}"][
            "value"] == 1


# ------------------------------------------------- flagship plan threading


class TestFlagshipPlans:
    """Every flagship .audit() now carries a MemoryPlan whose floor is
    the program's own resident state — the audit-site threading gate."""

    def test_train_step_plan_covers_params_and_opt(self):
        model = _tiny_gpt()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        from paddle_tpu.jit.api import TrainStep
        step = TrainStep(model, opt,
                         lambda out, lbl: model.loss(out, lbl))
        ids = np.zeros((2, 16), np.int32)
        rep = step.audit(paddle.to_tensor(ids),
                         paddle.to_tensor(ids.astype(np.int64)))
        params_bytes = sum(_bytes_of(p._data)
                           for p in model.parameters())
        assert rep.memory is not None and rep.memory_checked
        # params (arg 0) exactly; peak holds params + adam moments
        assert rep.memory.arg_bytes[0] == params_bytes
        assert rep.memory.peak_bytes >= 3 * params_bytes

    def test_engine_audit_reports_all_carry_plans(self):
        eng = _tiny_engine()
        reports = eng.audit()
        for key, rep in reports.items():
            assert rep.memory is not None, key
            assert rep.memory.peak_bytes > 0, key
        # decode resident floor: weights + kv cache
        mp = eng.memory_plan()
        assert reports["decode"].memory.peak_bytes >= \
            mp["weights_bytes"] + mp["kv_cache_bytes"]


def _tiny_engine(warmup=False, **serving_kw):
    from paddle_tpu.inference import Config
    from paddle_tpu.serving import ServingEngine
    model = _tiny_gpt()
    spec = [paddle.to_tensor(np.zeros((2, 32), np.int32))]
    cfg = (Config().from_layer(model, spec)
           .enable_generation(max_new_tokens=8,
                              prefill_buckets=(16, 32), max_batch=2,
                              eos_token_id=None)
           .enable_serving(max_queue=8, **serving_kw))
    return ServingEngine(cfg, warmup=warmup)


# --------------------------------------------------- engine budget gate


class TestEngineBudget:
    def test_fail_fast_on_impossible_budget(self):
        with pytest.raises(ValueError, match="predicted peak HBM"):
            _tiny_engine(hbm_budget=100_000)

    def test_health_reports_headroom(self):
        eng = _tiny_engine(hbm_budget="1GiB")
        h = eng.health()
        assert h["hbm_budget"] == 1 << 30
        assert h["predicted_peak_bytes"] > 0
        assert h["predicted_headroom_bytes"] == \
            (1 << 30) - h["predicted_peak_bytes"]

    def test_memory_plan_breakdown_exact(self):
        eng = _tiny_engine()
        mp = eng.memory_plan()
        assert mp["kv_cache_bytes"] == _bytes_of(eng._cache)
        assert mp["weights_bytes"] == _bytes_of(eng._state)
        assert mp["predicted_peak_bytes"] >= mp["decode_peak_bytes"]
        # plan surfaces in health() once computed
        assert eng.health()["predicted_peak_bytes"] == \
            mp["predicted_peak_bytes"]

    def test_int8_engine_plans_smaller_cache(self):
        wide = _tiny_engine().memory_plan()
        quant = _tiny_engine(
            kv_cache_dtype="int8").memory_plan()
        # int8 values + bf16 sidecars < fp32 values (the quant
        # geometry flows through the planner end to end)
        assert quant["kv_cache_bytes"] < wide["kv_cache_bytes"]
        assert quant["predicted_peak_bytes"] < \
            wide["predicted_peak_bytes"]

    def test_garbage_env_budget_swallowed_observably(self, monkeypatch):
        monkeypatch.setenv("PADDLE_HBM_BUDGET", "garbage")
        metrics.enable()
        eng = _tiny_engine()   # must not raise
        assert eng.hbm_budget is None
        snap = metrics.snapshot()
        assert any(k.startswith("errors.swallowed") for k in snap)

    def test_garbage_explicit_budget_raises(self):
        """An operator who ASKED for a gate must get one: explicit
        garbage raises instead of silently serving ungated."""
        with pytest.raises(ValueError, match="unparseable byte size"):
            _tiny_engine(hbm_budget="16 gigs")


# ------------------------------------------------- predicted vs measured


class TestPredictedVsMeasured:
    """The plan against live-byte deltas from device.max_memory_
    allocated() on CPU: never below the resident set, within the
    documented _SLACK above it."""

    def _measure(self, fn, args, held):
        """(resident_bytes, outs): inputs in ``held`` stay referenced
        across the dispatch; resident = held bytes + the live-byte
        growth the outputs caused."""
        device.reset_peak_memory_stats()
        m0 = device.memory_allocated()
        outs = fn(*args)
        jax.block_until_ready(outs)
        m1 = device.max_memory_allocated()
        return _bytes_of(held) + max(0, m1 - m0), outs

    def test_decode_program_within_slack(self):
        from paddle_tpu.generation.api import (GenerationConfig,
                                               GenerationSession)
        model = _tiny_gpt()
        sess = GenerationSession(model)
        cfg = GenerationConfig()
        state = sess.state_values()
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, 512, (2, 16)),
            jnp.int32)
        plen = jnp.full((2,), 16, jnp.int32)
        key = jax.random.PRNGKey(0)
        tok, cache, key2, fin = sess.prefill(state, ids, plen, key,
                                             cfg, 128)
        jax.block_until_ready(tok)
        # CPU dispatch donates nothing: plan the same undonated program
        plan = analysis.plan_memory(
            sess._decode_fn, state, tok, cache, key2, fin, cfg,
            static_argnums=(5,), name="decode.measured")
        measured, _ = self._measure(
            lambda *a: sess.decode(*a, cfg),
            (state, tok, cache, key2, fin),
            (state, tok, cache, key2, fin))
        assert measured <= plan.peak_bytes <= _SLACK * measured, \
            (measured, plan.peak_bytes)

    def test_engine_decode_program_within_slack(self):
        eng = _tiny_engine()
        args = (eng._state, eng._tok, eng._cache, eng._key,
                eng._finished, eng._steps, eng._budget, eng._out_buf)
        plan = analysis.plan_memory(
            eng._step_fn, *args, eng._cfg, static_argnums=(8,),
            name="engine.decode.measured")
        measured, _ = self._measure(
            lambda *a: eng._step_jit(*a, eng._cfg), args, args)
        assert measured <= plan.peak_bytes <= _SLACK * measured, \
            (measured, plan.peak_bytes)


# ----------------------------------------------------------- the ledger


class TestProgramLedger:
    """The committed docs/programs.json drift gate (the docs/metrics.md
    precedent): a PR that silently drops a donation, bakes a constant,
    or grows any flagship program's peak HBM fails HERE with a diff
    naming the program and the field."""

    def test_manifest_current_and_update_byte_stable(self, monkeypatch):
        from paddle_tpu.analysis import ledger
        # hermetic: a developer's exported knobs must not alter the
        # regenerated programs (tools/ledger scrubs these the same way)
        for knob in ledger.SCRUB_ENV:
            monkeypatch.delenv(knob, raising=False)
        fresh = ledger.build_ledger()          # trace-only, built once
        diffs = ledger.check(fresh=fresh)
        assert not diffs, \
            "docs/programs.json drift (run `python -m tools.ledger " \
            "--update` if deliberate):\n  " + "\n  ".join(diffs)
        # --update on an unchanged tree is byte-stable: regenerated
        # text == the committed file, byte for byte
        with open(ledger.ledger_path(), "r", encoding="utf-8") as f:
            assert ledger.render(fresh) == f.read()

    def test_entry_fields_are_plain_data(self):
        """Ledger rows hold only JSON-stable scalars — every field
        round-trips json.dumps bit-exactly (floats pre-rounded)."""
        import json

        from paddle_tpu.analysis import ledger
        rep = analysis.audit(_fixture_donated_update,
                             jnp.zeros((64, 64)), jnp.ones((8, 8)),
                             donate=(0,))
        entry = ledger.entry_for(rep)
        assert entry["peak_bytes"] == rep.memory.peak_bytes
        assert entry["fingerprint"] == rep.fingerprint
        assert json.loads(json.dumps(entry)) == entry

    def test_fingerprint_tracks_structure_not_values(self):
        """Same shapes/program -> same fingerprint; a donation change
        or a shape change re-fingerprints (the drift key is
        structural)."""
        a = analysis.audit(_fixture_donated_update,
                           jnp.zeros((64, 64)), jnp.ones((8, 8)))
        b = analysis.audit(_fixture_donated_update,
                           jnp.full((64, 64), 3.0), jnp.ones((8, 8)))
        assert a.fingerprint == b.fingerprint
        c = analysis.audit(_fixture_donated_update,
                           jnp.zeros((64, 64)), jnp.ones((8, 8)),
                           donate=(0,))
        d = analysis.audit(_fixture_donated_update,
                           jnp.zeros((32, 32)), jnp.ones((8, 8)))
        assert len({a.fingerprint, c.fingerprint, d.fingerprint}) == 3


# ------------------------------------------------------ runtime crosscheck


class TestCrossCheckMemory:
    def test_refuses_unchecked_report(self):
        rep = analysis.audit(_fixture_donated_update,
                             jnp.zeros((8, 8)), jnp.ones((4, 4)),
                             checks=("host_sync",))
        assert not rep.memory_checked
        with pytest.raises(ValueError, match="without the 'memory'"):
            analysis.cross_check_memory(rep, measured_bytes=1)

    def test_flags_underestimate_only(self):
        rep = analysis.audit(_fixture_donated_update,
                             jnp.zeros((8, 8)), jnp.ones((4, 4)))
        peak = rep.memory.peak_bytes
        ok = analysis.cross_check_memory(rep, measured_bytes=peak)
        assert not ok.by_check("mem.underestimate")
        bad = analysis.cross_check_memory(rep,
                                          measured_bytes=peak * 10)
        hits = bad.by_check("mem.underestimate")
        assert hits and hits[0].severity == Severity.WARNING
        assert hits[0].data == {"measured": peak * 10,
                                "predicted": peak}
