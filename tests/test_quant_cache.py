"""Quantized KV cache + int4 weight path (ISSUE 13).

Covers: the quantize_kv unit contract (round trip, structurally-zero
saturation), QuantKVCache/QuantPagedKVCache protocol + verbatim
install parity, THE parity gates (bounded decode logit error AND
greedy eos-position parity vs the full-width cache on test-tiny), the
int8 engine bitwise-vs-sequential gate with zero post-warmup
retraces, int8 pages x shared-prefix COW (scales privatize with the
page), speculative ngram windows over the int8 cache (accept rate
within tolerance of full width), int4 pack/unpack round-trip units +
the int4-weight serving path, the dtype.quant_escape detector (fires
on unsanctioned widening, silent on the fused dequant sites), the
audit gates over every quantized program (zero ERRORs, donation 1.0),
the serve.cache.kv_dtype / gen.cache.quant.* metrics, the health()
capacity-in-tokens fields, and the PADDLE_KV_CACHE_DTYPE env knob.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.generation.kv_cache import (KVCache, QuantKVCache,
                                            quantize_kv,
                                            resolve_cache_dtype)
from paddle_tpu.generation.paged_cache import (PagedKVCache,
                                               QuantPagedKVCache)
from paddle_tpu.inference import Config
from paddle_tpu.inference.config import PrecisionType
from paddle_tpu.models.gpt import gpt
from paddle_tpu.serving import RequestParams, RequestStatus, ServingEngine


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


def _spec():
    return [paddle.to_tensor(np.zeros((2, 12), np.int32))]


def _config(m, *, max_new=8, buckets=(16,), max_batch=2, eos=None,
            speculative=None, kv_cache_dtype="int8", **serving_kw):
    cfg = (Config().from_layer(m, _spec())
           .enable_generation(max_new_tokens=max_new,
                              prefill_buckets=buckets,
                              max_batch=max_batch, eos_token_id=eos,
                              speculative=speculative,
                              kv_cache_dtype=kv_cache_dtype))
    cfg.enable_serving(**serving_kw)
    return cfg


@pytest.fixture(scope="module")
def int8_engine(tiny_gpt):
    """Shared dense int8-cache engine."""
    return ServingEngine(_config(tiny_gpt), poll_every=2)


@pytest.fixture(scope="module")
def int8_paged_engine(tiny_gpt):
    """Shared paged int8-cache engine (page 16)."""
    return ServingEngine(_config(tiny_gpt, buckets=(16, 32), paged=True,
                                 kv_page_size=16), poll_every=2)


@pytest.fixture(scope="module")
def int8_reference(tiny_gpt):
    """Sequential batch-1 int8-cache reference at the engines' bucket
    and cache geometry (the PR-8 gate shape: engine rows must be
    bitwise this)."""
    from paddle_tpu.generation.api import GenerationSession, generate
    sess = GenerationSession(tiny_gpt, cache_dtype="int8")

    def ref(prompt, budget, cache_len):
        bucket = 16 if prompt.size <= 16 else 32
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :prompt.size] = prompt
        out = generate(tiny_gpt, ids, budget,
                       prompt_len=np.array([prompt.size], np.int32),
                       cache_max_len=cache_len, session=sess)
        return np.asarray(out._data)[0]

    return ref


def _counter(name):
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


# ----------------------------------------------------------- cache unit


def test_quantize_kv_roundtrip_no_saturation():
    """Per-(token, head) absmax scales: dequant error bounded by half a
    step of the token's own absmax, and the saturation counter is
    structurally zero under round-to-nearest bf16 scales (the
    worst-case ratio 127 * (1 + 2^-9) < 127.5) — exactly what the
    gen.cache.quant.scale_clips guardrail asserts in production."""
    rng = np.random.RandomState(0)
    x = (rng.randn(3, 5, 2, 16) * rng.lognormal(0, 2, (3, 5, 2, 1))) \
        .astype(np.float32)
    q, s, clips = quantize_kv(jnp.asarray(x))
    assert q.dtype == jnp.int8 and s.dtype == jnp.bfloat16
    assert int(clips) == 0
    deq = np.asarray(q.astype(jnp.float32) * s.astype(jnp.float32)[..., None])
    absmax = np.abs(x).max(-1, keepdims=True)
    # half an int8 step of the token absmax + the bf16 scale rounding
    assert (np.abs(deq - x) <= absmax * (0.5 / 127 + 2 ** -8) + 1e-6).all()


def test_quant_cache_update_protocol():
    """QuantKVCache speaks the ring-cache protocol: scatter writes at
    kv_len quantize in place, scales land beside the values, and
    reset_rows/with_kv_len/copy_row_from preserve the quantized class
    (a wide cache must never silently reappear mid-stream)."""
    rng = np.random.RandomState(1)
    c = KVCache.create(2, 2, 8, 2, 4, cache_dtype="int8")
    assert isinstance(c, QuantKVCache) and c.cache_dtype == "int8"
    k = rng.randn(2, 3, 2, 4).astype(np.float32)
    v = rng.randn(2, 3, 2, 4).astype(np.float32)
    c = c.update(0, jnp.asarray(k), jnp.asarray(v), c.kv_len)
    deq = np.asarray(c.k[0].astype(jnp.float32)) * \
        np.asarray(c.k_scale[0].astype(jnp.float32))[..., None]
    np.testing.assert_allclose(deq[:, :3], k, atol=2e-2, rtol=2e-2)
    c2 = c.with_kv_len(3).reset_rows(np.array([1]))
    assert isinstance(c2, QuantKVCache)
    assert np.asarray(c2.kv_len).tolist() == [3, 0]
    # row copy is verbatim: int8 values + scales bitwise
    dst = KVCache.create(2, 2, 8, 2, 4, cache_dtype="int8")
    dst = dst.copy_row_from(c2, 0, 1)
    np.testing.assert_array_equal(np.asarray(dst.k[:, 1]),
                                  np.asarray(c2.k[:, 0]))
    np.testing.assert_array_equal(np.asarray(dst.k_scale[:, 1]),
                                  np.asarray(c2.k_scale[:, 0]))


def test_quant_paged_install_bitwise():
    """install_row routes a batch-1 quant row's int8 values AND scales
    through the page table verbatim (no requantization), and a
    subsequent paged update quantizes the SAME bits the dense update
    would — the cache-level facts that make engine admissions
    bitwise-reproducible (the engine tests below close the loop
    end-to-end)."""
    rng = np.random.RandomState(0)
    L, T, H, D, ps = 2, 64, 4, 16, 16
    row = KVCache.create(L, 1, T, H, D, cache_dtype="int8")
    for layer in range(L):
        row = row.update(layer, jnp.asarray(
            rng.randn(1, 10, H, D).astype(np.float32)), jnp.asarray(
            rng.randn(1, 10, H, D).astype(np.float32)), row.kv_len)
    row = row.with_kv_len(10)
    paged = PagedKVCache.create(L, 2, 16, ps, T // ps, H, D,
                                cache_dtype="int8")
    assert isinstance(paged, QuantPagedKVCache)
    table = jnp.asarray(np.array([1, 2, 3, 4], np.int32))
    paged = paged.install_row(row, 0, table, 0)
    tb = np.asarray(table)
    kp = np.asarray(paged.k)[:, tb].reshape(L, T, H, D)
    sp = np.asarray(paged.k_scale)[:, tb].reshape(L, T, H)
    np.testing.assert_array_equal(kp[:, :10], np.asarray(row.k)[:, 0, :10])
    np.testing.assert_array_equal(sp[:, :10],
                                  np.asarray(row.k_scale)[:, 0, :10])
    # the next decode write quantizes identical bits through the table
    k1 = rng.randn(1, 1, H, D).astype(np.float32)
    v1 = rng.randn(1, 1, H, D).astype(np.float32)
    drow = row.update(0, jnp.asarray(k1), jnp.asarray(v1), row.kv_len)
    prow = paged.with_kv_len(jnp.asarray(np.array([10, 0], np.int32)))
    prow = prow.update(0, jnp.asarray(np.concatenate([k1, k1])),
                       jnp.asarray(np.concatenate([v1, v1])),
                       prow.kv_len)
    kq = np.asarray(prow.k)[:, tb].reshape(L, T, H, D)
    sq = np.asarray(prow.k_scale)[:, tb].reshape(L, T, H)
    np.testing.assert_array_equal(kq[0, 10], np.asarray(drow.k)[0, 0, 10])
    np.testing.assert_array_equal(sq[0, 10],
                                  np.asarray(drow.k_scale)[0, 0, 10])


def test_quant_decode_kernel_interpret_parity():
    """The Pallas int8 decode kernel (interpret mode) against the XLA
    fused-dequant fallback — same scale-on-score-columns structure, so
    they agree to float tolerance (the TPU-vs-CPU parity contract the
    wide kernel already carries)."""
    from paddle_tpu.kernels.flash_attention import (_decode_pallas,
                                                    _decode_xla)
    rng = np.random.RandomState(2)
    B, T, D, sq = 2, 128, 64, 2
    k8 = rng.randint(-127, 128, (B, T, D)).astype(np.int8)
    v8 = rng.randint(-127, 128, (B, T, D)).astype(np.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (B, T))
                     .astype(np.float32)).astype(jnp.bfloat16)
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (B, T))
                     .astype(np.float32)).astype(jnp.bfloat16)
    q = rng.randn(B, sq, D).astype(np.float32)
    kv_len = jnp.asarray(np.array([37, 100], np.int32))
    args = (jnp.asarray(q), jnp.asarray(k8), jnp.asarray(v8), kv_len,
            float(D ** -0.5))
    ref = _decode_xla(*args, ks=ks, vs=vs)
    out = _decode_pallas(*args, k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


# --------------------------------------------- THE parity gates (tier-1)


def test_int8_logit_error_bounded(tiny_gpt):
    """Decode logits over the int8 cache stay within a calibrated
    bound of the full-width cache (measured ~3e-4 on test-tiny; gate
    at 10x headroom relative to the logit scale)."""
    ids = np.random.RandomState(0).randint(0, 512, (1, 24)) \
        .astype(np.int32)
    plen = Tensor(np.full((1,), 24, np.int32))
    _, cw = tiny_gpt.forward(Tensor(ids), use_cache=True,
                             prompt_len=plen, cache_max_len=128)
    _, cq = tiny_gpt.forward(Tensor(ids), use_cache=True,
                             prompt_len=plen, cache_max_len=128,
                             cache_dtype="int8")
    tok = Tensor(np.array([[3]], np.int32))
    lw, _ = tiny_gpt.forward(tok, cache=cw)
    lq, _ = tiny_gpt.forward(tok, cache=cq)
    a, b = np.asarray(lw._data), np.asarray(lq._data)
    assert np.abs(a - b).max() <= 0.01 * max(1.0, np.abs(a).max())


def test_int8_greedy_eos_position_parity(tiny_gpt):
    """Greedy generation over the int8 cache stops at the SAME eos
    position as the full-width cache on test-tiny (the PR-pattern
    parity gate: the quantization error must not move the argmax at
    any step before eos)."""
    ids = np.random.RandomState(5).randint(0, 512, (2, 20)) \
        .astype(np.int32)
    wide = np.asarray(tiny_gpt.generate(ids, max_new_tokens=16)._data)
    # pick the token the wide stream emits mid-sequence as eos, so the
    # parity test exercises a REAL stop
    row = 0
    eos = int(wide[row, 4])
    w = np.asarray(tiny_gpt.generate(
        ids, max_new_tokens=16, eos_token_id=eos)._data)
    q = np.asarray(tiny_gpt.generate(
        ids, max_new_tokens=16, eos_token_id=eos,
        kv_cache_dtype="int8")._data)
    w_eos = np.argmax(w[row] == eos)
    q_eos = np.argmax(q[row] == eos)
    assert (eos in w[row]) and (eos in q[row])
    assert w_eos == q_eos
    np.testing.assert_array_equal(w[row][:w_eos], q[row][:q_eos])
    # the other row's full streams must agree token-for-token up to
    # ITS first eos too (positions after a row's eos hold padding)
    other = 1 - row
    w_cut = np.argmax(w[other] == eos) if eos in w[other] else 16
    q_cut = np.argmax(q[other] == eos) if eos in q[other] else 16
    assert w_cut == q_cut
    np.testing.assert_array_equal(w[other][:w_cut], q[other][:q_cut])


def test_int8_engine_bitwise_and_zero_retrace(tiny_gpt, int8_engine,
                                              int8_reference):
    """The PR-8 gate shape under int8: ragged traffic through the
    dense int8 engine with mid-decode arrivals — zero post-warmup
    compiles AND every request bitwise-equal to the sequential int8
    session (prefill quantizes once, the admit copies int8+scales
    verbatim, decode quantizes per row independently)."""
    from paddle_tpu.core import monitor
    engine = int8_engine
    rng = np.random.RandomState(0)
    lens = (5, 12, 14, 7, 3)
    budgets = (8, 3, 6, 5, 8)
    prompts = [rng.randint(0, 512, n).astype(np.int32) for n in lens]
    monitor.enable()
    try:
        ns0 = _counter("jit.compile{cause=new_shape}")
        tot0 = _counter("jit.compile.total")
        handles = [engine.submit(p, RequestParams(max_new_tokens=b))
                   for p, b in zip(prompts[:2], budgets[:2])]
        for _ in range(3):
            engine.step()
        handles += [engine.submit(p, RequestParams(max_new_tokens=b))
                    for p, b in zip(prompts[2:], budgets[2:])]
        while engine.busy:
            engine.step()
        assert _counter("jit.compile{cause=new_shape}") - ns0 == 0
        assert _counter("jit.compile.total") - tot0 == 0
        # the structural invariant: absmax scales never saturate
        assert _counter("gen.cache.quant.scale_clips") == 0
    finally:
        monitor.disable()
    assert all(h.status is RequestStatus.COMPLETED for h in handles)
    for p, b, h in zip(prompts, budgets, handles):
        np.testing.assert_array_equal(
            h.result(), int8_reference(p, b, engine.max_len)[:b])


def test_int8_pages_cow_scales_privatize(tiny_gpt, int8_paged_engine,
                                         int8_reference):
    """int8 pages x shared-prefix COW: two identical 20-token prompts
    (20 % 16 != 0) — the second references the first's full page and
    privatizes the partial tail, VALUES AND SCALES together (the
    scales live in the page), so both decode bitwise-equal to the
    sequential int8 reference."""
    engine = int8_paged_engine
    stats0 = dict(engine._alloc.stats)
    prompt = np.random.RandomState(3).randint(0, 512, 20) \
        .astype(np.int32)
    h1 = engine.submit(prompt, RequestParams(max_new_tokens=6))
    while engine.busy:
        engine.step()
    h2 = engine.submit(prompt.copy(), RequestParams(max_new_tokens=8))
    while engine.busy:
        engine.step()
    s = engine._alloc.stats
    assert s["prefix_hits"] - stats0["prefix_hits"] == 1
    assert s["cow_copies"] - stats0["cow_copies"] == 1
    np.testing.assert_array_equal(
        h1.result(), int8_reference(prompt, 6, engine.max_len)[:6])
    np.testing.assert_array_equal(
        h2.result(), int8_reference(prompt, 8, engine.max_len)[:8])
    engine._alloc.assert_conserved()


def test_int8_speculative_accept_rate(tiny_gpt):
    """Speculative ngram windows over the int8 cache: greedy output
    matches the sequential int8 stream bitwise, and the accept rate
    stays within tolerance of the full-width run (quantization must
    not break the drafter's repetition hits)."""
    from paddle_tpu.core import monitor
    motif = np.random.RandomState(7).randint(0, 512, 8)
    ids = np.tile(motif, 8)[None, :48].astype(np.int32)

    def accept_rate(kv_dtype):
        monitor.enable()
        try:
            p0 = _counter("gen.spec.proposed")
            a0 = _counter("gen.spec.accepted")
            out = tiny_gpt.generate(ids, max_new_tokens=16,
                                    speculative="ngram",
                                    kv_cache_dtype=kv_dtype)
            dp = _counter("gen.spec.proposed") - p0
            da = _counter("gen.spec.accepted") - a0
        finally:
            monitor.disable()
        return np.asarray(out._data)[0], (da / dp if dp else 0.0)

    seq = np.asarray(tiny_gpt.generate(
        ids, max_new_tokens=16, kv_cache_dtype="int8")._data)[0]
    out_q, rate_q = accept_rate("int8")
    _, rate_w = accept_rate(None)
    np.testing.assert_array_equal(out_q, seq)   # greedy bitwise gate
    assert abs(rate_q - rate_w) <= 0.15


# ----------------------------------------------------- int4 weight path


def test_int4_pack_unpack_roundtrip():
    """Two-nibbles-per-byte packing round-trips exactly for the int4
    value range, even and odd row counts (the pad row slices off)."""
    from paddle_tpu.inference.precision import pack_int4, unpack_int4
    rng = np.random.RandomState(0)
    for rows in (6, 7):
        q = rng.randint(-7, 8, (rows, 5)).astype(np.int8)
        packed = pack_int4(jnp.asarray(q))
        assert packed.shape == ((rows + 1) // 2, 5)
        assert packed.dtype == jnp.int8
        np.testing.assert_array_equal(
            np.asarray(unpack_int4(packed, rows)), q)


def test_int4_weight_serving(tiny_gpt):
    """precision Int8 + weight_bits=4: Linear weights pack two values
    per stored byte with per-channel scales, materialize reconstructs
    them in-trace, and the served engine still decodes correctly
    (finite outputs, zero post-warmup compiles, audit clean at
    donation 1.0)."""
    from paddle_tpu.core import monitor
    from paddle_tpu.inference.precision import serving_params
    cfg = _config(tiny_gpt, kv_cache_dtype="int8", weight_bits=4)
    cfg.precision = PrecisionType.Int8
    sp = serving_params(tiny_gpt, cfg)
    assert sp.int4, "no Linear weight took the int4 path"
    for n, rows in sp.int4.items():
        i = sp.names.index(n)
        assert sp.vals[i].shape[0] == (rows + 1) // 2
    # dequant error bounded by the per-channel int4 step
    n = next(iter(sp.int4))
    i = sp.names.index(n)
    w = tiny_gpt.state_dict()[n]._data
    deq = np.asarray(sp.materialize(list(sp.vals))[i], np.float32)
    step = np.asarray(sp.scales[n], np.float32)  # absmax/7 per channel
    assert (np.abs(deq - np.asarray(w)) <= step * 0.75 + 1e-6).all()

    engine = ServingEngine(cfg, poll_every=2)
    monitor.enable()
    try:
        tot0 = _counter("jit.compile.total")
        h = engine.submit(np.arange(1, 9, dtype=np.int32),
                          RequestParams(max_new_tokens=6))
        while engine.busy:
            engine.step()
        assert _counter("jit.compile.total") - tot0 == 0
    finally:
        monitor.disable()
    assert h.status is RequestStatus.COMPLETED and len(h.result()) == 6
    reports = engine.audit()
    assert all(not r.errors for r in reports.values())
    assert reports["decode"].donation_coverage == 1.0
    engine.shutdown()


# -------------------------------------------------- analysis satellite


def test_quant_escape_detector():
    """dtype.quant_escape: an int8 buffer widened to float in
    UNSANCTIONED code fires a WARNING naming the site; registering the
    site silences it; the sanctioned fused-dequant paths never fire
    (asserted on a real quantized decode program below)."""
    from paddle_tpu.analysis import audit, register_dequant_site
    from paddle_tpu.analysis.detectors import QUANT_DEQUANT_SITES

    def escape(x8, w):
        return jnp.dot(x8.astype(jnp.float32), w)

    rep = audit(escape, jax.ShapeDtypeStruct((8, 8), jnp.int8),
                jax.ShapeDtypeStruct((8, 8), jnp.float32))
    qe = [f for f in rep.findings if f.check == "dtype.quant_escape"]
    assert len(qe) == 1 and "widens a quantized" in qe[0].message
    assert qe[0].severity.name == "WARNING"   # gate stays zero-ERROR
    # registering this test file as a dequant site silences it
    register_dequant_site("test_quant_cache.py")
    try:
        rep2 = audit(escape, jax.ShapeDtypeStruct((8, 8), jnp.int8),
                     jax.ShapeDtypeStruct((8, 8), jnp.float32))
        assert not [f for f in rep2.findings
                    if f.check == "dtype.quant_escape"]
    finally:
        QUANT_DEQUANT_SITES.discard("test_quant_cache.py")


def test_quant_audit_gates(int8_paged_engine):
    """The tier-1 audit gate over every int8-cache program (paged
    prefill/decode/admit/free): zero ERRORs, donation 1.0 on decode,
    and ZERO quant_escape findings — the int8 pools and scale sidecars
    are sanctioned storage, their only widening is the fused kernel
    dequant."""
    reports = int8_paged_engine.audit()
    for key, r in reports.items():
        assert not r.errors, f"{key}: {r.errors}"
        assert not [f for f in r.findings
                    if f.check == "dtype.quant_escape"], key
    assert reports["decode"].donation_coverage == 1.0
    assert reports["admit"].donation_coverage == 1.0


# ------------------------------------------------- health + metrics


def test_health_capacity_tokens(int8_engine, int8_paged_engine):
    """health() reports effective cache capacity in TOKENS (the PR-12
    remainder): slots x max_len dense, pool pages x page size paged —
    the number already reflects the cache dtype because an int8 pool
    at equal HBM is configured with ~2x the pages."""
    h = int8_engine.health()
    assert h["kv_cache_dtype"] == "int8"
    assert h["capacity_tokens"] == \
        int8_engine.max_batch * int8_engine.max_len
    assert h["free_tokens"] <= h["capacity_tokens"]
    hp = int8_paged_engine.health()
    assert hp["kv_cache_dtype"] == "int8"
    assert hp["capacity_tokens"] == \
        (int8_paged_engine._alloc.n_pages - 1) * \
        int8_paged_engine.page_size
    assert hp["free_tokens"] == \
        int8_paged_engine._alloc.free_pages() * \
        int8_paged_engine.page_size


def test_kv_dtype_gauge_and_bytes_saved(tiny_gpt):
    """Engine construction publishes the serve.cache.kv_dtype info
    gauge and the gen.cache.quant.bytes_saved accounting (int8 values
    + bf16 scales vs the wide dtype)."""
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    monitor.enable()
    try:
        b0 = _counter("gen.cache.quant.bytes_saved")
        engine = ServingEngine(_config(tiny_gpt), poll_every=2)
        snap = metrics.snapshot()
        assert snap["serve.cache.kv_dtype{dtype=int8}"]["value"] == 1.0
        saved = _counter("gen.cache.quant.bytes_saved") - b0
        # k+v elements * (4 - 1) bytes minus the bf16 scale sidecars
        k = engine._cache.k
        expect = 2 * k.size * 3 - 2 * (k.size // k.shape[-1]) * 2
        assert saved == expect
        engine.shutdown()
    finally:
        monitor.disable()


# ------------------------------------------------------------- knobs


def test_resolve_cache_dtype_env(monkeypatch):
    assert resolve_cache_dtype(None) is None
    assert resolve_cache_dtype("int8") == "int8"
    with pytest.raises(ValueError):
        resolve_cache_dtype("int3")
    monkeypatch.setenv("PADDLE_KV_CACHE_DTYPE", "int8")
    assert resolve_cache_dtype(None) == "int8"
    monkeypatch.setenv("PADDLE_KV_CACHE_DTYPE", "garbage")
    assert resolve_cache_dtype(None) is None   # swallowed, falls wide
    monkeypatch.setenv("PADDLE_KV_CACHE_DTYPE", "off")
    assert resolve_cache_dtype(None) is None


def test_generate_session_dtype_mismatch_raises(tiny_gpt):
    from paddle_tpu.generation.api import GenerationSession, generate
    sess = GenerationSession(tiny_gpt)   # full-width session
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        generate(tiny_gpt, np.arange(1, 9, dtype=np.int32)[None, :],
                 4, session=sess, kv_cache_dtype="int8")
    with pytest.raises(ValueError):
        Config().enable_generation(kv_cache_dtype="int3")
    with pytest.raises(ValueError):
        Config().enable_serving(weight_bits=5)
