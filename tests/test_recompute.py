"""`fleet.utils.recompute` / RecomputeConfig policy parity.

Recompute must change HBM/FLOPs, never numerics: loss AND grads of a
2-block GPT under `full` vs `dots_saveable` vs no-remat agree to fp32
tolerance, and wrapping the loss in `jax.checkpoint` costs exactly one
compile — the jit retrace tracker reports zero extra retraces across
repeated steps (≈ the reference's test_recompute.py asserting
recompute == no-recompute grads, plus our retrace gate)."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.utils import RecomputeConfig, recompute
from paddle_tpu.jit.api import TrainStep, functional_call, _unwrap, _wrap
from paddle_tpu.models.gpt import gpt
from paddle_tpu.profiler import metrics


def _gpt2block():
    paddle.seed(0)
    return gpt("test-tiny")  # test-tiny is the 2-block config


def _loss_and_grads(policy):
    """Loss + per-param grads of one forward/backward, the whole loss
    function wrapped per ``policy`` (None = no remat)."""
    model = _gpt2block()
    names = [n for n, _ in model.named_parameters()]
    pvals = [p._data for _, p in model.named_parameters()]
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (2, 16)).astype(np.int32)
    labels = ids.astype(np.int64)

    def loss_of(params):
        out = functional_call(model, dict(zip(names, params)),
                              _wrap(ids))
        return _unwrap(model.loss(out, _wrap(labels)))

    cfg = RecomputeConfig(policy) if policy is not None else None
    fn = cfg.wrap(loss_of) if cfg is not None else loss_of
    loss, grads = jax.jit(jax.value_and_grad(fn))(pvals)
    return float(loss), [np.asarray(g) for g in grads]


class TestPolicyParity:
    @pytest.mark.parametrize("policy", ["full", "dots_saveable",
                                        "dots_with_no_batch_dims_saveable"])
    def test_loss_and_grads_match_no_remat(self, policy):
        ref_loss, ref_grads = _loss_and_grads(None)
        loss, grads = _loss_and_grads(policy)
        np.testing.assert_allclose(loss, ref_loss, rtol=1e-6, atol=1e-7)
        assert len(grads) == len(ref_grads)
        for g, r in zip(grads, ref_grads):
            np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)

    def test_trainstep_recompute_param_parity(self):
        """One fused TrainStep under recompute updates params exactly
        like the un-rematted step (same seed, same batch)."""

        def one_step(recompute_cfg):
            model = _gpt2block()
            # SGD: the update is LINEAR in the grad, so param parity
            # inherits the grad tolerance (Adam's sign-like step blows
            # roundoff in near-zero grads up to the full ±lr)
            opt = optimizer.SGD(learning_rate=1e-2,
                                parameters=model.parameters())
            step = TrainStep(model, opt,
                             lambda out, lbl: model.loss(out, lbl),
                             recompute=recompute_cfg)
            rng = np.random.RandomState(0)
            ids = rng.randint(0, 512, (2, 16)).astype(np.int32)
            loss = step(paddle.to_tensor(ids),
                        paddle.to_tensor(ids.astype(np.int64)))
            return float(loss), {n: p.numpy() for n, p in
                                 model.named_parameters()}

        ref_loss, ref_params = one_step(None)
        for cfg in ("full", RecomputeConfig("dots_saveable")):
            loss, params = one_step(cfg)
            np.testing.assert_allclose(loss, ref_loss, rtol=1e-6,
                                       atol=1e-7)
            for n in ref_params:
                np.testing.assert_allclose(params[n], ref_params[n],
                                           rtol=1e-5, atol=1e-6, err_msg=n)


class TestRetraceGate:
    def test_recompute_costs_exactly_one_compile(self):
        """3 steps under recompute: jit.compile.total grows by exactly
        one (the first trace) — the checkpoint wrapper must not perturb
        the jit cache key step-to-step."""
        model = _gpt2block()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, opt,
                         lambda out, lbl: model.loss(out, lbl),
                         recompute="dots_saveable")
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 512, (2, 16)).astype(np.int32)
        x = paddle.to_tensor(ids)
        y = paddle.to_tensor(ids.astype(np.int64))
        metrics.reset()
        metrics.enable()
        try:
            for _ in range(3):
                float(step(x, y))
            snap = metrics.snapshot()
        finally:
            metrics.disable()
        total = snap.get("jit.compile.total", {}).get("value", 0)
        assert total == 1, f"expected 1 compile, tracker saw {total}"


class TestRecomputeConfig:
    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="unknown recompute policy"):
            RecomputeConfig("save_everything_twice")

    def test_none_policy_is_identity(self):
        cfg = RecomputeConfig(None)
        assert not cfg.enabled
        fn = lambda x: x + 1
        assert cfg.wrap(fn) is fn

    def test_raw_jax_callable_policy_accepted(self):
        """The docstring promises raw jax.checkpoint_policies callables
        work everywhere a policy name does."""
        raw = jax.checkpoint_policies.dots_saveable
        cfg = RecomputeConfig(raw)
        assert cfg.enabled and cfg.jax_policy() is raw
        paddle.seed(3)
        layer = nn.Linear(8, 8)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        out = recompute(layer, x, policy=raw)
        np.testing.assert_allclose(out.numpy(), layer(x).numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_recompute_policy_none_means_full(self):
        """recompute(fn, policy=None) remats under the default 'full'
        policy (Paddle's recompute always recomputes); only
        RecomputeConfig(None) spells recompute OFF."""
        paddle.seed(3)
        layer = nn.Linear(8, 8)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        out = recompute(layer, x, policy=None)
        np.testing.assert_allclose(out.numpy(), layer(x).numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_alias_policies_share_jax_policy(self):
        assert RecomputeConfig("full").jax_policy() is \
            RecomputeConfig("nothing_saveable").jax_policy() is None
        assert RecomputeConfig("core_attn").jax_policy() is \
            RecomputeConfig("dots_saveable").jax_policy()


class TestPaddleParityEntry:
    def test_recompute_matches_direct_call(self):
        paddle.seed(3)
        layer = nn.Linear(8, 8)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        # the reference's kwargs are accepted and ignored
        out = recompute(layer, x, use_reentrant=False,
                        preserve_rng_state=True)
        np.testing.assert_allclose(out.numpy(), layer(x).numpy(),
                                   rtol=1e-6, atol=1e-7)

    def test_recompute_grads_flow(self):
        paddle.seed(3)
        layer = nn.Linear(8, 4)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 8).astype(np.float32))
        out = recompute(layer, x, policy="dots_saveable")
        out.mean().backward()
        assert layer.weight.grad is not None
        assert np.abs(layer.weight.grad.numpy()).sum() > 0


class TestGranularityMapping:
    def test_typo_granularity_raises_not_falls_back(self):
        """A typo'd recompute_granularity must error, not silently
        train under a default policy — and GPT/ERNIE agree on that."""
        from paddle_tpu.models.ernie import ernie
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 512, (2, 16)).astype(np.int32))
        paddle.seed(0)
        g = gpt("test-tiny", use_recompute=True,
                recompute_granularity="core-attn")  # hyphen typo
        g.train()
        with pytest.raises(ValueError, match="recompute_granularity"):
            g(ids)
        paddle.seed(0)
        e = ernie("test-tiny", use_recompute=True,
                  recompute_granularity="core-attn")
        e.train()
        with pytest.raises(ValueError, match="recompute_granularity"):
            e(ids)


@pytest.fixture
def mesh_dp8():
    hcg = fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 8}))
    yield hcg
    dist.set_hybrid_communicate_group(None)


def test_fleet_step_recompute_loss_parity(mesh_dp8):
    """DistributedTrainStep(recompute=...) must not move the loss."""

    def one(recompute_cfg):
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=m.parameters())
        m = fleet.distributed_model(m)
        opt = fleet.distributed_optimizer(opt)
        step = fleet.DistributedTrainStep(
            m, opt, nn.functional.cross_entropy, recompute=recompute_cfg)
        rng = np.random.RandomState(0)
        xs = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
        ys = paddle.to_tensor(rng.randint(0, 4, 16))
        return [float(step(xs, ys)) for _ in range(3)]

    np.testing.assert_allclose(one("full"), one(None), rtol=1e-6,
                               atol=1e-7)
