"""Auto-checkpoint resume tests (reference:
unittests/test_auto_checkpoint*.py — epoch-range resume semantics)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import auto_checkpoint as ac


def _env(tmp_path, monkeypatch, job="j1"):
    monkeypatch.setenv("PADDLE_RUNNING_ENV",
                       "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_JOB_ID", job)
    monkeypatch.setenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", str(tmp_path))


def test_disabled_passthrough(monkeypatch):
    monkeypatch.delenv("PADDLE_RUNNING_ENV", raising=False)
    assert list(ac.train_epoch_range(3)) == [0, 1, 2]


def test_resume_skips_completed_epochs(tmp_path, monkeypatch):
    _env(tmp_path, monkeypatch)
    status = ac.ExeTrainStatus()
    seen = []
    for epoch in ac.train_epoch_range(5, status=status):
        status.update(last_done=epoch, w=np.float32(epoch * 2.0))
        seen.append(epoch)
        if epoch == 2:
            # simulate preemption DURING epoch 2: control never returns
            # to the generator, so epoch 2 is not recorded as complete
            break
    assert seen == [0, 1, 2]

    # "restarted" process: fresh status, same env -> redo epoch 2
    status2 = ac.ExeTrainStatus()
    seen2 = list(ac.train_epoch_range(5, status=status2))
    assert seen2 == [2, 3, 4]
    assert int(status2.state["last_done"]) == 1
    np.testing.assert_allclose(float(status2.state["w"]), 2.0)

    # fully finished: nothing left to run
    seen3 = list(ac.train_epoch_range(5))
    assert seen3 == []


def test_distinct_jobs_isolated(tmp_path, monkeypatch):
    _env(tmp_path, monkeypatch, job="jobA")
    list(ac.train_epoch_range(2))
    _env(tmp_path, monkeypatch, job="jobB")
    assert list(ac.train_epoch_range(2)) == [0, 1]
