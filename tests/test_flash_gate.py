"""The scaled_dot_product_attention flash gate is load-bearing: r3
measured +36% ERNIE / +34% BERT from engaging at s512, and r4 measured
ViT REGRESSING when the gate was widened to big-batch s197 (BASELINE.md
negatives). Pin exactly when the Pallas path engages.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle  # noqa: F401
import paddle_tpu.nn.functional.attention as attn_mod


@pytest.fixture()
def spy(monkeypatch):
    calls = []

    def fake_flash(query, key, value, causal=False, scale=None, **kw):
        calls.append((query.shape, causal))
        # cheap stand-in so the dispatch path completes
        return query

    import importlib
    fa_mod = importlib.import_module("paddle_tpu.kernels.flash_attention")
    monkeypatch.setattr(fa_mod, "flash_attention", fake_flash)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    return calls


def _sdpa(b, s, h, d, causal=False, sk=None, mask=None, dropout=0.0):
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, sk or s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, sk or s, h, d), jnp.float32)
    return attn_mod.scaled_dot_product_attention(
        q, k, v, attn_mask=mask, dropout_p=dropout, is_causal=causal)


@pytest.mark.parametrize("s,causal", [(512, False), (512, True),
                                      (1024, True), (2048, False)])
def test_gate_engages_at_512_and_beyond(spy, s, causal):
    _sdpa(2, s, 2, 64, causal=causal)
    assert spy, f"flash must engage at s={s}"


def test_gate_stays_off_below_512(spy):
    _sdpa(2, 256, 2, 64)
    assert not spy


def test_gate_stays_off_for_vit_shape(spy):
    """b64 h16 s197: measured SLOWER on the padded flash path
    (BASELINE.md r4 ViT negative) — must stay on XLA."""
    _sdpa(64, 197, 16, 64)
    assert not spy


def test_gate_stays_off_with_mask_or_dropout(spy):
    import jax.numpy as jnp
    mask = jnp.zeros((2, 2, 512, 512), jnp.float32)
    _sdpa(2, 512, 2, 64, mask=mask)
    assert not spy
    _sdpa(2, 512, 2, 64, dropout=0.5)
    assert not spy


def test_gate_stays_off_for_unsupported_head_dim(spy):
    _sdpa(2, 512, 2, 80)
    assert not spy
