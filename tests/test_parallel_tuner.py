"""Auto-parallel strategy tuner (VERDICT r2 Next #4): candidate mesh
degrees are compiled on the 8-device virtual mesh and ranked by the
compiled-program cost model (roofline + HLO-parsed collective bytes,
DCN-aware). The tuner must pick sane configs for a GPT-6.7B-style block
and an ERNIE-class model within a small candidate budget."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel.tuner import (
    Candidate, ParallelTuner, collective_bytes)


def _gpt_step_builder(cfg_name, batch, seq, **model_kw):
    """step_builder for ParallelTuner over the fleet hybrid path."""
    from paddle_tpu.models.gpt import gpt

    def build(hybrid_configs):
        paddle.seed(0)
        strategy = fleet.DistributedStrategy(
            hybrid_configs=dict(hybrid_configs),
            sharding=hybrid_configs.get("sharding_degree", 1) > 1,
            sharding_configs={"stage": 2})
        fleet.init(strategy=strategy)
        model = gpt(cfg_name, **model_kw)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(opt)
        step = fleet.DistributedTrainStep(
            model, opt, lambda lo, la: model.loss(lo, la))
        ids = np.random.RandomState(0).randint(
            0, model.cfg.vocab_size, (batch, seq)).astype(np.int32)
        return step, (paddle.to_tensor(ids),
                      paddle.to_tensor(ids.astype(np.int64)))

    return build


def test_candidate_enumeration_and_pruning():
    tuner = ParallelTuner(8, lambda cfg: None, num_heads=6,
                          num_layers=4, max_mp=4)
    cands = tuner._enumerate()
    degrees = {(c.dp, c.sharding, c.pp, c.mp) for c in cands}
    # all factorizations of 8 over 4 axes present
    assert (8, 1, 1, 1) in degrees and (1, 2, 2, 2) in degrees
    by_cfg = {(c.dp, c.sharding, c.pp, c.mp): c for c in cands}
    # mp=8 > max_mp pruned; mp=4 fails num_heads divisibility (6 % 4)
    assert not by_cfg[(1, 1, 1, 8)].feasible
    assert not by_cfg[(1, 1, 2, 4)].feasible
    assert "num_heads" in by_cfg[(1, 1, 2, 4)].reason
    # pp=8 > ... pp must divide num_layers=4: pp=8 infeasible
    assert not by_cfg[(1, 1, 8, 1)].feasible
    assert by_cfg[(2, 2, 1, 2)].feasible


def test_memory_pruning_and_dcn_rule():
    # 6.7B-class params cannot fit replicated: dp8 must be pruned
    tuner = ParallelTuner(8, lambda cfg: None,
                          param_bytes=6.7e9 * 4, hbm_capacity=16e9)
    cands = {(c.dp, c.sharding, c.pp, c.mp): c
             for c in tuner._enumerate()}
    assert not cands[(8, 1, 1, 1)].feasible
    assert "HBM" in cands[(8, 1, 1, 1)].reason
    assert cands[(1, 8, 1, 1)].feasible  # fully sharded fits
    # DCN rule: with 2 slices of 4 devices, dp must cover the slices
    tuner2 = ParallelTuner(8, lambda cfg: None, devices_per_slice=4)
    cands2 = {(c.dp, c.sharding, c.pp, c.mp): c
              for c in tuner2._enumerate()}
    assert not cands2[(1, 1, 1, 8)].feasible
    assert "DCN" in cands2[(1, 1, 1, 8)].reason
    assert cands2[(2, 2, 1, 2)].feasible


def test_collective_bytes_parser():
    hlo = """
  %ar = f32[1024,64]{1,0} all-reduce(f32[1024,64]{1,0} %x), replica_groups={{0,1,2,3}}, to_apply=%add
  %ag = bf16[512]{0} all-gather(bf16[256]{0} %y), replica_groups={{0,4},{1,5},{2,6},{3,7}}, dimensions={0}
  %mm = f32[8,8]{1,0} dot(f32[8,8]{1,0} %a, f32[8,8]{1,0} %b)
"""
    ici, dcn, n_ici, n_dcn = collective_bytes(hlo, devices_per_slice=4)
    assert ici == 1024 * 64 * 4          # all-reduce within one slice
    assert dcn == 512 * 2                # all-gather crosses slices
    assert n_ici == 1 and n_dcn == 1


def test_tuner_picks_sane_config_gpt67b_block():
    """GPT-6.7B hidden size (h=4096, heads=32) scaled to 4 layers on 8
    devices: replicated-dp must be pruned for memory and the winner
    must shard the parameter state."""
    builder = _gpt_step_builder(
        "test-tiny", batch=8, seq=32, hidden_size=256, num_layers=4,
        num_heads=8)
    # parameter bytes of the REAL 6.7B target drive the memory prune;
    # the compiled candidates use the scaled model (same structure)
    tuner = ParallelTuner(
        8, builder, num_layers=4, num_heads=8,
        param_bytes=6.7e9 * 4, hbm_capacity=16e9, max_candidates=6)
    best = tuner.tune(verbose=True)
    assert best.feasible and np.isfinite(best.cost_s)
    # sane: the memory-infeasible pure-dp config cannot win, and the
    # parameter state is split over at least 4 ways
    assert best.sharding * best.mp * best.pp >= 4
    scored = [c for c in tuner.candidates
              if c.feasible and np.isfinite(c.cost_s)]
    assert 1 <= len(scored) <= 6  # candidate budget respected


def test_tuner_picks_dp_for_small_model():
    """ERNIE-class model that fits replicated: pure data parallel (or
    dp-heavy) should win — collective traffic per step is smallest."""
    builder = _gpt_step_builder(
        "test-tiny", batch=8, seq=32, hidden_size=128, num_layers=2,
        num_heads=4)
    tuner = ParallelTuner(
        8, builder, num_layers=2, num_heads=4,
        param_bytes=120e6 * 4, hbm_capacity=16e9, max_candidates=6)
    best = tuner.tune()
    # small model: data-style parallelism (dp and/or ZeRO sharding,
    # which costs the same collective volume but touches fewer HBM
    # bytes) must win over per-layer mp/pp communication
    assert best.dp * best.sharding == 8
    assert best.mp == 1 and best.pp == 1


def test_engine_strategy_auto():
    """Engine(strategy='auto').tune picks a mesh from the model's own
    annotations and leaves the engine ready to fit."""
    from paddle_tpu import nn
    from paddle_tpu.distributed.auto_parallel import Engine

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            from paddle_tpu.models._common import spec_linear
            from jax.sharding import PartitionSpec as P
            self.fc1 = spec_linear(16, 64, 0.02, P(None, "mp"), P("mp"))
            self.fc2 = spec_linear(64, 4, 0.02, P("mp", None), P())

        def forward(self, x):
            return self.fc2(self.fc1(x))

    paddle.seed(0)
    model = MLP()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    eng = Engine(model=model,
                 loss=lambda out, y: ((out - y) ** 2).mean(),
                 optimizer=opt, strategy="auto")
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    best = eng.tune(x, y, max_candidates=4)
    assert isinstance(best, Candidate)
    assert best.dp * best.mp == 8
    assert eng.mesh is not None
    # engine still trains on the tuned mesh
    hist = eng.fit((x, y), epochs=1, batch_size=8, verbose=0)
    assert eng._history
