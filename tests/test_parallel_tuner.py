"""Auto-parallel strategy tuner (VERDICT r2 Next #4): candidate mesh
degrees are compiled on the 8-device virtual mesh and ranked by the
compiled-program cost model (roofline + HLO-parsed collective bytes,
DCN-aware). The tuner must pick sane configs for a GPT-6.7B-style block
and an ERNIE-class model within a small candidate budget."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.auto_parallel.tuner import (
    Candidate, ParallelTuner, collective_bytes)


def _gpt_step_builder(cfg_name, batch, seq, **model_kw):
    """step_builder for ParallelTuner over the fleet hybrid path."""
    from paddle_tpu.models.gpt import gpt

    def build(hybrid_configs):
        paddle.seed(0)
        strategy = fleet.DistributedStrategy(
            hybrid_configs=dict(hybrid_configs),
            sharding=hybrid_configs.get("sharding_degree", 1) > 1,
            sharding_configs={"stage": 2})
        fleet.init(strategy=strategy)
        model = gpt(cfg_name, **model_kw)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        model = fleet.distributed_model(model)
        opt = fleet.distributed_optimizer(opt)
        step = fleet.DistributedTrainStep(
            model, opt, lambda lo, la: model.loss(lo, la))
        ids = np.random.RandomState(0).randint(
            0, model.cfg.vocab_size, (batch, seq)).astype(np.int32)
        return step, (paddle.to_tensor(ids),
                      paddle.to_tensor(ids.astype(np.int64)))

    return build


def test_candidate_enumeration_and_pruning():
    tuner = ParallelTuner(8, lambda cfg: None, num_heads=6,
                          num_layers=4, max_mp=4)
    cands = tuner._enumerate()
    degrees = {(c.dp, c.sharding, c.pp, c.mp) for c in cands}
    # all factorizations of 8 over 4 axes present
    assert (8, 1, 1, 1) in degrees and (1, 2, 2, 2) in degrees
    by_cfg = {(c.dp, c.sharding, c.pp, c.mp): c for c in cands}
    # mp=8 > max_mp pruned; mp=4 fails num_heads divisibility (6 % 4)
    assert not by_cfg[(1, 1, 1, 8)].feasible
    assert not by_cfg[(1, 1, 2, 4)].feasible
    assert "num_heads" in by_cfg[(1, 1, 2, 4)].reason
    # pp=8 > ... pp must divide num_layers=4: pp=8 infeasible
    assert not by_cfg[(1, 1, 8, 1)].feasible
    assert by_cfg[(2, 2, 1, 2)].feasible


def test_memory_pruning_and_dcn_rule():
    # 6.7B-class params cannot fit replicated: dp8 must be pruned
    tuner = ParallelTuner(8, lambda cfg: None,
                          param_bytes=6.7e9 * 4, hbm_capacity=16e9)
    cands = {(c.dp, c.sharding, c.pp, c.mp): c
             for c in tuner._enumerate()}
    assert not cands[(8, 1, 1, 1)].feasible
    assert "HBM" in cands[(8, 1, 1, 1)].reason
    assert cands[(1, 8, 1, 1)].feasible  # fully sharded fits
    # DCN rule: with 2 slices of 4 devices, dp must cover the slices
    tuner2 = ParallelTuner(8, lambda cfg: None, devices_per_slice=4)
    cands2 = {(c.dp, c.sharding, c.pp, c.mp): c
              for c in tuner2._enumerate()}
    assert not cands2[(1, 1, 1, 8)].feasible
    assert "DCN" in cands2[(1, 1, 1, 8)].reason
    assert cands2[(2, 2, 1, 2)].feasible


def test_collective_bytes_parser():
    hlo = """
  %ar = f32[1024,64]{1,0} all-reduce(f32[1024,64]{1,0} %x), replica_groups={{0,1,2,3}}, to_apply=%add
  %ag = bf16[512]{0} all-gather(bf16[256]{0} %y), replica_groups={{0,4},{1,5},{2,6},{3,7}}, dimensions={0}
  %mm = f32[8,8]{1,0} dot(f32[8,8]{1,0} %a, f32[8,8]{1,0} %b)
"""
    ici, dcn, n_ici, n_dcn = collective_bytes(hlo, devices_per_slice=4)
    assert ici == 1024 * 64 * 4          # all-reduce within one slice
    assert dcn == 512 * 2                # all-gather crosses slices
    assert n_ici == 1 and n_dcn == 1


@pytest.mark.slow  # ~21s on CPU (lowers candidate meshes): tier-2
def test_tuner_picks_sane_config_gpt67b_block():
    """GPT-6.7B hidden size (h=4096, heads=32) scaled to 4 layers on 8
    devices: replicated-dp must be pruned for memory and the winner
    must shard the parameter state."""
    builder = _gpt_step_builder(
        "test-tiny", batch=8, seq=32, hidden_size=256, num_layers=4,
        num_heads=8)
    # parameter bytes of the REAL 6.7B target drive the memory prune;
    # the compiled candidates use the scaled model (same structure)
    tuner = ParallelTuner(
        8, builder, num_layers=4, num_heads=8,
        param_bytes=6.7e9 * 4, hbm_capacity=16e9, max_candidates=6)
    best = tuner.tune(verbose=True)
    assert best.feasible and np.isfinite(best.cost_s)
    # sane: the memory-infeasible pure-dp config cannot win, and the
    # parameter state is split over at least 4 ways
    assert best.sharding * best.mp * best.pp >= 4
    scored = [c for c in tuner.candidates
              if c.feasible and np.isfinite(c.cost_s)]
    assert 1 <= len(scored) <= 6  # candidate budget respected


def test_tuner_picks_dp_for_small_model():
    """ERNIE-class model that fits replicated: pure data parallel (or
    dp-heavy) should win — collective traffic per step is smallest."""
    builder = _gpt_step_builder(
        "test-tiny", batch=8, seq=32, hidden_size=128, num_layers=2,
        num_heads=4)
    tuner = ParallelTuner(
        8, builder, num_layers=2, num_heads=4,
        param_bytes=120e6 * 4, hbm_capacity=16e9, max_candidates=6)
    best = tuner.tune()
    # small model: data-style parallelism (dp and/or ZeRO sharding,
    # which costs the same collective volume but touches fewer HBM
    # bytes) must win over per-layer mp/pp communication
    assert best.dp * best.sharding == 8
    assert best.mp == 1 and best.pp == 1


def test_cost_model_calibration():
    """VERDICT r3 Next #2: the tuner's roofline constants are calibrated
    against the measured single-chip rows (recorded on the real v5e in
    experiments/tuner_calibration.json). Shipped global defaults hold
    every row within 30%; per-model-family calibration reaches the 20%
    target (GPT family spans 3 shape configs)."""
    import json
    import os
    from paddle_tpu.distributed.auto_parallel.tuner import (
        calibrate, predict_step_time)
    path = os.path.join(os.path.dirname(__file__), "..", "experiments",
                        "tuner_calibration.json")
    data = json.load(open(path))
    # the fused row's cost-analysis flops are an artifact (Pallas custom
    # calls carry the flops XLA cannot see) — excluded, see BASELINE.md
    rows = [r for r in data["rows"] if r["name"] != "resnet50 b128 fused"]
    assert len(rows) >= 7
    for r in rows:
        pred = predict_step_time(r["flops"], r["hbm_bytes"])
        assert abs(pred - r["measured_s"]) / r["measured_s"] < 0.30, \
            (r["name"], pred, r["measured_s"])
    me, he, worst = calibrate(rows)
    assert worst < 0.30
    assert abs(me - 0.41) < 0.05 and abs(he - 0.91) < 0.1, (me, he)
    gpt_rows = [r for r in rows if r["name"].startswith("gpt2")]
    assert len(gpt_rows) == 3
    _, _, worst_gpt = calibrate(gpt_rows)
    assert worst_gpt < 0.20
    for fam in ("ernie", "bert", "resnet50 b128 unfused", "vit"):
        sub = [r for r in rows if r["name"].startswith(fam)]
        assert sub, fam
        _, _, w = calibrate(sub)
        assert w < 0.20, (fam, w)


def test_northstar_plan_artifact():
    """The published v5e-256 plan (BASELINE.md 'Predicted at scale')
    stays consistent: winner is dp256, predicted single-slice scaling
    efficiency >= 0.95, predicted MFU clears the 0.40 north-star, and
    the 2-slice DCN variant is strictly worse."""
    import json
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "experiments",
                        "northstar_plan.json")
    data = json.load(open(path))
    cands = [r for r in data["rows"] if r["kind"] == "candidate-256"]
    assert len(cands) >= 3
    winner = min(cands, key=lambda r: r["pred_ms"])
    assert winner["dp"] == 256 and winner["sharding"] == 1
    assert winner["pred_scaling_eff"] >= 0.95
    # measured single-chip MFU (BASELINE.md r5 ERNIE row, conservative
    # end of the 0.475-0.481 drift band) x predicted scaling efficiency
    # must clear the 0.40 north-star target
    assert 0.475 * winner["pred_scaling_eff"] >= 0.40
    assert winner["pred_ms_2slice"] > winner["pred_ms"]
    # grad all-reduce payload ~ fp32 param bytes (118M params)
    assert 4.0e8 < winner["coll_bytes"] < 8.0e8


def test_northstar_gradient_accumulation_model():
    """The 2-slice DCN penalty's published fix (gradient merge) is
    MODELED in the plan artifact: the accumulation curve recovers the
    per-sample efficiency monotonically toward 1 with the exact
    amortization algebra (collective paid once per K microsteps), and
    the K the dryrun exercises (mesh #4) sits on the curve. The link
    sensitivity rows carry the prediction's error bars."""
    import json
    import os
    path = os.path.join(os.path.dirname(__file__), "..", "experiments",
                        "northstar_plan.json")
    data = json.load(open(path))
    winner = min((r for r in data["rows"]
                  if r["kind"] == "candidate-256"),
                 key=lambda r: r["pred_ms"])
    curve = {int(k): v for k, v in winner["accum_2slice"].items()}
    ks = sorted(curve)
    assert ks[0] == 1 and curve[1] == winner["pred_scaling_eff_2slice"]
    # monotone recovery, approaching the single-slice ceiling
    for a, b in zip(ks, ks[1:]):
        assert curve[b] > curve[a]
    assert curve[max(ks)] > 0.95
    # exact amortization algebra: eff(K) = T1 / (T1 + t_coll/K) where
    # t_coll = T1 * (1/eff(1) - 1) — closed form from the model
    t1 = data["measured_1chip_ms"]
    t_coll = t1 * (1.0 / curve[1] - 1.0)
    for k in ks:
        expect = t1 / (t1 + t_coll / k)
        assert abs(curve[k] - expect) < 2e-3, (k, curve[k], expect)
    # sensitivity rows exist and bracket the nominal prediction
    sens = winner["sensitivity"]
    assert sens["ici_0.5x"] < winner["pred_scaling_eff"] < sens["ici_2x"]
    assert sens["dcn_0.5x_2slice"] < winner["pred_scaling_eff_2slice"] \
        < sens["dcn_2x_2slice"]


def test_abstract_lowering_matches_concrete():
    """DistributedTrainStep(abstract=True).lower_abstract compiles the
    SAME program XLA would build for real buffers: collective payloads
    parsed from both HLOs agree (8-device dp mesh)."""
    from paddle_tpu.models.gpt import gpt
    import jax

    def build(abstract):
        paddle.seed(0)
        fleet.init(strategy=fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 8}))
        model = gpt("test-tiny", num_layers=2, hidden_size=64,
                    num_heads=4)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        opt = fleet.distributed_optimizer(opt)
        return fleet.DistributedTrainStep(
            model, opt, lambda lo, la: model.loss(lo, la),
            abstract=abstract), model

    ids = np.random.RandomState(0).randint(
        0, 512, (16, 8)).astype(np.int32)
    step_a, _ = build(True)
    low_a = step_a.lower_abstract(
        jax.ShapeDtypeStruct(ids.shape, np.int32),
        jax.ShapeDtypeStruct(ids.shape, np.int64))
    hlo_a = low_a.compile().as_text()
    step_c, _ = build(False)
    low_c = step_c.lower(paddle.to_tensor(ids),
                         paddle.to_tensor(ids.astype(np.int64)))
    hlo_c = low_c.compile().as_text()
    ba = collective_bytes(hlo_a, None)
    bc = collective_bytes(hlo_c, None)
    assert ba == bc and ba[0] > 0, (ba, bc)


@pytest.mark.slow  # ~9s full-space lowering on CPU: tier-2
def test_engine_full_space_picks_pp():
    """VERDICT r3 Next #5: Engine(strategy='auto') reaches the FULL
    dp x sharding x pp x mp space through the fleet path. With a
    deliberately HBM-tight candidate set (dp/pp axes only; replicated
    parameter+optimizer state too large for one chip) the winner must
    run pp > 1, and fit() trains through the installed fleet step."""
    from paddle_tpu.distributed.auto_parallel import Engine
    from paddle_tpu.models.gpt import gpt, gpt_pipe

    def model_builder(cfg):
        paddle.seed(0)
        pp = cfg.get("pp_degree", 1)
        kw = dict(num_layers=4, hidden_size=128, num_heads=4)
        if pp > 1:
            model = gpt_pipe("test-tiny", num_stages=pp,
                             num_microbatches=2, **kw)
            loss_fn = model.loss_fn
        else:
            model = gpt("test-tiny", **kw)
            loss_fn = lambda lo, la: model.loss(lo, la)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        return model, opt, loss_fn

    eng = Engine(strategy="auto")
    ids = np.random.RandomState(0).randint(0, 512, (8, 16)).astype(np.int32)
    labels = ids.astype(np.int64)
    # param_bytes of a 1.5B-param target: state = 3.5 * 6 GB = 21 GB >
    # 85% of 16 GB HBM replicated -> pp=1 candidates all pruned; pp=2
    # shard (10.5 GB) fits
    best = eng.tune(ids, labels, model_builder=model_builder,
                    axes=("dp", "pp"), num_layers=4, num_heads=4,
                    param_bytes=1.5e9 * 4, hbm_capacity=16e9,
                    max_candidates=4)
    assert best.pp > 1, best
    assert eng._fleet_step is not None
    hist = eng.fit((ids, labels), epochs=1, batch_size=8, verbose=0)
    assert hist and hist[-1]["loss"] is not None
    assert np.isfinite(hist[-1]["loss"])


def test_engine_strategy_auto():
    """Engine(strategy='auto').tune picks a mesh from the model's own
    annotations and leaves the engine ready to fit."""
    from paddle_tpu import nn
    from paddle_tpu.distributed.auto_parallel import Engine

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            from paddle_tpu.models._common import spec_linear
            from jax.sharding import PartitionSpec as P
            self.fc1 = spec_linear(16, 64, 0.02, P(None, "mp"), P("mp"))
            self.fc2 = spec_linear(64, 4, 0.02, P("mp", None), P())

        def forward(self, x):
            return self.fc2(self.fc1(x))

    paddle.seed(0)
    model = MLP()
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    eng = Engine(model=model,
                 loss=lambda out, y: ((out - y) ** 2).mean(),
                 optimizer=opt, strategy="auto")
    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    best = eng.tune(x, y, max_candidates=4)
    assert isinstance(best, Candidate)
    assert best.dp * best.mp == 8
    assert eng.mesh is not None
    # engine still trains on the tuned mesh
    hist = eng.fit((x, y), epochs=1, batch_size=8, verbose=0)
    assert eng._history
