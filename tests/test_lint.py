"""Framework-lint coverage (tools/lint): each rule caught on a minimal
bad snippet and silent on the corresponding good one, the allowlist
markers, and — the tier-1 gate — ``python -m tools.lint paddle_tpu
tests`` exiting 0 on the shipped tree."""
import os
import subprocess
import sys
import textwrap

import pytest

from tools.lint import lint_file, lint_paths, RULES

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_snippet(tmp_path, source, relpath):
    """Lint `source` as if it lived at `relpath` in the repo."""
    p = tmp_path / os.path.basename(relpath)
    p.write_text(textwrap.dedent(source))
    return lint_file(str(p), relpath)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


class TestHostSyncRule:
    HOT = "paddle_tpu/generation/api.py"

    def test_flags_numpy_float_asarray(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            import numpy as np
            def step(t):
                a = t.numpy()
                b = float(t)
                c = np.asarray(t)
                return a, b, c
            """, self.HOT)
        assert _rules_of(found) == ["host-sync"]
        assert len(found) == 3
        assert [f.line for f in found] == [4, 5, 6]

    def test_cold_module_and_markers_pass(self, tmp_path):
        src = """
            import numpy as np
            def step(t):
                a = t.numpy()  # lint: host-sync-ok (deliberate)
                b = np.asarray(t)  # lint: host-sync-ok (end-of-call)
                c = float(1.5)
                d = jnp.asarray(t)
                return a, b, c, d
            """
        assert not _lint_snippet(tmp_path, src, self.HOT)
        # same calls, unmarked, in a non-hot-path module: fine
        bad = """
            import numpy as np
            def helper(t):
                return np.asarray(t.numpy())
            """
        assert not _lint_snippet(tmp_path, bad,
                                 "paddle_tpu/vision/ops.py")


class TestJitRandomRule:
    def test_flags_np_random_in_jitted_fn(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            import jax
            import numpy as np

            @jax.jit
            def decorated(x):
                return x + np.random.randn(4)

            def by_reference(x):
                noise = np.random.normal(size=4)
                return x + noise

            jitted = jax.jit(by_reference)

            def eager(x):
                return x + np.random.randn(4)  # never jitted: fine
            """, "paddle_tpu/nn/whatever.py")
        assert _rules_of(found) == ["jit-random"]
        assert len(found) == 2
        assert {f.line for f in found} == {7, 10}

    def test_stdlib_random_and_to_static(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            import random
            from paddle_tpu.jit import to_static

            @to_static
            def f(x):
                return x * random.random()
            """, "paddle_tpu/nn/whatever.py")
        assert len(found) == 1 and found[0].rule == "jit-random"


class TestBareExceptRule:
    def test_flags_silent_swallow(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            def f():
                try:
                    risky()
                except:
                    pass
            """, "paddle_tpu/utils/x.py")
        assert _rules_of(found) == ["bare-except"]

    def test_recorded_or_reraised_pass(self, tmp_path):
        src = """
            from paddle_tpu.core import monitor
            def f():
                try:
                    risky()
                except:
                    monitor.record_swallowed("f", Exception("x"))
                try:
                    risky()
                except:
                    raise
            """
        assert not _lint_snippet(tmp_path, src, "paddle_tpu/utils/x.py")


class TestMetricNameRule:
    def test_flags_undeclared_literal(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            from ..core import metrics
            def f():
                metrics.counter("totally.undeclared").inc()
                metrics.gauge("comm.bytes").set(1)  # declared: fine
                metrics.counter(name_var).inc()     # dynamic: fine
            """, "paddle_tpu/nn/whatever.py")
        assert _rules_of(found) == ["metric-name"]
        assert len(found) == 1 and "totally.undeclared" in found[0].message

    def test_tests_and_monitor_exempt(self, tmp_path):
        src = """
            from paddle_tpu.profiler import metrics
            metrics.counter("t.anything.goes").inc()
            """
        assert not _lint_snippet(tmp_path, src, "tests/test_whatever.py")
        assert not _lint_snippet(tmp_path, src,
                                 "paddle_tpu/core/monitor.py")


class TestEventNameRule:
    """Flight-recorder event names in the framework must come from
    core/flight_recorder.DECLARED_EVENTS (the metric-name contract
    applied to the black box)."""

    def test_flags_undeclared_literal(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            from ..core import flight_recorder
            def f(kind):
                flight_recorder.record("serve.typo_event", req=1)
                flight_recorder.record("serve.admit", req=1)  # declared
                flight_recorder.record(kind, req=1)   # dynamic: fine
                flight_recorder.record_span("req3.decode", 0, 1)  # span
            """, "paddle_tpu/serving/whatever.py")
        assert _rules_of(found) == ["event-name"]
        assert len(found) == 1 and "serve.typo_event" in found[0].message

    def test_exemptions_and_marker(self, tmp_path):
        src = """
            from . import flight_recorder
            flight_recorder.record("anything.at.all")
            """
        # the declaring module and tests name events freely
        assert not _lint_snippet(tmp_path, src,
                                 "paddle_tpu/core/flight_recorder.py")
        assert not _lint_snippet(tmp_path, src, "tests/test_x.py")
        marked = """
            from ..core import flight_recorder
            flight_recorder.record("x.y")  # lint: event-name-ok (test hook)
            """
        assert not _lint_snippet(tmp_path, marked,
                                 "paddle_tpu/nn/whatever.py")


class TestDeadMetricRule:
    """The metric-name rule pointed the other way: a DECLARED name no
    ``metrics.counter/gauge/histogram`` call under paddle_tpu/ ever
    records is schema rot."""

    MONITOR = "paddle_tpu/core/monitor.py"

    def test_flags_declared_but_never_recorded(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            DECLARED_METRICS = frozenset({
                "serve.requests",
                "zombie.metric.nobody.records",
            })
            """, self.MONITOR)
        assert _rules_of(found) == ["dead-metric"]
        assert len(found) == 1
        assert "zombie.metric.nobody.records" in found[0].message
        # the finding anchors on the stale declaration's line
        assert found[0].line == 4

    def test_recorded_names_pass(self, tmp_path):
        # "serve.requests" is recorded by the real tree; "jit.compile"
        # only via an f-string (f"{target}.compile") — both live.
        # "snippet.local" is recorded by this very module's own call.
        src = """
            from . import metrics
            DECLARED_METRICS = frozenset({
                "serve.requests",
                "jit.compile",
                "snippet.local",
            })
            def record_local():
                metrics.counter("snippet.local").inc()
            """
        assert not _lint_snippet(tmp_path, src, self.MONITOR)

    def test_marker_and_scope(self, tmp_path):
        src = """
            DECLARED_METRICS = frozenset({
                "zombie.allowed",  # lint: dead-metric-ok (wired next PR)
            })
            """
        assert not _lint_snippet(tmp_path, src, self.MONITOR)
        # the rule only fires on the schema-declaring core module
        bad = """
            DECLARED_METRICS = frozenset({"zombie.elsewhere"})
            """
        assert not _lint_snippet(tmp_path, bad,
                                 "paddle_tpu/vision/ops.py")
        assert not _lint_snippet(tmp_path, bad, "tests/test_x.py")


class TestCompileCacheDirRule:
    def test_flags_direct_config_update(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            import jax
            def setup(path):
                jax.config.update("jax_compilation_cache_dir", path)
                jax.config.update("jax_default_matmul_precision",
                                  "highest")   # other keys: fine
            """, "paddle_tpu/inference/predictor.py")
        assert _rules_of(found) == ["compile-cache-dir"]
        assert len(found) == 1 and found[0].line == 4
        assert "enable_compile_cache" in found[0].message

    def test_owner_module_and_marker_pass(self, tmp_path):
        src = """
            import jax
            def setup(path):
                jax.config.update("jax_compilation_cache_dir", path)
            """
        # the owning module sets it freely
        assert not _lint_snippet(tmp_path, src,
                                 "paddle_tpu/jit/compile_cache.py")
        # ...everyone else needs the marker
        marked = """
            import jax
            def restore(prev):
                jax.config.update("jax_compilation_cache_dir", prev)  # lint: compile-cache-dir-ok (test restore)
            """
        assert not _lint_snippet(tmp_path, marked,
                                 "tests/test_whatever.py")
        # and tests/benches are NOT exempt without one
        assert _lint_snippet(tmp_path, src, "tests/test_whatever.py")
        assert _lint_snippet(tmp_path, src, "bench.py")


class TestLockDisciplineRule:
    ALLOC = "paddle_tpu/generation/paged_cache.py"
    ENGINE = "paddle_tpu/serving/engine.py"

    BAD_ALLOC = """
        import threading

        class PageAllocator:
            def __init__(self):
                self._lock = threading.Lock()
                self._free = [1, 2, 3]
                self._ref = {}

            def free_row(self, pages):
                for p in pages:
                    n = self._ref.get(p, 0) - 1
                    if n <= 0:
                        self._ref.pop(p, None)
                        self._free.append(p)

            def forget(self, key):
                del self._page_key[key]
        """

    def test_flags_unlocked_allocator_writes(self, tmp_path):
        found = _lint_snippet(tmp_path, self.BAD_ALLOC, self.ALLOC)
        assert _rules_of(found) == ["lock-discipline"]
        # _ref.pop + _free.append + the del-statement mutation form
        assert len(found) == 3
        # __init__ construction is exempt (no second thread exists yet)
        assert all(f.line > 10 for f in found)

    def test_locked_writes_and_markers_pass(self, tmp_path):
        src = """
            import threading

            class PageAllocator:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._free = []
                    self._ref = {}

                def free_row(self, pages):
                    with self._lock:
                        for p in pages:
                            self._free.append(p)
                            self._ref.pop(p, None)

                def _maybe_release(self, page):  # lint: lock-discipline-ok (caller holds self._lock)
                    self._free.append(page)

                def reads_are_free(self):
                    return len(self._free)
            """
        assert not _lint_snippet(tmp_path, src, self.ALLOC)
        # same writes in a module OUTSIDE the scoped set: fine
        assert not _lint_snippet(tmp_path, self.BAD_ALLOC,
                                 "paddle_tpu/vision/ops.py")

    def test_flags_engine_slot_and_queue_writes(self, tmp_path):
        src = """
            import threading

            class ServingEngine:
                def __init__(self):
                    self._qlock = threading.Lock()
                    self._pump_lock = threading.RLock()
                    self._queue = []
                    self._slots = [None] * 4

                def submit(self, req):
                    self._queue.append(req)

                def finish(self, slot):
                    self._slots[slot] = None

                def locked_ok(self, req, slot):
                    with self._qlock:
                        self._queue.append(req)
                    with self._pump_lock:
                        self._slots[slot] = req
            """
        found = _lint_snippet(tmp_path, src, self.ENGINE)
        assert _rules_of(found) == ["lock-discipline"]
        assert len(found) == 2
        assert {f.line for f in found} == {12, 15}

    def test_line_marker_escapes_with_reason(self, tmp_path):
        src = """
            import threading

            class ServingEngine:
                def __init__(self):
                    self._pump_lock = threading.RLock()
                    self._slots = [None] * 4

                def _evict(self, slot):
                    self._slots[slot] = None  # lint: lock-discipline-ok (caller holds pump lock)
            """
        assert not _lint_snippet(tmp_path, src, self.ENGINE)


class TestChaosMarkerRule:
    def test_flags_unmarked_import(self, tmp_path):
        found = _lint_snippet(tmp_path, """
            from paddle_tpu.utils import fault_injection

            def test_kill():
                fault_injection.poison_batch(None)
            """, "tests/test_whatever.py")
        assert _rules_of(found) == ["chaos-marker"]

    def test_module_class_and_function_markers_pass(self, tmp_path):
        src = """
            import pytest
            pytestmark = pytest.mark.chaos
            from paddle_tpu.utils import fault_injection
            """
        assert not _lint_snippet(tmp_path, src, "tests/test_a.py")
        src = """
            import pytest

            @pytest.mark.chaos
            def test_kill():
                from paddle_tpu.utils import fault_injection as fi
                fi.poison_batch(None)
            """
        assert not _lint_snippet(tmp_path, src, "tests/test_b.py")
        # non-test files import the harness freely (it's the library)
        src = "from paddle_tpu.utils import fault_injection\n"
        assert not _lint_snippet(tmp_path, src,
                                 "paddle_tpu/utils/__init__.py")


class TestEngine:
    def test_all_rules_registered(self):
        assert set(RULES) == {"host-sync", "jit-random", "bare-except",
                              "metric-name", "chaos-marker",
                              "compile-cache-dir", "dead-metric",
                              "event-name", "lock-discipline"}

    def test_syntax_error_is_reported_not_raised(self, tmp_path):
        found = _lint_snippet(tmp_path, "def broken(:\n",
                              "paddle_tpu/x.py")
        assert found and found[0].rule == "syntax"

    def test_nonexistent_path_fails_not_clean(self, tmp_path):
        """A typo'd path must FAIL (exit 2), never read as a clean
        pass — CI with `tools.lint paddel_tpu` must go red."""
        with pytest.raises(FileNotFoundError, match="does not exist"):
            lint_paths(["definitely_not_a_dir_xyz"])
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "paddel_tpu"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 2
        assert "error:" in proc.stderr

    def test_lint_paths_walks_directories(self, tmp_path):
        pkg = tmp_path / "paddle_tpu" / "sub"
        pkg.mkdir(parents=True)
        (pkg / "mod.py").write_text(
            "try:\n    pass\nexcept:\n    pass\n")
        found = lint_paths(["paddle_tpu"], root=str(tmp_path))
        assert len(found) == 1 and found[0].rule == "bare-except"
        assert found[0].path == "paddle_tpu/sub/mod.py"


class TestTreeIsClean:
    def test_shipped_tree_lints_clean(self):
        """THE tier-1 lint gate: the exact command CI runs must exit 0
        on the shipped tree — any new violation fails here with the
        offending findings in the assertion message."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "paddle_tpu", "tests"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, \
            f"framework lint found violations:\n{proc.stdout}"

    def test_cli_rules_listing(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--rules"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0
        for rule_name in RULES:
            assert rule_name in proc.stdout

    def test_cli_nonzero_on_finding(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("try:\n    pass\nexcept:\n    pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", str(bad)],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "bare-except" in proc.stdout

    def test_cli_from_foreign_cwd_still_scopes_rules(self, tmp_path):
        """Relative paths resolve against the REPO root, not the cwd:
        invoked from a neutral directory (the verify-skill workflow),
        the lint must still walk the real tree — a bad cwd reads as
        '0 file(s)', never as a vacuous clean pass — and the
        repo-relative paths that scope host-sync/metric-name must
        survive absolute-path invocation too."""
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "paddle_tpu", "tests"],
            cwd=str(tmp_path), capture_output=True, text=True,
            timeout=120, env=env)
        assert proc.returncode == 0, proc.stdout
        n_files = int(proc.stderr.split("file(s)")[0].strip())
        assert n_files > 100  # the walk matched the real tree

    def test_path_scoped_rules_apply_under_absolute_invocation(self):
        """A hot-path file addressed ABSOLUTELY must still resolve to
        its repo-relative identity (the host-sync scoping bug class:
        relpath-vs-cwd silently disabling scoped rules)."""
        from tools.lint import lint_paths
        target = os.path.join(REPO_ROOT, "paddle_tpu", "hapi",
                              "model.py")
        stats = {}
        findings = lint_paths([target], stats=stats)
        assert stats["files"] == 1
        # the shipped file is clean — but ONLY because its deliberate
        # sync points carry markers; strip the markers in a shadow copy
        # at the same relpath under a mirrored root to prove the rule
        # actually fires on this path
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            shadow = os.path.join(td, "paddle_tpu", "hapi")
            os.makedirs(shadow)
            with open(target) as f:
                src = f.read().replace("# lint: host-sync-ok", "#")
            with open(os.path.join(shadow, "model.py"), "w") as f:
                f.write(src)
            hits = lint_paths(["paddle_tpu"], root=td)
            assert any(f.rule == "host-sync" for f in hits)
        assert findings == []
