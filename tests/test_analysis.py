"""Program-auditor coverage (paddle_tpu.analysis): golden fixtures of
deliberately bad programs (each seeded defect must be reported with the
right severity and source location), the audit API surface, collective
accounting cross-checked against the runtime counters, and — the tier-1
acceptance gates — audits of the flagship programs: TrainStep,
DistributedTrainStep on the dryrun hybrid mesh, the generation
prefill/decode pair, and the Predictor's AOT bucket executables, with
zero ERROR findings and full donation coverage asserted."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import analysis, optimizer
from paddle_tpu.analysis import Severity
from paddle_tpu.core import monitor
from paddle_tpu.profiler import metrics


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.disable()
    metrics.reset()
    yield
    metrics.disable()
    metrics.reset()


# ------------------------------------------------- golden bad programs
# Each fixture seeds exactly one defect; the auditor must report it with
# the right check id, severity, and (where an equation exists) a source
# location pointing INTO this file.


def _fixture_missed_donation(params, batch):
    return [p - 0.1 * batch.sum() for p in params]


def _fixture_hidden_io_callback(x):
    jax.experimental.io_callback(
        lambda a: None, None, x, ordered=True)
    return x * 2


def _fixture_fp64_leak(x):
    return x.astype(jnp.float64) * 2.0


_BIG_CONST = None  # lazily built 8 MiB array (module import stays cheap)


def _fixture_baked_constant(x):
    global _BIG_CONST
    if _BIG_CONST is None:
        _BIG_CONST = np.ones((1024, 2048), np.float32)  # 8 MiB
    return x @ jnp.asarray(_BIG_CONST)


def _fixture_bf16_promotion(x):
    y = x * np.float32(1.5)  # f32 scalar re-widens the bf16 block
    return y.sum()


class TestGoldenFixtures:
    def test_missed_donation(self):
        params = [jnp.zeros((128, 128)), jnp.zeros((64, 64))]
        report = analysis.audit(_fixture_missed_donation, params,
                                jnp.ones((8, 16)))
        misses = report.by_check("donation.miss")
        assert len(misses) == 2
        assert all(f.severity == Severity.WARNING for f in misses)
        assert report.donation_coverage == 0.0
        sizes = sorted(f.data["bytes"] for f in misses)
        assert sizes == [64 * 64 * 4, 128 * 128 * 4]
        # donating repairs it
        fixed = analysis.audit(_fixture_missed_donation, params,
                               jnp.ones((8, 16)), donate=(0,))
        assert not fixed.by_check("donation.miss")
        assert fixed.donation_coverage == 1.0

    def test_hidden_io_callback(self):
        report = analysis.audit(_fixture_hidden_io_callback,
                                jnp.ones((4,)))
        hits = report.by_check("host_sync.callback")
        assert len(hits) == 1
        assert hits[0].severity == Severity.ERROR
        assert "io_callback" in hits[0].message
        assert "test_analysis.py" in hits[0].source
        with pytest.raises(analysis.AuditError, match="io_callback"):
            report.raise_on_error()

    def test_debug_print_is_warning_not_error(self):
        def prog(x):
            jax.debug.print("x={x}", x=x)
            return x + 1

        report = analysis.audit(prog, jnp.ones((4,)))
        hits = report.by_check("host_sync.callback")
        assert len(hits) == 1
        assert hits[0].severity == Severity.WARNING
        report.raise_on_error()  # warnings don't fail the gate

    def test_fp64_leak(self):
        try:
            jax.config.update("jax_enable_x64", True)
            report = analysis.audit(_fixture_fp64_leak,
                                    jnp.ones((8,), jnp.float32))
        finally:
            jax.config.update("jax_enable_x64", False)
        errs = report.by_check("dtype.fp64")
        assert errs and all(f.severity == Severity.ERROR for f in errs)
        assert any("test_analysis.py" in f.source for f in errs)

    def test_baked_constant_over_budget(self):
        report = analysis.audit(_fixture_baked_constant,
                                jnp.ones((4, 1024)))
        hits = report.by_check("const.baked")
        assert len(hits) == 1
        assert hits[0].severity == Severity.ERROR
        assert hits[0].data["bytes"] == 8 * 1024 * 1024
        # a budget above the const passes
        ok = analysis.audit(_fixture_baked_constant, jnp.ones((4, 1024)),
                            const_budget_bytes=16 * 1024 * 1024)
        assert not ok.by_check("const.baked")

    def test_fp32_promotion_in_bf16_block(self):
        report = analysis.audit(_fixture_bf16_promotion,
                                jnp.ones((8, 8), jnp.bfloat16),
                                bf16_compute=True)
        hits = report.by_check("dtype.bf16_upcast")
        assert hits and all(f.severity == Severity.WARNING for f in hits)
        assert any("test_analysis.py" in f.source for f in hits)
        # the same program is CLEAN without the declared-bf16 contract
        plain = analysis.audit(_fixture_bf16_promotion,
                               jnp.ones((8, 8), jnp.bfloat16))
        assert not plain.by_check("dtype.bf16_upcast")


# ------------------------------------------------------------ audit api


class TestAuditAPI:
    def test_checks_subset_and_unknown_check(self):
        report = analysis.audit(_fixture_hidden_io_callback,
                                jnp.ones((4,)), checks=("constants",))
        assert not report.by_check("host_sync")  # pass not selected
        with pytest.raises(ValueError, match="unknown detector"):
            analysis.audit(lambda x: x, jnp.ones((2,)),
                           checks=("nope",))

    def test_allow_suppresses_to_info(self):
        report = analysis.audit(
            _fixture_hidden_io_callback, jnp.ones((4,)),
            allow=("host_sync",))
        hits = report.by_check("host_sync.callback")
        assert hits and hits[0].severity == Severity.INFO
        assert hits[0].data.get("allowed")
        report.raise_on_error()  # suppressed: the gate passes
        # a scoped allow that does NOT match keeps the error
        strict = analysis.audit(
            _fixture_hidden_io_callback, jnp.ones((4,)),
            allow=("host_sync@some_other_file.py",))
        assert strict.errors

    def test_findings_counted_into_monitor(self):
        metrics.enable()
        analysis.audit(_fixture_hidden_io_callback, jnp.ones((4,)))
        snap = metrics.snapshot()
        key = ("analysis.findings{check=host_sync.callback,"
               "severity=ERROR}")
        assert snap[key]["value"] == 1
        assert snap["analysis.findings"]["value"] >= 1

    def test_register_detector(self):
        def too_many_eqns(ctx):
            from paddle_tpu.analysis.jaxpr_utils import walk_eqns
            n = sum(1 for _ in walk_eqns(ctx.closed_jaxpr))
            return [analysis.Finding("custom.eqn_budget",
                                     Severity.WARNING,
                                     f"{n} eqns")] if n > 1 else []

        analysis.register_detector("custom_eqn_budget", too_many_eqns)
        try:
            report = analysis.audit(lambda x: x * 2 + 1, jnp.ones((4,)))
            assert report.by_check("custom.eqn_budget")
            with pytest.raises(ValueError, match="already registered"):
                analysis.register_detector("custom_eqn_budget",
                                           too_many_eqns)
        finally:
            del analysis.DETECTORS["custom_eqn_budget"]

    def test_out_shape_exposed_from_the_same_trace(self):
        """report.out_shape == eval_shape of the program, recovered
        from the audit's own trace (chained audits never re-trace)."""
        report = analysis.audit(lambda x: (x * 2, x.sum()),
                                jnp.ones((4,), jnp.float32))
        a, b = report.out_shape
        assert a.shape == (4,) and b.shape == ()
        assert a.dtype == jnp.float32

    def test_unchecked_donation_coverage_raises(self):
        """A report whose audit excluded the donation pass must not
        satisfy a coverage gate with a vacuous 1.0."""
        report = analysis.audit(_fixture_missed_donation,
                                [jnp.zeros((64, 64))], jnp.ones((8,)),
                                checks=("host_sync",))
        assert not report.donation_checked
        with pytest.raises(ValueError, match="without the donation"):
            _ = report.donation_coverage
        assert "n/a" in report.summary()  # summary still printable

    def test_generation_audit_name_override(self):
        from paddle_tpu.generation.api import GenerationSession
        model = _tiny_gpt()
        sess = GenerationSession(model)
        pre, dec = sess.audit(2, 16, 128, name="bucket16")
        assert pre.name == "bucket16.prefill"
        assert dec.name == "bucket16.decode"

    def test_abstract_inputs_never_execute(self):
        calls = []

        def prog(x):
            calls.append(1)  # runs at TRACE time only
            return x + 1

        sds = jax.ShapeDtypeStruct((8, 8), jnp.float32)
        report = analysis.audit(prog, sds)
        assert report.findings == [] and calls == [1]


# -------------------------------------------- collective accounting


class TestCollectiveAccounting:
    @pytest.fixture(autouse=True)
    def _default_world_mesh(self):
        from paddle_tpu.distributed import topology
        prev = topology.get_hybrid_communicate_group()
        topology.set_hybrid_communicate_group(None)
        yield
        topology.set_hybrid_communicate_group(prev)

    def _world_psum(self):
        from paddle_tpu.core.jaxshim import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()).reshape(-1), ("world",))
        return shard_map(lambda a: jax.lax.psum(a, "world"), mesh=mesh,
                         in_specs=P("world"), out_specs=P("world"),
                         check_vma=False)

    def test_static_bytes_match_measured_counters(self):
        """The static per-axis accounting equals what one real
        execution records into comm.bytes{axis=...} — the PR-2
        cross-check the detector exists for."""
        from paddle_tpu.distributed import collective
        metrics.enable()
        x = paddle.ones([8, 8])
        collective.all_reduce(x)
        snap = metrics.snapshot()
        metrics.disable()

        report = analysis.audit(self._world_psum(), jnp.ones((8, 8)))
        assert report.collectives == {"world": 8 * 8 * 4}
        checked = analysis.cross_check_collectives(report, snap)
        assert not checked.by_check("collective.mismatch")

    def test_cross_check_flags_divergence(self):
        report = analysis.audit(self._world_psum(), jnp.ones((8, 8)))
        fake = {"comm.bytes{axis=world,op=all_reduce}": {"value": 999}}
        checked = analysis.cross_check_collectives(report, fake)
        bad = checked.by_check("collective.mismatch")
        assert bad and bad[0].severity == Severity.WARNING
        assert bad[0].data == {"axis": "world", "static": 256,
                               "measured": 999}

    def test_cross_check_refuses_unchecked_report(self):
        """A report whose audit EXCLUDED the collectives pass has no
        static accounting — cross-checking it must raise, not report a
        spurious 0-vs-measured mismatch."""
        report = analysis.audit(self._world_psum(), jnp.ones((8, 8)),
                                checks=("host_sync",))
        assert not report.collectives_checked
        fake = {"comm.bytes{axis=world,op=all_reduce}": {"value": 256}}
        with pytest.raises(ValueError, match="without the 'collectives'"):
            analysis.cross_check_collectives(report, fake)


# ------------------------------------------------- flagship tier-1 gates


def _tiny_gpt():
    from paddle_tpu.models.gpt import gpt
    paddle.seed(0)
    return gpt("test-tiny")


class TestFlagshipGates:
    """THE audit gates: the invariants PRs 2-6 established, enforced
    statically on every flagship program. Zero ERROR findings; donation
    coverage 1.0 for train state and the KV cache."""

    def test_train_step_audit_clean(self):
        model = _tiny_gpt()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        from paddle_tpu.jit.api import TrainStep
        step = TrainStep(model, opt,
                         lambda out, lbl: model.loss(out, lbl))
        ids = np.random.RandomState(0).randint(
            0, 512, (2, 16)).astype(np.int32)
        report = step.audit(paddle.to_tensor(ids),
                            paddle.to_tensor(ids.astype(np.int64)))
        report.raise_on_error()
        assert not report.by_check("host_sync")
        assert not report.by_check("donation.miss")
        # params + optimizer state fully donated: in-place HBM updates
        assert report.donation_coverage == 1.0
        # ISSUE-14: every flagship audit carries a memory plan
        assert report.memory is not None
        assert report.memory.peak_bytes > 0

    def test_distributed_step_audit_clean(self):
        from paddle_tpu.distributed import fleet, topology
        from paddle_tpu.models.ernie import ernie
        prev = topology.get_hybrid_communicate_group()
        try:
            paddle.seed(0)
            fleet.init(strategy=fleet.DistributedStrategy(
                hybrid_configs={"mp_degree": 2}))
            model = ernie("test-tiny")
            opt = optimizer.AdamW(learning_rate=1e-3,
                                  parameters=model.parameters())
            step = fleet.DistributedTrainStep(
                model, opt, lambda out, lab: model.loss(out, lab))
            rng = np.random.RandomState(0)
            ids = paddle.to_tensor(
                rng.randint(0, 512, (4, 16)).astype(np.int32))
            labels = (
                paddle.to_tensor(
                    rng.randint(0, 512, (4, 16)).astype(np.int64)),
                paddle.to_tensor(
                    rng.randint(0, 2, (4,)).astype(np.int64)))
            report = step.audit(ids, labels)
        finally:
            topology.set_hybrid_communicate_group(prev)
        report.raise_on_error()
        assert not report.by_check("donation.miss")
        assert report.donation_coverage == 1.0
        assert report.memory is not None           # ISSUE-14 threading

    def test_generation_pair_audit_clean(self):
        from paddle_tpu.generation.api import GenerationSession
        model = _tiny_gpt()
        sess = GenerationSession(model)
        # a mid-fit audit must trace the EVAL program, exactly like
        # every dispatch path (train-mode dropout baked into the traced
        # jaxpr would gate a program that is never served)
        model.train()
        prefill, decode = sess.audit(2, 16, 128)
        assert not model.training
        prefill.raise_on_error()
        decode.raise_on_error()
        for rep in (prefill, decode):
            assert not rep.by_check("host_sync")
            assert not rep.by_check("const.baked")
        # the KV cache is donated through the decode step (audited at
        # the TPU intent even on the CPU test backend)
        assert decode.donation_coverage == 1.0
        assert not decode.by_check("donation.miss")
        # ISSUE-14: the pair carries memory plans, and donation keeps
        # the decode peak below two cache copies' worth of growth
        assert prefill.memory is not None and decode.memory is not None
        assert decode.memory.donated_bytes > 0

    def test_predictor_bucket_audit_clean(self):
        from paddle_tpu.inference import Config, create_predictor
        model = _tiny_gpt()
        ids = np.random.RandomState(0).randint(
            0, 512, (2, 16)).astype(np.int32)
        cfg = Config().from_layer(
            model, input_spec=[paddle.to_tensor(ids)])
        cfg.enable_generation(max_new_tokens=6,
                              prefill_buckets=(16, 32),
                              max_batch=2, eos_token_id=None)
        pred = create_predictor(cfg)
        reports = pred.audit_generation()
        assert set(reports) == {("prefill", 16), ("decode", 16),
                                ("prefill", 32), ("decode", 32)}
        for key, rep in reports.items():
            rep.raise_on_error()
            if key[0] == "decode":
                assert rep.donation_coverage == 1.0
            assert rep.memory is not None          # ISSUE-14 threading
        pred.audit_forward().raise_on_error()

    def test_predictor_audit_mirrors_serving_precision(self):
        """Under a low-precision config, run() casts floating feeds to
        bf16 before dispatch; audit_forward must trace THAT program —
        bf16 inputs, bf16 outputs — not the declared-fp32 one."""
        from paddle_tpu.inference import Config, PrecisionType, \
            create_predictor
        from paddle_tpu import nn
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU())
        x = paddle.to_tensor(np.zeros((2, 8), np.float32))
        cfg = Config().from_layer(net, input_spec=[x])
        cfg.enable_tpu(precision=PrecisionType.Bfloat16)
        pred = create_predictor(cfg)
        report = pred.audit_forward()
        report.raise_on_error()
        out_dtypes = {np.dtype(s.dtype).name
                      for s in jax.tree_util.tree_leaves(report.out_shape)}
        assert out_dtypes == {"bfloat16"}

    def test_audit_catches_seeded_regression(self):
        """Sanity that the gates FAIL when a flagship program actually
        regresses: a TrainStep whose step_fn sneaks in a pure_callback
        must produce an ERROR (the gate is not vacuously green)."""
        model = _tiny_gpt()
        opt = optimizer.SGD(learning_rate=1e-2,
                            parameters=model.parameters())
        from paddle_tpu.jit.api import TrainStep
        step = TrainStep(model, opt,
                         lambda out, lbl: model.loss(out, lbl))
        inner = step._step_fn

        def poisoned(params, opt_state, lr, step_no, *batch):
            jax.pure_callback(lambda: np.float32(0.0),
                              jax.ShapeDtypeStruct((), np.float32))
            return inner(params, opt_state, lr, step_no, *batch)

        step._step_fn = poisoned
        ids = np.random.RandomState(0).randint(
            0, 512, (2, 16)).astype(np.int32)
        report = step.audit(paddle.to_tensor(ids),
                            paddle.to_tensor(ids.astype(np.int64)))
        assert report.errors
        with pytest.raises(analysis.AuditError, match="pure_callback"):
            report.raise_on_error()
