"""Chaos tier for the SLO watchtower: a 4-rank fleet with one slowed
rank whose store is fault-injected mid-poll — the straggler alert must
fire EXACTLY once, stay latched while the rank is slow, and resolve
after the slowdown ends; then a SIGTERM landing while an SLO alert is
firing must leave a flight-recorder dump that contains the firing
alert's spans (the ISSUE-17 post-mortem contract: the black box a dying
process leaves behind is enough to reconstruct the alert)."""
import glob
import json
import os
import signal

import pytest

import paddle_tpu.utils.fault_injection as fi
from paddle_tpu.core import flight_recorder, monitor, slo, timeseries
from paddle_tpu.distributed import fleet_telemetry as ft
from paddle_tpu.distributed.resilience import GracefulShutdown
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import metrics

pytestmark = pytest.mark.chaos

NS = "__fleet/chaos-slo"


@pytest.fixture(autouse=True)
def _clean_slate():
    metrics.disable()
    metrics.reset()
    timeseries._reset_for_tests()
    slo._reset_for_tests()
    flight_recorder.clear()
    yield
    metrics.disable()
    metrics.reset()
    timeseries._reset_for_tests()
    slo._reset_for_tests()
    flight_recorder.clear()


@pytest.fixture()
def store():
    s = TCPStore("127.0.0.1", 0, is_master=True)
    yield s
    s.shutdown_server()


def _publish(store, rank, seq, count, total_s):
    """One hand-rolled rank payload: an absolute train.step_time
    histogram (what MetricsPublisher's full publish carries)."""
    store.set(f"{NS}/m/{rank}", {
        "seq": seq, "rank": rank, "incarnation": 0,
        "replica": str(rank), "pid": 1000 + rank, "clock_offset_ns": 0,
        "delta": {"full": True, "metrics": {
            "train.step_time": {"kind": "histogram", "bounds": [10.0],
                                "counts": [count, 0], "count": count,
                                "sum": total_s}}},
        "health": {"ready": True},
    })
    store.set_timestamp(f"{NS}/ts/{rank}")


class TestStragglerUnderStoreFaults:
    def test_slow_rank_fires_once_and_resolves(self, store):
        metrics.enable()
        agg = ft.FleetAggregator(store, period_s=1000.0,
                                 stale_after_s=60.0, expected_ranks=4,
                                 namespace=NS)
        means = {0: 0.1, 1: 0.1, 2: 0.1, 3: 0.1}
        totals = {r: 0.0 for r in means}
        counts = {r: 0 for r in means}

        def advance(seq, rank_means):
            for r, m in rank_means.items():
                counts[r] += 10
                totals[r] += m * 10
                _publish(store, r, seq, counts[r], totals[r])

        # poll 1: everyone healthy
        advance(1, means)
        agg.poll()
        assert agg.straggler.straggler_ranks() == []
        # poll 2: rank 2 turns 10x slow, AND the store delays every
        # payload read — the detector must still see the poll through
        advance(2, {**means, 2: 1.0})
        with fi.StoreFaults(delay=0.05, ops=("get",), count=4):
            agg.poll()
        assert agg.straggler.straggler_ranks() == [2]
        hz = agg.healthz()
        assert hz["stragglers"] == [2]
        assert hz["ranks"]["2"]["straggler"] is True
        assert hz["ranks"]["2"]["ready"] is True  # marked, not dropped
        assert hz["ranks"]["0"]["straggler"] is False
        # poll 3: still slow — the alert is LATCHED, no re-fire
        advance(3, {**means, 2: 1.0})
        agg.poll()
        assert agg.straggler.straggler_ranks() == [2]
        # poll 4: back to normal — resolves
        advance(4, means)
        agg.poll()
        assert agg.straggler.straggler_ranks() == []
        assert agg.healthz()["stragglers"] == []
        # exactly one detected + one resolved event, both for rank 2
        evs = [f for _, k, f in flight_recorder.events()
               if k == "train.straggler"]
        assert [(e["rank"], e["phase"]) for e in evs] == \
            [(2, "detected"), (2, "resolved")]
        assert evs[0]["z"] > 3.5
        snap = metrics.snapshot()
        assert snap["train.straggler{rank=2}"]["value"] == 1
        # the fleet /slo section carries the flags
        rep = agg.slo_report()
        assert rep["stragglers"] == []
        assert rep["scope"] == "fleet"


class TestSigtermMidFire:
    def _drive_slo_to_firing(self):
        """ok -> pending -> firing on a 2s/10s chaos spec: good TTFTs
        t=1..10, all-bad from t=11; fast trips at t=12 (pending), slow
        at t=16 (firing) — the pending->firing escalation becomes the
        span the post-mortem dump must contain."""
        spec = slo.SLO("chaos-ttft", "latency", "serve.ttft", 0.05,
                       window_s=10, fast_window_s=2, percentile=50)
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=50)
        ev = slo.SLOEvaluator(ring, slos=[spec], scope="process")
        ring.sample(now=0.0)
        states = {}
        for t in range(1, 17):
            monitor.record_serve_ttft(0.01 if t <= 10 else 1.0)
            ring.sample(now=float(t))
            states[t] = ev.evaluate(now=float(t))["chaos-ttft"]
        assert states[11] == "ok"        # fast burn exactly 1.0
        assert states[12] == "pending"
        assert states[15] == "pending"
        assert states[16] == "firing"
        return ev

    def test_dump_contains_firing_alert_and_straggler(
            self, tmp_path, monkeypatch):
        metrics.enable()
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        ev = self._drive_slo_to_firing()
        assert ev.states()["chaos-ttft"] == "firing"
        # a straggler flagged at SIGTERM time rides in the same dump
        det = slo.StragglerDetector(min_ranks=3)
        det.observe({0: (10, 1.0), 1: (10, 1.0), 2: (10, 1.0),
                     3: (10, 1.0)})
        det.observe({0: (20, 2.0), 1: (20, 2.0), 2: (20, 11.0),
                     3: (20, 2.0)})
        assert det.straggler_ranks() == [2]
        # clear the per-reason rate limit + per-process cap so THIS
        # dump is never swallowed by earlier chaos tests' dumps
        flight_recorder._recorder._last_auto.pop("preemption", None)
        flight_recorder._recorder._auto_dumps = 0
        with GracefulShutdown(store=None, exit_on_save=False) as gs:
            os.kill(os.getpid(), signal.SIGTERM)
            assert gs.check(step=5)      # dump, no exit
        dumps = glob.glob(
            str(tmp_path / "flightrecorder_preemption_*.json"))
        assert len(dumps) == 1
        with open(dumps[0]) as f:
            doc = json.load(f)
        tev = doc["traceEvents"]
        # the escalation span (pending -> firing build-up window)
        spans = [e for e in tev if e.get("ph") == "X"
                 and e.get("name") == "slo:chaos-ttft"]
        assert [s["args"]["phase"] for s in spans] == ["escalation"]
        # the firing instant with its burn rates
        firing = [e for e in tev if e.get("name") == "slo.firing"]
        assert len(firing) == 1
        assert firing[0]["args"]["slo"] == "chaos-ttft"
        assert firing[0]["args"]["burn_fast"] > 1.0
        assert firing[0]["args"]["burn_slow"] > 1.0
        # the straggler instant for the slowed rank
        strag = [e for e in tev if e.get("name") == "train.straggler"]
        assert [(s["args"]["rank"], s["args"]["phase"])
                for s in strag] == [(2, "detected")]
        # the preemption instant itself (the dump's trigger)
        assert any(e.get("name") == "resilience.preemption"
                   for e in tev)
        assert doc["metadata"]["reason"] == "preemption"
        # and the post-mortem CLI reconstructs the alert from it
        from tools import slo_report
        text = slo_report.report(slo_report.load_paths([dumps[0]]))
        assert "chaos-ttft" in text
        assert "firing" in text and "escalation" in text
        assert "detected" in text
