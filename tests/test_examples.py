"""Every script in examples/ must run end-to-end with --smoke (tiny
CPU-fast settings). Mirrors the reference's book/e2e tests
(python/paddle/fluid/tests/book/) which keep the documented user
journeys executable.
"""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = os.path.join(_ROOT, "examples")

# detection_train compiles the full PP-YOLOE stack (~30s on CPU) and
# graph_and_pointcloud ~15s: tier-2 via the slow marker
# each entry overlaps dedicated tier-1 suites (test_e2e_mnist,
# test_fused_resnet/test_models, test_models bert, fleet tests)
_SLOW_SCRIPTS = {"detection_train.py", "graph_and_pointcloud.py",
                 "mnist_lenet.py", "resnet_train.py",
                 "bert_finetune.py", "gpt2_hybrid_parallel.py"}
SCRIPTS = [pytest.param(f, marks=pytest.mark.slow)
           if f in _SLOW_SCRIPTS else f
           for f in sorted(os.listdir(_EXAMPLES)) if f.endswith(".py")]


@pytest.mark.parametrize("script", SCRIPTS)
def test_example_smoke(script):
    env = dict(os.environ)
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(_EXAMPLES, script), "--smoke"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=_ROOT)
    assert proc.returncode == 0, (
        f"{script} failed\nstdout:\n{proc.stdout[-3000:]}\n"
        f"stderr:\n{proc.stderr[-3000:]}")
