"""Context-parallel (ring / Ulysses) attention on the 8-device CPU mesh,
compared against single-device dense attention (the reference's
collective-test pattern: per-rank program vs numpy golden,
unittests/collective/)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.parallel import ring_attention, ulysses_attention
from paddle_tpu.nn.functional.attention import _sdpa_xla


def _mk_mesh(sp):
    hcg = topology.HybridCommunicateGroup(
        dp_degree=len(jax.devices()) // sp, sp_degree=sp)
    topology.set_hybrid_communicate_group(hcg)
    return hcg.mesh


def _rand(*shape, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).standard_normal(shape)
        .astype(np.float32) * 0.3)


@pytest.fixture(autouse=True)
def _restore_mesh():
    prev = topology.get_hybrid_communicate_group()
    yield
    topology.set_hybrid_communicate_group(prev)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        _mk_mesh(sp=4)
        b, s, h, d = 2, 64, 4, 16
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))
        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, causal=causal))(q, k, v)
        ref = _sdpa_xla(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grads_match_dense(self):
        _mk_mesh(sp=4)
        b, s, h, d = 1, 32, 2, 8
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(_sdpa_xla(q, k, v, is_causal=True) ** 2)

        gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, r in zip(gr, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       atol=1e-4, rtol=1e-4)

    def test_trivial_axis_fallback(self):
        _mk_mesh(sp=1)
        q, k, v = (_rand(1, 16, 2, 8, seed=i) for i in range(3))
        out = ring_attention(q, k, v, causal=True)
        ref = _sdpa_xla(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        _mk_mesh(sp=4)
        b, s, h, d = 2, 64, 4, 16  # heads divisible by sp
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, causal=causal))(q, k, v)
        ref = _sdpa_xla(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_heads_not_divisible_raises(self):
        _mk_mesh(sp=4)
        q, k, v = (_rand(1, 32, 3, 8, seed=i) for i in range(3))
        with pytest.raises(ValueError):
            ulysses_attention(q, k, v)


class TestGPTSequenceParallel:
    def test_gpt_with_ring_attention_trains(self):
        """GPT forward+backward with sp axis active end to end."""
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu import optimizer
        strategy = fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 2, "sp_degree": 4})
        fleet.init(strategy=strategy)
        paddle.seed(0)
        model = gpt("test-tiny", sequence_parallel=True)
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        step = fleet.DistributedTrainStep(
            model, opt, lambda logits, labels: model.loss(logits, labels))
        ids = np.random.RandomState(0).randint(0, 512, (4, 32)).astype(
            np.int32)
        loss = step(paddle.to_tensor(ids),
                    paddle.to_tensor(ids.astype(np.int64)))
        assert np.isfinite(float(loss))
