"""Golden-comparison sweep (OpTest harness) over the newer op surface:
dual-path (eager + jit) output checks vs numpy and numeric-grad checks
(reference op_test.py pattern, SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output

rng = np.random.RandomState(0)


def test_kron_golden():
    a = rng.randn(3, 2).astype(np.float32)
    b = rng.randn(2, 4).astype(np.float32)
    check_output(paddle.kron, np.kron, [a, b])
    check_grad(paddle.kron, [a, b], grad_idx=0)
    check_grad(paddle.kron, [a, b], grad_idx=1)


def test_trace_diagonal_golden():
    x = rng.randn(4, 5).astype(np.float32)
    check_output(paddle.trace, np.trace, [x])
    check_output(paddle.diagonal, np.diagonal, [x])
    check_grad(paddle.trace, [x])
    check_grad(paddle.diagonal, [x])


def test_lerp_golden():
    a = rng.randn(8).astype(np.float32)
    b = rng.randn(8).astype(np.float32)
    check_output(lambda x, y: paddle.lerp(x, y, 0.3),
                 lambda x, y: x + 0.3 * (y - x), [a, b])
    check_grad(lambda x, y: paddle.lerp(x, y, 0.3), [a, b], grad_idx=0)
    check_grad(lambda x, y: paddle.lerp(x, y, 0.3), [a, b], grad_idx=1)


def test_diff_golden():
    x = rng.randn(6).astype(np.float32)
    check_output(paddle.diff, np.diff, [x])
    check_grad(paddle.diff, [x])


def test_take_along_axis_golden():
    x = rng.randn(4, 6).astype(np.float32)
    idx = rng.randint(0, 6, (4, 3))
    check_output(
        lambda a: paddle.take_along_axis(a, paddle.to_tensor(
            idx.astype(np.int32)), 1),
        lambda a: np.take_along_axis(a, idx, 1), [x])
    check_grad(
        lambda a: paddle.take_along_axis(a, paddle.to_tensor(
            idx.astype(np.int32)), 1), [x])


def test_index_add_golden():
    x = rng.randn(5, 3).astype(np.float32)
    upd = rng.randn(2, 3).astype(np.float32)
    index = np.array([1, 3], np.int32)

    def np_ref(a, u):
        out = a.copy()
        out[index] += u
        return out

    check_output(
        lambda a, u: paddle.index_add(a, paddle.to_tensor(index), 0, u),
        np_ref, [x, upd])
    check_grad(
        lambda a, u: paddle.index_add(a, paddle.to_tensor(index), 0, u),
        [x, upd], grad_idx=1)


def test_segment_ops_golden():
    from paddle_tpu import geometric
    data = rng.randn(6, 4).astype(np.float32)
    ids = np.array([0, 0, 1, 1, 1, 2], np.int32)
    # hoisted: to_tensor INSIDE a traced fn would make ids a tracer and
    # defeat the eager num_segments inference
    ids_t = paddle.to_tensor(ids)

    def np_sum(d):
        return np.stack([d[ids == s].sum(0) for s in range(3)])

    def np_mean(d):
        return np.stack([d[ids == s].mean(0) for s in range(3)])

    check_output(lambda d: geometric.segment_sum(d, ids_t, 3),
                 np_sum, [data])
    check_output(lambda d: geometric.segment_mean(d, ids_t, 3),
                 np_mean, [data])
    check_grad(lambda d: geometric.segment_sum(d, ids_t, 3), [data])


def test_grid_sample_grad_golden():
    from paddle_tpu.nn import functional as F
    x = rng.rand(1, 2, 5, 5).astype(np.float32)
    ys, xs = np.meshgrid(np.linspace(-0.8, 0.8, 4),
                         np.linspace(-0.8, 0.8, 4), indexing="ij")
    grid = np.stack([xs, ys], -1)[None].astype(np.float32)
    check_grad(
        lambda a: F.grid_sample(a, paddle.to_tensor(grid)), [x],
        rtol=5e-2, atol=5e-3)


def test_pixel_shuffle_golden():
    x = rng.randn(1, 8, 3, 3).astype(np.float32)

    def np_ref(a):
        n, c, h, w = a.shape
        r = 2
        out = a.reshape(n, c // (r * r), r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, c // (r * r), h * r, w * r)

    check_output(lambda a: paddle.pixel_shuffle(a, 2), np_ref, [x])
    check_grad(lambda a: paddle.pixel_shuffle(a, 2), [x])


def test_fft_golden():
    from paddle_tpu import fft
    x = rng.randn(3, 16).astype(np.float32)
    check_output(fft.rfft, np.fft.rfft, [x], rtol=1e-4, atol=1e-4)
    check_output(fft.fftshift, np.fft.fftshift, [x])


def test_masked_fill_golden():
    x = rng.randn(4, 4).astype(np.float32)
    mask = rng.rand(4, 4) > 0.5
    check_output(
        lambda a: paddle.masked_fill(a, paddle.to_tensor(mask), -1.0),
        lambda a: np.where(mask, -1.0, a), [x])
    check_grad(
        lambda a: paddle.masked_fill(a, paddle.to_tensor(mask), -1.0),
        [x])


def test_logcumsumexp_like_composites_golden():
    x = rng.randn(5, 3).astype(np.float32)
    check_output(paddle.logsumexp,
                 lambda a: np.log(np.exp(a).sum()), [x],
                 rtol=1e-4, atol=1e-5)
    check_grad(paddle.logsumexp, [x])


def test_rnn_cell_grad_golden():
    from paddle_tpu import nn
    paddle.seed(3)
    cell = nn.GRUCell(4, 4)
    x = rng.randn(2, 4).astype(np.float32)

    def fwd(a):
        out, _ = cell(a)
        return out

    check_grad(fwd, [x], rtol=5e-2, atol=5e-3)
