"""OpTest-style golden-comparison harness.

Reference analog: python/paddle/fluid/tests/unittests/op_test.py:333 —
check_output runs ops through both static and dygraph paths vs numpy;
check_grad compares analytic grads against finite differences
(get_numeric_gradient, op_test.py:140). Here the two execution paths are
(a) the eager tape and (b) jax.jit-traced, and grads check the tape's vjp
against central finite differences.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(op_fn: Callable, np_ref: Callable, inputs: Sequence,
                 kwargs=None, rtol=1e-5, atol=1e-6, check_jit=True):
    """Run op eagerly and jitted; compare both to the numpy reference."""
    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(np.asarray(i)) for i in inputs]
    expected = np_ref(*[np.asarray(i) for i in inputs], **kwargs)

    out_eager = op_fn(*tensors, **kwargs)
    _assert_close(out_eager, expected, rtol, atol, "eager")

    if check_jit:
        jitted = jax.jit(lambda *raw: _unwrap_tree(
            op_fn(*[Tensor(r) for r in raw], **kwargs)))
        out_jit = jitted(*[t.data for t in tensors])
        _assert_close(out_jit, expected, rtol, atol, "jit")


# per-op bf16 tolerance whitelist (reference analog:
# unittests/white_list/op_accuracy_white_list.py — ops allowed looser
# low-precision error bounds). bf16 eps ~ 7.8e-3; default bound ~4 ulp.
BF16_TOL_WHITELIST = {
    "default": (3e-2, 3e-2),
    "exp": (6e-2, 6e-2), "expm1": (6e-2, 6e-2),
    "cumprod": (8e-2, 8e-2), "logsumexp": (6e-2, 6e-2),
    "softmax": (2e-2, 2e-2), "matmul": (6e-2, 6e-1),
    "tanh": (2e-2, 2e-2), "erf": (2e-2, 2e-2),
    "var": (8e-2, 8e-2), "std": (6e-2, 6e-2),
    "mean": (2e-2, 2e-2), "sum": (6e-2, 4e-1),
    "addmm": (6e-2, 6e-1), "kron": (4e-2, 4e-2),
    "logit": (8e-2, 8e-2), "log1p": (4e-2, 4e-2),
}


def check_output_bf16(op_fn: Callable, np_ref: Callable,
                      inputs: Sequence, kwargs=None, name: str = None,
                      check_jit: bool = True):
    """Low-precision golden check: float inputs cast to bfloat16, op runs
    in bf16, result compared (as f32) to the f32 numpy reference under
    the per-op whitelist tolerance."""
    import jax.numpy as jnp
    kwargs = kwargs or {}
    rtol, atol = BF16_TOL_WHITELIST.get(
        name or getattr(op_fn, "op_name", ""),
        BF16_TOL_WHITELIST["default"])
    arrays = [np.asarray(i) for i in inputs]
    expected = np_ref(*[a.astype(np.float32)
                        if np.issubdtype(a.dtype, np.floating) else a
                        for a in arrays], **kwargs)
    tensors = []
    for a in arrays:
        t = paddle.to_tensor(a)
        if np.issubdtype(a.dtype, np.floating):
            t = t.astype("bfloat16")
        tensors.append(t)
    out = op_fn(*tensors, **kwargs)
    leaves = jax.tree_util.tree_leaves(_unwrap_tree(out))
    exp_leaves = expected if isinstance(expected, (list, tuple)) else \
        [expected]
    for o, e in zip(leaves, exp_leaves):
        np.testing.assert_allclose(
            np.asarray(o).astype(np.float32),
            np.asarray(e).astype(np.float32), rtol=rtol, atol=atol,
            err_msg=f"[bf16] output mismatch for {name or op_fn}")
    if check_jit:
        jitted = jax.jit(lambda *raw: _unwrap_tree(
            op_fn(*[Tensor(r) for r in raw], **kwargs)))
        out_jit = jitted(*[t.data for t in tensors])
        for o, e in zip(jax.tree_util.tree_leaves(_unwrap_tree(out_jit)),
                        exp_leaves):
            np.testing.assert_allclose(
                np.asarray(o).astype(np.float32),
                np.asarray(e).astype(np.float32), rtol=rtol, atol=atol,
                err_msg=f"[bf16-jit] output mismatch for {name or op_fn}")


def check_grad(op_fn: Callable, inputs: Sequence, grad_idx=0, kwargs=None,
               eps=1e-3, rtol=1e-2, atol=1e-3, reduce_to_scalar=True):
    """Compare tape gradients to central finite differences (float64 on CPU
    would be ideal; we use float32 + loose tolerances like the reference's
    fp32 white-list)."""
    kwargs = kwargs or {}
    arrays = [np.asarray(i, np.float32) for i in inputs]
    tensors = [paddle.to_tensor(a, stop_gradient=(k != grad_idx))
               for k, a in enumerate(arrays)]

    out = op_fn(*tensors, **kwargs)
    loss = out.sum() if reduce_to_scalar else out
    loss.backward()
    analytic = np.asarray(tensors[grad_idx].grad.numpy(), np.float64)

    def scalar_f(x_flat):
        args = [a.copy() for a in arrays]
        args[grad_idx] = x_flat.reshape(arrays[grad_idx].shape).astype(
            np.float32)
        o = op_fn(*[paddle.to_tensor(a) for a in args], **kwargs)
        return float(o.sum().numpy())

    x0 = arrays[grad_idx].reshape(-1).astype(np.float64)
    numeric = np.zeros_like(x0)
    for i in range(x0.size):
        xp = x0.copy()
        xp[i] += eps
        xm = x0.copy()
        xm[i] -= eps
        numeric[i] = (scalar_f(xp) - scalar_f(xm)) / (2 * eps)
    numeric = numeric.reshape(arrays[grad_idx].shape)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                               err_msg=f"grad mismatch for {op_fn}")


def _unwrap_tree(out):
    return jax.tree_util.tree_map(
        lambda t: t.data if isinstance(t, Tensor) else t, out,
        is_leaf=lambda x: isinstance(x, Tensor))


def _assert_close(out, expected, rtol, atol, tag):
    out_leaves = jax.tree_util.tree_leaves(
        _unwrap_tree(out))
    exp_leaves = expected if isinstance(expected, (list, tuple)) else \
        [expected]
    for o, e in zip(out_leaves, exp_leaves):
        o_arr, e_arr = np.asarray(o), np.asarray(e)
        # complex outputs compare as complex (casting to float64 would
        # silently drop the imaginary part)
        dt = np.complex128 if (np.iscomplexobj(o_arr) or
                               np.iscomplexobj(e_arr)) else np.float64
        np.testing.assert_allclose(
            o_arr.astype(dt), e_arr.astype(dt),
            rtol=rtol, atol=atol, err_msg=f"[{tag}] output mismatch")
