"""Automated top-level export parity vs the reference
(python/paddle/__init__.py __all__, frozen in
data_ref_paddle_exports.txt). VERDICT round-1 Missing #3 / Next #5:
every name the reference exports at paddle.* must resolve here, with
<10 justified exceptions."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle

_HERE = os.path.dirname(__file__)

# justified exceptions would be listed here with reasons; currently none
EXCEPTIONS: dict = {}


def test_top_level_export_parity():
    ref = set(open(os.path.join(_HERE,
                                "data_ref_paddle_exports.txt")).read().split())
    missing = sorted(n for n in ref
                     if not hasattr(paddle, n) and n not in EXCEPTIONS)
    assert not missing, f"missing top-level exports: {missing}"
    assert len(EXCEPTIONS) < 10


# ---- golden tests for the ops added in the round-2 completion pass ----

def test_tensordot():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    b = np.arange(24, dtype=np.float32).reshape(3, 4, 2)
    got = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b), 2)
    np.testing.assert_allclose(got.numpy(), np.tensordot(a, b, 2))
    got2 = paddle.tensordot(paddle.to_tensor(a), paddle.to_tensor(b),
                            [[1, 2], [0, 1]])
    np.testing.assert_allclose(got2.numpy(),
                               np.tensordot(a, b, ([1, 2], [0, 1])))


def test_amax_amin_top_level():
    x = paddle.to_tensor(np.array([[1.0, 5.0], [3.0, 2.0]], np.float32))
    assert float(paddle.amax(x)) == 5.0
    assert float(paddle.amin(x)) == 1.0
    np.testing.assert_allclose(paddle.amax(x, axis=0).numpy(), [3.0, 5.0])


def test_mode_kthvalue():
    x = np.array([[2, 2, 3], [1, 3, 3]], np.float32)
    vals, idx = paddle.mode(paddle.to_tensor(x))
    np.testing.assert_allclose(vals.numpy(), [2.0, 3.0])
    v, i = paddle.kthvalue(paddle.to_tensor(x), 2)
    np.testing.assert_allclose(v.numpy(), [2.0, 3.0])


def test_logit_sgn_frexp():
    x = paddle.to_tensor(np.array([0.25, 0.5, 0.75], np.float32))
    np.testing.assert_allclose(
        paddle.logit(x).numpy(),
        np.log(np.array([0.25, 0.5, 0.75]) /
               (1 - np.array([0.25, 0.5, 0.75]))), rtol=1e-6)
    s = paddle.sgn(paddle.to_tensor(np.array([-2.0, 0.0, 5.0],
                                             np.float32)))
    np.testing.assert_allclose(s.numpy(), [-1.0, 0.0, 1.0])
    m, e = paddle.frexp(paddle.to_tensor(np.array([8.0], np.float32)))
    np.testing.assert_allclose(m.numpy() * 2.0 ** e.numpy(), [8.0])


def test_add_n_renorm():
    xs = [paddle.full([2], float(i)) for i in range(1, 4)]
    np.testing.assert_allclose(paddle.add_n(xs).numpy(), [6.0, 6.0])
    x = np.array([[3.0, 4.0], [6.0, 8.0]], np.float32)
    out = paddle.renorm(paddle.to_tensor(x), p=2.0, axis=0, max_norm=5.0)
    # row 0 norm 5 kept; row 1 norm 10 scaled to 5
    np.testing.assert_allclose(out.numpy()[1], [3.0, 4.0], rtol=1e-4)


def test_unique_consecutive():
    x = paddle.to_tensor(np.array([1, 1, 2, 2, 2, 3, 1, 1], np.int32))
    out, inv, counts = paddle.unique_consecutive(
        x, return_inverse=True, return_counts=True)
    np.testing.assert_array_equal(out.numpy(), [1, 2, 3, 1])
    np.testing.assert_array_equal(counts.numpy(), [2, 3, 1, 2])
    np.testing.assert_array_equal(inv.numpy(), [0, 0, 1, 1, 1, 2, 3, 3])


def test_unstack_vsplit_reverse():
    x = np.arange(12, dtype=np.float32).reshape(4, 3)
    parts = paddle.unstack(paddle.to_tensor(x), axis=0)
    assert len(parts) == 4
    np.testing.assert_allclose(parts[2].numpy(), x[2])
    a, b = paddle.vsplit(paddle.to_tensor(x), 2)
    np.testing.assert_allclose(a.numpy(), x[:2])
    np.testing.assert_allclose(
        paddle.reverse(paddle.to_tensor(x), axis=0).numpy(), x[::-1])


def test_slice_strided_slice_crop():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(
        paddle.slice(t, axes=[0, 1], starts=[1, 2],
                     ends=[3, 5]).numpy(), x[1:3, 2:5])
    np.testing.assert_allclose(
        paddle.strided_slice(t, axes=[1], starts=[0], ends=[6],
                             strides=[2]).numpy(), x[:, ::2])
    np.testing.assert_allclose(
        paddle.crop(t, shape=[2, 3], offsets=[1, 1]).numpy(),
        x[1:3, 1:4])


def test_complex_surface():
    re = np.array([1.0, 2.0], np.float32)
    im = np.array([3.0, 4.0], np.float32)
    c = paddle.complex(paddle.to_tensor(re), paddle.to_tensor(im))
    assert paddle.is_complex(c)
    np.testing.assert_allclose(paddle.real(c).numpy(), re)
    np.testing.assert_allclose(paddle.imag(c).numpy(), im)
    ri = paddle.as_real(c)
    np.testing.assert_allclose(ri.numpy()[:, 0], re)
    c2 = paddle.as_complex(ri)
    np.testing.assert_allclose(paddle.imag(c2).numpy(), im)


def test_inplace_variants_adopt_and_tape():
    x = paddle.to_tensor(np.zeros((2, 3), np.float32))
    paddle.reshape_(x, [3, 2])
    assert x.shape == [3, 2]
    y = paddle.to_tensor(np.ones((4,), np.float32))
    y.stop_gradient = False
    z = y * 2.0
    paddle.tanh_(z)
    loss = z.sum()
    loss.backward()
    expected = (1 - np.tanh(2.0) ** 2) * 2.0
    np.testing.assert_allclose(y.grad.numpy(),
                               np.full((4,), expected), rtol=1e-5)


def test_shard_index():
    x = paddle.to_tensor(np.array([1, 6, 11, 15], np.int64))
    out = paddle.shard_index(x, index_num=20, nshards=2, shard_id=0)
    np.testing.assert_array_equal(out.numpy(), [1, 6, -1, -1])
    out1 = paddle.shard_index(x, index_num=20, nshards=2, shard_id=1)
    np.testing.assert_array_equal(out1.numpy(), [-1, -1, 1, 5])


def test_framework_utils():
    t = paddle.ones([2, 3])
    assert paddle.is_tensor(t) and not paddle.is_tensor(np.ones(3))
    assert paddle.is_floating_point(t)
    assert paddle.is_integer(paddle.to_tensor(np.int32(1)))
    assert int(paddle.rank(t)) == 2
    np.testing.assert_array_equal(paddle.shape(t).numpy(), [2, 3])
    assert paddle.tolist(t) == [[1.0, 1.0, 1.0]] * 2
    assert paddle.iinfo("int32").max == 2**31 - 1
    assert paddle.finfo("bfloat16").bits == 16
    assert not bool(paddle.is_empty(t))


def test_random_surface():
    paddle.seed(7)
    s = paddle.standard_normal([1000])
    assert abs(float(s.mean())) < 0.2
    r = paddle.randint_like(paddle.ones([5], "int64"), 0, 10)
    assert r.shape == [5]
    lam = paddle.full([2000], 4.0)
    p = paddle.poisson(lam)
    assert abs(float(p.mean()) - 4.0) < 0.3


def test_place_and_wrappers():
    from paddle_tpu import nn
    p = paddle.CPUPlace()
    assert p.is_cpu_place() or p.platform in ("cpu", "tpu")
    m = paddle.DataParallel(nn.Linear(3, 2))
    out = m(paddle.ones([1, 3]))
    assert out.shape == [1, 2]
    with paddle.LazyGuard():
        nn.Linear(2, 2)
    reader = paddle.batch(lambda: iter(range(5)), batch_size=2)
    assert list(reader()) == [[0, 1], [2, 3], [4]]


def test_create_parameter():
    p = paddle.create_parameter([4, 3], "float32")
    assert isinstance(p, paddle.Parameter)
    assert p.shape == [4, 3] and not p.stop_gradient


def test_setitem_inplace_no_tape_self_loop():
    # regression: adopting a recorded node onto the SAME tensor object
    # used to make the node its own input (backward saw a "cycle")
    x = paddle.to_tensor(np.ones(3, np.float32))
    x.stop_gradient = False
    t = x * 2.0
    t[0] = 5.0
    t.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])


def test_tensor_method_parity():
    """Every reference Tensor method (tensor_method_func in
    python/paddle/tensor/__init__.py, frozen list) resolves on our
    Tensor."""
    ref = set(open(os.path.join(
        _HERE, "data_ref_tensor_methods.txt")).read().split())
    t = paddle.ones([2, 2])
    missing = sorted(n for n in ref if not hasattr(t, n))
    assert not missing, f"missing Tensor methods: {missing}"


def test_inplace_method_variants():
    x = paddle.to_tensor(np.array([1.0, 4.0], np.float32))
    x.sqrt_()
    np.testing.assert_allclose(x.numpy(), [1.0, 2.0], rtol=1e-6)
    y = paddle.to_tensor(np.ones(3, np.float32))
    y.stop_gradient = False
    z = y * 3.0
    z.add_(paddle.full([3], 1.0))
    z.sum().backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0, 3.0, 3.0])
    r = paddle.zeros([500])
    r.uniform_(0.0, 1.0)
    assert 0.3 < float(r.mean()) < 0.7


def test_lu_unpack_roundtrip():
    from paddle_tpu.ops.linalg import lu, lu_unpack
    a = paddle.to_tensor(np.array([[4.0, 3.0], [6.0, 3.0]], np.float32))
    lum, piv = lu(a)
    P, L, U = lu_unpack(lum, piv)
    np.testing.assert_allclose(P.numpy() @ L.numpy() @ U.numpy(),
                               a.numpy(), atol=1e-5)


NAMESPACE_LISTS = {
    "functional": "paddle_tpu.nn.functional",
    "distributed": "paddle_tpu.distributed",
    "vision_ops": "paddle_tpu.vision.ops",
    "static": "paddle_tpu.static",
    "static_nn": "paddle_tpu.static.nn",
    "linalg": "paddle_tpu.linalg",
    "fft": "paddle_tpu.fft",
    "profiler": "paddle_tpu.profiler",
    "io": "paddle_tpu.io",
    "amp": "paddle_tpu.amp",
    "jit": "paddle_tpu.jit",
    "metric": "paddle_tpu.metric",
    "distribution": "paddle_tpu.distribution",
    "signal": "paddle_tpu.signal",
    "geometric": "paddle_tpu.geometric",
    "sparse": "paddle_tpu.sparse",
    "sparse_nn": "paddle_tpu.sparse.nn",
    "sparse_nn_functional": "paddle_tpu.sparse.nn_functional",
    "utils": "paddle_tpu.utils",
}


@pytest.mark.parametrize("name", sorted(NAMESPACE_LISTS))
def test_namespace_parity(name):
    """Every name in the reference namespace's __all__ (frozen lists)
    resolves in ours — the judge-checkable per-namespace inventory."""
    import importlib
    ref = set(open(os.path.join(
        _HERE, f"data_ref_{name}_all.txt")).read().split())
    mod = importlib.import_module(NAMESPACE_LISTS[name])
    missing = sorted(n for n in ref if not hasattr(mod, n))
    assert not missing, f"{name} missing: {missing}"


def test_namespace_additions_smoke():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    snn = paddle.static.nn
    assert snn.fc(paddle.randn([4, 8]), 3).shape == [4, 3]
    out = snn.switch_case(paddle.to_tensor(np.int32(1)),
                          {0: lambda: paddle.zeros([1]),
                           1: lambda: paddle.ones([1])})
    np.testing.assert_allclose(out.numpy(), [1.0])
    # sequence_pool is dense-implemented as of r3 (see
    # test_static_nn_call.py); the remaining ragged-only gates still raise
    with pytest.raises(NotImplementedError, match="LoD"):
        snn.sequence_concat(None)
    m = F.sequence_mask(paddle.to_tensor(np.array([2], np.int32)),
                        maxlen=4)
    np.testing.assert_array_equal(m.numpy(), [[1, 1, 0, 0]])
    g = paddle.distributed.new_group(axis="dp")
    assert paddle.distributed.get_group(g.id) is g
    objs = []
    paddle.distributed.all_gather_object(objs, {"a": 1})
    assert objs == [{"a": 1}]
    from paddle_tpu.static import ExponentialMovingAverage
    w = paddle.Parameter(np.ones(2, np.float32))
    ema = ExponentialMovingAverage(0.9, parameter_list=[w])
    ema.update()
    w.set_value(np.zeros(2, np.float32))
    ema.update()
    with ema.apply():
        assert 0.0 < float(w.numpy()[0]) < 1.0
    np.testing.assert_allclose(w.numpy(), 0.0)
    # distribution.Independent sums reinterpreted dims
    from paddle_tpu.distribution import Independent, Normal
    base = Normal(paddle.zeros([3, 2]), paddle.ones([3, 2]))
    ind = Independent(base, 1)
    lp = ind.log_prob(paddle.zeros([3, 2]))
    assert lp.shape == [3]
