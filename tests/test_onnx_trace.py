"""Trace-based ONNX export (VERDICT r3 Next #3): jaxpr -> ONNX for
real models — ResNet-18 (residual adds, convs, pools) and an ERNIE
encoder block (attention einsums, softmax, layernorm, gelu) — each
numerically validated by EXECUTING the emitted graph with the in-repo
numpy evaluator (onnx_eval) against the framework forward. Reference
analog: python/paddle/onnx/export.py:21 (paddle2onnx trace path).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.onnx_eval import load_model, run_onnx
from paddle_tpu.onnx_trace import trace_to_onnx


def _roundtrip(layer, inputs, tmp_path, name, atol=2e-4):
    p = trace_to_onnx(layer, inputs, str(tmp_path / name))
    ref = layer(*[paddle.to_tensor(a) for a in inputs])
    ref = np.asarray(ref.data)
    feed = {"input" if i == 0 else f"input_{i}": a
            for i, a in enumerate(inputs)}
    out = run_onnx(p, feed)[0]
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=atol)
    return p


def test_resnet18_onnx_roundtrip(tmp_path):
    from paddle_tpu.models.resnet import resnet18
    paddle.seed(0)
    m = resnet18(num_classes=10)
    m.eval()
    x = np.random.RandomState(0).randn(1, 3, 32, 32).astype(np.float32)
    p = _roundtrip(m, [x], tmp_path, "resnet18", atol=5e-4)
    nodes, inits, _, _ = load_model(p)
    ops = {n.op for n in nodes}
    # the graph really contains the structural ops of a residual net
    assert "Conv" in ops and "Add" in ops and "MaxPool" in ops
    assert sum(1 for n in nodes if n.op == "Conv") == 20  # 18 + 2 downsample


def test_ernie_block_onnx_roundtrip(tmp_path):
    from paddle_tpu.models.ernie import ErnieConfig, ErnieLayer
    paddle.seed(1)
    cfg = ErnieConfig(hidden_size=64, num_layers=2, num_heads=4)
    blk = ErnieLayer(cfg)
    blk.eval()
    x = np.random.RandomState(0).randn(2, 8, 64).astype(np.float32)
    p = _roundtrip(blk, [x], tmp_path, "ernie_block", atol=5e-4)
    nodes, _, _, _ = load_model(p)
    ops = {n.op for n in nodes}
    # attention contractions ride Einsum; softmax decomposes to
    # exp/reduce/div; layernorm to mul/sub/sqrt
    assert "Einsum" in ops and "Exp" in ops and "Sqrt" in ops


def test_mlp_residual_function_export(tmp_path):
    """Plain function (not a Layer) with a residual add + softmax."""
    from paddle_tpu import nn
    paddle.seed(2)
    fc1 = nn.Linear(16, 16)
    fc2 = nn.Linear(16, 16)

    def f(x):
        h = paddle.nn.functional.relu(fc1(x))
        h = fc2(h) + x           # residual
        return paddle.nn.functional.softmax(h, axis=-1)

    x = np.random.RandomState(3).randn(4, 16).astype(np.float32)
    p = trace_to_onnx(f, [x], str(tmp_path / "mlp_res"))
    ref = np.asarray(f(paddle.to_tensor(x)).data)
    out = run_onnx(p, {"input": x})[0]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_export_api_routes_models(tmp_path):
    """paddle.onnx.export(format='onnx') handles non-Sequential models
    through the trace path (r3 raised NotImplementedError here)."""
    from paddle_tpu import onnx as onnx_api
    from paddle_tpu.models.resnet import resnet18
    paddle.seed(0)
    m = resnet18(num_classes=4)
    m.eval()
    x = np.random.RandomState(1).randn(1, 3, 32, 32).astype(np.float32)
    path = onnx_api.export(m, str(tmp_path / "r18"),
                           input_spec=[paddle.to_tensor(x)],
                           format="onnx")
    ref = np.asarray(m(paddle.to_tensor(x)).data)
    out = run_onnx(path, {"input": x})[0]
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)


def test_legacy_sequential_artifact_now_executes(tmp_path):
    """The r3 Sequential walker's artifact runs under the evaluator too
    (discharges the 'loads anywhere' claim numerically)."""
    from paddle_tpu import nn
    from paddle_tpu.onnx_proto import export_onnx
    paddle.seed(4)
    m = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                      nn.LayerNorm(32), nn.Linear(32, 4),
                      nn.Softmax())
    m.eval()
    x = np.random.RandomState(5).randn(6, 8).astype(np.float32)
    p = export_onnx(m, str(tmp_path / "seq"), [None, 8])
    ref = np.asarray(m(paddle.to_tensor(x)).data)
    out = run_onnx(p, {"input": x})[0]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_full_ernie_model_onnx_roundtrip(tmp_path):
    """WHOLE ErnieForPretraining (embedding Gather, 2 encoder blocks,
    pooler, MLM + SOP heads) through export -> numpy-execute ->
    compare. The dynamic embedding lookup rides ONNX Gather."""
    from paddle_tpu.models.ernie import ernie
    paddle.seed(0)
    m = ernie("test-tiny")
    m.eval()
    ids = np.random.RandomState(0).randint(0, 512, (2, 8)).astype(np.int32)
    p = trace_to_onnx(m, [ids], str(tmp_path / "ernie_full"))
    outs = run_onnx(p, {"input": ids})
    ref = m(paddle.to_tensor(ids))
    refs = [np.asarray(r.data) for r in
            (ref if isinstance(ref, (list, tuple)) else [ref])]
    assert len(outs) == len(refs)
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o, r, rtol=1e-3, atol=5e-4)
    nodes, _, _, _ = load_model(p)
    assert any(n.op == "Gather" for n in nodes)


def test_full_gpt_model_onnx_roundtrip(tmp_path):
    """WHOLE GPT (tied embeddings, causal mask via Where, LM head)."""
    from paddle_tpu.models.gpt import gpt
    paddle.seed(0)
    m = gpt("test-tiny", num_layers=2)
    m.eval()
    ids = np.random.RandomState(0).randint(0, 512, (2, 8)).astype(np.int32)
    p = trace_to_onnx(m, [ids], str(tmp_path / "gpt_full"))
    out = run_onnx(p, {"input": ids})[0]
    ref = np.asarray(m(paddle.to_tensor(ids)).data)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)


@pytest.mark.slow  # ~5s; static-batch onnx export coverage stays tier-1
def test_dynamic_batch_export(tmp_path):
    """dynamic_batch=True: trace at batch 3, execute at batch 5 — the
    reference's dynamic-batch export. Covers the batch-agnostic
    Reshape-0 / Expand-broadcast / huge-end Slice rewrites and the
    no-batch-constant-folding rule across all three model families."""
    from paddle_tpu.models.ernie import ernie
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.models.resnet import resnet18
    rng = np.random.RandomState(0)
    ids3 = rng.randint(0, 512, (3, 8)).astype(np.int32)
    ids5 = rng.randint(0, 512, (5, 8)).astype(np.int32)

    paddle.seed(0)
    m = ernie("test-tiny")
    m.eval()
    p = trace_to_onnx(m, [ids3], str(tmp_path / "ernie_dyn"),
                      dynamic_batch=True)
    outs = run_onnx(p, {"input": ids5})
    refs = [np.asarray(r.data) for r in m(paddle.to_tensor(ids5))]
    for o, r in zip(outs, refs):
        assert o.shape == r.shape
        np.testing.assert_allclose(o, r, rtol=1e-3, atol=5e-4)

    paddle.seed(0)
    g = gpt("test-tiny", num_layers=2)
    g.eval()
    p = trace_to_onnx(g, [ids3], str(tmp_path / "gpt_dyn"),
                      dynamic_batch=True)
    o = run_onnx(p, {"input": ids5})[0]
    np.testing.assert_allclose(
        o, np.asarray(g(paddle.to_tensor(ids5)).data),
        rtol=1e-3, atol=5e-4)

    paddle.seed(0)
    r18 = resnet18(num_classes=10)
    r18.eval()
    # traced batch 5, run at 7: must NOT collide with the 3-channel
    # input dim (docstring caveat)
    x5i = rng.randn(5, 3, 16, 16).astype(np.float32)
    x7 = rng.randn(7, 3, 16, 16).astype(np.float32)
    p = trace_to_onnx(r18, [x5i], str(tmp_path / "r18_dyn"),
                      dynamic_batch=True)
    o = run_onnx(p, {"input": x7})[0]
    np.testing.assert_allclose(
        o, np.asarray(r18(paddle.to_tensor(x7)).data),
        rtol=1e-3, atol=5e-4)

    # non-broadcasting consumer of a batch-shaped broadcast: the
    # Expand target is built from Shape(input) at runtime
    fc = paddle.nn.Linear(4, 4)

    def f(x):
        ones = paddle.ones([x.shape[0], 1])
        return paddle.concat([fc(x), ones], axis=1)

    xa = rng.randn(3, 4).astype(np.float32)
    xb = rng.randn(6, 4).astype(np.float32)
    p2 = trace_to_onnx(f, [xa], str(tmp_path / "cat_dyn"),
                       dynamic_batch=True)
    o2 = run_onnx(p2, {"input": xb})[0]
    np.testing.assert_allclose(
        o2, np.asarray(f(paddle.to_tensor(xb)).data),
        rtol=1e-4, atol=1e-5)


def test_vit_exports_and_matches(tmp_path):
    """ViT rounds out the exported families (conv stem + patch reshape
    + pre-norm attention blocks + CLS-token head) — the artifact must
    execute to parity on the numpy evaluator."""
    from paddle_tpu import onnx as onnx_api
    from paddle_tpu.models.vit import vit
    paddle.seed(0)
    m = vit("test-tiny", num_classes=4)
    m.eval()
    x = np.random.RandomState(0).randn(1, 3, 32, 32).astype(np.float32)
    path = onnx_api.export(m, str(tmp_path / "vit"),
                           input_spec=[paddle.to_tensor(x)],
                           format="onnx")
    ref = np.asarray(m(paddle.to_tensor(x)).data)
    out = run_onnx(path, {"input": x})[0]
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_unmappable_primitive_raises(tmp_path):
    """Genuinely unmappable ops fail loudly, not silently."""
    def f(x):
        # lax.sort has no mapping in this exporter
        return paddle.sort(x, axis=-1)

    x = np.random.RandomState(6).randn(4, 3).astype(np.float32)
    with pytest.raises(NotImplementedError):
        trace_to_onnx(f, [x], str(tmp_path / "bad"))

    def g(x):
        # two-axis advanced indexing produces a gather outside the
        # axis-gather (jnp.take) and static-index patterns
        idx = paddle.to_tensor(np.array([0, 2], np.int64))
        return x[idx, idx]

    with pytest.raises(NotImplementedError):
        trace_to_onnx(g, [x], str(tmp_path / "bad2"))
