"""Enforce-grade error reporting (VERDICT Next #8).

Reference: paddle/phi/core/enforce.h PADDLE_ENFORCE_* +
infermeta validations (paddle/phi/infermeta/binary.cc) — common misuse
must produce an op-named expected-vs-got message, not a raw XLA
traceback."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.enforce import EnforceError


def test_matmul_shape_mismatch_message():
    a = paddle.ones([2, 3])
    b = paddle.ones([4, 5])
    with pytest.raises(EnforceError, match=r"matmul.*inner dims.*3 != 4"):
        paddle.matmul(a, b)


def test_matmul_transpose_aware():
    a = paddle.ones([2, 3])
    b = paddle.ones([5, 3])
    # valid with transpose_y
    assert paddle.matmul(a, b, transpose_y=True).shape == [2, 5]
    with pytest.raises(EnforceError, match="matmul"):
        paddle.matmul(a, b)


def test_binary_broadcast_message():
    x = paddle.ones([2, 3])
    y = paddle.ones([4])
    with pytest.raises(EnforceError,
                       match=r"add.*broadcast.*\[2, 3\].*\[4\]"):
        paddle.add(x, y)


def test_concat_rank_and_shape():
    with pytest.raises(EnforceError, match=r"concat.*axis"):
        paddle.concat([paddle.ones([2, 2])], axis=5)
    with pytest.raises(EnforceError, match=r"concat.*mismatches"):
        paddle.concat([paddle.ones([2, 2]), paddle.ones([2, 3])], axis=0)


def test_reshape_count_mismatch():
    with pytest.raises(EnforceError, match=r"reshape.*6 elements"):
        paddle.reshape(paddle.ones([2, 3]), [4, 2])
    with pytest.raises(EnforceError, match=r"reshape.*one -1"):
        paddle.reshape(paddle.ones([2, 3]), [-1, -1])


def test_softmax_axis_range():
    import paddle_tpu.nn.functional as F
    with pytest.raises(EnforceError, match=r"softmax.*axis"):
        F.softmax(paddle.ones([2, 3]), axis=4)


def test_linear_feature_mismatch():
    import paddle_tpu.nn.functional as F
    x = paddle.ones([2, 7])
    w = paddle.ones([3, 4])
    with pytest.raises(EnforceError, match=r"linear.*7 != weight rows 3"):
        F.linear(x, w)


def test_transpose_bad_perm():
    with pytest.raises(EnforceError, match=r"transpose.*permutation"):
        paddle.transpose(paddle.ones([2, 3]), perm=[0, 0])


def test_topk_k_range():
    with pytest.raises(EnforceError, match=r"topk.*k must be"):
        paddle.topk(paddle.ones([3]), k=9)


def test_expand_invalid_dim():
    with pytest.raises(EnforceError, match=r"expand.*cannot expand"):
        paddle.expand(paddle.ones([2, 3]), [2, 5])


def test_stack_needs_same_shapes():
    with pytest.raises(EnforceError, match=r"stack.*identical"):
        paddle.stack([paddle.ones([2]), paddle.ones([3])])


def test_bmm_messages():
    with pytest.raises(EnforceError, match=r"bmm.*3-d"):
        paddle.bmm(paddle.ones([2, 2]), paddle.ones([2, 2]))
    with pytest.raises(EnforceError, match=r"bmm.*batch"):
        paddle.bmm(paddle.ones([2, 3, 4]), paddle.ones([5, 4, 3]))


def test_conv2d_channel_mismatch():
    import paddle_tpu.nn.functional as F
    x = paddle.ones([1, 3, 8, 8])
    w = paddle.ones([4, 5, 3, 3])  # expects in_c 5 != 3
    with pytest.raises(EnforceError, match=r"conv2d.*in_channels 3"):
        F.conv2d(x, w)


def test_generic_augment_names_op_and_operands():
    # an op without a dedicated validator still gets op-named context
    with pytest.raises((TypeError, ValueError), match=r"op 'cross'"):
        paddle.cross(paddle.ones([2, 2]), paddle.ones([5]))


def test_valid_calls_unaffected():
    # enforce must not reject correct programs
    assert paddle.matmul(paddle.ones([2, 3]), paddle.ones([3, 4])).shape \
        == [2, 4]
    assert paddle.concat([paddle.ones([1, 2]), paddle.ones([3, 2])],
                         axis=0).shape == [4, 2]
    assert paddle.reshape(paddle.ones([2, 3]), [-1]).shape == [6]
    assert paddle.expand(paddle.ones([1, 3]), [4, 3]).shape == [4, 3]
