"""Enforce-grade error reporting (VERDICT Next #8).

Reference: paddle/phi/core/enforce.h PADDLE_ENFORCE_* +
infermeta validations (paddle/phi/infermeta/binary.cc) — common misuse
must produce an op-named expected-vs-got message, not a raw XLA
traceback."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.enforce import EnforceError


def test_matmul_shape_mismatch_message():
    a = paddle.ones([2, 3])
    b = paddle.ones([4, 5])
    with pytest.raises(EnforceError, match=r"matmul.*inner dims.*3 != 4"):
        paddle.matmul(a, b)


def test_matmul_transpose_aware():
    a = paddle.ones([2, 3])
    b = paddle.ones([5, 3])
    # valid with transpose_y
    assert paddle.matmul(a, b, transpose_y=True).shape == [2, 5]
    with pytest.raises(EnforceError, match="matmul"):
        paddle.matmul(a, b)


def test_binary_broadcast_message():
    x = paddle.ones([2, 3])
    y = paddle.ones([4])
    with pytest.raises(EnforceError,
                       match=r"add.*broadcast.*\[2, 3\].*\[4\]"):
        paddle.add(x, y)


def test_concat_rank_and_shape():
    with pytest.raises(EnforceError, match=r"concat.*axis"):
        paddle.concat([paddle.ones([2, 2])], axis=5)
    with pytest.raises(EnforceError, match=r"concat.*mismatches"):
        paddle.concat([paddle.ones([2, 2]), paddle.ones([2, 3])], axis=0)


def test_reshape_count_mismatch():
    with pytest.raises(EnforceError, match=r"reshape.*6 elements"):
        paddle.reshape(paddle.ones([2, 3]), [4, 2])
    with pytest.raises(EnforceError, match=r"reshape.*one -1"):
        paddle.reshape(paddle.ones([2, 3]), [-1, -1])


def test_softmax_axis_range():
    import paddle_tpu.nn.functional as F
    with pytest.raises(EnforceError, match=r"softmax.*axis"):
        F.softmax(paddle.ones([2, 3]), axis=4)


def test_linear_feature_mismatch():
    import paddle_tpu.nn.functional as F
    x = paddle.ones([2, 7])
    w = paddle.ones([3, 4])
    with pytest.raises(EnforceError, match=r"linear.*7 != weight rows 3"):
        F.linear(x, w)


def test_transpose_bad_perm():
    with pytest.raises(EnforceError, match=r"transpose.*permutation"):
        paddle.transpose(paddle.ones([2, 3]), perm=[0, 0])


def test_topk_k_range():
    with pytest.raises(EnforceError, match=r"topk.*k must be"):
        paddle.topk(paddle.ones([3]), k=9)


def test_expand_invalid_dim():
    with pytest.raises(EnforceError, match=r"expand.*cannot expand"):
        paddle.expand(paddle.ones([2, 3]), [2, 5])


def test_stack_needs_same_shapes():
    with pytest.raises(EnforceError, match=r"stack.*identical"):
        paddle.stack([paddle.ones([2]), paddle.ones([3])])


def test_bmm_messages():
    with pytest.raises(EnforceError, match=r"bmm.*3-d"):
        paddle.bmm(paddle.ones([2, 2]), paddle.ones([2, 2]))
    with pytest.raises(EnforceError, match=r"bmm.*batch"):
        paddle.bmm(paddle.ones([2, 3, 4]), paddle.ones([5, 4, 3]))


def test_conv2d_channel_mismatch():
    import paddle_tpu.nn.functional as F
    x = paddle.ones([1, 3, 8, 8])
    w = paddle.ones([4, 5, 3, 3])  # expects in_c 5 != 3
    with pytest.raises(EnforceError, match=r"conv2d.*in_channels 3"):
        F.conv2d(x, w)


def test_generic_augment_names_op_and_operands():
    # an op without a dedicated validator still gets op-named context
    with pytest.raises((TypeError, ValueError), match=r"op 'cross'"):
        paddle.cross(paddle.ones([2, 2]), paddle.ones([5]))


def test_valid_calls_unaffected():
    # enforce must not reject correct programs
    assert paddle.matmul(paddle.ones([2, 3]), paddle.ones([3, 4])).shape \
        == [2, 4]
    assert paddle.concat([paddle.ones([1, 2]), paddle.ones([3, 2])],
                         axis=0).shape == [4, 2]
    assert paddle.reshape(paddle.ones([2, 3]), [-1]).shape == [6]
    assert paddle.expand(paddle.ones([1, 3]), [4, 3]).shape == [4, 3]


# ---- round-3 breadth (VERDICT r2 Next #7): the next failure magnets


def test_slice_validators():
    with pytest.raises(EnforceError, match=r"slice.*equal length"):
        paddle.slice(paddle.ones([4, 4]), axes=[0, 1], starts=[0],
                     ends=[2, 2])
    with pytest.raises(EnforceError, match=r"strided_slice.*non-zero"):
        paddle.strided_slice(paddle.ones([4]), axes=[0], starts=[0],
                             ends=[4], strides=[0])


def test_pad_validators():
    import paddle_tpu.nn.functional as F
    with pytest.raises(EnforceError, match=r"pad.*even length"):
        F.pad(paddle.ones([2, 2]), [1, 0, 1])
    with pytest.raises(EnforceError, match=r"pad.*mode"):
        F.pad(paddle.ones([2, 2]), [1, 1], mode="bogus")


def test_gather_scatter_validators():
    with pytest.raises(EnforceError, match=r"gather_nd.*<= x.ndim"):
        paddle.gather_nd(paddle.ones([2, 2]),
                         paddle.to_tensor(np.zeros((1, 3), np.int64)))
    with pytest.raises(EnforceError, match=r"scatter.*trailing dims"):
        paddle.scatter(paddle.ones([4, 3]),
                       paddle.to_tensor(np.array([0], np.int64)),
                       paddle.ones([1, 5]))
    with pytest.raises(EnforceError, match=r"scatter_nd_add.*updates"):
        paddle.scatter_nd_add(
            paddle.ones([4, 3]),
            paddle.to_tensor(np.zeros((2, 1), np.int64)),
            paddle.ones([2, 7]))


def test_pool_validators():
    import paddle_tpu.nn.functional as F
    with pytest.raises(EnforceError, match=r"max_pool2d.*4-d"):
        F.max_pool2d(paddle.ones([2, 3, 8]), 2)
    with pytest.raises(EnforceError, match=r"avg_pool1d.*3-d"):
        F.avg_pool1d(paddle.ones([2, 3, 8, 8]), 2)
    with pytest.raises(EnforceError, match=r"kernel_size needs 2"):
        F.max_pool2d(paddle.ones([2, 3, 8, 8]), [2, 2, 2])


def test_conv_transpose_validators():
    import paddle_tpu.nn.functional as F
    # transpose weights are [in, out//groups, kh, kw]
    with pytest.raises(EnforceError,
                       match=r"conv2d_transpose.*weight.shape\[0\]"):
        F.conv2d_transpose(paddle.ones([1, 3, 8, 8]),
                           paddle.ones([5, 4, 3, 3]))
    with pytest.raises(EnforceError, match=r"conv3d.*5-d"):
        F.conv3d(paddle.ones([1, 3, 8, 8]), paddle.ones([4, 3, 3, 3, 3]))


def test_norm_validators():
    import paddle_tpu.nn.functional as F
    with pytest.raises(EnforceError, match=r"group_norm.*divide"):
        F.group_norm(paddle.ones([2, 6, 4, 4]), num_groups=4)
    with pytest.raises(EnforceError,
                       match=r"instance_norm.*channel count"):
        F.instance_norm(paddle.ones([2, 3, 4, 4]),
                        weight=paddle.ones([5]))


def test_interpolate_grid_sample_validators():
    import paddle_tpu.nn.functional as F
    with pytest.raises(EnforceError, match=r"interpolate.*required"):
        F.interpolate(paddle.ones([1, 3, 8, 8]))
    with pytest.raises(EnforceError, match=r"mutually exclusive"):
        F.interpolate(paddle.ones([1, 3, 8, 8]), size=[4, 4],
                      scale_factor=2)
    with pytest.raises(EnforceError, match=r"grid_sample.*last dim"):
        F.grid_sample(paddle.ones([1, 3, 8, 8]),
                      paddle.ones([1, 4, 4, 3]))


def test_misc_r3_validators():
    with pytest.raises(EnforceError, match=r"kthvalue.*k must be"):
        paddle.kthvalue(paddle.ones([4]), k=9)
    with pytest.raises(EnforceError, match=r"cross.*size 3"):
        paddle.cross(paddle.ones([2, 4]), paddle.ones([2, 4]), axis=1)
    with pytest.raises(EnforceError, match=r"dot.*equal-shape"):
        paddle.dot(paddle.ones([3]), paddle.ones([4]))
    with pytest.raises(EnforceError, match=r"diagonal.*must differ"):
        paddle.diagonal(paddle.ones([3, 3]), axis1=0, axis2=0)
    with pytest.raises(EnforceError, match=r"temporal_shift.*divide"):
        import paddle_tpu.nn.functional as F
        F.temporal_shift(paddle.ones([3, 4, 2, 2]), seg_num=2)
    with pytest.raises(EnforceError, match=r"pixel_shuffle.*divide"):
        import paddle_tpu.nn.functional as F
        F.pixel_shuffle(paddle.ones([1, 6, 2, 2]), 2)


def test_r3_valid_calls_unaffected():
    import paddle_tpu.nn.functional as F
    assert F.max_pool2d(paddle.ones([1, 3, 8, 8]), 2).shape \
        == [1, 3, 4, 4]
    assert paddle.gather_nd(
        paddle.ones([2, 3]),
        paddle.to_tensor(np.array([[0, 1]], np.int64))).shape == [1]
    assert F.conv2d_transpose(paddle.ones([1, 3, 4, 4]),
                              paddle.ones([3, 5, 3, 3])).shape \
        == [1, 5, 6, 6]
    assert paddle.slice(paddle.ones([4, 4]), [0], [1], [3]).shape \
        == [2, 4]
    out, idx = paddle.kthvalue(paddle.ones([4]), k=2)
    assert float(out.numpy()) == 1.0
