"""paddle.sparse analog tests (reference:
python/paddle/fluid/tests/unittests/test_sparse_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _demo_coo():
    # [[0, 1, 0], [2, 0, 3]]
    return sparse.sparse_coo_tensor(
        indices=[[0, 1, 1], [1, 0, 2]], values=[1.0, 2.0, 3.0],
        shape=[2, 3])


def test_coo_create_to_dense():
    s = _demo_coo()
    assert s.shape == [2, 3]
    assert s.nnz == 3
    np.testing.assert_allclose(s.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    np.testing.assert_array_equal(np.asarray(s.indices().numpy()),
                                  [[0, 1, 1], [1, 0, 2]])
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3])


def test_coo_shape_inference_and_validation():
    s = sparse.sparse_coo_tensor([[0, 2]], [5.0, 6.0])
    assert s.shape == [3]
    with pytest.raises(ValueError):
        sparse.sparse_coo_tensor([0, 1], [1.0, 2.0])  # not 2-D indices


def test_csr_create_and_convert():
    s = sparse.sparse_csr_tensor(
        crows=[0, 1, 3], cols=[1, 0, 2], values=[1.0, 2.0, 3.0],
        shape=[2, 3])
    np.testing.assert_allclose(s.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    coo = s.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(back.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    np.testing.assert_array_equal(np.asarray(back.crows().numpy()),
                                  [0, 1, 3])


def test_coalesce_sums_duplicates():
    s = sparse.sparse_coo_tensor(
        indices=[[0, 0], [1, 1]], values=[1.0, 4.0], shape=[2, 2])
    c = s.coalesce()
    np.testing.assert_allclose(c.to_dense().numpy(),
                               [[0, 5], [0, 0]])
    assert c.nnz == 1


def test_unary_ops_preserve_structure():
    s = _demo_coo()
    r = sparse.relu(sparse.neg(s))
    np.testing.assert_allclose(r.to_dense().numpy(), 0.0)
    sq = sparse.square(s)
    np.testing.assert_allclose(sq.to_dense().numpy(),
                               [[0, 1, 0], [4, 0, 9]])
    assert sq.nnz == 3
    t = sparse.tanh(s)
    np.testing.assert_allclose(t.values().numpy(),
                               np.tanh([1, 2, 3]), rtol=1e-6)


def test_binary_add_subtract():
    a = _demo_coo()
    b = sparse.sparse_coo_tensor([[0], [0]], [10.0], shape=[2, 3])
    np.testing.assert_allclose(sparse.add(a, b).to_dense().numpy(),
                               [[10, 1, 0], [2, 0, 3]])
    np.testing.assert_allclose(sparse.subtract(a, b).to_dense().numpy(),
                               [[-10, 1, 0], [2, 0, 3]])


def test_multiply_divide_scalar_and_sparse():
    a = _demo_coo()
    np.testing.assert_allclose(
        sparse.multiply(a, 2.0).to_dense().numpy(),
        [[0, 2, 0], [4, 0, 6]])
    prod = sparse.multiply(a, a)
    np.testing.assert_allclose(prod.to_dense().numpy(),
                               [[0, 1, 0], [4, 0, 9]])


def test_matmul_sparse_dense():
    a = _demo_coo()
    d = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
    out = sparse.matmul(a, d)
    ref = np.array([[0, 1, 0], [2, 0, 3]], np.float32) @ \
        np.arange(6, dtype=np.float32).reshape(3, 2)
    np.testing.assert_allclose(out.numpy(), ref)
    # dense @ sparse
    dd = np.random.RandomState(1).randn(4, 2).astype(np.float32)
    out2 = sparse.matmul(paddle.to_tensor(dd), a)
    np.testing.assert_allclose(
        out2.numpy(), dd @ np.array([[0, 1, 0], [2, 0, 3]], np.float32),
        rtol=1e-5)


def test_masked_matmul_matches_dense_at_pattern():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(5, 4).astype(np.float32)
    mask = sparse.sparse_coo_tensor(
        indices=[[0, 1, 3], [1, 2, 0]], values=[1.0, 1.0, 1.0],
        shape=[4, 4])
    out = sparse.masked_matmul(x, y, mask)
    dense = x @ y
    got = out.to_dense().numpy()
    for r, c in [(0, 1), (1, 2), (3, 0)]:
        np.testing.assert_allclose(got[r, c], dense[r, c], rtol=1e-5)
    assert got[0, 0] == 0


def test_sparse_nn_relu_softmax():
    s = sparse.sparse_coo_tensor(
        indices=[[0, 0, 1], [0, 1, 1]], values=[-1.0, 2.0, 0.5],
        shape=[2, 2])
    r = sparse.nn.ReLU()(s)
    np.testing.assert_allclose(r.to_dense().numpy(),
                               [[0, 2], [0, 0.5]])
    sm = sparse.nn.Softmax()(_demo_coo())
    dense = sm.to_dense().numpy()
    np.testing.assert_allclose(dense[0, 1], 1.0, rtol=1e-6)
    np.testing.assert_allclose(dense[1, [0, 2]].sum(), 1.0, rtol=1e-6)


def test_is_same_shape_and_cast():
    a, b = _demo_coo(), _demo_coo()
    assert sparse.is_same_shape(a, b)
    c = sparse.cast(a, index_dtype="int32", value_dtype="float16")
    assert str(c.dtype) == "float16"
    assert str(c._mat.indices.dtype) == "int32"
    np.testing.assert_allclose(c.to_dense().numpy().astype(np.float32),
                               a.to_dense().numpy(), rtol=1e-2)


# ---------------------------------------------------------------------------
# r5: sparse.nn 3-D layer family (Conv3D / SubmConv3D / BatchNorm /
# MaxPool3D) — goldens against a DENSE oracle on small inputs, plus
# finite-difference grad checks (VERDICT r4 Next #5).

def _rand_sparse_3d(seed=0, n=2, d=4, h=4, w=4, c=3, nnz=10):
    rs = np.random.RandomState(seed)
    coords = set()
    while len(coords) < nnz:
        coords.add((rs.randint(n), rs.randint(d), rs.randint(h),
                    rs.randint(w)))
    idx = np.array(sorted(coords), np.int32).T          # [4, nnz]
    vals = rs.standard_normal((idx.shape[1], c)).astype(np.float32)
    return sparse.sparse_coo_tensor(idx, vals, shape=[n, d, h, w, c])


def _dense_conv3d_oracle(x_dense, w, b, stride, padding, dilation):
    import jax
    import jax.numpy as jnp
    out = jax.lax.conv_general_dilated(
        jnp.asarray(x_dense), jnp.asarray(w),
        window_strides=(stride,) * 3, padding=[(padding, padding)] * 3,
        rhs_dilation=(dilation,) * 3,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    if b is not None:
        out = out + jnp.asarray(b)
    return np.asarray(out)


@pytest.mark.parametrize("stride,padding", [(1, 1), (2, 0), (1, 0)])
def test_sparse_conv3d_matches_dense_oracle(stride, padding):
    """conv3d values equal the dense conv at every active output site,
    and the active set is exactly the receptive-field union."""
    rs = np.random.RandomState(1)
    x = _rand_sparse_3d(seed=1)
    k, cin, cout = 3, 3, 4
    w = rs.standard_normal((k, k, k, cin, cout)).astype(np.float32) * 0.3
    b = rs.standard_normal((cout,)).astype(np.float32)
    out = sparse.nn.functional.conv3d(x, w, b,
                                      stride=stride, padding=padding)
    dense_in = x.to_dense().numpy()
    oracle = _dense_conv3d_oracle(dense_in, w, None, stride, padding, 1)
    got = out.to_dense().numpy()
    idx = np.asarray(out._mat.indices)
    for r in range(idx.shape[0]):
        nn_, dd, hh, ww = idx[r]
        np.testing.assert_allclose(
            got[nn_, dd, hh, ww], oracle[nn_, dd, hh, ww] + b,
            rtol=2e-4, atol=2e-4)


def test_sparse_subm_conv3d_keeps_index_set_and_matches_oracle():
    rs = np.random.RandomState(2)
    x = _rand_sparse_3d(seed=2)
    k, cin, cout = 3, 3, 5
    w = rs.standard_normal((k, k, k, cin, cout)).astype(np.float32) * 0.3
    out = sparse.nn.functional.subm_conv3d(x, w, None, padding=1)
    assert np.array_equal(np.asarray(out._mat.indices),
                          np.asarray(x._mat.indices))
    oracle = _dense_conv3d_oracle(x.to_dense().numpy(), w, None, 1, 1, 1)
    got = out.to_dense().numpy()
    idx = np.asarray(out._mat.indices)
    for r in range(idx.shape[0]):
        nn_, dd, hh, ww = idx[r]
        np.testing.assert_allclose(got[nn_, dd, hh, ww],
                                   oracle[nn_, dd, hh, ww],
                                   rtol=2e-4, atol=2e-4)


def test_sparse_conv3d_grads_finite_difference():
    """jax.grad through the sparse conv w.r.t. weight AND input values
    matches central finite differences."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import sparse as jsparse
    rs = np.random.RandomState(3)
    x = _rand_sparse_3d(seed=3, nnz=6, c=2)
    w = rs.standard_normal((2, 2, 2, 2, 3)).astype(np.float32) * 0.4
    mat = x._mat
    cot = rs.standard_normal((mat.nse, 3)).astype(np.float32)

    def loss(wv, vals):
        xx = sparse.SparseCooTensor(
            jsparse.BCOO((vals, mat.indices), shape=mat.shape))
        out = sparse.nn.functional.subm_conv3d(xx, wv, None, padding=1)
        return jnp.vdot(out._mat.data, jnp.asarray(cot))

    gw, gv = jax.grad(loss, argnums=(0, 1))(jnp.asarray(w), mat.data)
    eps = 1e-2
    for arg, g in ((0, gw), (1, gv)):
        base = [jnp.asarray(w), mat.data]
        flat = np.asarray(base[arg]).ravel()
        for j in rs.choice(flat.size, 5, replace=False):
            # fresh buffer per evaluation: jnp.asarray on the CPU
            # backend may zero-copy alias numpy memory, so reusing a
            # mutated scratch array corrupts the earlier operand
            v_hi = flat.copy(); v_hi[j] += eps
            v_lo = flat.copy(); v_lo[j] -= eps
            hi = [*base]; hi[arg] = jnp.asarray(
                v_hi.reshape(base[arg].shape))
            lo = [*base]; lo[arg] = jnp.asarray(
                v_lo.reshape(base[arg].shape))
            fd = (loss(*hi) - loss(*lo)) / (2 * eps)
            np.testing.assert_allclose(np.asarray(g).ravel()[j], fd,
                                       rtol=5e-2, atol=5e-3)


def test_sparse_max_pool3d_matches_dense_oracle():
    """Pooling maxes over PRESENT sites only: the dense oracle fills
    absent sites with -inf before pooling."""
    import jax
    import jax.numpy as jnp
    x = _rand_sparse_3d(seed=4, nnz=12)
    out = sparse.nn.functional.max_pool3d(x, kernel_size=2, stride=2)
    dense = x.to_dense().numpy()
    present = (np.abs(dense).sum(-1, keepdims=True) > 0)
    filled = np.where(present, dense, -np.inf)
    oracle = jax.lax.reduce_window(
        jnp.asarray(filled), -jnp.inf, jax.lax.max,
        (1, 2, 2, 2, 1), (1, 2, 2, 2, 1), "VALID")
    got = out.to_dense().numpy()
    idx = np.asarray(out._mat.indices)
    for r in range(idx.shape[0]):
        nn_, dd, hh, ww = idx[r]
        np.testing.assert_allclose(got[nn_, dd, hh, ww],
                                   np.asarray(oracle)[nn_, dd, hh, ww],
                                   rtol=1e-6)


def test_sparse_max_pool3d_integer_values():
    """Integer-valued sparse tensors pool with the dtype's own minimum
    as the identity — no float(-inf) fill leaking into an int lattice."""
    rs = np.random.RandomState(7)
    idx = np.array([[0, 0, 0, 0], [0, 0, 0, 1],
                    [0, 1, 1, 1], [1, 2, 3, 3]], np.int32).T
    vals = rs.randint(-50, 50, (4, 3)).astype(np.int32)
    x = sparse.sparse_coo_tensor(idx, vals, shape=[2, 4, 4, 4, 3])
    out = sparse.nn.functional.max_pool3d(x, kernel_size=2, stride=2)
    got = out.to_dense().numpy()
    assert got.dtype == np.int32
    # sites (0,0,0,0), (0,0,0,1) and (0,1,1,1) all fall in output
    # window (0,0,0,0): elementwise max of their value rows
    np.testing.assert_array_equal(
        got[0, 0, 0, 0], np.maximum.reduce(vals[:3]))
    np.testing.assert_array_equal(got[1, 1, 1, 1], vals[3])


def test_sparse_batchnorm_layers_and_conv_layers():
    """Layer wrappers: BatchNorm normalizes value rows (matches dense
    BatchNorm1D on the values), Conv3D/SubmConv3D/MaxPool3D run
    end-to-end as a tiny sparse backbone."""
    from paddle_tpu import nn as dnn
    x = _rand_sparse_3d(seed=5, c=4, nnz=14)
    bn = sparse.nn.BatchNorm(4)
    ref = dnn.BatchNorm1D(4)
    out = bn(x)
    want = ref(x.values())
    np.testing.assert_allclose(np.asarray(out._mat.data),
                               np.asarray(want.data), rtol=1e-5,
                               atol=1e-5)
    assert np.array_equal(np.asarray(out._mat.indices),
                          np.asarray(x._mat.indices))
    # eval mode uses running stats
    bn.eval()
    out_eval = bn(x)
    assert out_eval.shape == x.shape

    net_in = _rand_sparse_3d(seed=6, c=3, nnz=16)
    conv = sparse.nn.SubmConv3D(3, 8, 3, padding=1)
    bn2 = sparse.nn.BatchNorm(8)
    relu = sparse.nn.ReLU()
    pool = sparse.nn.MaxPool3D(2, 2)
    y = pool(relu(bn2(conv(net_in))))
    assert y.shape[0] == 2 and y.shape[-1] == 8
    assert all(s == 2 for s in y.shape[1:4])
    assert np.isfinite(np.asarray(y._mat.data)).all()
    # down-sampling conv (the stride-2 "sparse conv" stage)
    conv2 = sparse.nn.Conv3D(3, 4, 2, stride=2)
    z = conv2(net_in)
    assert z.shape == [2, 2, 2, 2, 4]


def test_sparse_activations_and_attention():
    s = _demo_coo()
    r6 = sparse.nn.ReLU6()(sparse.unary.pow(s, 3))
    assert float(np.asarray(r6._mat.data).max()) <= 6.0
    lr = sparse.nn.LeakyReLU(0.1)(s)
    dense = s.to_dense().numpy()
    want = np.where(dense >= 0, dense, 0.1 * dense)
    np.testing.assert_allclose(lr.to_dense().numpy(), want, rtol=1e-6)

    # sparse-mask attention: equals dense attention where the mask is
    # full, zero contribution where masked out
    import jax
    rs = np.random.RandomState(0)
    b, h, sq, d = 1, 2, 4, 8
    q, k, v = (rs.standard_normal((b, h, sq, d)).astype(np.float32)
               for _ in range(3))
    # CSR mask over [b*h*sq, sq] rows: full lower triangle
    tri = np.tril(np.ones((sq, sq), np.float32))
    full = np.tile(tri, (b * h, 1))
    crows = np.arange(0, full.size + 1, sq)[: b * h * sq + 1]
    mask = sparse.sparse_csr_tensor(
        np.concatenate([[0], np.cumsum((full != 0).sum(1))]),
        np.concatenate([np.nonzero(r)[0] for r in full]),
        full[full != 0], shape=[b * h * sq, sq])
    out = sparse.nn.functional.attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        mask)
    # oracle: causal softmax attention
    scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    scores = np.where(tri[None, None] != 0, scores, -np.inf)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    want = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(np.asarray(out.data), want, rtol=1e-4,
                               atol=1e-5)
