"""paddle.sparse analog tests (reference:
python/paddle/fluid/tests/unittests/test_sparse_*.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _demo_coo():
    # [[0, 1, 0], [2, 0, 3]]
    return sparse.sparse_coo_tensor(
        indices=[[0, 1, 1], [1, 0, 2]], values=[1.0, 2.0, 3.0],
        shape=[2, 3])


def test_coo_create_to_dense():
    s = _demo_coo()
    assert s.shape == [2, 3]
    assert s.nnz == 3
    np.testing.assert_allclose(s.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    np.testing.assert_array_equal(np.asarray(s.indices().numpy()),
                                  [[0, 1, 1], [1, 0, 2]])
    np.testing.assert_allclose(s.values().numpy(), [1, 2, 3])


def test_coo_shape_inference_and_validation():
    s = sparse.sparse_coo_tensor([[0, 2]], [5.0, 6.0])
    assert s.shape == [3]
    with pytest.raises(ValueError):
        sparse.sparse_coo_tensor([0, 1], [1.0, 2.0])  # not 2-D indices


def test_csr_create_and_convert():
    s = sparse.sparse_csr_tensor(
        crows=[0, 1, 3], cols=[1, 0, 2], values=[1.0, 2.0, 3.0],
        shape=[2, 3])
    np.testing.assert_allclose(s.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    coo = s.to_sparse_coo()
    np.testing.assert_allclose(coo.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(back.to_dense().numpy(),
                               [[0, 1, 0], [2, 0, 3]])
    np.testing.assert_array_equal(np.asarray(back.crows().numpy()),
                                  [0, 1, 3])


def test_coalesce_sums_duplicates():
    s = sparse.sparse_coo_tensor(
        indices=[[0, 0], [1, 1]], values=[1.0, 4.0], shape=[2, 2])
    c = s.coalesce()
    np.testing.assert_allclose(c.to_dense().numpy(),
                               [[0, 5], [0, 0]])
    assert c.nnz == 1


def test_unary_ops_preserve_structure():
    s = _demo_coo()
    r = sparse.relu(sparse.neg(s))
    np.testing.assert_allclose(r.to_dense().numpy(), 0.0)
    sq = sparse.square(s)
    np.testing.assert_allclose(sq.to_dense().numpy(),
                               [[0, 1, 0], [4, 0, 9]])
    assert sq.nnz == 3
    t = sparse.tanh(s)
    np.testing.assert_allclose(t.values().numpy(),
                               np.tanh([1, 2, 3]), rtol=1e-6)


def test_binary_add_subtract():
    a = _demo_coo()
    b = sparse.sparse_coo_tensor([[0], [0]], [10.0], shape=[2, 3])
    np.testing.assert_allclose(sparse.add(a, b).to_dense().numpy(),
                               [[10, 1, 0], [2, 0, 3]])
    np.testing.assert_allclose(sparse.subtract(a, b).to_dense().numpy(),
                               [[-10, 1, 0], [2, 0, 3]])


def test_multiply_divide_scalar_and_sparse():
    a = _demo_coo()
    np.testing.assert_allclose(
        sparse.multiply(a, 2.0).to_dense().numpy(),
        [[0, 2, 0], [4, 0, 6]])
    prod = sparse.multiply(a, a)
    np.testing.assert_allclose(prod.to_dense().numpy(),
                               [[0, 1, 0], [4, 0, 9]])


def test_matmul_sparse_dense():
    a = _demo_coo()
    d = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
    out = sparse.matmul(a, d)
    ref = np.array([[0, 1, 0], [2, 0, 3]], np.float32) @ \
        np.arange(6, dtype=np.float32).reshape(3, 2)
    np.testing.assert_allclose(out.numpy(), ref)
    # dense @ sparse
    dd = np.random.RandomState(1).randn(4, 2).astype(np.float32)
    out2 = sparse.matmul(paddle.to_tensor(dd), a)
    np.testing.assert_allclose(
        out2.numpy(), dd @ np.array([[0, 1, 0], [2, 0, 3]], np.float32),
        rtol=1e-5)


def test_masked_matmul_matches_dense_at_pattern():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32)
    y = rng.randn(5, 4).astype(np.float32)
    mask = sparse.sparse_coo_tensor(
        indices=[[0, 1, 3], [1, 2, 0]], values=[1.0, 1.0, 1.0],
        shape=[4, 4])
    out = sparse.masked_matmul(x, y, mask)
    dense = x @ y
    got = out.to_dense().numpy()
    for r, c in [(0, 1), (1, 2), (3, 0)]:
        np.testing.assert_allclose(got[r, c], dense[r, c], rtol=1e-5)
    assert got[0, 0] == 0


def test_sparse_nn_relu_softmax():
    s = sparse.sparse_coo_tensor(
        indices=[[0, 0, 1], [0, 1, 1]], values=[-1.0, 2.0, 0.5],
        shape=[2, 2])
    r = sparse.nn.ReLU()(s)
    np.testing.assert_allclose(r.to_dense().numpy(),
                               [[0, 2], [0, 0.5]])
    sm = sparse.nn.Softmax()(_demo_coo())
    dense = sm.to_dense().numpy()
    np.testing.assert_allclose(dense[0, 1], 1.0, rtol=1e-6)
    np.testing.assert_allclose(dense[1, [0, 2]].sum(), 1.0, rtol=1e-6)


def test_is_same_shape_and_cast():
    a, b = _demo_coo(), _demo_coo()
    assert sparse.is_same_shape(a, b)
    c = sparse.cast(a, index_dtype="int32", value_dtype="float16")
    assert str(c.dtype) == "float16"
    assert str(c._mat.indices.dtype) == "int32"
    np.testing.assert_allclose(c.to_dense().numpy().astype(np.float32),
                               a.to_dense().numpy(), rtol=1e-2)
