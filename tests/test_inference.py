"""Inference predictor tests (AnalysisPredictor analog).

Mirrors the reference's inference API tests
(paddle/fluid/inference/tests/api/) — save a model, create a predictor,
feed via handles, compare outputs against the live model.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import Config, PrecisionType, create_predictor


def _small_model():
    paddle.seed(7)
    return nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_predictor_from_jit_artifact(tmp_path):
    model = _small_model()
    x = paddle.randn([2, 8])
    ref = model(x).numpy()
    path = str(tmp_path / "m")
    paddle.jit.save(model, path, input_spec=[x])

    config = Config(path)
    config.set_compile_cache_dir(str(tmp_path / "cache"))
    pred = create_predictor(config)
    names = pred.get_input_names()
    assert len(names) == 1
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x.numpy())
    assert pred.run() is True
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_run_list_api(tmp_path):
    model = _small_model()
    x = paddle.randn([3, 8])
    ref = model(x).numpy()
    paddle.jit.save(model, str(tmp_path / "m"), input_spec=[x])
    pred = create_predictor(Config(str(tmp_path / "m")))
    outs = pred.run([x.numpy()])
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-5)


def test_predictor_from_layer_bf16():
    model = _small_model()
    x = paddle.randn([2, 8])
    ref = model(x).numpy()
    config = Config().from_layer(model, input_spec=[x])
    config.enable_tpu(precision=PrecisionType.Bfloat16)
    pred = create_predictor(config)
    outs = pred.run([x.numpy()])
    # bf16 serving ~ 1e-2 agreement with fp32
    np.testing.assert_allclose(outs[0].astype(np.float32), ref,
                               rtol=0.1, atol=0.1)


def test_predictor_clone_isolated_feeds(tmp_path):
    model = _small_model()
    x1 = paddle.randn([2, 8])
    x2 = paddle.randn([2, 8])
    paddle.jit.save(model, str(tmp_path / "m"), input_spec=[x1])
    p1 = create_predictor(Config(str(tmp_path / "m")))
    p2 = p1.clone()
    o1 = p1.run([x1.numpy()])[0]
    o2 = p2.run([x2.numpy()])[0]
    np.testing.assert_allclose(o1, model(x1).numpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(o2, model(x2).numpy(), rtol=1e-5,
                               atol=1e-5)


def test_predictor_from_static_inference_model(tmp_path):
    # static path: build a program, save_inference_model, serve it
    from paddle_tpu import static
    paddle.seed(0)
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        lin = nn.Linear(4, 3)
        x = static.data("x", [None, 4], "float32")
        y = lin(x)
    exe = static.Executor()
    exe.run(startup)
    prefix = str(tmp_path / "static_m")
    static.save_inference_model(prefix, [x], [y], executor=exe,
                                program=main)
    pred = create_predictor(Config(prefix))
    xin = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    out = pred.run([xin])[0]
    ref = exe.run(main, feed={"x": xin}, fetch_list=[y])[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_errors(tmp_path):
    with pytest.raises(ValueError):
        create_predictor(Config())
    with pytest.raises(FileNotFoundError):
        create_predictor(Config(str(tmp_path / "nope")))
    model = _small_model()
    x = paddle.randn([2, 8])
    paddle.jit.save(model, str(tmp_path / "m"), input_spec=[x])
    pred = create_predictor(Config(str(tmp_path / "m")))
    with pytest.raises(KeyError):
        pred.get_input_handle("bogus")
    with pytest.raises(RuntimeError, match="inputs not set"):
        pred.run()


def test_predictor_int8_weight_serving():
    """Int8 serving path (VERDICT r1 Next #9): weights held as int8 +
    per-channel scales, dequant inside the compiled program; outputs
    must stay close to the fp32 predictor's."""
    paddle.seed(0)
    from paddle_tpu.models.lenet import LeNet
    m = LeNet(num_classes=10)
    m.eval()
    x = np.random.RandomState(0).randn(4, 1, 28, 28).astype(np.float32)

    spec = [paddle.to_tensor(x)]
    ref = create_predictor(Config().from_layer(m, spec))
    ref_out = ref.run([x])[0]

    cfg = Config().from_layer(m, spec)
    cfg.enable_tpu(PrecisionType.Int8)
    pred = create_predictor(cfg)
    out = pred.run([x])[0]
    assert out.shape == ref_out.shape
    # int8 weights + bf16 activations: small bounded drift, same top-1
    assert np.abs(out.astype(np.float32) - ref_out).max() < 0.15, \
        np.abs(out.astype(np.float32) - ref_out).max()
    np.testing.assert_array_equal(out.argmax(-1), ref_out.argmax(-1))


def test_predictor_int8_after_ptq():
    """PTQ calibrate -> convert -> int8 predictor (the reference's
    post_training_quantization.py deployment flow)."""
    paddle.seed(1)
    from paddle_tpu.quantization import PTQ
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    m.eval()
    rng = np.random.RandomState(1)
    calib = rng.randn(64, 16).astype(np.float32)
    x = rng.randn(8, 16).astype(np.float32)
    ref_out = m(paddle.to_tensor(x)).numpy()

    ptq = PTQ()
    q = ptq.quantize(m, inplace=False)
    q.eval()
    q(paddle.to_tensor(calib))  # calibration pass
    q = ptq.convert(q)
    assert ptq.quant_info  # scales recorded for export

    spec = [paddle.to_tensor(x)]
    cfg = Config().from_layer(q, spec)
    cfg.enable_tpu(PrecisionType.Int8)
    pred = create_predictor(cfg)
    out = pred.run([x])[0]
    err = np.abs(out.astype(np.float32) - ref_out).max()
    scale = np.abs(ref_out).max()
    assert err < 0.1 * scale + 0.1, (err, scale)


def test_device_time_per_run_extraction():
    """The scan-slope device-time extractor (the serving-latency path
    that sidesteps the tunnel dispatch floor) returns a positive,
    batch-scaling latency and leaves the predictor's outputs intact."""
    from paddle_tpu.inference import (Benchmark, Config,
                                      create_predictor,
                                      device_time_per_run)
    from paddle_tpu import nn
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                          nn.Linear(256, 10))
    model.eval()
    x1 = np.random.RandomState(0).randn(4, 64).astype(np.float32)
    cfg = Config().from_layer(model, input_spec=[paddle.to_tensor(x1)])
    pred = create_predictor(cfg)
    t = device_time_per_run(pred, [x1], iters=(4, 16), repeats=2)
    assert t >= 0.0 and np.isfinite(t)
    # outputs after benchmarking still match a direct run
    out = pred.run([x1])
    want = np.asarray(model(paddle.to_tensor(x1)).data)
    np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-5)

    bm = Benchmark("mlp", batch_size=4)
    bm.measure(pred, [x1], iters=(4, 16), repeats=2)
    line = bm.report()
    assert "name=mlp" in line and "batch=4" in line
    assert bm.qps is None or bm.qps > 0
