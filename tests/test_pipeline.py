"""Pipeline-parallel tests on the 8-device CPU mesh.

Golden comparison ≈ the reference's hybrid_parallel_pp_* tests
(unittests/collective/fleet/hybrid_parallel_pp_embedding.py etc.): the
pipelined model must produce the SAME forward/loss/updates as the serial
model with identical weights — pipelining is a schedule, not a different
computation."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.parallel.pipeline import (LayerDesc,
                                                      PipelineLayer)
from paddle_tpu.models.gpt import gpt, gpt_pipe


@pytest.fixture
def mesh_pp4():
    hcg = fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 2, "pp_degree": 4}))
    yield hcg
    dist.set_hybrid_communicate_group(None)


def _copy_gpt_weights_to_pipe(serial, pipe):
    """Map serial GPT state -> PipelineLayer state ([S, v, maxB] block
    stack; traversal order unit u = chunk*S + stage)."""
    import jax.numpy as jnp
    sd = serial.state_dict()
    tgt = pipe.state_dict()
    # pre: embeddings
    tgt["pre.0.wte.weight"].set_value(sd["gpt.embed.wte.weight"])
    tgt["pre.0.wpe.weight"].set_value(sd["gpt.embed.wpe.weight"])
    # post: final norm
    tgt["post.0.ln_f.weight"].set_value(sd["gpt.ln_f.weight"])
    tgt["post.0.ln_f.bias"].set_value(sd["gpt.ln_f.bias"])
    # trunk: stack blocks [S, v, maxB, ...]
    S, v = pipe.num_stages, pipe.interleave
    sizes = pipe.seg_sizes
    maxB = pipe._max_blocks
    offs = np.concatenate([[0], np.cumsum(sizes)])
    for name in pipe._block_state_names:
        rows = []
        for s in range(S):
            chunk_rows = []
            for c in range(v):
                u = c * S + s
                vals = [sd[f"gpt.blocks.{blk}.{name}"]._data
                        for blk in range(offs[u], offs[u + 1])]
                while len(vals) < maxB:
                    vals.append(jnp.zeros_like(
                        sd[f"gpt.blocks.0.{name}"]._data))
                chunk_rows.append(jnp.stack(vals, axis=0))
            rows.append(jnp.stack(chunk_rows, axis=0))
        reg = pipe._stacked_names[name]
        tgt[reg].set_value(paddle.to_tensor(jnp.stack(rows, axis=0)))


def test_pipeline_forward_matches_serial(mesh_pp4):
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=4, tie_word_embeddings=True)
    serial.eval()
    pipe = gpt_pipe("test-tiny", num_layers=4, num_stages=4,
                    num_microbatches=4, tie_word_embeddings=True)
    pipe.eval()
    _copy_gpt_weights_to_pipe(serial, pipe)

    ids = np.random.RandomState(0).randint(0, 512, (8, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    ref = serial(x).numpy()
    out = pipe(x).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_pipeline_train_step_matches_serial(mesh_pp4):
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=4)
    pipe = gpt_pipe("test-tiny", num_layers=4, num_stages=4,
                    num_microbatches=4)
    _copy_gpt_weights_to_pipe(serial, pipe)

    ids = np.random.RandomState(1).randint(0, 512, (8, 16)).astype(np.int32)
    labels = ids.astype(np.int64)

    # serial loss/grads on a 1-device view: compute loss value directly
    x = paddle.to_tensor(ids)
    serial.eval()
    logits = serial(x)
    ref_loss = float(serial.loss(logits, paddle.to_tensor(labels)))

    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=pipe.parameters())
    opt = fleet.distributed_optimizer(opt)
    step = fleet.DistributedTrainStep(
        pipe, opt, pipe.loss_fn)
    pipe.eval()  # disable dropout for determinism (dropout=0 anyway)
    loss = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
    assert abs(float(loss) - ref_loss) < 2e-3, (float(loss), ref_loss)
    # params actually changed
    loss2 = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
    assert float(loss2) < float(loss)


def test_pipeline_degenerate_single_stage():
    # no mesh needed: num_stages=1 runs serially
    pipe = gpt_pipe("test-tiny", num_layers=2, num_stages=1)
    pipe.eval()
    ids = np.random.RandomState(0).randint(0, 512, (2, 8)).astype(np.int32)
    out = pipe(paddle.to_tensor(ids))
    assert tuple(out.shape) == (2, 8, 512)


def test_layerdesc_deferred_build():
    d = LayerDesc(nn.Linear, 4, 4)
    layer = d.build()
    assert isinstance(layer, nn.Linear)


def test_pipeline_unbalanced_partition(mesh_pp4):
    # 6 blocks over 4 stages -> [2, 2, 1, 1]: the seg_method analog,
    # no divisibility restriction (VERDICT round-1 Missing #1)
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=6, tie_word_embeddings=True)
    serial.eval()
    pipe = gpt_pipe("test-tiny", num_layers=6, num_stages=4,
                    num_microbatches=4, tie_word_embeddings=True)
    pipe.eval()
    assert pipe.seg_sizes == [2, 2, 1, 1]
    _copy_gpt_weights_to_pipe(serial, pipe)
    ids = np.random.RandomState(3).randint(0, 512, (8, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    np.testing.assert_allclose(pipe(x).numpy(), serial(x).numpy(),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_interleaved_matches_serial(mesh_pp4):
    # interleave=2: 4 layers -> 8 virtual units... use 8 layers so each
    # of the 4 stages hosts 2 chunks of 1 block
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=8, tie_word_embeddings=True)
    serial.eval()
    pipe = gpt_pipe("test-tiny", num_layers=8, num_stages=4,
                    num_microbatches=4, interleave=2,
                    tie_word_embeddings=True)
    pipe.eval()
    assert pipe.interleave == 2 and pipe.seg_sizes == [1] * 8
    _copy_gpt_weights_to_pipe(serial, pipe)
    ids = np.random.RandomState(4).randint(0, 512, (8, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    np.testing.assert_allclose(pipe(x).numpy(), serial(x).numpy(),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_interleaved_train_step(mesh_pp4):
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=8)
    pipe = gpt_pipe("test-tiny", num_layers=8, num_stages=4,
                    num_microbatches=4, interleave=2)
    _copy_gpt_weights_to_pipe(serial, pipe)
    ids = np.random.RandomState(5).randint(0, 512, (8, 16)).astype(np.int32)
    labels = ids.astype(np.int64)
    serial.eval()
    ref_loss = float(serial.loss(serial(paddle.to_tensor(ids)),
                                 paddle.to_tensor(labels)))
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=pipe.parameters())
    opt = fleet.distributed_optimizer(opt)
    step = fleet.DistributedTrainStep(pipe, opt, pipe.loss_fn)
    pipe.eval()
    loss = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
    assert abs(float(loss) - ref_loss) < 2e-3, (float(loss), ref_loss)
    loss2 = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
    assert float(loss2) < float(loss)


def test_pipeline_interleave_needs_enough_microbatches(mesh_pp4):
    pipe = gpt_pipe("test-tiny", num_layers=8, num_stages=4,
                    num_microbatches=2, interleave=2)
    pipe.eval()
    ids = np.random.RandomState(0).randint(0, 512, (4, 8)).astype(np.int32)
    with pytest.raises(ValueError, match="interleaved pipeline needs"):
        pipe(paddle.to_tensor(ids))


def test_pipeline_bad_seg_sizes_rejected(mesh_pp4):
    with pytest.raises(ValueError, match="seg_sizes"):
        gpt_pipe("test-tiny", num_layers=4, num_stages=4,
                 seg_sizes=[1, 1, 1])  # wrong count
