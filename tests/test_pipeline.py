"""Pipeline-parallel tests on the 8-device CPU mesh.

Golden comparison ≈ the reference's hybrid_parallel_pp_* tests
(unittests/collective/fleet/hybrid_parallel_pp_embedding.py etc.): the
pipelined model must produce the SAME forward/loss/updates as the serial
model with identical weights — pipelining is a schedule, not a different
computation."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import fleet
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.parallel.pipeline import (LayerDesc,
                                                      PipelineLayer)
from paddle_tpu.models.gpt import gpt, gpt_pipe


@pytest.fixture
def mesh_pp4():
    hcg = fleet.init(strategy=fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 2, "pp_degree": 4}))
    yield hcg
    dist.set_hybrid_communicate_group(None)


def _copy_gpt_weights_to_pipe(serial, pipe):
    """Map serial GPT state -> PipelineLayer state (stacked trunk)."""
    import jax.numpy as jnp
    sd = serial.state_dict()
    tgt = pipe.state_dict()
    # pre: embeddings
    tgt["pre.0.wte.weight"].set_value(sd["gpt.embed.wte.weight"])
    tgt["pre.0.wpe.weight"].set_value(sd["gpt.embed.wpe.weight"])
    # post: final norm
    tgt["post.0.ln_f.weight"].set_value(sd["gpt.ln_f.weight"])
    tgt["post.0.ln_f.bias"].set_value(sd["gpt.ln_f.bias"])
    # trunk: stack blocks along stage dim
    n_layers = serial.cfg.num_layers
    stages = pipe.num_stages
    per = n_layers // stages
    for name in pipe._unit_state_names:
        # name like "0.ln1.weight" (index within stage) -> block index
        idx, rest = name.split(".", 1)
        stacked = []
        for s in range(stages):
            blk = s * per + int(idx)
            stacked.append(sd[f"gpt.blocks.{blk}.{rest}"]._data)
        reg = pipe._stacked_names[name]
        tgt[reg].set_value(paddle.to_tensor(jnp.stack(stacked, axis=0)))


def test_pipeline_forward_matches_serial(mesh_pp4):
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=4, tie_word_embeddings=True)
    serial.eval()
    pipe = gpt_pipe("test-tiny", num_layers=4, num_stages=4,
                    num_microbatches=4, tie_word_embeddings=True)
    pipe.eval()
    _copy_gpt_weights_to_pipe(serial, pipe)

    ids = np.random.RandomState(0).randint(0, 512, (8, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    ref = serial(x).numpy()
    out = pipe(x).numpy()
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_pipeline_train_step_matches_serial(mesh_pp4):
    paddle.seed(7)
    serial = gpt("test-tiny", num_layers=4)
    pipe = gpt_pipe("test-tiny", num_layers=4, num_stages=4,
                    num_microbatches=4)
    _copy_gpt_weights_to_pipe(serial, pipe)

    ids = np.random.RandomState(1).randint(0, 512, (8, 16)).astype(np.int32)
    labels = ids.astype(np.int64)

    # serial loss/grads on a 1-device view: compute loss value directly
    x = paddle.to_tensor(ids)
    serial.eval()
    logits = serial(x)
    ref_loss = float(serial.loss(logits, paddle.to_tensor(labels)))

    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=pipe.parameters())
    opt = fleet.distributed_optimizer(opt)
    step = fleet.DistributedTrainStep(
        pipe, opt, pipe.loss_fn)
    pipe.eval()  # disable dropout for determinism (dropout=0 anyway)
    loss = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
    assert abs(float(loss) - ref_loss) < 2e-3, (float(loss), ref_loss)
    # params actually changed
    loss2 = step(paddle.to_tensor(ids), paddle.to_tensor(labels))
    assert float(loss2) < float(loss)


def test_pipeline_degenerate_single_stage():
    # no mesh needed: num_stages=1 runs serially
    pipe = gpt_pipe("test-tiny", num_layers=2, num_stages=1)
    pipe.eval()
    ids = np.random.RandomState(0).randint(0, 512, (2, 8)).astype(np.int32)
    out = pipe(paddle.to_tensor(ids))
    assert tuple(out.shape) == (2, 8, 512)


def test_layerdesc_deferred_build():
    d = LayerDesc(nn.Linear, 4, 4)
    layer = d.build()
    assert isinstance(layer, nn.Linear)


def test_pipeline_rejects_bad_division(mesh_pp4):
    with pytest.raises(ValueError):
        gpt_pipe("test-tiny", num_layers=3, num_stages=4)
