"""Optimizer + LR scheduler tests (≈ unittests/test_adam_op.py,
test_sgd_op.py, test_lr_scheduler.py) — update rules checked against
hand-rolled numpy."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quadratic_setup(opt_cls, **kw):
    w = paddle.Parameter(np.array([3.0, -2.0], np.float32))
    opt = opt_cls(parameters=[w], **kw)
    return w, opt


def test_sgd_matches_numpy():
    w, opt = _quadratic_setup(optimizer.SGD, learning_rate=0.1)
    loss = (w * w).sum()
    loss.backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [3.0 - 0.1 * 6, -2.0 + 0.1 * 4],
                               rtol=1e-6)


def test_momentum():
    w, opt = _quadratic_setup(optimizer.Momentum, learning_rate=0.1,
                              momentum=0.9)
    for _ in range(2):
        (w * w).sum().backward()
        opt.step()
        opt.clear_grad()
    # manual: v1=g1, w1=w0-lr*v1 ; v2=0.9v1+g2, w2=w1-lr*v2
    w0 = np.array([3.0, -2.0])
    v = 2 * w0
    w1 = w0 - 0.1 * v
    v = 0.9 * v + 2 * w1
    w2 = w1 - 0.1 * v
    np.testing.assert_allclose(w.numpy(), w2, rtol=1e-5)


def test_adam_matches_numpy():
    w, opt = _quadratic_setup(optimizer.Adam, learning_rate=0.1)
    (w * w).sum().backward()
    opt.step()
    g = 2 * np.array([3.0, -2.0])
    m = 0.1 * g
    v = 0.001 * g * g
    mh = m / 0.1
    vh = v / 0.001
    expected = np.array([3.0, -2.0]) - 0.1 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(w.numpy(), expected, rtol=1e-5)


def test_adamw_decoupled_decay():
    w, opt = _quadratic_setup(optimizer.AdamW, learning_rate=0.1,
                              weight_decay=0.1)
    (w * w).sum().backward()
    opt.step()
    g = 2 * np.array([3.0, -2.0])
    mh = g
    vh = g * g
    expected = np.array([3.0, -2.0]) * (1 - 0.1 * 0.1) - \
        0.1 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(w.numpy(), expected, rtol=1e-5)


def test_quadratic_converges():
    w = paddle.Parameter(np.array([5.0], np.float32))
    opt = optimizer.Adam(learning_rate=0.5, parameters=[w])
    for _ in range(100):
        loss = ((w - 1.5) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(w.numpy(), [1.5], atol=0.05)


def test_grad_clip_global_norm():
    clip = optimizer.ClipGradByGlobalNorm(1.0)
    g = [np.array([3.0, 4.0], np.float32)]  # norm 5
    out = clip([paddle.to_tensor(x).data for x in g])
    np.testing.assert_allclose(np.asarray(out[0]), [0.6, 0.8], rtol=1e-5)


def test_lr_schedulers():
    s = optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(s())
        s.step()
    np.testing.assert_allclose(vals, [0.1, 0.1, 0.05, 0.05, 0.025])

    c = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(c() - 1.0) < 1e-6
    c.step(10)
    assert abs(c()) < 1e-6

    w = optimizer.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0,
                                  end_lr=0.1)
    assert w() == 0.0
    w.step(5)
    np.testing.assert_allclose(w(), 0.05, rtol=1e-6)


def test_optimizer_state_dict_roundtrip():
    w = paddle.Parameter(np.ones(3, np.float32))
    opt = optimizer.Adam(parameters=[w])
    (w * w).sum().backward()
    opt.step()
    sd = opt.state_dict()
    w2 = paddle.Parameter(np.ones(3, np.float32))
    opt2 = optimizer.Adam(parameters=[w2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    from paddle_tpu.optimizer.optimizer import opt_key
    np.testing.assert_allclose(
        np.asarray(opt2._state[opt_key(w2)]["moment1"]),
        np.asarray(opt._state[opt_key(w)]["moment1"]))


def test_scheduler_with_optimizer():
    w = paddle.Parameter(np.ones(2, np.float32))
    sched = optimizer.lr.NoamDecay(d_model=64, warmup_steps=10,
                                   learning_rate=1.0)
    opt = optimizer.Adam(learning_rate=sched, parameters=[w])
    lr0 = opt.get_lr()
    for _ in range(2):  # Noam clamps step 0 -> 1, so advance twice
        (w.sum()).backward()
        opt.step()
        opt.clear_grad()
    assert opt.get_lr() != lr0  # per-iter scheduler advanced


def test_lookahead_optimizer():
    """incubate.optimizer.LookAhead: slow weights pull toward fast
    weights every k steps (reference lookahead.py semantics)."""
    from paddle_tpu.incubate.optimizer import LookAhead
    paddle.seed(0)
    w = paddle.Parameter(np.ones(2, np.float32))
    inner = optimizer.SGD(learning_rate=0.1, parameters=[w])
    la = LookAhead(inner, alpha=0.5, k=2)
    for _ in range(4):
        (w * w).sum().backward()
        la.step()
        la.clear_grad()
    # fast-only SGD after 4 steps would differ; lookahead interpolates
    assert 0.0 < float(w.numpy()[0]) < 1.0
    sd = la.state_dict()
    assert "_k_count" in sd and sd["_k_count"] == 4


def test_model_average_apply_restore():
    from paddle_tpu.incubate.optimizer import ModelAverage
    w = paddle.Parameter(np.zeros(2, np.float32))
    ma = ModelAverage(0.15, parameters=[w], min_average_window=2,
                      max_average_window=10)
    for v in (1.0, 2.0, 3.0):
        w.set_value(np.full(2, v, np.float32))
        ma.step()
    live = w.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(w.numpy(), 2.0)  # mean(1,2,3)
    np.testing.assert_allclose(w.numpy(), live)  # restored
