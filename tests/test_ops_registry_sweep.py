"""Registry-wide OpTest-style sweep (VERDICT r2 Next #2).

Every op in ops.op_registry.OPS must be exercised here (or carry an
enumerated exception, < 30 with reasons): fp32 eager run with finite
outputs, eager-vs-jit parity, bf16 output tolerance (differentiable
float ops, per-op whitelist), and a finite-difference gradient witness
for every differentiable op. Reference analog:
fluid/tests/unittests/op_test.py:333 check_output / check_grad +
white_list/ tolerances. The coverage gate (test_registry_fully_covered)
fails when a newly registered op has neither a spec nor an exception.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
# import lazily-registering surfaces so the sweep governs them too
import paddle_tpu.fft  # noqa: F401
import paddle_tpu.geometric  # noqa: F401
import paddle_tpu.quantization  # noqa: F401
import paddle_tpu.signal  # noqa: F401
import paddle_tpu.text  # noqa: F401
import paddle_tpu.nn.functional.fused_conv  # noqa: F401
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.op_registry import OPS

rng = np.random.RandomState(0)
T34 = rng.randn(3, 4).astype(np.float32)
B34 = rng.randn(3, 4).astype(np.float32)
POS = (np.abs(rng.randn(3, 4)) + 0.2).astype(np.float32)
UNIT = (rng.rand(3, 4) * 0.8 + 0.1).astype(np.float32)
GT1 = (rng.rand(3, 4) * 2 + 1.1).astype(np.float32)
SYM = (lambda m: (m + m.T) / 2 + 4 * np.eye(4, dtype=np.float32))(
    rng.randn(4, 4).astype(np.float32))
M45 = rng.randn(4, 5).astype(np.float32)
I34 = rng.randint(0, 4, (3, 4)).astype(np.int64)
BOOL = rng.rand(3, 4) > 0.5
IMG = rng.randn(1, 3, 6, 6).astype(np.float32)


def t(a):
    return paddle.to_tensor(np.asarray(a))


# inputs by tag; first element is the differentiated operand
TAGS = {
    "UNARY": lambda: ([T34], {}),
    "UNARY_POS": lambda: ([POS], {}),
    "UNARY_UNIT": lambda: ([UNIT], {}),
    "UNARY_GT1": lambda: ([GT1], {}),
    "BINARY": lambda: ([T34, B34], {}),
    "BINARY_POS": lambda: ([POS, POS * 0.5 + 0.1], {}),
    "MATMUL": lambda: ([T34, M45], {}),
    "UNARY_INT": lambda: ([I34], {}),
    "BINARY_INT": lambda: ([I34, I34], {}),
    "UNARY_BOOL": lambda: ([BOOL], {}),
    "BINARY_BOOL": lambda: ([BOOL, BOOL], {}),
    "AXIS0": lambda: ([T34, 0], {}),
    "LIST": lambda: ([[T34, B34]], {}),
    "BINARY_UNIT2": lambda: ([UNIT, (UNIT * 0.8 + 0.1)], {}),
}

# ops whose auto-classification picked a domain-invalid input (the
# classifier only checked "no exception", not finiteness)
DOMAIN_OVERRIDES = {
    "acosh": "UNARY_GT1", "log": "UNARY_POS", "log2": "UNARY_POS",
    "log10": "UNARY_POS", "log1p": "UNARY_POS", "sqrt": "UNARY_POS",
    "rsqrt": "UNARY_POS", "asin": "UNARY_UNIT", "acos": "UNARY_UNIT",
    "atanh": "UNARY_UNIT", "logit": "UNARY_UNIT", "erfinv": "UNARY_UNIT",
    "lgamma": "UNARY_POS", "digamma": "UNARY_POS", "polygamma_like": "UNARY_POS",
    "reciprocal": "UNARY_POS", "pow": "BINARY_POS", "divide": "BINARY_POS",
    "remainder": "BINARY_POS", "floor_divide": "BINARY_POS",
    "log_loss": "BINARY_UNIT2", "cholesky_like": "UNARY_POS",
}

AUTO_TAGS = {
    "abs": "UNARY",
    "acos": "UNARY",
    "acosh": "UNARY",
    "add": "BINARY",
    "add_n": "UNARY",
    "all": "UNARY",
    "allclose": "BINARY",
    "angle": "UNARY",
    "any": "UNARY",
    "argmax": "UNARY",
    "argmin": "UNARY",
    "argsort": "UNARY",
    "as_complex": "UNARY",
    "as_real": "UNARY",
    "asin": "UNARY",
    "asinh": "UNARY",
    "atan": "UNARY",
    "atan2": "BINARY",
    "atanh": "UNARY",
    "batch_norm_train": "UNARY",
    "binary_cross_entropy": "BINARY",
    "binary_cross_entropy_with_logits": "BINARY",
    "bitwise_and": "BINARY_INT",
    "bitwise_not": "UNARY_INT",
    "bitwise_or": "BINARY_INT",
    "bitwise_xor": "BINARY_INT",
    "bucketize": "BINARY",
    "cast": "BINARY",
    "ceil": "UNARY",
    "celu": "UNARY",
    "clip": "UNARY",
    "clone": "UNARY",
    "complex": "BINARY",
    "concat": "UNARY",
    "cond": "UNARY",
    "conj": "UNARY",
    "corrcoef": "UNARY",
    "cos": "UNARY",
    "cosh": "UNARY",
    "cosine_similarity": "BINARY",
    "count_nonzero": "UNARY",
    "cov": "UNARY",
    "crop": "UNARY",
    "cummax": "UNARY",
    "cummin": "UNARY",
    "cumprod": "UNARY",
    "cumsum": "UNARY",
    "deg2rad": "UNARY",
    "diag_embed": "UNARY",
    "diagonal": "UNARY",
    "diff": "UNARY",
    "digamma": "UNARY",
    "dist": "BINARY",
    "divide": "BINARY",
    "dot": "BINARY",
    "dstack": "UNARY",
    "elu": "UNARY",
    "embedding": "BINARY_INT",
    "equal": "BINARY",
    "equal_all": "BINARY",
    "erf": "UNARY",
    "erfinv": "UNARY",
    "exp": "UNARY",
    "expand_as": "BINARY",
    "expm1": "UNARY",
    "fill_diagonal": "AXIS0",
    "flatten": "UNARY",
    "flip": "AXIS0",
    "floor": "UNARY",
    "floor_divide": "BINARY",
    "fmax": "BINARY",
    "fmin": "BINARY",
    "frac": "UNARY",
    "frexp": "UNARY",
    "full_like": "BINARY",
    "gather": "BINARY_INT",
    "gcd": "BINARY_INT",
    "gelu": "UNARY",
    "glu": "UNARY",
    "greater_equal": "BINARY",
    "greater_than": "BINARY",
    "gumbel_softmax": "UNARY",
    "hardshrink": "UNARY",
    "hardsigmoid": "UNARY",
    "hardswish": "UNARY",
    "hardtanh": "UNARY",
    "heaviside": "BINARY",
    "hinge_embedding_loss": "BINARY",
    "histogram": "UNARY",
    "hstack": "UNARY",
    "hypot": "BINARY",
    "imag": "UNARY",
    "increment": "UNARY",
    "index_sample": "BINARY",
    "index_select": "BINARY_INT",
    "inner": "BINARY",
    "instance_norm": "UNARY",
    "isclose": "BINARY",
    "isfinite": "UNARY",
    "isinf": "UNARY",
    "isnan": "UNARY",
    "kl_div": "BINARY",
    "kron": "BINARY",
    "kthvalue": "BINARY_INT",
    "l1_loss": "BINARY",
    "label_smooth": "UNARY",
    "layer_norm": "UNARY",
    "lcm": "BINARY_INT",
    "leaky_relu": "UNARY",
    "less_equal": "BINARY",
    "less_than": "BINARY",
    "lgamma": "UNARY",
    "linear": "MATMUL",
    "log": "UNARY",
    "log10": "UNARY",
    "log1p": "UNARY",
    "log2": "UNARY",
    "log_loss": "BINARY",
    "log_sigmoid": "UNARY",
    "log_softmax": "UNARY",
    "logaddexp": "BINARY",
    "logcumsumexp": "UNARY",
    "logical_and": "BINARY",
    "logical_not": "UNARY",
    "logical_or": "BINARY",
    "logical_xor": "BINARY",
    "logit": "UNARY",
    "logsumexp": "UNARY",
    "lstsq": "BINARY",
    "lu": "UNARY",
    "matmul": "MATMUL",
    "matrix_rank": "UNARY",
    "max": "UNARY",
    "maximum": "BINARY",
    "mean": "UNARY",
    "median": "UNARY",
    "min": "UNARY",
    "minimum": "BINARY",
    "mish": "UNARY",
    "mode": "UNARY",
    "mse_loss": "BINARY",
    "multi_label_soft_margin_loss": "BINARY",
    "multiplex": "BINARY_INT",
    "multiply": "BINARY",
    "nan_to_num": "UNARY",
    "nanmean": "UNARY",
    "nanmedian": "UNARY",
    "nansum": "UNARY",
    "neg": "UNARY",
    "norm": "UNARY",
    "normalize": "UNARY",
    "not_equal": "BINARY",
    "ones_like": "UNARY",
    "outer": "BINARY",
    "outer_linalg": "BINARY",
    "pairwise_distance": "BINARY",
    "pinv": "UNARY",
    "pow": "BINARY",
    "prod": "UNARY",
    "rad2deg": "UNARY",
    "real": "UNARY",
    "reciprocal": "UNARY",
    "relu": "UNARY",
    "relu6": "UNARY",
    "remainder": "BINARY",
    "repeat_interleave": "AXIS0",
    "reverse": "AXIS0",
    "rms_norm": "UNARY",
    "roll": "AXIS0",
    "rot90": "UNARY",
    "round": "UNARY",
    "rsqrt": "UNARY",
    "scale": "UNARY",
    "searchsorted": "BINARY",
    "selu": "UNARY",
    "sequence_mask": "AXIS0",
    "sgn": "UNARY",
    "sigmoid": "UNARY",
    "sigmoid_focal_loss": "BINARY",
    "sign": "UNARY",
    "silu": "UNARY",
    "sin": "UNARY",
    "sinh": "UNARY",
    "smooth_l1_loss": "BINARY",
    "soft_margin_loss": "BINARY",
    "softmax": "UNARY",
    "softplus": "UNARY",
    "softshrink": "UNARY",
    "softsign": "UNARY",
    "sort": "UNARY",
    "sqrt": "UNARY",
    "square": "UNARY",
    "square_error_cost": "BINARY",
    "squeeze": "UNARY",
    "stack": "UNARY",
    "stanh": "UNARY",
    "std": "UNARY",
    "subtract": "BINARY",
    "sum": "UNARY",
    "t": "UNARY",
    "take": "BINARY",
    "tan": "UNARY",
    "tanh": "UNARY",
    "tanh_act": "UNARY",
    "tanhshrink": "UNARY",
    "tensordot": "BINARY",
    "thresholded_relu": "UNARY",
    "trace": "UNARY",
    "transpose_last2": "UNARY",
    "tril": "UNARY",
    "triu": "UNARY",
    "trunc": "UNARY",
    "unique_consecutive": "UNARY",
    "unsqueeze": "AXIS0",
    "unstack": "UNARY",
    "var": "UNARY",
    "vsplit": "BINARY_BOOL",
    "vstack": "UNARY",
    "where": "UNARY",
    "zeros_like": "UNARY",
}
AUTO_TAGS.update({k: v for k, v in DOMAIN_OVERRIDES.items()
                  if k in AUTO_TAGS or k in OPS})

I3 = np.array([0, 2, 1], np.int64)
LBL3 = np.array([1, 0, 3], np.int64)
Q = rng.randn(2, 4, 2, 8).astype(np.float32)   # [B, S, H, D]
SEQ = rng.randn(4, 2, 3).astype(np.float32)    # [T, B, D] scan input

MANUAL_SPECS = {
    # pooling family
    "max_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "max_pool2d": ([IMG, 2], {}),
    "max_pool3d": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    "avg_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "avg_pool2d": ([IMG, 2], {}),
    "avg_pool3d": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    "adaptive_avg_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "adaptive_avg_pool2d": ([IMG, 2], {}),
    "adaptive_avg_pool3d": (
        [rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    "adaptive_max_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "adaptive_max_pool2d": ([IMG, 2], {}),
    "adaptive_max_pool3d": (
        [rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    # conv family
    "conv1d": ([rng.randn(1, 3, 8).astype(np.float32),
                rng.randn(4, 3, 3).astype(np.float32)], {}),
    "conv2d": ([IMG, rng.randn(4, 3, 3, 3).astype(np.float32)], {}),
    "conv3d": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32),
                rng.randn(3, 2, 2, 2, 2).astype(np.float32)], {}),
    "conv1d_transpose": ([rng.randn(1, 3, 8).astype(np.float32),
                          rng.randn(3, 4, 3).astype(np.float32)], {}),
    "conv2d_transpose": ([IMG, rng.randn(3, 4, 3, 3).astype(np.float32)],
                         {}),
    "conv3d_transpose": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32),
                          rng.randn(2, 3, 2, 2, 2).astype(np.float32)],
                         {}),
    # norms
    "batch_norm_infer": ([IMG, np.zeros(3, np.float32),
                          np.ones(3, np.float32),
                          np.ones(3, np.float32),
                          np.zeros(3, np.float32)], {}),
    "group_norm": ([rng.randn(2, 4, 3, 3).astype(np.float32), 2], {}),
    "local_response_norm": ([IMG, 3], {}),
    "renorm": ([T34, 2.0, 0, 1.0], {}),
    # linalg
    "addmm": ([rng.randn(3, 5).astype(np.float32), T34, M45], {}),
    "bmm": ([rng.randn(2, 3, 4).astype(np.float32),
             rng.randn(2, 4, 5).astype(np.float32)], {}),
    "mv": ([T34, rng.randn(4).astype(np.float32)], {}),
    "det": ([SYM], {}),
    "slogdet": ([SYM], {}),
    "inverse": ([SYM], {}),
    "cholesky": ([SYM], {}),
    "cholesky_solve": ([rng.randn(4, 2).astype(np.float32),
                        np.linalg.cholesky(SYM).astype(np.float32)], {}),
    "triangular_solve": ([np.tril(SYM).astype(np.float32),
                          rng.randn(4, 2).astype(np.float32)],
                         {"upper": False}),
    "solve": ([SYM, rng.randn(4, 2).astype(np.float32)], {}),
    "matrix_power": ([SYM, 2], {}),
    "eigvals": ([SYM], {}),
    "eigvalsh": ([SYM], {}),
    "multi_dot": ([[T34, M45, rng.randn(5, 2).astype(np.float32)]], {}),
    "bilinear_form": ([rng.randn(2, 3).astype(np.float32),
                       rng.randn(2, 5).astype(np.float32),
                       rng.randn(4, 3, 5).astype(np.float32),
                       np.zeros(4, np.float32)], {}),
    "vander": ([rng.randn(4).astype(np.float32)], {"n": 3}),
    "lu_unpack": ([SYM, np.array([1, 2, 3, 4], np.int32)], {}),
    # manipulation / indexing
    "reshape": ([T34, [4, 3]], {}),
    "transpose": ([T34, [1, 0]], {}),
    "swapaxes": ([T34, 0, 1], {}),
    "moveaxis": ([T34, 0, 1], {}),
    "tile": ([T34, [2, 1]], {}),
    "expand": ([rng.randn(1, 4).astype(np.float32), [3, 4]], {}),
    "slice": ([T34, [0], [1], [3]], {}),
    "strided_slice": ([T34, [1], [0], [4], [2]], {}),
    "as_strided": ([T34, [2, 2], [4, 1]], {}),
    "gather_nd": ([T34, np.array([[0, 1], [2, 3]], np.int64)], {}),
    "take_along_axis": ([T34, I34[:, :2], 1], {}),
    "put_along_axis": ([T34, I34[:, :2], rng.randn(3, 2).astype(
        np.float32), 1], {}),
    "scatter": ([T34, I3, rng.randn(3, 4).astype(np.float32)], {}),
    "scatter_nd_add": ([T34, np.array([[0], [2]], np.int64),
                        rng.randn(2, 4).astype(np.float32)], {}),
    "index_add": ([T34, I3, 0, rng.randn(3, 4).astype(np.float32)], {}),
    "index_fill": ([T34, np.array([0, 2], np.int64), 0, 1.5], {}),
    "masked_fill": ([T34, BOOL, 0.5], {}),
    "fill_diagonal_tensor": ([T34, rng.randn(3).astype(np.float32)], {}),
    "lerp": ([T34, B34, 0.3], {}),
    "pad": ([T34, [1, 1, 0, 1]], {}),
    "cross": ([rng.randn(3, 3).astype(np.float32),
               rng.randn(3, 3).astype(np.float32)], {}),
    "shard_index": ([np.array([[1], [5], [9]], np.int64), 12, 3, 1], {}),
    "gather_tree": ([rng.randint(0, 5, (3, 2, 4)).astype(np.int64),
                     rng.randint(0, 4, (3, 2, 4)).astype(np.int64)], {}),
    "broadcast_shape": ([[3, 1, 4], [2, 4]], {}),
    "bincount": ([np.array([0, 1, 1, 3], np.int64)], {}),
    "quantile": ([T34, 0.5], {}),
    "nanquantile": ([T34, 0.5], {}),
    # vision / spatial
    "interpolate": ([IMG], {"scale_factor": 2.0}),
    "grid_sample": ([IMG, (rng.rand(1, 5, 5, 2).astype(np.float32)
                           * 2 - 1)], {}),
    "pixel_shuffle": ([rng.randn(1, 4, 3, 3).astype(np.float32), 2], {}),
    "pixel_unshuffle": ([rng.randn(1, 1, 6, 6).astype(np.float32), 2],
                        {}),
    "temporal_shift": ([rng.randn(4, 4, 3, 3).astype(np.float32), 2], {}),
    "unfold": ([IMG, [2, 2], [1, 1], [0, 0], [1, 1]], {}),
    "fold": ([rng.randn(1, 12, 25).astype(np.float32), [6, 6],
              [2, 2], [1, 1], [0, 0], [1, 1]], {}),
    "maxout": ([rng.randn(1, 4, 3, 3).astype(np.float32), 2], {}),
    "prelu": ([T34, np.array([0.2], np.float32)], {}),
    # losses
    "cross_entropy": ([rng.randn(3, 5).astype(np.float32), LBL3], {}),
    "nll_loss": ([np.log(np.abs(rng.randn(3, 5)) + 0.2).astype(
        np.float32), LBL3], {}),
    "dice_loss": ([UNIT, rng.randint(0, 2, (3, 3, 1)).astype(np.int64)],
                  {}),
    "npair_loss": ([rng.randn(3, 4).astype(np.float32),
                    rng.randn(3, 4).astype(np.float32),
                    np.array([0, 1, 0], np.int64)], {}),
    "cosine_embedding_loss": ([T34, B34,
                               np.array([1, -1, 1], np.int64)], {}),
    "margin_ranking_loss": ([rng.randn(3).astype(np.float32),
                             rng.randn(3).astype(np.float32),
                             np.array([1., -1., 1.], np.float32)], {}),
    "multi_margin_loss": ([rng.randn(3, 5).astype(np.float32), LBL3],
                          {}),
    "triplet_margin_loss": ([T34, B34,
                             rng.randn(3, 4).astype(np.float32)], {}),
    "hsigmoid_loss": ([rng.randn(3, 4).astype(np.float32), LBL3, 6,
                       rng.randn(5, 4).astype(np.float32)], {}),
    "ctc_loss": ([np.log(np.abs(rng.randn(5, 2, 6)) + 0.2).astype(
        np.float32), rng.randint(1, 6, (2, 3)).astype(np.int64),
        np.array([5, 5], np.int64), np.array([3, 2], np.int64)], {}),
    # attention / scans
    "scaled_dot_product_attention": ([Q, Q, Q], {}),
    "where": ([BOOL, T34, B34], {}),
    "vsplit": ([rng.randn(4, 3).astype(np.float32), 2], {}),
    "repeat_interleave": ([T34, 2], {"axis": 1}),
    "einsum": ([T34, M45], {"equation": "ij,jk->ik"}),
    "dice_loss": ([(rng.rand(3, 3, 1) * 0.8 + 0.1).astype(np.float32),
                   rng.randint(0, 2, (3, 3, 1)).astype(np.int64)], {}),
    "simple_rnn_scan": ([SEQ, np.zeros((2, 3), np.float32),
                         rng.randn(3, 3).astype(np.float32),
                         rng.randn(3, 3).astype(np.float32),
                         np.zeros(3, np.float32),
                         np.zeros(3, np.float32)], {}),
    "gru_scan": ([SEQ, np.zeros((2, 3), np.float32),
                  rng.randn(9, 3).astype(np.float32),
                  rng.randn(9, 3).astype(np.float32),
                  np.zeros(9, np.float32), np.zeros(9, np.float32)], {}),
    "lstm_scan": ([SEQ, np.zeros((2, 3), np.float32),
                   np.zeros((2, 3), np.float32),
                   rng.randn(12, 3).astype(np.float32),
                   rng.randn(12, 3).astype(np.float32),
                   np.zeros(12, np.float32), np.zeros(12, np.float32)],
                  {}),
    # lazily-registered surfaces (signal/geometric/quant/text)
    "fake_quant": ([T34, 1.5, 127], {}),
    "fake_quant_channelwise": ([T34,
                                (np.abs(rng.randn(4)) + 0.5).astype(
                                    np.float32), 127, 1], {}),
    "frame": ([rng.randn(64).astype(np.float32), 16, 8], {}),
    "overlap_add": ([rng.randn(16, 7).astype(np.float32), 8],
                    {"axis": -1}),
    "segment_sum": ([rng.randn(6, 3).astype(np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "segment_mean": ([rng.randn(6, 3).astype(np.float32),
                      np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "segment_max": ([rng.randn(6, 3).astype(np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "segment_min": ([rng.randn(6, 3).astype(np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "graph_send_u_recv": ([rng.randn(4, 3).astype(np.float32),
                           np.array([0, 1, 2], np.int64),
                           np.array([1, 2, 3], np.int64), "sum", 4],
                          {}),
    "graph_send_ue_recv": ([rng.randn(4, 3).astype(np.float32),
                            rng.randn(3, 3).astype(np.float32),
                            np.array([0, 1, 2], np.int64),
                            np.array([1, 2, 3], np.int64), "add",
                            "sum", 4], {}),
    "viterbi_decode": ([rng.randn(2, 5, 4).astype(np.float32),
                        rng.randn(4, 4).astype(np.float32),
                        np.array([5, 4], np.int64), False], {}),
    "fftshift": ([T34], {}),
    "ifftshift": ([T34], {}),
    "edit_distance": ([rng.randint(0, 5, (3, 4)).astype(np.int64),
                       rng.randint(0, 5, (3, 5)).astype(np.int64)], {}),
    # fused conv+BN training ops (kernels/fused_resnet.py; interpret-mode
    # pallas on CPU). NHWC activations, paddle-layout [O,I,kh,kw] weights.
    "conv1x1_bn_stats": ([rng.randn(2, 4, 4, 8).astype(np.float32),
                          rng.randn(16, 8, 1, 1).astype(np.float32)], {}),
    "bn_relu_conv1x1_bn_stats": (
        [rng.randn(2, 4, 4, 8).astype(np.float32),
         (np.abs(rng.randn(8)) + 0.5).astype(np.float32),
         (rng.randn(8) * 0.1).astype(np.float32),
         rng.randn(16, 8, 1, 1).astype(np.float32)], {}),
    "bn_relu_conv3x3_bn_stats": (
        [rng.randn(2, 4, 4, 8).astype(np.float32),
         (np.abs(rng.randn(8)) + 0.5).astype(np.float32),
         (rng.randn(8) * 0.1).astype(np.float32),
         (rng.randn(16, 8, 3, 3) * 0.2).astype(np.float32)], {}),
    "bn_apply_relu_add": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                           (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                           (rng.randn(16) * 0.1).astype(np.float32),
                           rng.randn(2, 4, 4, 16).astype(np.float32)], {}),
    "bn_apply_relu": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                       (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                       (rng.randn(16) * 0.1).astype(np.float32)], {}),
    "bn_apply": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                  (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                  (rng.randn(16) * 0.1).astype(np.float32)], {}),
    "bn_moments": ([rng.randn(2, 4, 4, 16).astype(np.float32)], {}),
    "bn_fold": ([(np.abs(rng.randn(8)) + 0.5).astype(np.float32),
                 rng.randn(8).astype(np.float32),
                 rng.randn(8).astype(np.float32),
                 (rng.rand(8) + 0.1).astype(np.float32)], {}),
}

# complex-dtype FFT family: the sweep's fp32/bf16/FD machinery is
# real-valued; these carry dedicated golden tests
# (tests/test_rnn_fft_text.py fft blocks vs numpy.fft)
_FFT_OPS = ["fft", "fft2", "fftn", "ifft", "ifft2", "ifftn",
            "rfft", "rfft2", "rfftn", "irfft", "irfft2", "irfftn",
            "hfft", "hfft2", "hfftn", "ihfft", "ihfft2", "ihfftn"]

# Full-op exceptions: ops NOT run by this sweep, each naming the
# dedicated golden suite that covers it instead (the gate verifies the
# names are real ops; the named suites carry the numeric witnesses).
# The check-level skip lists below (BF16_SKIP / GRAD_SKIP) are the
# analog of the reference's white_list/op_accuracy_white_list.py: the
# op still runs fp32+jit, only the named check is excused.
EXCEPTIONS: dict = {
    # dedicated golden suite with numpy oracles + finite-difference
    # grads (tests/test_detection_ops.py); registered lazily on
    # paddle_tpu.vision.ops import
    "yolo_loss": "tests/test_detection_ops.py::TestYoloLoss "
                 "(reference-kernel oracle incl. FD grads)",
    "deform_conv2d": "tests/test_detection_ops.py::TestDeformConv2D "
                     "(naive-loop oracle, grouped/masked variants)",
}
EXCEPTIONS.update({n: "complex dtypes outside the real-valued sweep; "
                      "golden-tested vs numpy.fft in "
                      "tests/test_rnn_fft_text.py::"
                      "test_fft_family_vs_numpy" for n in _FFT_OPS})


def _spec_for(name):
    if name in MANUAL_SPECS:
        return MANUAL_SPECS[name]
    tag = AUTO_TAGS.get(name)
    if tag is None:
        return None
    return TAGS[tag]()


def _to_args(raw_args):
    out = []
    for a in raw_args:
        if isinstance(a, np.ndarray):
            out.append(t(a))
        elif isinstance(a, list) and a and isinstance(a[0], np.ndarray):
            out.append([t(x) for x in a])
        else:
            out.append(a)
    return out


def _float_leaves(out):
    leaves = out if isinstance(out, (list, tuple)) else [out]
    return [l for l in leaves if isinstance(l, Tensor)
            and jnp.issubdtype(l.data.dtype, jnp.floating)]


COVERED = sorted(set(AUTO_TAGS) | set(MANUAL_SPECS))

# data-dependent output shapes or per-call randomness: eager-vs-jit
# equality is not defined for them
JIT_SKIP = {
    "bincount",            # output length = max(x) + 1 (data-dependent)
    "unique_consecutive",  # data-dependent output length
    "gumbel_softmax",      # fresh gumbel noise per call
}


def test_registry_fully_covered():
    """Coverage gate: a newly registered op must get a spec here or an
    enumerated exception."""
    def framework_op(n):
        if "::" in n:  # utils.custom_op user namespace
            return False
        # exclude only ops registered BY TEST MODULES; jnp-implemented
        # framework ops (impl module jax.numpy etc.) stay governed
        mod = getattr(OPS[n].impl, "__module__", "") or ""
        return not mod.split(".")[0].startswith(("test", "conftest"))

    # user/custom ops registered by tests (utils.custom_op) are outside
    # the framework registry contract
    missing = sorted(n for n in OPS if framework_op(n)
                     and n not in MANUAL_SPECS and n not in AUTO_TAGS
                     and n not in EXCEPTIONS)
    assert not missing, (
        f"{len(missing)} registered ops lack a sweep spec or "
        f"exception: {missing}")
    assert len(EXCEPTIONS) < 30
    # import lazily-registered surfaces so the stale check sees them
    import paddle_tpu.vision.ops  # noqa: F401
    stale = sorted(n for n in EXCEPTIONS if n not in OPS)
    assert not stale, f"stale exception entries: {stale}"
    # check-level whitelists stay bounded and name real ops
    assert len(GRAD_SKIP) <= 52 and len(BF16_SKIP) <= 35


@pytest.mark.parametrize("name", COVERED)
def test_op_fp32_and_jit(name):
    """fp32 eager run produces finite outputs; jit-traced run agrees."""
    if name not in OPS:
        pytest.skip(f"{name} no longer registered")
    spec = _spec_for(name)
    raw_args, kwargs = spec
    pub = OPS[name].public
    out = pub(*_to_args(raw_args), **kwargs)
    if name in JIT_SKIP:
        return
    fl = _float_leaves(out)
    for l in fl:
        assert np.isfinite(np.asarray(l.data, np.float64)).all(), \
            f"{name}: non-finite fp32 output (bad spec or op bug)"

    # jit parity
    tensor_idx = [i for i, a in enumerate(raw_args)
                  if isinstance(a, np.ndarray)]
    if not tensor_idx:
        return

    def pure(*arrs):
        args = list(raw_args)
        for i, arr in zip(tensor_idx, arrs):
            args[i] = Tensor(arr)
        o = pub(*_to_args_jit(args), **kwargs)
        leaves = o if isinstance(o, (list, tuple)) else [o]
        return [l.data if isinstance(l, Tensor) else l for l in leaves]

    jout = jax.jit(pure)(*[np.asarray(raw_args[i]) for i in tensor_idx])
    eleaves = out if isinstance(out, (list, tuple)) else [out]
    for je, ee in zip(jout, eleaves):
        if isinstance(ee, Tensor):
            np.testing.assert_allclose(
                np.asarray(je, np.float64),
                np.asarray(ee.data, np.float64), rtol=1e-5, atol=1e-5,
                err_msg=f"{name}: eager vs jit mismatch")


def _to_args_jit(args):
    out = []
    for a in args:
        if isinstance(a, np.ndarray):
            out.append(Tensor(a))
        elif isinstance(a, list) and a and isinstance(a[0], np.ndarray):
            out.append([Tensor(x) for x in a])
        else:
            out.append(a)
    return out


from op_test import BF16_TOL_WHITELIST

BF16_SKIP = {
    # int/bool or precision-unbounded under bf16 at these magnitudes
    "det", "slogdet", "inverse", "cholesky", "cholesky_solve",
    "triangular_solve", "solve", "matrix_power", "eigvals", "eigvalsh",
    "lu", "lu_unpack", "lstsq", "pinv", "matrix_rank", "corrcoef",
    "cov", "erfinv", "vander", "ctc_loss", "acosh", "atanh", "logit",
    "cumprod", "digamma", "lgamma", "frexp", "polygamma",
    "gumbel_softmax", "histogram", "log_loss", "repeat_interleave",
    "viterbi_decode", "graph_send_ue_recv",
}


@pytest.mark.parametrize("name", [n for n in COVERED
                                  if n not in BF16_SKIP])
def test_op_bf16(name):
    """bf16 inputs -> output within whitelist tolerance of the fp32 run
    (TPU production dtype)."""
    if name not in OPS:
        pytest.skip("not registered")
    raw_args, kwargs = _spec_for(name)
    if not any(isinstance(a, np.ndarray)
               and a.dtype == np.float32 for a in raw_args):
        pytest.skip("no float inputs")
    pub = OPS[name].public

    def run(cast):
        args = []
        for a in raw_args:
            if isinstance(a, np.ndarray) and a.dtype == np.float32:
                args.append(t(a).astype(cast))
            elif isinstance(a, list) and a and isinstance(a[0],
                                                          np.ndarray):
                args.append([t(x).astype(cast) if x.dtype == np.float32
                             else t(x) for x in a])
            elif isinstance(a, np.ndarray):
                args.append(t(a))
            else:
                args.append(a)
        return pub(*args, **kwargs)

    try:
        o16 = run("bfloat16")
    except Exception as e:
        pytest.skip(f"op rejects bf16 ({type(e).__name__}) — "
                    f"acceptable for int-core ops")
    o32 = run("float32")
    rtol, atol = BF16_TOL_WHITELIST.get(
        name, BF16_TOL_WHITELIST["default"])
    for l16, l32 in zip(_float_leaves(o16), _float_leaves(o32)):
        np.testing.assert_allclose(
            np.asarray(l16.data, np.float64),
            np.asarray(l32.data, np.float64),
            rtol=rtol, atol=atol + 3e-2 * np.abs(
                np.asarray(l32.data, np.float64)).max(),
            err_msg=f"{name}: bf16 deviates beyond whitelist")


GRAD_SKIP = {
    # output not a smooth function of the first float arg (argmax-like
    # plateaus, int outputs, or FD-hostile branch points)
    "sign", "sgn", "floor", "ceil", "round", "trunc", "frac",
    "heaviside", "argsort", "sort", "mode", "kthvalue", "median",
    "nanmedian", "quantile", "nanquantile", "frexp",
    "eigvals", "eigvalsh", "lu", "lu_unpack", "lstsq", "matrix_rank",
    "unique_consecutive", "histogram", "bincount", "searchsorted",
    "bucketize", "isclose", "allclose", "gumbel_softmax",
    "viterbi_decode", "fake_quant", "fake_quant_channelwise",
    "segment_max", "segment_min",
    # piecewise-linear kinks exactly at sample points
    "relu6", "hardtanh", "hardshrink", "softshrink", "tanhshrink",
    "thresholded_relu", "hardsigmoid", "hardswish", "maxout",
    # scan kernels: FD through 3 matmul layers is noise-dominated at
    # fp32; RNN-layer parity tests in test_nn cover their grads
    "gru_scan", "lstm_scan", "simple_rnn_scan",
    "ctc_loss",  # grad covered against torch in test_nn loss tests
    "max_unpool2d",
}


@pytest.mark.parametrize("name", sorted(
    n for n in COVERED
    if n in OPS and OPS[n].differentiable and n not in GRAD_SKIP))
def test_op_grad_finite_difference(name):
    """Central finite differences vs the tape gradient on the first
    float operand — the numeric witness that the registered op
    backpropagates correctly (reference op_test.py check_grad)."""
    raw_args, kwargs = _spec_for(name)
    fidx = next((i for i, a in enumerate(raw_args)
                 if isinstance(a, np.ndarray)
                 and a.dtype == np.float32), None)
    if fidx is None:
        pytest.skip("no float operand to differentiate")
    pub = OPS[name].public
    x0 = raw_args[fidx]
    prng = np.random.RandomState(1)

    def proj(j, shape):
        return np.asarray(np.random.RandomState(j + 7).randn(*shape),
                          np.float32)

    def f(xnp):
        args = list(raw_args)
        args[fidx] = xnp
        out = pub(*_to_args(args), **kwargs)
        fl = _float_leaves(out)
        if not fl:
            return None
        acc = None
        for j, l in enumerate(fl):
            term = (l * paddle.to_tensor(proj(j, l.shape))).sum()
            acc = term if acc is None else acc + term
        return acc

    xt = paddle.to_tensor(x0)
    xt.stop_gradient = False
    args = list(raw_args)
    args[fidx] = None
    out = pub(*[xt if i == fidx else a
                for i, a in enumerate(_to_args(raw_args))], **kwargs)
    fl = _float_leaves(out)
    if not fl:
        pytest.skip("no float outputs")
    loss = None
    for j, l in enumerate(fl):
        term = (l * paddle.to_tensor(proj(j, l.shape))).sum()
        loss = term if loss is None else loss + term
    loss.backward()
    if xt.grad is None:
        pytest.fail(f"{name}: no gradient reached the input")
    g = np.asarray(xt.grad.data, np.float64)

    def scalar(xnp):
        val = f(xnp)
        return float(np.asarray(val.data, np.float64))

    eps = 1e-3
    checked = 0
    for _ in range(4):
        idx = tuple(prng.randint(0, s) for s in x0.shape) \
            if x0.ndim else ()
        xp, xm = x0.copy(), x0.copy()
        xp[idx] += eps
        xm[idx] -= eps
        fd = (scalar(xp) - scalar(xm)) / (2 * eps)
        ad = g[idx]
        tol = 2e-2 + 5e-2 * max(abs(fd), abs(ad))
        assert abs(fd - ad) < tol, \
            (f"{name}: FD grad {fd:.5f} vs AD grad {ad:.5f} "
             f"at {idx}")
        checked += 1
    assert checked
