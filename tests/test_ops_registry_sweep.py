"""Registry-wide OpTest-style sweep (VERDICT r2 Next #2).

Every op in ops.op_registry.OPS must be exercised here (or carry an
enumerated exception, < 30 with reasons): fp32 eager run with finite
outputs, eager-vs-jit parity, bf16 output tolerance (differentiable
float ops, per-op whitelist), and a finite-difference gradient witness
for every differentiable op. Reference analog:
fluid/tests/unittests/op_test.py:333 check_output / check_grad +
white_list/ tolerances. The coverage gate (test_registry_fully_covered)
fails when a newly registered op has neither a spec nor an exception.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
# import lazily-registering surfaces so the sweep governs them too
import paddle_tpu.fft  # noqa: F401
import paddle_tpu.geometric  # noqa: F401
import paddle_tpu.quantization  # noqa: F401
import paddle_tpu.signal  # noqa: F401
import paddle_tpu.text  # noqa: F401
import paddle_tpu.nn.functional.fused_conv  # noqa: F401
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.op_registry import OPS

rng = np.random.RandomState(0)
T34 = rng.randn(3, 4).astype(np.float32)
B34 = rng.randn(3, 4).astype(np.float32)
POS = (np.abs(rng.randn(3, 4)) + 0.2).astype(np.float32)
UNIT = (rng.rand(3, 4) * 0.8 + 0.1).astype(np.float32)
GT1 = (rng.rand(3, 4) * 2 + 1.1).astype(np.float32)
SYM = (lambda m: (m + m.T) / 2 + 4 * np.eye(4, dtype=np.float32))(
    rng.randn(4, 4).astype(np.float32))
M45 = rng.randn(4, 5).astype(np.float32)
I34 = rng.randint(0, 4, (3, 4)).astype(np.int64)
BOOL = rng.rand(3, 4) > 0.5
IMG = rng.randn(1, 3, 6, 6).astype(np.float32)


def t(a):
    return paddle.to_tensor(np.asarray(a))


# inputs by tag; first element is the differentiated operand
TAGS = {
    "UNARY": lambda: ([T34], {}),
    "UNARY_POS": lambda: ([POS], {}),
    "UNARY_UNIT": lambda: ([UNIT], {}),
    "UNARY_GT1": lambda: ([GT1], {}),
    "BINARY": lambda: ([T34, B34], {}),
    "BINARY_POS": lambda: ([POS, POS * 0.5 + 0.1], {}),
    "MATMUL": lambda: ([T34, M45], {}),
    "UNARY_INT": lambda: ([I34], {}),
    "BINARY_INT": lambda: ([I34, I34], {}),
    "UNARY_BOOL": lambda: ([BOOL], {}),
    "BINARY_BOOL": lambda: ([BOOL, BOOL], {}),
    "AXIS0": lambda: ([T34, 0], {}),
    "LIST": lambda: ([[T34, B34]], {}),
    "BINARY_UNIT2": lambda: ([UNIT, (UNIT * 0.8 + 0.1)], {}),
}

# ops whose auto-classification picked a domain-invalid input (the
# classifier only checked "no exception", not finiteness)
DOMAIN_OVERRIDES = {
    "acosh": "UNARY_GT1", "log": "UNARY_POS", "log2": "UNARY_POS",
    "log10": "UNARY_POS", "log1p": "UNARY_POS", "sqrt": "UNARY_POS",
    "rsqrt": "UNARY_POS", "asin": "UNARY_UNIT", "acos": "UNARY_UNIT",
    "atanh": "UNARY_UNIT", "logit": "UNARY_UNIT", "erfinv": "UNARY_UNIT",
    "lgamma": "UNARY_POS", "digamma": "UNARY_POS", "polygamma_like": "UNARY_POS",
    "reciprocal": "UNARY_POS", "pow": "BINARY_POS", "divide": "BINARY_POS",
    "remainder": "BINARY_POS", "floor_divide": "BINARY_POS",
    "log_loss": "BINARY_UNIT2", "cholesky_like": "UNARY_POS",
}

AUTO_TAGS = {
    "abs": "UNARY",
    "acos": "UNARY",
    "acosh": "UNARY",
    "add": "BINARY",
    "add_n": "UNARY",
    "all": "UNARY",
    "allclose": "BINARY",
    "angle": "UNARY",
    "any": "UNARY",
    "argmax": "UNARY",
    "argmin": "UNARY",
    "argsort": "UNARY",
    "as_complex": "UNARY",
    "as_real": "UNARY",
    "asin": "UNARY",
    "asinh": "UNARY",
    "atan": "UNARY",
    "atan2": "BINARY",
    "atanh": "UNARY",
    "batch_norm_train": "UNARY",
    "binary_cross_entropy": "BINARY",
    "binary_cross_entropy_with_logits": "BINARY",
    "bitwise_and": "BINARY_INT",
    "bitwise_not": "UNARY_INT",
    "bitwise_or": "BINARY_INT",
    "bitwise_xor": "BINARY_INT",
    "bucketize": "BINARY",
    "cast": "BINARY",
    "ceil": "UNARY",
    "celu": "UNARY",
    "clip": "UNARY",
    "clone": "UNARY",
    "complex": "BINARY",
    "concat": "UNARY",
    "cond": "UNARY",
    "conj": "UNARY",
    "corrcoef": "UNARY",
    "cos": "UNARY",
    "cosh": "UNARY",
    "cosine_similarity": "BINARY",
    "count_nonzero": "UNARY",
    "cov": "UNARY",
    "crop": "UNARY",
    "cummax": "UNARY",
    "cummin": "UNARY",
    "cumprod": "UNARY",
    "cumsum": "UNARY",
    "deg2rad": "UNARY",
    "diag_embed": "UNARY",
    "diagonal": "UNARY",
    "diff": "UNARY",
    "digamma": "UNARY",
    "dist": "BINARY",
    "divide": "BINARY",
    "dot": "BINARY",
    "dstack": "UNARY",
    "elu": "UNARY",
    "embedding": "BINARY_INT",
    "equal": "BINARY",
    "equal_all": "BINARY",
    "erf": "UNARY",
    "erfinv": "UNARY",
    "exp": "UNARY",
    "expand_as": "BINARY",
    "expm1": "UNARY",
    "fill_diagonal": "AXIS0",
    "flatten": "UNARY",
    "flip": "AXIS0",
    "floor": "UNARY",
    "floor_divide": "BINARY",
    "fmax": "BINARY",
    "fmin": "BINARY",
    "frac": "UNARY",
    "frexp": "UNARY",
    "full_like": "BINARY",
    "gather": "BINARY_INT",
    "gcd": "BINARY_INT",
    "gelu": "UNARY",
    "glu": "UNARY",
    "greater_equal": "BINARY",
    "greater_than": "BINARY",
    "gumbel_softmax": "UNARY",
    "hardshrink": "UNARY",
    "hardsigmoid": "UNARY",
    "hardswish": "UNARY",
    "hardtanh": "UNARY",
    "heaviside": "BINARY",
    "hinge_embedding_loss": "BINARY",
    "histogram": "UNARY",
    "hstack": "UNARY",
    "hypot": "BINARY",
    "imag": "UNARY",
    "increment": "UNARY",
    "index_sample": "BINARY",
    "index_select": "BINARY_INT",
    "inner": "BINARY",
    "instance_norm": "UNARY",
    "isclose": "BINARY",
    "isfinite": "UNARY",
    "isinf": "UNARY",
    "isnan": "UNARY",
    "kl_div": "BINARY",
    "kron": "BINARY",
    "kthvalue": "BINARY_INT",
    "l1_loss": "BINARY",
    "label_smooth": "UNARY",
    "layer_norm": "UNARY",
    "lcm": "BINARY_INT",
    "leaky_relu": "UNARY",
    "less_equal": "BINARY",
    "less_than": "BINARY",
    "lgamma": "UNARY",
    "linear": "MATMUL",
    "log": "UNARY",
    "log10": "UNARY",
    "log1p": "UNARY",
    "log2": "UNARY",
    "log_loss": "BINARY",
    "log_sigmoid": "UNARY",
    "log_softmax": "UNARY",
    "logaddexp": "BINARY",
    "logcumsumexp": "UNARY",
    "logical_and": "BINARY",
    "logical_not": "UNARY",
    "logical_or": "BINARY",
    "logical_xor": "BINARY",
    "logit": "UNARY",
    "logsumexp": "UNARY",
    "lstsq": "BINARY",
    "lu": "UNARY",
    "matmul": "MATMUL",
    "matrix_rank": "UNARY",
    "max": "UNARY",
    "maximum": "BINARY",
    "mean": "UNARY",
    "median": "UNARY",
    "min": "UNARY",
    "minimum": "BINARY",
    "mish": "UNARY",
    "mode": "UNARY",
    "mse_loss": "BINARY",
    "multi_label_soft_margin_loss": "BINARY",
    "multiplex": "BINARY_INT",
    "multiply": "BINARY",
    "nan_to_num": "UNARY",
    "nanmean": "UNARY",
    "nanmedian": "UNARY",
    "nansum": "UNARY",
    "neg": "UNARY",
    "norm": "UNARY",
    "normalize": "UNARY",
    "not_equal": "BINARY",
    "ones_like": "UNARY",
    "outer": "BINARY",
    "outer_linalg": "BINARY",
    "pairwise_distance": "BINARY",
    "pinv": "UNARY",
    "pow": "BINARY",
    "prod": "UNARY",
    "rad2deg": "UNARY",
    "real": "UNARY",
    "reciprocal": "UNARY",
    "relu": "UNARY",
    "relu6": "UNARY",
    "remainder": "BINARY",
    "repeat_interleave": "AXIS0",
    "reverse": "AXIS0",
    "rms_norm": "UNARY",
    "roll": "AXIS0",
    "rot90": "UNARY",
    "round": "UNARY",
    "rsqrt": "UNARY",
    "scale": "UNARY",
    "searchsorted": "BINARY",
    "selu": "UNARY",
    "sequence_mask": "AXIS0",
    "sgn": "UNARY",
    "sigmoid": "UNARY",
    "sigmoid_focal_loss": "BINARY",
    "sign": "UNARY",
    "silu": "UNARY",
    "sin": "UNARY",
    "sinh": "UNARY",
    "smooth_l1_loss": "BINARY",
    "soft_margin_loss": "BINARY",
    "softmax": "UNARY",
    "softplus": "UNARY",
    "softshrink": "UNARY",
    "softsign": "UNARY",
    "sort": "UNARY",
    "sqrt": "UNARY",
    "square": "UNARY",
    "square_error_cost": "BINARY",
    "squeeze": "UNARY",
    "stack": "UNARY",
    "stanh": "UNARY",
    "std": "UNARY",
    "subtract": "BINARY",
    "sum": "UNARY",
    "t": "UNARY",
    "take": "BINARY",
    "tan": "UNARY",
    "tanh": "UNARY",
    "tanh_act": "UNARY",
    "tanhshrink": "UNARY",
    "tensordot": "BINARY",
    "thresholded_relu": "UNARY",
    "trace": "UNARY",
    "transpose_last2": "UNARY",
    "tril": "UNARY",
    "triu": "UNARY",
    "trunc": "UNARY",
    "unique_consecutive": "UNARY",
    "unsqueeze": "AXIS0",
    "unstack": "UNARY",
    "var": "UNARY",
    "vsplit": "BINARY_BOOL",
    "vstack": "UNARY",
    "where": "UNARY",
    "zeros_like": "UNARY",
}
AUTO_TAGS.update({k: v for k, v in DOMAIN_OVERRIDES.items()
                  if k in AUTO_TAGS or k in OPS})

I3 = np.array([0, 2, 1], np.int64)
LBL3 = np.array([1, 0, 3], np.int64)
Q = rng.randn(2, 4, 2, 8).astype(np.float32)   # [B, S, H, D]
SEQ = rng.randn(4, 2, 3).astype(np.float32)    # [T, B, D] scan input

MANUAL_SPECS = {
    # pooling family
    "max_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "max_pool2d": ([IMG, 2], {}),
    "max_pool3d": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    "avg_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "avg_pool2d": ([IMG, 2], {}),
    "avg_pool3d": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    "adaptive_avg_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "adaptive_avg_pool2d": ([IMG, 2], {}),
    "adaptive_avg_pool3d": (
        [rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    "adaptive_max_pool1d": ([rng.randn(1, 2, 8).astype(np.float32), 2], {}),
    "adaptive_max_pool2d": ([IMG, 2], {}),
    "adaptive_max_pool3d": (
        [rng.randn(1, 2, 4, 4, 4).astype(np.float32), 2], {}),
    # conv family
    "conv1d": ([rng.randn(1, 3, 8).astype(np.float32),
                rng.randn(4, 3, 3).astype(np.float32)], {}),
    "conv2d": ([IMG, rng.randn(4, 3, 3, 3).astype(np.float32)], {}),
    "conv3d": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32),
                rng.randn(3, 2, 2, 2, 2).astype(np.float32)], {}),
    "conv1d_transpose": ([rng.randn(1, 3, 8).astype(np.float32),
                          rng.randn(3, 4, 3).astype(np.float32)], {}),
    "conv2d_transpose": ([IMG, rng.randn(3, 4, 3, 3).astype(np.float32)],
                         {}),
    "conv3d_transpose": ([rng.randn(1, 2, 4, 4, 4).astype(np.float32),
                          rng.randn(2, 3, 2, 2, 2).astype(np.float32)],
                         {}),
    # norms
    "batch_norm_infer": ([IMG, np.zeros(3, np.float32),
                          np.ones(3, np.float32),
                          np.ones(3, np.float32),
                          np.zeros(3, np.float32)], {}),
    "group_norm": ([rng.randn(2, 4, 3, 3).astype(np.float32), 2], {}),
    "local_response_norm": ([IMG, 3], {}),
    "renorm": ([T34, 2.0, 0, 1.0], {}),
    # linalg
    "addmm": ([rng.randn(3, 5).astype(np.float32), T34, M45], {}),
    "bmm": ([rng.randn(2, 3, 4).astype(np.float32),
             rng.randn(2, 4, 5).astype(np.float32)], {}),
    "mv": ([T34, rng.randn(4).astype(np.float32)], {}),
    "det": ([SYM], {}),
    "slogdet": ([SYM], {}),
    "inverse": ([SYM], {}),
    "cholesky": ([SYM], {}),
    "cholesky_solve": ([rng.randn(4, 2).astype(np.float32),
                        np.linalg.cholesky(SYM).astype(np.float32)], {}),
    "triangular_solve": ([np.tril(SYM).astype(np.float32),
                          rng.randn(4, 2).astype(np.float32)],
                         {"upper": False}),
    "solve": ([SYM, rng.randn(4, 2).astype(np.float32)], {}),
    "matrix_power": ([SYM, 2], {}),
    "eigvals": ([SYM], {}),
    "eigvalsh": ([SYM], {}),
    "multi_dot": ([[T34, M45, rng.randn(5, 2).astype(np.float32)]], {}),
    "bilinear_form": ([rng.randn(2, 3).astype(np.float32),
                       rng.randn(2, 5).astype(np.float32),
                       rng.randn(4, 3, 5).astype(np.float32),
                       np.zeros(4, np.float32)], {}),
    "vander": ([rng.randn(4).astype(np.float32)], {"n": 3}),
    "lu_unpack": ([SYM, np.array([1, 2, 3, 4], np.int32)], {}),
    # manipulation / indexing
    "reshape": ([T34, [4, 3]], {}),
    "transpose": ([T34, [1, 0]], {}),
    "swapaxes": ([T34, 0, 1], {}),
    "moveaxis": ([T34, 0, 1], {}),
    "tile": ([T34, [2, 1]], {}),
    "expand": ([rng.randn(1, 4).astype(np.float32), [3, 4]], {}),
    "slice": ([T34, [0], [1], [3]], {}),
    "strided_slice": ([T34, [1], [0], [4], [2]], {}),
    "as_strided": ([T34, [2, 2], [4, 1]], {}),
    "gather_nd": ([T34, np.array([[0, 1], [2, 3]], np.int64)], {}),
    "take_along_axis": ([T34, I34[:, :2], 1], {}),
    "put_along_axis": ([T34, I34[:, :2], rng.randn(3, 2).astype(
        np.float32), 1], {}),
    "scatter": ([T34, I3, rng.randn(3, 4).astype(np.float32)], {}),
    "scatter_nd_add": ([T34, np.array([[0], [2]], np.int64),
                        rng.randn(2, 4).astype(np.float32)], {}),
    "index_add": ([T34, I3, 0, rng.randn(3, 4).astype(np.float32)], {}),
    "index_fill": ([T34, np.array([0, 2], np.int64), 0, 1.5], {}),
    "masked_fill": ([T34, BOOL, 0.5], {}),
    "fill_diagonal_tensor": ([T34, rng.randn(3).astype(np.float32)], {}),
    "lerp": ([T34, B34, 0.3], {}),
    "pad": ([T34, [1, 1, 0, 1]], {}),
    "cross": ([rng.randn(3, 3).astype(np.float32),
               rng.randn(3, 3).astype(np.float32)], {}),
    "shard_index": ([np.array([[1], [5], [9]], np.int64), 12, 3, 1], {}),
    "gather_tree": ([rng.randint(0, 5, (3, 2, 4)).astype(np.int64),
                     rng.randint(0, 4, (3, 2, 4)).astype(np.int64)], {}),
    "broadcast_shape": ([[3, 1, 4], [2, 4]], {}),
    "bincount": ([np.array([0, 1, 1, 3], np.int64)], {}),
    "quantile": ([T34, 0.5], {}),
    "nanquantile": ([T34, 0.5], {}),
    # vision / spatial
    "interpolate": ([IMG], {"scale_factor": 2.0}),
    "grid_sample": ([IMG, (rng.rand(1, 5, 5, 2).astype(np.float32)
                           * 2 - 1)], {}),
    "pixel_shuffle": ([rng.randn(1, 4, 3, 3).astype(np.float32), 2], {}),
    "pixel_unshuffle": ([rng.randn(1, 1, 6, 6).astype(np.float32), 2],
                        {}),
    "temporal_shift": ([rng.randn(4, 4, 3, 3).astype(np.float32), 2], {}),
    "unfold": ([IMG, [2, 2], [1, 1], [0, 0], [1, 1]], {}),
    "fold": ([rng.randn(1, 12, 25).astype(np.float32), [6, 6],
              [2, 2], [1, 1], [0, 0], [1, 1]], {}),
    "maxout": ([rng.randn(1, 4, 3, 3).astype(np.float32), 2], {}),
    "prelu": ([T34, np.array([0.2], np.float32)], {}),
    # losses
    "cross_entropy": ([rng.randn(3, 5).astype(np.float32), LBL3], {}),
    "nll_loss": ([np.log(np.abs(rng.randn(3, 5)) + 0.2).astype(
        np.float32), LBL3], {}),
    "dice_loss": ([UNIT, rng.randint(0, 2, (3, 3, 1)).astype(np.int64)],
                  {}),
    "npair_loss": ([rng.randn(3, 4).astype(np.float32),
                    rng.randn(3, 4).astype(np.float32),
                    np.array([0, 1, 0], np.int64)], {}),
    "cosine_embedding_loss": ([T34, B34,
                               np.array([1, -1, 1], np.int64)], {}),
    "margin_ranking_loss": ([rng.randn(3).astype(np.float32),
                             rng.randn(3).astype(np.float32),
                             np.array([1., -1., 1.], np.float32)], {}),
    "multi_margin_loss": ([rng.randn(3, 5).astype(np.float32), LBL3],
                          {}),
    "triplet_margin_loss": ([T34, B34,
                             rng.randn(3, 4).astype(np.float32)], {}),
    "hsigmoid_loss": ([rng.randn(3, 4).astype(np.float32), LBL3, 6,
                       rng.randn(5, 4).astype(np.float32)], {}),
    "ctc_loss": ([np.log(np.abs(rng.randn(5, 2, 6)) + 0.2).astype(
        np.float32), rng.randint(1, 6, (2, 3)).astype(np.int64),
        np.array([5, 5], np.int64), np.array([3, 2], np.int64)], {}),
    # attention / scans
    "scaled_dot_product_attention": ([Q, Q, Q], {}),
    "where": ([BOOL, T34, B34], {}),
    "vsplit": ([rng.randn(4, 3).astype(np.float32), 2], {}),
    "repeat_interleave": ([T34, 2], {"axis": 1}),
    "einsum": ([T34, M45], {"equation": "ij,jk->ik"}),
    "dice_loss": ([(rng.rand(3, 3, 1) * 0.8 + 0.1).astype(np.float32),
                   rng.randint(0, 2, (3, 3, 1)).astype(np.int64)], {}),
    "simple_rnn_scan": ([SEQ, np.zeros((2, 3), np.float32),
                         rng.randn(3, 3).astype(np.float32),
                         rng.randn(3, 3).astype(np.float32),
                         np.zeros(3, np.float32),
                         np.zeros(3, np.float32)], {}),
    "gru_scan": ([SEQ, np.zeros((2, 3), np.float32),
                  rng.randn(9, 3).astype(np.float32),
                  rng.randn(9, 3).astype(np.float32),
                  np.zeros(9, np.float32), np.zeros(9, np.float32)], {}),
    "lstm_scan": ([SEQ, np.zeros((2, 3), np.float32),
                   np.zeros((2, 3), np.float32),
                   rng.randn(12, 3).astype(np.float32),
                   rng.randn(12, 3).astype(np.float32),
                   np.zeros(12, np.float32), np.zeros(12, np.float32)],
                  {}),
    # lazily-registered surfaces (signal/geometric/quant/text)
    "fake_quant": ([T34, 1.5, 127], {}),
    "fake_quant_channelwise": ([T34,
                                (np.abs(rng.randn(4)) + 0.5).astype(
                                    np.float32), 127, 1], {}),
    "frame": ([rng.randn(64).astype(np.float32), 16, 8], {}),
    "overlap_add": ([rng.randn(16, 7).astype(np.float32), 8],
                    {"axis": -1}),
    "segment_sum": ([rng.randn(6, 3).astype(np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "segment_mean": ([rng.randn(6, 3).astype(np.float32),
                      np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "segment_max": ([rng.randn(6, 3).astype(np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "segment_min": ([rng.randn(6, 3).astype(np.float32),
                     np.array([0, 0, 1, 1, 2, 2], np.int64), 3], {}),
    "graph_send_u_recv": ([rng.randn(4, 3).astype(np.float32),
                           np.array([0, 1, 2], np.int64),
                           np.array([1, 2, 3], np.int64), "sum", 4],
                          {}),
    "graph_send_ue_recv": ([rng.randn(4, 3).astype(np.float32),
                            rng.randn(3, 3).astype(np.float32),
                            np.array([0, 1, 2], np.int64),
                            np.array([1, 2, 3], np.int64), "add",
                            "sum", 4], {}),
    "graph_send_uv": ([rng.randn(4, 3).astype(np.float32),
                       rng.randn(4, 3).astype(np.float32),
                       np.array([0, 1, 2], np.int64),
                       np.array([1, 2, 3], np.int64), "add"], {}),
    "viterbi_decode": ([rng.randn(2, 5, 4).astype(np.float32),
                        rng.randn(4, 4).astype(np.float32),
                        np.array([5, 4], np.int64), False], {}),
    "fftshift": ([T34], {}),
    "ifftshift": ([T34], {}),
    "edit_distance": ([rng.randint(0, 5, (3, 4)).astype(np.int64),
                       rng.randint(0, 5, (3, 5)).astype(np.int64)], {}),
    # fused conv+BN training ops (kernels/fused_resnet.py; interpret-mode
    # pallas on CPU). NHWC activations, paddle-layout [O,I,kh,kw] weights.
    "conv1x1_bn_stats": ([rng.randn(2, 4, 4, 8).astype(np.float32),
                          rng.randn(16, 8, 1, 1).astype(np.float32)], {}),
    "bn_relu_conv1x1_bn_stats": (
        [rng.randn(2, 4, 4, 8).astype(np.float32),
         (np.abs(rng.randn(8)) + 0.5).astype(np.float32),
         (rng.randn(8) * 0.1).astype(np.float32),
         rng.randn(16, 8, 1, 1).astype(np.float32)], {}),
    "bn_relu_conv3x3_bn_stats": (
        [rng.randn(2, 4, 4, 8).astype(np.float32),
         (np.abs(rng.randn(8)) + 0.5).astype(np.float32),
         (rng.randn(8) * 0.1).astype(np.float32),
         (rng.randn(16, 8, 3, 3) * 0.2).astype(np.float32)], {}),
    "bn_apply_relu_add": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                           (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                           (rng.randn(16) * 0.1).astype(np.float32),
                           rng.randn(2, 4, 4, 16).astype(np.float32)], {}),
    "bn_apply_relu": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                       (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                       (rng.randn(16) * 0.1).astype(np.float32)], {}),
    "bn_apply": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                  (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                  (rng.randn(16) * 0.1).astype(np.float32)], {}),
    "bn_center_apply_relu_add": (
        [rng.randn(2, 4, 4, 16).astype(np.float32),
         rng.randn(16).astype(np.float32),
         (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
         (rng.randn(16) * 0.1).astype(np.float32),
         rng.randn(2, 4, 4, 16).astype(np.float32)], {}),
    "bn_center_apply": ([rng.randn(2, 4, 4, 16).astype(np.float32),
                         rng.randn(16).astype(np.float32),
                         (np.abs(rng.randn(16)) + 0.5).astype(np.float32),
                         (rng.randn(16) * 0.1).astype(np.float32)], {}),
    "bn_moments": ([rng.randn(2, 4, 4, 16).astype(np.float32)], {}),
    "bn_fold": ([(np.abs(rng.randn(8)) + 0.5).astype(np.float32),
                 rng.randn(8).astype(np.float32),
                 rng.randn(8).astype(np.float32),
                 (rng.rand(8) + 0.1).astype(np.float32)], {}),
}

# complex-dtype FFT family: the sweep's fp32/bf16/FD machinery is
# real-valued; these carry dedicated golden tests
# (tests/test_rnn_fft_text.py fft blocks vs numpy.fft)
_FFT_OPS = ["fft", "fft2", "fftn", "ifft", "ifft2", "ifftn",
            "rfft", "rfft2", "rfftn", "irfft", "irfft2", "irfftn",
            "hfft", "hfft2", "hfftn", "ihfft", "ihfft2", "ihfftn"]

# Full-op exceptions: ops NOT run by this sweep, each naming the
# dedicated golden suite that covers it instead (the gate verifies the
# names are real ops; the named suites carry the numeric witnesses).
# The check-level skip lists below (BF16_SKIP / GRAD_SKIP) are the
# analog of the reference's white_list/op_accuracy_white_list.py: the
# op still runs fp32+jit, only the named check is excused.
EXCEPTIONS: dict = {
    # dedicated golden suite with numpy oracles + finite-difference
    # grads (tests/test_detection_ops.py); registered lazily on
    # paddle_tpu.vision.ops import
    "yolo_loss": "tests/test_detection_ops.py::TestYoloLoss "
                 "(reference-kernel oracle incl. FD grads)",
    "deform_conv2d": "tests/test_detection_ops.py::TestDeformConv2D "
                     "(naive-loop oracle, grouped/masked variants)",
}
EXCEPTIONS.update({n: "complex dtypes outside the real-valued sweep; "
                      "golden-tested vs numpy.fft in "
                      "tests/test_rnn_fft_text.py::"
                      "test_fft_family_vs_numpy" for n in _FFT_OPS})


def _spec_for(name):
    if name in MANUAL_SPECS:
        return MANUAL_SPECS[name]
    tag = AUTO_TAGS.get(name)
    if tag is None:
        return None
    return TAGS[tag]()


def _to_args(raw_args):
    out = []
    for a in raw_args:
        if isinstance(a, np.ndarray):
            out.append(t(a))
        elif isinstance(a, list) and a and isinstance(a[0], np.ndarray):
            out.append([t(x) for x in a])
        else:
            out.append(a)
    return out


def _float_leaves(out):
    leaves = out if isinstance(out, (list, tuple)) else [out]
    return [l for l in leaves if isinstance(l, Tensor)
            and jnp.issubdtype(l.data.dtype, jnp.floating)]


COVERED = sorted(set(AUTO_TAGS) | set(MANUAL_SPECS))

# data-dependent output shapes or per-call randomness: eager-vs-jit
# equality is not defined for them
JIT_SKIP = {
    "bincount",            # output length = max(x) + 1 (data-dependent)
    "unique_consecutive",  # data-dependent output length
    "gumbel_softmax",      # fresh gumbel noise per call
}


def test_registry_fully_covered():
    """Coverage gate: a newly registered op must get a spec here or an
    enumerated exception."""
    def framework_op(n):
        if "::" in n:  # utils.custom_op user namespace
            return False
        # exclude only ops registered BY TEST MODULES; jnp-implemented
        # framework ops (impl module jax.numpy etc.) stay governed
        mod = getattr(OPS[n].impl, "__module__", "") or ""
        return not mod.split(".")[0].startswith(("test", "conftest"))

    # user/custom ops registered by tests (utils.custom_op) are outside
    # the framework registry contract
    missing = sorted(n for n in OPS if framework_op(n)
                     and n not in MANUAL_SPECS and n not in AUTO_TAGS
                     and n not in EXCEPTIONS)
    assert not missing, (
        f"{len(missing)} registered ops lack a sweep spec or "
        f"exception: {missing}")
    assert len(EXCEPTIONS) < 30
    # import lazily-registered surfaces so the stale check sees them
    import paddle_tpu.vision.ops  # noqa: F401
    stale = sorted(n for n in EXCEPTIONS if n not in OPS)
    assert not stale, f"stale exception entries: {stale}"
    # check-level whitelists stay bounded and name real ops
    assert len(GRAD_SKIP) <= 52 and len(BF16_SKIP) <= 35
    # per-operand grad exemptions must point at live, reachable,
    # float operands (the EXCEPTIONS-style staleness gate)
    for (opname, idx), _reason in GRAD_ARG_SKIP.items():
        assert opname in OPS, (opname, "not registered")
        assert opname not in GRAD_SKIP, (opname, "already op-skipped")
        spec_args, _ = _spec_for(opname)
        assert idx < len(spec_args) and \
            isinstance(spec_args[idx], np.ndarray) and \
            spec_args[idx].dtype == np.float32, \
            (opname, idx, "exemption names a non-float operand")


@pytest.mark.parametrize("name", COVERED)
def test_op_fp32_and_jit(name):
    """fp32 eager run produces finite outputs; jit-traced run agrees."""
    if name not in OPS:
        pytest.skip(f"{name} no longer registered")
    spec = _spec_for(name)
    raw_args, kwargs = spec
    pub = OPS[name].public
    out = pub(*_to_args(raw_args), **kwargs)
    if name in JIT_SKIP:
        return
    fl = _float_leaves(out)
    for l in fl:
        assert np.isfinite(np.asarray(l.data, np.float64)).all(), \
            f"{name}: non-finite fp32 output (bad spec or op bug)"

    # jit parity
    tensor_idx = [i for i, a in enumerate(raw_args)
                  if isinstance(a, np.ndarray)]
    if not tensor_idx:
        return

    def pure(*arrs):
        args = list(raw_args)
        for i, arr in zip(tensor_idx, arrs):
            args[i] = Tensor(arr)
        o = pub(*_to_args_jit(args), **kwargs)
        leaves = o if isinstance(o, (list, tuple)) else [o]
        return [l.data if isinstance(l, Tensor) else l for l in leaves]

    jout = jax.jit(pure)(*[np.asarray(raw_args[i]) for i in tensor_idx])
    eleaves = out if isinstance(out, (list, tuple)) else [out]
    for je, ee in zip(jout, eleaves):
        if isinstance(ee, Tensor):
            np.testing.assert_allclose(
                np.asarray(je, np.float64),
                np.asarray(ee.data, np.float64), rtol=1e-5, atol=1e-5,
                err_msg=f"{name}: eager vs jit mismatch")


def _to_args_jit(args):
    out = []
    for a in args:
        if isinstance(a, np.ndarray):
            out.append(Tensor(a))
        elif isinstance(a, list) and a and isinstance(a[0], np.ndarray):
            out.append([Tensor(x) for x in a])
        else:
            out.append(a)
    return out


from op_test import BF16_TOL_WHITELIST

BF16_SKIP = {
    # int/bool or precision-unbounded under bf16 at these magnitudes
    "det", "slogdet", "inverse", "cholesky", "cholesky_solve",
    "triangular_solve", "solve", "matrix_power", "eigvals", "eigvalsh",
    "lu", "lu_unpack", "lstsq", "pinv", "matrix_rank", "corrcoef",
    "cov", "erfinv", "vander", "ctc_loss", "acosh", "atanh", "logit",
    "cumprod", "digamma", "lgamma", "frexp", "polygamma",
    "gumbel_softmax", "histogram", "log_loss", "repeat_interleave",
    "viterbi_decode", "graph_send_ue_recv",
}


@pytest.mark.parametrize("name", [n for n in COVERED
                                  if n not in BF16_SKIP])
def test_op_bf16(name):
    """bf16 inputs -> output within whitelist tolerance of the fp32 run
    (TPU production dtype)."""
    if name not in OPS:
        pytest.skip("not registered")
    raw_args, kwargs = _spec_for(name)
    if not any(isinstance(a, np.ndarray)
               and a.dtype == np.float32 for a in raw_args):
        pytest.skip("no float inputs")
    pub = OPS[name].public

    def run(cast):
        args = []
        for a in raw_args:
            if isinstance(a, np.ndarray) and a.dtype == np.float32:
                args.append(t(a).astype(cast))
            elif isinstance(a, list) and a and isinstance(a[0],
                                                          np.ndarray):
                args.append([t(x).astype(cast) if x.dtype == np.float32
                             else t(x) for x in a])
            elif isinstance(a, np.ndarray):
                args.append(t(a))
            else:
                args.append(a)
        return pub(*args, **kwargs)

    try:
        o16 = run("bfloat16")
    except Exception as e:
        pytest.skip(f"op rejects bf16 ({type(e).__name__}) — "
                    f"acceptable for int-core ops")
    o32 = run("float32")
    rtol, atol = BF16_TOL_WHITELIST.get(
        name, BF16_TOL_WHITELIST["default"])
    for l16, l32 in zip(_float_leaves(o16), _float_leaves(o32)):
        np.testing.assert_allclose(
            np.asarray(l16.data, np.float64),
            np.asarray(l32.data, np.float64),
            rtol=rtol, atol=atol + 3e-2 * np.abs(
                np.asarray(l32.data, np.float64)).max(),
            err_msg=f"{name}: bf16 deviates beyond whitelist")


GRAD_SKIP = {
    # output not a smooth function of the first float arg (argmax-like
    # plateaus, int outputs, or FD-hostile branch points)
    "sign", "sgn", "floor", "ceil", "round", "trunc", "frac",
    "heaviside", "argsort", "sort", "mode", "kthvalue", "median",
    "nanmedian", "quantile", "nanquantile", "frexp",
    "eigvals", "eigvalsh", "lu", "lu_unpack", "lstsq", "matrix_rank",
    "unique_consecutive", "histogram", "bincount", "searchsorted",
    "bucketize", "isclose", "allclose", "gumbel_softmax",
    "viterbi_decode", "fake_quant", "fake_quant_channelwise",
    "segment_max", "segment_min",
    # piecewise-linear kinks exactly at sample points
    "relu6", "hardtanh", "hardshrink", "softshrink", "tanhshrink",
    "thresholded_relu", "hardsigmoid", "hardswish", "maxout",
    # scan kernels: FD through 3 matmul layers is noise-dominated at
    # fp32; RNN-layer parity tests in test_nn cover their grads
    "gru_scan", "lstm_scan", "simple_rnn_scan",
    "ctc_loss",  # grad covered against torch in test_nn loss tests
    "max_unpool2d",
}


# per-operand grad exceptions: (op, operand index) pairs where the
# gradient legitimately does not flow or FD is hostile for THAT input
# (labels/targets, integer-like floats, branch-point inputs) — the
# analog of op_test's no_grad_set
GRAD_ARG_SKIP = {
    ("binary_cross_entropy", 1): "target operand (reference "
                                 "no_grad_set: label)",
    ("binary_cross_entropy_with_logits", 1): "target operand",
    ("log_loss", 1): "label operand",
    ("smooth_l1_loss", 1): "FD straddles the kink",
    ("fmax", 1): "tie-breaking plateau on equal elements",
    ("fmin", 1): "tie-breaking plateau",
    ("maximum", 1): "tie plateau", ("minimum", 1): "tie plateau",
    ("pow", 1): "exponent grad needs log(base) domain care",
    ("remainder", 1): "piecewise-constant in the divisor",
    ("floor_divide", 1): "integer-valued output",
    ("margin_ranking_loss", 2): "label in {-1, 1}",
}


# FD sweeps that alone cost >8s on CPU (fused multi-op kernels whose
# vjp compiles are huge): tier-2 via slow; fp32/jit parity still runs
# for them in the main sweep above
_GRAD_FD_SLOW = {"bn_relu_conv3x3_bn_stats"}


@pytest.mark.parametrize("name", [
    pytest.param(n, marks=pytest.mark.slow) if n in _GRAD_FD_SLOW else n
    for n in sorted(
        n for n in COVERED
        if n in OPS and OPS[n].differentiable and n not in GRAD_SKIP)])
def test_op_grad_finite_difference(name):
    """Central finite differences vs the tape gradient on EVERY float
    operand (r4: was first-operand-only) — the numeric witness that
    the registered op backpropagates correctly through each input
    (reference op_test.py:2131 check_grad with inputs_to_check)."""
    raw_args, kwargs = _spec_for(name)
    float_idxs = [i for i, a in enumerate(raw_args)
                  if isinstance(a, np.ndarray)
                  and a.dtype == np.float32
                  and (name, i) not in GRAD_ARG_SKIP][:3]
    if not float_idxs:
        pytest.skip("no float operand to differentiate")
    pub = OPS[name].public
    for fidx in float_idxs:
        _check_grad_operand(name, pub, raw_args, kwargs, fidx)


def _check_grad_operand(name, pub, raw_args, kwargs, fidx):
    x0 = raw_args[fidx]
    prng = np.random.RandomState(1)

    def proj(j, shape):
        return np.asarray(np.random.RandomState(j + 7).randn(*shape),
                          np.float32)

    def f(xnp):
        args = list(raw_args)
        args[fidx] = xnp
        out = pub(*_to_args(args), **kwargs)
        fl = _float_leaves(out)
        if not fl:
            return None
        acc = None
        for j, l in enumerate(fl):
            term = (l * paddle.to_tensor(proj(j, l.shape))).sum()
            acc = term if acc is None else acc + term
        return acc

    xt = paddle.to_tensor(x0)
    xt.stop_gradient = False
    out = pub(*[xt if i == fidx else a
                for i, a in enumerate(_to_args(raw_args))], **kwargs)
    fl = _float_leaves(out)
    if not fl:
        pytest.skip("no float outputs")
    loss = None
    for j, l in enumerate(fl):
        term = (l * paddle.to_tensor(proj(j, l.shape))).sum()
        loss = term if loss is None else loss + term
    loss.backward()
    if xt.grad is None:
        pytest.fail(f"{name}: no gradient reached operand {fidx}")
    g = np.asarray(xt.grad.data, np.float64)

    def scalar(xnp):
        val = f(xnp)
        return float(np.asarray(val.data, np.float64))

    eps = 1e-3
    checked = 0
    for _ in range(4):
        idx = tuple(prng.randint(0, s) for s in x0.shape) \
            if x0.ndim else ()
        xp, xm = x0.copy(), x0.copy()
        xp[idx] += eps
        xm[idx] -= eps
        fd = (scalar(xp) - scalar(xm)) / (2 * eps)
        ad = g[idx]
        tol = 2e-2 + 5e-2 * max(abs(fd), abs(ad))
        assert abs(fd - ad) < tol, \
            (f"{name}[operand {fidx}]: FD grad {fd:.5f} vs AD grad "
             f"{ad:.5f} at {idx}")
        checked += 1
    assert checked


# ---------------------------------------------------------------------------
# r4 depth extensions (VERDICT r3 Next #4): multi-shape configs,
# per-operand FD grads, int/bool exactness witnesses, zero-size dims,
# and governance of the public vision-function surface.
# ---------------------------------------------------------------------------

_DOMAIN = {
    "UNARY": lambda a: a,
    "UNARY_POS": lambda a: np.abs(a) + 0.2,
    "UNARY_UNIT": lambda a: (np.abs(a) % 0.8) + 0.1,
    "UNARY_GT1": lambda a: np.abs(a) + 1.1,
    "BINARY": lambda a: a,
    "BINARY_POS": lambda a: np.abs(a) + 0.2,
    "BINARY_UNIT2": lambda a: (np.abs(a) % 0.8) + 0.1,
}

_VSHAPES = {
    "rank1": [(5,)],
    "rank4": [(2, 1, 5, 3)],
    "broadcast": [(2, 1, 5, 3), (5, 3)],   # rhs broadcasts up
}

# ops whose semantics genuinely constrain the input shape/rank — each
# with the reason (the analog of OpTest's per-op shape dicts)
SHAPE_SKIP = {
    "cross": "needs a length-3 axis",
    "dot": "1-D/2-D contraction only",
    "dist": "p-norm defined pairwise on equal shapes",
    "matmul": "contraction dims must agree (MANUAL spec covers)",
    "equal_all": "no broadcasting by definition",
    "t": "rank <= 2 by definition",
    "corrcoef": "rank <= 2 matrix semantics",
    "cov": "rank <= 2 matrix semantics",
    "median": "nan-propagation on even counts differs per shape",
    "rot90": "needs rank >= 2",
    "searchsorted": "sorted-sequence semantics",
    "bucketize": "sorted-boundary semantics",
    "embedding": "index/table contract",
    "histogramdd": "sample-matrix contract",
    "unfold": "rank-3+ window contract",
    "trace": "rank >= 2",
    "dstack": "stack semantics need rank >= 1 pairs",
    "diag_embed": "appends matrix dims (rank guard)",
    "diagonal": "rank >= 2",
    "triu": "rank >= 2", "tril": "rank >= 2",
    "block_diag": "matrix semantics",
    "take_along_axis": "index tensor contract",
    "index_sample": "2-D contract",
    "batch_norm_train": "(N, C, ...) ndim >= 2 contract",
    "complex": "real/imag pair must share rank",
    "concat": "list-of-tensors argument contract",
    "cond": "matrix condition number: rank 2",
    "cosine_similarity": "axis-1 pairing contract",
    "expand_as": "second arg is the TARGET shape",
    "glu": "even split dim required",
    "instance_norm": "(N, C, spatial...) ndim >= 3",
    "lstsq": "matrix 2-D contract",
    "lu": "matrix 2-D contract",
    "pinv": "matrix 2-D contract",
    "normalize": "axis=1 default needs ndim >= 2",
    "tensordot": "contraction-dim agreement",
    "transpose_last2": "rank >= 2 by definition",
    "where": "(cond, x, y) triple contract",
}


def _variant_args(name, tag, variant):
    """Build inputs for a shape variant, honoring the op's domain."""
    base = TAGS[tag]()[0]
    dom = _DOMAIN[tag]
    import zlib
    vr = np.random.RandomState(
        zlib.crc32(f"{name}:{variant}".encode()) % (2 ** 31))
    shapes = list(_VSHAPES[variant])
    if tag.startswith("BINARY") and len(shapes) == 1:
        shapes = shapes * 2
    arrs = [dom(vr.randn(*s).astype(np.float32)) for s in shapes]
    # keep any trailing non-array args from the base spec (none for
    # UNARY/BINARY tags, by construction)
    return arrs + [a for a in base[len(arrs):]
                   if not isinstance(a, np.ndarray)]


_SHAPE_ELIGIBLE = sorted(
    n for n, tag in AUTO_TAGS.items()
    if tag in _DOMAIN and n not in SHAPE_SKIP)


@pytest.mark.parametrize("name", _SHAPE_ELIGIBLE)
def test_op_shape_variants(name):
    """OpTest-style multi-shape coverage (reference op_test.py:1533
    runs each op over several shape configs): rank-1, rank-4
    non-square, and (for binary ops) rank-broadcasting inputs must run
    finite and agree between eager and jit."""
    if name not in OPS:
        pytest.skip("not registered")
    tag = AUTO_TAGS[name]
    variants = ["rank1", "rank4"]
    if tag.startswith("BINARY"):
        variants.append("broadcast")
    pub = OPS[name].public
    for variant in variants:
        raw_args = _variant_args(name, tag, variant)
        out = pub(*_to_args(raw_args))
        if name in JIT_SKIP:
            continue
        for l in _float_leaves(out):
            assert np.isfinite(np.asarray(l.data, np.float64)).all(), \
                f"{name}[{variant}]: non-finite output"

        tensor_idx = [i for i, a in enumerate(raw_args)
                      if isinstance(a, np.ndarray)]

        def pure(*arrs):
            args = list(raw_args)
            for i, arr in zip(tensor_idx, arrs):
                args[i] = Tensor(arr)
            o = pub(*_to_args_jit(args))
            leaves = o if isinstance(o, (list, tuple)) else [o]
            return [l.data if isinstance(l, Tensor) else l
                    for l in leaves]

        jout = jax.jit(pure)(*[np.asarray(raw_args[i])
                               for i in tensor_idx])
        eleaves = out if isinstance(out, (list, tuple)) else [out]
        for je, ee in zip(jout, eleaves):
            if isinstance(ee, Tensor):
                np.testing.assert_allclose(
                    np.asarray(je, np.float64),
                    np.asarray(ee.data, np.float64),
                    rtol=1e-5, atol=1e-5,
                    err_msg=f"{name}[{variant}]: eager vs jit")


# int32 exactness witnesses: integer arithmetic must be EXACT (the
# float sweep's tolerances would hide off-by-one integer bugs)
_INT_ORACLES = {
    "add": np.add, "subtract": np.subtract, "multiply": np.multiply,
    "maximum": np.maximum, "minimum": np.minimum,
    "floor_divide": lambda a, b: np.floor_divide(a, b),
    "remainder": lambda a, b: np.mod(a, b),
    "abs": np.abs, "sign": np.sign,
    "square": lambda a: a * a, "neg": np.negative,
}


@pytest.mark.parametrize("name", sorted(_INT_ORACLES))
def test_op_int32_exact(name):
    if name not in OPS:
        pytest.skip("not registered")
    rng2 = np.random.RandomState(3)
    a = rng2.randint(-50, 50, (3, 4)).astype(np.int32)
    b = rng2.randint(1, 50, (3, 4)).astype(np.int32)
    oracle = _INT_ORACLES[name]
    import inspect
    n_args = len(inspect.signature(oracle).parameters) \
        if not isinstance(oracle, np.ufunc) else oracle.nin
    args = [a, b][:n_args]
    out = OPS[name].public(*_to_args(list(args)))
    ref = oracle(*args)
    got = np.asarray(out.data if isinstance(out, Tensor) else out)
    assert got.dtype.kind in "iu", f"{name}: int in, {got.dtype} out"
    np.testing.assert_array_equal(got, ref, err_msg=name)


# zero-size-dim witnesses on shape-preserving elementwise ops: the
# empty tensor must flow through (shape preserved) without error
_ZERO_SIZE_OPS = [
    "abs", "add", "subtract", "multiply", "divide", "exp", "log",
    "sqrt", "tanh", "sigmoid", "relu", "floor", "ceil", "sign",
    "maximum", "minimum", "square", "clip",
]


@pytest.mark.parametrize("name", _ZERO_SIZE_OPS)
def test_op_zero_size_dim(name):
    if name not in OPS:
        pytest.skip("not registered")
    tag = AUTO_TAGS.get(name, "UNARY")
    dom = _DOMAIN.get(tag, lambda x: x)
    z = dom(np.zeros((0, 4), np.float32))
    args = [z, z] if tag.startswith("BINARY") else [z]
    out = OPS[name].public(*_to_args(args))
    leaf = out[0] if isinstance(out, (list, tuple)) else out
    assert tuple(leaf.shape) == (0, 4), f"{name}: shape not preserved"


# the 7 public vision functions outside the op registry: each must
# name its golden suite, and that suite must actually exercise it —
# a future unregistered-untested vision op fails this gate
VISION_FN_GOLDENS = {
    "nms": "test_vision_ops.py",
    "matrix_nms": "test_detection_ops.py",
    "generate_proposals": "test_detection_ops.py",
    "distribute_fpn_proposals": "test_detection_ops.py",
    "read_file": "test_detection_ops.py",
    "decode_jpeg": "test_detection_ops.py",
    # roi/box utilities golden-tested in the vision-op suite
    "roi_align": "test_vision_ops.py",
    "roi_pool": "test_vision_ops.py",
    "psroi_pool": "test_vision_ops.py",
    "yolo_box": "test_vision_ops.py",
    "box_coder": "test_vision_ops.py",
    "prior_box": "test_vision_ops.py",
}


def test_vision_function_surface_governed():
    import inspect
    import paddle_tpu.vision.ops as vops
    here = os.path.dirname(os.path.abspath(__file__))
    public = [n for n in dir(vops)
              if not n.startswith("_")
              and inspect.isfunction(getattr(vops, n))
              and getattr(vops, n).__module__ == "paddle_tpu.vision.ops"]
    missing = []
    for n in public:
        if n in OPS or n in MANUAL_SPECS:
            continue
        suite = VISION_FN_GOLDENS.get(n)
        if suite is None:
            missing.append(n)
            continue
        path = os.path.join(here, suite)
        assert os.path.exists(path), (n, suite)
        import re
        with open(path) as f:
            assert re.search(rf"\b{n}\b", f.read()), \
                f"{n}: named golden suite {suite} never mentions it"
    assert not missing, (
        f"public vision functions with neither a registered op nor a "
        f"declared golden suite: {missing}")
