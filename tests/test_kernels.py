"""Pallas kernel tests: run in interpreter mode on CPU and compare against
plain-XLA references (the reference's OpTest golden-comparison pattern,
op_test.py:1533 style: same op through two execution paths + numeric grads).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.kernels import (
    flash_attention, fused_adamw_update, fused_layer_norm, fused_rms_norm)
from paddle_tpu.nn.functional.attention import _sdpa_xla


def _rand(*shape, dtype=np.float32, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).standard_normal(shape).astype(dtype))


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_xla(self, causal):
        b, s, h, d = 2, 256, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))
        out = flash_attention(q, k, v, causal=causal,
                              block_q=128, block_k=128)
        ref = _sdpa_xla(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_cross_attention_lengths(self):
        q = _rand(1, 128, 2, 64, seed=0)
        k = _rand(1, 256, 2, 64, seed=1)
        v = _rand(1, 256, 2, 64, seed=2)
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        ref = _sdpa_xla(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa(self):
        q = _rand(1, 128, 4, 64, seed=0)
        k = _rand(1, 128, 2, 64, seed=1)
        v = _rand(1, 128, 2, 64, seed=2)
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        kr = jnp.repeat(k, 2, axis=2)
        vr = jnp.repeat(v, 2, axis=2)
        ref = _sdpa_xla(q, kr, vr)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla(self, causal):
        b, s, h, d = 1, 256, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=causal)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla_fused_single_kblock(self, causal):
        """block_k >= seq takes the r4 fused single-k-block backward
        (one kernel, shared s/p/dp) — grads must match XLA, including
        the dk/dv accumulation across multiple q-blocks."""
        b, s, h, d = 1, 256, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=256)  # nq=2, nk=1
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=causal)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    def test_grads_cross_length_fused(self):
        """Fused backward with sq != sk (causal diagonal offset)."""
        q = _rand(1, 128, 2, 64, seed=0)
        k = _rand(1, 256, 2, 64, seed=1)
        v = _rand(1, 256, 2, 64, seed=2)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=True,
                                block_q=64, block_k=256)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=True)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla_tiled_fused_path(self, causal, monkeypatch):
        """The k-tiled fused backward is the live path for
        sk > _FUSED_BWD_MAX_SK at head_dim <= _TILED_BWD_MAX_D (s8192/s16384
        long-context); force it via the gates with a small k-chunk so
        multi-chunk dk/dv/dq accumulation and the per-chunk causal skip
        are exercised."""
        import importlib
        fa_mod = importlib.import_module(
            "paddle_tpu.kernels.flash_attention")
        monkeypatch.setattr(fa_mod, "_FUSED_BWD_MAX_SK", 0)
        monkeypatch.setattr(fa_mod, "_TILED_BWD_K_CHUNK", 128)
        b, s, h, d = 1, 512, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=causal)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_multiblock_online_softmax_path(self, causal, monkeypatch):
        """The multi-block online-softmax forward (_fwd_kernel) serves
        sk > _WHOLE_K_MAX_SK in production (s8192+), where every suite
        shape would otherwise take the whole-K override — force the
        gate to 0 so the online-rescale math (base-2 exp2, scale folded
        into q) keeps parity coverage."""
        import importlib
        fa_mod = importlib.import_module(
            "paddle_tpu.kernels.flash_attention")
        monkeypatch.setattr(fa_mod, "_WHOLE_K_MAX_SK", 0)
        b, s, h, d = 1, 512, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))
        # nk > 1 so the multi-block kernel (not the single-block fast
        # path) actually runs
        fwd = flash_attention(q, k, v, causal=causal,
                              block_q=128, block_k=128)
        ref = _sdpa_xla(q, k, v, is_causal=causal)
        np.testing.assert_allclose(np.asarray(fwd), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=causal)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_tiled_dispatch_recursion(self, causal, monkeypatch):
        """Past the dq-accumulator cap the tiled dispatch halves the q
        range recursively (causal low halves drop their masked high
        keys; dk/dv halves recombine in fp32) — force two recursion
        levels with a tiny cap and check grads against XLA."""
        import importlib
        fa_mod = importlib.import_module(
            "paddle_tpu.kernels.flash_attention")
        monkeypatch.setattr(fa_mod, "_FUSED_BWD_MAX_SK", 0)
        monkeypatch.setattr(fa_mod, "_TILED_BWD_K_CHUNK", 128)
        monkeypatch.setattr(fa_mod, "_TILED_BWD_DQ_CAP", 128 * 64)
        b, s, h, d = 1, 512, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=causal)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    def test_grads_tiled_fused_cross_length(self, monkeypatch):
        """Tiled fused backward with sq != sk (causal diagonal offset)
        and a chunked K."""
        import importlib
        fa_mod = importlib.import_module(
            "paddle_tpu.kernels.flash_attention")
        monkeypatch.setattr(fa_mod, "_FUSED_BWD_MAX_SK", 0)
        monkeypatch.setattr(fa_mod, "_TILED_BWD_K_CHUNK", 128)
        q = _rand(1, 128, 2, 64, seed=0)
        k = _rand(1, 384, 2, 64, seed=1)
        v = _rand(1, 384, 2, 64, seed=2)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=True,
                                block_q=64, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=True)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla_split_path(self, causal, monkeypatch):
        """The tiled split dq/dkv backward stays the live path for
        sk*d beyond the tiled-fused cap (d=128 at s16384); force it via
        both gates and keep it parity-covered."""
        import importlib
        fa_mod = importlib.import_module(
            "paddle_tpu.kernels.flash_attention")
        monkeypatch.setattr(fa_mod, "_FUSED_BWD_MAX_SK", 0)
        monkeypatch.setattr(fa_mod, "_TILED_BWD_MAX_D", 0)
        b, s, h, d = 1, 256, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal,
                                block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=causal)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("path", ["fused", "tiled", "split"])
    def test_grads_causal_sq_gt_sk_fully_masked_rows(self, path,
                                                     monkeypatch):
        """causal with sq > sk: q rows below offset are FULLY masked
        (forward emits zeros with lse = -inf). Their backward must be
        exactly zero — the lse = _NEG_INF sentinel made exp(s - lse)
        = 1 on masked entries (phantom gradients) before the r4 fix,
        in all three backward kernels."""
        if path != "fused":
            import importlib
            fa_mod = importlib.import_module(
                "paddle_tpu.kernels.flash_attention")
            monkeypatch.setattr(fa_mod, "_FUSED_BWD_MAX_SK", 0)
            if path == "split":
                monkeypatch.setattr(fa_mod, "_TILED_BWD_MAX_D", 0)
            else:
                monkeypatch.setattr(fa_mod, "_TILED_BWD_K_CHUNK", 64)
        q = _rand(1, 256, 2, 64, seed=0)
        k = _rand(1, 128, 2, 64, seed=1)
        v = _rand(1, 128, 2, 64, seed=2)
        # offset = sk - sq = -128: q rows 0..127 attend to nothing

        # forward must emit zeros on the masked rows in EVERY kernel
        # variant (the r5 whole-K kernel initially shipped mean(v)
        # there — caught in review because only the grads were checked)
        for blocks in [dict(block_q=64, block_k=64),
                       dict(block_q=64, block_k=128)]:  # both whole-K
            # (sk 256 <= _WHOLE_K_MAX_SK: the whole-K override serves
            # nk > 1 too; the multi-block kernel is gate-forced in
            # test_fwd_multiblock_online_softmax_path)
            fwd = flash_attention(q, k, v, causal=True, **blocks)
            assert np.all(np.asarray(fwd)[:, :128] == 0.0), \
                f"masked-row forward not zero under {blocks}"

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=True,
                                block_q=64, block_k=64)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v, is_causal=True)
            o = jnp.where(jnp.isnan(o), 0.0, o)  # ref NaNs on empty rows
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        dq = np.asarray(g_flash[0])
        assert np.all(dq[:, :128] == 0.0), "phantom dq on masked rows"
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            a, b_ = np.asarray(a), np.asarray(b_)
            np.testing.assert_allclose(np.where(np.isnan(b_), 0.0, a),
                                       np.where(np.isnan(b_), 0.0, b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("path", ["fused", "tiled", "split"])
    def test_ragged_seq_padded_path(self, path, monkeypatch):
        """Non-divisible sequence (ViT's 197 patches): the wrapper pads
        to the 128 grid and masks phantom key columns in-kernel —
        forward AND grads must match XLA on the real length."""
        if path != "fused":
            import importlib
            fa_mod = importlib.import_module(
                "paddle_tpu.kernels.flash_attention")
            monkeypatch.setattr(fa_mod, "_FUSED_BWD_MAX_SK", 0)
            if path == "split":
                monkeypatch.setattr(fa_mod, "_TILED_BWD_MAX_D", 0)
            else:
                monkeypatch.setattr(fa_mod, "_TILED_BWD_K_CHUNK", 64)
        b, s, h, d = 2, 197, 2, 64
        q, k, v = (_rand(b, s, h, d, seed=i) for i in range(3))
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        ref = _sdpa_xla(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = _sdpa_xla(q, k, v)
            return jnp.sum(o * o)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    def test_ragged_cross_length(self):
        """Ragged query vs key lengths (both padded independently)."""
        q = _rand(1, 100, 2, 64, seed=0)
        k = _rand(1, 197, 2, 64, seed=1)
        v = _rand(1, 197, 2, 64, seed=2)
        out = flash_attention(q, k, v, block_q=128, block_k=128)
        ref = _sdpa_xla(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_jit_and_multiblock(self):
        # seq > block so the online-softmax accumulation loop runs >1 step
        q, k, v = (_rand(1, 512, 1, 64, seed=i) for i in range(3))
        f = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128))
        out = f(q, k, v)
        ref = _sdpa_xla(q, k, v, is_causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


class TestFusedNorm:
    def test_layer_norm_matches(self):
        x = _rand(4, 32, 256)
        w = _rand(256, seed=1) * 0.1 + 1.0
        b = _rand(256, seed=2) * 0.1
        out = fused_layer_norm(x, w, b)
        xf = x
        mean = jnp.mean(xf, -1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), -1, keepdims=True)
        ref = (xf - mean) / jnp.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_rms_norm_matches(self):
        x = _rand(8, 256)
        w = _rand(256, seed=1) * 0.1 + 1.0
        out = fused_rms_norm(x, w)
        ref = x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_layer_norm_grads(self):
        x = _rand(16, 128)
        w = _rand(128, seed=1) * 0.1 + 1.0
        b = _rand(128, seed=2) * 0.1

        def loss_fused(x, w, b):
            return jnp.sum(jnp.square(fused_layer_norm(x, w, b)))

        def loss_ref(x, w, b):
            mean = jnp.mean(x, -1, keepdims=True)
            var = jnp.mean(jnp.square(x - mean), -1, keepdims=True)
            return jnp.sum(jnp.square(
                (x - mean) / jnp.sqrt(var + 1e-5) * w + b))

        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
        for a, r in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       atol=1e-4, rtol=1e-4)

    def test_rms_norm_grads(self):
        x = _rand(16, 128)
        w = _rand(128, seed=1) * 0.1 + 1.0

        def loss_fused(x, w):
            return jnp.sum(jnp.square(fused_rms_norm(x, w)))

        def loss_ref(x, w):
            return jnp.sum(jnp.square(
                x / jnp.sqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-6) * w))

        gf = jax.grad(loss_fused, argnums=(0, 1))(x, w)
        gr = jax.grad(loss_ref, argnums=(0, 1))(x, w)
        for a, r in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       atol=1e-4, rtol=1e-4)


class TestFusedAdamW:
    def test_matches_reference_update(self):
        shape = (130, 7)  # deliberately unaligned → exercises padding
        p = _rand(*shape, seed=0)
        g = _rand(*shape, seed=1)
        m = jnp.zeros(shape, jnp.float32)
        v = jnp.zeros(shape, jnp.float32)
        lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01
        new_p, new_m, new_v = p, m, v
        for step in (1, 2, 3):
            new_p, new_m, new_v = fused_adamw_update(
                new_p, g, new_m, new_v, lr, b1, b2, eps, wd, step)
        # reference loop
        rp, rm, rv = np.asarray(p), np.zeros(shape, np.float32), \
            np.zeros(shape, np.float32)
        gn = np.asarray(g)
        for step in (1, 2, 3):
            rm = b1 * rm + (1 - b1) * gn
            rv = b2 * rv + (1 - b2) * gn * gn
            mh = rm / (1 - b1 ** step)
            vh = rv / (1 - b2 ** step)
            rp = rp - lr * (mh / (np.sqrt(vh) + eps) + wd * rp)
        np.testing.assert_allclose(np.asarray(new_p), rp, atol=1e-5,
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(new_m), rm, atol=1e-6,
                                   rtol=1e-6)

    def test_traced_lr_no_recompile(self):
        p = _rand(64, seed=0)
        g = _rand(64, seed=1)
        m = jnp.zeros((64,), jnp.float32)
        v = jnp.zeros((64,), jnp.float32)

        @jax.jit
        def step(p, g, m, v, lr, t):
            return fused_adamw_update(p, g, m, v, lr, 0.9, 0.999, 1e-8,
                                      0.0, t)
        p1, m1, v1 = step(p, g, m, v, jnp.float32(1e-3), jnp.float32(1))
        p2, _, _ = step(p1, g, m1, v1, jnp.float32(5e-4), jnp.float32(2))
        assert np.all(np.isfinite(np.asarray(p2)))


def test_check_nan_inf_in_program_flag():
    """FLAGS_check_nan_inf_in_program traps NaNs inside jitted code
    without per-op host syncs (VERDICT r1 weak #7)."""
    import jax
    import jax.numpy as jnp
    import pytest
    import paddle_tpu as paddle

    paddle.set_flags({"FLAGS_check_nan_inf_in_program": True})
    try:
        @jax.jit
        def f(x):
            return jnp.log(x)

        with pytest.raises(FloatingPointError):
            f(jnp.asarray(-1.0)).block_until_ready()
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf_in_program": False})


def test_trainstep_offload_flag_falls_back_on_cpu():
    """offload_opt_state must degrade gracefully where the backend has
    no pinned_host memory kind (CPU test mesh) and still train."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer

    paddle.seed(0)
    m = nn.Linear(4, 2)
    opt = optimizer.AdamW(learning_rate=0.1,
                          parameters=m.parameters())
    step = paddle.jit.TrainStep(
        m, opt, lambda out, y: ((out - y) ** 2).mean(),
        offload_opt_state=True)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.zeros((8, 2), np.float32))
    l0 = float(step(x, y))
    for _ in range(5):
        ln = float(step(x, y))
    assert ln < l0
