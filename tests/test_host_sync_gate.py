"""Host-sync regression gate: the async train loop must not drain the
device dispatch queue. A 10-step ``Model.fit`` may charge at most ONE
blocking loss read-back (``train.host_syncs``) per log interval (here:
per epoch — the epoch-end drain is a single barrier however many values
are pending), and the AsyncScalarFetcher's lag window must flush on
epoch end with no loss value dropped or reordered."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.hapi.model import AsyncScalarFetcher
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.nn import functional as F
from paddle_tpu.profiler import metrics


class Toy(Dataset):
    def __init__(self, n=40, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.standard_normal((n, 8)).astype(np.float32)
        w = np.random.RandomState(42).standard_normal((8,))
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = Model(net)
    m.prepare(optimizer=optimizer.Adam(learning_rate=0.01,
                                       parameters=net.parameters()),
              loss=lambda out, lbl: F.cross_entropy(out, lbl))
    return m


class Trace(Callback):
    """Records (kind, step|epoch, loss) in arrival order."""

    def __init__(self):
        self.events = []

    def on_train_batch_end(self, step, logs=None):
        self.events.append(("batch", step, logs["loss"]))

    def on_epoch_end(self, epoch, logs=None):
        self.events.append(("epoch", epoch, None))

    @property
    def losses(self):
        return [(s, l) for kind, s, l in self.events if kind == "batch"]


def _fit(lag, epochs=1):
    os.environ["PADDLE_ASYNC_STEPS"] = str(lag)
    try:
        m = _model()
        trace = Trace()
        # 40 samples / batch 4 = 10 steps per epoch
        m.fit(Toy(), batch_size=4, epochs=epochs, verbose=0,
              callbacks=[trace], shuffle=False)
    finally:
        os.environ.pop("PADDLE_ASYNC_STEPS", None)
    return trace


class TestHostSyncGate:
    def test_ten_step_fit_bounded_host_syncs(self):
        metrics.reset()
        metrics.enable()
        try:
            trace = _fit(lag=2)
            snap = metrics.snapshot()
            fetches = snap.get("train.loss_fetches", {}).get("value", 0)
            syncs = snap.get("train.host_syncs", {}).get("value", 0)
        finally:
            metrics.disable()
        # every one of the 10 losses was read back exactly once ...
        assert fetches == 10, snap
        # ... and at most one read-back blocked per log interval (one
        # epoch): the lag window keeps the dispatch queue full and the
        # epoch-end drain is a single barrier
        assert syncs <= 1, f"{syncs} blocking host syncs in 10 steps"

    def test_lag_window_drains_in_order_on_epoch_end(self):
        trace = _fit(lag=3, epochs=2)
        batch_steps = [s for kind, s, _ in trace.events if kind == "batch"]
        # no loss dropped: 10 per epoch, and none reordered
        assert batch_steps == list(range(10)) + list(range(10))
        # the window drains BEFORE on_epoch_end fires
        kinds = [kind for kind, _, _ in trace.events]
        assert kinds.index("epoch") == 10  # all 10 batch events first
        assert kinds.count("batch") == 20 and kinds.count("epoch") == 2

    def test_async_losses_match_synchronous_run(self):
        """The lag only delays OBSERVATION — values are bitwise those a
        fully synchronous loop (PADDLE_ASYNC_STEPS=0) produces."""
        sync = _fit(lag=0).losses
        lagged = _fit(lag=2).losses
        assert len(sync) == len(lagged) == 10
        for (s0, l0), (s1, l1) in zip(sync, lagged):
            assert s0 == s1
            np.testing.assert_array_equal(l0, l1)


class TestAsyncScalarFetcher:
    def test_window_holds_lag_values(self):
        f = AsyncScalarFetcher(lag=2)
        assert f.push(0, 1.0) == []
        assert f.push(1, 2.0) == []
        assert f.push(2, 3.0) == [(0, 1.0)]  # matured out of the window
        assert len(f) == 2

    def test_drain_flushes_in_push_order(self):
        f = AsyncScalarFetcher(lag=4)
        for i in range(3):
            f.push(i, float(i))
        assert f.drain() == [(0, 0.0), (1, 1.0), (2, 2.0)]
        assert len(f) == 0 and f.drain() == []

    def test_lag_zero_is_fully_synchronous(self):
        f = AsyncScalarFetcher(lag=0)
        assert f.push(7, 42.0) == [(7, 42.0)]
        assert len(f) == 0

    def test_env_var_and_garbage_fall_back(self, monkeypatch):
        monkeypatch.setenv("PADDLE_ASYNC_STEPS", "5")
        assert AsyncScalarFetcher().lag == 5
        monkeypatch.setenv("PADDLE_ASYNC_STEPS", "bogus")
        assert AsyncScalarFetcher().lag == 2  # default
        monkeypatch.setenv("PADDLE_ASYNC_STEPS", "-3")
        assert AsyncScalarFetcher().lag == 0  # clamped

    def test_sync_leaves_window_intact(self):
        f = AsyncScalarFetcher(lag=2)
        x = paddle.to_tensor(np.float32(1.5))
        f.push(0, x)
        f.sync()  # blocks until computed, consumes nothing
        assert len(f) == 1
        assert f.drain() == [(0, 1.5)]
