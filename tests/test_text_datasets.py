"""Text dataset readers (VERDICT r2 Next #9): Imikolov/Conll05st/
Movielens/WMT14/WMT16 read the STANDARD archive layouts (egress-gated
environment: tests build synthetic archives in those layouts)."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import (Conll05st, Imikolov, Movielens, WMT14,
                             WMT16)


def _tar_add(tf, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture
def ptb_tar(tmp_path):
    p = str(tmp_path / "simple-examples.tgz")
    train = b"the cat sat\nthe dog sat on the cat\n"
    valid = b"the cat ran\n"
    test = b"a dog sat\n"
    with tarfile.open(p, "w:gz") as tf:
        _tar_add(tf, "./simple-examples/data/ptb.train.txt", train)
        _tar_add(tf, "./simple-examples/data/ptb.valid.txt", valid)
        _tar_add(tf, "./simple-examples/data/ptb.test.txt", test)
    return p


def test_imikolov_ngram_and_seq(ptb_tar):
    ds = Imikolov(data_file=ptb_tar, data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=1)
    # vocab: words with freq > 1 over train+valid, (-freq, word) order,
    # <s>/<e> counted per line, <unk> last
    wi = ds.word_idx
    assert wi["<unk>"] == len(wi) - 1
    assert "the" in wi and "cat" in wi
    assert len(ds) > 0
    first = ds[0]
    assert len(first) == 2 and all(x.shape == () for x in first)

    seq = Imikolov(data_file=ptb_tar, data_type="SEQ", mode="test",
                   min_word_freq=1)
    src, trg = seq[0]
    assert len(src) == len(trg)
    np.testing.assert_array_equal(src[1:], trg[:-1])


def test_conll05st(tmp_path):
    words = b"The\ncat\nsat\n\nDogs\nbark\n\n"
    # props: first column = verb sense ('-' for none), then per-verb
    # span columns
    props = (b"-\t*\n-\t*\nsit\t(V*)\n\n"
             b"-\t(A0*)\nbark\t(V*)\n\n")
    p = str(tmp_path / "conll05st-tests.tar.gz")
    with tarfile.open(p, "w:gz") as tf:
        wbuf = io.BytesIO()
        with gzip.GzipFile(fileobj=wbuf, mode="w") as g:
            g.write(words)
        _tar_add(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                 wbuf.getvalue())
        pbuf = io.BytesIO()
        with gzip.GzipFile(fileobj=pbuf, mode="w") as g:
            g.write(props)
        _tar_add(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                 pbuf.getvalue())
    wd = str(tmp_path / "words.dict")
    open(wd, "w").write("The\ncat\nsat\nDogs\nbark\n")
    vd = str(tmp_path / "verbs.dict")
    open(vd, "w").write("sit\nbark\n")
    td = str(tmp_path / "targets.dict")
    open(td, "w").write("B-V\nI-V\nB-A0\nI-A0\n")
    ds = Conll05st(data_file=p, word_dict_file=wd, verb_dict_file=vd,
                   target_dict_file=td)
    assert len(ds) == 2
    row = ds[0]
    assert len(row) == 9
    word_idx, *_ctx, pred, mark, label = row
    assert word_idx.shape == (3,)
    assert mark.tolist().count(1) >= 1
    assert label.shape == (3,)


def test_movielens(tmp_path):
    p = str(tmp_path / "ml-1m.zip")
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Jumanji (1995)::Adventure\n")
        z.writestr("ml-1m/users.dat",
                   "1::M::25::4::12345\n2::F::35::7::67890\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n2::2::3::978302109\n"
                   "1::2::4::978301968\n")
    ds = Movielens(data_file=p, mode="train", test_ratio=0.0)
    assert len(ds) == 3
    row = ds[0]
    # uid, gender, age, job, mov_id, categories, title words, rating
    assert len(row) == 8
    assert row[-1].shape == (1,)
    assert float(row[-1][0]) in (5.0, 1.0, 3.0)  # rating*2-5
    test = Movielens(data_file=p, mode="test", test_ratio=0.0)
    assert len(test) == 0


@pytest.fixture
def wmt14_tar(tmp_path):
    p = str(tmp_path / "wmt14.tgz")
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = b"hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(p, "w:gz") as tf:
        _tar_add(tf, "wmt14/src.dict", src_dict)
        _tar_add(tf, "wmt14/trg.dict", trg_dict)
        _tar_add(tf, "wmt14/train/train", train)
        _tar_add(tf, "wmt14/test/test", b"world\tmonde\n")
    return p


def test_wmt14(wmt14_tar):
    ds = WMT14(data_file=wmt14_tar, mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    # <s> hello world <e>
    assert src.tolist() == [0, 3, 4, 1]
    assert trg.tolist() == [0, 3, 4]       # <s> bonjour monde
    assert trg_next.tolist() == [3, 4, 1]  # bonjour monde <e>
    sd, td = ds.get_dict()
    assert sd["hello"] == 3 and td["monde"] == 4
    test = WMT14(data_file=wmt14_tar, mode="test", dict_size=5)
    assert len(test) == 1


def test_wmt16(tmp_path):
    p = str(tmp_path / "wmt16.tar.gz")
    train = b"hello world\thallo welt\nhello\thallo\n"
    val = b"world\twelt\n"
    with tarfile.open(p, "w:gz") as tf:
        _tar_add(tf, "wmt16/train", train)
        _tar_add(tf, "wmt16/val", val)
        _tar_add(tf, "wmt16/test", val)
    ds = WMT16(data_file=p, mode="train", src_dict_size=10,
               trg_dict_size=10, lang="en")
    src, trg, trg_next = ds[0]
    # <s>=1 <e>=2; "hello" most frequent -> id 4
    assert src[0] == 1 and src[-1] == 2
    assert trg[0] == 1 and trg_next[-1] == 2
    np.testing.assert_array_equal(trg[1:], trg_next[:-1])
    # de source direction swaps columns
    de = WMT16(data_file=p, mode="val", src_dict_size=10,
               trg_dict_size=10, lang="de")
    s2, t2, _ = de[0]
    assert len(s2) == 3 and len(t2) == 2
