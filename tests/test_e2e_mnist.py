"""End-to-end slice: LeNet on synthetic MNIST, dygraph + jitted TrainStep
(SURVEY.md §7 step 3 = BASELINE.json config #1). Mirrors the reference's
book/e2e tests (python/paddle/fluid/tests/book/) which train to
convergence; here we train a few steps and assert the loss drops."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.nn import functional as F


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120),
            nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = paddle.flatten(x, 1)
        return self.fc(x)


def _synthetic_mnist(n=256):
    rng = np.random.RandomState(42)
    labels = rng.randint(0, 10, n)
    # separable synthetic digits: class-dependent blob position
    imgs = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    for i, l in enumerate(labels):
        imgs[i, 0, 2 + 2 * (l // 5): 10 + 2 * (l // 5),
             2 + 2 * (l % 5): 10 + 2 * (l % 5)] += 1.0
    return imgs, labels.astype(np.int64)


def test_mnist_dygraph_loss_drops():
    paddle.seed(0)
    imgs, labels = _synthetic_mnist(128)
    model = LeNet()
    opt = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    loader = DataLoader(TensorDataset([imgs, labels]), batch_size=32,
                        shuffle=True)
    losses = []
    for epoch in range(2):
        for x, y in loader:
            logits = model(x)
            loss = F.cross_entropy(logits, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_mnist_jitted_trainstep():
    paddle.seed(0)
    imgs, labels = _synthetic_mnist(128)
    model = LeNet()
    opt = optimizer.Adam(learning_rate=2e-3, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, opt, F.cross_entropy)
    losses = []
    for epoch in range(3):
        for i in range(0, 128, 32):
            loss = step(paddle.to_tensor(imgs[i:i + 32]),
                        paddle.to_tensor(labels[i:i + 32]))
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_jitted_and_eager_same_model():
    """The jitted forward on a model equals the eager forward."""
    model = LeNet()
    model.eval()
    x = paddle.randn((4, 1, 28, 28))
    eager_out = model(x)
    jitted = paddle.jit.to_static(model)
    jit_out = jitted(x)
    np.testing.assert_allclose(eager_out.numpy(), jit_out.numpy(),
                               rtol=1e-4, atol=1e-5)


def test_save_load_checkpoint_resume(tmp_path):
    model = LeNet()
    opt = optimizer.Adam(parameters=model.parameters())
    x = paddle.randn((8, 1, 28, 28))
    y = paddle.to_tensor(np.zeros(8, np.int64))
    loss = F.cross_entropy(model(x), y)
    loss.backward()
    opt.step()
    paddle.save({"model": model.state_dict(), "opt": opt.state_dict()},
                str(tmp_path / "ckpt.pdparams"))
    ckpt = paddle.load(str(tmp_path / "ckpt.pdparams"))
    model2 = LeNet()
    model2.set_state_dict(ckpt["model"])
    opt2 = optimizer.Adam(parameters=model2.parameters())
    opt2.set_state_dict(ckpt["opt"])
    model.eval()
    model2.eval()
    np.testing.assert_allclose(model(x).numpy(), model2(x).numpy(),
                               rtol=1e-5, atol=1e-6)
