"""Static graph (Program/Executor) tests.

Mirrors the reference's static-mode tests: build Program via
program_guard + static.data, run via Executor, train via
optimizer.minimize, save/load inference model
(python/paddle/fluid/tests/unittests/test_program.py,
test_executor_*.py, test_inference_model_io.py analogs).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


def test_build_and_run_forward():
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        x = static.data("x", [4, 3], "float32")
        y = x * 2.0 + 1.0
        z = y.sum()
    assert len(prog.ops) >= 2
    exe = static.Executor()
    exe.run(startup)
    xv = np.arange(12, dtype=np.float32).reshape(4, 3)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[y, z])
    np.testing.assert_allclose(out[0], xv * 2 + 1, rtol=1e-6)
    np.testing.assert_allclose(out[1], (xv * 2 + 1).sum(), rtol=1e-6)


def test_layer_in_program_captures_params():
    paddle.seed(0)
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        lin = nn.Linear(3, 2)
        x = static.data("x", [5, 3], "float32")
        out = lin(x)
    assert len(prog.parameters()) == 2  # weight + bias captured
    exe = static.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    res = exe.run(prog, feed={"x": xv}, fetch_list=[out])[0]
    # eager reference
    ref = lin(paddle.to_tensor(xv)).numpy()
    np.testing.assert_allclose(res, ref, rtol=1e-5, atol=1e-5)


def test_append_backward_matches_numeric():
    paddle.seed(1)
    prog = static.Program()
    with static.program_guard(prog, static.Program()):
        lin = nn.Linear(4, 1)
        x = static.data("x", [8, 4], "float32")
        loss = (lin(x) ** 2).mean()
        pairs = static.append_backward(loss)
    assert all(g.endswith("@GRAD") for _, g in pairs)
    exe = static.Executor()
    xv = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    grads = exe.run(prog, feed={"x": xv}, fetch_list=[g for _, g in pairs])

    # eager reference: same layer, same loss, tape backward
    xt = paddle.to_tensor(xv)
    eager_loss = (lin(xt) ** 2).mean()
    eager_loss.backward()
    eager_grads = {n: p.grad.numpy() for n, p in lin.named_parameters()}
    # match static grads by shape (param order is registration order)
    for (pname, _), gv in zip(pairs, grads):
        match = [eg for eg in eager_grads.values() if eg.shape == gv.shape]
        assert match, f"no eager grad of shape {gv.shape}"
        np.testing.assert_allclose(gv, match[0], rtol=1e-4, atol=1e-5)


def test_minimize_trains():
    paddle.seed(2)
    prog = static.Program()
    startup = static.Program()
    rng = np.random.RandomState(2)
    xv = rng.randn(32, 4).astype(np.float32)
    true_w = rng.randn(4, 1).astype(np.float32)
    yv = xv @ true_w

    with static.program_guard(prog, startup):
        lin = nn.Linear(4, 1)
        x = static.data("x", [32, 4], "float32")
        y = static.data("y", [32, 1], "float32")
        loss = ((lin(x) - y) ** 2).mean()
        opt = optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    losses = [float(exe.run(prog, feed={"x": xv, "y": yv},
                            fetch_list=[loss])[0]) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_adam_minimize_trains():
    paddle.seed(3)
    prog = static.Program()
    startup = static.Program()
    rng = np.random.RandomState(3)
    xv = rng.randn(16, 3).astype(np.float32)
    yv = (xv.sum(1, keepdims=True) > 0).astype(np.float32)

    with static.program_guard(prog, startup):
        net = nn.Sequential(nn.Linear(3, 8), nn.ReLU(), nn.Linear(8, 1))
        x = static.data("x", [16, 3], "float32")
        y = static.data("y", [16, 1], "float32")
        logits = net(x)
        loss = nn.functional.binary_cross_entropy_with_logits(logits, y)
        opt = optimizer.Adam(learning_rate=0.05)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(startup)
    losses = [float(exe.run(prog, feed={"x": xv, "y": yv},
                            fetch_list=[loss])[0]) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_program_clone_and_str():
    prog = static.Program()
    with static.program_guard(prog, static.Program()):
        x = static.data("x", [2, 2], "float32")
        _ = x + 1.0
    s = str(prog)
    assert "var x" in s and "add" in s.lower()
    c = prog.clone(for_test=True)
    assert len(c.ops) == len(prog.ops)


def test_save_load_inference_model(tmp_path):
    paddle.seed(4)
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        lin = nn.Linear(3, 2)
        x = static.data("x", [4, 3], "float32")
        out = nn.functional.softmax(lin(x))
    exe = static.Executor()
    exe.run(startup)
    path = str(tmp_path / "infer_model")
    static.save_inference_model(path, [x], [out], exe)

    loaded, feed_names, fetch_names = static.load_inference_model(path)
    assert feed_names == ["x"]
    xv = np.random.RandomState(4).randn(4, 3).astype(np.float32)
    got = loaded.run({"x": xv})[0]
    ref = exe.run(prog, feed={"x": xv}, fetch_list=[out])[0]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_static_nn_cond_while():
    prog = static.Program()
    with static.program_guard(prog, static.Program()):
        x = static.data("x", [1], "float32")
        y = static.nn.cond(x.sum() > 0,
                           lambda: x * 2.0, lambda: x - 1.0)
    exe = static.Executor()
    pos = exe.run(prog, feed={"x": np.array([3.0], np.float32)},
                  fetch_list=[y])[0]
    neg = exe.run(prog, feed={"x": np.array([-3.0], np.float32)},
                  fetch_list=[y])[0]
    np.testing.assert_allclose(pos, [6.0])
    np.testing.assert_allclose(neg, [-4.0])


def test_clone_for_test_drops_training_ops():
    paddle.seed(5)
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        lin = nn.Linear(3, 1)
        x = static.data("x", [4, 3], "float32")
        y = static.data("y", [4, 1], "float32")
        loss = ((lin(x) - y) ** 2).mean()
        optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = prog.clone(for_test=True)
    assert all(o.type not in ("backward", "optimizer_update")
               for o in test_prog.ops)
    exe = static.Executor()
    exe.run(startup)
    xv = np.ones((4, 3), np.float32)
    yv = np.zeros((4, 1), np.float32)
    # eval on the test clone twice: loss identical (no training happened)
    l1 = float(exe.run(test_prog, feed={"x": xv, "y": yv},
                       fetch_list=[loss])[0])
    l2 = float(exe.run(test_prog, feed={"x": xv, "y": yv},
                       fetch_list=[loss])[0])
    assert l1 == l2


def test_run_without_fetch_does_not_wipe_params():
    paddle.seed(6)
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        lin = nn.Linear(2, 1)
        x = static.data("x", [4, 2], "float32")
        y = static.data("y", [4, 1], "float32")
        loss = ((lin(x) - y) ** 2).mean()
        optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((4, 2), np.float32),
            "y": 3 * np.ones((4, 1), np.float32)}
    for _ in range(5):
        exe.run(prog, feed=feed, fetch_list=[loss])
    pname = prog.parameters()[0]
    trained = np.asarray(static.global_scope().vars[pname]).copy()
    # run with no fetch_list: executes the program, must NOT reset params
    exe.run(prog, feed=feed)
    after = np.asarray(static.global_scope().vars[pname])
    assert not np.allclose(after, np.asarray(prog._param_inits[pname]))
    # and re-running startup does not clobber trained values either
    exe.run(startup)
    still = np.asarray(static.global_scope().vars[pname])
    np.testing.assert_allclose(still, after)


def test_lr_scheduler_reaches_static_updates():
    paddle.seed(7)
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        lin = nn.Linear(2, 1)
        x = static.data("x", [4, 2], "float32")
        loss = lin(x).mean()
        sched = optimizer.lr.StepDecay(learning_rate=1.0, step_size=1,
                                       gamma=0.1)
        opt = optimizer.SGD(learning_rate=sched)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((4, 2), np.float32)}
    exe.run(prog, feed=feed, fetch_list=[loss])
    lr_after_1 = float(np.asarray(static.global_scope().vars["@LR"]))
    sched.step()  # epoch-granular scheduler: user steps it
    exe.run(prog, feed=feed, fetch_list=[loss])
    lr_after_2 = float(np.asarray(static.global_scope().vars["@LR"]))
    assert lr_after_1 == pytest.approx(1.0)
    assert lr_after_2 == pytest.approx(0.1)


def test_minimize_with_parameter_subset():
    paddle.seed(8)
    prog = static.Program()
    startup = static.Program()
    with static.program_guard(prog, startup):
        a = nn.Linear(2, 2)
        b = nn.Linear(2, 1)
        x = static.data("x", [4, 2], "float32")
        loss = b(a(x)).mean()
        opt = optimizer.SGD(learning_rate=0.5, parameters=b.parameters())
        opt.minimize(loss)
    update_ops = [o for o in prog.ops if o.type == "optimizer_update"]
    assert len(update_ops) == 1
    exe = static.Executor()
    exe.run(startup)
    feed = {"x": np.ones((4, 2), np.float32)}
    a_name = prog._param_ids[id(a.weight)]
    b_name = prog._param_ids[id(b.weight)]
    a_before = np.asarray(static.global_scope().vars.get(a_name)
                          if static.global_scope().vars.get(a_name)
                          is not None else prog._param_inits[a_name]).copy()
    exe.run(prog, feed=feed, fetch_list=[loss])
    a_after = np.asarray(static.global_scope().vars[a_name])
    b_after = np.asarray(static.global_scope().vars[b_name])
    np.testing.assert_allclose(a_before, a_after)  # frozen subset untouched
    assert not np.allclose(np.asarray(prog._param_inits[b_name]), b_after)


def test_eager_unaffected_outside_guard():
    # building a program must not leak: eager ops after the guard behave
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    prog = static.Program()
    with static.program_guard(prog, static.Program()):
        x = static.data("x", [2, 2], "float32")
        _ = x + 1.0
    out = t * 3.0
    assert not hasattr(out, "_static_name")
    np.testing.assert_allclose(out.numpy(), 3 * np.ones((2, 2)))


def test_tensor_array_ops():
    arr = static.create_array()
    static.array_write(paddle.ones([2]), 0, arr)
    static.array_write(paddle.full([2], 5.0), 2, arr)
    assert int(static.array_length(arr)) == 3
    np.testing.assert_allclose(static.array_read(arr, 0).numpy(), 1.0)
    np.testing.assert_allclose(static.array_read(arr, 2).numpy(), 5.0)
    assert static.array_read(arr, 1) is None
