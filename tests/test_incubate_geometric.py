"""incubate.autograd functional prims + geometric ops tests
(reference: python/paddle/fluid/tests/unittests/autograd/ and
test_segment_ops.py / test_graph_send_recv_op.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric
from paddle_tpu.incubate import autograd as iag


# --------------------------------------------------------------- autograd
def test_jvp_matches_directional_derivative():
    def f(x):
        return (x * x).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    v = paddle.to_tensor(np.array([1.0, 0.0, 0.0], np.float32))
    out, tangent = iag.jvp(f, x, v)
    np.testing.assert_allclose(float(out), 14.0)
    np.testing.assert_allclose(float(tangent), 2.0)  # d/dx0 = 2*x0*v0


def test_vjp_and_grad():
    def f(x):
        return (x ** 3).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    out, g = iag.vjp(f, x)
    np.testing.assert_allclose(float(out), 9.0)
    np.testing.assert_allclose(g.numpy(), [3.0, 12.0])
    g2 = iag.grad(f, x)
    np.testing.assert_allclose(g2.numpy(), [3.0, 12.0])


def test_forward_grad_default_tangent():
    def f(x):
        return 2.0 * x

    x = paddle.to_tensor(np.array([1.0, 5.0], np.float32))
    t = iag.forward_grad(f, x)
    np.testing.assert_allclose(t.numpy(), [2.0, 2.0])


def test_multi_input_vjp():
    def f(x, y):
        return (x * y).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    y = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    _, (gx, gy) = iag.vjp(f, (x, y))
    np.testing.assert_allclose(gx.numpy(), [3.0, 4.0])
    np.testing.assert_allclose(gy.numpy(), [1.0, 2.0])


def test_jacobian_and_hessian():
    def f(x):
        return x * x

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    jac = iag.Jacobian(f, x)
    np.testing.assert_allclose(jac.numpy(), np.diag([2.0, 4.0, 6.0]))
    assert jac.shape == (3, 3)
    np.testing.assert_allclose(jac[0].numpy(), [2.0, 0.0, 0.0])

    def g(x):
        return (x ** 3).sum()

    hess = iag.Hessian(g, x)
    np.testing.assert_allclose(hess.numpy(), np.diag([6.0, 12.0, 18.0]))


def test_jacobian_multi_input():
    def f(a, b):
        return a * b

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    y = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    jac = iag.Jacobian(f, (x, y))
    # [2 outputs, 4 inputs]: d(a*b)/da = diag(b), d(a*b)/db = diag(a)
    expect = np.concatenate([np.diag([3.0, 4.0]), np.diag([1.0, 2.0])],
                            axis=1)
    np.testing.assert_allclose(jac.numpy(), expect)


def test_jacobian_batched_diagonal():
    def f(x):
        return x * 2.0

    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    jac = iag.Jacobian(f, x, is_batched=True)
    assert jac.shape == (2, 3, 3)
    np.testing.assert_allclose(jac.numpy(),
                               np.tile(2 * np.eye(3), (2, 1, 1)))
    with pytest.raises(NotImplementedError):
        iag.Jacobian(f, paddle.randn([2, 3, 4]),
                     is_batched=True).numpy()


def test_hessian_multi_input_and_scalar_check():
    def f(a, b):
        return (a * b).sum()

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    y = paddle.to_tensor(np.array([3.0, 4.0], np.float32))
    hess = iag.Hessian(f, (x, y))
    # d2/dadb = I in the off-diagonal blocks
    expect = np.block([[np.zeros((2, 2)), np.eye(2)],
                       [np.eye(2), np.zeros((2, 2))]])
    np.testing.assert_allclose(hess.numpy(), expect, atol=1e-6)
    with pytest.raises(ValueError, match="scalar"):
        iag.Hessian(lambda t: t * 2, x).numpy()  # vector output


def test_jacobian_layout_consistent_bare_vs_tuple():
    def f(a):
        return a * a

    x = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
    j1 = iag.Jacobian(f, x)
    j2 = iag.Jacobian(f, (x,))
    assert j1.shape == j2.shape == (4, 4)
    np.testing.assert_allclose(j1.numpy(), j2.numpy())


def test_hessian_batched():
    def f(x):
        return (x ** 2).sum(-1)  # per-sample scalar

    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3)
                         .astype(np.float32))
    hess = iag.Hessian(f, x, is_batched=True)
    assert hess.shape == (2, 3, 3)
    np.testing.assert_allclose(hess.numpy(),
                               np.tile(2 * np.eye(3), (2, 1, 1)),
                               atol=1e-5)


def test_segment_max_int_dtype_empty_fill():
    data = paddle.to_tensor(np.array([[1], [2]], np.int32))
    ids = paddle.to_tensor(np.array([0, 2], np.int32))
    out = geometric.segment_max(data, ids, num_segments=3)
    assert out.numpy().dtype == np.int32
    np.testing.assert_array_equal(out.numpy(), [[1], [0], [2]])


# -------------------------------------------------------------- geometric
def test_segment_ops():
    data = paddle.to_tensor(
        np.array([[1.0, 2], [3, 4], [5, 6], [7, 8]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1], np.int32))
    np.testing.assert_allclose(
        geometric.segment_sum(data, ids).numpy(), [[4, 6], [12, 14]])
    np.testing.assert_allclose(
        geometric.segment_mean(data, ids).numpy(), [[2, 3], [6, 7]])
    np.testing.assert_allclose(
        geometric.segment_max(data, ids).numpy(), [[3, 4], [7, 8]])
    np.testing.assert_allclose(
        geometric.segment_min(data, ids).numpy(), [[1, 2], [5, 6]])


def test_segment_empty_segment_fills_zero():
    data = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
    ids = paddle.to_tensor(np.array([0, 2], np.int32))
    out = geometric.segment_max(data, ids, num_segments=3).numpy()
    np.testing.assert_allclose(out, [[1.0], [0.0], [2.0]])


def test_send_u_recv():
    x = paddle.to_tensor(
        np.array([[0.0, 1], [2, 3], [4, 5]], np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
    out = geometric.send_u_recv(x, src, dst, reduce_op="sum").numpy()
    # node1 <- x0 + x2 ; node2 <- x1 ; node0 <- x0
    np.testing.assert_allclose(out, [[0, 1], [4, 6], [2, 3]])
    out_max = geometric.send_u_recv(x, src, dst,
                                    reduce_op="max").numpy()
    np.testing.assert_allclose(out_max, [[0, 1], [4, 5], [2, 3]])


def test_send_ue_recv():
    x = paddle.to_tensor(np.array([[1.0], [2.0]], np.float32))
    e = paddle.to_tensor(np.array([[10.0], [20.0]], np.float32))
    src = paddle.to_tensor(np.array([0, 1], np.int32))
    dst = paddle.to_tensor(np.array([1, 0], np.int32))
    out = geometric.send_ue_recv(x, e, src, dst, message_op="add",
                                 reduce_op="sum").numpy()
    np.testing.assert_allclose(out, [[22.0], [11.0]])
    out_mul = geometric.send_ue_recv(x, e, src, dst, message_op="mul",
                                     reduce_op="sum").numpy()
    np.testing.assert_allclose(out_mul, [[40.0], [10.0]])


def test_send_u_recv_grad_flows():
    x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
    x.stop_gradient = False
    src = paddle.to_tensor(np.array([0, 1], np.int32))
    dst = paddle.to_tensor(np.array([1, 1], np.int32))
    out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1.0], [1.0], [0.0]])


def test_bad_reduce_op():
    x = paddle.to_tensor(np.zeros((2, 1), np.float32))
    idx = paddle.to_tensor(np.array([0, 1], np.int32))
    with pytest.raises(ValueError):
        geometric.send_u_recv(x, idx, idx, reduce_op="prod")


# ----------------------------------------------------------- incubate.nn
def test_fused_multi_head_attention_matches_reference_math():
    from paddle_tpu.incubate import nn as inn
    paddle.seed(0)
    attn = inn.FusedMultiHeadAttention(embed_dim=16, num_heads=4,
                                       normalize_before=True)
    attn.eval()
    x = paddle.randn([2, 6, 16])
    out = attn(x)
    assert tuple(out.shape) == (2, 6, 16)
    # manual recompute of the same math
    import jax.numpy as jnp
    xe = attn.norm(x)
    qkv = attn.qkv_proj(xe).numpy().reshape(2, 6, 3, 4, 4)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    qh = np.transpose(q, (0, 2, 1, 3))
    kh = np.transpose(k, (0, 2, 1, 3))
    vh = np.transpose(v, (0, 2, 1, 3))
    logits = qh @ np.transpose(kh, (0, 1, 3, 2)) / np.sqrt(4.0)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    ref = np.transpose(w @ vh, (0, 2, 1, 3)).reshape(2, 6, 16)
    ref_out = x.numpy() + attn.out_proj(
        paddle.to_tensor(ref.astype(np.float32))).numpy()
    np.testing.assert_allclose(out.numpy(), ref_out, rtol=1e-3,
                               atol=1e-4)


def test_fused_multi_transformer_trains():
    from paddle_tpu.incubate import nn as inn
    from paddle_tpu import optimizer
    paddle.seed(0)
    model = inn.FusedMultiTransformer(embed_dim=16, num_heads=2,
                                      dim_feedforward=32, num_layers=2)
    opt = optimizer.Adam(learning_rate=1e-3,
                         parameters=model.parameters())
    x = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 5, 16])
    first = None
    for _ in range(10):
        loss = ((model(x) - tgt) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
    assert float(loss) < first


# ---------------------------------------------------------------------------
# r5: send_uv / reindex / sample_neighbors (VERDICT r4 Next #6) —
# goldens are the reference docstring examples (exact expected outputs)
# plus numpy oracles.

def test_send_uv_reference_example_and_grads():
    import jax
    import jax.numpy as jnp
    x = paddle.to_tensor(np.array([[0, 2, 3], [1, 4, 5], [2, 6, 7]],
                                  np.float32))
    y = paddle.to_tensor(np.array([[0, 1, 2], [2, 3, 4], [4, 5, 6]],
                                  np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
    out = geometric.send_uv(x, y, src, dst, message_op="add")
    np.testing.assert_allclose(
        out.numpy(), [[2, 5, 7], [5, 9, 11], [4, 9, 11], [0, 3, 5]])
    for op, fn in [("sub", np.subtract), ("mul", np.multiply),
                   ("div", np.divide)]:
        got = geometric.send_uv(x, y, src, dst, message_op=op).numpy()
        want = fn(x.numpy()[[0, 1, 2, 0]], y.numpy()[[1, 2, 1, 0]])
        np.testing.assert_allclose(got, want, rtol=1e-6)

    # differentiable wrt both node tensors
    def loss(xv, yv):
        from paddle_tpu.geometric.message_passing import _send_uv_impl
        return jnp.sum(_send_uv_impl.raw(
            xv, yv, jnp.asarray([0, 1], jnp.int32),
            jnp.asarray([1, 0], jnp.int32), message_op="mul") ** 2)
    gx, gy = jax.grad(loss, argnums=(0, 1))(
        jnp.asarray(x.numpy()), jnp.asarray(y.numpy()))
    assert np.isfinite(np.asarray(gx)).all()
    assert np.abs(np.asarray(gx)[2]).sum() == 0  # node 2 unused


def test_reindex_graph_reference_example():
    x = paddle.to_tensor(np.array([0, 1, 2], np.int64))
    neighbors = paddle.to_tensor(
        np.array([8, 9, 0, 4, 7, 6, 7], np.int64))
    count = paddle.to_tensor(np.array([2, 3, 2], np.int32))
    src, dst, out_nodes = geometric.reindex_graph(x, neighbors, count)
    np.testing.assert_array_equal(src.numpy(), [3, 4, 0, 5, 6, 7, 6])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1, 1, 2, 2])
    np.testing.assert_array_equal(out_nodes.numpy(),
                                  [0, 1, 2, 8, 9, 4, 7, 6])
    # invariant: out_nodes[src] recovers the original neighbor ids
    np.testing.assert_array_equal(out_nodes.numpy()[src.numpy()],
                                  neighbors.numpy())


def test_reindex_heter_graph_reference_example():
    x = paddle.to_tensor(np.array([0, 1, 2], np.int64))
    nA = paddle.to_tensor(np.array([8, 9, 0, 4, 7, 6, 7], np.int64))
    cA = paddle.to_tensor(np.array([2, 3, 2], np.int32))
    nB = paddle.to_tensor(np.array([0, 2, 3, 5, 1], np.int64))
    cB = paddle.to_tensor(np.array([1, 3, 1], np.int32))
    src, dst, out_nodes = geometric.reindex_heter_graph(
        x, [nA, nB], [cA, cB])
    np.testing.assert_array_equal(
        src.numpy(), [3, 4, 0, 5, 6, 7, 6, 0, 2, 8, 9, 1])
    np.testing.assert_array_equal(
        dst.numpy(), [0, 0, 1, 1, 1, 2, 2, 0, 1, 1, 1, 2])
    np.testing.assert_array_equal(
        out_nodes.numpy(), [0, 1, 2, 8, 9, 4, 7, 6, 3, 5])


def test_sample_neighbors_csc():
    paddle.seed(0)
    row = paddle.to_tensor(np.array(
        [3, 7, 0, 9, 1, 4, 2, 9, 3, 9, 1, 9, 7], np.int64))
    colptr = paddle.to_tensor(np.array(
        [0, 2, 4, 5, 6, 7, 9, 11, 11, 13, 13], np.int64))
    nodes = paddle.to_tensor(np.array([0, 8, 1, 2], np.int64))
    nb, ct = geometric.sample_neighbors(row, colptr, nodes,
                                        sample_size=2)
    counts = ct.numpy()
    np.testing.assert_array_equal(counts, [2, 2, 2, 1])
    # every sampled neighbor is a true CSC neighbor of its node
    r, cp = row.numpy(), colptr.numpy()
    flat = nb.numpy()
    ofs = 0
    for n, c in zip(nodes.numpy(), counts):
        true = set(r[cp[n]:cp[n + 1]])
        got = flat[ofs:ofs + c]
        assert set(got) <= true and len(set(got)) == c
        ofs += c
    # sample_size=-1 returns all neighbors in order
    nb_all, ct_all = geometric.sample_neighbors(row, colptr, nodes)
    np.testing.assert_array_equal(ct_all.numpy(), [2, 2, 2, 1])
    # eids returned when asked
    eids = paddle.to_tensor(np.arange(13, dtype=np.int64))
    nb3, ct3, ei = geometric.sample_neighbors(
        row, colptr, nodes, sample_size=2, eids=eids, return_eids=True)
    ofs = 0
    for n, c in zip(nodes.numpy(), ct3.numpy()):
        for e, v in zip(ei.numpy()[ofs:ofs + c],
                        nb3.numpy()[ofs:ofs + c]):
            assert r[e] == v  # eid points at the sampled edge
        ofs += c
