"""The fault-injection harness must itself be deterministic: faults
fire on exact call counts / exact files, never on wall-clock races —
otherwise every chaos test built on it is flaky by construction."""
import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (COMMIT_MARKER,
                                               CheckpointManager)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.chaos


def test_kill_after_fires_on_exact_step():
    hits = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: hits.append(s))
    try:
        kill = fi.KillAfter(3, sig=signal.SIGUSR1)
        fired = [kill.step() for _ in range(5)]
        assert fired == [False, False, True, False, False]
        assert len(hits) == 1  # exactly once, on call 3
        assert kill.calls == 5 and kill.fired
    finally:
        signal.signal(signal.SIGUSR1, prev)


def test_kill_after_rejects_zero():
    with pytest.raises(ValueError):
        fi.KillAfter(0)


def test_store_faults_trigger_exact_count():
    store = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        store.set("a", 1)
        with fi.StoreFaults(delay=0.0, ops=("get",), count=2) as faults:
            for _ in range(4):
                assert store.get("a", timeout=5.0) == 1
            assert faults.triggered == 2  # not 4: bounded by count
        # op filter: sets never match a get-only fault
        with fi.StoreFaults(delay=0.0, ops=("get",)) as faults:
            store.set("b", 2)
            assert faults.triggered == 0
        # key-prefix filter
        with fi.StoreFaults(delay=0.0, ops=("get",),
                            key_prefix="__x") as faults:
            store.get("a", timeout=5.0)
            assert faults.triggered == 0
    finally:
        store.shutdown_server()


def test_store_faults_drop_closes_without_reply():
    store = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        store.set("k", 41)
        with fi.StoreFaults(drop=True, ops=("get",), count=1):
            # the dropped reply looks like a transient reset; the
            # client's bounded retry gets the answer on reconnect
            assert store.get("k", timeout=10.0) == 41
    finally:
        store.shutdown_server()


def test_truncate_checkpoint_is_deterministic(tmp_path):
    rng = np.random.RandomState(0)
    tree = {"w": rng.randn(64, 64).astype(np.float32),
            "b": rng.randn(64).astype(np.float32)}
    d = str(tmp_path / "run")
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(0, dict(tree))
    mgr.close()
    # enumeration is a pure function of on-disk state
    a = fi.checkpoint_data_files(d)
    assert a == fi.checkpoint_data_files(d)
    victims = fi.truncate_checkpoint(d)
    assert victims == a
    assert all(os.path.getsize(v) == 0 for v in victims)
    # metadata/markers survive: the step still LOOKS committed
    assert os.path.exists(os.path.join(d, "0", COMMIT_MARKER))


def test_remove_commit_marker(tmp_path):
    d = str(tmp_path / "c")
    mgr = CheckpointManager(d, async_save=False)
    mgr.save(0, {"w": np.zeros(4, np.float32)})
    mgr.close()
    p = fi.remove_commit_marker(d, step=0)
    assert p.endswith(COMMIT_MARKER) and not os.path.exists(p)
    with pytest.raises(FileNotFoundError):
        fi.remove_commit_marker(d, step=0)  # already gone


def test_poison_batch_nans_floats_only():
    batch = (np.arange(6, dtype=np.float32).reshape(2, 3),
             np.array([1, 0], dtype=np.int64),
             {"aux": paddle.to_tensor(np.ones(2, np.float32))})
    poisoned = fi.poison_batch(batch)
    assert np.isnan(poisoned[0]).all()
    np.testing.assert_array_equal(poisoned[1], batch[1])  # labels intact
    assert np.isnan(np.asarray(poisoned[2]["aux"].data)).all()
    assert not np.isnan(batch[0]).any()  # original untouched


def test_nan_loss_fires_on_exact_calls():
    wrapped = fi.NaNLoss(lambda a, b: float(a + b), at_calls=(2, 4))
    out = [wrapped(1.0, 1.0) for _ in range(5)]
    assert [np.isnan(v) for v in out] == [False, True, False, True, False]
