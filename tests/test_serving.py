"""Continuous-batching serving engine tests (ISSUE 8).

Covers: the steady-state invariant (ragged multi-request traffic replay
with mid-decode arrivals completes with zero new-shape retraces, every
request bitwise-equal to a sequential Predictor.generate() reference
under greedy decoding, and slot reuse actually exercised), admission
control (queue bound, deadlines — queued and in-flight), eos slot
freeing, the serve.* SLA metrics family + MetricsCallback surfacing,
the tier-1 audit gate on the slot-decode program, the bf16 precision
path, thread mode, and the chaos graceful-shutdown drain.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, PrecisionType, create_predictor
from paddle_tpu.models.gpt import gpt
from paddle_tpu.serving import (QueueFull, RequestFailed, RequestParams,
                                RequestStatus, ServingEngine)

import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


def _spec():
    return [paddle.to_tensor(np.zeros((2, 12), np.int32))]


def _config(m, *, max_new=8, buckets=(16,), max_batch=2, eos=None,
            **serving_kw):
    cfg = (Config().from_layer(m, _spec())
           .enable_generation(max_new_tokens=max_new,
                              prefill_buckets=buckets,
                              max_batch=max_batch, eos_token_id=eos))
    if serving_kw:
        cfg.enable_serving(**serving_kw)
    return cfg


@pytest.fixture(scope="module")
def engine(tiny_gpt):
    """Shared 2-slot engine with two prompt buckets (reused across the
    steady-state, inline-pump, and metrics tests — all of which leave
    it drained of traffic but serviceable)."""
    return ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16, 32),
                                 max_batch=2), poll_every=2)


def _counter(name):
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


# ----------------------------------------------------------- validation


def test_engine_requires_layer_and_generation(tiny_gpt):
    with pytest.raises(ValueError, match="live layer"):
        ServingEngine(Config())
    with pytest.raises(ValueError, match="enable_generation"):
        ServingEngine(Config().from_layer(tiny_gpt, _spec()))
    with pytest.raises(ValueError, match="no prefill bucket"):
        # test-tiny max_position_embeddings=128: bucket 512 never fits
        ServingEngine(_config(tiny_gpt, buckets=(512,)), warmup=False)
    eng = ServingEngine(_config(tiny_gpt, max_new=4, buckets=(16,),
                                max_batch=1), warmup=False)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.submit([])
    with pytest.raises(ValueError, match="largest compiled"):
        eng.submit(list(range(17)))
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1, 2], RequestParams(max_new_tokens=9))


# --------------------------------------------- the steady-state invariant


def test_steady_state_ragged_traffic(tiny_gpt, engine):
    """THE acceptance gate: ragged prompts and budgets, arrivals
    mid-decode, zero retraces after warmup, bitwise parity with the
    sequential Predictor, and a request admitted into a freed slot."""
    from paddle_tpu.core import monitor
    rng = np.random.RandomState(0)
    lens = (5, 12, 20, 7, 3)
    budgets = (8, 3, 6, 5, 8)
    prompts = [rng.randint(0, 512, n).astype(np.int32) for n in lens]
    reused0 = engine.stats["slots_reused"]

    monitor.enable()
    try:
        ns0 = _counter("jit.compile{cause=new_shape}")
        tot0 = _counter("jit.compile.total")
        handles = [engine.submit(p, RequestParams(max_new_tokens=b))
                   for p, b in zip(prompts[:2], budgets[:2])]
        for _ in range(3):          # both slots now mid-decode
            engine.step()
        handles += [engine.submit(p, RequestParams(max_new_tokens=b))
                    for p, b in zip(prompts[2:], budgets[2:])]
        while engine.busy:
            engine.step()
        # steady-state no-retrace invariant: nothing compiled under
        # traffic (every dispatch hit a warm executable)
        assert _counter("jit.compile{cause=new_shape}") - ns0 == 0
        assert _counter("jit.compile.total") - tot0 == 0
    finally:
        monitor.disable()

    assert all(h.status is RequestStatus.COMPLETED for h in handles)
    # slot reuse actually exercised: 5 requests through 2 slots
    assert engine.stats["slots_reused"] - reused0 >= 3

    # bitwise parity with the sequential one-request-at-a-time reference
    pred = create_predictor(_config(tiny_gpt, max_new=8,
                                    buckets=(16, 32), max_batch=1))
    for p, b, h in zip(prompts, budgets, handles):
        ref = pred.generate([p], max_new_tokens=b)[0]
        np.testing.assert_array_equal(h.result(), ref)


def test_result_pumps_inline(engine):
    """submit(); result() makes progress without any pump thread."""
    h = engine.submit(np.arange(1, 9, dtype=np.int32),
                      RequestParams(max_new_tokens=4))
    out = h.result(timeout=60)
    assert out.shape == (4,) and h.status is RequestStatus.COMPLETED
    assert h.ttft is not None and h.ttft >= 0.0


# ---------------------------------------------------- admission control


def test_queue_bound_rejects(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                max_batch=1, max_queue=1), poll_every=1)
    running = eng.submit([1, 2, 3])
    eng.step()                       # admitted into the only slot
    queued = eng.submit([4, 5])      # fills the queue (depth bound 1)
    with pytest.raises(QueueFull):
        eng.submit([6, 7])
    assert eng.stats["rejected"] == 1
    assert running.result(timeout=60).size == 8
    assert queued.result(timeout=60).size == 8

    # deadline on a QUEUED request: expired before a slot freed
    blocker = eng.submit([1, 2, 3])
    eng.step()                       # admit it (queue has room again)
    late = eng.submit([4, 5], RequestParams(deadline_s=0.0))
    while not late.done():
        eng.step()
    assert late.status is RequestStatus.CANCELLED
    assert late.detail == "deadline"
    with pytest.raises(RequestFailed, match="deadline"):
        late.result(timeout=5)
    assert blocker.result(timeout=60).size == 8

    # deadline on an IN-FLIGHT request: evicted mid-decode, slot freed,
    # partial tokens kept. The deadline is expired EXPLICITLY after
    # admission (a wall-clock deadline_s raced the admission step on a
    # loaded machine)
    slow = eng.submit([1, 2, 3], RequestParams(deadline_s=60.0))
    eng.step()                       # admit
    assert slow.status is RequestStatus.RUNNING
    slow.deadline = time.monotonic() - 1e-3
    while not slow.done():
        eng.step()
    assert slow.status is RequestStatus.CANCELLED
    assert slow.detail == "deadline"
    assert all(s is None for s in eng._slots)
    nxt = eng.submit([9, 9])         # the evicted slot is reusable
    assert nxt.result(timeout=60).size == 8


def test_rejection_reason_dense(tiny_gpt):
    """ISSUE-19 satellite: a queue-bound rejection carries the
    STRUCTURED health reason on both the QueueFull and the
    already-terminal handle — the router's re-route classifier reads
    it, so it must distinguish lanes from pool memory from capacity."""
    # both decode lanes busy -> queue_full:no_free_slots
    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                max_batch=1, max_queue=1), poll_every=1)
    running = eng.submit([1, 2, 3])
    eng.step()
    queued = eng.submit([4, 5])
    with pytest.raises(QueueFull) as ei:
        eng.submit([6, 7])
    assert ei.value.reason == "queue_full:no_free_slots"
    handle = ei.value.request
    assert handle is not None and handle.done()
    assert handle.status is RequestStatus.REJECTED
    assert handle.detail == "queue_full:no_free_slots"
    with pytest.raises(RequestFailed, match="no_free_slots"):
        handle.result(timeout=1)
    assert running.result(timeout=60).size == 8
    assert queued.result(timeout=60).size == 8
    eng.shutdown()

    # queue at bound with lanes still free -> bare queue_full
    eng2 = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                 max_batch=2, max_queue=1), poll_every=1)
    first = eng2.submit([1, 2, 3])    # queued, no step yet
    with pytest.raises(QueueFull) as ei2:
        eng2.submit([4, 5])
    assert ei2.value.reason == "queue_full"
    assert ei2.value.request.detail == "queue_full"
    assert first.result(timeout=60).size == 8
    eng2.shutdown()


def test_rejection_reason_paged(tiny_gpt):
    """Paged twin: a queue blocked on POOL MEMORY stamps its rejections
    queue_full:no_free_pages (the retryable-pressure signal, distinct
    from the dense lane bound)."""
    eng = ServingEngine(_config(tiny_gpt, max_batch=2, paged=True,
                                kv_page_size=16, kv_pages=3,
                                max_queue=1), poll_every=1)
    a = eng.submit(np.arange(1, 16, dtype=np.int32))   # 2 pages
    eng.step()                                         # admit a
    b = eng.submit(np.arange(2, 17, dtype=np.int32))   # blocked on pages
    eng.step()                                         # marks _page_blocked
    assert eng.health()["queue_blocked_on"] == "pages"
    with pytest.raises(QueueFull) as ei:
        eng.submit(np.arange(3, 10, dtype=np.int32))
    assert ei.value.reason == "queue_full:no_free_pages"
    assert ei.value.request.status is RequestStatus.REJECTED
    assert ei.value.request.detail == "queue_full:no_free_pages"
    while eng.busy:
        eng.step()
    assert a.status is RequestStatus.COMPLETED
    assert b.status is RequestStatus.COMPLETED
    eng._alloc.assert_conserved()
    eng.shutdown()


def test_eos_frees_slot_and_trims(tiny_gpt):
    """A row finishing on eos ends early; its result is trimmed before
    the eos, matching the Predictor's contract."""
    prompt = np.arange(1, 7, dtype=np.int32)
    pred = create_predictor(_config(tiny_gpt, max_new=8, buckets=(16,),
                                    max_batch=1))
    ref = pred.generate([prompt])[0]          # no eos configured
    eos = int(ref[3])                         # greedy token at step 3
    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                max_batch=1, eos=eos), poll_every=1)
    h = eng.submit(prompt)
    out = h.result(timeout=60)
    first = int(np.nonzero(ref == eos)[0][0])  # eos may repeat earlier
    np.testing.assert_array_equal(out, ref[:first])
    assert h.n_emitted == first + 1            # the eos itself emitted


# ------------------------------------------------------------ SLA metrics


def test_serve_metrics_family(tiny_gpt, engine):
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    monitor.enable()
    try:
        c0 = _counter("serve.requests{status=completed}")
        hs = [engine.submit(np.arange(1, 5 + i, dtype=np.int32),
                            RequestParams(max_new_tokens=6))
              for i in range(4)]
        while engine.busy:
            engine.step()
        for h in hs:
            h.result(timeout=60)
        snap = metrics.snapshot()
        assert _counter("serve.requests{status=completed}") - c0 == 4
        assert snap["serve.ttft"]["count"] >= 4
        assert snap["serve.token_latency"]["count"] >= 1
        assert snap["serve.slot_occupancy"]["peak"] > 0
        assert "serve.queue_depth" in snap
        ttft = metrics.histogram("serve.ttft")
        p50, p95 = ttft.percentile(50), ttft.percentile(95)
        assert 0 < p50 <= p95

        # MetricsCallback surfaces both capacity gauges in its summary
        from paddle_tpu.hapi.callbacks import MetricsCallback
        cb = MetricsCallback(verbose=0)
        cb.on_train_begin()
        cb.on_epoch_begin(0)
        logs = {}
        cb.on_epoch_end(0, logs)
        assert "slot_occupancy" in logs
        assert "cache_occupancy" in logs
        cb.on_train_end()
    finally:
        monitor.disable()


def test_serve_forever_without_iterator_serves_through_idle(tiny_gpt):
    """serve_forever(None) really serves forever: it pumps submit()
    traffic from other threads THROUGH idle gaps (it must not return at
    the first idle instant) until shutdown — and the idle gap is not
    attributed to serve.token_latency."""
    import threading
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    eng = ServingEngine(_config(tiny_gpt, max_new=6, buckets=(16,),
                                max_batch=1), poll_every=2)
    monitor.enable()
    try:
        server = threading.Thread(target=eng.serve_forever, daemon=True)
        server.start()
        h1 = eng.submit([1, 2, 3])
        assert h1.result(timeout=60).size == 6
        time.sleep(0.25)                  # engine idle, loop must survive
        assert server.is_alive()
        h2 = eng.submit([4, 5])           # traffic after the gap
        assert h2.result(timeout=60).size == 6
        eng.shutdown()
        server.join(timeout=30)
        assert not server.is_alive()
        # the 0.25s idle gap must not leak into per-token latency
        lat = metrics.histogram("serve.token_latency")
        assert lat.percentile(99) < 0.2
    finally:
        monitor.disable()


# ------------------------------------------------------- tier-1 audit gate


def test_serving_audit_gate(tiny_gpt):
    """Flagship gate: zero analysis ERRORs across every program the
    scheduler dispatches, and full donation coverage on the slot-decode
    and admit programs — the KV cache and token buffers must stay
    donated (in-place) across scheduler steps."""
    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16, 32),
                                max_batch=2), warmup=False)
    reports = eng.audit()
    assert set(reports) == {("prefill", 16), ("prefill", 32), "decode",
                            "admit", "free"}
    for rep in reports.values():
        rep.raise_on_error()
    assert not reports["decode"].by_check("host_sync")
    assert reports["decode"].donation_coverage == 1.0
    assert reports["admit"].donation_coverage == 1.0


def test_audit_gate_not_vacuous(tiny_gpt):
    """Seeded regression: a host callback smuggled into the decode
    path must fail the gate."""
    import jax
    from paddle_tpu.analysis import AuditError
    eng = ServingEngine(_config(tiny_gpt, max_new=4, buckets=(16,),
                                max_batch=1), warmup=False)
    orig = eng._step_fn

    def poisoned(*args):
        out = orig(*args)
        leak = jax.pure_callback(
            lambda t: np.asarray(t), jax.ShapeDtypeStruct((1,), jnp.int32),
            out[0])
        return (out[0] + leak * 0,) + out[1:]

    eng._step_fn = poisoned
    with pytest.raises(AuditError):
        eng.audit()["decode"].raise_on_error()


def test_engine_forces_eval_at_trace_points():
    """A shared layer flipped to train mode by a fit() loop must not
    leak train-mode tracing into the served programs: deferred
    warmup(), lazy compiles, and audit() all force eval first (the
    GenerationSession._ensure_eval contract — a train-mode trace bakes
    active dropout in, or closes over extra RNG inputs and breaks the
    compiled call signature)."""
    paddle.seed(0)
    m = gpt("test-tiny", dropout=0.5)
    eng = ServingEngine(_config(m, max_new=4, buckets=(16,),
                                max_batch=1), warmup=False)
    m.train()                         # what every fit() batch does
    eng.audit()["decode"].raise_on_error()
    assert not m.training
    m.train()
    out = eng.submit([1, 2, 3]).result(timeout=60)  # lazy compile here
    assert out.size == 4 and not m.training


# -------------------------------------------------------- precision paths


def test_bf16_precision_path(tiny_gpt):
    """The engine serves the bf16 cast the Predictor audits: cast
    params, bf16 activations, bf16 KV cache — and still completes."""
    cfg = (Config().from_layer(tiny_gpt, _spec())
           .enable_tpu(precision=PrecisionType.Bfloat16)
           .enable_generation(max_new_tokens=4, prefill_buckets=(16,),
                              max_batch=1))
    eng = ServingEngine(cfg)
    assert eng._cache.dtype == jnp.bfloat16
    assert all(v.dtype == jnp.bfloat16 for v in eng._state
               if jnp.issubdtype(v.dtype, jnp.floating))
    out = eng.submit(np.arange(1, 7, dtype=np.int32)).result(timeout=60)
    assert out.shape == (4,)
    # the module-scope model must stay fp32 (the cast is serving-side)
    assert all(
        jnp.issubdtype(t._data.dtype, jnp.floating) is False
        or t._data.dtype == jnp.float32
        for t in tiny_gpt.state_dict().values())


def test_int8_weight_only_path(tiny_gpt):
    """int8 weight-only serving: quantized Linear weights + in-trace
    dequant, engine end-to-end."""
    cfg = (Config().from_layer(tiny_gpt, _spec())
           .enable_tpu(precision=PrecisionType.Int8)
           .enable_generation(max_new_tokens=4, prefill_buckets=(16,),
                              max_batch=1))
    eng = ServingEngine(cfg)
    assert eng._sp.scales              # something actually quantized
    assert any(v.dtype == jnp.int8 for v in eng._state)
    out = eng.submit(np.arange(1, 7, dtype=np.int32)).result(timeout=60)
    assert out.shape == (4,)


# ----------------------------------------------------------- thread mode


def test_thread_mode_and_shutdown(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, max_new=4, buckets=(16,),
                                max_batch=1), poll_every=1)
    eng.start()
    try:
        hs = [eng.submit(np.arange(1, 4 + i, dtype=np.int32))
              for i in range(3)]
        outs = [h.result(timeout=60) for h in hs]
        assert all(o.size == 4 for o in outs)
    finally:
        eng.shutdown()
    assert eng._thread is None
    with pytest.raises(RuntimeError, match="shut down"):
        eng.submit([1, 2])
    eng.shutdown()                    # idempotent


def test_drain_completes_rows_finished_since_last_poll(tiny_gpt):
    """A row whose decode finished between the last cadence poll and
    the drain cutoff must drain as COMPLETED, not CANCELLED: drain runs
    one final poll before declaring stragglers."""
    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                max_batch=1, drain_timeout_s=0.0),
                        poll_every=4)
    h = eng.submit([1, 2, 3], RequestParams(max_new_tokens=2))
    eng.step()   # admit + 1 decode step: budget reached, but the poll
    #              cadence (4) has not come around yet
    eng.drain()  # zero drain window: only the final poll can save it
    assert h.status is RequestStatus.COMPLETED
    assert h.result().size == 2


def test_admission_failure_never_hangs_the_handle(tiny_gpt):
    """A request popped from the queue whose admission raises (device
    error mid-prefill) must still reach a terminal status — its Future
    can never hang — and the engine keeps serving later requests."""
    eng = ServingEngine(_config(tiny_gpt, max_new=4, buckets=(16,),
                                max_batch=1), poll_every=1)
    orig = eng._exe_prefill
    calls = {"n": 0}

    def flaky(bucket):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected device failure")
        return orig(bucket)

    eng._exe_prefill = flaky
    doomed = eng.submit([1, 2, 3])
    ok = eng.submit([4, 5])
    eng.step()
    assert doomed.done()
    assert doomed.status is RequestStatus.CANCELLED
    assert "admission error" in doomed.detail
    with pytest.raises(RequestFailed, match="injected device failure"):
        doomed.result(timeout=5)
    assert ok.result(timeout=60).size == 4   # engine kept serving


def test_drain_with_no_traffic_is_clean(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, max_new=4, buckets=(16,),
                                max_batch=1), warmup=False)
    eng.drain()
    with pytest.raises(RuntimeError, match="shut down"):
        eng.submit([1, 2])


# ----------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_graceful_shutdown_drains_serving(tiny_gpt):
    """SIGTERM mid-serve_forever: in-flight requests drain to a
    terminal status (here: complete within the drain window), queued
    requests get a clean rejection, nothing hangs, and the engine
    accepts no new work afterwards."""
    import signal
    from paddle_tpu.distributed.resilience import GracefulShutdown
    from paddle_tpu.utils.fault_injection import KillAfter

    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                max_batch=2, max_queue=8,
                                drain_timeout_s=60.0), poll_every=2)
    rng = np.random.RandomState(1)
    traffic = [rng.randint(0, 512, 4 + i).astype(np.int32)
               for i in range(5)]
    killer = KillAfter(4, signal.SIGTERM)
    with GracefulShutdown(exit_on_save=False) as gs:
        handles = eng.serve_forever(
            iter(traffic), on_step=lambda e: killer.step())
        assert gs.preempted
    assert killer.fired
    assert len(handles) == 5
    assert all(h.done() for h in handles), "a request hung"
    assert all(h.status.terminal for h in handles)
    completed = [h for h in handles
                 if h.status is RequestStatus.COMPLETED]
    rejected = [h for h in handles
                if h.status is RequestStatus.REJECTED]
    assert completed and all(h.tokens.size == 8 for h in completed)
    assert rejected and all(h.detail == "shutdown" for h in rejected)
    with pytest.raises(RuntimeError, match="shut down"):
        eng.submit(traffic[0])


@pytest.mark.chaos
def test_drain_timeout_cancels_stragglers(tiny_gpt):
    """A drain window too short for the in-flight budget cancels the
    stragglers with a shutdown status instead of hanging."""
    eng = ServingEngine(_config(tiny_gpt, max_new=8, buckets=(16,),
                                max_batch=1, drain_timeout_s=0.0),
                        poll_every=1)
    h = eng.submit([1, 2, 3])
    eng.step()                       # admitted, 7 tokens to go
    eng.drain()
    assert h.done()
    assert h.status is RequestStatus.CANCELLED
    assert h.detail == "shutdown"
    assert h.tokens is not None and 1 <= h.tokens.size < 8
