"""Generation subsystem tests: KV cache, decode flash kernel, sampling,
prefill/decode parity against the full forward (the tier-1 acceptance
gate), the exactly-2-compiles retrace contract, ragged batches, the
Predictor serving mode, and the gen.* metrics family.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.generation import (GenerationConfig, GenerationSession,
                                   KVCache, generate, sample)
from paddle_tpu.models.gpt import gpt

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompt_ids():
    return np.random.RandomState(0).randint(
        0, 512, (2, 12)).astype(np.int32)


# ------------------------------------------------------------ KV cache


def test_kv_cache_create_update_advance():
    c = KVCache.create(2, 3, 16, 4, 8, dtype=jnp.float32)
    assert c.num_layers == 2 and c.batch == 3 and c.max_len == 16
    assert c.kv_len.shape == (3,) and int(c.kv_len.sum()) == 0
    k = np.arange(3 * 2 * 4 * 8, dtype=np.float32).reshape(3, 2, 4, 8)
    c2 = c.update(1, k, k + 1.0, c.kv_len)       # prefill write at 0
    # layer 0 untouched, layer 1 holds the new rows at positions 0..1
    assert float(jnp.abs(c2.k[0]).max()) == 0.0
    np.testing.assert_array_equal(np.asarray(c2.k[1][:, :2]), k)
    np.testing.assert_array_equal(np.asarray(c2.v[1][:, :2]), k + 1.0)
    # kv_len does NOT advance in update; with_kv_len does
    assert int(c2.kv_len.sum()) == 0
    c3 = c2.with_kv_len(2)
    np.testing.assert_array_equal(np.asarray(c3.kv_len), [2, 2, 2])


def test_kv_cache_per_row_positions_and_ring_wrap():
    c = KVCache.create(1, 2, 8, 1, 4).with_kv_len(np.array([3, 7]))
    new = np.ones((2, 2, 1, 4), np.float32)
    c2 = c.update(0, new, new, c.kv_len)
    # row 0 wrote at 3..4; row 1 at 7 wraps to [7, 0] (ring)
    got = np.asarray(c2.k[0][:, :, 0, 0])
    np.testing.assert_array_equal(got[0], [0, 0, 0, 1, 1, 0, 0, 0])
    np.testing.assert_array_equal(got[1], [1, 0, 0, 0, 0, 0, 0, 1])


def test_kv_cache_is_a_pytree():
    c = KVCache.create(1, 1, 8, 2, 4).with_kv_len(5)
    leaves, treedef = jax.tree_util.tree_flatten(c)
    assert len(leaves) == 3
    c2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(c2, KVCache) and c2.max_len == 8
    doubled = jax.tree_util.tree_map(lambda x: x, c)
    assert isinstance(doubled, KVCache)
    assert c.occupancy() == 5 / 8


def test_kv_cache_reset_rows_and_ring_reuse():
    """Slot-reuse helper: reset_rows zeroes kv_len (index, int array,
    or bool mask) without touching K/V or the pytree, and a reused row
    writes from position 0 again instead of wrapping."""
    c = KVCache.create(1, 3, 8, 1, 4).with_kv_len(np.array([3, 7, 5]))
    ones = np.ones((3, 2, 1, 4), np.float32)
    c = c.update(0, ones, ones, c.kv_len)

    r = c.reset_rows(1)                                   # scalar index
    np.testing.assert_array_equal(np.asarray(r.kv_len), [3, 0, 5])
    r2 = c.reset_rows(np.array([0, 2]))                   # int array
    np.testing.assert_array_equal(np.asarray(r2.kv_len), [0, 7, 0])
    r3 = c.reset_rows(np.array([True, False, True]))      # bool mask
    np.testing.assert_array_equal(np.asarray(r3.kv_len), [0, 7, 0])
    # K/V untouched, pytree structure unchanged
    np.testing.assert_array_equal(np.asarray(r.k), np.asarray(c.k))
    assert len(jax.tree_util.tree_leaves(r)) == 3

    # reuse: the reset row's next write starts at 0 (no wrap); before
    # the reset, row 1 at kv_len 7 would have wrapped to [7, 0]
    new = np.full((3, 2, 1, 4), 2.0, np.float32)
    w = r.update(0, new, new, r.kv_len)
    got = np.asarray(w.k[0][1, :, 0, 0])
    np.testing.assert_array_equal(got, [2, 2, 0, 0, 0, 0, 0, 1])

    # donation-compatible: reset inside jit with the cache donated
    reset = jax.jit(lambda cc, rows: cc.reset_rows(rows),
                    donate_argnums=() if jax.default_backend() != "tpu"
                    else (0,))
    d = reset(c, jnp.asarray(1, jnp.int32))
    assert isinstance(d, KVCache)
    np.testing.assert_array_equal(np.asarray(d.kv_len), [3, 0, 5])


def test_kv_cache_copy_row_from_slot_admission():
    """copy_row_from installs a batch-1 prefill row into one slot of a
    shared cache (K, V, kv_len), leaving other rows alone; traced slot
    indices compile to ONE program for every slot."""
    shared = KVCache.create(2, 3, 8, 1, 4).with_kv_len(
        np.array([2, 6, 4]))
    src = KVCache.create(2, 1, 8, 1, 4)
    fill = np.arange(1 * 5 * 1 * 4, dtype=np.float32).reshape(1, 5, 1, 4)
    for layer in range(2):
        src = src.update(layer, fill, fill + 10.0, src.kv_len)
    src = src.with_kv_len(5)

    admit = jax.jit(lambda dst, s, slot: dst.copy_row_from(s, 0, slot))
    out = admit(shared, src, jnp.asarray(1, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out.kv_len), [2, 5, 4])
    np.testing.assert_array_equal(np.asarray(out.k[:, 1]),
                                  np.asarray(src.k[:, 0]))
    np.testing.assert_array_equal(np.asarray(out.v[:, 1]),
                                  np.asarray(src.v[:, 0]))
    # untouched rows stay zero
    assert float(jnp.abs(out.k[:, 0]).max()) == 0.0
    # same compiled program serves a different slot (traced index)
    out2 = admit(shared, src, jnp.asarray(2, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out2.kv_len), [2, 6, 5])
    np.testing.assert_array_equal(np.asarray(out2.k[:, 2]),
                                  np.asarray(src.k[:, 0]))


# ------------------------------------------------------- decode kernel


def _naive_decode(q, kc, vc, kv_len):
    b, sq, h, d = q.shape
    t = kc.shape[1]
    scale = 1.0 / np.sqrt(d)
    out = np.zeros_like(q, dtype=np.float32)
    for bi in range(b):
        for hi in range(h):
            s = (q[bi, :, hi] @ kc[bi, :, hi].T) * scale
            for i in range(sq):
                lim = kv_len[bi] - sq + i
                mask = np.arange(t) <= lim
                e = np.exp(s[i] - s[i][mask].max()) * mask
                out[bi, i, hi] = (e / e.sum()) @ vc[bi, :, hi]
    return out


@pytest.mark.parametrize("sq", [1, 4, 8])
def test_flash_attention_decode_parity(sq):
    from paddle_tpu.kernels.flash_attention import flash_attention_decode
    rng = np.random.RandomState(1)
    b, h, d, t = 3, 4, 64, 256
    kv = np.array([sq, sq + 9, t], np.int32)
    q = rng.randn(b, sq, h, d).astype(np.float32)
    kc = rng.randn(b, t, h, d).astype(np.float32)
    vc = rng.randn(b, t, h, d).astype(np.float32)
    out = np.asarray(flash_attention_decode(q, kc, vc, kv))
    np.testing.assert_allclose(out, _naive_decode(q, kc, vc, kv),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_decode_pallas_interpret_parity():
    """The Pallas decode kernel itself (interpret mode on CPU) against
    the same reference — per-row kv_len masking and block skipping."""
    from paddle_tpu.kernels.flash_attention import _decode_pallas
    rng = np.random.RandomState(2)
    b, h, d, t, sq = 2, 2, 64, 256, 3
    kv = np.array([5, 250], np.int32)
    q = rng.randn(b, sq, h, d).astype(np.float32)
    kc = rng.randn(b, t, h, d).astype(np.float32)
    vc = rng.randn(b, t, h, d).astype(np.float32)
    qt = jnp.swapaxes(jnp.asarray(q), 1, 2).reshape(b * h, sq, d)
    kt = jnp.swapaxes(jnp.asarray(kc), 1, 2).reshape(b * h, t, d)
    vt = jnp.swapaxes(jnp.asarray(vc), 1, 2).reshape(b * h, t, d)
    out = _decode_pallas(qt, kt, vt, jnp.repeat(jnp.asarray(kv), h),
                         1.0 / np.sqrt(d), block_k=128)
    out = np.asarray(jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2))
    np.testing.assert_allclose(out, _naive_decode(q, kc, vc, kv),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sq,hq,hk", [(1, 4, 2), (4, 4, 2), (1, 8, 1)])
def test_flash_attention_decode_gqa(sq, hq, hk):
    """GQA/MQA decode (hk < hq) via head-index mapping: parity against
    the naive reference with explicitly repeated caches — the kernel
    path itself never materializes the repeat."""
    from paddle_tpu.kernels.flash_attention import flash_attention_decode
    rng = np.random.RandomState(3)
    b, d, t = 2, 64, 128
    kv = np.array([7 + sq, 60], np.int32)
    q = rng.randn(b, sq, hq, d).astype(np.float32)
    kc = rng.randn(b, t, hk, d).astype(np.float32)
    vc = rng.randn(b, t, hk, d).astype(np.float32)
    out = np.asarray(flash_attention_decode(q, kc, vc, kv))
    ref = _naive_decode(q, np.repeat(kc, hq // hk, 2),
                        np.repeat(vc, hq // hk, 2), kv)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_attention_decode_gqa_pallas_interpret_parity():
    """The Pallas kernel's GQA head-index mapping (k/v BlockSpec index
    maps reading cache row b // group) in interpret mode: grid row i
    must attend kv head i//group's cache, with that head's kv_len."""
    from paddle_tpu.kernels.flash_attention import _decode_pallas
    rng = np.random.RandomState(4)
    b, hq, hk, d, t, sq = 2, 4, 2, 64, 256, 3
    group = hq // hk
    kv = np.array([5 + sq, 250], np.int32)
    q = rng.randn(b, sq, hq, d).astype(np.float32)
    kc = rng.randn(b, t, hk, d).astype(np.float32)
    vc = rng.randn(b, t, hk, d).astype(np.float32)
    qt = jnp.swapaxes(jnp.asarray(q), 1, 2).reshape(b * hq, sq, d)
    kt = jnp.swapaxes(jnp.asarray(kc), 1, 2).reshape(b * hk, t, d)
    vt = jnp.swapaxes(jnp.asarray(vc), 1, 2).reshape(b * hk, t, d)
    out = _decode_pallas(qt, kt, vt, jnp.repeat(jnp.asarray(kv), hk),
                         1.0 / np.sqrt(d), block_k=128, group=group)
    out = np.asarray(jnp.swapaxes(out.reshape(b, hq, sq, d), 1, 2))
    ref = _naive_decode(q, np.repeat(kc, group, 2),
                        np.repeat(vc, group, 2), kv)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_flash_attention_decode_qlen_cap():
    from paddle_tpu.kernels.flash_attention import flash_attention_decode
    z = np.zeros((1, 9, 2, 64), np.float32)
    c = np.zeros((1, 128, 2, 64), np.float32)
    with pytest.raises(ValueError, match="q_len"):
        flash_attention_decode(z, c, c, np.array([9], np.int32))


# ------------------------------------------------------------ sampling


def test_sample_greedy_is_argmax():
    logits = jnp.asarray(np.random.RandomState(0).randn(4, 50))
    tok = sample(logits)
    np.testing.assert_array_equal(np.asarray(tok),
                                  np.argmax(np.asarray(logits), -1))
    # temperature 0 forces greedy even with do_sample
    tok2 = sample(logits, jax.random.PRNGKey(0), do_sample=True,
                  temperature=0.0)
    np.testing.assert_array_equal(np.asarray(tok2), np.asarray(tok))


def test_sample_top_k_support():
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(2, 40) * 3)
    topk = set(np.argsort(-np.asarray(logits), -1)[:, :5].flatten()
               .tolist())
    for i in range(30):
        tok = sample(logits, jax.random.PRNGKey(i), do_sample=True,
                     top_k=5)
        row_top = np.argsort(-np.asarray(logits), -1)[:, :5]
        for r in range(2):
            assert int(np.asarray(tok)[r]) in row_top[r]


def test_sample_top_p_support():
    # peaked distribution: nucleus at p=0.5 is a small set
    logits_np = np.full((1, 20), -10.0, np.float32)
    logits_np[0, :3] = [5.0, 4.0, 3.0]
    logits = jnp.asarray(logits_np)
    probs = np.exp(logits_np[0] - logits_np[0].max())
    probs /= probs.sum()
    order = np.argsort(-probs)
    keep = order[: np.searchsorted(np.cumsum(probs[order]), 0.5) + 1]
    for i in range(30):
        tok = int(np.asarray(sample(logits, jax.random.PRNGKey(i),
                                    do_sample=True, top_p=0.5))[0])
        assert tok in keep


def test_sample_top_p_zero_is_greedy():
    # top_p <= 0 must degrade to greedy (the top token always
    # survives), never to an all-masked row sampled uniformly
    logits = jnp.asarray(np.random.RandomState(3).randn(4, 30))
    want = np.argmax(np.asarray(logits), -1)
    for i in range(5):
        tok = sample(logits, jax.random.PRNGKey(i), do_sample=True,
                     top_p=0.0)
        np.testing.assert_array_equal(np.asarray(tok), want)


def test_sample_deterministic_per_key():
    logits = jnp.asarray(np.random.RandomState(2).randn(3, 30))
    a = sample(logits, jax.random.PRNGKey(7), do_sample=True,
               temperature=1.3, top_k=10, top_p=0.9)
    b = sample(logits, jax.random.PRNGKey(7), do_sample=True,
               temperature=1.3, top_k=10, top_p=0.9)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="PRNG key"):
        sample(logits, None, do_sample=True)


# ------------------------------------- prefill/decode parity (tier-1)


def test_prefill_then_decode_matches_full_forward(tiny_gpt, prompt_ids):
    """The acceptance gate: prefill over the first 8 tokens then 4
    decode steps (feeding the golden next tokens) must reproduce the
    full-forward logits at every position within fp32 tolerance."""
    m, ids = tiny_gpt, prompt_ids
    full = m(paddle.to_tensor(ids)).numpy()          # [2, 12, 512]
    with paddle.no_grad():
        logits, cache = m(paddle.to_tensor(ids[:, :8]), use_cache=True,
                          cache_max_len=128)
        np.testing.assert_allclose(np.asarray(logits.numpy())[:, 0],
                                   full[:, 7], rtol=2e-4, atol=2e-4)
        for t in range(8, 12):
            logits, cache = m(paddle.to_tensor(ids[:, t:t + 1]),
                              cache=cache)
            np.testing.assert_allclose(np.asarray(logits.numpy())[:, 0],
                                       full[:, t], rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(cache.kv_len), [12, 12])


def test_multi_token_decode_window(tiny_gpt, prompt_ids):
    """Decode with a q-len-4 window (the speculative-verify shape) in
    one call matches four single-token steps."""
    m, ids = tiny_gpt, prompt_ids
    full = m(paddle.to_tensor(ids)).numpy()
    with paddle.no_grad():
        _, cache = m(paddle.to_tensor(ids[:, :8]), use_cache=True,
                     cache_max_len=128)
        logits, cache = m(paddle.to_tensor(ids[:, 8:12]), cache=cache)
    got = np.asarray(logits.numpy())                 # [2, 4, 512]
    np.testing.assert_allclose(got, full[:, 8:12], rtol=2e-4, atol=2e-4)


def test_generate_exactly_two_compiles(prompt_ids):
    """One prefill compile + one decode compile for the whole call, and
    repeated calls with the same shapes add zero."""
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    paddle.seed(1)
    m = gpt("test-tiny")

    def count(name):
        snap = metrics.snapshot().get(name)
        return int(snap["value"]) if snap else 0

    monitor.enable()
    try:
        t0 = count("jit.compile.total")
        s0 = count("jit.compile{cause=new_shape}")
        m.generate(prompt_ids, max_new_tokens=6)
        assert count("jit.compile.total") - t0 == 2
        assert count("jit.compile{cause=new_shape}") - s0 == 0
        m.generate(prompt_ids, max_new_tokens=6)   # warm: no new compile
        assert count("jit.compile.total") - t0 == 2
        assert count("gen.prefill_steps") >= 2
        assert count("gen.decode_steps") >= 10
        assert count("gen.tokens") >= 24
        occ = metrics.snapshot().get("gen.cache_occupancy")
        assert occ and 0.0 < occ["value"] <= 1.0
    finally:
        monitor.disable()


# ----------------------------------------------------------- generate


def test_generate_greedy_deterministic(tiny_gpt, prompt_ids):
    a = np.asarray(tiny_gpt.generate(prompt_ids, max_new_tokens=6)._data)
    b = np.asarray(tiny_gpt.generate(prompt_ids, max_new_tokens=6)._data)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 6) and a.dtype == np.int32
    # greedy continuation parity: feeding the generated prefix back
    # through the full forward reproduces the same argmax choices
    ext = np.concatenate([prompt_ids, a[:, :3]], axis=1)
    logits = tiny_gpt(paddle.to_tensor(ext)).numpy()
    np.testing.assert_array_equal(np.argmax(logits[:, -1], -1), a[:, 3])


def test_generate_sampling_seeded(tiny_gpt, prompt_ids):
    a = np.asarray(tiny_gpt.generate(prompt_ids, max_new_tokens=6,
                                     do_sample=True, temperature=1.5,
                                     top_k=50, seed=11)._data)
    b = np.asarray(tiny_gpt.generate(prompt_ids, max_new_tokens=6,
                                     do_sample=True, temperature=1.5,
                                     top_k=50, seed=11)._data)
    c = np.asarray(tiny_gpt.generate(prompt_ids, max_new_tokens=6,
                                     do_sample=True, temperature=1.5,
                                     top_k=50, seed=12)._data)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)  # different seed, different draw


def test_generate_eos_pads_tail(tiny_gpt, prompt_ids):
    # pick the greedy token at step 2 as eos: everything after a row's
    # first eos must be pad_token_id
    base = np.asarray(tiny_gpt.generate(prompt_ids, max_new_tokens=6)._data)
    eos = int(base[0, 1])
    out = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=6, eos_token_id=eos,
        pad_token_id=499)._data)
    row = out[0]
    first = int(np.nonzero(row == eos)[0][0])
    assert (row[first + 1:] == 499).all()


def test_generate_ragged_rows_match_solo(tiny_gpt, prompt_ids):
    ids = prompt_ids
    ragged = np.asarray(tiny_gpt.generate(
        ids, max_new_tokens=4, prompt_len=[5, 12],
        cache_max_len=128)._data)
    solo0 = np.asarray(tiny_gpt.generate(
        ids[:1, :5], max_new_tokens=4, cache_max_len=128)._data)
    solo1 = np.asarray(tiny_gpt.generate(
        ids[1:, :12], max_new_tokens=4, cache_max_len=128)._data)
    np.testing.assert_array_equal(ragged[0], solo0[0])
    np.testing.assert_array_equal(ragged[1], solo1[0])


def test_generate_rejects_out_of_range_positions(tiny_gpt, prompt_ids):
    """Satellite bugfix: past max_position_embeddings (128 on
    test-tiny) generate() must raise up front, not silently gather a
    clipped position embedding."""
    with pytest.raises(ValueError, match="max_position_embeddings"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=117)
    # boundary: prompt 12 + 116 == 128 is allowed
    tiny_gpt.generate(prompt_ids[:, :4], max_new_tokens=124,
                      eos_token_id=None, cache_max_len=128)
    with pytest.raises(ValueError, match="max_new_tokens"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=0)
    with pytest.raises(ValueError, match="prompt_len"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=2,
                          prompt_len=[13, 5])
    with pytest.raises(ValueError, match="cache_max_len"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=8, cache_max_len=16)


def test_generate_unseeded_sampling_draws_fresh_entropy(tiny_gpt,
                                                        prompt_ids):
    """seed=None must not replay one fixed key stream: repeated calls
    differ, while paddle.seed pins the whole sequence."""
    kw = dict(max_new_tokens=6, do_sample=True, temperature=1.5,
              top_k=50)
    paddle.seed(21)
    a = np.asarray(tiny_gpt.generate(prompt_ids, **kw)._data)
    b = np.asarray(tiny_gpt.generate(prompt_ids, **kw)._data)
    assert not np.array_equal(a, b)  # fresh draw per call
    paddle.seed(21)
    a2 = np.asarray(tiny_gpt.generate(prompt_ids, **kw)._data)
    b2 = np.asarray(tiny_gpt.generate(prompt_ids, **kw)._data)
    np.testing.assert_array_equal(a, a2)
    np.testing.assert_array_equal(b, b2)


def test_gen_tokens_metric_counts_real_tokens_only(prompt_ids):
    """gen.tokens stops at each row's first eos and ignores padding
    rows (live_rows) — it reports real throughput, not dispatch*batch."""
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    paddle.seed(1)
    m = gpt("test-tiny")
    base = np.asarray(m.generate(prompt_ids, max_new_tokens=6)._data)
    eos = int(base[0, 1])  # row 0 hits eos at step 2

    def count():
        snap = metrics.snapshot().get("gen.tokens")
        return int(snap["value"]) if snap else 0

    monitor.enable()
    try:
        t0 = count()
        out = np.asarray(m.generate(
            prompt_ids, max_new_tokens=6, eos_token_id=eos,
            pad_token_id=499)._data)
        # expected: per row, tokens up to and including first eos
        want = 0
        for row in out:
            hits = np.nonzero(row == eos)[0]
            want += int(hits[0]) + 1 if hits.size else 6
        assert count() - t0 == want < 12
        t1 = count()
        m.generate(prompt_ids, max_new_tokens=4, live_rows=1)
        assert count() - t1 == 4  # only the live row counted
    finally:
        monitor.disable()


def test_generate_rejects_encoder_network():
    """An encoder-protocol cached forward (3-tuple) fails with a clear
    TypeError, not an opaque unpack error inside the trace."""
    from paddle_tpu.models.ernie import ernie
    paddle.seed(0)
    m = ernie("test-tiny")
    ids = np.random.RandomState(0).randint(0, 512, (1, 6)) \
        .astype(np.int32)
    with pytest.raises(TypeError, match="logits, cache"):
        generate(m.ernie, ids, max_new_tokens=2)


def test_generate_forces_eval_on_retrace(prompt_ids):
    """A cached session must not bake train-mode dropout into a
    retrace: generate() on a train-mode network (e.g. mid-fit callback)
    with a NEW prompt shape matches the eval-mode output."""
    paddle.seed(3)
    m = gpt("test-tiny", dropout=0.5)
    ref = np.asarray(m.generate(prompt_ids, max_new_tokens=4)._data)
    m.train()                       # fit() flips this back every batch
    got = np.asarray(m.generate(prompt_ids, max_new_tokens=4)._data)
    np.testing.assert_array_equal(got, ref)
    m.train()
    short = np.asarray(                      # new shape => fresh trace
        m.generate(prompt_ids[:, :6], max_new_tokens=4)._data)
    m.eval()
    ref_short = np.asarray(
        m.generate(prompt_ids[:, :6], max_new_tokens=4)._data)
    np.testing.assert_array_equal(short, ref_short)


def test_generate_via_hapi_model(prompt_ids):
    from paddle_tpu.hapi.model import Model
    paddle.seed(0)
    net = gpt("test-tiny")
    out = Model(net).generate(prompt_ids, max_new_tokens=3)
    assert tuple(out.shape) == (2, 3)


# -------------------------------------------------------------- ernie


def test_ernie_incremental_encoding_consistency():
    """Prefill + one 4-token append equals prefill + four 1-token
    appends (the cache protocol on the bidirectional trunk)."""
    from paddle_tpu.models.ernie import ernie
    paddle.seed(0)
    m = ernie("test-tiny")
    m.eval()
    ids = np.random.RandomState(0).randint(0, 512, (2, 10)) \
        .astype(np.int32)
    with paddle.no_grad():
        _, _, c1 = m.ernie(paddle.to_tensor(ids[:, :6]), use_cache=True,
                           cache_max_len=128)
        h_block, _, c1 = m.ernie(paddle.to_tensor(ids[:, 6:10]),
                                 cache=c1)
        _, _, c2 = m.ernie(paddle.to_tensor(ids[:, :6]), use_cache=True,
                           cache_max_len=128)
        steps = []
        for t in range(6, 10):
            h, _, c2 = m.ernie(paddle.to_tensor(ids[:, t:t + 1]),
                               cache=c2)
            steps.append(np.asarray(h.numpy())[:, 0])
    got = np.stack(steps, axis=1)
    np.testing.assert_allclose(np.asarray(h_block.numpy()), got,
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(c2.kv_len), [10, 10])


def test_ernie_decode_pooled_is_none():
    """Decode windows don't contain CLS: pooled must be None on append
    calls (pooling x[:, 0] there would be a wrong sentence embedding),
    and present on prefill when the model has a pooler."""
    from paddle_tpu.models.ernie import ErnieConfig, ErnieModel
    paddle.seed(0)
    m = ErnieModel(ErnieConfig(
        vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        with_pooler=True))
    m.eval()
    ids = np.random.RandomState(1).randint(0, 128, (1, 6)) \
        .astype(np.int32)
    with paddle.no_grad():
        _, pooled, c = m(paddle.to_tensor(ids[:, :4]), use_cache=True,
                         cache_max_len=64)
        assert pooled is not None
        _, pooled2, _ = m(paddle.to_tensor(ids[:, 4:]), cache=c)
        assert pooled2 is None


# ---------------------------------------------------------- predictor


def test_predictor_generation_mode(prompt_ids):
    from paddle_tpu.core import monitor
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.profiler import metrics
    paddle.seed(0)
    m = gpt("test-tiny")
    cfg = Config().from_layer(
        m, input_spec=[paddle.to_tensor(prompt_ids)])
    cfg.enable_generation(max_new_tokens=6, prefill_buckets=(16, 32, 512),
                          max_batch=2, eos_token_id=None)
    pred = create_predictor(cfg)
    # buckets too large for max_position_embeddings=128 are dropped
    assert pred._gen_buckets == [16, 32]

    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 512, n).tolist() for n in (5, 12, 30)]
    monitor.enable()
    try:
        snap0 = metrics.snapshot().get("jit.compile.total")
        t0 = int(snap0["value"]) if snap0 else 0
        outs = pred.generate(prompts)
        snap1 = metrics.snapshot().get("jit.compile.total")
        t1 = int(snap1["value"]) if snap1 else 0
        # serving dispatches against the AOT pair only: zero retraces
        assert t1 - t0 == 0
    finally:
        monitor.disable()
    assert [o.shape for o in outs] == [(6,), (6,), (6,)]
    # parity with the Model-level greedy path on the same padded batch
    ref = np.asarray(generate(
        m, np.asarray(prompts[0], np.int32)[None, :],
        max_new_tokens=6, cache_max_len=128)._data)[0]
    np.testing.assert_array_equal(outs[0], ref)


def test_predictor_generation_errors(prompt_ids):
    from paddle_tpu.inference import Config, create_predictor
    paddle.seed(0)
    m = gpt("test-tiny")
    spec = [paddle.to_tensor(prompt_ids)]
    pred = create_predictor(Config().from_layer(m, spec))
    with pytest.raises(RuntimeError, match="generation mode"):
        pred.generate([[1, 2, 3]])
    cfg = Config().from_layer(m, spec)
    cfg.enable_generation(max_new_tokens=6, prefill_buckets=(16,),
                          max_batch=1)
    gp = create_predictor(cfg)
    with pytest.raises(ValueError, match="bucket"):
        gp.generate([list(range(17))])
    with pytest.raises(ValueError, match="max_new_tokens"):
        gp.generate([[1, 2]], max_new_tokens=60)
    with pytest.raises(ValueError, match="no prefill bucket"):
        bad = Config().from_layer(m, spec)
        bad.enable_generation(max_new_tokens=6, prefill_buckets=(512,))
        create_predictor(bad)


def test_kv_cache_sharding_spec_trims_to_mesh():
    from jax.sharding import Mesh
    import jax
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                ("dp", "mp"))
    c = KVCache.create(1, 2, 16, 4, 8, mesh=mesh)
    # placement succeeded on a mesh without the 'sharding' axis
    assert c.k.shape == (1, 2, 16, 4, 8)
    specs = c.k.sharding.spec
    assert specs[1] in (("dp",), "dp", None)
