"""Speculative decoding tests (ISSUE 11): the n-gram prompt-lookup
drafter, greedy + rejection-sampling acceptance, the session and engine
verify paths (greedy bitwise-equal to sequential decode — the
acceptance gate), the eos/budget/ring overshoot clamps at their exact
boundaries, the q-len guard, GQA verify-window kernel parity, the
gen.spec.* metrics family, audit gates, the Predictor bucket path, and
the chaos-tier drain.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.generation import (GenerationConfig, GenerationSession,
                                   SpeculativeConfig, generate,
                                   ngram_propose, spec_accept)
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.models.gpt import gpt
from paddle_tpu.serving import RequestParams, RequestStatus, ServingEngine

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


@pytest.fixture(scope="module")
def draft_gpt():
    paddle.seed(7)
    m = gpt("test-tiny-draft")
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompt_ids():
    return np.random.RandomState(0).randint(
        0, 512, (2, 12)).astype(np.int32)


def _counter(name):
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


# -------------------------------------------------------------- drafter


def test_ngram_propose_finds_most_recent_continuation():
    # row 0: suffix (7, 8) occurred twice; the MOST RECENT match (at 4)
    # must win, proposing its continuation 9, 1, 7
    buf = np.zeros((2, 16), np.int32)
    buf[0, :10] = [7, 8, 3, 5, 7, 8, 9, 1, 7, 8]
    # row 1: suffix (5, 6) never occurred earlier -> repeat last token
    buf[1, :6] = [1, 2, 3, 4, 5, 6]
    out = np.asarray(ngram_propose(jnp.asarray(buf),
                                   jnp.asarray([10, 6], np.int32),
                                   k=3, n=2))
    np.testing.assert_array_equal(out[0], [9, 1, 7])
    np.testing.assert_array_equal(out[1], [6, 6, 6])


def test_ngram_propose_clamps_continuation_to_known_tokens():
    # the match continuation runs off the valid region: missing slots
    # fall back to the last token, never read padding garbage
    buf = np.full((1, 12), 99, np.int32)
    buf[0, :7] = [4, 5, 1, 2, 4, 5, 1]
    out = np.asarray(ngram_propose(jnp.asarray(buf),
                                   jnp.asarray([7], np.int32),
                                   k=4, n=2))
    # match at 0 (suffix 4,5 at 4..5 -> wait: suffix is buf[5:7]=(5,1);
    # its earlier occurrence is at 1..2, continuation 2, 4, 5, then the
    # clamp repeats the last known token (1), never 99
    np.testing.assert_array_equal(out[0], [2, 4, 5, 1])
    assert 99 not in out


def test_ngram_propose_short_history_falls_back():
    buf = np.zeros((1, 8), np.int32)
    buf[0, :2] = [3, 4]
    out = np.asarray(ngram_propose(jnp.asarray(buf),
                                   jnp.asarray([2], np.int32),
                                   k=2, n=3))
    np.testing.assert_array_equal(out[0], [4, 4])


# ----------------------------------------------------------- acceptance


def test_spec_accept_greedy_prefix_and_correction():
    # vocab 6; target argmax per position: [2, 3, 4] (k=2, window 3)
    logits = np.full((1, 3, 6), -5.0, np.float32)
    logits[0, 0, 2] = 5.0
    logits[0, 1, 3] = 5.0
    logits[0, 2, 4] = 5.0
    cfg = GenerationConfig()
    # draft [2, 3]: both match -> n_accept 2, bonus token 4 at index 2
    emitted, n = spec_accept(jnp.asarray(logits),
                             jnp.asarray([[2, 3]], np.int32),
                             jax.random.PRNGKey(0), cfg)
    assert int(n[0]) == 2
    np.testing.assert_array_equal(np.asarray(emitted)[0], [2, 3, 4])
    # draft [2, 9]: mismatch at index 1 -> accept 1, correction 3 there
    emitted, n = spec_accept(jnp.asarray(logits),
                             jnp.asarray([[2, 9]], np.int32),
                             jax.random.PRNGKey(0), cfg)
    assert int(n[0]) == 1
    np.testing.assert_array_equal(np.asarray(emitted)[0, :2], [2, 3])
    # draft [9, 9]: immediate mismatch -> accept 0, correction 2 first
    emitted, n = spec_accept(jnp.asarray(logits),
                             jnp.asarray([[9, 9]], np.int32),
                             jax.random.PRNGKey(0), cfg)
    assert int(n[0]) == 0
    assert int(np.asarray(emitted)[0, 0]) == 2


def test_spec_accept_rejection_matches_target_distribution():
    """The distributional satellite: with a deterministic (point-mass)
    drafter, accept-with-prob-p(d) + residual resampling must emit the
    FIRST token exactly from the target distribution — empirically,
    over many keys, against the analytic softmax."""
    probs = np.array([0.45, 0.25, 0.15, 0.10, 0.05], np.float64)
    logits = np.log(probs)[None, None, :].repeat(2, axis=1)  # [1, 2, 5]
    draft = jnp.asarray([[0]], np.int32)       # draft the likeliest token
    cfg = GenerationConfig(do_sample=True, temperature=1.0)
    n_trials = 800
    counts = np.zeros(5)
    for i in range(n_trials):
        emitted, _ = spec_accept(jnp.asarray(logits, jnp.float32), draft,
                                 jax.random.PRNGKey(i), cfg)
        counts[int(np.asarray(emitted)[0, 0])] += 1
    emp = counts / n_trials
    tv = 0.5 * np.abs(emp - probs).sum()
    assert tv < 0.1, f"total variation {tv:.3f}: emp={emp} vs {probs}"


def test_spec_accept_temperature_filters_apply():
    # top_k=1 collapses the filtered distribution to argmax: rejection
    # sampling must then behave exactly greedily for any key
    logits = np.zeros((1, 2, 8), np.float32)
    logits[0, 0, 3] = 4.0
    logits[0, 1, 5] = 4.0
    cfg = GenerationConfig(do_sample=True, temperature=1.7, top_k=1)
    for i in range(10):
        emitted, n = spec_accept(jnp.asarray(logits),
                                 jnp.asarray([[3]], np.int32),
                                 jax.random.PRNGKey(i), cfg)
        assert int(n[0]) == 1
        np.testing.assert_array_equal(np.asarray(emitted)[0], [3, 5])


# ------------------------------------------------------------ config


def test_spec_config_validation():
    from paddle_tpu.kernels.flash_attention import MAX_DECODE_QLEN
    with pytest.raises(ValueError, match="mode"):
        SpeculativeConfig(mode="telepathy")
    with pytest.raises(ValueError, match="draft_k"):
        SpeculativeConfig(k=0)
    # the q-len guard at the API boundary, naming the kernel limit
    with pytest.raises(ValueError, match="MAX_DECODE_QLEN"):
        SpeculativeConfig(k=MAX_DECODE_QLEN)
    SpeculativeConfig(k=MAX_DECODE_QLEN - 1)     # boundary: window == 8
    with pytest.raises(ValueError, match="ngram"):
        SpeculativeConfig(ngram=0)


def test_spec_mode_model_crosschecks(tiny_gpt, draft_gpt, prompt_ids):
    with pytest.raises(ValueError, match="draft_model"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=4,
                          speculative="draft")
    with pytest.raises(ValueError, match="ngram"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=4,
                          speculative="ngram", draft_model=draft_gpt)
    with pytest.raises(TypeError, match="SpeculativeConfig"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=4, speculative=3)


# --------------------------------------------- session greedy parity


@pytest.mark.parametrize("k", [1, 4])
def test_generate_ngram_greedy_bitwise(tiny_gpt, prompt_ids, k):
    """THE acceptance gate (session path): greedy speculative output is
    bitwise-equal to sequential decode, eos padding included."""
    ref = np.asarray(tiny_gpt.generate(prompt_ids,
                                       max_new_tokens=16)._data)
    out = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=16,
        speculative=SpeculativeConfig(k=k))._data)
    np.testing.assert_array_equal(out, ref)
    eos = int(ref[0, 3])
    ref_e = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=16, eos_token_id=eos,
        pad_token_id=499)._data)
    out_e = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=16, eos_token_id=eos,
        pad_token_id=499, speculative=SpeculativeConfig(k=k))._data)
    np.testing.assert_array_equal(out_e, ref_e)


def test_generate_ngram_ragged_rows_bitwise(tiny_gpt, prompt_ids):
    ref = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=8, prompt_len=[5, 12],
        cache_max_len=128)._data)
    out = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=8, prompt_len=[5, 12],
        cache_max_len=128, speculative="ngram")._data)
    np.testing.assert_array_equal(out, ref)


def test_generate_draft_model_greedy_bitwise(tiny_gpt, draft_gpt,
                                             prompt_ids):
    """Draft-model path: an arbitrary (even useless) draft model never
    changes greedy output — and a perfect drafter (the target itself)
    accepts everything while still matching bitwise."""
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    ref = np.asarray(tiny_gpt.generate(prompt_ids,
                                       max_new_tokens=12)._data)
    out = np.asarray(tiny_gpt.generate(
        prompt_ids, max_new_tokens=12, speculative="draft",
        draft_model=draft_gpt)._data)
    np.testing.assert_array_equal(out, ref)
    monitor.enable()
    try:
        p0, a0 = _counter("gen.spec.proposed"), _counter("gen.spec.accepted")
        # max_new 11 = prefill token + two FULL k=4 windows, so the
        # budget clamp never discards an over-budget acceptance and the
        # self-draft accept rate is exactly 1.0
        out_self = np.asarray(tiny_gpt.generate(
            prompt_ids, max_new_tokens=11, speculative="draft",
            draft_model=tiny_gpt)._data)
        dp = _counter("gen.spec.proposed") - p0
        da = _counter("gen.spec.accepted") - a0
    finally:
        monitor.disable()
    np.testing.assert_array_equal(out_self, ref[:, :11])
    assert dp > 0 and da == dp    # self-draft: every proposal accepted


def test_generate_spec_sampling_seeded(tiny_gpt, prompt_ids):
    kw = dict(max_new_tokens=8, do_sample=True, temperature=1.3,
              top_k=50, speculative="ngram")
    a = np.asarray(tiny_gpt.generate(prompt_ids, seed=11, **kw)._data)
    b = np.asarray(tiny_gpt.generate(prompt_ids, seed=11, **kw)._data)
    c = np.asarray(tiny_gpt.generate(prompt_ids, seed=12, **kw)._data)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert a.shape == (2, 8) and (a >= 0).all() and (a < 512).all()


# ------------------------------------------- overshoot clamps (satellite)


def _looping_prompt(n=24):
    # a repeated motif makes the prompt-lookup drafter accept (the
    # boundary tests need real multi-token acceptances to clamp)
    motif = np.array([11, 7, 42, 99, 3, 5], np.int32)
    return np.tile(motif, n // motif.size + 1)[None, :n]


def test_spec_budget_boundary_never_overshoots(tiny_gpt):
    """max_new_tokens lands MID verify window (k=4, window 5, budget 6
    with high accept): the clamp emits exactly the budget, bitwise
    equal to sequential decode, nothing written past the buffer."""
    ids = _looping_prompt()
    for max_new in (5, 6, 7):
        ref = np.asarray(tiny_gpt.generate(
            ids, max_new_tokens=max_new)._data)
        out = np.asarray(tiny_gpt.generate(
            ids, max_new_tokens=max_new, speculative="ngram")._data)
        assert out.shape == (1, max_new)
        np.testing.assert_array_equal(out, ref)


def test_spec_ring_capacity_exact_boundary(tiny_gpt):
    """The ring must carry spec.k slack for the last window's
    unaccepted overhang: the exact bound passes, one below raises up
    front (never discovered as ring corruption)."""
    ids = _looping_prompt()                       # prompt 24
    k, max_new = 4, 8
    exact = 24 + max_new + k
    out = np.asarray(tiny_gpt.generate(
        ids, max_new_tokens=max_new, cache_max_len=exact,
        speculative=SpeculativeConfig(k=k))._data)
    ref = np.asarray(tiny_gpt.generate(
        ids, max_new_tokens=max_new, cache_max_len=exact)._data)
    np.testing.assert_array_equal(out, ref)
    with pytest.raises(ValueError, match="overhang"):
        tiny_gpt.generate(ids, max_new_tokens=max_new,
                          cache_max_len=exact - 1,
                          speculative=SpeculativeConfig(k=k))
    # the same budget fits fine without speculation
    tiny_gpt.generate(ids, max_new_tokens=max_new,
                      cache_max_len=exact - 1)


def test_spec_position_table_overhang_guard(tiny_gpt, prompt_ids):
    # prompt 12 + max_new 113 fits max_position_embeddings=128 plain,
    # but not with the k=4 verify-window overhang
    with pytest.raises(ValueError, match="overhang"):
        tiny_gpt.generate(prompt_ids, max_new_tokens=113,
                          speculative="ngram")


# --------------------------------------------------- retraces + metrics


def test_spec_generate_compiles_once(prompt_ids):
    """First speculative call compiles prefill + draft + verify; the
    repeat adds zero (the no-retrace contract, same gate shape as the
    plain exactly-two-compiles test)."""
    from paddle_tpu.core import monitor
    paddle.seed(1)
    m = gpt("test-tiny")
    monitor.enable()
    try:
        t0 = _counter("jit.compile.total")
        s0 = _counter("jit.compile{cause=new_shape}")
        m.generate(prompt_ids, max_new_tokens=6, speculative="ngram")
        first = _counter("jit.compile.total") - t0
        assert first == 3        # prefill + spec draft + spec verify
        m.generate(prompt_ids, max_new_tokens=6, speculative="ngram")
        assert _counter("jit.compile.total") - t0 == first
        assert _counter("jit.compile{cause=new_shape}") - s0 == 0
    finally:
        monitor.disable()


def test_spec_metrics_family(tiny_gpt):
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    ids = _looping_prompt()
    monitor.enable()
    try:
        p0, a0 = _counter("gen.spec.proposed"), _counter("gen.spec.accepted")
        tiny_gpt.generate(ids, max_new_tokens=12, speculative="ngram")
        dp = _counter("gen.spec.proposed") - p0
        da = _counter("gen.spec.accepted") - a0
        assert dp > 0
        assert 0 < da <= dp     # the looping prompt really accepts
        rate = metrics.snapshot().get("gen.spec.accept_rate")
        assert rate and 0.0 < rate["value"] <= 1.0
    finally:
        monitor.disable()


# ------------------------------------------------------------ audit gate


def test_session_audit_speculative_gate(tiny_gpt, draft_gpt):
    """Tier-1 gate: the draft + single-dispatch verify programs audit
    at zero ERRORs with full donation coverage on verify (cache, token
    buffers, and every lane in place across windows)."""
    sess = GenerationSession(tiny_gpt)
    for spec_kw in (dict(speculative="ngram"),
                    dict(speculative="draft", draft_network=draft_gpt)):
        reports = sess.audit(2, 16, 128, GenerationConfig(),
                             max_new=8, **spec_kw)
        assert len(reports) == 4
        for rep in reports:
            rep.raise_on_error()
        draft_rep, verify_rep = reports[2], reports[3]
        assert verify_rep.donation_coverage == 1.0
        assert not verify_rep.by_check("host_sync")
        assert draft_rep.donation_coverage == 1.0


# -------------------------------------- GQA verify-window kernel parity


@pytest.mark.parametrize("hq,hk", [(4, 2), (8, 1)])
def test_decode_kernel_gqa_verify_window_equivalence(hq, hk):
    """MQA/GQA satellite: a q-len-4 verify window through the
    head-index-mapped decode kernel equals four sequential q-len-1
    calls at incrementing kv_len — the exact shape speculative verify
    dispatches on grouped-head models."""
    from paddle_tpu.kernels.flash_attention import flash_attention_decode
    rng = np.random.RandomState(5)
    b, d, t, w, base = 2, 64, 128, 4, 9
    q = rng.randn(b, w, hq, d).astype(np.float32)
    kc = rng.randn(b, t, hk, d).astype(np.float32)
    vc = rng.randn(b, t, hk, d).astype(np.float32)
    window = np.asarray(flash_attention_decode(
        q, kc, vc, np.full((b,), base + w, np.int32)))
    for i in range(w):
        step = np.asarray(flash_attention_decode(
            q[:, i:i + 1], kc, vc,
            np.full((b,), base + i + 1, np.int32)))
        np.testing.assert_allclose(window[:, i], step[:, 0],
                                   rtol=2e-5, atol=2e-5)


def test_decode_kernel_qlen_guard_names_limit():
    from paddle_tpu.kernels.flash_attention import (MAX_DECODE_QLEN,
                                                    flash_attention_decode)
    assert MAX_DECODE_QLEN == 8
    z = np.zeros((1, MAX_DECODE_QLEN + 1, 2, 64), np.float32)
    c = np.zeros((1, 128, 2, 64), np.float32)
    with pytest.raises(ValueError, match="MAX_DECODE_QLEN"):
        flash_attention_decode(z, c, c, np.array([9], np.int32))


# ------------------------------------------------------------- predictor


def test_predictor_speculative_buckets(tiny_gpt, prompt_ids):
    """Predictor path: spec draft+verify AOT-compiled per bucket, zero
    compiles under traffic, greedy parity with the plain predictor."""
    from paddle_tpu.core import monitor
    spec = [paddle.to_tensor(prompt_ids)]
    pred = create_predictor(
        Config().from_layer(tiny_gpt, spec)
        .enable_generation(max_new_tokens=6, prefill_buckets=(16, 32),
                           max_batch=2, speculative="ngram"))
    plain = create_predictor(
        Config().from_layer(tiny_gpt, spec)
        .enable_generation(max_new_tokens=6, prefill_buckets=(16, 32),
                           max_batch=2))
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 512, n).tolist() for n in (5, 12, 30)]
    monitor.enable()
    try:
        t0 = _counter("jit.compile.total")
        outs = pred.generate(prompts)
        assert _counter("jit.compile.total") - t0 == 0
    finally:
        monitor.disable()
    for got, ref in zip(outs, plain.generate(prompts)):
        np.testing.assert_array_equal(got, ref)
    # the audit covers the spec pair per bucket at zero errors
    reports = pred.audit_generation()
    assert ("spec_verify", 16) in reports and ("spec_draft", 16) in reports
    for rep in reports.values():
        rep.raise_on_error()
    assert reports[("spec_verify", 16)].donation_coverage == 1.0


def test_predictor_spec_smaller_max_new_stays_warm(tiny_gpt,
                                                   prompt_ids):
    """Review regression: generate(max_new_tokens=<below the compiled
    budget>) must decode into the compiled out-buffer width (budget is
    a lane) and hit the AOT verify executable — zero compiles, result
    still the requested length, parity with the plain path."""
    from paddle_tpu.core import monitor
    spec = [paddle.to_tensor(prompt_ids)]
    pred = create_predictor(
        Config().from_layer(tiny_gpt, spec)
        .enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                           max_batch=2, speculative="ngram"))
    plain = create_predictor(
        Config().from_layer(tiny_gpt, spec)
        .enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                           max_batch=2))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
    monitor.enable()
    try:
        t0 = _counter("jit.compile.total")
        outs = pred.generate(prompts, max_new_tokens=4)
        assert _counter("jit.compile.total") - t0 == 0
    finally:
        monitor.disable()
    assert all(o.size <= 4 for o in outs)
    for got, ref in zip(outs, plain.generate(prompts, max_new_tokens=4)):
        np.testing.assert_array_equal(got, ref)


def test_draft_model_position_table_guard(tiny_gpt):
    """Review regression: a draft model whose position table is
    smaller than the decode range fails up front, not as a silently
    clipped gather producing garbage proposals."""
    paddle.seed(9)
    short_draft = gpt("test-tiny-draft", max_position_embeddings=16)
    short_draft.eval()
    ids = np.random.RandomState(0).randint(0, 512, (1, 12)) \
        .astype(np.int32)
    with pytest.raises(ValueError, match="DRAFT"):
        tiny_gpt.generate(ids, max_new_tokens=8, speculative="draft",
                          draft_model=short_draft)


def test_predictor_spec_bucket_overhang_filter(tiny_gpt, prompt_ids):
    # 118 + 6 fits max_position_embeddings=128 plain but not with k=4
    spec = [paddle.to_tensor(prompt_ids)]
    plain = create_predictor(
        Config().from_layer(tiny_gpt, spec)
        .enable_generation(max_new_tokens=6, prefill_buckets=(16, 122)))
    assert plain._gen_buckets == [16, 122]
    pred = create_predictor(
        Config().from_layer(tiny_gpt, spec)
        .enable_generation(max_new_tokens=6, prefill_buckets=(16, 122),
                           speculative="ngram"))
    assert pred._gen_buckets == [16]


# ---------------------------------------------------------------- engine


def _spec_config(m, *, max_new=8, buckets=(16, 32), max_batch=2,
                 eos=None, speculative="ngram"):
    return (Config()
            .from_layer(m, [paddle.to_tensor(np.zeros((2, 12), np.int32))])
            .enable_generation(max_new_tokens=max_new,
                               prefill_buckets=buckets,
                               max_batch=max_batch, eos_token_id=eos,
                               speculative=speculative))


def test_engine_rejects_draft_mode(tiny_gpt, draft_gpt):
    with pytest.raises(ValueError, match="ngram"):
        ServingEngine(_spec_config(
            tiny_gpt, speculative=SpeculativeConfig(mode="draft")),
            warmup=False)


def test_engine_speculative_ragged_bitwise(tiny_gpt):
    """THE engine acceptance gate: ragged prompts/budgets with
    mid-decode arrivals through the speculative slot scheduler — zero
    new-shape retraces after warmup, every request bitwise-equal to
    the sequential non-speculative Predictor."""
    from paddle_tpu.core import monitor
    eng = ServingEngine(_spec_config(tiny_gpt), poll_every=2)
    rng = np.random.RandomState(0)
    lens = (5, 12, 20, 7, 3)
    budgets = (8, 3, 6, 5, 8)
    prompts = [rng.randint(0, 512, n).astype(np.int32) for n in lens]
    monitor.enable()
    try:
        ns0 = _counter("jit.compile{cause=new_shape}")
        tot0 = _counter("jit.compile.total")
        handles = [eng.submit(p, RequestParams(max_new_tokens=b))
                   for p, b in zip(prompts[:2], budgets[:2])]
        for _ in range(3):
            eng.step()
        handles += [eng.submit(p, RequestParams(max_new_tokens=b))
                    for p, b in zip(prompts[2:], budgets[2:])]
        while eng.busy:
            eng.step()
        assert _counter("jit.compile{cause=new_shape}") - ns0 == 0
        assert _counter("jit.compile.total") - tot0 == 0
        # the poll drained the on-device counters into gen.spec.*
        assert _counter("gen.spec.proposed") > 0
    finally:
        monitor.disable()
    assert all(h.status is RequestStatus.COMPLETED for h in handles)
    assert eng.stats["spec_proposed"] > 0
    # speculation actually amortized dispatches: fewer decode steps
    # than tokens decoded (5 requests, budgets sum 30, batch 2)
    assert eng.stats["spec_accepted"] > 0
    pred = create_predictor(
        Config()
        .from_layer(tiny_gpt,
                    [paddle.to_tensor(np.zeros((2, 12), np.int32))])
        .enable_generation(max_new_tokens=8, prefill_buckets=(16, 32),
                           max_batch=1))
    for p, b, h in zip(prompts, budgets, handles):
        ref = pred.generate([p], max_new_tokens=b)[0]
        np.testing.assert_array_equal(h.result(), ref)


def test_engine_spec_budget_exact_boundary(tiny_gpt):
    """A verify window spanning the budget (looping prompt => real
    multi-token acceptance) emits EXACTLY the budget: the overshoot
    clamp satellite at its boundary, bitwise vs the sequential path."""
    eng = ServingEngine(_spec_config(tiny_gpt, max_new=8,
                                     buckets=(32,), max_batch=1),
                        poll_every=1)
    prompt = _looping_prompt()[0]
    for budget in (2, 3, 5):
        h = eng.submit(prompt, RequestParams(max_new_tokens=budget))
        out = h.result(timeout=60)
        assert out.size == budget
        assert int(np.asarray(eng._steps)[0]) == budget
    pred = create_predictor(
        Config()
        .from_layer(tiny_gpt,
                    [paddle.to_tensor(np.zeros((2, 12), np.int32))])
        .enable_generation(max_new_tokens=8, prefill_buckets=(32,),
                           max_batch=1))
    ref = pred.generate([prompt], max_new_tokens=5)[0]
    h = eng.submit(prompt, RequestParams(max_new_tokens=5))
    np.testing.assert_array_equal(h.result(timeout=60), ref)


def test_engine_spec_eos_trims_within_window(tiny_gpt):
    """An eos landing mid-acceptance finishes the row there: emitted
    tokens stop at the eos, the result is eos-trimmed, matching the
    sequential reference exactly."""
    prompt = np.arange(1, 7, dtype=np.int32)
    pred = create_predictor(
        Config()
        .from_layer(tiny_gpt,
                    [paddle.to_tensor(np.zeros((2, 12), np.int32))])
        .enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                           max_batch=1))
    ref = pred.generate([prompt])[0]
    eos = int(ref[3])
    eng = ServingEngine(_spec_config(tiny_gpt, max_new=8, buckets=(16,),
                                     max_batch=1, eos=eos),
                        poll_every=1)
    h = eng.submit(prompt)
    out = h.result(timeout=60)
    first = int(np.nonzero(ref == eos)[0][0])
    np.testing.assert_array_equal(out, ref[:first])
    assert h.n_emitted == first + 1


def test_engine_speculative_audit_gate(tiny_gpt):
    """Tier-1 gate: the speculative slot-decode program (fused ngram
    draft + verify) and the spec admit program audit at zero ERRORs
    with full donation coverage — cache, token buffers, counters all
    in place across polls."""
    eng = ServingEngine(_spec_config(tiny_gpt), warmup=False)
    reports = eng.audit()
    assert set(reports) == {("prefill", 16), ("prefill", 32), "decode",
                            "admit", "free"}
    for rep in reports.values():
        rep.raise_on_error()
    assert not reports["decode"].by_check("host_sync")
    assert reports["decode"].donation_coverage == 1.0
    assert reports["admit"].donation_coverage == 1.0


def test_engine_spec_cache_overhang_validation(tiny_gpt):
    # exact bound passes, one below names the speculative overhang
    ServingEngine(_spec_config(tiny_gpt, max_new=8, buckets=(16,),
                               max_batch=1), warmup=False,
                  cache_max_len=16 + 8 + 4)
    with pytest.raises(ValueError, match="overhang"):
        ServingEngine(_spec_config(tiny_gpt, max_new=8, buckets=(16,),
                                   max_batch=1), warmup=False,
                      cache_max_len=16 + 8 + 3)


# ----------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_sigterm_mid_speculative_serve_drains(tiny_gpt):
    """SIGTERM mid-speculative-serve (the chaos satellite): every
    handle reaches a terminal status, queued requests reject cleanly,
    and cancelled in-flight requests keep ONLY accepted tokens — their
    partial output is a bitwise prefix of the sequential reference,
    never unverified draft garbage."""
    import signal
    from paddle_tpu.distributed.resilience import GracefulShutdown
    from paddle_tpu.utils.fault_injection import KillAfter

    eng = ServingEngine(_spec_config(tiny_gpt, max_new=8,
                                     buckets=(16,), max_batch=2),
                        poll_every=2, drain_timeout_s=0.0)
    pred = create_predictor(
        Config()
        .from_layer(tiny_gpt,
                    [paddle.to_tensor(np.zeros((2, 12), np.int32))])
        .enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                           max_batch=1))
    rng = np.random.RandomState(1)
    traffic = [rng.randint(0, 512, 4 + i).astype(np.int32)
               for i in range(5)]
    killer = KillAfter(3, signal.SIGTERM)
    with GracefulShutdown(exit_on_save=False) as gs:
        handles = eng.serve_forever(
            iter(traffic), on_step=lambda e: killer.step())
        assert gs.preempted
    assert killer.fired
    assert len(handles) == 5
    assert all(h.done() for h in handles), "a request hung"
    assert all(h.status.terminal for h in handles)
    rejected = [h for h in handles if h.status is RequestStatus.REJECTED]
    assert all(h.detail == "shutdown" for h in rejected)
    # zero-length drain window: in-flight rows were evicted mid-decode
    # with partial tokens — accepted-only, a prefix of the reference
    partial = [h for h in handles
               if h.status is RequestStatus.CANCELLED
               and h.tokens is not None]
    for h in partial:
        assert 0 < h.tokens.size < 8
        ref = pred.generate([h.prompt])[0]
        np.testing.assert_array_equal(h.tokens, ref[:h.tokens.size])
    # at least one request actually exercised the partial-trim path
    assert partial or any(h.status is RequestStatus.COMPLETED
                          for h in handles)
