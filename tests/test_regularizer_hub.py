"""Tests for the r4 parity additions: paddle.regularizer (L1/L2Decay
wired into optimizers), paddle.sysconfig, and paddle.hub (local
source). Reference: python/paddle/regularizer.py, sysconfig.py,
hapi/hub.py.
"""
import os
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.regularizer import L1Decay, L2Decay


def _train_one(reg):
    paddle.seed(0)
    m = nn.Linear(4, 4)
    opt = optimizer.Momentum(learning_rate=0.1,
                             parameters=m.parameters(),
                             weight_decay=reg)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    loss = m(x).sum()
    loss.backward()
    opt.step()
    return np.asarray(m.weight.numpy())


def test_l2decay_matches_manual():
    """L2Decay(c) must act as grad += c * p (the reference's
    L2DecayRegularizer convention)."""
    coeff = 0.5
    paddle.seed(0)
    ref = nn.Linear(4, 4)
    w0 = np.asarray(ref.weight.numpy()).copy()
    x = np.ones((2, 4), np.float32)
    # manual: grad of sum(x@W+b) wrt W is x^T @ ones = 2 for every entry
    g_manual = np.full_like(w0, 2.0) + coeff * w0
    expected = w0 - 0.1 * g_manual  # momentum first step = sgd step
    got = _train_one(L2Decay(coeff))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_l1decay_matches_manual():
    coeff = 0.3
    paddle.seed(0)
    ref = nn.Linear(4, 4)
    w0 = np.asarray(ref.weight.numpy()).copy()
    g_manual = np.full_like(w0, 2.0) + coeff * np.sign(w0)
    expected = w0 - 0.1 * g_manual
    got = _train_one(L1Decay(coeff))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_regularizer_under_trainstep():
    """Regularizer objects must survive the jitted functional update."""
    paddle.seed(0)
    m = nn.Linear(4, 1)
    opt = optimizer.SGD(learning_rate=0.05,
                        parameters=m.parameters(),
                        weight_decay=L2Decay(0.1))
    step = paddle.jit.TrainStep(m, opt, lambda out, y: ((out - y) ** 2).mean())
    x = paddle.to_tensor(np.random.RandomState(0).rand(8, 4).astype(np.float32))
    y = paddle.to_tensor(np.zeros((8, 1), np.float32))
    l0 = float(step(x, y))
    for _ in range(5):
        ln = float(step(x, y))
    assert ln < l0


def test_sysconfig_paths_exist():
    inc, lib = paddle.sysconfig.get_include(), paddle.sysconfig.get_lib()
    assert os.path.isdir(inc)
    assert os.path.isdir(lib)


@pytest.fixture()
def hub_repo(tmp_path):
    (tmp_path / "hubconf.py").write_text(textwrap.dedent("""
        dependencies = ["numpy"]

        def tiny_mlp(hidden=3):
            \"\"\"A tiny MLP entrypoint.\"\"\"
            from paddle_tpu import nn
            return nn.Sequential(nn.Linear(4, hidden), nn.ReLU(),
                                 nn.Linear(hidden, 2))

        def _private_helper():
            pass
    """))
    return str(tmp_path)


def test_param_regularizer_count_mismatch_raises():
    """If parameters carry regularizers but the functional update gets a
    different leaf count, the optimizer must raise instead of silently
    skipping them (jitted path would otherwise diverge from eager)."""
    from paddle_tpu.nn.initializer import ParamAttr

    paddle.seed(0)
    m = nn.Linear(4, 4, weight_attr=ParamAttr(regularizer=L2Decay(0.1)))
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=[m.weight])  # bias excluded
    with pytest.raises(ValueError, match="per-parameter regularizers"):
        opt._param_regularizers([m.weight.data, m.bias.data])


def test_param_regularizer_identity_match_survives_reorder():
    """Tensor leaves are matched to their regularizers by identity, so a
    params tree flattened in a different order than _parameter_list
    (e.g. a dict-keyed tree) still applies decay to the right params."""
    from paddle_tpu.nn.initializer import ParamAttr

    paddle.seed(0)
    m = nn.Linear(4, 4, weight_attr=ParamAttr(regularizer=L2Decay(0.1)))
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=[m.weight, m.bias])
    regs = opt._param_regularizers([m.bias, m.weight])  # reversed
    assert regs[0] is None                   # bias: no regularizer
    assert regs[1] is not None               # weight: L2Decay


def test_hub_list_help_load_local(hub_repo):
    names = paddle.hub.list(hub_repo, source="local")
    assert "tiny_mlp" in names and "_private_helper" not in names
    assert "tiny MLP" in paddle.hub.help(hub_repo, "tiny_mlp",
                                         source="local")
    model = paddle.hub.load(hub_repo, "tiny_mlp", hidden=5, source="local")
    out = model(paddle.to_tensor(np.ones((1, 4), np.float32)))
    assert tuple(out.shape) == (1, 2)


def test_hub_remote_sources_gated(hub_repo):
    with pytest.raises(RuntimeError, match="egress"):
        paddle.hub.list("owner/repo", source="github")
    with pytest.raises(ValueError, match="Unknown source"):
        paddle.hub.list(hub_repo, source="ftp")


def test_hub_missing_entrypoint_and_dependency(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['nonexistent_pkg_xyz']\n\ndef m():\n    pass\n")
    with pytest.raises(RuntimeError, match="Missing dependencies"):
        paddle.hub.list(str(tmp_path), source="local")


def test_hub_dotted_missing_dependency(tmp_path):
    """A dotted dependency with a missing parent must give the clean
    'Missing dependencies' error, not a raw ModuleNotFoundError from
    find_spec."""
    (tmp_path / "hubconf.py").write_text(
        "dependencies = ['no_such_parent_pkg.sub']\n\ndef m():\n    pass\n")
    with pytest.raises(RuntimeError, match="Missing dependencies"):
        paddle.hub.list(str(tmp_path), source="local")


def test_param_attr_regularizer_overrides_optimizer():
    """ParamAttr(regularizer=...) on a weight must override the
    optimizer-level weight_decay for that parameter (reference
    precedence), both eagerly and under the jitted TrainStep."""
    from paddle_tpu.nn.initializer import ParamAttr

    def build():
        paddle.seed(0)
        return nn.Linear(4, 4,
                         weight_attr=ParamAttr(regularizer=L1Decay(0.3)))

    x = paddle.to_tensor(np.ones((2, 4), np.float32))

    # eager: optimizer-level L2 should be ignored for the weight
    m = build()
    w0 = np.asarray(m.weight.numpy()).copy()
    opt = optimizer.Momentum(learning_rate=0.1,
                             parameters=m.parameters(),
                             weight_decay=L2Decay(10.0))
    loss = m(x).sum()
    loss.backward()
    opt.step()
    expected = w0 - 0.1 * (np.full_like(w0, 2.0) + 0.3 * np.sign(w0))
    np.testing.assert_allclose(np.asarray(m.weight.numpy()), expected,
                               rtol=1e-5, atol=1e-6)

    # jitted TrainStep path uses the same per-param override
    m2 = build()
    w0 = np.asarray(m2.weight.numpy()).copy()
    opt2 = optimizer.Momentum(learning_rate=0.1,
                              parameters=m2.parameters(),
                              weight_decay=L2Decay(10.0))
    step = paddle.jit.TrainStep(m2, opt2, lambda o, y: (o - y).sum())
    step(x, paddle.to_tensor(np.zeros((2, 4), np.float32)))
    np.testing.assert_allclose(np.asarray(m2.weight.numpy()), expected,
                               rtol=1e-5, atol=1e-6)
