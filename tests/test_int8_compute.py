"""Int8 compute path (VERDICT r2 Next #5): int8 x int8 -> int32 dots on
the MXU, accuracy-bounded vs the float model, wired into the predictor.
Measured on one v5e chip: 1.49x (b256) / 1.79x (b2048) over bf16 on a
3-layer 4096^2 MLP block — see BASELINE.md r3."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import PTQ, convert_to_int8_compute
from paddle_tpu.quantization.int8_compute import Int8ComputeLinear


def _mlp():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                         nn.Linear(64, 8))


def test_dynamic_int8_accuracy_bounded():
    model = _mlp()
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 32).astype(np.float32))
    ref = np.asarray(model(x).data)
    m = convert_to_int8_compute(model, inplace=False)
    assert isinstance(m[0], Int8ComputeLinear)
    got = np.asarray(m(x).data)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_ptq_calibrated_int8_accuracy_bounded():
    model = _mlp()
    model.eval()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 32).astype(np.float32))
    ref = np.asarray(model(x).data)
    ptq = PTQ()
    q = ptq.quantize(model, inplace=False)
    for _ in range(4):
        q(paddle.to_tensor(rng.randn(16, 32).astype(np.float32)))
    conv = ptq.convert(q)
    m = convert_to_int8_compute(conv)
    # calibrated scales flow from the PTQ wrapper
    assert m[0]._act_scale is not None
    got = np.asarray(m(x).data)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.08, rel


def test_int8_dot_in_program():
    """The compiled program must contain a true i8 x i8 -> i32 dot —
    the whole point vs the weight-only dequant path."""
    import jax
    model = _mlp()
    model.eval()
    m = convert_to_int8_compute(model, inplace=False)
    x = np.random.RandomState(0).randn(16, 32).astype(np.float32)
    sd = {k: v._data for k, v in m.state_dict().items()}
    from paddle_tpu.jit.api import functional_call

    def f(state, a):
        return functional_call(m, state, paddle.Tensor(a)).data

    txt = jax.jit(f).lower(sd, x).as_text()
    assert "xi8>" in txt and "xi32>" in txt
    assert "i8>, tensor<32x64xi8>) -> tensor<16x64xi32>" in txt


def test_state_dict_roundtrip():
    model = _mlp()
    model.eval()
    m = convert_to_int8_compute(model, inplace=False)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 32).astype(np.float32))
    ref = np.asarray(m(x).data)
    sd = m.state_dict()
    assert any("weight_int8" in k for k in sd)
    m2 = convert_to_int8_compute(_mlp(), inplace=False)
    m2.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(m2(x).data), ref,
                               rtol=1e-5, atol=1e-5)


def test_predictor_int8_compute_path():
    from paddle_tpu.inference import Config, PrecisionType, \
        create_predictor
    model = _mlp()
    model.eval()
    x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    ref = np.asarray(model(paddle.to_tensor(x)).data)
    cfg = Config().from_layer(
        model, [paddle.to_tensor(np.zeros((8, 32), np.float32))])
    cfg.enable_tpu(PrecisionType.Int8)
    cfg.enable_int8_compute()
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    rel = np.abs(out - ref).max() / np.abs(ref).max()
    assert rel < 0.06, rel
