"""Int8 compute path (VERDICT r2 Next #5): int8 x int8 -> int32 dots on
the MXU, accuracy-bounded vs the float model, wired into the predictor.
Measured on one v5e chip: 1.49x (b256) / 1.79x (b2048) over bf16 on a
3-layer 4096^2 MLP block — see BASELINE.md r3."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import PTQ, convert_to_int8_compute
from paddle_tpu.quantization.int8_compute import Int8ComputeLinear


def _mlp():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(32, 64), nn.ReLU(),
                         nn.Linear(64, 8))


def test_dynamic_int8_accuracy_bounded():
    model = _mlp()
    model.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(16, 32).astype(np.float32))
    ref = np.asarray(model(x).data)
    m = convert_to_int8_compute(model, inplace=False)
    assert isinstance(m[0], Int8ComputeLinear)
    got = np.asarray(m(x).data)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel


def test_ptq_calibrated_int8_accuracy_bounded():
    model = _mlp()
    model.eval()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 32).astype(np.float32))
    ref = np.asarray(model(x).data)
    ptq = PTQ()
    q = ptq.quantize(model, inplace=False)
    for _ in range(4):
        q(paddle.to_tensor(rng.randn(16, 32).astype(np.float32)))
    conv = ptq.convert(q)
    m = convert_to_int8_compute(conv)
    # calibrated scales flow from the PTQ wrapper
    assert m[0]._act_scale is not None
    got = np.asarray(m(x).data)
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.08, rel


def test_int8_dot_in_program():
    """The compiled program must contain a true i8 x i8 -> i32 dot —
    the whole point vs the weight-only dequant path."""
    import jax
    model = _mlp()
    model.eval()
    m = convert_to_int8_compute(model, inplace=False)
    x = np.random.RandomState(0).randn(16, 32).astype(np.float32)
    sd = {k: v._data for k, v in m.state_dict().items()}
    from paddle_tpu.jit.api import functional_call

    def f(state, a):
        return functional_call(m, state, paddle.Tensor(a)).data

    txt = jax.jit(f).lower(sd, x).as_text()
    assert "xi8>" in txt and "xi32>" in txt
    assert "i8>, tensor<32x64xi8>) -> tensor<16x64xi32>" in txt


def test_state_dict_roundtrip():
    model = _mlp()
    model.eval()
    m = convert_to_int8_compute(model, inplace=False)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 32).astype(np.float32))
    ref = np.asarray(m(x).data)
    sd = m.state_dict()
    assert any("weight_int8" in k for k in sd)
    m2 = convert_to_int8_compute(_mlp(), inplace=False)
    m2.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(m2(x).data), ref,
                               rtol=1e-5, atol=1e-5)


def test_predictor_int8_compute_path():
    from paddle_tpu.inference import Config, PrecisionType, \
        create_predictor
    model = _mlp()
    model.eval()
    x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    ref = np.asarray(model(paddle.to_tensor(x)).data)
    cfg = Config().from_layer(
        model, [paddle.to_tensor(np.zeros((8, 32), np.float32))])
    cfg.enable_tpu(PrecisionType.Int8)
    cfg.enable_int8_compute()
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    rel = np.abs(out - ref).max() / np.abs(ref).max()
    assert rel < 0.06, rel


def test_int8_conv_accuracy_bounded():
    """r4: XLA:TPU runs int8 convolutions natively (the r3 'upcast
    wall' was re-measured and falsified — experiments/
    int8_conv_probe.py); Int8ComputeConv2D must stay within a few
    percent of the float conv across stride/padding/groups/layouts."""
    import itertools
    from paddle_tpu import nn
    from paddle_tpu.quantization.int8_compute import Int8ComputeConv2D
    rng = np.random.RandomState(0)
    for stride, padding, groups, df in [
            (1, 0, 1, "NCHW"), (2, 1, 1, "NCHW"),
            (1, 1, 2, "NCHW"), (1, 1, 1, "NHWC")]:
        paddle.seed(1)
        conv = nn.Conv2D(8, 12, 3, stride=stride, padding=padding,
                         groups=groups, data_format=df)
        qconv = Int8ComputeConv2D.from_conv(conv)
        shape = (2, 8, 10, 10) if df == "NCHW" else (2, 10, 10, 8)
        x = paddle.to_tensor(rng.randn(*shape).astype(np.float32))
        ref = np.asarray(conv(x).data)
        got = np.asarray(qconv(x).data)
        rel = np.linalg.norm(got - ref) / (np.linalg.norm(ref) + 1e-9)
        assert rel < 0.05, (stride, padding, groups, df, rel)


def test_int8_conv_emits_int8_convolution():
    """The compiled HLO must contain a DIRECT s8 convolution — the
    measured premise of the conv compute path."""
    import jax
    from paddle_tpu import nn
    from paddle_tpu.quantization.int8_compute import Int8ComputeConv2D
    paddle.seed(2)
    conv = nn.Conv2D(8, 8, 1)
    qconv = Int8ComputeConv2D.from_conv(conv)

    def f(x):
        return qconv(paddle.to_tensor(x)).data

    x = np.random.RandomState(3).randn(1, 8, 6, 6).astype(np.float32)
    hlo = jax.jit(f).lower(x).as_text()
    # the traced program feeds i8 operands straight into the
    # convolution (no upcast inserted by OUR code; the TPU backend
    # compiles this to a native s8 conv — measured in
    # experiments/int8_conv_probe.py)
    assert "convolution" in hlo
    conv_line = next(l for l in hlo.splitlines()
                     if "stablehlo.convolution" in l)
    assert "i8" in conv_line, conv_line


def test_convert_swaps_convs():
    from paddle_tpu import nn
    from paddle_tpu.quantization.int8_compute import (
        Int8ComputeConv2D, convert_to_int8_compute)
    paddle.seed(3)
    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                        nn.Conv2D(8, 4, 1), nn.Flatten(),
                        nn.Linear(4 * 36, 10))
    convert_to_int8_compute(net)
    kinds = [type(l).__name__ for l in net]
    assert kinds.count("Int8ComputeConv2D") == 2
    assert kinds.count("Int8ComputeLinear") == 1
    x = paddle.to_tensor(
        np.random.RandomState(4).randn(2, 3, 6, 6).astype(np.float32))
    out = net(x)
    assert np.isfinite(np.asarray(out.data)).all()


def test_ptq_converted_convs_swap_to_int8_compute():
    """PTQ.convert() output with convs must swap cleanly (the r4
    review repro: _FrozenQuantConv2D previously crashed the walk)."""
    from paddle_tpu import nn
    from paddle_tpu.quantization import PTQ, QuantConfig
    from paddle_tpu.quantization.int8_compute import (
        Int8ComputeConv2D, convert_to_int8_compute)
    paddle.seed(5)
    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(8 * 36, 4))
    ptq = PTQ(QuantConfig())
    qnet = ptq.quantize(net)
    rng = np.random.RandomState(6)
    for _ in range(2):
        qnet(paddle.to_tensor(rng.randn(2, 3, 6, 6).astype(np.float32)))
    final = ptq.convert(qnet)
    convert_to_int8_compute(final)
    names = [type(l).__name__ for l in final]
    assert "Int8ComputeConv2D" in names, names
    out = final(paddle.to_tensor(
        rng.randn(2, 3, 6, 6).astype(np.float32)))
    assert np.isfinite(np.asarray(out.data)).all()


def test_int8_conv_string_and_asymmetric_padding():
    from paddle_tpu import nn
    from paddle_tpu.quantization.int8_compute import Int8ComputeConv2D
    rng = np.random.RandomState(7)
    for padding in ("SAME", [1, 0, 2, 1]):
        paddle.seed(8)
        conv = nn.Conv2D(4, 6, 3, padding=padding)
        qconv = Int8ComputeConv2D.from_conv(conv)
        x = paddle.to_tensor(rng.randn(2, 4, 8, 8).astype(np.float32))
        ref = np.asarray(conv(x).data)
        got = np.asarray(qconv(x).data)
        assert got.shape == ref.shape, padding
        rel = np.linalg.norm(got - ref) / (np.linalg.norm(ref) + 1e-9)
        assert rel < 0.05, (padding, rel)
