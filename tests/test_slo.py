"""SLO watchtower (ISSUE 17): the time-series ring's windowed queries
and delta sharing, burn-rate math on raw bucket arrays, the re-bound
serve latency histograms' resolution vs exact quantiles, the
deterministic pending->firing->resolved burn-rate state machine on a
replayed synthetic burst trace (reflected live at /slo and
/fleet/healthz), per-request cost attribution reconciling against the
goodput ledger's compute bucket, the straggler detector's robust
z-score latch, and the tools/slo_report.py post-mortem CLI."""
import glob
import json
import os
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flight_recorder, monitor, slo, timeseries
from paddle_tpu.core.telemetry_server import TelemetryServer
from paddle_tpu.profiler import metrics


@pytest.fixture(autouse=True)
def _clean_slate():
    metrics.disable()
    metrics.reset()
    timeseries._reset_for_tests()
    slo._reset_for_tests()
    yield
    metrics.disable()
    metrics.reset()
    timeseries._reset_for_tests()
    slo._reset_for_tests()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read().decode())


# ------------------------------------------------------ time-series ring


class TestTimeSeriesRing:
    def test_counter_delta_and_rate(self):
        metrics.enable()
        c = metrics.counter("t.slo.count")
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=10)
        ring.sample(now=0.0)
        c.inc(5)
        ring.sample(now=1.0)
        c.inc(15)
        ring.sample(now=2.0)
        assert ring.delta("t.slo.count", 2.0) == 20
        assert ring.delta("t.slo.count", 1.0) == 15
        assert ring.rate("t.slo.count", 2.0) == pytest.approx(10.0)
        assert ring.latest("t.slo.count") == 20
        # unknown metric: no evidence, not zero
        assert ring.delta("t.slo.nope", 2.0) is None

    def test_unchanged_records_shared_by_reference(self):
        """The delta encoding applied in-memory: a metric that did not
        move between samples costs a POINTER in the next snapshot, not
        a copy — the idle-ring memory bound."""
        metrics.enable()
        a = metrics.counter("t.share.a")
        b = metrics.counter("t.share.b")
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=10)
        a.inc()
        b.inc()
        ring.sample(now=0.0)
        b.inc()  # only b moves
        ring.sample(now=1.0)
        s0 = ring._entries[0][1]
        s1 = ring._entries[1][1]
        assert s1["t.share.a"] is s0["t.share.a"]
        assert s1["t.share.b"] is not s0["t.share.b"]

    def test_labeled_subset_matching_and_double_count_trap(self):
        """Label-subset queries sum matching series; the bare
        serve.requests name also matches the UNLABELED parent the
        recorder bumps alongside each status — which is exactly why
        the error-rate SLO enumerates labeled statuses for its total."""
        metrics.enable()
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=10)
        ring.sample(now=0.0)
        for _ in range(3):
            monitor.record_serve_request("completed")
        monitor.record_serve_request("cancelled")
        ring.sample(now=1.0)
        assert ring.delta("serve.requests{status=completed}", 1.0) == 3
        assert ring.delta("serve.requests{status=cancelled}", 1.0) == 1
        # bare name = labeled series + unlabeled parent = 2x the truth
        assert ring.delta("serve.requests", 1.0) == 8
        spec = next(s for s in slo.default_slos()
                    if s.name == "serve-error-rate")
        measured, bad = spec.measure(ring, 1.0)
        assert measured == pytest.approx(0.25)
        assert bad == pytest.approx(0.25)

    def test_retention_bound_and_disabled(self):
        metrics.enable()
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=3)
        for t in range(6):
            ring.sample(now=float(t))
        assert len(ring) == 3
        assert ring.span() == (3.0, 5.0)
        off = timeseries.TimeSeriesRing(period_s=0.0, retention=3)
        assert off.disabled
        assert not off.maybe_sample()
        assert len(off) == 0

    def test_maybe_sample_period_gate(self):
        metrics.enable()
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=10)
        assert ring.maybe_sample(now=0.0)
        assert not ring.maybe_sample(now=0.5)   # not due
        assert ring.maybe_sample(now=1.0)
        assert len(ring) == 2

    def test_hist_window_queries(self):
        metrics.enable()
        h = metrics.histogram("t.slo.lat",
                              bounds=(0.1, 0.2, 0.4, 0.8))
        h.observe(0.15)   # before the window: must not count
        ring = timeseries.TimeSeriesRing(period_s=1.0, retention=10)
        ring.sample(now=0.0)
        for v in (0.15, 0.15, 0.3, 0.7):
            h.observe(v)
        ring.sample(now=1.0)
        bounds, d_counts, d_count, d_sum = ring.hist_delta(
            "t.slo.lat", 1.0)
        assert d_count == 4
        assert d_sum == pytest.approx(1.3)
        assert sum(d_counts) == 4
        frac = ring.hist_fraction_above("t.slo.lat", 0.2, 1.0)
        assert frac == pytest.approx(0.5)  # 0.3 and 0.7 of the four
        p100 = ring.hist_percentile_over("t.slo.lat", 100.0, 1.0)
        assert 0.4 < p100 <= 0.8


class TestPercentileMath:
    def test_percentile_of_matches_histogram_object(self):
        metrics.enable()
        h = metrics.histogram("t.pct", bounds=(1.0, 2.0, 4.0, 8.0))
        for v in (0.5, 1.5, 1.5, 3.0, 6.0, 20.0):
            h.observe(v)
        bounds, counts, count, _ = h.raw()
        for q in (0, 10, 50, 90, 99, 100):
            assert timeseries.percentile_of(bounds, counts, count, q) \
                == h.percentile(q)
        assert timeseries.percentile_of(bounds, counts, 0, 50) == 0.0

    def test_fraction_above_interpolates(self):
        bounds = (1.0, 2.0, 4.0)
        counts = [2, 2, 2, 0]       # uniform-ish, none in overflow
        # threshold on a bucket edge: exactly the upper buckets
        assert timeseries.fraction_above(bounds, counts, 6, 2.0) == \
            pytest.approx(2 / 6)
        # mid-bucket: half of the (1,2] bucket counts as above
        assert timeseries.fraction_above(bounds, counts, 6, 1.5) == \
            pytest.approx(0.5)
        assert timeseries.fraction_above(bounds, counts, 6, 0.0) == 1.0
        assert timeseries.fraction_above(bounds, counts, 6, 100.0) == \
            pytest.approx(0.0)
        assert timeseries.fraction_above(bounds, counts, 0, 1.0) == 0.0


# ------------------------------------- satellite: re-bound serve latency


class TestServeLatencyBounds:
    def test_quarter_octave_spacing_and_coverage(self):
        """The SLO-gateable contract: consecutive bounds 2^0.25 apart
        (worst-case relative quantile error ~19%, vs ~41% before the
        re-bound), covering 100us through >60s."""
        b = monitor._SERVE_LATENCY_BOUNDS
        assert b[0] == pytest.approx(1e-4)
        assert b[-1] > 60.0
        for lo, hi in zip(b, b[1:]):
            assert hi / lo == pytest.approx(2 ** 0.25)

    def test_p99_resolution_vs_exact_quantiles(self):
        """Seeded latency sample through the real serve.ttft recorder:
        the bucket-interpolated percentile must sit within one bucket
        of the exact empirical quantile — relative error <= 2^0.25-1."""
        metrics.enable()
        rng = np.random.RandomState(7)
        vals = np.exp(rng.normal(np.log(0.05), 1.0, size=2000))
        for v in vals:
            monitor.record_serve_ttft(float(v))
        h = metrics.histogram("serve.ttft",
                              bounds=monitor._SERVE_LATENCY_BOUNDS)
        assert h.count == len(vals)
        tol = 2 ** 0.25 - 1
        for q in (50, 95, 99):
            exact = float(np.percentile(vals, q))
            est = h.percentile(q)
            assert abs(est - exact) / exact <= tol + 1e-9, (
                f"p{q}: est {est:.5f} vs exact {exact:.5f}")


# ----------------------------------------------------------- SLO specs


class TestDefaultSlos:
    def test_env_objective_and_windows(self, monkeypatch):
        monkeypatch.setenv("PADDLE_SLO_TTFT_P99", "0.25")
        monkeypatch.setenv("PADDLE_SLO_TOKEN_P99", "off")
        monkeypatch.setenv("PADDLE_SLO_ERROR_RATE", "garbage")
        monkeypatch.setenv("PADDLE_SLO_WINDOW_S", "120")
        monkeypatch.setenv("PADDLE_SLO_FAST_WINDOW_S", "15")
        specs = {s.name: s for s in slo.default_slos()}
        assert "serve-token-p99" not in specs
        assert specs["serve-ttft-p99"].objective == 0.25
        assert specs["serve-error-rate"].objective == 0.01  # fallback
        assert all(s.window_s == 120.0 and s.fast_window_s == 15.0
                   for s in specs.values())

    def test_budgets(self):
        lat = slo.SLO("l", "latency", "m", 0.5, percentile=99.0)
        assert lat.budget == pytest.approx(0.01)
        err = slo.SLO("e", "error_rate", "m", 0.02)
        assert err.budget == pytest.approx(0.02)
        frac = slo.SLO("f", "fraction_min", "m", 0.2, good_metric="g")
        assert frac.budget == pytest.approx(0.8)
        assert lat.burn(0.03) == pytest.approx(3.0)


# -------------------------------------------------- straggler detection


class TestStragglerDetector:
    def _totals(self, means, steps=10, polls=1):
        return {r: (steps * polls, m * steps * polls)
                for r, m in means.items()}

    def test_latched_detect_and_hysteresis_clear(self):
        flight_recorder.clear()
        metrics.enable()
        det = slo.StragglerDetector(z_threshold=3.5, min_ranks=3)
        base = {0: 0.1, 1: 0.1, 2: 0.1, 3: 0.1}
        assert det.observe(self._totals(base)) == []
        # rank 3 turns 10x slow: detected exactly once (latched)
        slow = dict(base)
        slow[3] = 1.0
        t2 = {r: (20, base[r] * 10 + slow[r] * 10) for r in base}
        ev = det.observe(t2)
        assert [(e["rank"], e["phase"]) for e in ev] == \
            [(3, "detected")]
        t3 = {r: (30, t2[r][1] + slow[r] * 10) for r in base}
        assert det.observe(t3) == []          # still slow: no re-fire
        assert det.straggler_ranks() == [3]
        assert 3 in det.flags()
        # back to normal: resolves with hysteresis
        t4 = {r: (40, t3[r][1] + base[r] * 10) for r in base}
        ev = det.observe(t4)
        assert [(e["rank"], e["phase"]) for e in ev] == \
            [(3, "resolved")]
        assert det.straggler_ranks() == []
        # events + counter landed
        names = [(k, f) for _, k, f in flight_recorder.events()
                 if k == "train.straggler"]
        assert [f["phase"] for _, f in names] == ["detected",
                                                  "resolved"]
        snap = metrics.snapshot()
        assert snap["train.straggler"]["value"] == 1
        assert snap["train.straggler{rank=3}"]["value"] == 1

    def test_min_ranks_guard(self):
        det = slo.StragglerDetector(min_ranks=3)
        assert det.observe({0: (10, 1.0), 1: (10, 9.0)}) == []
        assert det.straggler_ranks() == []

    def test_restarted_rank_counter_reset(self):
        det = slo.StragglerDetector(min_ranks=3)
        det.observe({0: (100, 10.0), 1: (100, 10.0), 2: (100, 10.0)})
        # rank 2 relaunched: totals below the last seen -> treat as
        # fresh absolutes, not a negative window
        ev = det.observe({0: (110, 11.0), 1: (110, 11.0),
                          2: (10, 1.0)})
        assert ev == []
        assert det.straggler_ranks() == []


# ------------------------- THE acceptance test: deterministic burn rates


class TestBurnRateStateMachine:
    """Replay a synthetic partial-burst TTFT trace through the ring:
    the serve-ttft-p99 SLO must transition ok -> pending -> firing ->
    resolved at exactly the predicted snapshots, and /slo plus
    /fleet/healthz must reflect each state as it happens.

    Numbers (objective 0.5s, p99 -> budget 1%; fast window 10s, slow
    100s; 100 obs/s): baseline t=1..100 all good; burst t=101..140 has
    3 bad obs/s (0.3%/s of the fast window's 1000 obs); good again
    t=141..150.

      pending  at t=104: fast window holds 4 burst seconds -> 12/1000
               = 1.2% > 1% budget (t=103: 9/1000 = 0.9%, still ok)
      firing   at t=134: slow window holds 34 burst seconds ->
               102/10000 = 1.02% > 1% (t=133: 99/10000, not yet)
      resolved at t=147: fast window down to 3 burst seconds ->
               9/1000 = 0.9% <= 1% (t=146: 12/1000, still firing)
    """

    GOOD, BAD = 0.01, 1.0

    @staticmethod
    def _expected(t):
        if t < 104:
            return "ok"
        if t < 134:
            return "pending"
        if t < 147:
            return "firing"
        return "resolved"

    def test_replayed_burst_transitions_and_endpoints(self, monkeypatch):
        from paddle_tpu.core.metrics import snapshot_delta
        from paddle_tpu.distributed import fleet_telemetry as ft
        from paddle_tpu.distributed.store import TCPStore
        monkeypatch.setenv("PADDLE_TS_PERIOD_S", "1.0")
        monkeypatch.setenv("PADDLE_TS_RETENTION", "200")
        monkeypatch.setenv("PADDLE_SLO_WINDOW_S", "100")
        monkeypatch.setenv("PADDLE_SLO_FAST_WINDOW_S", "10")
        timeseries._reset_for_tests()
        slo._reset_for_tests()
        flight_recorder.clear()
        metrics.enable()
        store = TCPStore("127.0.0.1", 0, is_master=True)
        server = TelemetryServer(port=0).start()
        try:
            # fleet mode: the SAME specs over sample_state()-fed merged
            # snapshots (the aggregator's poll loop is driven by hand)
            agg = ft.FleetAggregator(store, period_s=1.0,
                                     expected_ranks=1,
                                     namespace="__fleet/slo-accept")
            server.attach_aggregator(agg)
            # the timeline below drives the aggregator's ring by hand
            # with synthetic timestamps; park the scrape-triggered
            # refresh so real-clock samples can't interleave
            agg._last_poll = float("inf")
            base = f"http://127.0.0.1:{server.port}"
            assert slo.tick(now=0.0)         # baseline snapshot
            checkpoints = {}
            for t in range(1, 151):
                bad = 3 if 101 <= t <= 140 else 0
                for _ in range(100 - bad):
                    monitor.record_serve_ttft(self.GOOD)
                for _ in range(bad):
                    monitor.record_serve_ttft(self.BAD)
                assert slo.tick(now=float(t))
                states = slo.watchtower().states()
                assert states["serve-ttft-p99"] == self._expected(t), \
                    f"t={t}"
                fleet_state, _ = snapshot_delta(None)
                agg._slo_ring.sample_state(fleet_state, now=float(t))
                fstates = agg.slo_evaluator.evaluate(now=float(t))
                assert fstates["serve-ttft-p99"] == self._expected(t), \
                    f"fleet t={t}"
                if t in (103, 104, 133, 134, 146, 147):
                    doc = _get_json(base + "/slo")
                    row = next(s for s in doc["slos"]
                               if s["name"] == "serve-ttft-p99")
                    assert row["state"] == self._expected(t), f"t={t}"
                    hz = _get_json(base + "/fleet/healthz")
                    assert hz["slo"]["serve-ttft-p99"] == \
                        self._expected(t), f"t={t}"
                    checkpoints[t] = row
            # the firing-time measurement shows the burst's p99 over
            # the fast window breaching the objective
            assert checkpoints[134]["measured"] > 0.5
            assert checkpoints[134]["burn_fast"] > 1.0
            assert checkpoints[134]["burn_slow"] > 1.0
            assert checkpoints[147]["burn_fast"] <= 1.0
            # alert history carries the exact transition timeline
            doc = _get_json(base + "/slo")
            ttft_alerts = [(a["to"], a["t"]) for a in doc["alerts"]
                           if a["slo"] == "serve-ttft-p99"]
            assert ttft_alerts == [("pending", 104.0),
                                   ("firing", 134.0),
                                   ("resolved", 147.0)]
            assert doc["fleet"]["scope"] == "fleet"
            # flight recorder: one event per transition per scope, and
            # the escalation + firing spans for the post-mortem dump
            evs = [(k, f) for _, k, f in flight_recorder.events()
                   if k in ("slo.pending", "slo.firing",
                            "slo.resolved")]
            for scope in ("process", "fleet"):
                seq = [k for k, f in evs if f["scope"] == scope]
                assert seq == ["slo.pending", "slo.firing",
                               "slo.resolved"], scope
            spans = [f for _, k, f in flight_recorder.events()
                     if k == "span" and
                     f["name"] == "slo:serve-ttft-p99"]
            phases = sorted(s["phase"] for s in spans
                            if s["scope"] == "process")
            assert phases == ["escalation", "firing"]
            # resolved event reports how long the alert was firing
            resolved = next(f for k, f in evs
                            if k == "slo.resolved"
                            and f["scope"] == "process")
            assert resolved["firing_s"] == pytest.approx(13.0)
            # slo.* metrics landed (state gauge back at 0 == resolved)
            snap = metrics.snapshot()
            assert snap["slo.state{scope=process,slo=serve-ttft-p99}"][
                "value"] == 0
            assert snap["slo.transitions{scope=process,"
                        "slo=serve-ttft-p99,to=firing}"]["value"] == 1
        finally:
            server.stop()
            store.shutdown_server()


# ----------------------------------------- per-request cost attribution


class TestCostAttribution:
    def test_costs_reconcile_with_goodput_compute(self):
        """The acceptance contract: Request.cost() summed across all
        requests matches the goodput ledger's compute bucket within 1%
        — every admission second and every decode-window second is
        attributed to exactly one request (warm engine: nothing lands
        in the compile bucket)."""
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=8,
                                  prefill_buckets=(16,), max_batch=2)
               .enable_serving(telemetry_port=0))
        eng = ServingEngine(cfg, poll_every=1)
        try:
            handles = [eng.submit(
                np.arange(1, 5 + (i % 3), dtype=np.int32))
                for i in range(6)]
            for h in handles:
                h.result(timeout=120)
            total_cost = sum(h.cost()["total_s"] for h in handles)
            compute = eng.goodput()["buckets"].get("compute", 0.0)
            assert compute > 0
            assert abs(total_cost - compute) <= 0.01 * compute, (
                f"sum(cost)={total_cost:.6f} vs compute="
                f"{compute:.6f}")
            # component sanity: every request paid a prefill and at
            # least one decode window
            for h in handles:
                c = h.cost()
                assert c["prefill_s"] > 0
                assert c["decode_s"] > 0
                assert c["total_s"] == pytest.approx(
                    c["prefill_s"] + c["decode_s"])
            # the top-K table is costliest-first and on /slo
            table = eng.cost_table()
            assert len(table) == 6
            totals = [row["total_s"] for row in table]
            assert totals == sorted(totals, reverse=True)
            assert eng.telemetry is not None
            doc = _get_json(
                f"http://127.0.0.1:{eng.telemetry.port}/slo")
            assert len(doc["top_cost"]) == 6
            # serve.cost.* histograms populated
            snap = metrics.snapshot()
            assert snap["serve.cost.prefill_ms"]["count"] == 6
            assert snap["serve.cost.decode_ms"]["count"] == 6
        finally:
            eng.shutdown()


# ------------------------------------------------ post-mortem CLI tool


class TestSloReportCLI:
    def _make_dump(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        flight_recorder.clear()
        t0 = flight_recorder.now_ns()
        flight_recorder.record("slo.pending", slo="serve-ttft-p99",
                               scope="process", burn_fast=1.4,
                               burn_slow=0.6, measured=0.61)
        flight_recorder.record("slo.firing", slo="serve-ttft-p99",
                               scope="process", burn_fast=2.5,
                               burn_slow=1.1, measured=0.9)
        flight_recorder.record_span("slo:serve-ttft-p99", t0,
                                    flight_recorder.now_ns(),
                                    scope="process", phase="escalation")
        flight_recorder.record("train.straggler", rank=3,
                               phase="detected", z=5.1, mean_s=0.91,
                               median_s=0.3)
        flight_recorder.record("slo.resolved", slo="serve-ttft-p99",
                               scope="process", burn_fast=0.4,
                               burn_slow=1.0, firing_s=12.5)
        return flight_recorder.dump(reason="test")

    def test_render_and_cli_smoke(self, tmp_path, monkeypatch, capsys):
        from tools import slo_report
        path = self._make_dump(tmp_path, monkeypatch)
        assert slo_report.main([path]) == 0
        out = capsys.readouterr().out
        assert "serve-ttft-p99" in out
        for word in ("pending", "firing", "resolved", "escalation"):
            assert word in out
        # straggler table: rank + z + phase
        assert "Stragglers" in out and "detected" in out
        assert "5.100" in out
        # firing duration surfaced from the resolved event
        assert "firing_s=12.500" in out

    def test_directory_glob_and_output_file(self, tmp_path,
                                            monkeypatch, capsys):
        from tools import slo_report
        self._make_dump(tmp_path, monkeypatch)
        assert glob.glob(str(tmp_path / "flightrecorder_*.json"))
        out_path = tmp_path / "postmortem.txt"
        assert slo_report.main(
            ["-o", str(out_path), str(tmp_path)]) == 0
        text = out_path.read_text()
        assert "serve-ttft-p99" in text and "Alert timeline" in text
        capsys.readouterr()

    def test_empty_dump_renders_placeholders(self, tmp_path,
                                             monkeypatch, capsys):
        from tools import slo_report
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        flight_recorder.clear()
        flight_recorder.record("serve.finish", req=1,
                               status="completed", tokens=2)
        path = flight_recorder.dump(reason="quiet")
        assert slo_report.main([path]) == 0
        out = capsys.readouterr().out
        assert "no slo.* transitions" in out
        assert "no train.straggler events" in out
