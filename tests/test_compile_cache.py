"""Executable-persistence tests (ISSUE 9): the jit.compile_cache layer.

Covers: store roundtrip + the jit.compile_cache.* metrics family, THE
tier-1 warm-restart gate (a rebuilt ServingEngine in a cleared-jax-cache
state loads every program from the store — hits == program count,
misses == 0, zero XLA compiles — with outputs bitwise-equal to the cold
reference), the Predictor's per-bucket build, the TrainStep warm path
behind Model.fit(resume=True), cache-key invalidation (changing ANY key
component must MISS — a stale hit silently serving the wrong program is
the failure mode to prove impossible), the process-global conflict
warning, and the chaos tier's corrupt-entry fallback.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import compile_cache
from paddle_tpu.jit.compile_cache import ExecutableStore

import jax
import jax.numpy as jnp


def _counter(name):
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    from paddle_tpu.models.gpt import gpt
    m = gpt("test-tiny")
    m.eval()
    return m


def _serve_cfg(m, max_new=6, buckets=(16, 32), max_batch=2):
    from paddle_tpu.inference import Config
    spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
    return (Config().from_layer(m, spec)
            .enable_generation(max_new_tokens=max_new,
                               prefill_buckets=buckets,
                               max_batch=max_batch))


# ------------------------------------------------------------- the store


def test_store_roundtrip_and_metrics(tmp_path):
    """Cold miss compiles + persists; a fresh lookup deserializes
    (hit); both executables compute the same thing; every event lands
    in the jit.compile_cache.* counters."""
    from paddle_tpu.core import monitor
    store = ExecutableStore(str(tmp_path / "exe"))

    def f(x):
        return x * 2 + 1

    aval = jax.ShapeDtypeStruct((8,), jnp.float32)
    monitor.enable()
    try:
        h0 = _counter("jit.compile_cache.hits")
        m0 = _counter("jit.compile_cache.misses")
        b0 = _counter("jit.compile_cache.bytes")
        exe = store.get_or_compile(jax.jit(f).lower(aval), label="t")
        assert store.stats["misses"] == 1 and store.stats["hits"] == 0
        assert store.stats["saves"] == 1 and len(store) == 1
        exe2 = store.get_or_compile(jax.jit(f).lower(aval), label="t")
        assert store.stats["hits"] == 1 and store.stats["misses"] == 1
        assert _counter("jit.compile_cache.hits") - h0 == 1
        assert _counter("jit.compile_cache.misses") - m0 == 1
        assert _counter("jit.compile_cache.bytes") - b0 > 0
    finally:
        monitor.disable()
    x = jnp.arange(8, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(exe(x)),
                                  np.asarray(exe2(x)))


def test_cache_key_invalidation():
    """Changing any key component — program, donation signature, mesh
    axes, jax/jaxlib version, backend platform/device/count — must
    produce a different key (MISS). Identical programs from fresh
    traces must produce the SAME key (the warm-restart hit)."""
    store = ExecutableStore("/tmp/never-written-key-test")

    def f(x):
        return x + 1

    def g(x):
        return x + 2

    aval = jax.ShapeDtypeStruct((4,), jnp.float32)
    base = store.key_for(jax.jit(f).lower(aval))
    # deterministic across fresh traces of the same program
    assert store.key_for(jax.jit(f).lower(aval)) == base
    # a different program misses
    assert store.key_for(jax.jit(g).lower(aval)) != base
    # ...and a different shape is a different program
    assert store.key_for(
        jax.jit(f).lower(jax.ShapeDtypeStruct((8,), jnp.float32))) != base
    low = jax.jit(f).lower(aval)
    # donation signature
    assert store.key_for(low, extra=dict(donation=(0,))) != base
    assert store.key_for(low, extra=dict(donation=(0,))) != \
        store.key_for(low, extra=dict(donation=(1,)))
    # mesh axes (the DistributedTrainStep warm path's extra)
    assert store.key_for(low, extra=dict(mesh=(("dp", 8),))) != \
        store.key_for(low, extra=dict(mesh=(("dp", 4), ("mp", 2))))
    # environment half: jaxlib / jax / backend / device flavor / count
    assert store.key_for(low, jaxlib_version="9.9.9") != base
    assert store.key_for(low, jax_version="9.9.9") != base
    assert store.key_for(low, backend="tpu") != base
    assert store.key_for(low, device_kind="TPU v5e") != base
    assert store.key_for(low, n_devices=256) != base


def test_enable_compile_cache_conflict_warns(tmp_path):
    """Process-global set-once + warn-on-conflict semantics — the
    predictor's original `_ensure_compile_cache` contract, now owned by
    the one shared implementation."""
    prev_dir = compile_cache._CACHE_DIR
    prev_store = compile_cache._DEFAULT_STORE
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    try:
        if prev_dir is None:
            store = compile_cache.enable_compile_cache(a)
            assert isinstance(store, ExecutableStore)
            assert compile_cache.cache_dir() == a
            current = a
        else:  # some earlier test already anchored the process cache
            current = prev_dir
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            compile_cache.enable_compile_cache(b)
        assert any("process-global" in str(x.message) for x in w)
        assert compile_cache.cache_dir() == current
        # re-naming the SAME dir is silent (idempotent re-entry)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            compile_cache.enable_compile_cache(current)
        assert not w
    finally:
        if prev_dir is None:
            # undo the jax-global side effect so later tests don't
            # write cache entries into this test's tmp dir
            jax.config.update("jax_compilation_cache_dir", prev_dir)  # lint: compile-cache-dir-ok (test restore)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        compile_cache._CACHE_DIR = prev_dir
        compile_cache.set_default_store(prev_store)


# ----------------------------------------------- the traceless manifest


def test_manifest_hit_skips_tracing(tmp_path):
    """A manifest (signature) hit deserializes WITHOUT calling
    lower_fn — zero traces, zero compiles; a changed signature falls
    back to the traced path (which still resolves to the same
    executable by its HLO key and heals the manifest)."""
    root = str(tmp_path / "exe")
    store = ExecutableStore(root)

    def f(x):
        return x * 5.0

    aval = jax.ShapeDtypeStruct((4,), jnp.float32)
    sig = dict(kind="t", operands=compile_cache.aval_signature((aval,)))
    exe = store.get_or_build(sig, lambda: jax.jit(f).lower(aval))
    assert store.stats["misses"] == 1 and len(store.refs()) == 1

    def boom():
        raise AssertionError("manifest hit must not trace")

    warm = ExecutableStore(root)
    exe2 = warm.get_or_build(sig, boom)
    assert warm.stats["hits"] == 1 and warm.stats["misses"] == 0
    x = jnp.arange(4, dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(exe(x)),
                                  np.asarray(exe2(x)))
    # changed signature: traced fallback, same executable, new ref
    exe3 = warm.get_or_build(dict(sig, kind="other"),
                             lambda: jax.jit(f).lower(aval))
    assert warm.stats["hits"] == 2 and warm.stats["misses"] == 0
    np.testing.assert_array_equal(np.asarray(exe(x)),
                                  np.asarray(exe3(x)))
    assert len(warm.refs()) == 2
    # signature=None (no sound structural key): traced path, still hits
    exe4 = warm.get_or_build(None, lambda: jax.jit(f).lower(aval))
    assert warm.stats["hits"] == 3
    np.testing.assert_array_equal(np.asarray(exe(x)),
                                  np.asarray(exe4(x)))


def test_verify_mode_catches_poisoned_ref(tmp_path, monkeypatch):
    """PADDLE_COMPILE_CACHE_VERIFY=1: a manifest entry disagreeing with
    the program's real fingerprint is recorded as
    misses{cause=stale_ref}, the CORRECT program is served, and the ref
    is repaired in place."""
    from paddle_tpu.core import monitor
    root = str(tmp_path / "exe")
    store = ExecutableStore(root)

    def f(x):
        return x + 1.0

    def g(x):
        return x * 100.0

    aval = jax.ShapeDtypeStruct((4,), jnp.float32)
    sig_f = dict(kind="f", operands=compile_cache.aval_signature((aval,)))
    store.get_or_build(sig_f, lambda: jax.jit(f).lower(aval))
    key_g = store.key_for(jax.jit(g).lower(aval))
    store.get_or_compile(jax.jit(g).lower(aval))
    # poison the manifest: f's signature now points at g's executable —
    # an unverified lookup would serve the WRONG program
    store._write_ref(
        compile_cache._signature_key(sig_f, None), key_g)
    x = jnp.ones((4,), jnp.float32)
    lied = ExecutableStore(root).get_or_build(
        sig_f, lambda: jax.jit(f).lower(aval))
    assert float(np.asarray(lied(x))[0]) == 100.0   # the lie, shown

    monkeypatch.setenv("PADDLE_COMPILE_CACHE_VERIFY", "1")
    fixed = ExecutableStore(root)
    monitor.enable()
    try:
        s0 = _counter("jit.compile_cache.misses{cause=stale_ref}")
        exe = fixed.get_or_build(sig_f, lambda: jax.jit(f).lower(aval))
        assert _counter(
            "jit.compile_cache.misses{cause=stale_ref}") - s0 == 1
    finally:
        monitor.disable()
    assert float(np.asarray(exe(x))[0]) == 2.0      # truth restored
    # the ref was repaired: a clean unverified lookup is correct now
    monkeypatch.delenv("PADDLE_COMPILE_CACHE_VERIFY")
    healed = ExecutableStore(root).get_or_build(
        sig_f, lambda: (_ for _ in ()).throw(
            AssertionError("repaired ref must resolve tracelessly")))
    assert float(np.asarray(healed(x))[0]) == 2.0


# ------------------------------------------------- THE warm-restart gate


def _run_traffic(engine):
    from paddle_tpu.serving import RequestParams
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 512, n).astype(np.int32)
               for n in (5, 12, 20)]
    handles = [engine.submit(p, RequestParams(max_new_tokens=6))
               for p in prompts]
    while engine.busy:
        engine.step()
    return [h.tokens for h in handles]


def test_warm_restart_gate_serving(tiny_gpt, tmp_path):
    """THE tier-1 gate: one cold warmup populates the store; a rebuilt
    engine in a cleared-jax-cache state loads EVERY program from the
    store — jit.compile_cache.hits == program count, misses == 0, zero
    XLA compiles — and serves traffic bitwise-equal to the cold
    reference."""
    from paddle_tpu.core import monitor
    from paddle_tpu.serving import ServingEngine
    root = str(tmp_path / "exe")
    n_programs = 2 + 3   # one prefill per bucket + decode/admit/free

    cold_store = ExecutableStore(root)
    cold = ServingEngine(_serve_cfg(tiny_gpt), poll_every=2,
                         executable_store=cold_store)
    assert cold_store.stats["misses"] == n_programs
    assert cold_store.stats["hits"] == 0
    assert len(cold_store) == n_programs       # all persisted
    assert len(cold_store.refs()) == n_programs  # manifest written too
    ref = _run_traffic(cold)
    assert cold_store.stats["misses"] == n_programs  # no compile under
    #                                                  traffic either

    # "relaunch": drop every in-memory trace/compile cache; only the
    # on-disk store survives — exactly what a fresh process sees
    jax.clear_caches()
    warm_store = ExecutableStore(root)
    monitor.enable()
    try:
        h0 = _counter("jit.compile_cache.hits")
        m0 = _counter("jit.compile_cache.misses")
        warm = ServingEngine(_serve_cfg(tiny_gpt), poll_every=2,
                             executable_store=warm_store)
        assert _counter("jit.compile_cache.hits") - h0 == n_programs
        assert _counter("jit.compile_cache.misses") - m0 == 0
    finally:
        monitor.disable()
    assert warm_store.stats["hits"] == n_programs
    assert warm_store.stats["misses"] == 0     # zero XLA compiles
    out = _run_traffic(warm)
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)    # bitwise-equal serving


def test_predictor_bucket_build_warm(tiny_gpt, tmp_path):
    """The Predictor's per-bucket (prefill, decode) AOT build loads
    from the store on relaunch and generates identically."""
    from paddle_tpu.inference import create_predictor
    store = ExecutableStore(str(tmp_path / "exe"))
    prev = compile_cache.set_default_store(store)
    try:
        p1 = create_predictor(
            _serve_cfg(tiny_gpt, buckets=(16,), max_batch=2))
        assert store.stats["misses"] == 2   # prefill + decode
        ref = p1.generate([[1, 2, 3]], max_new_tokens=4, seed=0)

        jax.clear_caches()
        store2 = ExecutableStore(store.root)
        compile_cache.set_default_store(store2)
        p2 = create_predictor(
            _serve_cfg(tiny_gpt, buckets=(16,), max_batch=2))
        assert store2.stats["hits"] == 2
        assert store2.stats["misses"] == 0
        out = p2.generate([[1, 2, 3]], max_new_tokens=4, seed=0)
        np.testing.assert_array_equal(ref[0], out[0])
    finally:
        compile_cache.set_default_store(prev)


def test_trainstep_warm_start(tmp_path):
    """The fit(resume=True) warm path: a rebuilt TrainStep loads the
    fused-step executable (hits == 1, misses == 0), its first loss is
    bitwise-equal to the cold run's, and a drifted operand signature
    falls back to the jit path instead of erroring."""
    from paddle_tpu import nn, optimizer

    def build():
        paddle.seed(11)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                          nn.Linear(16, 4))
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=m.parameters())
        ce = nn.CrossEntropyLoss()
        return paddle.jit.TrainStep(m, opt, lambda out, lbl: ce(out, lbl))

    rng = np.random.RandomState(0)
    xa = rng.randn(4, 8).astype(np.float32)
    ya = rng.randint(0, 4, (4,)).astype(np.int64)

    store = ExecutableStore(str(tmp_path / "exe"))
    step = build().enable_warm_start(store)
    cold = float(step(paddle.to_tensor(xa), paddle.to_tensor(ya)))
    assert store.stats["misses"] == 1 and store.stats["saves"] == 1

    jax.clear_caches()
    store2 = ExecutableStore(store.root)
    step2 = build().enable_warm_start(store2)
    warm = float(step2(paddle.to_tensor(xa), paddle.to_tensor(ya)))
    assert store2.stats["hits"] == 1 and store2.stats["misses"] == 0
    assert warm == cold     # identical init (same seed) + same program
    assert step2._warm_exe is not None
    # steps keep dispatching the warmed executable...
    float(step2(paddle.to_tensor(xa), paddle.to_tensor(ya)))
    assert step2._warm_exe is not None
    # ...until the operand signature drifts: clean fallback to jit
    xb = rng.randn(6, 8).astype(np.float32)
    yb = rng.randint(0, 4, (6,)).astype(np.int64)
    drift = float(step2(paddle.to_tensor(xb), paddle.to_tensor(yb)))
    assert np.isfinite(drift) and step2._warm_exe is None


def test_trainstep_warm_multi_step_loss_curve(tmp_path):
    """Repeated dispatch of a warm-loaded fused step — the bug class
    this pins: a serialized executable REPLAYS its donation aliasing
    on load, and deserialized-on-CPU aliasing double-frees the donated
    buffers (heap corruption on the second call). The AOT path bakes
    donation only where the backend implements it, so a warm relaunch
    replays the cold run's loss curve bitwise."""
    from paddle_tpu import optimizer
    from paddle_tpu.models.gpt import gpt

    def losses(store):
        paddle.seed(5)
        m = gpt("test-tiny", max_position_embeddings=32)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=m.parameters())
        step = paddle.jit.TrainStep(m, opt,
                                    lambda lg, y: m.loss(lg, y))
        step.enable_warm_start(store)
        ids = np.random.RandomState(0).randint(
            0, m.cfg.vocab_size, (2, 32)).astype(np.int32)
        x = paddle.to_tensor(ids)
        y = paddle.to_tensor(ids.astype(np.int64))
        return [float(step(x, y)) for _ in range(4)]

    root = str(tmp_path / "exe")
    cold = losses(ExecutableStore(root))
    assert cold[-1] < cold[0]          # it actually trains
    jax.clear_caches()
    store = ExecutableStore(root)
    warm = losses(store)
    assert store.stats["hits"] == 1 and store.stats["misses"] == 0
    assert warm == cold                # bitwise-equal 4-step curve


def test_distributed_trainstep_warm_start(tmp_path):
    """The sharded step's warm path on the 8-device CPU mesh: a rebuilt
    DistributedTrainStep loads its executable (hits == 1, misses == 0)
    and replays the cold loss curve bitwise; the mesh axes are part of
    the key."""
    from paddle_tpu import distributed as dist, nn, optimizer
    from paddle_tpu.distributed import fleet
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    root = str(tmp_path / "exe")
    rng = np.random.RandomState(3)
    xs = rng.randn(16, 8).astype(np.float32)
    ys = rng.randn(16, 2).astype(np.float32)
    try:
        fleet.init(strategy=fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 8}))

        def losses(store):
            paddle.seed(7)
            m = nn.Linear(8, 2)
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=m.parameters())
            step = fleet.DistributedTrainStep(
                m, opt, nn.functional.mse_loss)
            step.enable_warm_start(store)
            return [float(step(paddle.to_tensor(xs),
                               paddle.to_tensor(ys)))
                    for _ in range(3)]

        cold = losses(ExecutableStore(root))
        assert cold[-1] < cold[0]
        jax.clear_caches()
        store = ExecutableStore(root)
        warm = losses(store)
        assert store.stats["hits"] == 1 and store.stats["misses"] == 0
        assert warm == cold
    finally:
        dist.set_hybrid_communicate_group(None)


def test_fit_resume_enables_warm_start(tmp_path):
    """Model.fit(resume=...) is the opt-in: with a store active, the
    fused step warm-starts (and persists its executable for the next
    relaunch); without resume, fit never touches the store."""
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi import Model
    store = ExecutableStore(str(tmp_path / "exe"))
    prev = compile_cache.set_default_store(store)
    try:
        def build():
            paddle.seed(3)
            net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(),
                                nn.Linear(8, 2))
            m = Model(net)
            m.prepare(optimizer.SGD(learning_rate=0.01,
                                    parameters=net.parameters()),
                      nn.CrossEntropyLoss())
            return m

        rng = np.random.RandomState(0)
        data = [([rng.randn(2, 4).astype(np.float32)],
                 [rng.randint(0, 2, (2,)).astype(np.int64)])
                for _ in range(3)]
        # no resume: the store is never consulted
        build().fit(train_data=data, epochs=1, verbose=0)
        assert store.stats == dict(hits=0, misses=0, saves=0,
                                   bytes_loaded=0, bytes_saved=0)
        # resume (fresh start — no checkpoint yet): warm path active,
        # cold store populated
        m = build()
        m.fit(train_data=data, epochs=1, verbose=0,
              resume=str(tmp_path / "ckpt"))
        assert m._train_step._warm_exe is not None
        assert store.stats["saves"] == 1
        # relaunch: the step executable loads instead of compiling
        m2 = build()
        m2.fit(train_data=data, epochs=1, verbose=0,
               resume=str(tmp_path / "ckpt"))
        assert store.stats["hits"] == 1
    finally:
        compile_cache.set_default_store(prev)


# ------------------------------------------------------------ chaos tier


@pytest.mark.chaos
class TestCorruptEntryFallback:
    """A bad store entry must NEVER crash a relaunch: the load falls
    back to a fresh compile, records misses{cause=corrupt}, drops the
    bad entry, and rewrites a good one (the CheckpointManager
    corruption-fallback idiom applied to executables)."""

    def _seed_store(self, tmp_path):
        store = ExecutableStore(str(tmp_path / "exe"))

        def f(x):
            return (x * 3.0).sum()

        aval = jax.ShapeDtypeStruct((16,), jnp.float32)
        store.get_or_compile(jax.jit(f).lower(aval))
        assert len(store) == 1
        return store, f, aval

    def test_truncated_entry_recompiles_and_rewrites(self, tmp_path):
        from paddle_tpu.core import monitor
        from paddle_tpu.utils import fault_injection as fi
        store, f, aval = self._seed_store(tmp_path)
        fi.truncate_executable(store, keep_bytes=7)  # torn write
        monitor.enable()
        try:
            c0 = _counter("jit.compile_cache.misses{cause=corrupt}")
            exe = store.get_or_compile(jax.jit(f).lower(aval))
            assert _counter(
                "jit.compile_cache.misses{cause=corrupt}") - c0 == 1
        finally:
            monitor.disable()
        x = jnp.arange(16, dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(exe(x)),
                                      np.asarray(jax.jit(f)(x)))
        # a good entry was rewritten: the next relaunch hits clean
        store2 = ExecutableStore(store.root)
        assert store2.load(
            store2.key_for(jax.jit(f).lower(aval))) is not None
        assert store2.stats["hits"] == 1 and store2.stats["misses"] == 0

    def test_bitflipped_entry_checksum_catches(self, tmp_path):
        from paddle_tpu.utils import fault_injection as fi
        store, f, aval = self._seed_store(tmp_path)
        fi.corrupt_executable(store)                 # bit rot in payload
        fresh = ExecutableStore(store.root)
        key = fresh.key_for(jax.jit(f).lower(aval))
        assert fresh.load(key) is None               # checksum caught it
        assert fresh.stats["misses"] == 1
        assert len(fresh) == 0                       # bad entry dropped
        # the recompile path still produces a working executable
        exe = fresh.get_or_compile(jax.jit(f).lower(aval))
        x = jnp.ones((16,), jnp.float32)
        np.testing.assert_array_equal(np.asarray(exe(x)),
                                      np.asarray(jax.jit(f)(x)))
