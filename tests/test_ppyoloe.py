"""PP-YOLOE detector tests (BASELINE config #5; reference:
PaddleDetection ppyoloe test suite analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import ppyoloe as Y


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    m = Y.ppyoloe_s(num_classes=4)
    return m


def test_forward_levels(model):
    model.eval()
    outs = model(paddle.randn([2, 3, 128, 128]))
    strides = [o[3] for o in outs]
    assert strides == [8, 16, 32]
    for cls, reg, centers, stride in outs:
        hw = (128 // stride) ** 2
        assert tuple(cls.shape) == (2, hw, 4)
        assert tuple(reg.shape) == (2, hw, 4, 17)
        assert centers.shape == (hw, 2)


def test_decode_boxes_geometry(model):
    model.eval()
    outs = model(paddle.randn([1, 3, 64, 64]))
    boxes, scores = Y.decode_boxes(outs)
    b = np.asarray(boxes)
    assert b.shape[-1] == 4
    # boxes are centered on their anchors: x1 <= cx <= x2
    centers = np.concatenate([np.asarray(o[2]) for o in outs], 0)
    assert (b[0, :, 0] <= centers[:, 0] + 1e-3).all()
    assert (b[0, :, 2] >= centers[:, 0] - 1e-3).all()
    s = np.asarray(scores)
    assert (s >= 0).all() and (s <= 1).all()


def test_loss_finite_and_positive(model):
    model.train()
    outs = model(paddle.randn([2, 3, 64, 64]))
    gt_boxes = paddle.to_tensor(np.array(
        [[[4.0, 4, 40, 40], [10, 10, 30, 50]],
         [[8.0, 8, 56, 56], [0, 0, 0, 0]]], np.float32))
    gt_labels = paddle.to_tensor(np.array([[0, 2], [1, 0]], np.int64))
    gt_mask = paddle.to_tensor(np.array([[1, 1], [1, 0]], np.float32))
    loss = model.loss(outs, gt_boxes, gt_labels, gt_mask)
    val = float(loss)
    assert np.isfinite(val) and val > 0


@pytest.mark.slow  # ~17s full-detector train compile on CPU: tier-2
def test_train_step_reduces_loss():
    paddle.seed(0)
    from paddle_tpu import optimizer
    from paddle_tpu.jit.api import functional_call
    import jax

    m = Y.PPYOLOE(num_classes=3, width_mult=0.25, depth_mult=0.33)
    names = [n for n, _ in m.named_parameters()]
    params = [p for _, p in m.named_parameters()]
    opt = optimizer.Adam(learning_rate=1e-3, parameters=params)

    imgs = paddle.randn([1, 3, 64, 64])
    gt_boxes = paddle.to_tensor(
        np.array([[[8.0, 8, 48, 48]]], np.float32))
    gt_labels = paddle.to_tensor(np.array([[1]], np.int64))
    gt_mask = paddle.to_tensor(np.array([[1]], np.float32))

    def loss_fn(param_vals):
        from paddle_tpu.core.tensor import Tensor
        outs = functional_call(m, dict(zip(names, param_vals)), imgs)
        return m.loss(outs, gt_boxes, gt_labels, gt_mask)._data

    vg = jax.jit(jax.value_and_grad(
        lambda pv: loss_fn(pv)))
    vals = [p._data for p in params]
    first = None
    state = [opt.init_state_for(p._data) for p in params]
    for step in range(8):
        lv, grads = vg(vals)
        vals, state = opt.apply_gradients(vals, grads, state,
                                          lr=np.float32(1e-3),
                                          step=np.int32(step + 1))
        first = first if first is not None else float(lv)
    assert float(lv) < first


def test_nms_and_predict(model):
    model.eval()
    res = model.predict(paddle.randn([1, 3, 64, 64]),
                        score_thresh=0.0, max_dets=10)
    assert len(res) == 1
    out = res[0]
    assert out["boxes"].shape[1] == 4
    assert len(out["boxes"]) <= 10
    assert (out["scores"][:-1] >= out["scores"][1:]).all()


def test_nms_suppresses_duplicates():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.zeros((3, 2), np.float32)
    scores[:, 0] = [0.9, 0.8, 0.7]
    out = Y._nms_single(boxes, scores, 0.1, 0.5, 10)
    assert len(out["boxes"]) == 2  # overlapping same-class pair merged
    np.testing.assert_allclose(out["scores"], [0.9, 0.7])


def test_repvgg_fuse_preserves_output():
    paddle.seed(0)
    blk = Y.RepVggBlock(8, 8)
    blk.eval()
    x = paddle.randn([1, 8, 6, 6])
    before = blk(x).numpy()
    blk.fuse()
    after = blk(x).numpy()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)


def test_full_model_fuse():
    paddle.seed(0)
    m = Y.PPYOLOE(num_classes=2, width_mult=0.25, depth_mult=0.33)
    m.eval()
    x = paddle.randn([1, 3, 64, 64])
    ref_boxes, ref_scores = Y.decode_boxes(m(x))
    m.fuse()
    boxes, scores = Y.decode_boxes(m(x))
    np.testing.assert_allclose(np.asarray(scores),
                               np.asarray(ref_scores), rtol=1e-3,
                               atol=1e-4)
