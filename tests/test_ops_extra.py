"""Extra tensor ops + functional pad/grid_sample/pixel_shuffle
(reference: test_take_along_axis_op.py, test_put_along_axis_op.py,
test_index_add_op.py, test_searchsorted_op.py, test_pad3d_op.py,
test_grid_sampler_op.py, test_pixel_shuffle.py analogs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _t(a):
    return paddle.to_tensor(np.asarray(a))


def test_take_put_along_axis():
    x = _t(np.arange(12, dtype=np.float32).reshape(3, 4))
    idx = _t(np.array([[0], [2], [1]], np.int32))
    got = paddle.take_along_axis(x, idx, 1)
    np.testing.assert_allclose(got.numpy().ravel(), [0, 6, 9])
    put = paddle.put_along_axis(x, idx, 99.0, 1)
    assert put.numpy()[0, 0] == 99 and put.numpy()[1, 2] == 99
    add = paddle.put_along_axis(x, idx, 1.0, 1, reduce="add")
    assert add.numpy()[0, 0] == 1.0 and add.numpy()[2, 1] == 10.0
    # include_self=False: touched slots start from the reduce identity
    ones = paddle.to_tensor(np.ones((3, 4), np.float32))
    ex = paddle.put_along_axis(ones, idx, 5.0, 1, reduce="add",
                               include_self=False, broadcast=False)
    assert ex.numpy()[0, 0] == 5.0 and ex.numpy()[0, 1] == 1.0
    # mul handles zero/negative values (native scatter-multiply)
    twos = paddle.to_tensor(np.full((3, 4), 2.0, np.float32))
    mul = paddle.put_along_axis(twos, idx, -3.0, 1, reduce="mul",
                                broadcast=False)
    assert mul.numpy()[0, 0] == -6.0
    # mean / amax / amin reduce modes
    mean = paddle.put_along_axis(twos, idx, 4.0, 1, reduce="mean",
                                 broadcast=False)
    assert mean.numpy()[0, 0] == 3.0
    amx = paddle.put_along_axis(twos, idx, 9.0, 1, reduce="amax",
                                broadcast=False)
    assert amx.numpy()[0, 0] == 9.0
    # integer mean keeps input dtype (truncating) instead of promoting
    ints = paddle.to_tensor(np.full((3, 4), 2, np.int32))
    imean = paddle.put_along_axis(ints, idx, 5, 1, reduce="mean",
                                  broadcast=False)
    assert imean.numpy().dtype == np.int32
    assert imean.numpy()[0, 0] == 3  # (2 + 5) / 2 truncated
    # broadcast=True (paddle default): indices broadcast over rows
    brd = paddle.put_along_axis(
        paddle.to_tensor(np.zeros((2, 3), np.float32)),
        paddle.to_tensor(np.array([[1]], np.int32)), 7.0, 1)
    np.testing.assert_allclose(brd.numpy(), [[0, 7, 0], [0, 7, 0]])


def test_masked_fill_index_add_index_fill():
    x = _t(np.zeros((2, 3), np.float32))
    mask = _t(np.array([[1, 0, 0], [0, 0, 1]], bool))
    np.testing.assert_allclose(
        paddle.masked_fill(x, mask, 5.0).numpy(),
        [[5, 0, 0], [0, 0, 5]])
    idx = _t(np.array([0, 2], np.int32))
    out = paddle.index_add(x, idx, 1, _t(np.ones((2, 2), np.float32)))
    np.testing.assert_allclose(out.numpy(), [[1, 0, 1], [1, 0, 1]])
    out2 = paddle.index_fill(x, idx, 1, 7.0)
    np.testing.assert_allclose(out2.numpy(), [[7, 0, 7], [7, 0, 7]])


def test_repeat_interleave_kron_trace_diagonal_lerp_diff():
    x = _t(np.array([[1.0, 2], [3, 4]], np.float32))
    np.testing.assert_allclose(
        paddle.repeat_interleave(x, 2, axis=0).numpy(),
        np.repeat(x.numpy(), 2, axis=0))
    np.testing.assert_allclose(paddle.kron(x, x).numpy(),
                               np.kron(x.numpy(), x.numpy()))
    assert float(paddle.trace(x)) == 5.0
    np.testing.assert_allclose(paddle.diagonal(x).numpy(), [1, 4])
    np.testing.assert_allclose(
        paddle.lerp(_t(np.zeros(3, np.float32)),
                    _t(np.ones(3, np.float32)), 0.25).numpy(), 0.25)
    np.testing.assert_allclose(
        paddle.diff(_t(np.array([1.0, 4, 9], np.float32))).numpy(),
        [3, 5])


def test_searchsorted_and_bucketize():
    seq = _t(np.array([1.0, 3.0, 5.0, 7.0], np.float32))
    vals = _t(np.array([0.0, 3.0, 8.0], np.float32))
    np.testing.assert_array_equal(
        paddle.searchsorted(seq, vals).numpy(), [0, 1, 4])
    np.testing.assert_array_equal(
        paddle.searchsorted(seq, vals, right=True).numpy(), [0, 2, 4])
    np.testing.assert_array_equal(
        paddle.bucketize(vals, seq).numpy(), [0, 1, 4])
    # batched rows
    seq2 = _t(np.array([[1.0, 2, 3], [10, 20, 30]], np.float32))
    vals2 = _t(np.array([[1.5, 2.5], [15.0, 25.0]], np.float32))
    np.testing.assert_array_equal(
        paddle.searchsorted(seq2, vals2).numpy(), [[1, 2], [1, 2]])


def test_pixel_shuffle_roundtrip():
    x = _t(np.random.RandomState(0).rand(2, 8, 3, 3).astype(np.float32))
    up = paddle.pixel_shuffle(x, 2)
    assert tuple(up.shape) == (2, 2, 6, 6)
    back = paddle.pixel_unshuffle(up, 2)
    np.testing.assert_allclose(back.numpy(), x.numpy())


def test_f_pad_modes():
    x = _t(np.arange(8, dtype=np.float32).reshape(1, 2, 2, 2))
    assert tuple(F.pad(x, [1, 1, 1, 1]).shape) == (1, 2, 4, 4)
    ref = np.pad(x.numpy(), [(0, 0), (0, 0), (1, 1), (1, 1)],
                 mode="reflect")
    np.testing.assert_allclose(
        F.pad(x, [1, 1, 1, 1], mode="reflect").numpy(), ref)
    rep = F.pad(x, [2, 0], mode="replicate")  # 1 spatial pair -> last dim
    assert tuple(rep.shape) == (1, 2, 2, 4)
    np.testing.assert_allclose(rep.numpy()[..., 0], x.numpy()[..., 0])


def test_grid_sample_identity_and_shift():
    rng = np.random.RandomState(0)
    x = _t(rng.rand(1, 3, 5, 5).astype(np.float32))
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                         indexing="ij")
    grid = _t(np.stack([xs, ys], -1)[None].astype(np.float32))
    out = F.grid_sample(x, grid, align_corners=True)
    np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-5)
    # zeros padding outside
    far = _t(np.full((1, 2, 2, 2), 3.0, np.float32))
    out2 = F.grid_sample(x, far, padding_mode="zeros")
    np.testing.assert_allclose(out2.numpy(), 0.0)
    # nearest mode
    outn = F.grid_sample(x, grid, mode="nearest")
    np.testing.assert_allclose(outn.numpy(), x.numpy(), atol=1e-5)


def test_grid_sample_grad_flows():
    x = _t(np.random.RandomState(1).rand(1, 1, 4, 4).astype(np.float32))
    x.stop_gradient = False
    ys, xs = np.meshgrid(np.linspace(-0.5, 0.5, 3),
                         np.linspace(-0.5, 0.5, 3), indexing="ij")
    grid = _t(np.stack([xs, ys], -1)[None].astype(np.float32))
    out = F.grid_sample(x, grid)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()


def test_cumulative_and_nan_ops():
    x = _t(np.array([3.0, 1.0, 4.0, 1.0, 5.0], np.float32))
    v, i = paddle.cummax(x)
    np.testing.assert_allclose(v.numpy(), [3, 3, 4, 4, 5])
    np.testing.assert_array_equal(i.numpy(), [0, 0, 2, 2, 4])
    v2, i2 = paddle.cummin(x)
    np.testing.assert_allclose(v2.numpy(), [3, 1, 1, 1, 1])
    np.testing.assert_array_equal(i2.numpy(), [0, 1, 1, 1, 1])
    np.testing.assert_allclose(
        paddle.logcumsumexp(x).numpy(),
        np.logaddexp.accumulate(x.numpy()), rtol=1e-4)
    # axis=None on 2-D flattens (paddle semantics)
    m2 = _t(np.random.RandomState(0).rand(2, 3).astype(np.float32))
    assert paddle.logcumsumexp(m2).numpy().shape == (6,)
    # take modes
    t = _t(np.arange(6))
    np.testing.assert_array_equal(
        paddle.take(t, _t(np.array([7, -8], np.int32)),
                    mode="wrap").numpy(), [1, 4])
    with pytest.raises(IndexError):
        paddle.take(t, _t(np.array([9], np.int32)))
    m = _t(np.array([[1.0, np.nan], [2.0, 3.0]], np.float32))
    assert float(paddle.nanmean(m)) == pytest.approx(2.0)
    assert float(paddle.nansum(m)) == pytest.approx(6.0)
    np.testing.assert_allclose(paddle.frac(_t(np.array([1.5, -1.5]))).numpy(),
                               [0.5, -0.5])
    np.testing.assert_allclose(
        paddle.hypot(_t(np.array([3.0])), _t(np.array([4.0]))).numpy(),
        [5.0])


def test_take_and_index_sample():
    x = _t(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(
        paddle.take(x, _t(np.array([0, 4], np.int32))).numpy(), [0, 4])
    np.testing.assert_allclose(
        paddle.index_sample(x, _t(np.array([[2], [0]], np.int32)))
        .numpy().ravel(), [2, 3])
    np.testing.assert_allclose(
        paddle.vander(_t(np.array([1.0, 2.0], np.float32)), n=3).numpy(),
        np.vander(np.array([1.0, 2.0]), N=3))
