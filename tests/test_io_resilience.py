"""Chaos tests for the supervised data pipeline (io/dataloader.py),
driven by the deterministic fault-injection harness: a dead worker is
respawned with an identical batch stream, a wedged worker surfaces as
WatchdogTimeout (stack dump included) instead of stalling, bad samples
are quarantined and counted, and a preemption mid-epoch resumes from
the per-step checkpoint replaying the exact remaining batches."""
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import auto_checkpoint as ac
from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.distributed.elastic import ELASTIC_EXIT_CODE
from paddle_tpu.hapi import Model
from paddle_tpu.io import DataLoader, DataLoaderWorkerError
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.io.sampler import BatchSampler, RandomSampler
from paddle_tpu.profiler import metrics
from paddle_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_registry():
    """Emergency savers are process-global; never leak between tests."""
    yield
    resilience._EMERGENCY.clear()
    resilience._ACTIVE.clear()


@pytest.fixture(autouse=True)
def _metrics_on():
    was = metrics.is_enabled()
    metrics.enable()
    yield
    if not was:
        metrics.disable()


def _counter(name):
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


class ArangeDataset(Dataset):
    """dataset[i] = (f(i) vector, i) — every batch's content names its
    sample indices, so stream comparisons are bitwise-meaningful.
    ``delay`` throttles each fetch so the prefetch pipeline is still in
    flight when a test injects its fault (samples are tiny; without it
    the whole epoch is produced before the fault lands)."""

    def __init__(self, n, delay=0.0):
        self.n = n
        self.delay = delay

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if self.delay:
            time.sleep(self.delay)
        return (np.arange(4, dtype=np.float32) + 10.0 * i, np.int64(i))


class _GatedArange(ArangeDataset):
    """ArangeDataset whose ``gate_sample`` blocks while ``gate_file``
    exists (bounded by a 20s safety cap). The gate crosses the fork
    boundary — worker processes see the same filesystem — so a test can
    PIN a chosen batch in flight until its fault lands, instead of
    racing the prefetch pipeline."""

    def __init__(self, n, delay=0.0, gate_sample=None, gate_file=None):
        super().__init__(n, delay=delay)
        self.gate_sample = gate_sample
        self.gate_file = gate_file

    def __getitem__(self, i):
        if i == self.gate_sample and self.gate_file:
            t0 = time.monotonic()
            while os.path.exists(self.gate_file) and \
                    time.monotonic() - t0 < 20.0:
                time.sleep(0.005)
        return super().__getitem__(i)


def _arrs(batch):
    return np.asarray(batch[0].numpy())


# -------------------------------------------- worker death -> respawn

def test_worker_sigkill_respawns_and_stream_identical():
    ds = ArangeDataset(40, delay=0.02)
    ref = [_arrs(b) for b in DataLoader(ds, batch_size=4, shuffle=False,
                                        num_workers=0)]
    dl = DataLoader(ds, batch_size=4, shuffle=False, num_workers=2)
    before = _counter("io.worker.respawns")
    it = iter(dl)
    got = [_arrs(next(it))]
    time.sleep(0.2)  # let the pipeline fill so a batch is in flight
    fi.kill_worker(dl, worker_id=0)
    got += [_arrs(b) for b in it]
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)
    assert _counter("io.worker.respawns") > before
    assert _counter("io.worker.deaths") >= 1


def test_worker_death_past_respawn_budget_raises():
    ds = ArangeDataset(400, delay=0.01)
    dl = DataLoader(ds, batch_size=2, shuffle=False, num_workers=2,
                    worker_respawn_limit=0)
    it = iter(dl)
    next(it)
    fi.kill_worker(dl, worker_id=0)
    with pytest.raises(DataLoaderWorkerError, match="respawn budget"):
        list(it)
    assert it._pool is None  # error path reaped the pool


# ------------------------------------------------ wedged -> watchdog

def test_wedged_worker_surfaces_watchdog_timeout(capfd):
    ds = ArangeDataset(40, delay=0.02)
    dl = DataLoader(ds, batch_size=4, shuffle=False, num_workers=2,
                    timeout=1.0)
    before = _counter("resilience.watchdog.timeouts{label=io.fetch}")
    it = iter(dl)
    next(it)
    time.sleep(0.2)
    pid = fi.suspend_worker(dl, worker_id=1)
    t0 = time.monotonic()
    with pytest.raises(resilience.WatchdogTimeout, match="wedged"):
        list(it)
    assert time.monotonic() - t0 < 10.0  # surfaced, not stalled
    err = capfd.readouterr().err
    assert "Watchdog 'io.fetch' expired" in err
    assert "thread" in err  # the stack dump
    assert _counter(
        "resilience.watchdog.timeouts{label=io.fetch}") == before + 1
    it.close()  # reaps the SIGSTOPped worker via SIGKILL
    assert it._pool is None
    fi.resume_worker(pid)  # no-op: already reaped


# ------------------------------------------- bad sample -> quarantine

@pytest.mark.parametrize("num_workers", [0, 2])
def test_bad_samples_quarantined_with_metric(num_workers):
    ds = fi.FlakySamples(ArangeDataset(16), raise_at={5}, nan_at={9})
    before = _counter("io.sample.quarantined")
    dl = DataLoader(ds, batch_size=4, shuffle=False,
                    num_workers=num_workers, skip_bad_samples=True)
    batches = list(dl)
    total = sum(int(_arrs(b).shape[0]) for b in batches)
    assert total == 14  # two samples dropped, batches stay in order
    assert sorted(i for i, _ in dl.quarantined) == [5, 9]
    reasons = dict(dl.quarantined)
    assert "ValueError" in reasons[5]
    assert "non-finite" in reasons[9]
    assert _counter("io.sample.quarantined") == before + 2


@pytest.mark.parametrize("num_workers", [0, 2])
def test_bad_sample_error_attribution_without_quarantine(num_workers):
    ds = fi.FlakySamples(ArangeDataset(16), raise_at={5})
    dl = DataLoader(ds, batch_size=4, shuffle=False,
                    num_workers=num_workers)
    it = iter(dl)
    with pytest.raises(DataLoaderWorkerError) as ei:
        list(it)
    assert ei.value.sample_index == 5
    assert 5 in ei.value.batch_indices
    assert "FlakySamples" in str(ei.value)  # worker traceback included
    assert it._pool is None
    it.close()  # idempotent on an already-closed iterator
    it.close()


# ---------------------------- acceptance e2e: kill + preempt + resume

def test_kill_then_preempt_resume_replays_exact_batches(tmp_path):
    """The ISSUE acceptance path: a worker is SIGKILLed at a fixed step
    (respawn keeps the stream identical), the job is preempted (SIGTERM)
    two steps later, the per-step emergency checkpoint carries the
    loader state, and the relaunched job replays the exact remaining
    batch sequence — bitwise equal, <=1 step lost — with
    io.worker.respawns and io.sample.quarantined recorded."""
    # Deterministic kill window: batch 4 is worker 0's first batch the
    # consumer has NOT yet received at the kill step (round-robin:
    # batch i -> worker i % 2), so its first sample blocks on a gate
    # file until the kill lands. Without the gate, a fast machine
    # prefetches batch 4 before the kill and the stream finishes
    # without ever NEEDING the respawn (flaky respawn-counter assert).
    gate_file = str(tmp_path / "b4.gate")
    probe = RandomSampler(ArangeDataset(48), generator=123)
    first_of_b4 = list(probe)[16]  # epoch-0 permutation, position 16
    base = fi.FlakySamples(
        _GatedArange(48, delay=0.01, gate_sample=first_of_b4,
                     gate_file=gate_file), nan_at={7})

    def make_loader():
        sampler = RandomSampler(base, generator=123)
        bs = BatchSampler(base, sampler=sampler, batch_size=4)
        return DataLoader(base, batch_sampler=bs, num_workers=2,
                          skip_bad_samples=True, worker_respawn_limit=2)

    # uninterrupted reference stream (same seed -> same permutation;
    # the gate file does not exist yet, so nothing blocks)
    ref = [_arrs(b) for b in make_loader()]
    assert len(ref) == 12

    respawns0 = _counter("io.worker.respawns")
    quarantined0 = _counter("io.sample.quarantined")

    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
    with open(gate_file, "w"):
        pass  # arm the gate: batch 4 now stalls until the kill step
    loader = make_loader()
    step_box = {"step": -1}
    mgr.save_on_preemption(
        lambda: {"step": step_box["step"], "loader": loader.state_dict()})
    kill = fi.KillAfter(6, signal.SIGTERM)  # SIGTERM lands on step 5
    seen = []
    with pytest.raises(SystemExit) as exc:
        with resilience.GracefulShutdown():
            for step, batch in enumerate(loader):
                seen.append(_arrs(batch))
                step_box["step"] = step
                if step == 3:
                    # worker 0 is gated on batch 4: it dies holding it,
                    # and only the RESPAWNED worker (gate lifted) can
                    # deliver steps 4-5
                    fi.kill_worker(loader, worker_id=0)
                    os.remove(gate_file)
                kill.step()
                resilience.poll(step)  # step 5: emergency save + exit
    assert exc.value.code == ELASTIC_EXIT_CODE
    assert len(seen) == 6  # steps 0..5 completed
    # the mid-stream worker kill changed nothing
    for a, b in zip(seen, ref):
        np.testing.assert_array_equal(a, b)
    assert _counter("io.worker.respawns") > respawns0
    assert _counter("io.sample.quarantined") > quarantined0

    # ------------------------------------------------ "relaunch"
    resilience._EMERGENCY.clear()
    loader2 = make_loader()
    state = mgr.restore()
    assert int(np.asarray(getattr(state["step"], "data",
                                  state["step"]))) == 5
    loader2.load_state_dict(state["loader"])
    remaining = [_arrs(b) for b in loader2]
    # <=1 step lost: everything after the 6 consumed batches replays
    assert len(remaining) == len(ref) - 6
    for a, b in zip(remaining, ref[6:]):
        np.testing.assert_array_equal(a, b)
    mgr.close()


# --------------------------- train_epoch_range mid-epoch loader resume

def _env(tmp_path, monkeypatch, job, interval="1"):
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_JOB_ID", job)
    monkeypatch.setenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", str(tmp_path))
    monkeypatch.setenv("PADDLE_EDL_SAVE_CHECKPOINT_INTER", interval)


def test_train_epoch_range_midepoch_step_resume(tmp_path, monkeypatch):
    """Preempt INSIDE an epoch (per-step resilience.poll): the emergency
    checkpoint carries the loader cursor, and the relaunched range
    re-enters the interrupted epoch with only its remaining batches —
    interrupted-run + resumed-run batches == the uninterrupted stream."""
    _env(tmp_path, monkeypatch, "chaos-io-mid")
    ds = ArangeDataset(40)  # 10 batches/epoch

    def make_loader():
        sampler = RandomSampler(ds, generator=7)
        bs = BatchSampler(ds, sampler=sampler, batch_size=4)
        return DataLoader(ds, batch_sampler=bs, num_workers=0)

    ref = []
    ref_loader = make_loader()
    for _ in range(3):
        ref += [_arrs(b) for b in ref_loader]

    loader = make_loader()
    status = ac.ExeTrainStatus()
    kill = fi.KillAfter(6, signal.SIGTERM)  # fires at epoch 0, step 5
    consumed = []
    with pytest.raises(SystemExit) as exc:
        for epoch in ac.train_epoch_range(3, status=status, loader=loader):
            for step, batch in enumerate(loader):
                consumed.append(_arrs(batch))
                kill.step()
                resilience.poll(step)  # per-STEP preemption boundary
    assert exc.value.code == ELASTIC_EXIT_CODE
    assert len(consumed) == 6

    # relaunch: fresh loader + status, same env
    resilience._EMERGENCY.clear()
    loader2 = make_loader()
    status2 = ac.ExeTrainStatus()
    epochs2 = []
    for epoch in ac.train_epoch_range(3, status=status2, loader=loader2):
        epochs2.append(epoch)
        for batch in loader2:
            consumed.append(_arrs(batch))
    assert epochs2 == [0, 1, 2]  # re-entered the interrupted epoch
    assert len(consumed) == len(ref)
    for a, b in zip(consumed, ref):
        np.testing.assert_array_equal(a, b)


# --------------------------------------- hapi fit mid-epoch resume

def test_fit_preemption_resumes_mid_epoch(tmp_path):
    """Model.fit preempted mid-epoch writes emergency.pdstate (epoch,
    step, loader cursor); fit(resume=True) re-enters the interrupted
    epoch and trains only its remaining batches."""
    class XYDataset(Dataset):
        def __init__(self, n=32):
            rng = np.random.RandomState(0)
            self.x = rng.randn(n, 4).astype(np.float32)
            self.y = rng.randint(0, 2, (n,)).astype(np.int64)

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    def make(seed):
        paddle.seed(seed)
        net = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
        m = Model(net)
        m.prepare(optimizer.SGD(learning_rate=0.01,
                                parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss())
        return m

    def make_loader():
        ds = XYDataset()
        bs = BatchSampler(ds, sampler=RandomSampler(ds, generator=11),
                          batch_size=4)
        return DataLoader(ds, batch_sampler=bs)  # 8 batches/epoch

    from paddle_tpu.hapi.callbacks import Callback

    # on_train_batch_end observes the lagged loss: with the default
    # async window (PADDLE_ASYNC_STEPS=2) the 4th callback fires while
    # batch index 5 is in flight, so the emergency save records the
    # last fully-executed step: 6 steps launched+synced, cursor 6
    kill = fi.KillAfter(4, signal.SIGTERM)

    class Chaos(Callback):
        def on_train_batch_end(self, step, logs=None):
            kill.step()

    save_dir = str(tmp_path / "ckpts")
    m = make(0)
    with pytest.raises(SystemExit) as exc:
        with resilience.GracefulShutdown():
            m.fit(train_data=make_loader(), epochs=2, save_dir=save_dir,
                  verbose=0, callbacks=[Chaos()])
    assert exc.value.code == ELASTIC_EXIT_CODE
    from paddle_tpu import framework_io
    state = framework_io.load(os.path.join(save_dir,
                                           "emergency.pdstate"))
    assert state["epoch"] == 0 and state["step"] == 6
    assert state["loader"]["cursor"] == 6

    # relaunch: fresh model + loader; resume=True picks up the state
    resilience._EMERGENCY.clear()
    m2 = make(1)

    class CountSteps(Callback):
        per_epoch = {}

        def on_epoch_begin(self, epoch, logs=None):
            self._epoch = epoch
            self.per_epoch[epoch] = 0

        def on_train_batch_end(self, step, logs=None):
            self.per_epoch[self._epoch] += 1

    m2.fit(train_data=make_loader(), epochs=2, save_dir=save_dir,
           verbose=0, callbacks=[CountSteps()], resume=True)
    # epoch 0 replays only its 2 remaining batches; epoch 1 runs all 8
    # (the epoch-end drain flushes the lag window, so every replayed
    # batch still gets its on_train_batch_end)
    assert CountSteps.per_epoch == {0: 2, 1: 8}
