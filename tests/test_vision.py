"""Vision package tests: model zoo forward shapes, transforms,
datasets (MNIST idx files, CIFAR pickles, folders, FakeData).

Mirrors the reference's test_vision_models.py / test_transforms.py /
test_datasets.py (python/paddle/tests/)."""
import gzip
import os
import pickle
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets as D
from paddle_tpu.vision import models as M
from paddle_tpu.vision import transforms as T


# ------------------------------------------------------------------ models
@pytest.mark.parametrize("factory", [
    M.vgg11, M.alexnet, M.mobilenet_v1,
    # mobilenet_v3_small / densenet121 / googlenet compile 13-24s
    # each on CPU: tier-2 (slow) to keep the suite under budget
    pytest.param(M.mobilenet_v3_small, marks=pytest.mark.slow),
    pytest.param(M.mobilenet_v3_large, marks=pytest.mark.slow),
    pytest.param(M.mobilenet_v2, marks=pytest.mark.slow),
    pytest.param(M.squeezenet1_0, marks=pytest.mark.slow),
    pytest.param(M.shufflenet_v2_x1_0, marks=pytest.mark.slow),
    pytest.param(M.densenet121, marks=pytest.mark.slow),
    pytest.param(M.googlenet, marks=pytest.mark.slow),
    pytest.param(M.resnext50_32x4d, marks=pytest.mark.slow),
    M.wide_resnet50_2,
])
def test_model_forward_shape(factory):
    paddle.seed(0)
    m = factory(num_classes=5)
    m.eval()
    out = m(paddle.randn([2, 3, 96, 96]))
    assert tuple(out.shape) == (2, 5)


@pytest.mark.slow  # ~12s compile on CPU: tier-2
def test_inception_v3_forward():
    m = M.inception_v3(num_classes=4)
    m.eval()
    assert tuple(m(paddle.randn([1, 3, 299, 299])).shape) == (1, 4)


def test_vgg_batch_norm_variant():
    m = M.vgg11(batch_norm=True, num_classes=3)
    m.eval()
    assert tuple(m(paddle.randn([1, 3, 64, 64])).shape) == (1, 3)


# -------------------------------------------------------------- transforms
def test_to_tensor_and_normalize():
    img = (np.random.RandomState(0).rand(8, 6, 3) * 255).astype(np.uint8)
    t = T.Compose([T.ToTensor(),
                   T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)])
    out = t(img)
    assert out.shape == (3, 8, 6)
    assert out.dtype == np.float32
    assert out.min() >= -1.0 - 1e-6 and out.max() <= 1.0 + 1e-6


def test_resize_center_crop():
    img = (np.random.RandomState(1).rand(20, 30, 3) * 255).astype(np.uint8)
    assert T.resize(img, (10, 15)).shape == (10, 15, 3)
    assert T.resize(img, 10).shape[0] == 10  # short side
    assert T.center_crop(img, 12).shape == (12, 12, 3)


def test_flips_and_pad():
    img = np.arange(12, dtype=np.uint8).reshape(3, 4, 1)
    np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
    np.testing.assert_array_equal(T.vflip(img), img[::-1])
    padded = T.pad(img, 2)
    assert padded.shape == (7, 8, 1)


def test_random_transforms_shapes():
    img = (np.random.RandomState(2).rand(32, 32, 3) * 255).astype(np.uint8)
    assert T.RandomCrop(16)(img).shape == (16, 16, 3)
    assert T.RandomResizedCrop(24)(img).shape == (24, 24, 3)
    assert T.RandomHorizontalFlip(1.0)(img).shape == img.shape
    assert T.ColorJitter(0.4, 0.4, 0.4, 0.1)(img).shape == img.shape
    assert T.Grayscale(3)(img).shape == img.shape
    assert T.RandomRotation(30)(img).shape == img.shape


# ---------------------------------------------------------------- datasets
def _write_mnist(tmp_path, n=10, gz=False):
    rng = np.random.RandomState(0)
    images = (rng.rand(n, 28, 28) * 255).astype(np.uint8)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    op = (lambda p: gzip.open(p, "wb")) if gz else \
        (lambda p: open(p, "wb"))
    suffix = ".gz" if gz else ""
    with op(os.path.join(tmp_path, "train-images-idx3-ubyte" + suffix)) as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with op(os.path.join(tmp_path, "train-labels-idx1-ubyte" + suffix)) as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return images, labels


def test_mnist_idx_files(tmp_path):
    images, labels = _write_mnist(str(tmp_path))
    ds = D.MNIST(data_dir=str(tmp_path), mode="train")
    assert len(ds) == 10
    img, lbl = ds[3]
    np.testing.assert_array_equal(img, images[3])
    assert lbl == int(labels[3])


def test_mnist_gz(tmp_path):
    _write_mnist(str(tmp_path), gz=True)
    ds = D.MNIST(data_dir=str(tmp_path), mode="train",
                 transform=T.ToTensor())
    img, _ = ds[0]
    assert img.shape == (1, 28, 28)


def test_mnist_no_download():
    with pytest.raises(RuntimeError, match="download"):
        D.MNIST()


def test_cifar10_pickles(tmp_path):
    rng = np.random.RandomState(0)
    for i in range(1, 6):
        data = (rng.rand(4, 3072) * 255).astype(np.uint8)
        with open(tmp_path / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": data,
                         b"labels": list(rng.randint(0, 10, 4))}, f)
    ds = D.Cifar10(data_dir=str(tmp_path), mode="train")
    assert len(ds) == 20
    img, lbl = ds[0]
    assert img.shape == (32, 32, 3)
    assert 0 <= lbl < 10


def test_dataset_folder(tmp_path):
    from PIL import Image
    for cls in ("cat", "dog"):
        os.makedirs(tmp_path / cls)
        for i in range(3):
            arr = (np.random.RandomState(i).rand(8, 8, 3) * 255
                   ).astype(np.uint8)
            Image.fromarray(arr).save(tmp_path / cls / f"{i}.png")
    ds = D.DatasetFolder(str(tmp_path))
    assert len(ds) == 6
    assert ds.classes == ["cat", "dog"]
    img, target = ds[0]
    assert img.shape == (8, 8, 3)
    assert target == 0


def test_fake_data_deterministic():
    ds = D.FakeData(size=5, image_shape=(3, 16, 16), num_classes=4)
    img1, l1 = ds[2]
    img2, l2 = ds[2]
    np.testing.assert_array_equal(img1, img2)
    assert l1 == l2
    assert img1.shape == (3, 16, 16)


def test_fake_data_trains_with_dataloader():
    from paddle_tpu.io import DataLoader
    from paddle_tpu import nn, optimizer
    ds = D.FakeData(size=16, image_shape=(1, 8, 8), num_classes=3)
    dl = DataLoader(ds, batch_size=8, shuffle=True)
    paddle.seed(0)
    model = nn.Sequential(nn.Flatten(), nn.Linear(64, 3))
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=model.parameters())
    ce = nn.CrossEntropyLoss()
    for imgs, labels in dl:  # DataLoader already collates to Tensors
        loss = ce(model(imgs), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert np.isfinite(float(loss))


def test_dataloader_multiprocess_workers():
    from paddle_tpu.io import DataLoader
    ds = D.FakeData(size=20, image_shape=(1, 4, 4), num_classes=3)

    dl = DataLoader(ds, batch_size=4, shuffle=False, num_workers=2)
    batches = list(dl)
    assert len(batches) == 5
    # order preserved + content identical to the single-process path
    dl0 = DataLoader(ds, batch_size=4, shuffle=False, num_workers=0)
    for (i1, l1), (i0, l0) in zip(batches, dl0):
        np.testing.assert_allclose(np.asarray(i1.numpy()),
                                   np.asarray(i0.numpy()))
        np.testing.assert_array_equal(np.asarray(l1.numpy()),
                                      np.asarray(l0.numpy()))


def test_dataloader_worker_init_fn_runs_in_workers(tmp_path):
    from paddle_tpu.io import DataLoader

    def init_fn(worker_id):
        assert 0 <= worker_id < 2
        open(os.path.join(str(tmp_path), f"w{worker_id}"), "w").close()

    ds = D.FakeData(size=8, image_shape=(1, 2, 2), num_classes=2)
    dl = DataLoader(ds, batch_size=2, num_workers=2,
                    worker_init_fn=init_fn)
    list(dl)
    assert sorted(os.listdir(tmp_path)) == ["w0", "w1"]


def test_dataloader_early_abandon_reaps_workers():
    from paddle_tpu.io import DataLoader
    ds = D.FakeData(size=40, image_shape=(1, 2, 2), num_classes=2)
    dl = DataLoader(ds, batch_size=2, num_workers=2)
    it = iter(dl)
    next(it)
    it.close()  # must not hang; pool terminated
    assert it._pool is None


def test_dataloader_iterable_rejection():
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import IterableDataset

    class It(IterableDataset):
        def __iter__(self):
            yield from range(4)

    with pytest.raises(ValueError, match="map-style"):
        DataLoader(It(), batch_size=2, num_workers=2)


def test_flowers_dataset_local(tmp_path):
    import scipy.io as sio
    from PIL import Image
    jpg = tmp_path / "jpg"
    jpg.mkdir()
    rng = np.random.RandomState(0)
    for i in range(1, 7):
        Image.fromarray(rng.randint(0, 255, (8, 8, 3), np.uint8)).save(
            jpg / f"image_{i:05d}.jpg")
    sio.savemat(tmp_path / "imagelabels.mat",
                {"labels": np.array([[1, 2, 3, 1, 2, 3]])})
    sio.savemat(tmp_path / "setid.mat",
                {"trnid": np.array([[1, 2, 3, 4]]),
                 "valid": np.array([[5]]), "tstid": np.array([[6]])})
    from paddle_tpu.vision.datasets import Flowers
    ds = Flowers(data_file=str(jpg),
                 label_file=str(tmp_path / "imagelabels.mat"),
                 setid_file=str(tmp_path / "setid.mat"), mode="train")
    assert len(ds) == 4
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and label == 0


def test_voc2012_dataset_local(tmp_path):
    from PIL import Image
    root = tmp_path / "VOCdevkit" / "VOC2012"
    (root / "JPEGImages").mkdir(parents=True)
    (root / "SegmentationClass").mkdir()
    (root / "ImageSets" / "Segmentation").mkdir(parents=True)
    rng = np.random.RandomState(0)
    for stem in ("2007_000001", "2007_000002"):
        Image.fromarray(rng.randint(0, 255, (6, 6, 3), np.uint8)).save(
            root / "JPEGImages" / f"{stem}.jpg")
        seg = Image.fromarray(rng.randint(0, 20, (6, 6), np.uint8),
                              mode="P")
        seg.save(root / "SegmentationClass" / f"{stem}.png")
    (root / "ImageSets" / "Segmentation" / "train.txt").write_text(
        "2007_000001\n2007_000002\n")
    from paddle_tpu.vision.datasets import VOC2012
    ds = VOC2012(data_file=str(tmp_path), mode="train")
    assert len(ds) == 2
    img, seg = ds[0]
    assert img.shape == (6, 6, 3) and seg.shape == (6, 6)


def test_download_mirror_resolution(tmp_path, monkeypatch):
    from paddle_tpu.utils.download import get_path_from_url
    mirror = tmp_path / "mirror"
    mirror.mkdir()
    (mirror / "weights.bin").write_bytes(b"abc")
    monkeypatch.setenv("PADDLE_TPU_DOWNLOAD_DIR", str(mirror))
    out = get_path_from_url("https://example.com/x/weights.bin",
                            root_dir=str(tmp_path / "cache"),
                            decompress=False)
    assert open(out, "rb").read() == b"abc"
    with pytest.raises(RuntimeError, match="no network egress"):
        get_path_from_url("https://example.com/x/missing.bin",
                          root_dir=str(tmp_path / "cache"))
