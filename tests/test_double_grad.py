"""paddle.grad(outputs, inputs) + higher-order autograd.

Reference semantics: python/paddle/fluid/dygraph/base.py grad() over
eager/backward.cc:393, exercised by
fluid/tests/unittests/test_imperative_double_grad.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _x(vals, stop_gradient=False):
    t = paddle.to_tensor(np.asarray(vals, np.float32))
    t.stop_gradient = stop_gradient
    return t


def test_first_order_grad_matches_backward():
    x = _x([1.0, 2.0, 3.0])
    y = (x * x).sum()
    (gx,) = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [2.0, 4.0, 6.0])
    assert x.grad is None  # paddle.grad must not pollute .grad
    assert gx.stop_gradient  # create_graph=False -> detached result


def test_nonscalar_output_default_seed_ones():
    x = _x([1.0, 2.0])
    y = x * 3.0
    (gx,) = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), [3.0, 3.0])


def test_grad_outputs_seed():
    x = _x([1.0, 2.0])
    y = x * x
    seed = paddle.to_tensor(np.array([10.0, 100.0], np.float32))
    (gx,) = paddle.grad(y, [x], grad_outputs=[seed])
    np.testing.assert_allclose(gx.numpy(), [20.0, 400.0])


def test_double_grad_create_graph():
    # d/dx (x^2) = 2x; d/dx sum((2x)^2) = 8x
    x = _x([1.0, 2.0, 3.0])
    y = (x * x).sum()
    (dx,) = paddle.grad(y, [x], create_graph=True)
    assert not dx.stop_gradient
    np.testing.assert_allclose(dx.numpy(), [2.0, 4.0, 6.0])
    loss = (dx * dx).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [8.0, 16.0, 24.0])


def test_double_grad_via_second_grad_call():
    x = _x([2.0])
    y = (x ** 3).sum()
    (dx,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(dx.numpy(), [12.0])  # 3x^2
    (ddx,) = paddle.grad(dx, [x], create_graph=True)
    np.testing.assert_allclose(ddx.numpy(), [12.0])  # 6x
    (dddx,) = paddle.grad(ddx, [x])
    np.testing.assert_allclose(dddx.numpy(), [6.0])  # third order


def test_double_grad_through_matmul():
    a = _x([[1.0, 2.0], [3.0, 4.0]])
    b = _x([[1.0], [1.0]])
    y = paddle.matmul(a, b).sum()
    (da,) = paddle.grad(y, [a], create_graph=True)
    # d/db sum(da * const) where da = ones @ b.T depends on b
    loss = (da * da).sum()
    (db,) = paddle.grad(loss, [b])
    # da[i,j] = b[j]; loss = 2*(b0^2 + b1^2); dloss/db = 4b
    np.testing.assert_allclose(db.numpy(), [[4.0], [4.0]])


def test_gradient_penalty_pattern():
    # WGAN-GP style: penalty on ||d out/d in||^2 trains the layer
    paddle.seed(0)
    from paddle_tpu import nn
    lin = nn.Linear(4, 1)
    x = _x(np.random.RandomState(0).randn(8, 4).astype(np.float32))
    out = lin(x).sum()
    (gx,) = paddle.grad(out, [x], create_graph=True)
    penalty = ((gx * gx).sum() - 1.0) ** 2
    penalty.backward()
    w_grad = lin.weight.grad
    assert w_grad is not None
    assert float(paddle.abs(w_grad).sum()) > 0.0


def test_unused_input_raises_and_allow_unused():
    x = _x([1.0])
    z = _x([1.0])
    y = (x * 2.0).sum()
    with pytest.raises(RuntimeError):
        paddle.grad(y, [z])
    gx, gz = paddle.grad(y, [x, z], allow_unused=True)
    np.testing.assert_allclose(gx.numpy(), [2.0])
    assert gz is None


def test_grad_wrt_intermediate():
    x = _x([1.0, 2.0])
    h = x * 3.0
    y = (h * h).sum()
    (gh,) = paddle.grad(y, [h])
    np.testing.assert_allclose(gh.numpy(), [6.0, 12.0])  # 2h


def test_no_grad_vars_blocks_path():
    x = _x([1.0, 2.0])
    h = x * 2.0
    y = (h * x).sum()  # y = 2x^2, total dy/dx = 4x
    (gx,) = paddle.grad(y, [x], no_grad_vars=[h])
    # path through h removed: only the direct x factor remains (= h = 2x)
    np.testing.assert_allclose(gx.numpy(), [2.0, 4.0])


def test_retain_graph_false_frees():
    x = _x([1.0])
    y = (x * x).sum()
    paddle.grad(y, [x])
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x])


def test_retain_graph_true_allows_second_pass():
    x = _x([1.0, 2.0])
    y = (x * x).sum()
    (g1,) = paddle.grad(y, [x], retain_graph=True)
    (g2,) = paddle.grad(y, [x])
    np.testing.assert_allclose(g1.numpy(), g2.numpy())


def test_multiple_outputs_accumulate():
    x = _x([1.0, 2.0])
    y1 = (x * x).sum()
    y2 = (x * 3.0).sum()
    (gx,) = paddle.grad([y1, y2], [x])
    np.testing.assert_allclose(gx.numpy(), [5.0, 7.0])  # 2x + 3


def test_functional_grad_still_works():
    f = paddle.grad(lambda t: (t * t).sum())
    g = f(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0])


def test_backward_engine_unchanged_full_backward():
    x = _x([1.0, 2.0])
    w = _x([3.0, 4.0])
    y = (x * w).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 4.0])
    np.testing.assert_allclose(w.grad.numpy(), [1.0, 2.0])
