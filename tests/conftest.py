"""Test configuration: force an 8-device virtual CPU mesh BEFORE any jax
backend initializes (SURVEY.md §4: reference proves distributed logic with
single-host multi-process + CPU collectives; here it's jax CPU devices).
The axon sitecustomize pins JAX_PLATFORMS=axon, so we override via
jax.config before first device use."""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests (in-process, deterministic, <10s "
        "each — tier-1)")


def pytest_collection_modifyitems(config, items):
    """Chaos-marker guard: any test in a module that imports the
    fault-injection harness at module level MUST carry the ``chaos``
    marker (so ``pytest -m chaos`` really runs the whole chaos tier and
    ``-m 'not chaos'`` really excludes it). Fails collection otherwise."""
    import types
    unmarked = []
    for item in items:
        mod = getattr(item, "module", None)
        if mod is None:
            continue
        uses_fi = any(
            isinstance(v, types.ModuleType)
            and getattr(v, "__name__", "")
            == "paddle_tpu.utils.fault_injection"
            for v in vars(mod).values())
        if uses_fi and item.get_closest_marker("chaos") is None:
            unmarked.append(item.nodeid)
    if unmarked:
        raise pytest.UsageError(
            "tests built on paddle_tpu.utils.fault_injection must be "
            "@pytest.mark.chaos (or mark the module: pytestmark = "
            "pytest.mark.chaos):\n  " + "\n  ".join(sorted(unmarked)))


@pytest.fixture(autouse=True)
def _seed_rng():
    import paddle_tpu
    paddle_tpu.seed(1234)
    yield


@pytest.fixture(autouse=True)
def _restore_hybrid_mesh():
    """Process-global mesh hygiene: a test that calls ``fleet.init``
    (or sets the HybridCommunicateGroup directly) must not leak its
    mesh into later modules — that is exactly the order-dependent
    failure class where test_metrics' default-'world'-mesh collective
    counters saw test_models' hybrid mesh. Each test still SEES
    whatever was set before it (behavior unchanged mid-test); the
    snapshot/restore only guarantees the leak stops at the test
    boundary."""
    from paddle_tpu.distributed import topology
    prev = topology.get_hybrid_communicate_group()
    yield
    topology.set_hybrid_communicate_group(prev)
