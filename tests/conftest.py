"""Test configuration: force an 8-device virtual CPU mesh BEFORE any jax
backend initializes (SURVEY.md §4: reference proves distributed logic with
single-host multi-process + CPU collectives; here it's jax CPU devices).
The axon sitecustomize pins JAX_PLATFORMS=axon, so we override via
jax.config before first device use."""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection tests (in-process, deterministic, <10s "
        "each — tier-1)")


@pytest.fixture(autouse=True)
def _seed_rng():
    import paddle_tpu
    paddle_tpu.seed(1234)
    yield
