"""paddle.vision.ops detection operators (reference
python/paddle/vision/ops.py: nms/roi_align/roi_pool/psroi_pool/
yolo_box/box_coder/prior_box), golden-checked against hand math."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops
from paddle_tpu.ops import manipulation as manip


def test_nms_basic():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = vops.nms(paddle.to_tensor(boxes), 0.5,
                    scores=paddle.to_tensor(scores))
    np.testing.assert_array_equal(keep.numpy(), [0, 2])


def test_nms_categories():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1], np.int64)  # different classes: both kept
    keep = vops.nms(paddle.to_tensor(boxes), 0.5,
                    scores=paddle.to_tensor(scores),
                    category_idxs=paddle.to_tensor(cats),
                    categories=[0, 1])
    assert sorted(keep.numpy().tolist()) == [0, 1]


def test_roi_align_uniform_map():
    # constant feature map -> every pooled value equals the constant
    x = paddle.to_tensor(np.full((1, 3, 16, 16), 2.5, np.float32))
    boxes = paddle.to_tensor(np.array([[0, 0, 8, 8]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = vops.roi_align(x, boxes, num, output_size=4)
    assert out.shape == [1, 3, 4, 4]
    np.testing.assert_allclose(out.numpy(), 2.5, rtol=1e-5)


def test_roi_pool_max():
    fm = np.zeros((1, 1, 8, 8), np.float32)
    fm[0, 0, 2, 2] = 7.0
    x = paddle.to_tensor(fm)
    boxes = paddle.to_tensor(np.array([[0, 0, 4, 4]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = vops.roi_pool(x, boxes, num, output_size=2)
    assert float(out.numpy().max()) == 7.0


def test_psroi_pool_shapes():
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 8, 8, 8).astype(np.float32))
    boxes = paddle.to_tensor(np.array([[0, 0, 6, 6]], np.float32))
    num = paddle.to_tensor(np.array([1], np.int32))
    out = vops.psroi_pool(x, boxes, num, output_size=2)
    assert out.shape == [1, 2, 2, 2]


def test_yolo_box_decode():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 3 * 7, 4, 4).astype(np.float32))
    img = paddle.to_tensor(np.array([[64, 64], [32, 32]], np.int32))
    boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30, 33, 23],
                                  class_num=2, conf_thresh=0.0,
                                  downsample_ratio=16)
    assert boxes.shape == [2, 48, 4] and scores.shape == [2, 48, 2]
    b = boxes.numpy()
    assert (b[0, :, 2] <= 63.0 + 1e-3).all()  # clipped to image


def test_box_coder_roundtrip():
    priors = np.array([[0, 0, 10, 10], [10, 10, 30, 30]], np.float32)
    targets = np.array([[1, 1, 11, 11], [12, 8, 28, 32]], np.float32)
    enc = vops.box_coder(paddle.to_tensor(priors), None,
                         paddle.to_tensor(targets),
                         code_type="encode_center_size")
    dec = vops.box_coder(paddle.to_tensor(priors), None,
                         paddle.to_tensor(np.asarray(enc.numpy())),
                         code_type="decode_center_size")
    d = dec.numpy()
    # decoded box i against prior i must reproduce target i
    np.testing.assert_allclose(
        np.stack([d[0, 0], d[1, 1]]), targets, rtol=1e-4, atol=1e-3)


def test_prior_box_shapes_and_range():
    feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    boxes, var = vops.prior_box(feat, img, min_sizes=[16.0],
                                aspect_ratios=[1.0, 2.0], clip=True)
    assert boxes.shape == var.shape
    assert boxes.shape[0] == 4 and boxes.shape[1] == 4
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 1).all()


def test_new_tensor_ops():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    d = paddle.diag_embed(x)
    np.testing.assert_allclose(d.numpy(), np.diag([1.0, 2.0, 3.0]))
    m = paddle.to_tensor(np.zeros((3, 3), np.float32))
    f = paddle.fill_diagonal(m, 5.0)
    np.testing.assert_allclose(np.diag(f.numpy()), 5.0)
    ft = paddle.fill_diagonal_tensor(m, x)
    np.testing.assert_allclose(np.diag(ft.numpy()), [1.0, 2.0, 3.0])
    # temporal shift keeps shape and moves channel slices in time
    v = paddle.to_tensor(np.random.RandomState(0)
                         .randn(4, 8, 2, 2).astype(np.float32))
    ts = manip.temporal_shift(v, seg_num=2)
    assert ts.shape == [4, 8, 2, 2]
    # gather_tree reconstructs beams
    ids = paddle.to_tensor(np.array(
        [[[2, 2]], [[6, 1]]], np.int64))       # [T=2, B=1, beam=2]
    parents = paddle.to_tensor(np.array(
        [[[0, 0]], [[1, 0]]], np.int64))
    out = manip.gather_tree(ids, parents)
    assert out.shape == [2, 1, 2]
