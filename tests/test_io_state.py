"""Stateful-sampler and DataLoader checkpoint/resume semantics (the
t5x/Grain deterministic-input-iterator contract): per-epoch seeds derive
from stored state (no global-RNG dependence), state_dict round-trips
replay the exact index stream, and a mid-epoch resume fast-forwards to
bitwise-identical remaining batches."""
import numpy as np
import pytest

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.io import DataLoader
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.io.sampler import (BatchSampler, DistributedBatchSampler,
                                   RandomSampler, SequenceSampler,
                                   WeightedRandomSampler)


class ArangeDataset(Dataset):
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.arange(4, dtype=np.float32) + 10.0 * i, np.int64(i))


# ------------------------------------------------------------- samplers

def test_random_sampler_epoch_seeds_no_global_rng():
    ds = ArangeDataset(32)
    s = RandomSampler(ds, generator=42)
    e0 = list(s)
    e1 = list(s)
    assert e0 != e1  # epochs shuffle differently
    assert sorted(e0) == sorted(e1) == list(range(32))
    # global RNG state is irrelevant after construction
    np.random.seed(0)
    s2 = RandomSampler(ds, generator=42)
    np.random.seed(12345)
    assert list(s2) == e0
    assert list(s2) == e1


def test_random_sampler_state_roundtrip_and_set_epoch():
    ds = ArangeDataset(16)
    s = RandomSampler(ds, generator=7)
    e0, e1, e2 = list(s), list(s), list(s)
    st = s.state_dict()
    assert st == {"seed": 7, "epoch": 3}
    s.set_epoch(1)
    assert list(s) == e1
    s2 = RandomSampler(ds, generator=999)
    s2.load_state_dict({"seed": 7, "epoch": 2})
    assert list(s2) == e2
    assert list(s2) != e2  # advanced past the replayed epoch


def test_random_sampler_base_seed_follows_global_seed():
    # generator=None draws the base seed ONCE from the global RNG —
    # the FRAMEWORK one (paddle.seed), so seeded runs reproduce across
    # fresh processes (np.random's global is only the fallback when
    # paddle.seed was never called, which pytest's autouse seed fixture
    # makes unreachable here)
    import paddle_tpu
    ds = ArangeDataset(16)
    paddle_tpu.seed(123)
    a = list(RandomSampler(ds))
    paddle_tpu.seed(123)
    b = list(RandomSampler(ds))
    assert a == b


def test_weighted_sampler_seeded_and_stateful():
    w = [1.0, 2.0, 3.0, 4.0]
    s = WeightedRandomSampler(w, 8, generator=5)
    e0 = list(s)
    s2 = WeightedRandomSampler(w, 8, generator=5)
    assert list(s2) == e0
    s2.load_state_dict(s.state_dict())
    assert s2.state_dict() == s.state_dict()


def test_batch_sampler_delegates_state():
    ds = ArangeDataset(12)
    bs = BatchSampler(ds, shuffle=True, batch_size=4)
    st = bs.state_dict()
    assert set(st) == {"seed", "epoch"}
    first = list(bs)
    bs.load_state_dict(st)
    assert list(bs) == first
    # sequence-backed: stateless
    assert BatchSampler(ds, batch_size=4).state_dict() == {}
    assert isinstance(BatchSampler(ds, batch_size=4).sampler,
                      SequenceSampler)


def test_distributed_batch_sampler_state():
    ds = ArangeDataset(16)
    s = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0,
                                shuffle=True)
    s.set_epoch(3)
    e3 = list(s)
    assert s.state_dict() == {"epoch": 3}
    s2 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0,
                                 shuffle=True)
    s2.load_state_dict({"epoch": 3})
    assert list(s2) == e3


# ------------------------------------------------------ loader resume

def _arrs(b):
    return np.asarray(b[0].numpy())


@pytest.mark.parametrize("num_workers", [0, 2])
def test_loader_midepoch_resume_bitwise(num_workers):
    ds = ArangeDataset(40)

    def make():
        bs = BatchSampler(ds, sampler=RandomSampler(ds, generator=3),
                          batch_size=4)
        return DataLoader(ds, batch_sampler=bs, num_workers=num_workers)

    ref = [_arrs(b) for b in make()]

    dl = make()
    it = iter(dl)
    for _ in range(3):
        next(it)
    mid = dl.state_dict()
    assert mid["cursor"] == 3
    assert mid["sampler"] == {"seed": 3, "epoch": 0}
    it.close()

    dl2 = make()
    dl2.load_state_dict(mid)
    assert dl2.resumed_mid_epoch
    rest = [_arrs(b) for b in dl2]
    assert not dl2.resumed_mid_epoch  # one-shot
    assert len(rest) == len(ref) - 3
    for a, b in zip(rest, ref[3:]):
        np.testing.assert_array_equal(a, b)


def test_loader_state_after_epoch_is_fresh_next_epoch():
    ds = ArangeDataset(12)
    dl = DataLoader(ds, batch_size=4, shuffle=True)
    epoch0 = [_arrs(b) for b in dl]
    st = dl.state_dict()  # exhausted iterator: next epoch, cursor 0
    assert st["cursor"] == 0
    assert st["sampler"]["epoch"] == 1
    dl2 = DataLoader(ds, batch_size=4, shuffle=True)
    dl2.load_state_dict(st)
    epoch1 = [_arrs(b) for b in dl2]
    assert len(epoch1) == len(epoch0)
    # same loader continuing produces the identical second epoch
    epoch1_ref = [_arrs(b) for b in dl]
    for a, b in zip(epoch1, epoch1_ref):
        np.testing.assert_array_equal(a, b)


def test_loader_load_state_dict_coerces_checkpoint_leaves():
    # a state tree round-tripped through a checkpoint comes back as
    # Tensors / 0-d arrays — load_state_dict must coerce
    ds = ArangeDataset(20)
    dl = DataLoader(ds, batch_size=4, shuffle=True)
    st = {"cursor": Tensor(np.asarray(2)),
          "sampler": {"seed": Tensor(np.asarray(3)),
                      "epoch": np.asarray(0)}}
    assert dl.load_state_dict(st) == 2
    ref_dl = DataLoader(ds, batch_size=4,
                        batch_sampler=BatchSampler(
                            ds, sampler=RandomSampler(ds, generator=3),
                            batch_size=4))
    ref = [_arrs(b) for b in ref_dl]
    got = [_arrs(b) for b in dl]
    assert len(got) == len(ref) - 2
    for a, b in zip(got, ref[2:]):
        np.testing.assert_array_equal(a, b)


def test_fresh_loader_state_dict_shape():
    ds = ArangeDataset(8)
    st = DataLoader(ds, batch_size=4, shuffle=False).state_dict()
    assert st == {"cursor": 0, "sampler": {}}


def test_paddle_seed_makes_shuffle_reproducible():
    """paddle.seed(S) pins the shuffle order drawn by a generator-less
    RandomSampler — the base seed comes from the framework RNG, not
    NumPy's global (process-entropy) state."""
    import paddle_tpu

    def order():
        paddle_tpu.seed(77)
        ds = ArangeDataset(16)
        return [_arrs(b) for b in DataLoader(ds, batch_size=4,
                                             shuffle=True)]

    a, b = order(), order()
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # different seed -> different permutation (overwhelmingly likely)
    paddle_tpu.seed(78)
    ds = ArangeDataset(16)
    c = [_arrs(b) for b in DataLoader(ds, batch_size=4, shuffle=True)]
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))
