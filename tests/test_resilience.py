"""Chaos tests for the fault-tolerance layer (resilience.py), driven by
the deterministic in-process fault-injection harness — no real TPU, no
subprocesses, each test well under 10s.

Covers the acceptance path end to end: SIGTERM mid-run → emergency
checkpoint → fresh loop resumes losing at most one step; truncated
latest checkpoint → transparent fallback to the previous step; armed
watchdog around a stalled store op → WatchdogTimeout with a stack dump
instead of a hang; non-finite loss → skip + restore-from-last-good."""
import os
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import auto_checkpoint as ac
from paddle_tpu.distributed import resilience
from paddle_tpu.distributed.checkpoint import (CheckpointCorruption,
                                               CheckpointManager)
from paddle_tpu.distributed.elastic import ELASTIC_EXIT_CODE
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.hapi import Model
from paddle_tpu.profiler import metrics
from paddle_tpu.utils import fault_injection as fi

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_registry():
    """Emergency savers are process-global; never leak between tests."""
    yield
    resilience._EMERGENCY.clear()
    resilience._ACTIVE.clear()


def _counter(name):
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


def _env(tmp_path, monkeypatch, job, interval="100"):
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_JOB_ID", job)
    monkeypatch.setenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", str(tmp_path))
    monkeypatch.setenv("PADDLE_EDL_SAVE_CHECKPOINT_INTER", interval)


# ------------------------------------------------- preemption -> resume

def test_sigterm_mid_epoch_emergency_checkpoint_and_resume(
        tmp_path, monkeypatch):
    """A SIGTERM landing mid-epoch writes a synchronous emergency
    checkpoint at the next epoch boundary and exits ELASTIC_EXIT_CODE;
    the relaunched range resumes having lost at most one epoch."""
    # interval=100: withOUT the emergency save nothing would be on disk
    _env(tmp_path, monkeypatch, "chaos-sig")
    kill = fi.KillAfter(3, signal.SIGTERM)  # delivered during epoch 2
    status = ac.ExeTrainStatus()
    seen = []
    with pytest.raises(SystemExit) as exc:
        for epoch in ac.train_epoch_range(10, status=status):
            seen.append(epoch)
            status.update(last=epoch, w=np.float32(epoch * 2.0))
            kill.step()
    assert exc.value.code == ELASTIC_EXIT_CODE
    assert seen == [0, 1, 2]

    # "relaunched" process: fresh status, same env
    status2 = ac.ExeTrainStatus()
    seen2 = list(ac.train_epoch_range(5, status=status2))
    assert seen2 == [3, 4]  # epoch 2 completed before the boundary check
    assert int(status2.state["last"]) == 2
    np.testing.assert_allclose(float(status2.state["w"]), 4.0)


def test_fit_preemption_emergency_save_and_resume(tmp_path):
    """hapi path: a preemption caught by the active GracefulShutdown
    makes Model.fit write {save_dir}/emergency.pdparams (through the
    ModelCheckpoint emergency registration) and exit 101; a fresh Model
    loads it and continues."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    m = Model(net)
    m.prepare(optimizer.SGD(learning_rate=0.01,
                            parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    data = [(rng.randn(8, 4).astype(np.float32),
             rng.randint(0, 2, (8,)).astype(np.int64)) for _ in range(6)]
    save_dir = str(tmp_path / "ckpts")

    kill = fi.KillAfter(3, signal.SIGTERM)

    from paddle_tpu.hapi.callbacks import Callback

    class Chaos(Callback):
        def on_train_batch_end(self, step, logs=None):
            kill.step()

    with pytest.raises(SystemExit) as exc:
        with resilience.GracefulShutdown():
            m.fit(train_data=data, epochs=3, save_dir=save_dir,
                  verbose=0, callbacks=[Chaos()])
    assert exc.value.code == ELASTIC_EXIT_CODE
    assert os.path.exists(os.path.join(save_dir, "emergency.pdparams"))

    # relaunch: fresh model resumes from the emergency checkpoint
    paddle.seed(1)
    net2 = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 2))
    m2 = Model(net2)
    m2.prepare(optimizer.SGD(learning_rate=0.01,
                             parameters=net2.parameters()),
               loss=nn.CrossEntropyLoss())
    m2.load(os.path.join(save_dir, "emergency"))
    for (k, a), (_, b) in zip(sorted(net2.state_dict().items()),
                              sorted(net.state_dict().items())):
        np.testing.assert_allclose(np.asarray(a.data), np.asarray(b.data))
    m2.fit(train_data=data[:2], epochs=1, verbose=0)  # trains on


# --------------------------------------------- corruption -> fallback

def test_truncated_latest_epoch_resumes_previous(tmp_path, monkeypatch):
    """e2e: finish a few epochs, truncate the newest checkpoint, and the
    relaunched train_epoch_range transparently resumes from the previous
    committed epoch (one epoch redone, fallback metric bumped)."""
    _env(tmp_path, monkeypatch, "chaos-trunc", interval="1")
    status = ac.ExeTrainStatus()
    for epoch in ac.train_epoch_range(4, status=status):
        status.update(last=epoch)
    job_dir = os.path.join(str(tmp_path), "job_chaos-trunc")
    fi.truncate_checkpoint(job_dir)  # newest step: torn write

    was = metrics.is_enabled()
    metrics.enable()
    try:
        before = _counter("resilience.ckpt.fallback")
        status2 = ac.ExeTrainStatus()
        seen = list(ac.train_epoch_range(6, status=status2))
        assert _counter("resilience.ckpt.fallback") > before
    finally:
        if not was:
            metrics.disable()
    # latest (epoch 3) was truncated -> resumed from epoch 2: redo 3
    assert seen == [3, 4, 5]
    assert int(status2.state["last"]) == 2


def test_checkpoint_manager_explicit_step_raises_on_corruption(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "c"), async_save=False)
    mgr.save(0, {"w": np.arange(4.0, dtype=np.float32)})
    mgr.save(1, {"w": np.arange(4.0, dtype=np.float32) * 3})
    fi.truncate_checkpoint(str(tmp_path / "c"), step=1)
    with pytest.raises(CheckpointCorruption):
        mgr.restore(step=1)  # explicit step, no fallback
    state = mgr.restore(step=1, fallback=True)
    np.testing.assert_allclose(np.asarray(state["w"].data),
                               np.arange(4.0, dtype=np.float32))
    assert mgr.last_restored_step == 0
    mgr.close()


# ------------------------------------------------------------ watchdog

def test_watchdog_unblocks_stalled_store_op(capfd):
    """An armed watchdog around a store op whose reply is delayed past
    the deadline force-closes the socket and raises WatchdogTimeout with
    a full stack dump — instead of hanging for the op's own timeout."""
    store = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        store.set("k", 1)
        with fi.StoreFaults(delay=20.0, ops=("get",), count=1):
            t0 = time.monotonic()
            with pytest.raises(resilience.WatchdogTimeout):
                with resilience.watchdog(0.5, "store.get"):
                    store.get("k", timeout=15.0)
            assert time.monotonic() - t0 < 5.0  # un-hung, not waited out
        err = capfd.readouterr().err
        assert "Watchdog 'store.get' expired" in err
        assert "thread" in err  # the stack dump
        # the cancelled socket must not poison the next op
        assert store.get("k", timeout=5.0) == 1
    finally:
        store.shutdown_server()


def test_watchdog_run_abandons_hung_callable():
    ev = threading.Event()
    t0 = time.monotonic()
    with pytest.raises(resilience.WatchdogTimeout):
        resilience.Watchdog.run(ev.wait, timeout=0.3, label="hung",
                                dump_stacks=False)
    assert time.monotonic() - t0 < 5.0
    ev.set()


def test_watchdog_happy_path_no_raise():
    with resilience.watchdog(5.0, "fast"):
        x = 1 + 1
    assert x == 2


def test_watchdog_timeout_metric(capfd):
    was = metrics.is_enabled()
    metrics.enable()
    try:
        before = _counter("resilience.watchdog.timeouts")
        with pytest.raises(resilience.WatchdogTimeout):
            resilience.Watchdog.run(time.sleep, 5.0, timeout=0.2,
                                    label="metric", dump_stacks=False)
        assert _counter("resilience.watchdog.timeouts") == before + 1
    finally:
        if not was:
            metrics.disable()


# -------------------------------------------------------- anomaly guard

def test_fit_anomaly_guard_skips_and_restores(tmp_path):
    """Poisoned batches produce non-finite losses: each is skipped (the
    in-jit guard keeps params unchanged), and a streak of
    max_consecutive anomalies restores network+optimizer from the last
    good snapshot. Training ends with finite parameters."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m = Model(net)
    m.prepare(optimizer.AdamW(learning_rate=0.01,
                              parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    rng = np.random.RandomState(3)

    def batch():
        return (rng.randn(8, 4).astype(np.float32),
                rng.randint(0, 2, (8,)).astype(np.int64))

    data = [batch() for _ in range(8)]
    for i in (2, 3, 4):  # 3 consecutive poisoned batches
        data[i] = fi.poison_batch(data[i])

    guard = resilience.AnomalyGuard(max_consecutive=2)
    m.fit(train_data=data, epochs=1, verbose=0, anomaly_guard=guard,
          shuffle=False)
    assert guard.total == 3
    assert guard.restores >= 1
    for name, p in net.state_dict().items():
        assert np.isfinite(np.asarray(p.data)).all(), name


def test_trainstep_skip_nonfinite_keeps_params():
    """The in-jit guard alone: a NaN batch leaves parameters bit-exact
    while still reporting the non-finite loss."""
    from paddle_tpu.jit.api import TrainStep
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 2))
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    step = TrainStep(net, opt, nn.CrossEntropyLoss(), skip_nonfinite=True)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 4).astype(np.float32)
    y = rng.randint(0, 2, (4,)).astype(np.int64)

    step(paddle.to_tensor(x), paddle.to_tensor(y))  # warm, good step
    before = {k: np.array(v.numpy(), copy=True)
              for k, v in net.state_dict().items()}
    bad = np.full_like(x, np.nan)
    loss = step(paddle.to_tensor(bad), paddle.to_tensor(y))
    assert not np.isfinite(float(loss))
    for k, v in net.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v.data), before[k]), k


# ----------------------------------------- preemption flag cross-host

def test_graceful_shutdown_store_flag_propagates():
    """Host A is signaled; host B (same store, its own context) sees the
    preemption through the TCPStore flag and runs its own emergency
    save — the all-hosts-checkpoint-the-same-step mechanism."""
    store_a = TCPStore("127.0.0.1", 0, is_master=True)
    store_b = TCPStore("127.0.0.1", store_a.port)
    saved = []
    try:
        unreg = resilience.register_emergency(saved.append)
        with resilience.GracefulShutdown(store=store_a,
                                         exit_on_save=False) as gs_a:
            gs_b = resilience.GracefulShutdown(store=store_b,
                                               exit_on_save=False)
            gs_a.trigger()
            assert gs_a.check(7) is True  # publishes flag + saves
            assert saved == [7]
            # B never got the signal, only the store flag
            assert gs_b.preempted is True
            # B is a boundary ahead but ADOPTS the published step so
            # every host checkpoints under the same step id
            assert gs_b.check(8) is True
            assert saved == [7, 7]
        unreg()
        # relaunched incarnation (launcher bumps PADDLE_RESTART_COUNT):
        # the predecessor's flag is namespaced away — no crash loop
        gs_next = resilience.GracefulShutdown(store=store_b,
                                              exit_on_save=False,
                                              incarnation="1")
        assert gs_next.preempted is False
        assert gs_next.check(0) is False
    finally:
        store_b.close()
        store_a.shutdown_server()
