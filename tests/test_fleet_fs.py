"""Tests for fleet.utils FS clients (reference:
python/paddle/distributed/fleet/utils/fs.py — LocalFS fully, HDFSClient
construction gating in a hadoop-less environment).
"""
import os

import pytest

from paddle_tpu.distributed.fleet.utils import FS, LocalFS, HDFSClient
from paddle_tpu.distributed.fleet.utils.fs import (FSFileExistsError,
                                                   FSFileNotExistsError)


def test_localfs_roundtrip(tmp_path):
    fs = LocalFS()
    assert isinstance(fs, FS)
    root = str(tmp_path / "store")
    fs.mkdirs(root)
    assert fs.is_dir(root) and fs.is_exist(root)

    fs.touch(os.path.join(root, "a.txt"))
    fs.mkdirs(os.path.join(root, "sub"))
    dirs, files = fs.ls_dir(root)
    assert dirs == ["sub"] and files == ["a.txt"]
    assert fs.list_dirs(root) == ["sub"]
    assert fs.is_file(os.path.join(root, "a.txt"))
    assert not fs.need_upload_download()

    with open(os.path.join(root, "a.txt"), "w") as f:
        f.write("payload")
    assert fs.cat(os.path.join(root, "a.txt")) == "payload"

    fs.upload(os.path.join(root, "a.txt"), os.path.join(root, "b.txt"))
    assert fs.is_file(os.path.join(root, "b.txt"))
    fs.rename(os.path.join(root, "b.txt"), os.path.join(root, "c.txt"))
    assert fs.is_file(os.path.join(root, "c.txt"))

    with pytest.raises(FSFileExistsError):
        fs.mv(os.path.join(root, "a.txt"), os.path.join(root, "c.txt"))
    fs.mv(os.path.join(root, "a.txt"), os.path.join(root, "c.txt"),
          overwrite=True)
    assert fs.cat(os.path.join(root, "c.txt")) == "payload"
    with pytest.raises(FSFileNotExistsError):
        fs.mv(os.path.join(root, "nope"), os.path.join(root, "d"))

    fs.delete(os.path.join(root, "sub"))
    assert not fs.is_exist(os.path.join(root, "sub"))
    fs.delete(root)
    assert not fs.is_exist(root)
    assert fs.ls_dir(root) == ([], [])


def test_localfs_touch_exists(tmp_path):
    fs = LocalFS()
    p = str(tmp_path / "x")
    fs.touch(p)
    fs.touch(p, exist_ok=True)
    with pytest.raises(FSFileExistsError):
        fs.touch(p, exist_ok=False)


def test_hdfs_client_gated_without_hadoop(monkeypatch):
    monkeypatch.delenv("HADOOP_HOME", raising=False)
    import shutil
    if shutil.which("hadoop"):
        pytest.skip("hadoop present; gating not applicable")
    with pytest.raises(RuntimeError, match="hadoop"):
        HDFSClient()
