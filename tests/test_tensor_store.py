"""Native tensor store + paddle.save/load integration
(reference: framework/save_load_util.cc serialization tests analog)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.native import tensor_store

pytestmark = pytest.mark.skipif(not tensor_store.available(),
                                reason="native toolchain unavailable")


def test_store_roundtrip_many_dtypes(tmp_path):
    rng = np.random.RandomState(0)
    tensors = {
        "f32": rng.randn(16, 8).astype(np.float32),
        "i32": rng.randint(-5, 5, (7,)).astype(np.int32),
        "u8": rng.randint(0, 255, (3, 3, 3)).astype(np.uint8),
        "scalar": np.float32(3.5).reshape(()),
        "big": rng.randn(256, 256).astype(np.float32),
    }
    path = str(tmp_path / "blob.tensors")
    tensor_store.save_tensors(path, tensors, num_threads=3)
    back = tensor_store.load_tensors(path)
    assert set(back) == set(tensors)
    for k in tensors:
        np.testing.assert_array_equal(back[k], tensors[k])
        assert back[k].dtype == tensors[k].dtype


def test_store_corruption_detected(tmp_path):
    path = str(tmp_path / "c.tensors")
    tensor_store.save_tensors(
        path, {"w": np.ones((32, 32), np.float32)})
    with open(path, "r+b") as f:
        f.seek(100)
        f.write(b"\x13\x37")
    with pytest.raises(IOError, match="CRC"):
        tensor_store.load_tensors(path)


def test_store_bad_file(tmp_path):
    p = tmp_path / "junk.tensors"
    p.write_bytes(b"this is not a checkpoint")
    with pytest.raises(IOError):
        tensor_store.load_tensors(str(p))


def test_paddle_save_load_native_sidecar(tmp_path):
    paddle.seed(0)
    path = str(tmp_path / "model.pdparams")
    state = {"w": paddle.randn([32, 16]),
             "opt": {"m": paddle.zeros([32, 16]), "step": 7},
             "names": ["a", "b"]}
    paddle.save(state, path)
    sidecars = [f for f in os.listdir(tmp_path)
                if f.startswith("model.pdparams.tensors.")]
    assert len(sidecars) == 1
    back = paddle.load(path)
    np.testing.assert_allclose(back["w"].numpy(), state["w"].numpy())
    np.testing.assert_allclose(back["opt"]["m"].numpy(), 0.0)
    assert back["opt"]["step"] == 7
    assert back["names"] == ["a", "b"]


def test_crashed_resave_keeps_last_good_checkpoint(tmp_path):
    # a writer killed after the sidecar write but before the pickle
    # publish must leave the previous checkpoint loadable
    path = str(tmp_path / "m.pdparams")
    paddle.save({"w": paddle.full([4], 1.0)}, path)
    # simulate the crashed second save(): orphan sidecar, no new pickle
    orphan = path + ".tensors.deadbeef"
    tensor_store.save_tensors(
        orphan, {"t0": np.full((4,), 2.0, np.float32)})
    back = paddle.load(path)
    np.testing.assert_allclose(back["w"].numpy(), 1.0)
    # a successful re-save garbage-collects the orphan once it is past
    # the concurrent-writer grace window (age it artificially)
    old = os.path.getmtime(orphan) - 3600
    os.utime(orphan, (old, old))
    paddle.save({"w": paddle.full([4], 3.0)}, path)
    sidecars = [f for f in os.listdir(tmp_path)
                if f.startswith("m.pdparams.tensors.")]
    assert "m.pdparams.tensors.deadbeef" not in sidecars
    np.testing.assert_allclose(paddle.load(path)["w"].numpy(), 3.0)


def test_paddle_save_load_bf16(tmp_path):
    import jax.numpy as jnp
    path = str(tmp_path / "bf16.pdparams")
    src = {"p": paddle.to_tensor(
        jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
        .astype(jnp.bfloat16))}
    paddle.save(src, path)
    back = paddle.load(path)
    assert str(back["p"].numpy().dtype) == "bfloat16"
    np.testing.assert_allclose(
        np.asarray(back["p"].numpy()).astype(np.float32),
        np.arange(16, dtype=np.float32).reshape(4, 4))


def test_pickle_fallback_still_loads(tmp_path):
    # files written with the flag off (pure pickle) must keep loading
    from paddle_tpu.core import flags
    path = str(tmp_path / "plain.pdparams")
    flags.set_flags({"FLAGS_use_native_tensor_store": False})
    try:
        paddle.save({"w": paddle.ones([4])}, path)
        assert not os.path.exists(path + ".tensors")
        back = paddle.load(path)
        np.testing.assert_allclose(back["w"].numpy(), 1.0)
    finally:
        flags.set_flags({"FLAGS_use_native_tensor_store": True})


def test_state_dict_roundtrip_through_model(tmp_path):
    from paddle_tpu import nn
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
    path = str(tmp_path / "m.pdparams")
    paddle.save(m.state_dict(), path)
    m2 = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
    m2.set_state_dict(paddle.load(path))
    x = paddle.randn([3, 8])
    np.testing.assert_allclose(m2(x).numpy(), m(x).numpy(), rtol=1e-6)
