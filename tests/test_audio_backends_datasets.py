"""Audio backends (WAV load/info/save over the stdlib wave module) and
classification datasets (ESC50/TESS on the standard extracted
layouts), completing the paddle.audio surface (reference
python/paddle/audio/{backends,datasets})."""
import os
import wave

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio


def _write_wav(path, sr=16000, n=1600, ch=1, freq=440.0):
    t = np.arange(n) / sr
    sig = (0.3 * np.sin(2 * np.pi * freq * t)).astype(np.float32)
    data = np.tile(sig[:, None], (1, ch))
    pcm = (data * (1 << 15)).astype("<i2")
    with wave.open(path, "wb") as w:
        w.setnchannels(ch)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(pcm.tobytes())
    return sig


def test_info_load_save_roundtrip(tmp_path):
    p = str(tmp_path / "t.wav")
    sig = _write_wav(p, ch=2)
    meta = audio.info(p)
    assert (meta.sample_rate, meta.num_channels,
            meta.bits_per_sample) == (16000, 2, 16)
    assert meta.num_frames == 1600
    wav, sr = audio.load(p)
    assert sr == 16000 and tuple(wav.shape) == (2, 1600)
    np.testing.assert_allclose(np.asarray(wav.data)[0], sig, atol=1e-4)
    # raw int16 + frame windows
    raw, _ = audio.load(p, frame_offset=100, num_frames=50,
                        normalize=False)
    assert raw.dtype == paddle.int16 and tuple(raw.shape) == (2, 50)
    # save round-trip
    p2 = str(tmp_path / "o.wav")
    audio.save(p2, wav, 16000)
    wav2, sr2 = audio.load(p2)
    np.testing.assert_allclose(np.asarray(wav2.data),
                               np.asarray(wav.data), atol=1e-4)


def _esc50_tree(tmp_path):
    root = tmp_path / "ESC-50-master"
    (root / "meta").mkdir(parents=True)
    (root / "audio").mkdir()
    rows = ["filename,fold,target,category,esc10,src_file,take"]
    for i in range(10):
        fold = i % 5 + 1
        fn = f"{fold}-{i}-A-{i % 3}.wav"
        _write_wav(str(root / "audio" / fn), n=800)
        rows.append(f"{fn},{fold},{i % 3},cat,{i % 2},{i},A")
    (root / "meta" / "esc50.csv").write_text("\n".join(rows) + "\n")
    return str(tmp_path)


def test_esc50_split_and_features(tmp_path):
    data_dir = _esc50_tree(tmp_path)
    train = audio.datasets.ESC50(mode="train", split=1,
                                 data_dir=data_dir)
    dev = audio.datasets.ESC50(mode="dev", split=1, data_dir=data_dir)
    assert len(train) + len(dev) == 10
    assert len(dev) == 2  # fold 1 entries
    feat, label = train[0]
    assert feat.ndim == 1 and label.dtype == np.int64
    mel = audio.datasets.ESC50(mode="dev", split=1, data_dir=data_dir,
                               feat_type="melspectrogram", n_fft=256,
                               n_mels=32)
    f2, _ = mel[0]
    assert f2.shape[0] == 32  # mel bins


def test_tess_layout(tmp_path):
    root = tmp_path / "TESS_Toronto_emotional_speech_set"
    root.mkdir()
    emotions = ["angry", "happy", "sad", "neutral"]
    for i in range(8):
        _write_wav(str(root / f"OAF_word{i}_{emotions[i % 4]}.wav"),
                   n=400)
    ds = audio.datasets.TESS(mode="train", n_folds=4, split=1,
                             data_dir=str(tmp_path))
    dev = audio.datasets.TESS(mode="dev", n_folds=4, split=1,
                              data_dir=str(tmp_path))
    assert len(ds) + len(dev) == 8
    feat, label = ds[0]
    assert 0 <= int(label) < len(audio.datasets.TESS.emotions)


def test_download_gated():
    with pytest.raises(RuntimeError, match="download"):
        audio.datasets.ESC50()
    with pytest.raises(NotImplementedError):
        audio.backends.set_backend("soundfile")
