"""Plain-numpy port of the reference YOLOv3 loss CPU kernel
(phi/kernels/cpu/yolov3_loss_kernel.cc) — golden oracle for tests only."""
import numpy as np


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _bce(x, label):
    return max(x, 0.0) - x * label + np.log1p(np.exp(-abs(x)))


def _iou(b1, b2):
    def overlap(c1, w1, c2, w2):
        left = max(c1 - w1 / 2, c2 - w2 / 2)
        right = min(c1 + w1 / 2, c2 + w2 / 2)
        return right - left
    w = overlap(b1[0], b1[2], b2[0], b2[2])
    h = overlap(b1[1], b1[3], b2[1], b2[3])
    inter = 0.0 if (w < 0 or h < 0) else w * h
    union = b1[2] * b1[3] + b2[2] * b2[3] - inter
    return inter / union


def yolo_loss_ref(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                  ignore_thresh, downsample_ratio, gt_score=None,
                  use_label_smooth=True, scale_x_y=1.0):
    n, _, h, w = x.shape
    mask_num = len(anchor_mask)
    an_num = len(anchors) // 2
    b = gt_box.shape[1]
    input_size = downsample_ratio * h
    scale = scale_x_y
    bias = -0.5 * (scale - 1.0)
    if gt_score is None:
        gt_score = np.ones((n, b), np.float64)
    if use_label_smooth:
        smooth = min(1.0 / class_num, 1.0 / 40)
        pos, neg = 1.0 - smooth, smooth
    else:
        pos, neg = 1.0, 0.0

    xr = x.reshape(n, mask_num, 5 + class_num, h, w).astype(np.float64)
    loss = np.zeros(n, np.float64)
    obj_mask = np.zeros((n, mask_num, h, w), np.float64)
    valid = (gt_box[..., 2] >= 1e-6) & (gt_box[..., 3] >= 1e-6)

    for i in range(n):
        for j in range(mask_num):
            for k in range(h):
                for l in range(w):
                    px = (l + _sigmoid(xr[i, j, 0, k, l]) * scale + bias) / h
                    py = (k + _sigmoid(xr[i, j, 1, k, l]) * scale + bias) / h
                    pw = np.exp(xr[i, j, 2, k, l]) \
                        * anchors[2 * anchor_mask[j]] / input_size
                    ph = np.exp(xr[i, j, 3, k, l]) \
                        * anchors[2 * anchor_mask[j] + 1] / input_size
                    best = 0.0
                    for t in range(b):
                        if not valid[i, t]:
                            continue
                        best = max(best, _iou((px, py, pw, ph), gt_box[i, t]))
                    if best > ignore_thresh:
                        obj_mask[i, j, k, l] = -1.0
        for t in range(b):
            if not valid[i, t]:
                continue
            gt = gt_box[i, t]
            gi, gj = int(gt[0] * w), int(gt[1] * h)
            best_iou, best_n = 0.0, 0
            for a in range(an_num):
                an = (0.0, 0.0, anchors[2 * a] / input_size,
                      anchors[2 * a + 1] / input_size)
                iou = _iou(an, (0.0, 0.0, gt[2], gt[3]))
                if iou > best_iou:
                    best_iou, best_n = iou, a
            mask_idx = anchor_mask.index(best_n) \
                if best_n in anchor_mask else -1
            if mask_idx < 0:
                continue
            score = gt_score[i, t]
            tx = gt[0] * w - gi
            ty = gt[1] * h - gj
            tw = np.log(gt[2] * input_size / anchors[2 * best_n])
            th = np.log(gt[3] * input_size / anchors[2 * best_n + 1])
            sc = (2.0 - gt[2] * gt[3]) * score
            loss[i] += _bce(xr[i, mask_idx, 0, gj, gi], tx) * sc
            loss[i] += _bce(xr[i, mask_idx, 1, gj, gi], ty) * sc
            loss[i] += abs(tw - xr[i, mask_idx, 2, gj, gi]) * sc
            loss[i] += abs(th - xr[i, mask_idx, 3, gj, gi]) * sc
            obj_mask[i, mask_idx, gj, gi] = score
            label = int(gt_label[i, t])
            for c in range(class_num):
                loss[i] += _bce(xr[i, mask_idx, 5 + c, gj, gi],
                                pos if c == label else neg) * score
    for i in range(n):
        for j in range(mask_num):
            for k in range(h):
                for l in range(w):
                    o = obj_mask[i, j, k, l]
                    p = xr[i, j, 4, k, l]
                    if o > 1e-5:
                        loss[i] += _bce(p, 1.0) * o
                    elif o > -0.5:
                        loss[i] += _bce(p, 0.0)
    return loss
