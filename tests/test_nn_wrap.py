"""Round-2 nn surface completion: wrapper layers, losses, unpool,
decode (reference nn/layer/* + nn/decode.py parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_nn_class_parity_frozen_list():
    import os
    ref = set(open(os.path.join(os.path.dirname(__file__),
                                "data_ref_nn_all.txt")).read().split())
    missing = sorted(n for n in ref if not hasattr(nn, n))
    assert not missing, f"missing nn exports: {missing}"


def test_activation_wrappers():
    x = paddle.to_tensor(np.array([-2.0, -0.1, 0.5, 3.0], np.float32))
    np.testing.assert_allclose(nn.CELU(1.0)(x).numpy(),
                               F.celu(x, 1.0).numpy())
    np.testing.assert_allclose(nn.Softsign()(x).numpy(),
                               (x.numpy() / (1 + np.abs(x.numpy()))),
                               rtol=1e-6)
    h = nn.Hardtanh(-1.0, 1.0)(x)
    np.testing.assert_allclose(h.numpy(), np.clip(x.numpy(), -1, 1))
    s2 = nn.Softmax2D()(paddle.ones([1, 3, 2, 2]))
    np.testing.assert_allclose(s2.numpy().sum(axis=1), 1.0, rtol=1e-6)


def test_rrelu_train_eval():
    x = paddle.to_tensor(np.full((100,), -1.0, np.float32))
    m = nn.RReLU(0.1, 0.3)
    m.train()
    y = m(x).numpy()
    assert (y <= -0.1 + 1e-6).all() and (y >= -0.3 - 1e-6).all()
    m.eval()
    np.testing.assert_allclose(m(x).numpy(), -0.2, rtol=1e-5)


def test_pool_wrappers_and_unpool_roundtrip():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32)
                         .reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2, 2, return_mask=True) \
        if hasattr(nn.MaxPool2D(2, 2), "forward") else None
    from paddle_tpu.nn.functional.pooling import (max_pool2d_with_index,
                                                  max_unpool2d)
    out, mask = max_pool2d_with_index(x, 2, 2, 0)
    np.testing.assert_allclose(out.numpy().ravel(), [5, 7, 13, 15])
    np.testing.assert_array_equal(mask.numpy().ravel(), [5, 7, 13, 15])
    restored = max_unpool2d(out, mask, 2, 2)
    assert restored.shape == [1, 1, 4, 4]
    r = restored.numpy().ravel()
    assert r[5] == 5 and r[15] == 15 and r.sum() == 5 + 7 + 13 + 15
    un = nn.MaxUnPool2D(2, 2)
    np.testing.assert_allclose(un(out, mask).numpy(), restored.numpy())
    p1 = nn.AvgPool1D(2)(paddle.ones([1, 2, 8]))
    assert p1.shape == [1, 2, 4]
    p3 = nn.MaxPool3D(2)(paddle.ones([1, 1, 4, 4, 4]))
    assert p3.shape == [1, 1, 2, 2, 2]
    a1 = nn.AdaptiveAvgPool1D(3)(paddle.ones([1, 2, 9]))
    assert a1.shape == [1, 2, 3]


def test_loss_wrappers():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 5).astype(np.float32))
    y = paddle.to_tensor(np.array([1, -1, 1, -1], np.float32))
    l = nn.SoftMarginLoss()(paddle.to_tensor(
        rng.randn(4).astype(np.float32)), y)
    assert float(l) > 0
    lab = paddle.to_tensor(np.array([0, 2, 1, 4], np.int64))
    mm = nn.MultiMarginLoss()(x, lab)
    assert float(mm) >= 0
    ml = nn.MultiLabelSoftMarginLoss()(
        x, paddle.to_tensor((rng.rand(4, 5) > 0.5)
                            .astype(np.float32)))
    assert float(ml) > 0
    a = paddle.to_tensor(rng.randn(3, 8).astype(np.float32))
    p = paddle.to_tensor(rng.randn(3, 8).astype(np.float32))
    n = paddle.to_tensor(rng.randn(3, 8).astype(np.float32))
    t1 = nn.TripletMarginLoss()(a, p, n)
    t2 = nn.TripletMarginWithDistanceLoss()(a, p, n)
    assert float(t1) >= 0 and float(t2) >= 0


def test_hsigmoid_loss_trains():
    paddle.seed(0)
    m = nn.HSigmoidLoss(8, 6)
    x = paddle.randn([4, 8])
    x.stop_gradient = False
    lab = paddle.to_tensor(np.array([0, 1, 2, 5], np.int64))
    loss = m(x, lab).sum()
    loss.backward()
    assert m.weight.grad is not None
    assert float(loss) > 0


def test_channel_shuffle_and_instance_norm():
    x = paddle.to_tensor(np.arange(8, dtype=np.float32)
                         .reshape(1, 4, 1, 2))
    cs = nn.ChannelShuffle(2)(x)
    # groups=2: channels [0,1,2,3] -> [0,2,1,3]
    np.testing.assert_allclose(cs.numpy()[0, 1], x.numpy()[0, 2])
    inorm = nn.InstanceNorm1D(3)
    out = inorm(paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 16).astype(np.float32)))
    np.testing.assert_allclose(out.numpy().mean(-1), 0.0, atol=1e-5)


def test_conv_transpose_wrappers():
    paddle.seed(0)
    c1 = nn.Conv1DTranspose(2, 3, 3)
    out = c1(paddle.ones([1, 2, 8]))
    assert out.shape[0] == 1 and out.shape[1] == 3
    c3 = nn.Conv3DTranspose(2, 3, 3)
    out3 = c3(paddle.ones([1, 2, 4, 4, 4]))
    assert out3.shape[1] == 3


def test_beam_search_decode_greedy_case():
    """A cell whose logits always rank token sequence 3,1,<eos> first:
    beam search must return it (reference decode.py semantics)."""
    from paddle_tpu.nn.decode import BeamSearchDecoder, dynamic_decode

    V, EOS = 5, 4

    class ScriptCell:
        def __call__(self, inputs, states):
            step = states  # int step count per row
            import jax.numpy as jnp
            t = int(np.asarray(step.data if hasattr(step, "data")
                               else step).ravel()[0])
            row = np.full((1, V), -5.0, np.float32)
            plan = [3, 1, EOS]
            tok = plan[min(t, len(plan) - 1)]
            row[0, tok] = 5.0
            n = (inputs.shape[0] if hasattr(inputs, "shape")
                 else np.asarray(inputs).shape[0])
            logits = paddle.to_tensor(np.repeat(row, n, axis=0))
            new_state = paddle.to_tensor(
                np.full((n, 1), t + 1, np.int32))
            return logits, new_state

    dec = BeamSearchDecoder(ScriptCell(), start_token=0, end_token=EOS,
                            beam_size=2)
    init = paddle.to_tensor(np.zeros((1, 1), np.int32))
    ids, scores, lengths = dynamic_decode(dec, init, max_step_num=6,
                                          return_length=True)
    best = ids.numpy()[0, :, 0]
    assert best[0] == 3 and best[1] == 1 and best[2] == EOS
    assert int(lengths.numpy()[0, 0]) == 3


def test_weight_norm_eager_grads_flow():
    # regression: the derived weight must stay on the tape so eager
    # backward reaches weight_v / weight_g
    from paddle_tpu.nn.utils import weight_norm
    paddle.seed(0)
    lin = nn.Linear(4, 3)
    weight_norm(lin, "weight", dim=0)
    x = paddle.randn([2, 4])
    lin(x).sum().backward()
    g = dict(lin.named_parameters())
    assert g["weight_g"].grad is not None
    assert g["weight_v"].grad is not None
    assert float(paddle.abs(g["weight_v"].grad).sum()) > 0


def test_inplace_on_same_tensor_twice():
    # regression: x.add_(x) puts the same tensor twice in node.inputs;
    # snapshot dedup must not truth-test a Tensor
    x = paddle.to_tensor(np.ones(3, np.float32))
    x.stop_gradient = False
    x.add_(x)
    np.testing.assert_allclose(x.numpy(), 2.0)


def test_soft_margin_loss_stable_at_large_logits():
    big = paddle.to_tensor(np.array([100.0], np.float32))
    y = paddle.to_tensor(np.array([-1.0], np.float32))
    val = float(nn.SoftMarginLoss()(big, y))
    assert np.isfinite(val) and abs(val - 100.0) < 1e-3
