"""Goodput ledger (ISSUE 15): bucket/ambient unit semantics, flush
monotonicity, and the tier-1 invariant gates — buckets sum to measured
wall time within 5% on a real 10-step ``Model.fit`` and a drained
serving run, with a forced retrace and a forced checkpoint each
landing at least one nonzero sample in their own bucket (the gate is
non-vacuous)."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core import goodput, metrics, monitor
from paddle_tpu.hapi import Model
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.nn import functional as F


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.disable()
    metrics.reset()
    yield
    metrics.disable()
    metrics.reset()


# ---------------------------------------------------------- ledger unit


class TestLedgerUnit:
    def test_buckets_sum_to_wall_with_residual_fold(self):
        led = goodput.GoodputLedger("train").start()
        led.charge("data_stall", 0.002)
        time.sleep(0.02)
        led.close()
        snap = led.snapshot()
        assert sum(snap["buckets"].values()) == \
            pytest.approx(snap["wall_s"], rel=1e-9)
        # unattributed time folded into the train default: compute
        assert snap["buckets"]["compute"] > 0.015
        assert snap["buckets"]["data_stall"] == pytest.approx(0.002)
        assert 0.0 < snap["goodput_fraction"] <= 1.0

    def test_serve_default_bucket_is_idle(self):
        led = goodput.GoodputLedger("serve", default_bucket="idle")
        led.start()
        time.sleep(0.01)
        led.charge("compute", 0.001)
        led.close()
        snap = led.snapshot()
        assert snap["buckets"]["idle"] > 0.005
        assert sum(snap["buckets"].values()) == \
            pytest.approx(snap["wall_s"], rel=1e-9)

    def test_validation(self):
        with pytest.raises(ValueError, match="family"):
            goodput.GoodputLedger("inference")
        with pytest.raises(ValueError, match="bucket"):
            goodput.GoodputLedger("train", default_bucket="napping")
        led = goodput.GoodputLedger("train")
        with pytest.raises(ValueError, match="bucket"):
            led.charge("napping", 1.0)

    def test_ambient_stack_and_noop(self):
        assert goodput.active() is None
        goodput.charge("checkpoint", 5.0)        # no ledger: dropped
        with goodput.GoodputLedger("train") as led:
            assert goodput.active() is led
            goodput.charge("checkpoint", 0.25)
            inner = goodput.GoodputLedger("serve",
                                          default_bucket="idle")
            with inner:
                assert goodput.active() is inner
                goodput.charge("compile", 0.125)  # innermost wins
            assert goodput.active() is led
        assert goodput.active() is None
        assert led.bucket_total("checkpoint") == pytest.approx(0.25)
        assert led.bucket_total("compile") == 0.0
        assert inner.bucket_total("compile") == pytest.approx(0.125)

    def test_flush_keeps_counters_monotone(self):
        metrics.enable()
        led = goodput.GoodputLedger("train").start()
        led.charge("checkpoint", 0.5)
        led.flush()
        led.flush()      # repeat flush must not double-count
        led.charge("checkpoint", 0.25)
        led.close()      # close = final flush
        v = metrics.snapshot()[
            "train.goodput.seconds{bucket=checkpoint}"]["value"]
        assert v == pytest.approx(0.75, rel=1e-6)
        frac = metrics.snapshot()["train.goodput.fraction"]["value"]
        assert 0.0 <= frac <= 1.0


# -------------------------------------------------------- the fit gate


class _Toy(Dataset):
    """19 samples at batch 2 -> 10 batches, the LAST one smaller: a
    guaranteed mid-run new_shape retrace (the forced-retrace half of
    the non-vacuous gate, with no test-private model surgery)."""

    def __init__(self, n=19):
        rng = np.random.RandomState(0)
        self.x = rng.standard_normal((n, 8)).astype(np.float32)
        self.y = (self.x.sum(1) > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class TestFitGoodputGate:
    def test_ledger_invariant_on_ten_step_fit(self, tmp_path):
        """THE tier-1 invariant: a 10-step Model.fit's buckets sum to
        the measured wall time within 5%; the forced retrace (ragged
        last batch) and the forced checkpoint (ModelCheckpoint) each
        land >= one nonzero sample in their own bucket."""
        metrics.enable()
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 2))
        m = Model(net)
        m.prepare(
            optimizer=optimizer.Adam(learning_rate=0.01,
                                     parameters=net.parameters()),
            loss=lambda out, lbl: F.cross_entropy(out, lbl))
        retraces0 = monitor.retrace_count()
        t0 = time.perf_counter()
        m.fit(_Toy(), batch_size=2, epochs=1, verbose=0,
              save_dir=str(tmp_path / "ckpt"))
        wall = time.perf_counter() - t0
        snap = m.goodput_summary
        buckets = snap["buckets"]
        # buckets sum to the ledger's wall exactly (residual fold)...
        assert sum(buckets.values()) == \
            pytest.approx(snap["wall_s"], rel=1e-6)
        # ...and the ledger's wall is the fit's measured wall within
        # the 5% gate (setup outside the ledger is the only slack)
        assert snap["wall_s"] == pytest.approx(wall, rel=0.05)
        # non-vacuous: the ragged last batch retraced (first compile
        # plus the new_shape one), and the dispatch window that
        # retraced was charged to compile, not compute
        assert monitor.retrace_count() - retraces0 >= 2
        assert buckets["compile"] > 0.0
        # the forced checkpoint (epoch + final saves) hit its bucket
        assert buckets["checkpoint"] > 0.0
        assert buckets["data_stall"] > 0.0
        assert buckets["compute"] > 0.0
        # the registry carries the same story (flush path)
        reg = metrics.snapshot()
        assert reg["train.goodput.seconds{bucket=compile}"]["value"] \
            > 0.0
        assert reg["train.goodput.seconds{bucket=checkpoint}"][
            "value"] > 0.0
        assert 0.0 < reg["train.goodput.fraction"]["value"] <= 1.0

    def test_resume_restore_lands_in_recovery_bucket(self, tmp_path):
        """fit(resume=) restoring an emergency checkpoint charges the
        preemption_recovery bucket."""
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 2))
        m = Model(net)
        m.prepare(
            optimizer=optimizer.Adam(learning_rate=0.01,
                                     parameters=net.parameters()),
            loss=lambda out, lbl: F.cross_entropy(out, lbl))
        prefix = str(tmp_path / "emergency")
        m.save(prefix)
        m2 = Model(net)
        m2.prepare(
            optimizer=optimizer.Adam(learning_rate=0.01,
                                     parameters=net.parameters()),
            loss=lambda out, lbl: F.cross_entropy(out, lbl))
        m2.fit(_Toy(), batch_size=4, epochs=1, verbose=0,
               resume=prefix)
        assert m2.goodput_summary["buckets"][
            "preemption_recovery"] > 0.0


# ------------------------------------------------------ the serve gate


class TestServeGoodputGate:
    def test_ledger_invariant_on_drained_serve(self):
        """The serve half of the tier-1 invariant: a drained serving
        run's buckets sum to its measured wall within 5%, decode
        windows landed in compute, and un-pumped time folded into
        idle."""
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        metrics.enable()
        paddle.seed(0)
        model = gpt("test-tiny")
        model.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(model, spec)
               .enable_generation(max_new_tokens=8,
                                  prefill_buckets=(16,), max_batch=2))
        eng = ServingEngine(cfg, poll_every=2)
        t0 = time.perf_counter()
        reqs = [eng.submit(np.arange(1, 5 + i, dtype=np.int32))
                for i in range(3)]
        for r in reqs:
            r.result(timeout=60)
        time.sleep(0.05)              # un-pumped gap -> idle
        eng.drain()
        wall = time.perf_counter() - t0
        snap = eng.goodput()
        buckets = snap["buckets"]
        assert sum(buckets.values()) == \
            pytest.approx(snap["wall_s"], rel=1e-6)
        assert snap["wall_s"] == pytest.approx(wall, rel=0.05,
                                               abs=0.05)
        assert buckets["compute"] > 0.0         # decode windows
        assert buckets["idle"] > 0.0            # the un-pumped gap
        assert 0.0 < snap["goodput_fraction"] <= 1.0
        # the serve.goodput.* family carries the flushes
        reg = metrics.snapshot()
        assert reg["serve.goodput.seconds{bucket=compute}"]["value"] \
            > 0.0
        eng.shutdown()
