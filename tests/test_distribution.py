"""paddle.distribution analog tests: moments, log_prob vs scipy-free
closed forms, sampling statistics, KL registry, transforms.

Mirrors the reference's test_distribution_*.py
(python/paddle/fluid/tests/unittests/distribution/)."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(42)


def test_normal_logprob_entropy_kl():
    n = D.Normal(loc=1.0, scale=2.0)
    v = 0.5
    expect = -((v - 1.0) ** 2) / 8 - math.log(2.0) \
        - 0.5 * math.log(2 * math.pi)
    np.testing.assert_allclose(float(n.log_prob(v)), expect, rtol=1e-5)
    np.testing.assert_allclose(
        float(n.entropy()), 0.5 + 0.5 * math.log(2 * math.pi)
        + math.log(2.0), rtol=1e-5)
    m = D.Normal(loc=0.0, scale=1.0)
    kl = float(D.kl_divergence(n, m))
    expect_kl = 0.5 * (4 + 1 - 1 - math.log(4))
    np.testing.assert_allclose(kl, expect_kl, rtol=1e-5)
    assert float(D.kl_divergence(n, n)) == pytest.approx(0.0, abs=1e-6)


def test_normal_sample_moments():
    n = D.Normal(loc=3.0, scale=0.5)
    s = n.sample([20000]).numpy()
    assert abs(s.mean() - 3.0) < 0.05
    assert abs(s.std() - 0.5) < 0.05


def test_uniform():
    u = D.Uniform(low=-1.0, high=3.0)
    assert float(u.mean) == pytest.approx(1.0)
    assert float(u.variance) == pytest.approx(16 / 12)
    np.testing.assert_allclose(float(u.log_prob(0.0)), -math.log(4))
    assert np.isneginf(float(u.log_prob(5.0)))
    s = u.sample([5000]).numpy()
    assert s.min() >= -1.0 and s.max() < 3.0


def test_bernoulli_and_categorical():
    b = D.Bernoulli(probs=0.3)
    np.testing.assert_allclose(float(b.mean), 0.3)
    np.testing.assert_allclose(float(b.log_prob(1.0)), math.log(0.3),
                               rtol=1e-5)
    c = D.Categorical(probs=[0.2, 0.3, 0.5])
    np.testing.assert_allclose(float(c.log_prob(2)), math.log(0.5),
                               rtol=1e-5)
    ent = -sum(p * math.log(p) for p in (0.2, 0.3, 0.5))
    np.testing.assert_allclose(float(c.entropy()), ent, rtol=1e-5)
    s = c.sample([8000]).numpy()
    freq = np.bincount(s, minlength=3) / len(s)
    np.testing.assert_allclose(freq, [0.2, 0.3, 0.5], atol=0.03)
    # log_prob over sampled values (broadcast over sample dims)
    lp = c.log_prob(c.sample([10]))
    assert lp.numpy().shape == (10,)
    # batched categorical + batched multinomial sampling
    cb = D.Categorical(probs=[[0.5, 0.5], [0.1, 0.9]])
    sb = cb.sample([7])
    assert sb.numpy().shape == (7, 2)
    mb = D.Multinomial(6, probs=[[0.5, 0.5], [0.2, 0.8]])
    smb = mb.sample([3]).numpy()
    assert smb.shape == (3, 2, 2)
    np.testing.assert_allclose(smb.sum(-1), 6.0)


def test_categorical_requires_one_parameterization():
    with pytest.raises(ValueError):
        D.Categorical(logits=[0.0], probs=[1.0])
    with pytest.raises(ValueError):
        D.Categorical()


def test_beta_dirichlet():
    be = D.Beta(alpha=2.0, beta=3.0)
    np.testing.assert_allclose(float(be.mean), 0.4, rtol=1e-6)
    # log B(2,3) = log(Γ2Γ3/Γ5) = log(1*2/24)
    lp = float(be.log_prob(0.5))
    expect = (1) * math.log(0.5) + 2 * math.log(0.5) - math.log(2 / 24)
    np.testing.assert_allclose(lp, expect, rtol=1e-5)
    d = D.Dirichlet(concentration=[1.0, 2.0, 3.0])
    np.testing.assert_allclose(d.mean.numpy(), [1 / 6, 2 / 6, 3 / 6],
                               rtol=1e-6)
    s = d.sample([1000]).numpy()
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)
    np.testing.assert_allclose(s.mean(0), [1 / 6, 2 / 6, 3 / 6],
                               atol=0.03)


def test_multinomial():
    m = D.Multinomial(10, probs=[0.5, 0.2, 0.3])
    np.testing.assert_allclose(m.mean.numpy(), [5.0, 2.0, 3.0],
                               rtol=1e-6)
    s = m.sample([200]).numpy()
    np.testing.assert_allclose(s.sum(-1), 10.0)
    np.testing.assert_allclose(s.mean(0), [5, 2, 3], atol=0.5)
    # binomial-style exact check: P([10,0,0]) = 0.5^10
    np.testing.assert_allclose(float(m.log_prob([10.0, 0.0, 0.0])),
                               10 * math.log(0.5), rtol=1e-4)


def test_gamma_exponential_poisson():
    g = D.Gamma(concentration=3.0, rate=2.0)
    np.testing.assert_allclose(float(g.mean), 1.5)
    s = g.sample([20000]).numpy()
    assert abs(s.mean() - 1.5) < 0.05
    e = D.Exponential(rate=2.0)
    np.testing.assert_allclose(float(e.log_prob(1.0)),
                               math.log(2) - 2, rtol=1e-5)
    p = D.Poisson(rate=4.0)
    # P(X=2) = e^-4 4^2/2!
    np.testing.assert_allclose(float(p.log_prob(2.0)),
                               -4 + 2 * math.log(4) - math.log(2),
                               rtol=1e-5)


def test_laplace_gumbel_lognormal_studentt():
    lap = D.Laplace(loc=0.0, scale=1.0)
    np.testing.assert_allclose(float(lap.log_prob(0.0)), -math.log(2),
                               rtol=1e-5)
    gum = D.Gumbel(loc=0.0, scale=1.0)
    s = gum.sample([20000]).numpy()
    assert abs(s.mean() - 0.5772) < 0.05
    ln = D.LogNormal(loc=0.0, scale=0.5)
    s = ln.rsample([20000]).numpy()
    np.testing.assert_allclose(s.mean(), math.exp(0.125), atol=0.05)
    st = D.StudentT(df=5.0)
    assert float(st.variance) == pytest.approx(5 / 3, rel=1e-5)


def test_kl_registry_and_missing():
    a, b = D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)
    assert float(D.kl_divergence(a, b)) > 0
    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Normal(0.0, 1.0), D.Beta(1.0, 1.0))

    # custom registration wins
    class MyNormal(D.Normal):
        pass

    @D.register_kl(MyNormal, D.Normal)
    def _kl_mine(p, q):
        return paddle.to_tensor(123.0)

    assert float(D.kl_divergence(MyNormal(0.0, 1.0),
                                 D.Normal(0.0, 1.0))) == 123.0


def test_affine_exp_transforms_roundtrip():
    t = D.AffineTransform(loc=2.0, scale=3.0)
    x = paddle.to_tensor([0.5, -1.0])
    y = t.forward(x)
    np.testing.assert_allclose(t.inverse(y).numpy(), x.numpy(),
                               rtol=1e-6)
    np.testing.assert_allclose(
        t.forward_log_det_jacobian(x).numpy(), np.log(3.0), rtol=1e-6)
    e = D.ExpTransform()
    np.testing.assert_allclose(e.inverse(e.forward(x)).numpy(),
                               x.numpy(), rtol=1e-6)
    chain = D.ChainTransform([D.AffineTransform(1.0, 2.0),
                              D.ExpTransform()])
    y2 = chain.forward(x)
    np.testing.assert_allclose(chain.inverse(y2).numpy(), x.numpy(),
                               rtol=1e-5)
    np.testing.assert_allclose(
        chain.inverse_log_det_jacobian(y2).numpy(),
        -chain.forward_log_det_jacobian(x).numpy(), rtol=1e-5)


def test_transformed_distribution_lognormal_equivalence():
    base = D.Normal(loc=0.0, scale=0.5)
    td = D.TransformedDistribution(base, [D.ExpTransform()])
    ln = D.LogNormal(loc=0.0, scale=0.5)
    for v in (0.5, 1.0, 2.5):
        np.testing.assert_allclose(float(td.log_prob(v)),
                                   float(ln.log_prob(v)), rtol=1e-5)
    s = td.sample([10000]).numpy()
    assert (s > 0).all()


def test_sigmoid_tanh_transform_ldj():
    x = paddle.to_tensor([0.3, -0.7])
    sg = D.SigmoidTransform()
    y = sg.forward(x).numpy()
    # d sigmoid/dx = y(1-y)
    np.testing.assert_allclose(
        sg.forward_log_det_jacobian(x).numpy(),
        np.log(y * (1 - y)), rtol=1e-5)
    th = D.TanhTransform()
    yt = th.forward(x).numpy()
    np.testing.assert_allclose(
        th.forward_log_det_jacobian(x).numpy(),
        np.log(1 - yt ** 2), rtol=1e-4)


def test_stickbreaking_roundtrip():
    sb = D.StickBreakingTransform()
    x = paddle.to_tensor([0.5, -0.3, 0.8])
    y = sb.forward(x)
    assert y.numpy().shape == (4,)
    np.testing.assert_allclose(y.numpy().sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(sb.inverse(y).numpy(), x.numpy(),
                               rtol=1e-4)


# ---------------------------------------------------------------------------
# r5: IndependentTransform / ReshapeTransform / StackTransform
# (VERDICT r4 Next #8; reference transform.py:672, :831, :1046)

def test_independent_transform_reference_example():
    """The reference docstring's own numbers: Exp with
    reinterpreted_batch_rank=1 over [[1,2,3],[4,5,6]] -> fldj [6, 15]."""
    x = paddle.to_tensor(np.array([[1., 2., 3.], [4., 5., 6.]],
                                  np.float32))
    t = D.IndependentTransform(
        D.ExpTransform(), 1)
    np.testing.assert_allclose(t.forward(x).numpy(), np.exp(x.numpy()),
                               rtol=1e-6)
    np.testing.assert_allclose(
        t.forward_log_det_jacobian(x).numpy(), [6.0, 15.0], rtol=1e-6)
    np.testing.assert_allclose(t.inverse(t.forward(x)).numpy(),
                               x.numpy(), rtol=1e-5)
    assert t.event_dim == 1
    with pytest.raises(TypeError):
        D.IndependentTransform(object(), 1)
    with pytest.raises(ValueError):
        D.IndependentTransform(D.ExpTransform(), 0)


def test_reshape_transform_roundtrip_and_zero_ldj():
    t = D.ReshapeTransform((2, 3), (3, 2))
    x = paddle.to_tensor(
        np.arange(12, dtype=np.float32).reshape(2, 2, 3))
    y = t.forward(x)
    assert list(y.shape) == [2, 3, 2]
    np.testing.assert_allclose(t.inverse(y).numpy(), x.numpy())
    np.testing.assert_allclose(t.forward_log_det_jacobian(x).numpy(),
                               np.zeros(2))
    assert t.in_event_shape == (2, 3) and t.out_event_shape == (3, 2)
    with pytest.raises(ValueError):
        D.ReshapeTransform((2, 3), (4,))


def test_stack_transform_slicewise():
    t = D.StackTransform(
        [D.ExpTransform(),
         D.AffineTransform(paddle.to_tensor(1.0),
                                      paddle.to_tensor(2.0))], axis=1)
    x = paddle.to_tensor(np.array([[0.5, 3.0], [1.0, -1.0]], np.float32))
    y = t.forward(x).numpy()
    np.testing.assert_allclose(y[:, 0], np.exp([0.5, 1.0]), rtol=1e-6)
    np.testing.assert_allclose(y[:, 1], 1.0 + 2.0 * np.array([3., -1.]),
                               rtol=1e-6)
    np.testing.assert_allclose(t.inverse(t.forward(x)).numpy(),
                               x.numpy(), rtol=1e-5)
    ldj = t.forward_log_det_jacobian(x).numpy()
    np.testing.assert_allclose(ldj[:, 0], [0.5, 1.0], rtol=1e-6)
    np.testing.assert_allclose(ldj[:, 1], np.log(2.0), rtol=1e-6)
    with pytest.raises(ValueError):
        t.forward(paddle.to_tensor(np.zeros((2, 3), np.float32)))
    with pytest.raises(TypeError):
        D.StackTransform([])


def test_transformed_distribution_multi_event_dim_log_prob():
    """log_prob must reduce the base log-prob over ALL the transform's
    event axes (IndependentTransform can carry event_dim >= 2) —
    review-caught: the r4 code reduced exactly one axis."""
    base = D.Normal(paddle.to_tensor(np.zeros((4, 3, 2), np.float32)),
                    paddle.to_tensor(np.ones((4, 3, 2), np.float32)))
    t = D.IndependentTransform(D.ExpTransform(), 2)
    dist = D.TransformedDistribution(base, [t])
    y = paddle.to_tensor(np.full((4, 3, 2), 2.0, np.float32))
    lp = dist.log_prob(y)
    assert list(lp.shape) == [4]
    # closed form: sum over the (3,2) event of N(log y|0,1) - log y
    x = np.log(2.0)
    per = -0.5 * x * x - 0.5 * np.log(2 * np.pi) - x
    np.testing.assert_allclose(lp.numpy(), np.full(4, 6 * per),
                               rtol=1e-5)
