"""Metrics registry + runtime monitor coverage: lifecycle (values
survive enable/disable cycles), thread safety, device-memory peak
tracking/reset, retrace cause classification, counter events in the
exported Chrome trace, and the summary views built from the registry."""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.device as device
from paddle_tpu.core import monitor
from paddle_tpu.profiler import (Profiler, ProfilerTarget, RecordEvent,
                                 SummaryView, metrics)


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.disable()
    metrics.reset()
    yield
    metrics.disable()
    metrics.reset()


class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        metrics.enable()
        c = metrics.counter("t.counter")
        c.inc()
        c.inc(4)
        assert c.value == 5
        g = metrics.gauge("t.gauge")
        g.set(10)
        g.set(3)
        assert g.value == 3 and g.peak == 10
        h = metrics.histogram("t.hist")
        for v in (10, 100, 1000):
            h.observe(v)
        assert h.count == 3 and h.sum == 1110 and h.mean == 370

    def test_histogram_percentile(self):
        metrics.enable()
        h = metrics.histogram("t.pct", bounds=(1.0, 2.0, 4.0, 8.0))
        assert h.percentile(50) == 0.0          # empty -> 0
        for v in (0.5, 0.5, 1.5, 1.5, 3.0, 3.0, 3.0, 3.0):
            h.observe(v)
        # ranks: bucket le_1 holds 2, le_2 holds 2, le_4 holds 4
        # p25 -> target rank 2 = top of the first bucket
        assert h.percentile(25) == pytest.approx(1.0)
        # p50 -> rank 4 = top of the second bucket
        assert h.percentile(50) == pytest.approx(2.0)
        # p75 -> rank 6 = halfway into the (2, 4] bucket
        assert h.percentile(75) == pytest.approx(3.0)
        # monotonic and clamped
        assert h.percentile(0) <= h.percentile(99) <= 4.0
        h.observe(1e9)                          # overflow bucket
        assert h.percentile(100) == 8.0         # clamps at last bound
        # an EMPTY histogram created by a bounds-less reader rebinds to
        # the first explicit bounds (a dashboard polling percentile()
        # before traffic must not pin a latency histogram to the
        # byte-scaled defaults)
        early_reader = metrics.histogram("t.rebind")
        assert early_reader.bounds == metrics.Histogram.DEFAULT_BOUNDS
        rb = metrics.histogram("t.rebind", bounds=(1.0, 2.0, 4.0))
        assert rb is early_reader and rb.bounds == (1.0, 2.0, 4.0)
        rb.observe(1.5)
        # a POPULATED histogram under different bounds is a schema
        # conflict — warned once, never raised (this call sits on
        # recording hot paths; telemetry must not crash the scheduler)
        with pytest.warns(UserWarning, match="different bounds"):
            keep = metrics.histogram("t.rebind", bounds=(9.0,))
        assert keep is rb and keep.bounds == (1.0, 2.0, 4.0)
        metrics.histogram("t.rebind", bounds=(9.0,))  # warns only once
        # the serve recorders' latency-scaled bounds give sub-ms
        # percentile resolution end-to-end
        monitor.enable()
        monitor.record_serve_ttft(0.003)
        monitor.record_serve_ttft(0.004)
        assert 0.001 < metrics.histogram("serve.ttft").percentile(50) \
            < 0.01

    def test_same_name_same_instance(self):
        assert metrics.counter("t.same") is metrics.counter("t.same")
        assert metrics.counter("t.same", axis="dp") is not \
            metrics.counter("t.same", axis="mp")
        with pytest.raises(TypeError):
            metrics.gauge("t.same")

    def test_values_survive_enable_disable_enable(self):
        metrics.enable()
        c = metrics.counter("t.cycle")
        g = metrics.gauge("t.cycle.gauge")
        c.inc(7)
        g.set(42)
        metrics.disable()
        c.inc(100)   # dropped: recording is off
        g.set(1000)
        assert c.value == 7 and g.value == 42 and g.peak == 42
        metrics.enable()
        c.inc(3)
        assert c.value == 10
        assert metrics.counter("t.cycle").value == 10

    def test_disabled_mutations_are_noops(self):
        c = metrics.counter("t.off")
        c.inc(999)
        assert c.value == 0
        assert not metrics.is_enabled()

    def test_reset_zeroes(self):
        metrics.enable()
        metrics.counter("t.rst").inc(5)
        metrics.reset()
        assert metrics.counter("t.rst").value == 0

    def test_thread_hammer(self):
        """4 threads x 10k increments land exactly; gauge peak is the
        true maximum over every thread's writes."""
        metrics.enable()
        c = metrics.counter("t.hammer")
        g = metrics.gauge("t.hammer.gauge")
        n, per = 4, 10000

        def work(tid):
            for i in range(per):
                c.inc()
                g.set(tid * per + i)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n * per
        assert g.peak == (n - 1) * per + per - 1

    def test_sampling_drains(self):
        metrics.enable()
        metrics.start_sampling()
        metrics.counter("t.samp").inc()
        metrics.counter("t.samp").inc()
        out = metrics.stop_sampling()
        assert len(out["t.samp"]) == 2
        assert [v for _, v in out["t.samp"]] == [1, 2]
        # drained: a second stop returns nothing for this metric
        assert "t.samp" not in metrics.stop_sampling()

    def test_sampling_nests(self):
        """An inner start/stop pair must not switch off an outer
        recorder's capture."""
        metrics.enable()
        metrics.start_sampling()       # outer
        metrics.start_sampling()       # inner
        metrics.counter("t.nest").inc()
        metrics.stop_sampling()        # inner: drains, capture stays on
        metrics.counter("t.nest").inc()
        out = metrics.stop_sampling()  # outer
        assert [v for _, v in out["t.nest"]] == [2]

    def test_monitor_flag_mirrors_registry(self):
        assert monitor.enabled is False
        metrics.enable()
        assert monitor.enabled is True
        metrics.disable()
        assert monitor.enabled is False


class TestDeviceMemory:
    def test_allocated_nonzero_with_live_array(self):
        keep = paddle.to_tensor(np.ones((256, 256), np.float32))
        assert device.memory_allocated() >= keep.data.nbytes

    def test_reset_peak_memory_stats_resets_high_water(self):
        metrics.enable()
        base = device.reset_peak_memory_stats()
        big = paddle.to_tensor(np.ones((512, 512), np.float32))
        high = device.max_memory_allocated()
        assert high >= base + big.data.nbytes
        del big
        reset_to = device.reset_peak_memory_stats()
        assert reset_to < high
        assert device.max_memory_allocated() < high
        # the registry gauge's high-water mark was reset too
        g = metrics.gauge("device.memory.allocated")
        assert g.peak <= high

    def test_memory_reserved_and_aliases(self):
        assert device.memory_reserved() >= 0
        assert device.max_memory_reserved() >= 0
        # the CUDA-parity names reset their own mark only; the
        # torch-style name resets both
        assert device.reset_max_memory_allocated() >= 0
        assert device.reset_max_memory_reserved() >= 0
        assert device.reset_peak_memory_stats() >= 0


class TestRetraceTracking:
    def test_causes_classified(self):
        metrics.enable()
        fn = paddle.jit.to_static(lambda a: a + 1)
        fn(paddle.ones([3]))
        fn(paddle.ones([3]))      # cache hit: no new compile
        fn(paddle.ones([5]))      # new shape
        fn(paddle.ones([5]).astype("int32"))  # new dtype
        snap = metrics.snapshot()
        assert snap["jit.compile{cause=first}"]["value"] == 1
        assert snap["jit.compile{cause=new_shape}"]["value"] == 1
        assert snap["jit.compile{cause=new_dtype}"]["value"] == 1
        assert snap["jit.compile.total"]["value"] == 3

    def test_no_phantom_retrace_after_warmup(self):
        fn = paddle.jit.to_static(lambda a: a - 1)
        fn(paddle.ones([4]))   # warmed while the monitor is off
        metrics.enable()
        fn(paddle.ones([4]))   # cache hit: must not count a compile
        snap = metrics.snapshot()
        assert snap.get("jit.compile.total", {"value": 0})["value"] == 0


class TestCollectiveCounters:
    @pytest.fixture(autouse=True)
    def _default_world_mesh(self):
        """This test asserts the DEFAULT single-axis 'world' mesh path;
        clear any HybridCommunicateGroup a prior module leaked (e.g. a
        fleet.init in test_models) and restore it afterwards, so the
        test passes in any collection order."""
        from paddle_tpu.distributed import topology
        prev = topology.get_hybrid_communicate_group()
        topology.set_hybrid_communicate_group(None)
        yield
        topology.set_hybrid_communicate_group(prev)

    def test_all_reduce_counts_bytes(self):
        metrics.enable()
        from paddle_tpu.distributed import collective
        x = paddle.ones([8, 8])
        nbytes = x.data.nbytes
        collective.all_reduce(x)
        snap = metrics.snapshot()
        key = "comm.bytes{axis=world,op=all_reduce}"
        assert snap[key]["value"] == nbytes
        assert snap["comm.ops{axis=world,op=all_reduce}"]["value"] == 1


class TestProfilerIntegration:
    def test_trace_has_span_and_counter_events(self, tmp_path):
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        with RecordEvent("step"):
            paddle.ones([32, 32]).sum()
        from paddle_tpu.distributed import collective
        collective.all_reduce(paddle.ones([8, 8]))
        p.stop()
        path = str(tmp_path / "trace.json")
        p.result.export_chrome_tracing(path)
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        phases = {ev["ph"] for ev in events}
        assert {"X", "C", "M"} <= phases
        counters = {ev["name"] for ev in events if ev["ph"] == "C"}
        assert "device.memory.allocated" in counters
        assert any(c.startswith("comm.bytes") for c in counters)
        import os
        assert {ev["pid"] for ev in events} == {os.getpid()}
        meta = [ev for ev in events if ev["ph"] == "M"]
        assert meta and meta[0]["name"] == "process_name"

    def test_summary_views_populated(self, capsys):
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        with RecordEvent("step"):
            paddle.ones([16, 16]).sum()
        from paddle_tpu.distributed import collective
        collective.all_reduce(paddle.ones([8]))
        p.stop()
        mem = p.result.summary(sorted_by=SummaryView.MemoryView)
        assert "MemoryView" in mem and "device.memory.allocated" in mem
        dist = p.result.summary(sorted_by=SummaryView.DistributedView)
        assert "DistributedView" in dist and "all_reduce" in dist
        over = p.result.summary(sorted_by=SummaryView.OverView)
        assert "host spans" in over
        ops = p.result.summary(sorted_by=SummaryView.OperatorView)
        assert "OperatorView" in ops

    def test_profiler_restores_metrics_state(self):
        assert not metrics.is_enabled()
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        assert metrics.is_enabled()
        p.stop()
        assert not metrics.is_enabled()
        # ... and leaves a user-enabled registry enabled
        metrics.enable()
        p2 = Profiler(targets=[ProfilerTarget.CPU])
        p2.start()
        p2.stop()
        assert metrics.is_enabled()

    def test_bad_tuple_scheduler_raises(self):
        with pytest.raises(ValueError, match=r"\(5, 3\)"):
            Profiler(scheduler=(5, 3))
        with pytest.raises(ValueError, match=r"\(2, 2\)"):
            Profiler(scheduler=(2, 2))
        Profiler(scheduler=(0, 4))  # valid: records steps [0, 4)


class TestMetricsCallback:
    def test_epoch_stats_in_logs(self, capsys):
        from paddle_tpu.hapi.callbacks import MetricsCallback
        cb = MetricsCallback(tokens_per_sample=128)
        cb.set_params({"epochs": 1})
        cb.on_train_begin()
        cb.on_epoch_begin(0)
        fn = paddle.jit.to_static(lambda a: a * 2)
        fn(paddle.ones([4]))
        metrics.counter("io.samples").inc(64)
        for step in range(5):
            cb.on_train_batch_end(step)
        logs = {}
        cb.on_epoch_end(0, logs)
        cb.on_train_end()
        assert logs["steps_per_sec"] > 0
        assert logs["retraces"] >= 1
        assert logs["samples_per_sec"] > 0
        assert logs["tokens_per_sec"] == \
            pytest.approx(logs["samples_per_sec"] * 128)
        assert "peak_memory_bytes" in logs
        assert "[metrics]" in capsys.readouterr().out
        assert not metrics.is_enabled()  # restored

    def test_dataloader_counts_batches(self):
        metrics.enable()
        from paddle_tpu.io import DataLoader, Dataset

        class Ds(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return np.full((4,), i, np.float32)

        before = metrics.counter("io.batches").value
        n = sum(1 for _ in DataLoader(Ds(), batch_size=2))
        assert n == 4
        snap = metrics.snapshot()
        assert snap["io.batches"]["value"] - before == 4
        assert snap["io.samples"]["value"] >= 8


class TestGradScalerCounters:
    def test_skip_counted(self):
        metrics.enable()
        from paddle_tpu.amp import GradScaler

        class FakeOpt:
            _parameter_list = []

            def step(self):
                pass

        s = GradScaler(init_loss_scaling=8.0, decr_every_n_nan_or_inf=1)
        s._found_inf = False
        opt = FakeOpt()
        s.unscale_ = lambda o: None  # keep _found_inf as set below
        s.step(opt)
        s._found_inf = True
        s.step(opt)
        snap = metrics.snapshot()
        assert snap["amp.scaler.steps"]["value"] == 2
        assert snap["amp.scaler.skipped"]["value"] == 1
        assert snap["amp.loss_scale"]["value"] > 0
