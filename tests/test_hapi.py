"""hapi.Model tests (≈ the reference's test_model.py: fit/evaluate/
predict loops, callbacks, checkpointing, early stopping)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.hapi import (EarlyStopping, Model, ModelCheckpoint,
                             ProgBarLogger)
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.nn import functional as F


class ToyDataset(Dataset):
    """Linearly separable 2-class problem; the labeling hyperplane is
    fixed so different seeds draw train/eval splits of the SAME task."""

    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.x = rng.standard_normal((n, 8)).astype(np.float32)
        w = np.random.RandomState(42).standard_normal((8,))
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _model():
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
    m = Model(net)
    m.prepare(
        optimizer=optimizer.Adam(learning_rate=0.01,
                                 parameters=net.parameters()),
        loss=lambda out, lbl: F.cross_entropy(out, lbl),
        metrics=Accuracy())
    return m


class TestModelFit:
    def test_fit_converges_and_evaluates(self, capsys):
        paddle.seed(0)
        m = _model()
        m.fit(ToyDataset(), eval_data=ToyDataset(seed=1), batch_size=16,
              epochs=4, verbose=0)
        logs = m.evaluate(ToyDataset(seed=1), batch_size=16, verbose=0)
        assert logs["acc"] > 0.8, logs
        assert logs["loss"] < 0.7

    def test_predict_shapes(self):
        paddle.seed(0)
        m = _model()
        out = m.predict(ToyDataset(n=32), batch_size=8)
        assert out[0].shape == (32, 2)

    def test_train_batch_scalar_loss(self):
        paddle.seed(0)
        m = _model()
        ds = ToyDataset()
        # train_batch returns the ON-DEVICE scalar loss (non-blocking);
        # float() is the explicit host read-back
        loss = m.train_batch(ds.x[:8], ds.y[:8])
        assert loss.shape == [] or tuple(loss.shape) == ()
        assert np.isfinite(float(loss))

    def test_eval_batch_compiled_and_async(self):
        """eval loss is computed INSIDE the jitted eval step (one
        compile across batches) and returned as an on-device scalar,
        same contract as train_batch."""
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.profiler import metrics
        paddle.seed(0)
        m = _model()
        ds = ToyDataset()
        metrics.reset()
        metrics.enable()
        try:
            _, l1 = m.eval_batch(ds.x[:8], ds.y[:8])
            _, l2 = m.eval_batch(ds.x[8:16], ds.y[8:16])
            snap = metrics.snapshot()
        finally:
            metrics.disable()
        assert isinstance(l1, Tensor)  # read back only on float()
        assert np.isfinite(float(l1)) and np.isfinite(float(l2))
        compiles = snap.get("jit.compile.total", {}).get("value", 0)
        assert compiles == 1, f"eval step retraced: {compiles} compiles"

    def test_eval_callbacks_observe_float_losses(self):
        """on_eval_batch_end keeps the float contract (lagged, like
        train): every batch observed exactly once, in order."""
        from paddle_tpu.hapi.callbacks import Callback
        paddle.seed(0)
        m = _model()

        class Rec(Callback):
            seen = []

            def on_eval_batch_end(self, step, logs=None):
                Rec.seen.append((step, logs["loss"]))

        m.evaluate(ToyDataset(n=32), batch_size=8, verbose=0,
                   callbacks=[Rec()])
        assert [s for s, _ in Rec.seen] == [0, 1, 2, 3]
        assert all(isinstance(l, float) and np.isfinite(l)
                   for _, l in Rec.seen)

    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(0)
        m = _model()
        ds = ToyDataset()
        m.train_batch(ds.x[:16], ds.y[:16])
        path = str(tmp_path / "ckpt" / "model")
        m.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")

        paddle.seed(123)
        m2 = _model()
        m2.load(path)
        a = m.predict_batch(ds.x[:4]).numpy()
        b = m2.predict_batch(ds.x[:4]).numpy()
        np.testing.assert_allclose(a, b, atol=1e-6)

    def test_model_checkpoint_callback(self, tmp_path):
        paddle.seed(0)
        m = _model()
        save_dir = str(tmp_path / "ckpts")
        m.fit(ToyDataset(n=32), batch_size=16, epochs=2, verbose=0,
              save_dir=save_dir)
        assert os.path.exists(os.path.join(save_dir, "0.pdparams"))
        assert os.path.exists(os.path.join(save_dir, "final.pdparams"))

    def test_early_stopping(self):
        paddle.seed(0)
        m = _model()
        stopper = EarlyStopping(monitor="loss", patience=0, min_delta=10.0)
        # min_delta so large that no improvement counts: stops after
        # the second eval
        epochs_run = []

        class Spy(ProgBarLogger):
            def on_epoch_begin(self, epoch, logs=None):
                epochs_run.append(epoch)
                super().on_epoch_begin(epoch, logs)

        m.fit(ToyDataset(n=32), eval_data=ToyDataset(n=32, seed=1),
              batch_size=16, epochs=10, verbose=0,
              callbacks=[stopper, Spy(verbose=0)])
        assert stopper.stopped
        assert len(epochs_run) < 10

    def test_summary_counts_params(self, capsys):
        m = _model()
        info = m.summary()
        expect = 8 * 32 + 32 + 32 * 2 + 2
        assert info["total_params"] == expect


def test_summary_and_flops():
    import io
    import contextlib
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        info = paddle.summary(net, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
    assert info["trainable_params"] == info["total_params"]
    out = buf.getvalue()
    assert "Linear" in out and "Total params" in out
    f = paddle.flops(net, (1, 8))
    # at least the two matmuls' MACs
    assert f >= 2 * 8 * 16
    assert isinstance(paddle.Model, type)
