"""Chunked prefill (ISSUE 20): page-aligned prefill chunks interleaved
with decode.

Covers: the knob's validation surface (alignment, ring safety, env
parsing), the correctness gate (chunked admissions bitwise-equal to the
sequential Predictor reference — dense, paged, and int8-quant, with
arrivals mid-decode), the steady-state invariant extended to the chunk
programs (zero compiles under chunked traffic after warmup), the
PENDING_PREFILL slot state and its health/readiness surface
(pending_prefill_tokens / prefill_chunks_queued), mid-prefill rollback
(deadline expiry and drain release the committed pages — free-list
conserved), the audit/memory-plan extension (donation coverage 1.0 on
the chunk pair + span install), the serve.prefill.* metrics +
serve.prefill_chunk flight events, and the chunk attention kernel's
parity against the naive reference (XLA dispatch path and the Pallas
q-tiled kernel in interpret mode, wide and int8). The chaos-tier
SIGTERM-mid-prefill test and the TTFT head-of-line gate live at the
bottom (chaos / slow markers).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.models.gpt import gpt
from paddle_tpu.serving import RequestParams, RequestStatus, ServingEngine

import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


def _spec():
    return [paddle.to_tensor(np.zeros((2, 12), np.int32))]


def _config(m, *, max_new=8, buckets=(16, 32), max_batch=2, eos=None,
            kv_dtype=None, **serving_kw):
    cfg = (Config().from_layer(m, _spec())
           .enable_generation(max_new_tokens=max_new,
                              prefill_buckets=buckets,
                              max_batch=max_batch, eos_token_id=eos,
                              kv_cache_dtype=kv_dtype))
    cfg.enable_serving(**serving_kw)
    return cfg


def _counter(name):
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


@pytest.fixture(scope="module")
def reference(tiny_gpt):
    pred = create_predictor(
        Config().from_layer(tiny_gpt, _spec())
        .enable_generation(max_new_tokens=8, prefill_buckets=(16, 32),
                           max_batch=1))
    return lambda p, b=8: pred.generate([p], max_new_tokens=b)[0]


def _prompts(seed=0):
    """The adversarial mix: two chunk-worthy long prompts among
    shorts."""
    rng = np.random.RandomState(seed)
    lens = (5, 24, 12, 20, 7)
    return [rng.randint(0, 512, n).astype(np.int32) for n in lens]


# ----------------------------------------------------------- validation


def test_chunk_knob_validation(tiny_gpt):
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=0),
                      warmup=False)
    # paged: chunks must be page-aligned (span installs never straddle)
    with pytest.raises(ValueError, match="multiple"):
        ServingEngine(_config(tiny_gpt, paged=True, kv_page_size=8,
                              prefill_chunk_tokens=12), warmup=False)
    # ring safety: the final chunk is right-padded to a multiple of C;
    # ceil(32/24)*24 = 48 > cache_max_len 40 would wrap the ring onto
    # the row's own prefix
    with pytest.raises(ValueError, match="cache"):
        ServingEngine(_config(tiny_gpt, cache_max_len=40,
                              prefill_chunk_tokens=24), warmup=False)
    # a cap at/above the largest bucket disables chunking (inline
    # prefill already covers every admissible prompt)
    eng = ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=32),
                        warmup=False)
    assert not eng._chunk_enabled


def test_chunk_env_knob(tiny_gpt, monkeypatch):
    monkeypatch.setenv("PADDLE_PREFILL_CHUNK_TOKENS", "16")
    eng = ServingEngine(_config(tiny_gpt), warmup=False)
    assert eng.prefill_chunk_tokens == 16 and eng._chunk_enabled
    # garbage env falls back (recorded, not raised) — the constructor
    # must never die on a deploy-environment typo
    monkeypatch.setenv("PADDLE_PREFILL_CHUNK_TOKENS", "lots")
    eng = ServingEngine(_config(tiny_gpt), warmup=False)
    assert eng.prefill_chunk_tokens is None


# -------------------------------------------- the correctness invariant


def test_chunked_dense_matches_sequential(tiny_gpt, reference):
    """THE gate: long prompts admitted in chunks while short requests
    decode, zero compiles after warmup, every completion bitwise-equal
    to the sequential Predictor."""
    from paddle_tpu.core import monitor
    eng = ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=8,
                                max_queue=8), poll_every=2)
    prompts = _prompts()
    monitor.enable()
    try:
        ns0 = _counter("jit.compile{cause=new_shape}")
        tot0 = _counter("jit.compile.total")
        handles = [eng.submit(prompts[0])]     # short: decoding first
        for _ in range(3):
            eng.step()
        handles += [eng.submit(p) for p in prompts[1:]]
        while eng.busy:
            eng.step()
        assert _counter("jit.compile{cause=new_shape}") - ns0 == 0
        assert _counter("jit.compile.total") - tot0 == 0
    finally:
        monitor.disable()
    assert all(h.status is RequestStatus.COMPLETED for h in handles)
    for h, p in zip(handles, prompts):
        np.testing.assert_array_equal(h.tokens, reference(p))
    eng.shutdown()


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_chunked_paged_matches_sequential(tiny_gpt, reference, kv_dtype):
    """Chunked admission over the paged pool (span installs + final
    page-table commit), wide and int8-quant: bitwise parity with the
    matching sequential reference, pool conserved after traffic."""
    eng = ServingEngine(_config(tiny_gpt, paged=True, kv_page_size=8,
                                prefill_chunk_tokens=8, max_queue=8,
                                kv_cache_dtype=kv_dtype), poll_every=2)
    if kv_dtype is None:
        ref = reference
    else:
        pred = create_predictor(
            Config().from_layer(tiny_gpt, _spec())
            .enable_generation(max_new_tokens=8,
                               prefill_buckets=(16, 32), max_batch=1,
                               kv_cache_dtype="int8"))
        ref = lambda p: pred.generate([p], max_new_tokens=8)[0]  # noqa
    prompts = _prompts(seed=1)
    handles = [eng.submit(prompts[0])]
    for _ in range(2):
        eng.step()
    handles += [eng.submit(p) for p in prompts[1:]]
    while eng.busy:
        eng.step()
    assert all(h.status is RequestStatus.COMPLETED for h in handles)
    for h, p in zip(handles, prompts):
        np.testing.assert_array_equal(h.tokens, ref(p))
    assert eng._alloc.used_pages() == 0
    eng._alloc.assert_conserved()
    eng.shutdown()


# ------------------------------------- PENDING_PREFILL state + health


def test_pending_prefill_never_decoded(tiny_gpt):
    """Mid-chunking the slot holds PENDING_PREFILL: no tokens emitted,
    the health/readiness surface reports the backlog, and the final
    chunk flips it RUNNING with the first token."""
    eng = ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=8),
                        poll_every=1)
    long_p = np.arange(1, 25, dtype=np.int32)          # 24 -> 3 chunks
    h = eng.submit(long_p)
    eng.step()                                  # chunk 0 dispatched
    assert h.status is RequestStatus.PENDING_PREFILL
    assert h.n_emitted == 0 and h.first_token_at is None
    health = eng.health()
    assert health["prefill_chunks_queued"] >= 1
    assert health["pending_prefill_tokens"] >= 8
    while eng.busy:
        eng.step()
    assert h.status is RequestStatus.COMPLETED
    assert h.tokens.size == 8
    health = eng.health()
    assert health["prefill_chunks_queued"] == 0
    assert health["pending_prefill_tokens"] == 0
    eng.shutdown()


def test_chunked_admission_interleaves_and_serializes(tiny_gpt):
    """While one long prompt chunks, later short arrivals are still
    admitted into FREE slots (the interleave: decode traffic keeps
    flowing) — but a second chunk-worthy prompt parks at the queue head
    until the first finishes (ONE side cache, strict FIFO — the
    scheduler never interleaves two chunked prefills)."""
    eng = ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=8,
                                max_batch=2, max_queue=8), poll_every=1)
    long_a = np.arange(1, 25, dtype=np.int32)
    long_b = np.arange(2, 26, dtype=np.int32)
    short = np.array([3, 1, 4], np.int32)
    ha = eng.submit(long_a)
    eng.step()
    assert ha.status is RequestStatus.PENDING_PREFILL
    hs = eng.submit(short)
    hb = eng.submit(long_b)
    eng.step()
    # the short took the free slot mid-chunking; the second long is
    # parked (chunking busy) with its pages uncommitted
    assert hs.status in (RequestStatus.RUNNING, RequestStatus.COMPLETED)
    assert hb.status is RequestStatus.QUEUED
    while eng.busy:
        eng.step()
    assert all(h.status is RequestStatus.COMPLETED
               for h in (ha, hb, hs))
    eng.shutdown()


# -------------------------------------------------- mid-prefill rollback


def test_deadline_mid_prefill_releases_pages(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, paged=True, kv_page_size=8,
                                prefill_chunk_tokens=8), poll_every=1)
    h = eng.submit(np.arange(1, 25, dtype=np.int32),
                   RequestParams(deadline_s=0.05))
    eng.step()                                  # chunking underway
    assert h.status is RequestStatus.PENDING_PREFILL
    held = eng._alloc.used_pages()
    assert held > 0                             # pages committed
    time.sleep(0.08)
    eng.step()                                  # deadline check fires
    assert h.done() and h.status is RequestStatus.CANCELLED
    assert h.detail == "deadline"
    assert eng._alloc.used_pages() == 0
    eng._alloc.assert_conserved()
    # the slot is reusable: a fresh request completes
    h2 = eng.submit(np.array([1, 2, 3], np.int32))
    while eng.busy:
        eng.step()
    assert h2.status is RequestStatus.COMPLETED
    eng._alloc.assert_conserved()
    eng.shutdown()


def test_drain_mid_prefill_terminal_and_conserved(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, paged=True, kv_page_size=8,
                                prefill_chunk_tokens=8,
                                drain_timeout_s=30.0), poll_every=1)
    h = eng.submit(np.arange(1, 25, dtype=np.int32))
    eng.step()
    assert h.status is RequestStatus.PENDING_PREFILL
    eng.drain()
    assert h.done() and h.status is RequestStatus.CANCELLED
    assert h.detail == "shutdown"
    assert eng._alloc.used_pages() == 0
    eng._alloc.assert_conserved()


# ------------------------------------------- audit / memory-plan / docs


def test_audit_chunk_programs_donate_fully(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, paged=True, kv_page_size=8,
                                prefill_chunk_tokens=8), warmup=False)
    rs = eng.audit()
    for key in (("chunk", 8), ("chunk_final", 8), ("install_span",)):
        rep = rs[key]
        rep.raise_on_error()
        assert rep.donation_coverage == 1.0, key


def test_memory_plan_covers_chunk_program(tiny_gpt):
    eng = ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=8),
                        warmup=False)
    mp = eng.memory_plan()
    assert mp["chunk_peak_bytes"] > 0
    assert mp["predicted_peak_bytes"] >= mp["kv_cache_bytes"]


# --------------------------------------- metrics + flight-recorder trail


def test_chunk_metrics_and_flight_events(tiny_gpt):
    from paddle_tpu.core import flight_recorder, monitor
    eng = ServingEngine(_config(tiny_gpt, prefill_chunk_tokens=8,
                                trace_sample=1), poll_every=1)
    monitor.enable()
    try:
        c0 = _counter("serve.prefill.chunks")
        t0 = _counter("serve.prefill.chunk_tokens")
        h = eng.submit(np.arange(1, 25, dtype=np.int32))   # 3 chunks
        while eng.busy:
            eng.step()
        assert h.status is RequestStatus.COMPLETED
        assert _counter("serve.prefill.chunks") - c0 == 3
        assert _counter("serve.prefill.chunk_tokens") - t0 == 24
        from paddle_tpu.profiler import metrics as _m
        assert "serve.prefill.interleave_ratio" in _m.snapshot()
    finally:
        monitor.disable()
    evs = [f for _, k, f in flight_recorder.events()
           if k == "serve.prefill_chunk" and f.get("req") == h.id]
    assert [e["chunk"] for e in evs] == [0, 1, 2]
    assert sum(e["tokens"] for e in evs) == 24
    assert evs[-1]["remaining"] == 0
    # the traced request carries per-chunk spans (the preemption-dump
    # evidence the chaos test asserts end to end)
    spans = [s for s in flight_recorder.spans_between(0, 2 ** 62)
             if s[0] == f"req{h.id}.prefill_chunk"]
    assert len(spans) == 3
    eng.shutdown()


# ----------------------------------------------- chunk attention kernel


def _naive_decode(q, kc, vc, kv_len):
    b, sq, h, d = q.shape
    t = kc.shape[1]
    scale = 1.0 / np.sqrt(d)
    out = np.zeros_like(q, dtype=np.float32)
    for bi in range(b):
        for hi in range(h):
            s = (q[bi, :, hi] @ kc[bi, :, hi].T) * scale
            for i in range(sq):
                lim = kv_len[bi] - sq + i
                mask = np.arange(t) <= lim
                e = np.exp(s[i] - s[i][mask].max()) * mask
                out[bi, i, hi] = (e / e.sum()) @ vc[bi, :, hi]
    return out


@pytest.mark.parametrize("sq", [5, 16, 24])
def test_flash_attention_chunk_parity(sq):
    """The public chunk entry (XLA dispatch on CPU) against the naive
    causal-window reference — q_len past the decode kernel's 8-row
    cap."""
    from paddle_tpu.kernels.flash_attention import flash_attention_chunk
    rng = np.random.RandomState(1)
    b, h, d, t = 2, 4, 64, 256
    kv = np.array([sq + 3, 250], np.int32)
    q = rng.randn(b, sq, h, d).astype(np.float32)
    kc = rng.randn(b, t, h, d).astype(np.float32)
    vc = rng.randn(b, t, h, d).astype(np.float32)
    out = np.asarray(flash_attention_chunk(q, kc, vc, kv))
    np.testing.assert_allclose(out, _naive_decode(q, kc, vc, kv),
                               rtol=2e-5, atol=2e-5)


def test_chunk_pallas_interpret_parity():
    """The q-tiled Pallas kernel itself (interpret mode): per-tile
    causal window shift, GQA head mapping, k-block skipping — including
    a padded tail tile (sq 20 pads to 24, tile rows overhang)."""
    from paddle_tpu.kernels.flash_attention import _chunk_pallas
    rng = np.random.RandomState(2)
    b, hq, hk, d, t, sq = 2, 4, 2, 64, 256, 20
    group = hq // hk
    kv = np.array([sq + 5, 250], np.int32)
    q = rng.randn(b, sq, hq, d).astype(np.float32)
    kc = rng.randn(b, t, hk, d).astype(np.float32)
    vc = rng.randn(b, t, hk, d).astype(np.float32)
    qt = jnp.swapaxes(jnp.asarray(q), 1, 2).reshape(b * hq, sq, d)
    kt = jnp.swapaxes(jnp.asarray(kc), 1, 2).reshape(b * hk, t, d)
    vt = jnp.swapaxes(jnp.asarray(vc), 1, 2).reshape(b * hk, t, d)
    out = _chunk_pallas(qt, kt, vt, jnp.repeat(jnp.asarray(kv), hk),
                        1.0 / np.sqrt(d), block_k=128, group=group)
    out = np.asarray(jnp.swapaxes(out.reshape(b, hq, sq, d), 1, 2))
    ref = _naive_decode(q, np.repeat(kc, group, 2),
                        np.repeat(vc, group, 2), kv)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_chunk_pallas_int8_interpret_parity():
    """Fused int8 dequant through the q-tiled kernel (interpret mode)
    against the XLA fused-dequant fallback."""
    from paddle_tpu.kernels.flash_attention import (_chunk_pallas,
                                                    _decode_xla)
    rng = np.random.RandomState(3)
    B, T, D, sq = 2, 128, 64, 12
    k8 = rng.randint(-127, 128, (B, T, D)).astype(np.int8)
    v8 = rng.randint(-127, 128, (B, T, D)).astype(np.int8)
    ks = jnp.asarray(rng.uniform(0.005, 0.02, (B, T))
                     .astype(np.float32)).astype(jnp.bfloat16)
    vs = jnp.asarray(rng.uniform(0.005, 0.02, (B, T))
                     .astype(np.float32)).astype(jnp.bfloat16)
    q = rng.randn(B, sq, D).astype(np.float32)
    kv_len = jnp.asarray(np.array([sq + 25, 100], np.int32))
    args = (jnp.asarray(q), jnp.asarray(k8), jnp.asarray(v8), kv_len,
            float(D ** -0.5))
    ref = _decode_xla(*args, ks=ks, vs=vs)
    out = _chunk_pallas(*args, k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_cached_attention_dispatches_chunk_past_decode_cap():
    """The dense decode branch routes q_len > MAX_DECODE_QLEN to the
    chunk kernel instead of dying on the decode kernel's row cap."""
    from paddle_tpu.generation.attention import cached_attention
    from paddle_tpu.generation.kv_cache import KVCache
    rng = np.random.RandomState(4)
    b, h, d, t, sq = 1, 2, 64, 64, 12
    cache = KVCache.create(1, b, t, h, d)
    q = paddle.to_tensor(rng.randn(b, sq, h, d).astype(np.float32))
    k = paddle.to_tensor(rng.randn(b, sq, h, d).astype(np.float32))
    v = paddle.to_tensor(rng.randn(b, sq, h, d).astype(np.float32))
    out, cache = cached_attention(q, k, v, cache, 0, decode=True,
                                  causal=True)
    ref = _naive_decode(q.numpy(), np.asarray(cache.k[0]),
                        np.asarray(cache.v[0]),
                        np.full((b,), sq, np.int32))
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_sigterm_mid_chunked_prefill(tiny_gpt, tmp_path, monkeypatch):
    """SIGTERM landing while a chunked prefill is in flight under live
    Poisson traffic: every handle reaches a terminal status, the
    mid-prefill request's committed pages are released (free-list
    conserved), and the preemption dump carries the partial per-chunk
    spans — the post-mortem shows exactly how far the prompt got."""
    import glob
    import json
    import os
    import signal
    import threading
    from paddle_tpu.core import flight_recorder
    from paddle_tpu.distributed.resilience import GracefulShutdown

    monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
    eng = ServingEngine(_config(tiny_gpt, paged=True, kv_page_size=8,
                                prefill_chunk_tokens=8, max_queue=16,
                                trace_sample=1, drain_timeout_s=0.0),
                        poll_every=1)
    rng = np.random.RandomState(7)
    h_long = eng.submit(np.arange(1, 25, dtype=np.int32))
    shorts = []

    def feeder():
        for i in range(6):
            time.sleep(float(rng.exponential(0.004)))
            shorts.append(eng.submit(
                rng.randint(0, 512, 3 + i % 5).astype(np.int32)))

    th = threading.Thread(target=feeder, daemon=True)
    th.start()
    # step until the long prompt is mid-chunking (>= 1 chunk landed,
    # not yet admitted)
    for _ in range(200):
        eng.step()
        st = eng._chunking
        if h_long.status is RequestStatus.PENDING_PREFILL \
                and st is not None and st["next"] >= 1:
            break
    assert h_long.status is RequestStatus.PENDING_PREFILL
    th.join()
    # clear the per-reason rate limit + cap so THIS dump isn't swallowed
    # by earlier chaos tests' dumps
    flight_recorder._recorder._last_auto.pop("preemption", None)
    flight_recorder._recorder._auto_dumps = 0
    with GracefulShutdown(store=None, exit_on_save=False) as gs:
        os.kill(os.getpid(), signal.SIGTERM)
        assert gs.check(step=1)          # preemption dump, no exit
        eng.drain()                      # drain window 0: cancel all
    assert h_long.done() and h_long.status is RequestStatus.CANCELLED
    assert all(h.done() and h.status.terminal for h in shorts)
    assert eng._alloc.used_pages() == 0
    eng._alloc.assert_conserved()
    dumps = glob.glob(str(tmp_path / "flightrecorder_preemption_*.json"))
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        doc = json.load(f)
    chunk_spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"
                   and e.get("name") == f"req{h_long.id}.prefill_chunk"]
    assert 1 <= len(chunk_spans) < 3     # partial: killed mid-prefill
    assert doc["metadata"]["reason"] == "preemption"


@pytest.mark.slow
def test_short_request_ttft_head_of_line_gate():
    """The ISSUE-20 acceptance gate (slow tier): the `bench.py serve
    --adversarial` row — short-request Poisson traffic with periodic
    long-prompt injections, inline vs chunked at equal HBM — must show
    chunked short-request TTFT p99 >= 3x better (vs_baseline >= 1.0)
    with zero compiles under traffic in both passes. Runs the bench
    function itself so the gate and the published row can't diverge."""
    import jax

    from bench import bench_serve_adversarial
    row = bench_serve_adversarial(jax.devices()[0],
                                  jax.default_backend() == "tpu")
    assert row["vs_baseline"] >= 1.0, row["metric"]
    for mode in ("inline", "chunked"):
        assert row[mode]["counters"]["jit.compile.total"] == 0, mode
    assert row["chunked"]["prefill_chunks"] > 0
