"""RNN layers, fft/signal/linalg namespaces, text (viterbi, datasets),
onnx export (reference: test_rnn_op.py / test_fft.py / test_stft_op.py
/ test_viterbi_decode_op.py analogs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


# --------------------------------------------------------------------- RNN
def test_lstm_cell_step():
    paddle.seed(0)
    cell = nn.LSTMCell(4, 8)
    x = paddle.randn([2, 4])
    out, (h, c) = cell(x)
    assert tuple(out.shape) == (2, 8)
    assert tuple(h.shape) == (2, 8) and tuple(c.shape) == (2, 8)
    np.testing.assert_allclose(out.numpy(), h.numpy())


def test_gru_cell_matches_manual():
    paddle.seed(1)
    cell = nn.GRUCell(3, 5)
    x = paddle.randn([2, 3])
    h0 = paddle.zeros([2, 5])
    out, h = cell(x, (h0,))
    # manual recompute
    W_ih = cell.weight_ih.numpy()
    W_hh = cell.weight_hh.numpy()
    b_ih = cell.bias_ih.numpy()
    b_hh = cell.bias_hh.numpy()
    xg = x.numpy() @ W_ih.T + b_ih
    hg = np.zeros((2, 5 * 3)) + b_hh
    xr, xz, xc = np.split(xg, 3, -1)
    hr, hz, hc = np.split(hg, 3, -1)
    sig = lambda v: 1 / (1 + np.exp(-v))
    r, z = sig(xr + hr), sig(xz + hz)
    c = np.tanh(xc + r * hc)
    expect = (1 - z) * c
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4,
                               atol=1e-5)


def test_lstm_sequence_shapes_and_state():
    paddle.seed(0)
    lstm = nn.LSTM(input_size=6, hidden_size=8, num_layers=2)
    x = paddle.randn([3, 5, 6])  # [B, T, C]
    out, (h, c) = lstm(x)
    assert tuple(out.shape) == (3, 5, 8)
    assert tuple(h.shape) == (2, 3, 8)  # [L*D, B, H]
    assert tuple(c.shape) == (2, 3, 8)
    # final h of last layer equals last output step
    np.testing.assert_allclose(out.numpy()[:, -1], h.numpy()[1],
                               rtol=1e-5)


def test_bidirectional_gru():
    paddle.seed(0)
    gru = nn.GRU(input_size=4, hidden_size=6, direction="bidirect")
    x = paddle.randn([2, 7, 4])
    out, h = gru(x)
    assert tuple(out.shape) == (2, 7, 12)
    assert tuple(h.shape) == (2, 2, 6)


def test_simple_rnn_time_major_and_initial_state():
    paddle.seed(0)
    rnn = nn.SimpleRNN(input_size=3, hidden_size=4, time_major=True)
    x = paddle.randn([5, 2, 3])  # [T, B, C]
    h0 = paddle.randn([1, 2, 4])
    out, h = rnn(x, h0)
    assert tuple(out.shape) == (5, 2, 4)
    assert tuple(h.shape) == (1, 2, 4)


def test_rnn_wrapper_and_grads():
    paddle.seed(0)
    rnn = nn.RNN(nn.LSTMCell(3, 4))
    x = paddle.randn([2, 6, 3])
    x.stop_gradient = False
    out, _ = rnn(x)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()
    for p in rnn.parameters():
        assert p.grad is not None


def test_lstm_trains():
    paddle.seed(0)
    from paddle_tpu import optimizer
    model = nn.Sequential(nn.LSTM(4, 8))

    class Head(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lstm = nn.LSTM(4, 8)
            self.fc = nn.Linear(8, 2)

        def forward(self, x):
            out, _ = self.lstm(x)
            return self.fc(out[:, -1])

    m = Head()
    opt = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    ce = nn.CrossEntropyLoss()
    x = paddle.randn([8, 5, 4])
    y = paddle.to_tensor(np.random.RandomState(0).randint(0, 2, 8))
    first = None
    for _ in range(15):
        loss = ce(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
    assert float(loss) < first


# --------------------------------------------------------------- fft/signal
def test_fft_roundtrip_and_rfft():
    from paddle_tpu import fft
    x = paddle.randn([4, 16])
    X = fft.fft(x)
    back = fft.ifft(X)
    np.testing.assert_allclose(np.real(back.numpy()), x.numpy(),
                               atol=1e-5)
    R = fft.rfft(x)
    assert tuple(R.shape) == (4, 9)
    np.testing.assert_allclose(fft.irfft(R, n=16).numpy(), x.numpy(),
                               atol=1e-5)
    np.testing.assert_allclose(
        fft.fftfreq(8, d=0.5).numpy(), np.fft.fftfreq(8, d=0.5))


def test_fft2_matches_numpy():
    from paddle_tpu import fft
    x = paddle.randn([3, 8, 8])
    np.testing.assert_allclose(fft.fft2(x).numpy(),
                               np.fft.fft2(x.numpy()), rtol=1e-4,
                               atol=1e-4)


def test_stft_istft_roundtrip():
    from paddle_tpu import signal
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 512).astype(np.float32))
    win = paddle.to_tensor(np.hanning(128).astype(np.float32))
    spec = signal.stft(x, n_fft=128, hop_length=32, window=win)
    assert tuple(spec.shape)[:2] == (2, 65)
    back = signal.istft(spec, n_fft=128, hop_length=32, window=win,
                        length=512)
    # interior reconstructs (edges lose energy to the window)
    np.testing.assert_allclose(back.numpy()[:, 64:-64],
                               x.numpy()[:, 64:-64], atol=1e-3)


def test_frame_overlap_add_inverse():
    from paddle_tpu import signal
    x = paddle.to_tensor(np.arange(16, dtype=np.float32))
    f = signal.frame(x, frame_length=4, hop_length=4)
    assert tuple(f.shape) == (4, 4)
    back = signal.overlap_add(f, hop_length=4)
    np.testing.assert_allclose(back.numpy(), x.numpy())


# ------------------------------------------------------------------ linalg
def test_linalg_namespace():
    from paddle_tpu import linalg
    a = paddle.to_tensor(np.array([[2.0, 0], [1, 3]], np.float32))
    np.testing.assert_allclose(float(linalg.det(a)), 6.0, rtol=1e-5)
    lu_mat, piv = linalg.lu(a)
    assert lu_mat.numpy().shape == (2, 2)
    md = linalg.multi_dot([a, a, a])
    np.testing.assert_allclose(md.numpy(),
                               a.numpy() @ a.numpy() @ a.numpy(),
                               rtol=1e-5)


# -------------------------------------------------------------------- text
def test_viterbi_decode_against_bruteforce():
    from paddle_tpu.text import viterbi_decode
    rng = np.random.RandomState(0)
    B, T, N = 2, 4, 3
    pots = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    scores, paths = viterbi_decode(
        paddle.to_tensor(pots), paddle.to_tensor(trans),
        include_bos_eos_tag=False)
    # brute force
    import itertools
    for b in range(B):
        best, best_path = -1e9, None
        for path in itertools.product(range(N), repeat=T):
            s = pots[b, 0, path[0]]
            for t in range(1, T):
                s += trans[path[t - 1], path[t]] + pots[b, t, path[t]]
            if s > best:
                best, best_path = s, path
        np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                   rtol=1e-4)
        np.testing.assert_array_equal(paths.numpy()[b], best_path)


def test_viterbi_bos_eos_convention():
    """include_bos_eos_tag=True: last tag = BOS row, second-to-last =
    EOS column, both inside the [N, N] transition (reference layout)."""
    from paddle_tpu.text import viterbi_decode
    rng = np.random.RandomState(1)
    B, T, N = 1, 3, 4  # tags: 0, 1, EOS(2), BOS(3)
    pots = rng.randn(B, T, N).astype(np.float32)
    trans = rng.randn(N, N).astype(np.float32)
    scores, paths = viterbi_decode(paddle.to_tensor(pots),
                                   paddle.to_tensor(trans),
                                   include_bos_eos_tag=True)
    import itertools
    best, best_path = -1e9, None
    for path in itertools.product(range(N), repeat=T):
        s = trans[-1, path[0]] + pots[0, 0, path[0]]
        for t in range(1, T):
            s += trans[path[t - 1], path[t]] + pots[0, t, path[t]]
        s += trans[path[-1], -2]
        if s > best:
            best, best_path = s, path
    np.testing.assert_allclose(float(scores.numpy()[0]), best,
                               rtol=1e-4)
    np.testing.assert_array_equal(paths.numpy()[0], best_path)


def test_imdb_tar_and_cutoff(tmp_path):
    import io
    import tarfile as tf
    from paddle_tpu.text import datasets as TD
    # tiny aclImdb-layout tar: "good" appears 3x, rest once
    buf = {"aclImdb/train/pos/0.txt": b"good good movie",
           "aclImdb/train/pos/1.txt": b"good fine",
           "aclImdb/train/neg/0.txt": b"bad awful"}
    tar_path = tmp_path / "aclImdb.tar.gz"
    with tf.open(tar_path, "w:gz") as t:
        for name, data in buf.items():
            info = tf.TarInfo(name)
            info.size = len(data)
            t.addfile(info, io.BytesIO(data))
    ds = TD.Imdb(data_dir=str(tar_path), mode="train", cutoff=2)
    assert len(ds) == 3
    # only "good" (freq 3 > 2) makes the vocab; everything else is unk
    assert list(ds.word_idx) == ["good", "<unk>"]
    ids, label = ds[0]
    assert label in (0, 1)


def test_text_datasets(tmp_path):
    from paddle_tpu.text import datasets as TD
    with pytest.raises(RuntimeError, match="download"):
        TD.Imdb()
    # UCIHousing from a local file
    rng = np.random.RandomState(0)
    data = rng.rand(50, 14).astype(np.float32)
    np.savetxt(tmp_path / "housing.data", data)
    tr = TD.UCIHousing(data_file=str(tmp_path / "housing.data"))
    te = TD.UCIHousing(data_file=str(tmp_path / "housing.data"),
                       mode="test")
    assert len(tr) == 40 and len(te) == 10
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)
    ds = TD.FakeTextClassification(size=8, seq_len=16)
    ids, label = ds[3]
    assert ids.shape == (16,) and 0 <= label < 2


# -------------------------------------------------------------------- onnx
def test_onnx_export_stablehlo(tmp_path):
    from paddle_tpu import onnx as ponnx
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 2))
    x = paddle.randn([1, 4])
    out_path = ponnx.export(model, str(tmp_path / "m"), input_spec=[x])
    assert out_path.endswith(".stablehlo")
    loaded = paddle.jit.load(str(tmp_path / "m"))
    np.testing.assert_allclose(loaded(x).numpy(), model(x).numpy(),
                               rtol=1e-5)
    # r3: format="onnx" emits REAL ONNX protobuf (onnx_proto.py)
    p2 = ponnx.export(model, str(tmp_path / "m2"), input_spec=[x],
                      format="onnx")
    assert p2.endswith(".onnx")
    from paddle_tpu.onnx_proto import parse_wire
    fields = {f: v for f, w, v in parse_wire(open(p2, "rb").read())}
    assert fields[1] == 8  # ir_version


# ------------------------------------------------------------------- audio
def test_audio_features():
    from paddle_tpu import audio
    rng = np.random.RandomState(0)
    wave = paddle.to_tensor(rng.randn(1, 2048).astype(np.float32))
    spec = audio.Spectrogram(n_fft=256, hop_length=128)(wave)
    assert tuple(spec.shape)[1] == 129  # n_fft//2 + 1
    assert (spec.numpy() >= 0).all()
    mel = audio.MelSpectrogram(sr=16000, n_fft=256, hop_length=128,
                               n_mels=32)(wave)
    assert tuple(mel.shape)[1] == 32
    logmel = audio.LogMelSpectrogram(sr=16000, n_fft=256,
                                     hop_length=128, n_mels=32)(wave)
    assert np.isfinite(logmel.numpy()).all()
    mfcc = audio.MFCC(sr=16000, n_mfcc=13, n_fft=256,
                      hop_length=128, n_mels=32)(wave)
    assert tuple(mfcc.shape)[1] == 13


def test_audio_functional():
    from paddle_tpu.audio import functional as AF
    # mel scale round trip
    hz = np.array([100.0, 440.0, 4000.0])
    np.testing.assert_allclose(AF.mel_to_hz(AF.hz_to_mel(hz)), hz,
                               rtol=1e-6)
    np.testing.assert_allclose(
        AF.mel_to_hz(AF.hz_to_mel(hz, htk=True), htk=True), hz,
        rtol=1e-6)
    fb = AF.compute_fbank_matrix(16000, 256, n_mels=20)
    assert fb.shape == (20, 129)
    assert (fb >= 0).all()
    dct = AF.create_dct(13, 20)
    assert dct.shape == (20, 13)
    # orthonormal columns
    np.testing.assert_allclose(dct.T @ dct, np.eye(13), atol=1e-5)
    w = AF.get_window("hann", 64)
    assert w.shape == (64,) and abs(w[0]) < 1e-6



def test_fft_family_vs_numpy():
    """Every fft-family op vs the numpy.fft reference (the sweep's
    EXCEPTIONS entries point here — reference analog: the spectral
    OpTest cases)."""
    import paddle_tpu.fft as pfft
    rng2 = np.random.RandomState(3)
    xr = rng2.randn(4, 8).astype(np.float32)
    xc = (rng2.randn(4, 8) + 1j * rng2.randn(4, 8)).astype(np.complex64)
    half = (rng2.randn(4, 5) + 1j * rng2.randn(4, 5)).astype(
        np.complex64)

    cases = [
        ("fft", xc, lambda a: np.fft.fft(a)),
        ("ifft", xc, lambda a: np.fft.ifft(a)),
        ("fft2", xc, lambda a: np.fft.fft2(a)),
        ("ifft2", xc, lambda a: np.fft.ifft2(a)),
        ("fftn", xc, lambda a: np.fft.fftn(a)),
        ("ifftn", xc, lambda a: np.fft.ifftn(a)),
        ("rfft", xr, lambda a: np.fft.rfft(a)),
        ("rfft2", xr, lambda a: np.fft.rfft2(a)),
        ("rfftn", xr, lambda a: np.fft.rfftn(a)),
        ("irfft", half, lambda a: np.fft.irfft(a)),
        ("irfft2", half, lambda a: np.fft.irfft2(a)),
        ("irfftn", half, lambda a: np.fft.irfftn(a)),
        ("hfft", half, lambda a: np.fft.hfft(a)),
        ("ihfft", xr, lambda a: np.fft.ihfft(a)),
        ("fftshift", xr, lambda a: np.fft.fftshift(a)),
        ("ifftshift", xr, lambda a: np.fft.ifftshift(a)),
    ]
    for name, x, ref in cases:
        got = np.asarray(getattr(pfft, name)(paddle.to_tensor(x)).data)
        np.testing.assert_allclose(got, ref(x), rtol=2e-4, atol=2e-4,
                                   err_msg=name)
    # hermitian 2d/nd variants: numpy lacks them; scipy.fft is the
    # oracle
    import scipy.fft as sfft
    for name, x in (("hfft2", half), ("hfftn", half),
                    ("ihfft2", xr), ("ihfftn", xr)):
        got = np.asarray(getattr(pfft, name)(paddle.to_tensor(x)).data)
        ref2 = getattr(sfft, name)(x)
        np.testing.assert_allclose(got, ref2, rtol=2e-4, atol=2e-4,
                                   err_msg=name)
