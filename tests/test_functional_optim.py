"""Functional quasi-Newton minimizers (VERDICT r3 Next #7) vs
scipy.optimize goldens: Rosenbrock (the reference's own test problem,
incubate/optimizer/functional tests), an ill-conditioned quadratic
(line-search + curvature-update correctness), and a small-net fit.
Reference analog: python/paddle/incubate/optimizer/functional/
{bfgs,lbfgs}.py.
"""
import numpy as np
import pytest
from scipy import optimize as sciopt

import paddle_tpu as paddle
from paddle_tpu.incubate.optimizer.functional import (minimize_bfgs,
                                                      minimize_lbfgs)


def rosenbrock(x):
    return ((1 - x[0]) ** 2 + 100 * (x[1] - x[0] ** 2) ** 2)


def _np_rosen(x):
    return float((1 - x[0]) ** 2 + 100 * (x[1] - x[0] ** 2) ** 2)


@pytest.mark.parametrize("minimize", [minimize_bfgs, minimize_lbfgs])
def test_rosenbrock_converges_to_scipy_minimum(minimize):
    x0 = np.array([-1.2, 1.0], np.float32)
    ref = sciopt.minimize(_np_rosen, x0.astype(np.float64),
                          method="BFGS")
    ok, nfev, x, f, g = minimize(rosenbrock, paddle.to_tensor(x0),
                                 max_iters=200, tolerance_grad=1e-5)
    assert bool(np.asarray(ok.data)), "did not converge"
    np.testing.assert_allclose(np.asarray(x.data), ref.x, rtol=1e-2,
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(x.data), [1.0, 1.0],
                               rtol=1e-2, atol=1e-3)
    assert float(np.asarray(f.data)) < 1e-5
    assert int(np.asarray(nfev.data)) > 0


@pytest.mark.parametrize("minimize", [minimize_bfgs, minimize_lbfgs])
def test_illconditioned_quadratic(minimize):
    import jax
    rng = np.random.RandomState(0)
    # condition number 1e2: tight enough to exercise the curvature
    # updates, while keeping tolerance_grad=1e-4 above the fp32
    # cancellation noise of the gradient A@x - b near the optimum
    d = np.geomspace(1.0, 1e2, 6).astype(np.float32)
    q, _ = np.linalg.qr(rng.randn(6, 6).astype(np.float32))
    A = (q * d) @ q.T
    b = rng.randn(6).astype(np.float32)
    x_star = np.linalg.solve(A, b)

    def quad(x):
        return 0.5 * (x * (paddle.to_tensor(A) @ x)).sum() \
            - (paddle.to_tensor(b) * x).sum()

    # XLA:CPU's reduced-precision fp32 dot puts the gradient noise
    # floor above tolerance_grad; force full-precision contractions
    with jax.default_matmul_precision("highest"):
        ok, _, x, _, g = minimize(
            quad, paddle.to_tensor(np.zeros(6, np.float32)),
            max_iters=300, tolerance_grad=1e-4)
    assert bool(np.asarray(ok.data))
    np.testing.assert_allclose(np.asarray(x.data), x_star, rtol=1e-2,
                               atol=1e-2)


def test_lbfgs_small_net_fit():
    """Fit a tiny MLP's flattened parameter vector to a regression
    target — the 'train a small net with L-BFGS' golden. Loss must
    drop by >100x from the init."""
    rng = np.random.RandomState(1)
    xs = rng.randn(64, 4).astype(np.float32)
    w_true = rng.randn(4, 1).astype(np.float32)
    ys = np.tanh(xs @ w_true)

    w1_shape, w2_shape = (4, 8), (8, 1)
    n1 = np.prod(w1_shape)

    def unpack(theta):
        w1 = theta[:n1].reshape(w1_shape)
        w2 = theta[n1:].reshape(w2_shape)
        return w1, w2

    xt = paddle.to_tensor(xs)
    yt = paddle.to_tensor(ys)

    def loss(theta):
        w1, w2 = unpack(theta)
        pred = paddle.tanh(xt @ w1) @ w2
        return ((pred - yt) ** 2).mean()

    theta0 = (rng.randn(n1 + np.prod(w2_shape)) * 0.5).astype(np.float32)
    f_init = float(np.asarray(loss(paddle.to_tensor(theta0)).data))
    ok, _, theta, f, _ = minimize_lbfgs(
        loss, paddle.to_tensor(theta0), history_size=10, max_iters=200,
        tolerance_grad=1e-6)
    f_final = float(np.asarray(f.data))
    assert f_final < f_init / 100, (f_init, f_final)


def test_lbfgs_matches_bfgs_small_history():
    # with history >= iterations the two-loop recursion spans the full
    # curvature history; both should find the same minimum
    x0 = paddle.to_tensor(np.array([2.0, 2.0], np.float32))
    _, _, xb, fb, _ = minimize_bfgs(rosenbrock, x0, max_iters=150,
                                    tolerance_grad=1e-5)
    _, _, xl, fl, _ = minimize_lbfgs(rosenbrock, x0, history_size=150,
                                     max_iters=150, tolerance_grad=1e-5)
    np.testing.assert_allclose(np.asarray(xb.data), np.asarray(xl.data),
                               rtol=5e-2, atol=5e-3)


def test_already_converged_and_errors():
    # starting at the minimum: immediate convergence, 1 function call
    x0 = paddle.to_tensor(np.array([1.0, 1.0], np.float32))
    ok, nfev, x, f, g = minimize_bfgs(rosenbrock, x0,
                                      tolerance_grad=1e-3)
    assert bool(np.asarray(ok.data))
    assert int(np.asarray(nfev.data)) == 1
    with pytest.raises(NotImplementedError):
        minimize_bfgs(rosenbrock, x0, line_search_fn="armijo")
    with pytest.raises(NotImplementedError):
        minimize_lbfgs(rosenbrock, x0,
                       initial_inverse_hessian_estimate=np.eye(2))
