"""Launcher / store / spawn / elastic / rpc tests.

Mirrors the reference's pattern of proving distributed plumbing with
single-host multi-process runs (SURVEY.md §4: TestDistBase
test_dist_base.py:901 subprocess workers + env contract assertions).
"""
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from paddle_tpu.distributed.store import TCPStore, free_port
from paddle_tpu.distributed import elastic as el

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ store
def test_tcp_store_set_get_add():
    store = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        store.set("k", {"a": 1})
        assert store.get("k") == {"a": 1}
        assert store.add("n", 2) == 2
        assert store.add("n", 3) == 5
        assert store.delete("k") is True
        assert store.delete("k") is False
        with pytest.raises(TimeoutError):
            store.get("missing", timeout=0.2)
    finally:
        store.shutdown_server()


def test_tcp_store_multiclient_wait_and_barrier():
    master = TCPStore("127.0.0.1", 0, is_master=True)
    port = master.port
    results = []

    def client(i):
        c = TCPStore("127.0.0.1", port)
        c.barrier("b1", 3, timeout=10.0)
        results.append(i)
        c.close()

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        assert results == []  # barrier holds until the 3rd participant
        master.barrier("b1", 3, timeout=10.0)
        for t in threads:
            t.join(10.0)
        assert sorted(results) == [0, 1]
    finally:
        master.shutdown_server()


# ------------------------------------------------------------------ launch
def test_launch_cli_env_contract(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        out = os.environ["OUT_DIR"]
        rank = os.environ["PADDLE_TRAINER_ID"]
        info = {k: os.environ.get(k) for k in
                ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
                 "PADDLE_MASTER", "PADDLE_LOCAL_RANK", "PADDLE_JOB_ID")}
        info["argv"] = sys.argv[1:]
        with open(os.path.join(out, f"rank{rank}.json"), "w") as f:
            json.dump(info, f)
    """))
    env = dict(os.environ, OUT_DIR=str(tmp_path), PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--job_id", "jtest",
         "--log_dir", str(tmp_path / "logs"),
         str(script), "--foo", "bar"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    import json
    infos = [json.load(open(tmp_path / f"rank{i}.json"))
             for i in range(2)]
    assert [i["PADDLE_TRAINER_ID"] for i in infos] == ["0", "1"]
    assert all(i["PADDLE_TRAINERS_NUM"] == "2" for i in infos)
    assert all(i["PADDLE_JOB_ID"] == "jtest" for i in infos)
    assert all(i["argv"] == ["--foo", "bar"] for i in infos)
    assert (tmp_path / "logs" / "workerlog.0").exists()


def test_launch_cli_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 3


_CHAOS_WORKER = """
import json
import os
import signal
import sys

sys.path.insert(0, os.environ["REPO"])
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=1")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.auto_checkpoint import (ExeTrainStatus,
                                                    train_epoch_range)

rank = os.environ.get("PADDLE_TRAINER_ID", "0")
KILL_EPOCH = int(os.environ.get("KILL_EPOCH", "-1")) \
    if rank == os.environ.get("KILL_RANK", "0") else -1
marker = os.environ.get("KILL_MARKER", "")

paddle.seed(0)
net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
rng = np.random.RandomState(0)
x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
y = paddle.to_tensor(rng.randn(16, 1).astype(np.float32))

os.environ["PADDLE_JOB_ID"] = os.environ["PADDLE_JOB_ID"] + "_r" + rank
status = ExeTrainStatus()
final = None
for epoch in train_epoch_range(6, status=status):
    if status.state.get("weights") is not None:
        # restored leaves arrive as framework Tensors
        net.set_state_dict(dict(status.state["weights"]))
        status.state["weights"] = None  # restore once per incarnation
    out = net(x)
    loss = ((out - y) ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()
    final = float(np.asarray(loss.data))
    if epoch == KILL_EPOCH and marker and not os.path.exists(marker):
        open(marker, "w").close()
        os.kill(os.getpid(), signal.SIGKILL)   # hard preemption
    status.update(weights={k: np.asarray(v.data)
                           for k, v in net.state_dict().items()},
                  loss=final)

if final is None:
    # a relaunched incarnation can resume PAST the last epoch (this
    # rank had already completed every epoch before the pod teardown
    # got to it — a real scheduling race under load): the loop yields
    # nothing, and the honest result is the checkpointed final loss
    final = status.state.get("loss")

with open(os.environ["RESULT_JSON"] + "." + rank, "w") as f:
    json.dump({"loss": final}, f)
"""


@pytest.mark.slow  # ~20s multi-process relaunch e2e on CPU: tier-2
def test_preemption_chaos_resume_parity(tmp_path):
    """VERDICT r3 Next #6: SIGKILL a worker mid-epoch (a real kill,
    not exit-101 cooperation), let the launcher's fault-elastic path
    relaunch it, resume from the auto checkpoint, and land on the SAME
    final loss as an uninterrupted run."""
    script = tmp_path / "chaos_worker.py"
    script.write_text(textwrap.dedent(_CHAOS_WORKER))

    def run(job, kill_epoch, extra_args):
        env = dict(os.environ, REPO=REPO, PYTHONPATH=REPO,
                   PADDLE_RUNNING_ENV="PADDLE_EDL_AUTO_CHECKPOINT",
                   PADDLE_EDL_HDFS_CHECKPOINT_PATH=str(tmp_path / job),
                   KILL_EPOCH=str(kill_epoch), KILL_RANK="0",
                   KILL_MARKER=str(tmp_path / f"{job}.killed"),
                   RESULT_JSON=str(tmp_path / f"{job}.json"))
        env["PADDLE_JOB_ID"] = job
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--job_id", job, *extra_args,
             str(script)],
            env=env, capture_output=True, text=True, timeout=300)
        return r

    # uninterrupted reference run (2-worker pod)
    r0 = run("plain", -1, [])
    assert r0.returncode == 0, r0.stderr
    import json
    ref = [json.load(open(str(tmp_path / "plain.json") + f".{i}"))
           ["loss"] for i in range(2)]

    # chaos run: SIGKILL rank 0 mid-epoch-2; the controller tears the
    # POD down (rank 1 dies with it, possibly mid-epoch too),
    # fault-elastic relaunches everyone, each rank resumes from its
    # own auto checkpoint
    r1 = run("chaos", 2, ["--max_restarts", "2",
                          "--elastic_on_failure"])
    assert r1.returncode == 0, r1.stderr
    assert (tmp_path / "chaos.killed").exists(), \
        "the kill never happened — the chaos leg tested nothing"
    # interrupted epochs were never snapshotted: the restart redoes
    # them from the last completed state, so BOTH ranks' trajectories
    # are identical to the uninterrupted run
    for i in range(2):
        chaos = json.load(open(str(tmp_path / "chaos.json")
                               + f".{i}"))["loss"]
        assert abs(chaos - ref[i]) < 1e-6, (i, chaos, ref[i])

    # without elastic_on_failure a signal death still propagates
    r2 = run("nofault", 2, ["--max_restarts", "2"])
    assert r2.returncode != 0


def test_launch_elastic_restart(tmp_path):
    # worker exits 101 (elastic restart) once, then succeeds
    script = tmp_path / "elastic_worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        marker = os.environ["MARKER"] + os.environ["PADDLE_TRAINER_ID"]
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(101)
        sys.exit(0)
    """))
    env = dict(os.environ, PYTHONPATH=REPO,
               MARKER=str(tmp_path / "m"))
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--max_restarts", "1", str(script)],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr


# ------------------------------------------------------------------ spawn
def _spawn_target(out_dir):
    import json
    import os
    rank = os.environ["PADDLE_TRAINER_ID"]
    with open(os.path.join(out_dir, f"spawn{rank}.json"), "w") as f:
        json.dump({"rank": rank,
                   "world": os.environ["PADDLE_TRAINERS_NUM"]}, f)


def test_spawn(tmp_path):
    from paddle_tpu.distributed.spawn import spawn
    spawn(_spawn_target, args=(str(tmp_path),), nprocs=2)
    import json
    infos = [json.load(open(tmp_path / f"spawn{i}.json"))
             for i in range(2)]
    assert sorted(i["rank"] for i in infos) == ["0", "1"]
    assert all(i["world"] == "2" for i in infos)


def _spawn_fail(_):
    raise ValueError("boom")


def test_spawn_raises_on_child_failure(tmp_path):
    from paddle_tpu.distributed.spawn import spawn
    with pytest.raises(RuntimeError, match="boom"):
        spawn(_spawn_fail, args=(str(tmp_path),), nprocs=1)


def _skip_if_no_multiprocess_cpu(r):
    """Some jaxlib builds ship a CPU client without cross-process
    collectives ("Multiprocess computations aren't implemented on the
    CPU backend") — a toolchain capability gap, not a launcher bug."""
    if "Multiprocess computations aren't implemented" in (r.stderr or ""):
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")


def test_launch_multiprocess_jax_distributed(tmp_path):
    """Two real processes rendezvous via jax.distributed (the TCPStore
    analog) and run a cross-process allgather — the reference's
    test_dist_base subprocess-cluster pattern on the TPU stack."""
    script = tmp_path / "jd_worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        sys.path.insert(0, {REPO!r})
        import paddle_tpu.distributed as dist
        env = dist.init_parallel_env()
        import jax, jax.numpy as jnp
        assert jax.process_count() == 2
        from jax.experimental import multihost_utils
        got = multihost_utils.process_allgather(
            jnp.array([jax.process_index()]))
        assert sorted(int(x) for x in got.ravel()) == [0, 1]
    """))
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        env=env, capture_output=True, text=True, timeout=240)
    _skip_if_no_multiprocess_cpu(r)
    assert r.returncode == 0, r.stderr


def test_launch_multihost_global_mesh(tmp_path):
    """2 processes x 4 virtual devices = one 8-device GLOBAL mesh:
    multi-host SPMD with cross-process psum — the multi-pod execution
    model (each host drives its slice-local chips, XLA routes the
    collective) proven on CPU."""
    script = tmp_path / "mesh_worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = \\
            "--xla_force_host_platform_device_count=4"
        sys.path.insert(0, {REPO!r})
        import paddle_tpu.distributed as dist
        dist.init_parallel_env()
        import jax, jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        assert jax.process_count() == 2
        assert jax.device_count() == 8  # global
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
        # each process contributes its local shard; psum crosses hosts
        local = jnp.arange(4.0) + 4.0 * jax.process_index()

        def summed(x):
            return jax.lax.psum(x, "dp")

        from jax.experimental import multihost_utils
        global_x = multihost_utils.host_local_array_to_global_array(
            local, mesh, P("dp"))
        from paddle_tpu.core.jaxshim import shard_map
        out = jax.jit(shard_map(summed, mesh=mesh, in_specs=P("dp"),
                                out_specs=P()))(global_x)
        # fully replicated result: every host reads its local replica
        total = float(np.asarray(out.addressable_data(0)).ravel()[0])
        assert total == sum(range(8)), total
    """))
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", str(script)],
        env=env, capture_output=True, text=True, timeout=300)
    _skip_if_no_multiprocess_cpu(r)
    assert r.returncode == 0, r.stderr[-3000:]


# ----------------------------------------------------------------- elastic
def test_elastic_membership_and_scale_event():
    store = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        m1 = el.ElasticManager(store, "job1", (1, 4), host="h1",
                               heartbeat_timeout=30.0)
        m2 = el.ElasticManager(store, "job1", (1, 4), host="h2",
                               heartbeat_timeout=30.0)
        m1.register()
        assert m1.hosts() == ["h1"]
        events = []
        w = threading.Thread(
            target=m1.watch,
            kwargs=dict(on_scale=events.append, poll=0.05, max_events=1),
            daemon=True)
        w.start()
        time.sleep(0.15)
        m2.register()  # scale-up event
        w.join(10.0)
        assert events and events[0] == ["h1", "h2"]
        m2.deregister()
        assert m1.hosts() == ["h1"]
    finally:
        store.shutdown_server()


def test_elastic_watch_dip_below_min_then_rejoin_fires_once():
    """Scale-event semantics: the alive set dipping below min_np fires
    NOTHING (not a viable mesh), and the same host rejoining fires
    EXACTLY one event once the set is viable again."""
    store = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        m1 = el.ElasticManager(store, "job2", (2, 3), host="h1",
                               heartbeat_timeout=30.0)
        m2 = el.ElasticManager(store, "job2", (2, 3), host="h2",
                               heartbeat_timeout=30.0)
        m1.register()
        m2.register()
        events = []
        w = threading.Thread(
            target=m1.watch,
            kwargs=dict(on_scale=events.append, poll=0.05, max_events=1),
            daemon=True)
        w.start()
        time.sleep(0.2)
        assert events == []  # steady viable membership: no event
        m2.deregister()      # dip to 1 < min_np=2: tracked, not fired
        time.sleep(0.3)
        assert events == []
        m2.register()        # rejoin: viable again -> exactly one event
        w.join(10.0)
        assert not w.is_alive()
        assert events == [["h1", "h2"]]
    finally:
        store.shutdown_server()


def test_elastic_deregister_logs_swallowed_store_error():
    """deregister on a dead store must not raise — and must not be
    silent either: the swallowed exception is counted via the monitor."""
    from paddle_tpu.profiler import metrics
    store = TCPStore("127.0.0.1", 0, is_master=True)
    m = el.ElasticManager(store, "job3", (1, 2), host="h1",
                          heartbeat_timeout=30.0)
    m.register()
    store.shutdown_server()
    dead = TCPStore("127.0.0.1", store.port, timeout=0.3)
    m.store = dead
    was = metrics.is_enabled()
    metrics.enable()
    try:
        m.deregister()  # store is gone: swallowed, logged, counted
        snap = metrics.snapshot()
        key = [k for k in snap
               if k.startswith("errors.swallowed") and "elastic" in k]
        assert key, list(snap)[:20]
    finally:
        if not was:
            metrics.disable()
        dead.close()


# --------------------------------------------------------------------- rpc
def _double(x):
    return 2 * x


def test_rpc_single_worker_roundtrip():
    from paddle_tpu.distributed import rpc
    port = free_port()
    store = TCPStore("127.0.0.1", port, is_master=True)
    try:
        rpc.init_rpc("w0", rank=0, world_size=1, store=store)
        assert rpc.rpc_sync("w0", _double, args=(21,)) == 42
        fut = rpc.rpc_async("w0", _double, args=(5,))
        assert fut.result(timeout=10) == 10
        info = rpc.get_worker_info()
        assert info.name == "w0" and info.rank == 0
        rpc.shutdown()
    finally:
        store.shutdown_server()
