"""Paged KV cache with shared-prefix reuse (ISSUE 12).

Covers: PagedKVCache write/install parity with the dense ring cache
(including the dead-lane null-page contract), the PageAllocator's
prefix registry / refcounts / reclaim / conservation invariant, the
paged Pallas decode kernel (interpret mode) against the XLA gather
fallback, THE bitwise-parity gate (ragged mixed-length traffic with
mid-decode arrivals, slot turnover re-anchoring rows at position 0 —
the paged analog of ring-wrap — and zero post-warmup retraces),
mid-decode eviction returning pages, COW-after-share divergence,
speculative (ngram) decode windows over a paged cache, the
no_free_pages/no_free_slots health distinction, the serve.cache.* /
gen.cache.* metrics family, the tier-1 audit gate over the paged
admit/decode/free trio with a seeded regression, and the chaos
SIGTERM drain with shared pages live (free-list conserved).
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.generation.kv_cache import KVCache
from paddle_tpu.generation.paged_cache import PagedKVCache, PageAllocator
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.models.gpt import gpt
from paddle_tpu.serving import RequestParams, RequestStatus, ServingEngine

import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


def _spec():
    return [paddle.to_tensor(np.zeros((2, 12), np.int32))]


def _config(m, *, max_new=8, buckets=(16,), max_batch=2, eos=None,
            speculative=None, **serving_kw):
    cfg = (Config().from_layer(m, _spec())
           .enable_generation(max_new_tokens=max_new,
                              prefill_buckets=buckets,
                              max_batch=max_batch, eos_token_id=eos,
                              speculative=speculative))
    cfg.enable_serving(**serving_kw)
    return cfg


@pytest.fixture(scope="module")
def paged_engine(tiny_gpt):
    """Shared 2-slot paged engine (page 16 over the 128-token cache):
    reused across the parity, COW, eviction, and metrics tests — all
    of which leave it drained of traffic but serviceable."""
    return ServingEngine(_config(tiny_gpt, buckets=(16, 32), paged=True,
                                 kv_page_size=16), poll_every=2)


@pytest.fixture(scope="module")
def reference(tiny_gpt):
    """Sequential one-request-at-a-time dense reference."""
    pred = create_predictor(_config(tiny_gpt, buckets=(16, 32),
                                    max_batch=1))
    return lambda p, b: pred.generate([p], max_new_tokens=b)[0]


def _counter(name):
    from paddle_tpu.profiler import metrics
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


# ---------------------------------------------------------- cache unit


def test_paged_update_matches_dense_and_null_routes():
    """Writes through the page table land where the dense ring would
    put them; a dead lane (write base 0 — the engine's parked-slot
    contract) routes to the null page and cannot corrupt pages its
    stale table still names."""
    rng = np.random.RandomState(0)
    L, B, T, H, D, ps = 2, 2, 32, 2, 8, 8
    P = T // ps
    table = np.arange(1, 1 + B * P, dtype=np.int32).reshape(B, P)
    paged = PagedKVCache.create(L, B, n_pages=1 + B * P, page_size=ps,
                                pages_per_row=P, num_heads=H, head_dim=D)
    paged = PagedKVCache(paged.k, paged.v, jnp.asarray(table),
                         jnp.asarray([5, 9], np.int32))
    dense = KVCache.create(L, B, T, H, D).with_kv_len(
        jnp.asarray([5, 9], np.int32))
    k1 = rng.randn(B, 1, H, D).astype(np.float32)
    v1 = rng.randn(B, 1, H, D).astype(np.float32)
    for layer in range(L):
        paged = paged.update(layer, jnp.asarray(k1), jnp.asarray(v1),
                             paged.kv_len)
        dense = dense.update(layer, jnp.asarray(k1), jnp.asarray(v1),
                             dense.kv_len)
    for r, pos in enumerate((5, 9)):
        page, off = table[r][pos // ps], pos % ps
        np.testing.assert_array_equal(
            np.asarray(paged.k[:, page, off]),
            np.asarray(dense.k[:, r, pos]))
    # dead lane: kv_len 0 -> the write must land on the null page only
    dead = paged.with_kv_len(paged.kv_len.at[1].set(0))
    before = np.asarray(dead.k[:, table[1]])
    dead2 = dead.update(0, jnp.asarray(k1), jnp.asarray(v1), dead.kv_len)
    np.testing.assert_array_equal(np.asarray(dead2.k[:, table[1]]),
                                  before)
    # reset_rows severs the row's pointers too
    reset = paged.reset_rows(jnp.asarray([0]))
    assert np.asarray(reset.page_table)[0].sum() == 0
    assert int(np.asarray(reset.kv_len)[0]) == 0


def test_install_row_skips_shared_prefix_positions():
    """install_row writes only positions >= start: the shared-prefix
    pages' content is referenced, never re-written."""
    rng = np.random.RandomState(1)
    L, T, H, D, ps = 2, 32, 2, 8, 8
    row = KVCache.create(L, 1, T, H, D)
    for layer in range(L):
        row = row.update(layer,
                         jnp.asarray(rng.randn(1, 20, H, D), jnp.float32),
                         jnp.asarray(rng.randn(1, 20, H, D), jnp.float32),
                         jnp.zeros((1,), jnp.int32))
    row = row.with_kv_len(20)
    paged = PagedKVCache.create(L, 1, n_pages=8, page_size=ps,
                                pages_per_row=4, num_heads=H, head_dim=D)
    sentinel = np.full_like(np.asarray(paged.k[:, 1]), 7.0)
    paged = PagedKVCache(paged.k.at[:, 1].set(sentinel), paged.v,
                         paged.page_table, paged.kv_len)
    table_row = jnp.asarray([1, 2, 3, 0], jnp.int32)
    out = paged.install_row(row, 0, table_row, jnp.asarray(8, jnp.int32))
    # page 1 (positions 0..7, below start=8) kept its sentinel content
    np.testing.assert_array_equal(np.asarray(out.k[:, 1]), sentinel)
    # pages 2..3 carry the row's positions 8..19
    np.testing.assert_array_equal(np.asarray(out.k[:, 2]),
                                  np.asarray(row.k[:, 0, 8:16]))
    np.testing.assert_array_equal(np.asarray(out.k[:, 3, :4]),
                                  np.asarray(row.k[:, 0, 16:20]))
    assert int(np.asarray(out.kv_len)[0]) == 20


# ----------------------------------------------------------- allocator


def test_allocator_prefix_registry_and_conservation():
    a = PageAllocator(16, 8)
    ids = np.arange(20, dtype=np.int32)
    plan = a.plan(ids, extra_tokens=8)
    assert (plan.n_private, plan.total_pages, plan.shared_pages,
            plan.cow) == (4, 4, [], False)
    pages = a.commit(plan)
    a.register(plan, pages)
    # identical prompt: both full pages shared, divergence inside the
    # partial third page -> COW
    plan2 = a.plan(ids, extra_tokens=8)
    assert plan2.shared_pages == pages[:2] and plan2.cow
    pages2 = a.commit(plan2)
    assert pages2[:2] == pages[:2] and len(pages2) == 4
    assert a.stats["prefix_hits"] == 1 and a.stats["shared_pages"] == 2
    # a prompt diverging at the second page shares only the first
    ids3 = np.concatenate([ids[:8], ids[:8] + 1, ids[16:]])
    plan3 = a.plan(ids3, extra_tokens=8)
    assert plan3.shared_pages == pages[:1] and not plan3.cow
    # frees: shared pages stay (other rows + registry), private return
    a.free_row(pages2)
    a.free_row(pages)
    a.assert_conserved()
    # registered refcount-0 pages are allocatable and reclaimed LRU
    free_before = a.free_pages()
    big = a.plan(np.arange(100, 164, dtype=np.int32), extra_tokens=48)
    got = a.commit(big)
    assert got is not None and a.stats["reclaimed"] > 0
    a.free_row(got)
    a.assert_conserved()
    assert a.free_pages() == free_before


def test_allocator_exhaustion_returns_none():
    a = PageAllocator(4, 8)   # 3 allocatable pages
    p1 = a.commit(a.plan(np.arange(8, dtype=np.int32), 8))
    assert p1 is not None and len(p1) == 2
    assert a.commit(a.plan(np.arange(24, dtype=np.int32), 8)) is None
    a.free_row(p1)
    a.assert_conserved()


# ------------------------------------------------------- paged kernel


def test_paged_pallas_kernel_interpret_matches_fallback():
    """The scalar-prefetch Pallas kernel (interpret mode off-TPU) and
    the XLA gather fallback agree — the same index-map indirection the
    GQA head mapping uses, extended to page ids."""
    from paddle_tpu.kernels.flash_attention import (
        _paged_decode_pallas, flash_attention_decode_paged)
    rng = np.random.RandomState(1)
    B, P, ps, Hk, D, Hq, sq = 2, 4, 8, 2, 64, 4, 2
    pool_k = rng.randn(1 + B * P, ps, Hk, D).astype(np.float32)
    pool_v = rng.randn(1 + B * P, ps, Hk, D).astype(np.float32)
    table = np.arange(1, 1 + B * P, dtype=np.int32).reshape(B, P)
    kv_len = np.array([13, 27], np.int32)
    q = rng.randn(B, sq, Hq, D).astype(np.float32)
    ref = flash_attention_decode_paged(
        jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
        jnp.asarray(table), jnp.asarray(kv_len))
    qt = jnp.swapaxes(jnp.asarray(q), 1, 2).reshape(B * Hq, sq, D)
    kp = jnp.transpose(jnp.asarray(pool_k), (2, 0, 1, 3))
    vp = jnp.transpose(jnp.asarray(pool_v), (2, 0, 1, 3))
    out = _paged_decode_pallas(qt, kp, vp, jnp.asarray(table),
                               jnp.asarray(kv_len), float(D ** -0.5),
                               group=Hq // Hk, interpret=True)
    out = jnp.swapaxes(out.reshape(B, Hq, sq, D), 1, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# ------------------------------------------- THE bitwise-parity gate


def test_paged_ragged_traffic_bitwise_equal_dense(tiny_gpt, paged_engine,
                                                  reference):
    """THE acceptance gate: ragged prompts and budgets through the
    PAGED engine — arrivals mid-decode, slot turnover re-anchoring
    reused rows at position 0 (the paged ring-wrap analog), zero
    retraces after warmup — with every request bitwise-equal to the
    dense sequential reference, and the free list conserved."""
    from paddle_tpu.core import monitor
    engine = paged_engine
    rng = np.random.RandomState(0)
    lens = (5, 12, 20, 7, 3)
    budgets = (8, 3, 6, 5, 8)
    prompts = [rng.randint(0, 512, n).astype(np.int32) for n in lens]
    reused0 = engine.stats["slots_reused"]

    monitor.enable()
    try:
        ns0 = _counter("jit.compile{cause=new_shape}")
        tot0 = _counter("jit.compile.total")
        handles = [engine.submit(p, RequestParams(max_new_tokens=b))
                   for p, b in zip(prompts[:2], budgets[:2])]
        for _ in range(3):          # both slots now mid-decode
            engine.step()
        handles += [engine.submit(p, RequestParams(max_new_tokens=b))
                    for p, b in zip(prompts[2:], budgets[2:])]
        while engine.busy:
            engine.step()
        assert _counter("jit.compile{cause=new_shape}") - ns0 == 0
        assert _counter("jit.compile.total") - tot0 == 0
    finally:
        monitor.disable()

    assert all(h.status is RequestStatus.COMPLETED for h in handles)
    assert engine.stats["slots_reused"] - reused0 >= 3   # turnover hit
    for p, b, h in zip(prompts, budgets, handles):
        np.testing.assert_array_equal(h.result(), reference(p, b))
    engine._alloc.assert_conserved()


def test_mid_decode_eviction_returns_pages(tiny_gpt, paged_engine,
                                           reference):
    """Deadline eviction mid-decode frees the slot AND its pages; the
    next admission reuses them and still decodes bit-for-bit."""
    engine = paged_engine
    used0 = engine._alloc.used_pages()
    slow = engine.submit(np.arange(1, 8, dtype=np.int32),
                         RequestParams(deadline_s=60.0))
    engine.step()                      # admitted
    assert slow.status is RequestStatus.RUNNING
    assert engine._alloc.used_pages() > used0
    slow.deadline = time.monotonic() - 1e-3
    while not slow.done():
        engine.step()
    assert slow.status is RequestStatus.CANCELLED
    assert engine._alloc.used_pages() == used0   # pages back
    engine._alloc.assert_conserved()
    p = np.arange(3, 9, dtype=np.int32)
    nxt = engine.submit(p, RequestParams(max_new_tokens=6))
    np.testing.assert_array_equal(nxt.result(timeout=60),
                                  reference(p, 6))


def test_cow_after_share_divergence(tiny_gpt, paged_engine, reference):
    """Two requests with an identical 20-token prompt (20 % 16 != 0):
    the second references the first's full page and privatizes the
    partial tail (copy-on-write) before its decode writes diverge.
    Both match the dense reference bit-for-bit."""
    from paddle_tpu.core import monitor
    engine = paged_engine
    stats0 = dict(engine._alloc.stats)
    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 512, 20).astype(np.int32)
    monitor.enable()
    try:
        cow0 = _counter("serve.cache.cow_copies")
        hit0 = _counter("serve.cache.prefix_hits")
        h1 = engine.submit(prompt, RequestParams(max_new_tokens=6))
        while engine.busy:
            engine.step()
        # second arrival AFTER the first finished: its pages are cached
        # in the prefix registry (prefill once, reference many)
        h2 = engine.submit(prompt.copy(), RequestParams(max_new_tokens=8))
        while engine.busy:
            engine.step()
        assert _counter("serve.cache.cow_copies") - cow0 >= 1
        assert _counter("serve.cache.prefix_hits") - hit0 >= 1
    finally:
        monitor.disable()
    s = engine._alloc.stats
    assert s["prefix_hits"] - stats0["prefix_hits"] == 1
    assert s["shared_pages"] - stats0["shared_pages"] == 1
    assert s["cow_copies"] - stats0["cow_copies"] == 1
    np.testing.assert_array_equal(h1.result(), reference(prompt, 6))
    np.testing.assert_array_equal(h2.result(), reference(prompt, 8))
    engine._alloc.assert_conserved()


def test_page_metrics_family(tiny_gpt, paged_engine):
    """serve.cache.* / gen.cache.* land in the registry at the poll
    cadence (the dead-metric lint keeps them recorded; this keeps them
    MOVING)."""
    from paddle_tpu.core import monitor
    from paddle_tpu.profiler import metrics
    engine = paged_engine
    monitor.enable()
    try:
        al0 = _counter("gen.cache.pages_allocated")
        fr0 = _counter("gen.cache.pages_freed")
        hs = [engine.submit(np.arange(1, 6 + i, dtype=np.int32),
                            RequestParams(max_new_tokens=4))
              for i in range(3)]
        while engine.busy:
            engine.step()
        for h in hs:
            h.result(timeout=60)
        assert _counter("gen.cache.pages_allocated") - al0 > 0
        assert _counter("gen.cache.pages_freed") - fr0 > 0
        snap = metrics.snapshot()
        assert snap["serve.cache.page_occupancy"]["peak"] > 0
    finally:
        monitor.disable()


def test_page_blocked_flag_clears_when_head_leaves_queue(tiny_gpt):
    """A page-blocked queue head removed by the deadline sweep must
    clear the pressure flag — health() must not keep steering the
    router toward no_free_pages after the blocker is gone."""
    eng = ServingEngine(_config(tiny_gpt, max_batch=2, paged=True,
                                kv_page_size=16, kv_pages=3,
                                max_queue=4), poll_every=1)
    a = eng.submit(np.arange(1, 16, dtype=np.int32))   # takes both pages
    eng.step()
    late = eng.submit(np.arange(2, 17, dtype=np.int32),
                      RequestParams(deadline_s=60.0))
    eng.step()                                         # blocked on pages
    assert eng.health()["queue_blocked_on"] == "pages"
    late.deadline = time.monotonic() - 1e-3
    eng.step()                                         # sweep cancels it
    assert late.status is RequestStatus.CANCELLED
    assert eng.health()["queue_blocked_on"] is None
    assert a.result(timeout=60).size == 8
    eng._alloc.assert_conserved()
    eng.shutdown()


def test_admission_failure_releases_pages(tiny_gpt):
    """An admission that raises after its page plan committed must roll
    the pages back (no pool shrink, conservation holds) and the engine
    keeps serving."""
    from paddle_tpu.serving import RequestFailed
    eng = ServingEngine(_config(tiny_gpt, max_new=4, max_batch=1,
                                paged=True, kv_page_size=16),
                        poll_every=1)
    orig = eng._exe_prefill
    calls = {"n": 0}

    def flaky(bucket):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected device failure")
        return orig(bucket)

    eng._exe_prefill = flaky
    doomed = eng.submit([1, 2, 3])
    ok = eng.submit([4, 5])
    eng.step()
    assert doomed.done() and doomed.status is RequestStatus.CANCELLED
    with pytest.raises(RequestFailed, match="injected device failure"):
        doomed.result(timeout=5)
    assert ok.result(timeout=60).size == 4   # engine kept serving
    assert eng._alloc.used_pages() == 0      # nothing leaked
    eng._alloc.assert_conserved()
    eng.shutdown()


# ----------------------------------------------- speculative windows


def test_speculative_ngram_over_paged_cache(tiny_gpt):
    """ngram speculative decode windows (k+1-token verify writes +
    rollback) over the paged cache: bitwise-equal to the dense
    speculative engine under greedy decoding."""
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 64, n).astype(np.int32)
               for n in (5, 11, 20, 9)]
    outs = []
    for paged in (False, True):
        eng = ServingEngine(
            _config(tiny_gpt, buckets=(16, 32), speculative="ngram",
                    paged=paged, **({"kv_page_size": 16} if paged
                                    else {})),
            poll_every=2)
        hs = [eng.submit(p, RequestParams(max_new_tokens=8))
              for p in prompts]
        while eng.busy:
            eng.step()
        outs.append([h.result(timeout=60) for h in hs])
        if paged:
            eng._alloc.assert_conserved()
        eng.shutdown()
    for o_dense, o_paged in zip(*outs):
        np.testing.assert_array_equal(o_dense, o_paged)


# --------------------------------------------------- admission health


def test_health_distinguishes_pages_from_slots(tiny_gpt):
    """The item-1 router signal: a queue blocked on POOL MEMORY reports
    no_free_pages; one blocked on decode lanes reports no_free_slots."""
    # 3-page pool (2 allocatable): the second request cannot commit
    eng = ServingEngine(_config(tiny_gpt, max_batch=2, paged=True,
                                kv_page_size=16, kv_pages=3,
                                max_queue=2), poll_every=1)
    a = eng.submit(np.arange(1, 16, dtype=np.int32))   # 2 pages
    eng.step()                                         # admit a
    b = eng.submit(np.arange(2, 17, dtype=np.int32))   # blocked on pages
    eng.submit(np.arange(3, 10, dtype=np.int32))       # queue at bound
    eng.step()
    h = eng.health()
    assert h["queue_blocked_on"] == "pages"
    assert not h["ready"] and "no_free_pages" in h["reason"]
    assert h["free_pages"] == 0 and h["total_pages"] == 2
    while eng.busy:
        eng.step()
    assert a.status is RequestStatus.COMPLETED
    assert b.status is RequestStatus.COMPLETED
    eng._alloc.assert_conserved()
    eng.shutdown()

    # dense engine, both slots busy, queue at bound -> slots
    eng2 = ServingEngine(_config(tiny_gpt, max_batch=1, max_queue=1),
                         poll_every=1)
    eng2.submit(np.arange(1, 8, dtype=np.int32))
    eng2.step()
    eng2.submit(np.arange(1, 5, dtype=np.int32))
    h2 = eng2.health()
    assert h2["queue_blocked_on"] == "slots"
    assert not h2["ready"] and "no_free_slots" in h2["reason"]
    while eng2.busy:
        eng2.step()
    eng2.shutdown()


def test_pool_too_small_for_one_request_fails_fast(tiny_gpt):
    """A pool that could never cover one full-size request must raise
    at construction (naming the knobs), not stall the queue head
    forever."""
    with pytest.raises(ValueError, match="kv_pages"):
        ServingEngine(_config(tiny_gpt, max_batch=1, paged=True,
                              kv_page_size=16, kv_pages=2),
                      warmup=False)


# ------------------------------------------------------- tier-1 audit


def test_paged_audit_gate(tiny_gpt):
    """Zero analysis ERRORs across the paged program trio, donation
    coverage 1.0 on decode and admit — the pool and page tables must
    stay in-place across scheduler steps."""
    eng = ServingEngine(_config(tiny_gpt, buckets=(16, 32), paged=True,
                                kv_page_size=16), warmup=False)
    reports = eng.audit()
    assert set(reports) == {("prefill", 16), ("prefill", 32), "decode",
                            "admit", "free"}
    for rep in reports.values():
        rep.raise_on_error()
    assert not reports["decode"].by_check("host_sync")
    assert reports["decode"].donation_coverage == 1.0
    assert reports["admit"].donation_coverage == 1.0


def test_paged_audit_gate_not_vacuous(tiny_gpt):
    """Seeded regression: a host callback smuggled into the PAGED
    decode program must fail the gate — the new programs are held to
    the same zero-ERROR bar, not grandfathered."""
    import jax
    from paddle_tpu.analysis import AuditError
    eng = ServingEngine(_config(tiny_gpt, max_new=4, max_batch=1,
                                paged=True, kv_page_size=16),
                        warmup=False)
    orig = eng._step_fn

    def poisoned(*args):
        out = orig(*args)
        leak = jax.pure_callback(
            lambda t: np.asarray(t),
            jax.ShapeDtypeStruct((1,), jnp.int32), out[0])
        return (out[0] + leak * 0,) + out[1:]

    eng._step_fn = poisoned
    with pytest.raises(AuditError):
        eng.audit()["decode"].raise_on_error()


# ----------------------------------------------------------------- chaos


@pytest.mark.chaos
def test_sigterm_mid_serve_with_shared_pages_conserves(tiny_gpt):
    """SIGTERM mid-serve while rows SHARE prefix pages: the drain
    leaves every handle terminal and the free list conserved — no
    leaked pages, no double frees, shared refcounts fully unwound."""
    import signal
    from paddle_tpu.distributed.resilience import GracefulShutdown
    from paddle_tpu.utils.fault_injection import KillAfter

    eng = ServingEngine(_config(tiny_gpt, buckets=(16, 32), max_batch=2,
                                max_queue=8, paged=True, kv_page_size=16,
                                drain_timeout_s=60.0), poll_every=2)
    rng = np.random.RandomState(1)
    base = rng.randint(0, 512, 20).astype(np.int32)
    # every prompt shares the same 20-token prefix -> live shared pages
    # (and COW tails) at the moment the signal lands
    traffic = [np.concatenate([base, rng.randint(0, 512, i + 1)
                               .astype(np.int32)])[:32]
               for i in range(5)]
    killer = KillAfter(4, signal.SIGTERM)
    with GracefulShutdown(exit_on_save=False) as gs:
        handles = eng.serve_forever(
            iter(traffic), on_step=lambda e: killer.step())
        assert gs.preempted
    assert killer.fired
    assert len(handles) == 5
    assert all(h.done() for h in handles), "a request hung"
    assert all(h.status.terminal for h in handles)
    assert any(h.status is RequestStatus.COMPLETED for h in handles)
    eng._alloc.assert_conserved()
    assert eng._alloc.used_pages() == 0
    with pytest.raises(RuntimeError, match="shut down"):
        eng.submit(traffic[0])
