"""bf16 golden dtype sweep (VERDICT r1 Next #6).

Reference analog: unittests/op_test.py check_output_with_place over
bf16 places + white_list tolerances. TPU's native dtype is bfloat16 —
every core op must produce whitelist-bounded results in bf16, eagerly
AND under jit, or numeric regressions (flash attention, fused norms)
would ship silently. Extra finite-difference grad coverage rides along
(VERDICT weak #3).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_grad, check_output_bf16

rng = np.random.RandomState(0)
A23 = rng.randn(2, 3).astype(np.float32)
B23 = rng.randn(2, 3).astype(np.float32)
A34 = rng.randn(3, 4).astype(np.float32)
POS = (np.abs(rng.randn(2, 3)) + 0.1).astype(np.float32)
UNIT = rng.rand(2, 3).astype(np.float32) * 0.8 + 0.1

SWEEP = [
    # (name, fn, numpy ref, inputs, kwargs)
    ("add", paddle.add, np.add, [A23, B23], {}),
    ("subtract", paddle.subtract, np.subtract, [A23, B23], {}),
    ("multiply", paddle.multiply, np.multiply, [A23, B23], {}),
    ("divide", paddle.divide, np.divide, [A23, POS], {}),
    ("maximum", paddle.maximum, np.maximum, [A23, B23], {}),
    ("exp", paddle.exp, np.exp, [A23], {}),
    ("log", paddle.log, np.log, [POS], {}),
    ("log1p", paddle.log1p, np.log1p, [POS], {}),
    ("sqrt", paddle.sqrt, np.sqrt, [POS], {}),
    ("rsqrt", paddle.rsqrt, lambda x: 1 / np.sqrt(x), [POS], {}),
    ("tanh", paddle.tanh, np.tanh, [A23], {}),
    ("sin", paddle.sin, np.sin, [A23], {}),
    ("cos", paddle.cos, np.cos, [A23], {}),
    ("erf", paddle.erf,
     lambda x: np.vectorize(__import__("math").erf)(x).astype(np.float32),
     [A23], {}),
    ("abs", paddle.abs, np.abs, [A23], {}),
    ("square", paddle.square, np.square, [A23], {}),
    ("sigmoid", F.sigmoid, lambda x: 1 / (1 + np.exp(-x)), [A23], {}),
    ("logit", paddle.logit,
     lambda x: np.log(x / (1 - x)), [UNIT], {}),
    ("sum", paddle.sum, lambda x: np.sum(x), [A23], {}),
    ("mean", paddle.mean, lambda x: np.mean(x), [A23], {}),
    ("max", paddle.max, lambda x: np.max(x), [A23], {}),
    ("min", paddle.min, lambda x: np.min(x), [A23], {}),
    ("std", paddle.std,
     lambda x: np.std(x, ddof=1), [A23], {}),
    ("var", paddle.var,
     lambda x: np.var(x, ddof=1), [A23], {}),
    ("logsumexp", paddle.logsumexp,
     lambda x: np.log(np.sum(np.exp(x))), [A23], {}),
    ("cumsum", paddle.cumsum,
     lambda x, axis=None: np.cumsum(x, axis), [A23], {"axis": 1}),
    ("cumprod", paddle.cumprod,
     lambda x, dim=None: np.cumprod(x, dim), [A23], {"dim": 1}),
    ("matmul", paddle.matmul, np.matmul, [A23, A34], {}),
    ("addmm", paddle.addmm,
     lambda i, x, y: i + x @ y,
     [rng.randn(2, 4).astype(np.float32), A23, A34], {}),
    ("kron", paddle.kron, np.kron, [A23, B23], {}),
    ("clip", paddle.clip,
     lambda x, min=None, max=None: np.clip(x, min, max),
     [A23], {"min": -0.5, "max": 0.5}),
    ("floor", paddle.floor, np.floor, [A23], {}),
    ("ceil", paddle.ceil, np.ceil, [A23], {}),
    ("sign", paddle.sign, np.sign, [A23], {}),
    ("reciprocal", paddle.reciprocal, lambda x: 1.0 / x, [POS], {}),
    ("softmax", F.softmax,
     lambda x: np.exp(x - x.max(-1, keepdims=True)) /
     np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True),
     [A23], {}),
    ("relu", F.relu, lambda x: np.maximum(x, 0), [A23], {}),
    ("gelu", F.gelu,
     lambda x: 0.5 * x * (1 + np.vectorize(__import__("math").erf)(
         x / np.sqrt(2)).astype(np.float32)), [A23], {}),
    ("transpose", paddle.transpose,
     lambda x, perm: np.transpose(x, perm), [A23], {"perm": [1, 0]}),
    ("concat", lambda *xs, axis: paddle.concat(list(xs), axis=axis),
     lambda *xs, axis: np.concatenate(xs, axis), [A23, B23], {"axis": 0}),
    ("where", paddle.where,
     lambda c, x, y: np.where(c, x, y),
     [A23 > 0, A23, B23], {}),
    ("pow", paddle.pow, lambda x, y: np.power(x, y), [POS, B23], {}),
    ("lerp", paddle.lerp,
     lambda x, y, w: x + w * (y - x), [A23, B23, np.float32(0.3)], {}),
]


@pytest.mark.parametrize(
    "name,fn,ref,inputs,kwargs", SWEEP, ids=[s[0] for s in SWEEP])
def test_bf16_golden(name, fn, ref, inputs, kwargs):
    check_output_bf16(fn, ref, inputs, kwargs=kwargs, name=name)


# ---- extra finite-difference grad coverage (fp32) ---------------------

GRAD_OPS = [
    ("mul_grad", lambda x, y: (x * y), [A23, B23]),
    ("div_grad", lambda x, y: (x / y), [A23, POS]),
    ("tanh_grad", lambda x: paddle.tanh(x), [A23]),
    ("exp_grad", lambda x: paddle.exp(x), [A23 * 0.3]),
    ("log_grad", lambda x: paddle.log(x), [POS]),
    ("sqrt_grad", lambda x: paddle.sqrt(x), [POS]),
    ("matmul_grad", lambda x, y: paddle.matmul(x, y), [A23, A34]),
    ("softmax_grad", lambda x: F.softmax(x), [A23]),
    ("gelu_grad", lambda x: F.gelu(x), [A23]),
    ("sigmoid_grad", lambda x: F.sigmoid(x), [A23]),
    ("logsumexp_grad", lambda x: paddle.logsumexp(x), [A23]),
    ("mean_grad", lambda x: paddle.mean(x), [A23]),
    ("lerp_grad",
     lambda x, y: paddle.lerp(x, y, paddle.full([], 0.3)), [A23, B23]),
    ("kron_grad", lambda x, y: paddle.kron(x, y), [A23, B23]),
    ("renorm_grad",
     lambda x: paddle.renorm(x, p=2.0, axis=0, max_norm=1.0), [A23]),
    ("logit_grad", lambda x: paddle.logit(x), [UNIT]),
]


@pytest.mark.parametrize("name,fn,inputs", GRAD_OPS,
                         ids=[g[0] for g in GRAD_OPS])
def test_finite_difference_grads(name, fn, inputs):
    check_grad(fn, inputs)
