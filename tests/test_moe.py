"""MoE / expert-parallel tests (≈ the reference's moe tests for
incubate/distributed/models/moe: gate correctness, dispatch/combine
round-trip, and distributed execution on the CPU mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet, topology
from paddle_tpu.distributed.parallel.moe import (
    MoEMLP, aux_loss, load_balance_loss, top_k_routing)


@pytest.fixture(autouse=True)
def _restore_mesh():
    prev = topology.get_hybrid_communicate_group()
    yield
    topology.set_hybrid_communicate_group(prev)


class TestRouting:
    def test_top1_routing_dispatches_to_argmax(self):
        gates = jax.nn.softmax(jnp.asarray(
            np.random.RandomState(0).standard_normal((16, 4))), axis=-1)
        combine, disp, (me, ce) = top_k_routing(gates, top_k=1, capacity=16)
        # every token lands in exactly one (expert, slot)
        np.testing.assert_allclose(np.asarray(jnp.sum(disp, axis=(1, 2))),
                                   np.ones(16))
        chosen = np.asarray(jnp.argmax(jnp.sum(disp, axis=2), axis=1))
        np.testing.assert_array_equal(chosen,
                                      np.asarray(jnp.argmax(gates, axis=1)))
        # combine weight equals the chosen gate prob
        w = np.asarray(jnp.sum(combine, axis=(1, 2)))
        expect = np.asarray(jnp.max(gates, axis=1))
        np.testing.assert_allclose(w, expect, rtol=1e-6)

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0; capacity 2 keeps only 2
        gates = jnp.tile(jnp.asarray([[0.9, 0.1]]), (8, 1))
        combine, disp, _ = top_k_routing(gates, top_k=1, capacity=2)
        assert float(jnp.sum(disp)) == 2.0

    def test_top2_uses_two_experts(self):
        gates = jax.nn.softmax(jnp.asarray(
            np.random.RandomState(0).standard_normal((8, 4))), axis=-1)
        combine, disp, _ = top_k_routing(gates, top_k=2, capacity=8)
        np.testing.assert_allclose(np.asarray(jnp.sum(disp, axis=(1, 2))),
                                   2 * np.ones(8))

    def test_positions_unique_per_expert(self):
        gates = jax.nn.softmax(jnp.asarray(
            np.random.RandomState(1).standard_normal((32, 4))), axis=-1)
        _, disp, _ = top_k_routing(gates, top_k=2, capacity=32)
        # no (expert, slot) used twice
        slot_use = np.asarray(jnp.sum(disp, axis=0))
        assert slot_use.max() <= 1.0 + 1e-6

    def test_load_balance_loss_uniform_is_one(self):
        e = 4
        me = jnp.full((e,), 1.0 / e)
        ce = jnp.full((e,), 1.0 / e)
        assert abs(float(load_balance_loss(me, ce)) - 1.0) < 1e-6


class TestMoEMLP:
    def _dense_reference(self, layer, x):
        """Token-by-token numpy reference with ample capacity."""
        gw = np.asarray(layer.gate_weight.data)
        w1 = np.asarray(layer.w1.data)
        b1 = np.asarray(layer.b1.data)
        w2 = np.asarray(layer.w2.data)
        b2 = np.asarray(layer.b2.data)
        xf = np.asarray(x).reshape(-1, x.shape[-1])
        logits = xf.astype(np.float32) @ gw
        gates = np.exp(logits - logits.max(-1, keepdims=True))
        gates /= gates.sum(-1, keepdims=True)
        out = np.zeros_like(xf)
        for t in range(xf.shape[0]):
            top2 = np.argsort(-gates[t])[:2]
            wsum = gates[t][top2].sum()
            for e in top2:
                h = np.asarray(jax.nn.gelu(xf[t] @ w1[e] + b1[e]))
                y = h @ w2[e] + b2[e]
                out[t] += (gates[t][e] / wsum) * y
        return out.reshape(x.shape)

    def test_matches_dense_reference(self):
        paddle.seed(0)
        layer = MoEMLP(16, 32, num_experts=4, gate="gshard",
                       capacity_factor=100.0)  # ample: nothing dropped
        x = jnp.asarray(np.random.RandomState(0).standard_normal(
            (2, 8, 16)).astype(np.float32))
        out = layer.forward(paddle.to_tensor(np.asarray(x)))
        ref = self._dense_reference(layer, x)
        np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                                   atol=1e-4, rtol=1e-4)
        assert layer.l_aux is not None
        assert float(layer.l_aux) >= 1.0 - 1e-5  # lower bound of the loss

    def test_eager_grads_flow_to_all_params(self):
        paddle.seed(0)
        layer = MoEMLP(8, 16, num_experts=2, gate="switch",
                       capacity_factor=100.0)
        x = paddle.to_tensor(np.random.RandomState(0).standard_normal(
            (4, 8)).astype(np.float32))
        out = layer.forward(x)
        loss = out.pow(2).mean() + 0.01 * aux_loss(layer)
        loss.backward()
        for name, p in layer.named_parameters():
            assert p.grad is not None, f"no grad for {name}"
            assert float(jnp.max(jnp.abs(p.grad.data))) > 0.0, \
                f"zero grad for {name}"

    def test_expert_parallel_matches_single_device(self):
        """ep=4 sharded forward == unsharded forward."""
        paddle.seed(0)
        layer = MoEMLP(16, 32, num_experts=4, gate="gshard",
                       capacity_factor=100.0)
        x = np.random.RandomState(0).standard_normal(
            (32, 16)).astype(np.float32)
        ref = layer.forward(paddle.to_tensor(x)).numpy()

        strategy = fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 2, "ep_degree": 4})
        fleet.init(strategy=strategy)
        out = layer.forward(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)

    def test_in_distributed_train_step(self):
        """MoE transformer-ish model trains under the hybrid mesh with the
        aux loss folded into the objective."""
        from paddle_tpu import nn, optimizer
        strategy = fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 2, "ep_degree": 4})
        fleet.init(strategy=strategy)
        paddle.seed(0)

        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.proj = nn.Linear(16, 16)
                self.moe = MoEMLP(16, 32, num_experts=4, gate="switch",
                                  capacity_factor=2.0)
                self.head = nn.Linear(16, 4)

            def forward(self, x):
                return self.head(self.moe.forward(self.proj(x)))

        model = Net()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())

        def loss_fn(logits, labels):
            from paddle_tpu.nn import functional as F
            ce = F.cross_entropy(logits, labels)
            return ce + 0.01 * aux_loss(model)

        step = fleet.DistributedTrainStep(model, opt, loss_fn)
        x = np.random.RandomState(0).standard_normal(
            (16, 16)).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 4, (16,)).astype(np.int64)
        l0 = float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
        for _ in range(4):
            l = float(step(paddle.to_tensor(x), paddle.to_tensor(y)))
        assert np.isfinite(l)
        assert l < l0, f"MoE loss not dropping: {l0} -> {l}"


class TestGPTMoE:
    def test_gpt_moe_trains_on_ep_mesh(self):
        from paddle_tpu import optimizer
        from paddle_tpu.models.gpt import gpt
        strategy = fleet.DistributedStrategy(
            hybrid_configs={"dp_degree": 2, "ep_degree": 4})
        fleet.init(strategy=strategy)
        paddle.seed(0)
        model = gpt("test-tiny", moe_num_experts=4, moe_gate="gshard",
                    moe_capacity_factor=2.0)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = fleet.DistributedTrainStep(
            model, opt, lambda lo, la: model.loss(lo, la))
        ids = np.random.RandomState(0).randint(0, 512, (4, 32)).astype(
            np.int32)
        l0 = float(step(paddle.to_tensor(ids),
                        paddle.to_tensor(ids.astype(np.int64))))
        for _ in range(3):
            l = float(step(paddle.to_tensor(ids),
                           paddle.to_tensor(ids.astype(np.int64))))
        assert np.isfinite(l) and l < l0, f"GPT-MoE not training {l0}->{l}"


def test_moe_composes_with_recompute():
    """Aux loss crosses the jax.checkpoint boundary as a return value
    (previously rejected in GPTConfig.__post_init__)."""
    from paddle_tpu import optimizer
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.gpt import gpt

    paddle.seed(0)
    strategy = fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 2, "sp_degree": 2, "ep_degree": 2})
    fleet.init(strategy=strategy)
    model = gpt("test-tiny", use_recompute=True, moe_num_experts=4,
                moe_capacity_factor=2.0)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = fleet.DistributedTrainStep(
        model, opt, lambda lo, la: model.loss(lo, la))
    ids = np.random.RandomState(0).randint(0, 512, (4, 32)).astype(
        np.int32)
    loss = float(step(paddle.to_tensor(ids),
                      paddle.to_tensor(ids.astype(np.int64))))
    assert np.isfinite(loss)

    # aux term contributes (weight 0 gives a smaller loss)
    paddle.seed(0)
    m2 = gpt("test-tiny", use_recompute=True, moe_num_experts=4,
             moe_capacity_factor=2.0, moe_aux_weight=0.0)
    o2 = optimizer.AdamW(learning_rate=1e-4, parameters=m2.parameters())
    s2 = fleet.DistributedTrainStep(m2, o2,
                                    lambda lo, la: m2.loss(lo, la))
    loss0 = float(s2(paddle.to_tensor(ids),
                     paddle.to_tensor(ids.astype(np.int64))))
    assert loss > loss0
    # adapters must not duplicate parameters
    names = [n for n, _ in model.named_parameters()]
    assert len(names) == len(set(names))


def test_moe_global_norm_clip_parity_witness():
    """VERDICT r4 Missing #3 witness. The reference ships
    ClipGradForMOEByGlobalNorm (incubate/distributed/models/moe/
    grad_clip.py:21) because under its expert parallelism each rank
    holds ONLY its experts' grads, so a naive global norm is wrong.
    Under GSPMD the expert weights are sharded views of one logical
    array — the plain ClipGradByGlobalNorm reduction compiles to the
    correct global psum. Witness: one clipped step on the dp2 x ep4
    mesh must produce THE SAME parameters as the same clipped step on
    a single device, with a max_norm tight enough that the clip
    actually rescales (asserted). No MoE-special clip class is needed;
    this test is the proof the reference's extra class demands."""
    from paddle_tpu import nn, optimizer

    x = np.random.RandomState(0).standard_normal((16, 16)).astype(
        np.float32)
    y = np.random.RandomState(1).randint(0, 4, (16,)).astype(np.int64)

    class Net(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.proj = nn.Linear(16, 16)
            self.moe = MoEMLP(16, 32, num_experts=4, gate="gshard",
                              capacity_factor=100.0)
            self.head = nn.Linear(16, 4)

        def forward(self, xx):
            return self.head(self.moe.forward(self.proj(xx)))

    def build():
        paddle.seed(0)
        model = Net()

        def loss_fn(logits, labels):
            from paddle_tpu.nn import functional as F
            return F.cross_entropy(logits, labels) + \
                0.01 * aux_loss(model)
        opt = optimizer.AdamW(
            learning_rate=1e-2, parameters=model.parameters(),
            grad_clip=optimizer.ClipGradByGlobalNorm(0.05))
        return model, opt, loss_fn

    # the clip must actually engage: raw global grad norm >> max_norm
    model, _, loss_fn = build()
    out = model(paddle.to_tensor(x))
    loss = loss_fn(out, paddle.to_tensor(y))
    loss.backward()
    gn = np.sqrt(sum(float((np.asarray(p.grad.data) ** 2).sum())
                     for p in model.parameters() if p.grad is not None))
    assert gn > 0.05 * 3, f"grad norm {gn} too small to witness the clip"

    # single-device clipped step
    model, opt, loss_fn = build()
    step = paddle.jit.TrainStep(model, opt, loss_fn)
    step(paddle.to_tensor(x), paddle.to_tensor(y))
    single = {n: np.asarray(p.data) for n, p in model.named_parameters()}

    # dp2 x ep4 clipped step on the 8-device mesh
    strategy = fleet.DistributedStrategy(
        hybrid_configs={"dp_degree": 2, "ep_degree": 4})
    fleet.init(strategy=strategy)
    model, opt, loss_fn = build()
    dstep = fleet.DistributedTrainStep(model, opt, loss_fn)
    dstep(paddle.to_tensor(x), paddle.to_tensor(y))
    for n, p in model.named_parameters():
        np.testing.assert_allclose(
            np.asarray(p.data), single[n], rtol=2e-4, atol=2e-5,
            err_msg=f"clipped update diverged on {n} — the global-norm "
                    f"clip is NOT ep-sharding-correct")


def test_moe_grad_clip_reference_import_path():
    """Reference code importing ClipGradForMOEByGlobalNorm /
    MoELayer from paddle.incubate.distributed.models.moe keeps working;
    the clip aliases the plain global-norm clip (the parity witness
    above proves GSPMD makes the special re-aggregation unnecessary)."""
    from paddle_tpu.incubate.distributed.models.moe import (
        ClipGradForMOEByGlobalNorm, MoELayer)
    from paddle_tpu.optimizer import ClipGradByGlobalNorm
    clip = ClipGradForMOEByGlobalNorm(
        0.5, is_expert_param_func=lambda p: False, moe_group=None)
    assert isinstance(clip, ClipGradByGlobalNorm)
    assert clip.clip_norm == 0.5
    assert MoELayer is MoEMLP
