"""Profiler + native runtime component tests (host tracer ≈
host_event_recorder tests; token feeder ≈ data_feed tests; scheduler
states ≈ test_profiler.py state-machine coverage)."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, ProfilerTarget,
                                 RecordEvent, make_scheduler)


class TestScheduler:
    def test_cycle_states(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=1,
                               skip_first=1)
        states = [sched(i) for i in range(6)]
        assert states == [
            ProfilerState.CLOSED,            # skip_first
            ProfilerState.CLOSED,
            ProfilerState.READY,
            ProfilerState.RECORD,
            ProfilerState.RECORD_AND_RETURN,
            ProfilerState.CLOSED,            # repeat exhausted
        ]

    def test_repeat_forever(self):
        sched = make_scheduler(closed=0, ready=0, record=2)
        assert sched(0) == ProfilerState.RECORD
        assert sched(1) == ProfilerState.RECORD_AND_RETURN
        assert sched(2) == ProfilerState.RECORD

    def test_invalid_raises(self):
        with pytest.raises(ValueError):
            make_scheduler(closed=0, ready=0, record=0)


class TestProfiler:
    def test_records_user_and_op_spans(self, tmp_path):
        collected = []

        def on_ready(p):
            collected.append(p.result)

        p = Profiler(targets=[ProfilerTarget.CPU],
                     scheduler=make_scheduler(closed=0, ready=0, record=2,
                                              repeat=1),
                     on_trace_ready=on_ready)
        p.start()
        for _ in range(3):
            with RecordEvent("my_span"):
                x = paddle.ones([8, 8])
                (x @ x).sum()
            p.step()
        p.stop()
        assert collected, "on_trace_ready never fired"
        events = collected[0].events
        names = {e[0] for e in events}
        assert "my_span" in names
        assert any(n.startswith("op::") for n in names), names

    def test_chrome_trace_export(self, tmp_path):
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        with RecordEvent("span_a"):
            paddle.ones([4]).sum()
        p.stop()
        path = str(tmp_path / "trace.json")
        p.result.export_chrome_tracing(path)
        with open(path) as f:
            data = json.load(f)
        assert any(ev["name"] == "span_a" for ev in data["traceEvents"])
        spans = [ev for ev in data["traceEvents"] if ev["ph"] == "X"]
        assert spans
        for ev in spans:
            assert "ts" in ev and "dur" in ev
        # counter/metadata events ride along in the same trace
        assert all(ev["ph"] in ("X", "C", "M")
                   for ev in data["traceEvents"])

    def test_summary_table(self):
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        with RecordEvent("alpha"):
            pass
        with RecordEvent("alpha"):
            pass
        p.stop()
        table = p.result.summary()
        assert "alpha" in table and "Calls" in table

    def test_op_spans_off_when_not_profiling(self):
        from paddle_tpu.core import prof_hook
        assert not prof_hook.enabled
        paddle.ones([2]).sum()  # must not crash / record


class TestNativeTracer:
    def test_available(self):
        from paddle_tpu import native
        assert native.available(), "native build failed on this machine"

    def test_nested_spans(self):
        p = Profiler(targets=[ProfilerTarget.CPU])
        p.start()
        with RecordEvent("outer"):
            with RecordEvent("inner"):
                pass
        p.stop()
        ev = {e[0]: e for e in p.result.events}
        assert "outer" in ev and "inner" in ev
        # inner nests within outer
        assert ev["inner"][1] >= ev["outer"][1]
        assert ev["inner"][2] <= ev["outer"][2]


class TestTokenLoader:
    @pytest.fixture
    def corpus(self, tmp_path):
        path = str(tmp_path / "toks.bin")
        np.arange(8192, dtype=np.int32).tofile(path)
        return path

    @pytest.mark.parametrize("use_native", [True, False])
    def test_full_epoch_coverage(self, corpus, use_native):
        from paddle_tpu.io import TokenLoader
        loader = TokenLoader(corpus, seq_len=31, batch_size=4,
                             use_native=use_native, seed=7)
        starts = set()
        n = 0
        for x, y in loader:
            assert x.shape == (4, 31) and y.shape == (4, 31)
            assert y.dtype == np.int64
            # labels are inputs shifted by one
            np.testing.assert_array_equal(x[:, 1:], y[:, :-1])
            for row in x:
                starts.add(int(row[0]))
            n += 1
        assert n == len(loader)
        # every sample seen exactly once (corpus is contiguous arange)
        assert len(starts) == n * 4

    @pytest.mark.parametrize("use_native", [True, False])
    def test_rank_sharding_disjoint(self, corpus, use_native):
        from paddle_tpu.io import TokenLoader
        seen = []
        for rank in (0, 1):
            loader = TokenLoader(corpus, seq_len=31, batch_size=4,
                                 rank=rank, world_size=2, seed=3,
                                 use_native=use_native)
            s = set()
            for x, _ in loader:
                s.update(int(r[0]) for r in x)
            seen.append(s)
        assert not (seen[0] & seen[1])

    def test_second_epoch_reshuffles(self, corpus):
        from paddle_tpu.io import TokenLoader
        loader = TokenLoader(corpus, seq_len=31, batch_size=4, seed=11)
        first = [x[0, 0] for x, _ in loader]
        second = [x[0, 0] for x, _ in loader]
        assert len(first) == len(second)
        assert first != second, "epochs not reshuffled"

    def test_trains_gpt_tiny(self, corpus):
        """Input pipeline feeds an actual train step."""
        from paddle_tpu.io import TokenLoader
        from paddle_tpu import optimizer
        from paddle_tpu.models.gpt import gpt
        paddle.seed(0)
        model = gpt("test-tiny", max_position_embeddings=32)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = paddle.jit.TrainStep(
            model, opt, lambda lo, la: model.loss(lo, la))
        loader = TokenLoader(corpus, seq_len=31, batch_size=4)
        losses = []
        for i, (x, y) in enumerate(loader):
            x = np.clip(x, 0, 511)
            y = np.clip(y, 0, 511)
            losses.append(float(step(paddle.to_tensor(x),
                                     paddle.to_tensor(y))))
            if i >= 3:
                break
        assert all(np.isfinite(losses))

    def test_partial_epoch_restart_no_deadlock(self, corpus):
        """Breaking out mid-epoch then re-iterating must not hang."""
        from paddle_tpu.io import TokenLoader
        loader = TokenLoader(corpus, seq_len=31, batch_size=4, seed=5,
                             use_native=True)
        it = iter(loader)
        next(it); next(it)          # consume 2 of many batches
        del it
        n = sum(1 for _ in loader)  # restart: full epoch again
        assert n == len(loader)


class TestSummaryMidRecord:
    def test_summary_does_not_advance_cycle(self, capsys):
        fired = []
        p = Profiler(targets=[ProfilerTarget.CPU],
                     on_trace_ready=lambda prof: fired.append(prof._cycle))
        p.start()
        with RecordEvent("early_span"):
            pass
        p.summary()
        out = capsys.readouterr().out
        assert "early_span" in out
        assert not fired, "summary() fired on_trace_ready"
        assert p._cycle == 0
        with RecordEvent("late_span"):
            pass
        p.stop()
        assert fired == [1]
        names = {e[0] for e in p.result.events}
        assert {"early_span", "late_span"} <= names
