"""Layer system tests (≈ unittests/test_layers.py, test_imperative_*)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear_forward_shape():
    m = nn.Linear(8, 4)
    x = paddle.randn((2, 8))
    out = m(x)
    assert list(out.shape) == [2, 4]
    np.testing.assert_allclose(
        out.numpy(), x.numpy() @ m.weight.numpy() + m.bias.numpy(),
        rtol=1e-5)


def test_parameters_and_state_dict():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    params = m.parameters()
    assert len(params) == 4
    sd = m.state_dict()
    assert set(sd) == {"0.weight", "0.bias", "2.weight", "2.bias"}

    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m2.set_state_dict({k: v.numpy() for k, v in sd.items()})
    x = paddle.randn((3, 4))
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_save_load_roundtrip(tmp_path):
    m = nn.Linear(4, 3)
    path = str(tmp_path / "model.pdparams")
    paddle.save(m.state_dict(), path)
    loaded = paddle.load(path)
    m2 = nn.Linear(4, 3)
    m2.set_state_dict(loaded)
    np.testing.assert_allclose(m.weight.numpy(), m2.weight.numpy())


def test_train_eval_mode_dropout():
    m = nn.Dropout(0.5)
    x = paddle.ones((100,))
    m.eval()
    np.testing.assert_allclose(m(x).numpy(), np.ones(100))
    m.train()
    out = m(x).numpy()
    assert (out == 0).any()
    # upscale_in_train: kept elements are scaled by 1/(1-p)
    assert np.allclose(out[out != 0], 2.0)


def test_forward_hooks():
    m = nn.Linear(3, 3)
    calls = []
    h = m.register_forward_post_hook(
        lambda layer, inp, out: calls.append(1))
    m(paddle.randn((1, 3)))
    assert calls == [1]
    h.remove()
    m(paddle.randn((1, 3)))
    assert calls == [1]


def test_batchnorm_running_stats():
    m = nn.BatchNorm2D(3)
    x = paddle.randn((8, 3, 4, 4)) * 2 + 1
    m.train()
    m(x)
    assert not np.allclose(m._mean.numpy(), np.zeros(3))
    m.eval()
    out = m(x)
    assert list(out.shape) == [8, 3, 4, 4]


def test_embedding_padding_idx():
    m = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor(np.array([0, 3], np.int32))
    out = m(idx)
    np.testing.assert_allclose(out.numpy()[0], np.zeros(4))


def test_layerlist_and_dict():
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll.parameters()) == 6
    ld = nn.LayerDict({"a": nn.Linear(2, 2)})
    assert "a" in ld
    assert len(ld.parameters()) == 2


def test_multi_head_attention():
    m = nn.MultiHeadAttention(16, 4)
    m.eval()
    x = paddle.randn((2, 5, 16))
    out = m(x)
    assert list(out.shape) == [2, 5, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    enc.eval()
    x = paddle.randn((2, 6, 16))
    out = enc(x)
    assert list(out.shape) == [2, 6, 16]


def test_named_parameters_unique():
    shared = nn.Linear(3, 3)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = shared
            self.b = shared

        def forward(self, x):
            return self.b(self.a(x))

    m = M()
    names = [n for n, _ in m.named_parameters()]
    assert len(names) == 2  # shared params counted once


def test_device_memory_stats_api():
    import paddle_tpu as paddle
    stats = paddle.device.memory_stats()
    assert isinstance(stats, dict)
    assert paddle.device.max_memory_allocated() >= 0
    assert paddle.device.memory_allocated() >= 0


def test_version_and_mode_toggles():
    import paddle_tpu as paddle
    assert paddle.version.full_version
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
    finally:
        paddle.disable_static()
    assert paddle.in_dynamic_mode()
    assert paddle.get_cudnn_version() is None


def test_extra_layers_upsample_pad_bilinear():
    import paddle_tpu as paddle
    from paddle_tpu import nn as pnn
    x = paddle.randn([1, 2, 4, 4])
    assert tuple(pnn.Upsample(scale_factor=2)(x).shape) == (1, 2, 8, 8)
    assert tuple(pnn.UpsamplingBilinear2D(size=(6, 6))(x).shape) \
        == (1, 2, 6, 6)
    assert tuple(pnn.ZeroPad2D([1, 1, 2, 2])(x).shape) == (1, 2, 8, 6)
    assert tuple(pnn.Identity()(x).shape) == (1, 2, 4, 4)
    out = pnn.Bilinear(3, 4, 5)(paddle.randn([2, 3]),
                                paddle.randn([2, 4]))
    assert tuple(out.shape) == (2, 5)
    cs = pnn.CosineSimilarity(axis=1)(paddle.ones([2, 3]),
                                      paddle.ones([2, 3]))
    np.testing.assert_allclose(cs.numpy(), 1.0, rtol=1e-6)
    dist = pnn.PairwiseDistance()(paddle.zeros([2, 3]),
                                  paddle.ones([2, 3]))
    np.testing.assert_allclose(dist.numpy(), np.sqrt(3), rtol=1e-4)


def test_unfold_fold_match_torch():
    import torch
    import paddle_tpu as paddle
    from paddle_tpu import nn as pnn
    img = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 2, 6, 6).astype(np.float32))
    p_uf = pnn.Unfold(kernel_sizes=3, strides=1, paddings=1)(img)
    t_uf = torch.nn.functional.unfold(torch.tensor(img.numpy()),
                                      kernel_size=3, stride=1,
                                      padding=1)
    np.testing.assert_allclose(p_uf.numpy(), t_uf.numpy(), rtol=1e-5)
    # non-overlapping fold inverts unfold
    uf = pnn.Unfold(kernel_sizes=2, strides=2)(img)
    back = pnn.Fold(output_sizes=(6, 6), kernel_sizes=2,
                    strides=2)(uf)
    np.testing.assert_allclose(back.numpy(), img.numpy(), rtol=1e-6)


def test_weight_norm_and_remove():
    """nn.utils.weight_norm: w = g * v/||v||; output preserved at init
    and after removal (reference weight_norm_hook.py)."""
    from paddle_tpu.nn.utils import remove_weight_norm, weight_norm
    paddle.seed(0)
    lin = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    ref = lin(x).numpy()
    weight_norm(lin, "weight", dim=0)
    assert "weight_g" in dict(lin.named_parameters())
    np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5)
    remove_weight_norm(lin, "weight")
    np.testing.assert_allclose(lin(x).numpy(), ref, rtol=1e-5)


def test_spectral_norm_bounds_sigma():
    from paddle_tpu.nn.utils import spectral_norm
    paddle.seed(0)
    lin = nn.Linear(8, 8)
    lin.weight.set_value(5.0 * np.eye(8, dtype=np.float32))
    spectral_norm(lin, "weight", n_power_iterations=5)
    lin(paddle.randn([1, 8]))  # hook runs
    w = lin.weight.numpy()
    s = np.linalg.svd(np.asarray(w), compute_uv=False)
    assert abs(s[0] - 1.0) < 0.05  # sigma normalized to ~1


def test_parameters_vector_roundtrip():
    from paddle_tpu.nn.utils import (parameters_to_vector,
                                     vector_to_parameters)
    m = nn.Linear(3, 2)
    vec = parameters_to_vector(m.parameters())
    assert vec.shape == [3 * 2 + 2]
    vector_to_parameters(paddle.zeros_like(vec), m.parameters())
    assert float(paddle.abs(m.weight).sum()) == 0.0


def test_affine_grid_matches_identity():
    import paddle_tpu.nn.functional as F
    theta = paddle.to_tensor(
        np.array([[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]], np.float32))
    grid = F.affine_grid(theta, [1, 1, 4, 4], align_corners=True)
    assert grid.shape == [1, 4, 4, 2]
    np.testing.assert_allclose(grid.numpy()[0, 0, 0], [-1.0, -1.0],
                               atol=1e-6)
    np.testing.assert_allclose(grid.numpy()[0, -1, -1], [1.0, 1.0],
                               atol=1e-6)
    # identity grid sampling returns the input
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(1, 2, 4, 4).astype(np.float32))
    y = F.grid_sample(x, grid, align_corners=True)
    np.testing.assert_allclose(y.numpy(), x.numpy(), atol=1e-5)
