"""Fused conv+BN training kernels (kernels/fused_resnet.py) — parity
against the unfused path. Reference ships this fusion as
resnet_unit_op / fused_bn_add_activation_op
(paddle/fluid/operators/fused/resnet_unit_op.cu,
fused_bn_add_activation_op.cu) and tests it against the unfused
composition (test_fused_bn_add_act.py) — same strategy here: the Pallas
kernels (interpret mode on CPU) must match conv->bn->relu composition
in forward, gradients, and running-stat updates."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.fused_resnet import (
    bn_fold, bn_relu_matmul_bn_stats, conv1x1_bn_stats, matmul_bn_stats)


def _ref_stats(y):
    yf = y.astype(jnp.float32)
    return jnp.mean(yf, axis=0), jnp.var(yf, axis=0)


class TestMatmulBnStats:
    def test_forward(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(96, 16).astype(np.float32))
        w = jnp.asarray(rng.randn(16, 24).astype(np.float32))
        y, mean, var = matmul_bn_stats(x, w)
        y_ref = x @ w
        m_ref, v_ref = _ref_stats(y_ref)
        np.testing.assert_allclose(y, y_ref, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(mean, m_ref, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(var, v_ref, rtol=1e-4, atol=1e-4)

    def test_grads_match_composition(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(32, 8).astype(np.float32))
        w = jnp.asarray(rng.randn(8, 12).astype(np.float32))

        def fused(x, w):
            y, mean, var = matmul_bn_stats(x, w)
            # consume all three outputs so stats cotangents flow
            return jnp.sum(y * y) + jnp.sum(mean * 3.0) + jnp.sum(var * 0.5)

        def ref(x, w):
            y = x @ w
            m, v = _ref_stats(y)
            return jnp.sum(y * y) + jnp.sum(m * 3.0) + jnp.sum(v * 0.5)

        gx_f, gw_f = jax.grad(fused, argnums=(0, 1))(x, w)
        gx_r, gw_r = jax.grad(ref, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gx_f, gx_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(gw_f, gw_r, rtol=1e-4, atol=1e-4)

    def test_odd_rows_blocking(self):
        # M=98 forces a non-power-of-two row block (_pick_block -> 49)
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(98, 8).astype(np.float32))
        w = jnp.asarray(rng.randn(8, 8).astype(np.float32))
        y, mean, var = matmul_bn_stats(x, w)
        np.testing.assert_allclose(y, x @ w, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(mean, jnp.mean(x @ w, axis=0),
                                   rtol=1e-5, atol=1e-5)


class TestBnReluMatmulBnStats:
    def test_forward_and_grads(self):
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(64, 8).astype(np.float32))
        scale = jnp.asarray(rng.rand(8).astype(np.float32) + 0.5)
        shift = jnp.asarray(rng.randn(8).astype(np.float32) * 0.1)
        w = jnp.asarray(rng.randn(8, 16).astype(np.float32))

        def fused(x, scale, shift, w):
            y, m, v = bn_relu_matmul_bn_stats(x, scale, shift, w)
            return jnp.sum(y * y) + jnp.sum(m) + jnp.sum(v * 0.3)

        def ref(x, scale, shift, w):
            a = jnp.maximum(x * scale + shift, 0.0)
            y = a @ w
            m, v = _ref_stats(y)
            return jnp.sum(y * y) + jnp.sum(m) + jnp.sum(v * 0.3)

        np.testing.assert_allclose(fused(x, scale, shift, w),
                                   ref(x, scale, shift, w),
                                   rtol=1e-5, atol=1e-5)
        gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, scale, shift, w)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, scale, shift, w)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


class TestConv3x3BnActStats:
    def test_forward_and_grads_vs_composition(self):
        from paddle_tpu.kernels.fused_resnet import conv3x3_bn_act_stats
        rng = np.random.RandomState(11)
        n, h, w, c, o = 2, 8, 8, 8, 16
        x = jnp.asarray(rng.randn(n, h, w, c).astype(np.float32))
        scale = jnp.asarray(rng.rand(c).astype(np.float32) + 0.5)
        shift = jnp.asarray(rng.randn(c).astype(np.float32) * 0.1)
        w9 = jnp.asarray(rng.randn(9 * c, o).astype(np.float32) * 0.2)

        def fused(x, scale, shift, w9):
            y, m, v = conv3x3_bn_act_stats(x, scale, shift, w9)
            return jnp.sum(y * y) + jnp.sum(m * 2.0) + jnp.sum(v * 0.7)

        def ref(x, scale, shift, w9):
            a = jnp.maximum(x * scale + shift, 0.0)
            y = jax.lax.conv_general_dilated(
                a, w9.reshape(3, 3, c, o), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            yf = y.reshape(-1, o)
            m = jnp.mean(yf, axis=0)
            v = jnp.var(yf, axis=0)
            return jnp.sum(y * y) + jnp.sum(m * 2.0) + jnp.sum(v * 0.7)

        np.testing.assert_allclose(fused(x, scale, shift, w9),
                                   ref(x, scale, shift, w9),
                                   rtol=1e-4, atol=1e-4)
        gf = jax.grad(fused, argnums=(0, 1, 2, 3))(x, scale, shift, w9)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3))(x, scale, shift, w9)
        for i, (a, b) in enumerate(zip(gf, gr)):
            np.testing.assert_allclose(
                np.asarray(a).reshape(-1),
                np.asarray(b).reshape(-1), rtol=2e-4, atol=2e-4,
                err_msg=f"grad {i}")


class TestConv3x3PallasVsMirror:
    """The Pallas 3x3 kernels (run everywhere: interpret off-TPU,
    compiled on TPU) against the jnp mirror oracle — halo windowing,
    tap indexing, scratch init, stats accumulation."""

    def _data(self):
        rng = np.random.RandomState(12)
        n, h, w, c, o = 3, 6, 6, 8, 16
        x = jnp.asarray(rng.randn(n, h, w, c).astype(np.float32))
        scale = jnp.asarray(rng.rand(c).astype(np.float32) + 0.5)
        shift = jnp.asarray(rng.randn(c).astype(np.float32) * 0.1)
        w9 = jnp.asarray(rng.randn(9 * c, o).astype(np.float32) * 0.2)
        return x, scale, shift, w9

    def test_forward_kernel(self):
        from paddle_tpu.kernels import fused_resnet as fr
        x, scale, shift, w9 = self._data()
        y_p, s_p, q_p, k_p = fr._conv3x3_fwd_pallas(
            x, scale, shift, w9, interpret=fr._interpret())
        y_r, s_r, q_r, k_r = fr._conv3x3_ref_fwd(x, scale, shift, w9)
        np.testing.assert_allclose(y_p, y_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(k_p, k_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(s_p, s_r, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(q_p, q_r, rtol=1e-3, atol=1e-3)

    def test_backward_kernel(self):
        from paddle_tpu.kernels import fused_resnet as fr
        x, scale, shift, w9 = self._data()
        c, o = x.shape[-1], w9.shape[1]
        rng = np.random.RandomState(13)
        y, _, _, _ = fr._conv3x3_ref_fwd(x, scale, shift, w9)
        dy = jnp.asarray(rng.randn(*y.shape).astype(np.float32))
        perch = jnp.asarray(rng.randn(o).astype(np.float32) * 0.1)
        dvar2 = jnp.asarray(rng.randn(o).astype(np.float32) * 0.01)
        mean = jnp.asarray(rng.randn(o).astype(np.float32))
        wf9 = fr._conv3x3_flip(w9, c, o)
        dx_p, dw_p, ds_p, dt_p = fr._conv3x3_bwd_pallas(
            dy, y, x, scale, shift, w9, wf9, perch, dvar2, mean,
            interpret=fr._interpret())
        dx_r, ds_r, dt_r, dw_r = fr._conv3x3_ref_bwd(
            dy, y, x, scale, shift, w9, perch, dvar2, mean)
        for a, b, nm in zip((dx_p, dw_p, ds_p, dt_p),
                            (dx_r, dw_r, ds_r, dt_r),
                            ("dx", "dw", "dscale", "dshift")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b).reshape(np.asarray(a).shape),
                rtol=2e-4, atol=2e-4, err_msg=nm)


class TestConvEntryPoints:
    def test_conv1x1_stride2(self):
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(2, 8, 8, 4).astype(np.float32))
        w = jnp.asarray(rng.randn(6, 4, 1, 1).astype(np.float32))
        y, mean, var = conv1x1_bn_stats(x, w, stride=2)
        ref = jax.lax.conv_general_dilated(
            x, jnp.transpose(w, (2, 3, 1, 0)), (2, 2), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            mean, jnp.mean(ref.reshape(-1, 6), axis=0), rtol=1e-5, atol=1e-5)

    def test_bn_fold(self):
        rng = np.random.RandomState(5)
        g = jnp.asarray(rng.rand(4).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(4).astype(np.float32))
        m = jnp.asarray(rng.randn(4).astype(np.float32))
        v = jnp.asarray(rng.rand(4).astype(np.float32) + 0.1)
        scale, shift = bn_fold(g, b, m, v, 1e-5)
        y = jnp.asarray(rng.randn(10, 4).astype(np.float32))
        ref = (y - m) / jnp.sqrt(v + 1e-5) * g + b
        np.testing.assert_allclose(y * scale + shift, ref,
                                   rtol=1e-5, atol=1e-5)


class TestFusedBottleneckBlock:
    def _models(self, fused):
        import paddle_tpu as paddle
        from paddle_tpu.models.resnet import ResNet, BottleneckBlock
        paddle.seed(7)
        return ResNet(BottleneckBlock, [1, 1, 1, 1], num_classes=10,
                      data_format="NHWC", fused_bn=fused)

    def test_forward_parity_and_running_stats(self):
        import paddle_tpu as paddle
        rng = np.random.RandomState(6)
        img = rng.randn(2, 3, 32, 32).astype(np.float32)
        m_ref = self._models(False)
        m_fused = self._models(True)
        m_fused.set_state_dict(m_ref.state_dict())
        m_ref.train()
        m_fused.train()
        x = paddle.to_tensor(img)
        out_ref = m_ref(x)
        out_fused = m_fused(x)
        np.testing.assert_allclose(np.asarray(out_fused.data),
                                   np.asarray(out_ref.data),
                                   rtol=2e-3, atol=2e-3)
        # running stats must update identically through the fused path
        bn = "layer1.0.bn3"
        sd_r = {k: v for k, v in m_ref.state_dict().items()}
        sd_f = {k: v for k, v in m_fused.state_dict().items()}
        for suffix in ("_mean", "_variance"):
            key = f"{bn}.{suffix}" if f"{bn}.{suffix}" in sd_r else None
            if key is None:  # state_dict key layout may differ; scan
                cands = [k for k in sd_r if bn in k and suffix in k]
                assert cands, (bn, suffix, list(sd_r)[:10])
                key = cands[0]
            np.testing.assert_allclose(np.asarray(sd_f[key].data),
                                       np.asarray(sd_r[key].data),
                                       rtol=1e-3, atol=1e-4)

    def test_grad_parity(self):
        # XLA:CPU runs fp32 matmul/conv at reduced precision by default
        # (--xla_allow_excess_precision); both paths must use the same
        # high-precision contractions for a meaningful comparison.
        with jax.default_matmul_precision("highest"):
            self._grad_parity_body()

    def _grad_parity_body(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn
        rng = np.random.RandomState(8)
        img = rng.randn(2, 3, 32, 32).astype(np.float32)
        lbl = rng.randint(0, 10, (2,)).astype(np.int64)
        # ±1-ulp input noise for the conditioning probe below
        noise = (1 + 1.2e-7 * np.sign(rng.randn(*img.shape))
                 ).astype(np.float32)

        def run(m, x):
            m.train()
            ce = nn.CrossEntropyLoss()
            loss = ce(m(paddle.to_tensor(x)), paddle.to_tensor(lbl))
            loss.backward()
            out = {n: np.asarray(p.grad.data)
                   for n, p in m.named_parameters() if p.grad is not None}
            m.clear_gradients()
            return out

        m_ref = self._models(False)
        sd = m_ref.state_dict()
        grads = {False: run(m_ref, img)}
        m_fused = self._models(True)
        m_fused.set_state_dict(sd)
        grads[True] = run(m_fused, img)
        assert grads[True].keys() == grads[False].keys()
        # Conditioning floor: fp32 round-off through 16 BN stages is
        # CHAOTIC where few rows feed a channel's batch stats (layer4:
        # 1x1 spatial, batch 2 -> M=2, var ~ eps) — the unfused path vs
        # ITSELF under ±1-ulp input noise moves those grads ~3e-2, so no
        # independent implementation can match tighter. Calibrate the
        # floor in-situ and bound the fused error by it; well-
        # conditioned tensors keep the strict 1e-2 bound.
        m_floor = self._models(False)
        m_floor.set_state_dict(sd)
        floor = run(m_floor, img * noise)
        for name in grads[True]:
            a, b, f = grads[True][name], grads[False][name], floor[name]
            nb = np.linalg.norm(b) + 1e-12
            rel = np.linalg.norm(a - b) / nb
            chaos = np.linalg.norm(f - b) / nb
            assert rel < max(1e-2, 4.0 * chaos), (name, rel, chaos)

    def test_use_global_stats_skips_fused_path(self):
        # fuse_conv_bn folds BN into conv weights and sets
        # use_global_stats — the fused training path must then stay off
        # or BN would be applied twice and the neutralized buffers
        # clobbered.
        import paddle_tpu as paddle
        from paddle_tpu.nn.utils import fuse_conv_bn
        rng = np.random.RandomState(10)
        img = rng.randn(2, 3, 32, 32).astype(np.float32)
        m_fused = self._models(True)
        m_fused.eval()
        x = paddle.to_tensor(img)
        ref = np.asarray(m_fused(x).data)
        fuse_conv_bn(m_fused)
        m_fused.train()
        np.testing.assert_allclose(np.asarray(m_fused(x).data), ref,
                                   rtol=5e-3, atol=5e-3)

    def test_recompute_stages_jit_parity_and_eager_stats(self):
        # remat must change memory behavior only: identical jitted
        # training trajectory, and the eager path (where BN running
        # stats live) must keep updating stats — remat engages only
        # under jit tracing, where stats are frozen uniformly by design
        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer
        from paddle_tpu.models.resnet import ResNet, BottleneckBlock
        import pytest as _pytest
        with _pytest.raises(ValueError):
            ResNet(BottleneckBlock, [1, 1, 1, 1], recompute_stages=(0, 1))
        rng = np.random.RandomState(14)
        img = rng.randn(2, 3, 32, 32).astype(np.float32)
        lbl = rng.randint(0, 10, (2,)).astype(np.int64)
        losses = {}
        for remat in ((), (1, 2)):
            paddle.seed(7)
            m = ResNet(BottleneckBlock, [1, 1, 1, 1], num_classes=10,
                       data_format="NHWC", recompute_stages=remat)
            m.train()
            opt = optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                     parameters=m.parameters())
            ce = nn.CrossEntropyLoss()
            step = paddle.jit.TrainStep(
                m, opt, lambda lg, lb: ce(lg, lb))
            x, y = paddle.to_tensor(img), paddle.to_tensor(lbl)
            losses[remat] = [float(np.asarray(step(x, y).data))
                             for _ in range(2)]
        np.testing.assert_allclose(losses[(1, 2)], losses[()],
                                   rtol=1e-5, atol=1e-6)
        # eager forward with remat configured still updates running stats
        paddle.seed(7)
        m = ResNet(BottleneckBlock, [1, 1, 1, 1], num_classes=10,
                   data_format="NHWC", recompute_stages=(1,))
        m.train()
        before = np.asarray(m.layer1[0].bn1._mean.data).copy()
        m(paddle.to_tensor(img))
        after = np.asarray(m.layer1[0].bn1._mean.data)
        assert not np.allclose(before, after), \
            "remat froze eager BN running stats"

    def test_eval_path_unchanged(self):
        import paddle_tpu as paddle
        rng = np.random.RandomState(9)
        img = rng.randn(2, 3, 32, 32).astype(np.float32)
        m_ref = self._models(False)
        m_fused = self._models(True)
        m_fused.set_state_dict(m_ref.state_dict())
        m_ref.eval()
        m_fused.eval()
        x = paddle.to_tensor(img)
        np.testing.assert_allclose(np.asarray(m_fused(x).data),
                                   np.asarray(m_ref(x).data),
                                   rtol=1e-5, atol=1e-5)


import paddle_tpu as paddle  # noqa: E402  (used inside tests)
