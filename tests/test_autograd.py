"""Eager tape semantics tests (≈ unittests/test_imperative_*.py,
test_custom_grad / PyLayer tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def test_backward_accumulates_over_reuse():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * x + x * 3.0  # dy/dx = 2x + 3 = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0], rtol=1e-6)


def test_second_backward_raises_without_retain():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    g1 = x.grad.numpy().copy()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * g1)


def test_no_grad():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_stop_gradient_cuts_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * 2).detach()
    z = (y * 3).sum()
    assert z.stop_gradient  # no diff inputs upstream


def test_grad_hook():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    seen = []
    x.register_hook(lambda g: seen.append(g) or g * 2)
    (x * 3).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(x.grad.numpy(), 6 * np.ones(3))


def test_multi_output_op_grad():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                         stop_gradient=False)
    a, b = paddle.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum()).backward()
    expected = np.array([[2, 2, 2], [1, 1, 1]], np.float32)
    np.testing.assert_allclose(x.grad.numpy(), expected)


def test_non_scalar_backward_needs_grad_tensor():
    x = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y2 = x * 2
    y2.backward(paddle.to_tensor(np.ones((2, 2), np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones((2, 2)))


def test_pylayer():
    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * np.ones(3))


def test_functional_grad_matches_tape():
    w = paddle.to_tensor(np.random.randn(4, 3).astype(np.float32),
                         stop_gradient=False)
    x = paddle.to_tensor(np.random.randn(2, 4).astype(np.float32))

    def loss_fn(wt):
        return (paddle.matmul(x, wt) ** 2).mean()

    g_func = paddle.grad(loss_fn)(w)
    loss_fn(w).backward()
    np.testing.assert_allclose(g_func.numpy(), w.grad.numpy(), rtol=1e-5)


def test_check_nan_inf_flag():
    paddle.set_flags({"check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError):
            paddle.divide(x, paddle.to_tensor(np.zeros(2, np.float32)))
    finally:
        paddle.set_flags({"check_nan_inf": False})
