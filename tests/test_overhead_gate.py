"""Observability overhead gate: a disabled RecordEvent span plus a
disabled counter increment must stay under 5 µs/op on CPU, so
instrumentation creep can never silently slow the hot path. Runs in
tier-1 (deliberately NOT marked slow); the budget is ~50x the measured
cost on a warm CPython, so scheduler noise doesn't flake it."""
import time

from paddle_tpu.core import monitor
from paddle_tpu.profiler import RecordEvent, metrics

BUDGET_US = 5.0
N = 20000


def _measure() -> float:
    c = metrics.counter("gate.disabled")
    t0 = time.perf_counter()
    for _ in range(N):
        with RecordEvent("gate_span"):
            c.inc()
    return (time.perf_counter() - t0) / N * 1e6  # µs/op


def test_disabled_instrumentation_under_budget():
    metrics.disable()
    assert not monitor.enabled
    _measure()  # warm up allocator + bytecode caches
    best = min(_measure() for _ in range(3))
    assert best < BUDGET_US, (
        f"disabled RecordEvent+counter costs {best:.2f}µs/op "
        f"(budget {BUDGET_US}µs) — instrumentation crept into the "
        f"disabled hot path")
    assert metrics.counter("gate.disabled").value == 0  # truly off


# --------------------------------------------------- step pipeline layer
# The async step pipeline must be free when OFF: a lag-0 fetcher
# (PADDLE_ASYNC_STEPS=0, the fully synchronous mode) and an idempotent
# re-placement of an already-resident batch may add <10 µs of host work
# per train step, or the "optimization" taxes every non-pipelined user.

PIPELINE_BUDGET_US = 10.0
N_STEPS = 5000


def _measure_fetcher() -> float:
    from paddle_tpu.hapi.model import AsyncScalarFetcher
    f = AsyncScalarFetcher(lag=0)
    t0 = time.perf_counter()
    for i in range(N_STEPS):
        for _ in f.push(i, 0.5):
            pass
    f.drain()
    return (time.perf_counter() - t0) / N_STEPS * 1e6


def test_async_fetcher_disabled_under_budget():
    metrics.disable()
    _measure_fetcher()  # warm up
    best = min(_measure_fetcher() for _ in range(3))
    assert best < PIPELINE_BUDGET_US, (
        f"lag-0 AsyncScalarFetcher costs {best:.2f}µs/step "
        f"(budget {PIPELINE_BUDGET_US}µs)")


def _measure_place(batch) -> float:
    from paddle_tpu.io.device_prefetch import place_batch
    t0 = time.perf_counter()
    for _ in range(N_STEPS):
        place_batch(batch)  # every leaf already resident: all skips
    return (time.perf_counter() - t0) / N_STEPS * 1e6


def test_idempotent_placement_under_budget():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.io.device_prefetch import place_batch
    metrics.disable()
    x = paddle.to_tensor(np.zeros((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4,), np.int64))
    batch = (x, y)
    out = place_batch(batch)  # warm up; also prove it is a pass-through
    assert out[0] is x and out[1] is y
    best = min(_measure_place(batch) for _ in range(3))
    assert best < PIPELINE_BUDGET_US, (
        f"idempotent place_batch costs {best:.2f}µs/step "
        f"(budget {PIPELINE_BUDGET_US}µs) — the skip path regrew "
        f"per-step transfers or tree walks")


# ---------------------------------------------------- telemetry layer
# The flight recorder promises a SUB-MICROSECOND disabled path (it sits
# on per-step, per-collective and per-request call sites), and the
# per-request tracing helper must be free for the 7-in-8 unsampled
# requests. Budgets are ~5-10x the measured warm-CPython cost.

RECORDER_BUDGET_US = 1.0


def _measure_recorder() -> float:
    from paddle_tpu.core import flight_recorder as fr
    t0 = time.perf_counter()
    for _ in range(N):
        fr.record("gate.off", step=1)
    return (time.perf_counter() - t0) / N * 1e6


def test_flight_recorder_disabled_under_budget():
    from paddle_tpu.core import flight_recorder as fr
    was = fr.is_enabled()
    fr.disable()
    try:
        n0 = len(fr.events())
        _measure_recorder()  # warm up
        best = min(_measure_recorder() for _ in range(3))
        assert len(fr.events()) == n0  # truly off
    finally:
        fr.configure(on=was)
    assert best < RECORDER_BUDGET_US, (
        f"disabled flight_recorder.record costs {best:.2f}µs/op "
        f"(budget {RECORDER_BUDGET_US}µs) — the disabled path must "
        "stay a bool check")


def _measure_untraced_span(req) -> float:
    t0 = time.perf_counter()
    for _ in range(N):
        req.span("decode", 0, 1, tokens=1)
    return (time.perf_counter() - t0) / N * 1e6


def test_request_tracing_off_under_budget():
    import numpy as np
    from paddle_tpu.serving.request import Request, RequestParams
    req = Request(np.arange(4, dtype=np.int32), RequestParams(), 4,
                  None)
    assert not req.traced  # the engine samples 1-in-N; default is off
    _measure_untraced_span(req)  # warm up
    best = min(_measure_untraced_span(req) for _ in range(3))
    assert best < RECORDER_BUDGET_US, (
        f"untraced Request.span costs {best:.2f}µs/op "
        f"(budget {RECORDER_BUDGET_US}µs) — tracing-off must stay one "
        "attribute check")


# ------------------------------------------------- fleet/goodput layer
# The fleet plane publishes from a background thread — there is no
# per-step hook at all — so the only per-step cost its OFF path may
# add is the goodput ledger's ambient charge with no ledger active:
# one truthiness check (the ISSUE-15 <10µs/step publish-loop gate).


def _measure_ambient_goodput() -> float:
    from paddle_tpu.core import goodput
    t0 = time.perf_counter()
    for _ in range(N_STEPS):
        goodput.charge("checkpoint", 0.001)
        with goodput.timed("compute"):
            pass
    return (time.perf_counter() - t0) / N_STEPS * 1e6


def test_ambient_goodput_disabled_under_budget():
    from paddle_tpu.core import goodput
    assert goodput.active() is None  # nothing on the ambient stack
    _measure_ambient_goodput()  # warm up
    best = min(_measure_ambient_goodput() for _ in range(3))
    assert best < PIPELINE_BUDGET_US, (
        f"ambient goodput charge with no active ledger costs "
        f"{best:.2f}µs/step (budget {PIPELINE_BUDGET_US}µs) — the "
        "fleet/goodput off path must stay a truthiness check")


# ----------------------------------------------------- SLO watchtower
# slo.tick() sits inside the serving poll loop and the fit loop's step
# section. Its not-due path must stay one clock read + compare (ring
# not due) and its registry-off path one bool check, or the watchtower
# taxes every step it is supposed to be observing.


def _measure_maybe_sample(ring) -> float:
    t0 = time.perf_counter()
    for _ in range(N):
        ring.maybe_sample()
    return (time.perf_counter() - t0) / N * 1e6


def test_timeseries_not_due_under_budget():
    from paddle_tpu.core import timeseries
    metrics.disable()
    ring = timeseries.TimeSeriesRing(period_s=3600.0, retention=4)
    ring.sample()  # arms _next_due an hour out: every call is not-due
    _measure_maybe_sample(ring)  # warm up
    best = min(_measure_maybe_sample(ring) for _ in range(3))
    assert len(ring) == 1  # truly not due
    assert best < BUDGET_US, (
        f"not-due TimeSeriesRing.maybe_sample costs {best:.2f}µs/op "
        f"(budget {BUDGET_US}µs) — the record path must stay a clock "
        "read + compare")


def _measure_slo_tick() -> float:
    from paddle_tpu.core import slo
    t0 = time.perf_counter()
    for _ in range(N):
        slo.tick()
    return (time.perf_counter() - t0) / N * 1e6


def test_slo_tick_disabled_under_budget():
    from paddle_tpu.core import slo
    metrics.disable()
    assert not monitor.enabled
    assert slo.tick() is False  # registry off: nothing evaluated
    _measure_slo_tick()  # warm up
    best = min(_measure_slo_tick() for _ in range(3))
    assert best < BUDGET_US, (
        f"registry-off slo.tick costs {best:.2f}µs/op "
        f"(budget {BUDGET_US}µs) — the off path must stay a bool "
        "check")
