"""Observability overhead gate: a disabled RecordEvent span plus a
disabled counter increment must stay under 5 µs/op on CPU, so
instrumentation creep can never silently slow the hot path. Runs in
tier-1 (deliberately NOT marked slow); the budget is ~50x the measured
cost on a warm CPython, so scheduler noise doesn't flake it."""
import time

from paddle_tpu.core import monitor
from paddle_tpu.profiler import RecordEvent, metrics

BUDGET_US = 5.0
N = 20000


def _measure() -> float:
    c = metrics.counter("gate.disabled")
    t0 = time.perf_counter()
    for _ in range(N):
        with RecordEvent("gate_span"):
            c.inc()
    return (time.perf_counter() - t0) / N * 1e6  # µs/op


def test_disabled_instrumentation_under_budget():
    metrics.disable()
    assert not monitor.enabled
    _measure()  # warm up allocator + bytecode caches
    best = min(_measure() for _ in range(3))
    assert best < BUDGET_US, (
        f"disabled RecordEvent+counter costs {best:.2f}µs/op "
        f"(budget {BUDGET_US}µs) — instrumentation crept into the "
        f"disabled hot path")
    assert metrics.counter("gate.disabled").value == 0  # truly off
