"""Quantization (QAT/PTQ) + ASP tests.

Mirrors the reference's test_imperative_qat*.py /
test_post_training_quantization_*.py / test_asp_*.py
(python/paddle/fluid/tests/unittests/ and .../asp/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate import asp
from paddle_tpu.quantization import (PTQ, QAT, QuantConfig, QuantedConv2D,
                                     QuantedLinear, dequantize_int8,
                                     fake_quant, fake_quant_channelwise,
                                     quantize_int8)


# ------------------------------------------------------------- fake quant
def test_fake_quant_roundtrip_accuracy():
    paddle.seed(0)
    x = paddle.randn([64, 64])
    q = fake_quant(x)
    err = np.abs(q.numpy() - x.numpy()).max()
    scale = np.abs(x.numpy()).max()
    assert err <= scale / 127 + 1e-6  # one quantization step


def test_fake_quant_ste_gradient():
    x = paddle.to_tensor(np.array([0.5, -0.2, 3.0], np.float32))
    x.stop_gradient = False
    y = fake_quant(x, scale=1.0)  # 3.0 is outside the range
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0, 0.0])


def test_quantize_int8_channelwise():
    paddle.seed(1)
    w = paddle.randn([8, 16]).numpy() * np.linspace(
        0.1, 10, 16)[None, :]
    q, s = quantize_int8(w, axis=1)
    assert str(np.asarray(q).dtype) == "int8"
    deq = np.asarray(dequantize_int8(q, s))
    rel = np.abs(deq - w).max(0) / np.abs(w).max(0)
    assert rel.max() < 0.01  # per-channel keeps small channels accurate


# -------------------------------------------------------------------- QAT
def _mlp():
    paddle.seed(0)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))


def test_qat_swaps_layers_and_shares_params():
    model = _mlp()
    orig_params = {id(p) for p in model.parameters()}
    QAT().quantize(model)
    subs = dict(model.named_sublayers())
    assert any(isinstance(l, QuantedLinear) for l in subs.values())
    assert {id(p) for p in model.parameters()} == orig_params


def test_qat_trains_and_converts():
    model = _mlp()
    qat = QAT()
    qat.quantize(model)
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=model.parameters())
    mse = nn.MSELoss()
    x = paddle.randn([32, 8])
    y = paddle.randn([32, 4])
    first = None
    for _ in range(30):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        first = first if first is not None else float(loss)
    assert float(loss) < first
    QAT.convert(model)
    assert not any(isinstance(l, QuantedLinear)
                   for _, l in model.named_sublayers())


def test_qat_skip_config():
    cfg = QuantConfig().skip("2")  # skip the final Linear
    model = _mlp()
    QAT(cfg).quantize(model)
    subs = dict(model.named_sublayers())
    assert isinstance(subs["0"], QuantedLinear)
    assert isinstance(subs["2"], nn.Linear)


def test_qat_conv2d_forward():
    paddle.seed(0)
    model = nn.Sequential(nn.Conv2D(3, 4, 3, padding=1), nn.ReLU())
    QAT().quantize(model)
    assert isinstance(dict(model.named_sublayers())["0"], QuantedConv2D)
    out = model(paddle.randn([2, 3, 8, 8]))
    assert tuple(out.shape) == (2, 4, 8, 8)


# -------------------------------------------------------------------- PTQ
def test_ptq_calibrate_convert_close_outputs():
    model = _mlp()
    model.eval()
    x = paddle.randn([64, 8])
    ref = model(x).numpy()
    ptq = PTQ()
    ptq.quantize(model)
    for i in range(4):  # calibration passes
        model(x)
    ptq.convert(model)
    out = model(x).numpy()
    # int8 quantized model stays close on calibrated data
    assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05
    # quant_info recorded int8 weights per layer
    assert len(ptq.quant_info) == 2
    info = next(iter(ptq.quant_info.values()))
    assert info["weight_int8"].dtype == np.int8
    assert info["act_scale"] > 0


# -------------------------------------------------------------------- ASP
def test_asp_mask_1d_and_check():
    rng = np.random.RandomState(0)
    mat = rng.randn(16, 32)
    mask = asp.get_mask_1d(mat, 2, 4)
    assert asp.check_mask_1d(mat * mask, 2, 4)
    assert mask.reshape(-1, 4).sum(-1).max() == 2
    # keeps the 2 largest of each group
    grp = np.abs(mat.reshape(-1, 4))
    kept = np.where(mask.reshape(-1, 4), grp, 0)
    assert (kept.sum(-1) >= np.sort(grp, -1)[:, -2:].sum(-1) - 1e-9).all()


def test_asp_conv_prunes_reduction_dim():
    paddle.seed(0)
    m = nn.Sequential(nn.Conv2D(8, 8, 3, padding=1))
    asp.prune_model(m)
    w = np.asarray(m.parameters()[0].numpy())
    # groups of 4 must run along in*kh*kw (what sparse matmul contracts)
    assert asp.check_mask_1d(w.reshape(w.shape[0], -1), 2, 4)


def test_ptq_honors_type_flags():
    cfg = QuantConfig().add_type_config(nn.Linear, weight=True,
                                        activation=False)
    m = nn.Sequential(nn.Linear(4, 4))
    ptq = PTQ(cfg)
    ptq.quantize(m)
    m(paddle.randn([2, 4]))
    ptq.convert(m)
    assert ptq.quant_info["0"]["act_scale"] is None
    assert ptq.quant_info["0"]["weight_int8"].dtype == np.int8


def test_qat_custom_quanter_used():
    calls = []

    def my_act(x):
        calls.append(1)
        return x

    cfg = QuantConfig(activation=my_act)
    m = nn.Sequential(nn.Linear(4, 4))
    QAT(cfg).quantize(m)
    m(paddle.randn([2, 4]))
    assert calls


def test_asp_mask_2d_greedy():
    rng = np.random.RandomState(1)
    mat = rng.randn(8, 8)
    mask = asp.get_mask_2d_greedy(mat, 2, 4)
    assert asp.check_mask_2d(mat * mask, 2, 4)


def test_asp_prune_and_decorated_optimizer_keeps_sparsity():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 4))
    asp.prune_model(model)
    w0 = model.parameters()[0]
    assert asp.calculate_density(w0) == pytest.approx(0.5, abs=0.01)
    opt = asp.decorate(optimizer.SGD(learning_rate=0.05,
                                     parameters=model.parameters()))
    mse = nn.MSELoss()
    x = paddle.randn([8, 16])
    y = paddle.randn([8, 4])
    for _ in range(5):
        loss = mse(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    # sparsity pattern survives optimizer updates
    assert asp.calculate_density(w0) == pytest.approx(0.5, abs=0.01)
    arr = np.asarray(w0.numpy())
    assert asp.check_mask_1d(arr.T, 2, 4)
