"""Tests for the legacy-parity namespaces added in r4: paddle.compat
(to_text/to_bytes), paddle.reader (decorators), and paddle.dataset
(reader-creator wrappers). Reference: python/paddle/compat.py,
reader/decorator.py, dataset/.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import compat, reader


# ----------------------------------------------------------------- compat

def test_to_text_and_bytes_scalars_and_containers():
    assert compat.to_text(b"abc") == "abc"
    assert compat.to_bytes("abc") == b"abc"
    assert compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert compat.to_bytes(("a", "b")) == (b"a", b"b")
    assert compat.to_text({b"k": b"v"}) == {"k": "v"}
    assert compat.to_text(None) is None
    assert compat.to_text(7) == 7


def test_to_text_inplace_list():
    data = [b"x", b"y"]
    out = compat.to_text(data, inplace=True)
    assert out is data and data == ["x", "y"]


# ----------------------------------------------------------------- reader

def _r(n):
    def rd():
        return iter(range(n))
    return rd


def test_cache_and_firstn_and_chain():
    calls = []

    def rd():
        calls.append(1)
        return iter([1, 2, 3])

    c = reader.cache(rd)
    assert list(c()) == [1, 2, 3]
    assert list(c()) == [1, 2, 3]
    assert len(calls) == 1  # source consumed once
    assert list(reader.firstn(_r(10), 3)()) == [0, 1, 2]
    assert list(reader.chain(_r(2), _r(3))()) == [0, 1, 0, 1, 2]


def test_map_readers_and_compose():
    doubled = reader.map_readers(lambda a, b: a + b, _r(3), _r(3))
    assert list(doubled()) == [0, 2, 4]
    comp = reader.compose(_r(3), _r(3))
    assert list(comp()) == [(0, 0), (1, 1), (2, 2)]
    with pytest.raises(RuntimeError, match="not aligned"):
        list(reader.compose(_r(2), _r(3))())
    ok = reader.compose(_r(2), _r(3), check_alignment=False)
    assert list(ok()) == [(0, 0), (1, 1)]


def test_shuffle_buffered_multiprocess():
    import random
    random.seed(0)
    out = sorted(reader.shuffle(_r(10), 4)())
    assert out == list(range(10))
    assert sorted(reader.buffered(_r(10), 2)()) == list(range(10))
    combined = reader.multiprocess_reader([_r(3), _r(4)])
    assert sorted(combined()) == sorted(list(range(3)) + list(range(4)))


@pytest.mark.parametrize("order", [True, False])
def test_xmap_readers(order):
    xm = reader.xmap_readers(lambda x: x * 10, _r(6), 2, 3, order=order)
    got = list(xm())
    assert sorted(got) == [0, 10, 20, 30, 40, 50]
    if order:
        assert got == [0, 10, 20, 30, 40, 50]


def test_buffered_propagates_reader_errors():
    def bad():
        yield 1
        raise IOError("disk gone")

    with pytest.raises(IOError, match="disk gone"):
        list(reader.buffered(lambda: bad(), 4)())


def test_multiprocess_reader_propagates_errors():
    def bad():
        yield 1
        raise ValueError("corrupt shard")

    with pytest.raises(ValueError, match="corrupt shard"):
        list(reader.multiprocess_reader([lambda: bad()])())


def test_buffered_early_abandon_does_not_hang():
    for i, _ in enumerate(reader.buffered(_r(10_000), 4)()):
        if i >= 3:
            break  # feeder must release via the abandoned flag
    # reaching here without deadlock is the assertion


# ---------------------------------------------------------------- dataset

def test_dataset_uci_housing_reader(tmp_path):
    # standard housing.data layout: 14 whitespace-separated floats/row
    rng = np.random.RandomState(0)
    rows = rng.rand(20, 14)
    path = tmp_path / "housing.data"
    with open(path, "w") as f:
        for r in rows:
            f.write(" ".join(f"{v:.6f}" for v in r) + "\n")
    rd = paddle.dataset.uci_housing.train(data_file=str(path))
    samples = list(rd())
    assert len(samples) > 0
    x, y = samples[0]
    assert len(x) == 13 and len(y) == 1
    # works with paddle.reader decorators end-to-end
    assert len(list(reader.firstn(rd, 2)())) == 2


def test_dataset_unknown_module():
    with pytest.raises(AttributeError):
        paddle.dataset.nonexistent_set
