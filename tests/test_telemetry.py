"""Telemetry export surface (ISSUE 10): the Prometheus text renderer
(round-tripped by a parser), the HTTP server endpoints, readiness
semantics against a live ServingEngine (503 during drain), the
1-in-N request-trace sampling default, and the metrics-doc drift gate
(docs/metrics.md == generated; METRIC_DOC keys == DECLARED_METRICS)."""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.core.telemetry_server import (TelemetryServer,
                                              prometheus_text)
from paddle_tpu.profiler import metrics


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.disable()
    metrics.reset()
    yield
    metrics.disable()
    metrics.reset()


def parse_prometheus(text):
    """Minimal exposition-format parser: {"types": {family: kind},
    "samples": {(name, labels-frozenset): float}}. Raises on malformed
    lines — the round-trip IS the test."""
    types, samples = {}, {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            assert parts[0] == "#" and parts[1] == "TYPE", line
            assert parts[3] in ("counter", "gauge", "histogram"), line
            types[parts[2]] = parts[3]
            continue
        metric, _, value = line.rpartition(" ")
        assert metric and value, line
        if "{" in metric:
            name, _, rest = metric.partition("{")
            assert rest.endswith("}"), line
            labels = []
            for kv in rest[:-1].split(","):
                k, _, v = kv.partition("=")
                assert v.startswith('"') and v.endswith('"'), line
                labels.append((k, v[1:-1]))
            key = (name, frozenset(labels))
        else:
            key = (metric, frozenset())
        v = float(value)
        assert v == v and abs(v) != float("inf"), f"non-finite: {line}"
        samples[key] = v
    return {"types": types, "samples": samples}


class TestPrometheusRender:
    def test_counters_gauges_histograms_round_trip(self):
        metrics.enable()
        monitor.record_serve_request("completed")
        monitor.record_serve_request("completed")
        monitor.record_serve_request("cancelled")
        monitor.record_serve_queue_depth(3)
        monitor.record_serve_ttft(0.003)
        monitor.record_serve_ttft(0.2)
        parsed = parse_prometheus(prometheus_text())
        t, s = parsed["types"], parsed["samples"]
        assert t["serve_requests"] == "counter"
        assert t["serve_queue_depth"] == "gauge"
        assert t["serve_ttft"] == "histogram"
        assert s[("serve_requests", frozenset())] == 3
        assert s[("serve_requests",
                  frozenset({("status", "completed")}))] == 2
        assert s[("serve_queue_depth", frozenset())] == 3
        assert s[("serve_ttft_count", frozenset())] == 2
        assert s[("serve_ttft_sum", frozenset())] == \
            pytest.approx(0.203)
        # cumulative bucket monotonicity, +Inf == count
        buckets = sorted(
            ((dict(k[1])["le"], v) for k, v in s.items()
             if k[0] == "serve_ttft_bucket"),
            key=lambda kv: float("inf") if kv[0] == "+Inf"
            else float(kv[0]))
        values = [v for _, v in buckets]
        assert values == sorted(values)
        assert buckets[-1] == ("+Inf", 2)

    def test_non_finite_never_rendered(self):
        """The satellite contract: a poisoned observation (nan/inf)
        must not make any /metrics line non-finite."""
        metrics.enable()
        h = metrics.histogram("t.poison", bounds=(1.0, 2.0))
        h.observe(0.5)
        h.observe(float("nan"))
        h.observe(float("inf"))
        g = metrics.gauge("t.gone")
        g.set(float("nan"))
        parsed = parse_prometheus(prometheus_text())  # parser asserts
        s = parsed["samples"]
        assert s[("t_poison_count", frozenset())] == 3
        assert s[("t_poison_sum", frozenset())] == 0.5
        # non-finite observations land in the overflow bucket
        assert s[("t_poison_bucket", frozenset({("le", "+Inf")}))] == 3
        assert s[("t_poison_bucket", frozenset({("le", "2")}))] == 1

    def test_label_value_escaping(self):
        metrics.enable()
        monitor.record_swallowed("weird\"place", ValueError("x"))
        text = prometheus_text()
        assert 'where="weird\\"place"' in text
        parse_prometheus(text)


class TestHistogramPercentileEdges:
    """Satellite: pinned finite results for the degenerate shapes a
    /metrics reader can hit."""

    def test_empty_and_q_bounds(self):
        metrics.enable()
        h = metrics.histogram("t.edges", bounds=(1.0, 2.0, 4.0))
        assert h.percentile(0) == 0.0
        assert h.percentile(50) == 0.0
        assert h.percentile(100) == 0.0
        h.observe(1.5)
        assert h.percentile(0) == 1.0     # lower edge of its bucket
        assert h.percentile(100) == 2.0
        assert h.percentile(-5) == h.percentile(0)    # q clamps
        assert h.percentile(250) == h.percentile(100)

    def test_all_mass_in_overflow(self):
        metrics.enable()
        h = metrics.histogram("t.over", bounds=(1.0, 2.0))
        for _ in range(5):
            h.observe(100.0)
        for q in (0, 50, 99, 100):
            v = h.percentile(q)
            assert v == 2.0 and v == v  # last finite bound, never inf

    def test_inf_bound_clamps(self):
        metrics.enable()
        h = metrics.histogram("t.infb", bounds=(1.0, float("inf")))
        h.observe(50.0)
        assert h.percentile(99) == 1.0    # lower edge, not inf

    def test_non_finite_observations_keep_stats_finite(self):
        metrics.enable()
        h = metrics.histogram("t.nan", bounds=(1.0,))
        h.observe(float("nan"))
        h.observe(float("-inf"))
        assert h.count == 2
        assert h.sum == 0.0 and h.mean == 0.0
        assert h.percentile(50) == 1.0    # overflow clamp, finite


# ----------------------------------------------------------- http server


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


class TestServerEndpoints:
    def test_basic_endpoints_without_engine(self):
        server = TelemetryServer(port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            code, body = _get(base + "/healthz")
            assert code == 200 and json.loads(body)["status"] == "ok"
            code, body = _get(base + "/readyz")
            assert code == 200 and json.loads(body)["ready"]
            code, body = _get(base + "/metrics")
            assert code == 200
            parse_prometheus(body)
            code, body = _get(base + "/flightrecorder")
            assert code == 200 and "traceEvents" in json.loads(body)
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(base + "/nope")
            assert e.value.code == 404
            # scrapes are themselves metered (and starting the server
            # enabled the registry — the export opt-in contract)
            assert metrics.is_enabled()
            snap = metrics.snapshot()
            assert snap["telemetry.scrapes{endpoint=metrics}"][
                "value"] == 1
        finally:
            server.stop()
        assert not server.running
        server.stop()  # idempotent

    def test_engine_readiness_flips_on_drain(self):
        """The acceptance path: /metrics serves the serve.* histograms
        during live traffic, /readyz 200 while serving and 503 the
        moment the drain starts."""
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=4,
                                  prefill_buckets=(16,), max_batch=1)
               .enable_serving(telemetry_port=0))
        eng = ServingEngine(cfg, poll_every=1)
        server = eng.telemetry
        assert server is not None and server.running
        base = f"http://127.0.0.1:{server.port}"
        try:
            code, body = _get(base + "/readyz")
            assert code == 200 and json.loads(body)["warm"]
            out = eng.submit(np.arange(1, 7, dtype=np.int32)) \
                .result(timeout=60)
            assert out.size == 4
            code, text = _get(base + "/metrics")
            parsed = parse_prometheus(text)
            assert parsed["types"]["serve_ttft"] == "histogram"
            assert parsed["samples"][
                ("serve_ttft_count", frozenset())] >= 1
            assert parsed["samples"][
                ("serve_requests",
                 frozenset({("status", "completed")}))] >= 1
            eng.drain()
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(base + "/readyz")
            assert e.value.code == 503
            assert json.loads(e.value.read().decode())["reason"] == \
                "draining"
            # /metrics keeps serving through (and after) the drain —
            # the post-drain scrape is how the fleet sees the exit
            code, _ = _get(base + "/metrics")
            assert code == 200
        finally:
            server.stop()

    def test_fixed_port_rebuild_never_crashes_engine(self):
        """A rebuilt engine on the same fixed telemetry port: a
        predecessor that was only drained still holds the port — the
        new engine must come up serving (telemetry=None, swallow
        logged), never crash in the constructor; a predecessor that was
        shutdown() released the port, so the successor binds it."""
        import socket
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]

        def build():
            cfg = (Config().from_layer(m, spec)
                   .enable_generation(max_new_tokens=2,
                                      prefill_buckets=(16,),
                                      max_batch=1))
            return ServingEngine(cfg, warmup=False,
                                 telemetry_port=port)

        first = build()
        assert first.telemetry is not None and \
            first.telemetry.port == port
        first.drain()                  # drain keeps the port scrapeable
        second = build()               # bind fails: served, un-scraped
        assert second.telemetry is None
        second.shutdown()
        first.shutdown()               # releases the port...
        assert first.telemetry is None
        third = build()                # ...so the successor binds it
        assert third.telemetry is not None and \
            third.telemetry.port == port
        third.shutdown()

    def test_scrape_racing_shutdown_never_truncates(self):
        """Satellite (ISSUE 17): hammer /metrics from several threads
        while the engine shuts down mid-scrape. Every response that
        completes must be a FULL 200 (parseable exposition text, never
        a truncated body): stop() now joins in-flight handler threads
        after closing the listener. Post-stop connects are refused."""
        import socket
        import threading
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=2,
                                  prefill_buckets=(16,), max_batch=1))
        eng = ServingEngine(cfg, warmup=False, telemetry_port=0)
        assert eng.telemetry is not None
        port = eng.telemetry.port
        base = f"http://127.0.0.1:{port}"
        stop_scraping = threading.Event()
        failures, completed = [], []

        def scraper():
            while not stop_scraping.is_set():
                try:
                    code, body = _get(base + "/metrics")
                except (urllib.error.URLError, OSError):
                    continue   # refused/reset once the listener closed
                if code != 200:
                    failures.append(f"status {code}")
                    continue
                try:
                    parse_prometheus(body)   # truncation fails here
                except AssertionError as e:
                    failures.append(f"unparseable scrape: {e}")
                completed.append(code)

        threads = [threading.Thread(target=scraper, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        # let the hammer land a few scrapes, then shut down under it
        deadline = threading.Event()
        deadline.wait(0.2)
        eng.shutdown()
        stop_scraping.set()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        assert not failures, failures[:5]
        assert completed, "hammer never completed a scrape"
        # the port is really released (stop joined the handlers too)
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", port),
                                     timeout=1).close()

    def test_warmup_failure_releases_telemetry_port(self):
        """A constructor abort (warmup raises) must stop the telemetry
        server it just started — the caller never gets a handle, so
        nothing else could release the port."""
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=2,
                                  prefill_buckets=(16,), max_batch=1))

        class Boom(ServingEngine):
            def warmup(self):
                raise RuntimeError("injected warmup failure")

        with pytest.raises(RuntimeError, match="injected warmup"):
            Boom(cfg, telemetry_port=0)
        # a fresh engine on ANY fixed port proves no server leaked on
        # it; the stronger check is structural: the failed constructor
        # ran TelemetryServer.stop() (covered by the match above not
        # hanging and by the rebind test's port semantics)

    def test_trace_sample_env_off_and_garbage(self, monkeypatch):
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        from paddle_tpu.serving import ServingEngine
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]

        def build():
            cfg = (Config().from_layer(m, spec)
                   .enable_generation(max_new_tokens=2,
                                      prefill_buckets=(16,),
                                      max_batch=1))
            return ServingEngine(cfg, warmup=False)

        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "off")
        assert build().trace_sample == 0      # off really disables
        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "nonsense")
        assert build().trace_sample == 8      # fallback, swallow logged
        monkeypatch.setenv("PADDLE_TRACE_SAMPLE", "3")
        assert build().trace_sample == 3

    def test_start_from_env(self, monkeypatch):
        from paddle_tpu.core import telemetry_server
        monkeypatch.delenv("PADDLE_TELEMETRY_PORT", raising=False)
        assert telemetry_server.start_from_env() is None
        monkeypatch.setenv("PADDLE_TELEMETRY_PORT", "not-a-port")
        assert telemetry_server.start_from_env() is None
        monkeypatch.setenv("PADDLE_TELEMETRY_PORT", "0")
        server = telemetry_server.start_from_env()
        try:
            assert server is not None and server.running
        finally:
            server.stop()


class TestTraceSampling:
    def test_default_one_in_eight(self):
        """Request ids divisible by trace_sample (default 8) carry
        spans; the rest cost one attribute check."""
        from paddle_tpu.serving.request import Request, RequestParams
        from paddle_tpu.serving import ServingEngine
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=2,
                                  prefill_buckets=(16,), max_batch=1))
        eng = ServingEngine(cfg, warmup=False)
        assert eng.trace_sample == 8
        reqs = [eng.submit([1, 2]) for _ in range(9)]
        sampled = [r for r in reqs if r.traced]
        assert len(sampled) in (1, 2)  # ids are process-global
        assert all(r.id % 8 == 0 for r in sampled)
        assert all(r.trace_id for r in reqs)
        eng.drain()

    def test_trace_sample_zero_disables(self):
        from paddle_tpu.serving import ServingEngine
        from paddle_tpu.inference import Config
        from paddle_tpu.models.gpt import gpt
        paddle.seed(0)
        m = gpt("test-tiny")
        m.eval()
        spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
        cfg = (Config().from_layer(m, spec)
               .enable_generation(max_new_tokens=2,
                                  prefill_buckets=(16,), max_batch=1)
               .enable_serving(trace_sample=0))
        eng = ServingEngine(cfg, warmup=False)
        assert eng.trace_sample == 0
        reqs = [eng.submit([1, 2]) for _ in range(16)]
        assert not any(r.traced for r in reqs)
        eng.drain()


# ----------------------------------------------------------- schema gates


class TestMetricsDocDrift:
    def test_metric_doc_covers_declared_metrics(self):
        from paddle_tpu.core.monitor import (DECLARED_METRICS,
                                             METRIC_DOC)
        assert set(METRIC_DOC) == set(DECLARED_METRICS)
        for name, (kind, labels, desc) in METRIC_DOC.items():
            assert kind in ("counter", "gauge", "histogram"), name
            assert isinstance(labels, tuple), name
            assert desc and "\n" not in desc, name

    def test_generated_doc_is_fresh(self):
        """Tier-1 drift gate: docs/metrics.md must match what
        tools.metrics_doc renders from the live schema."""
        from tools.metrics_doc import doc_path, render
        with open(doc_path(), "r", encoding="utf-8") as f:
            committed = f.read()
        assert committed == render(), (
            "docs/metrics.md is stale — regenerate with "
            "`python -m tools.metrics_doc`")
