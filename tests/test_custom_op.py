"""Custom op + cpp_extension tests (reference:
python/paddle/fluid/tests/custom_op/ — custom_relu_op etc.)."""
import os
import shutil
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import register_custom_op
from paddle_tpu.utils import cpp_extension


# ------------------------------------------------------------- python ops
def test_register_custom_op_forward_autodiff():
    import jax.numpy as jnp

    my_gelu = register_custom_op(
        "test_my_gelu", lambda x: 0.5 * x * (1 + jnp.tanh(0.7978845608 *
                                                          (x + 0.044715 * x ** 3))))
    x = paddle.randn([4, 4])
    x.stop_gradient = False
    out = my_gelu(x)
    out.sum().backward()
    assert x.grad is not None
    assert np.isfinite(x.grad.numpy()).all()


def test_register_custom_op_custom_backward():
    import jax.numpy as jnp

    def fwd(x):
        return jnp.maximum(x, 0)

    def bwd(g, x):
        return (g * 3.0 * (x > 0),)  # deliberately x3 to prove it's used

    my_relu = register_custom_op("test_my_relu3", fwd, backward=bwd)
    x = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
    x.stop_gradient = False
    my_relu(x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 0.0])


def test_register_custom_op_rejects_duplicates():
    register_custom_op("test_dup_op", lambda x: x)
    with pytest.raises(ValueError, match="already registered"):
        register_custom_op("test_dup_op", lambda x: x)


# ---------------------------------------------------------- cpp extension
GXX = shutil.which("g++") is not None


@pytest.mark.skipif(not GXX, reason="no g++ in PATH")
def test_cpp_extension_build_and_run(tmp_path):
    src = tmp_path / "my_ops.cc"
    src.write_text(textwrap.dedent("""
        #include <cstdint>
        extern "C" void scaled_add(const float** ins,
                                   const int64_t* sizes, int n_ins,
                                   float* out, int64_t out_size) {
            // out = 2*a + b
            for (int64_t i = 0; i < out_size; ++i)
                out[i] = 2.0f * ins[0][i] + ins[1][i];
        }
        extern "C" void row_sums(const float** ins,
                                 const int64_t* sizes, int n_ins,
                                 float* out, int64_t out_size) {
            int64_t cols = sizes[0] / out_size;
            for (int64_t r = 0; r < out_size; ++r) {
                float acc = 0.f;
                for (int64_t c = 0; c < cols; ++c)
                    acc += ins[0][r * cols + c];
                out[r] = acc;
            }
        }
    """))
    mod = cpp_extension.load("myops", [str(src)])
    scaled_add = mod.def_op("scaled_add")
    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([10.0, 20.0], np.float32))
    np.testing.assert_allclose(scaled_add(a, b).numpy(), [12.0, 24.0])

    row_sums = mod.def_op("row_sums",
                          out_shape=lambda s: (s[0],))
    m = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(row_sums(m).numpy(), [3.0, 12.0])


@pytest.mark.skipif(not GXX, reason="no g++ in PATH")
def test_cpp_extension_works_under_jit(tmp_path):
    src = tmp_path / "jit_ops.cc"
    src.write_text(textwrap.dedent("""
        #include <cstdint>
        extern "C" void plus_one(const float** ins,
                                 const int64_t* sizes, int n_ins,
                                 float* out, int64_t out_size) {
            for (int64_t i = 0; i < out_size; ++i)
                out[i] = ins[0][i] + 1.0f;
        }
    """))
    mod = cpp_extension.load("jitops", [str(src)])
    plus_one = mod.def_op("plus_one")

    import jax

    @jax.jit
    def f(x):
        return plus_one.raw(x) * 2.0

    out = f(np.array([1.0, 5.0], np.float32))
    np.testing.assert_allclose(np.asarray(out), [4.0, 12.0])


@pytest.mark.skipif(not GXX, reason="no g++ in PATH")
def test_cpp_extension_build_cache(tmp_path):
    src = tmp_path / "c.cc"
    src.write_text("""#include <cstdint>
extern "C" void noop(const float** ins, const int64_t* sizes,
                     int n_ins, float* out, int64_t out_size) {}
""")
    so1 = cpp_extension._compile("cached", [str(src)])
    mtime = os.path.getmtime(so1)
    so2 = cpp_extension._compile("cached", [str(src)])
    assert so1 == so2 and os.path.getmtime(so2) == mtime


def test_cpp_extension_bad_source(tmp_path):
    src = tmp_path / "bad.cc"
    src.write_text("this is not C++")
    with pytest.raises(RuntimeError, match="build failed"):
        cpp_extension.load("bad", [str(src)])
