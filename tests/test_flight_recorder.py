"""Flight recorder coverage (ISSUE 10): the bounded event ring, span
storage, Perfetto/plaintext dumps, auto-dump rate limiting, the wiring
into retraces and the profiler export — and the chaos-tier acceptance
scenarios: a Watchdog timeout and a SIGTERM mid-``serve_forever`` each
leave a dump containing the stalled/in-flight request's spans."""
import glob
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flight_recorder as fr
from paddle_tpu.core import monitor


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Every test starts with an empty, enabled ring and leaves the
    process defaults behind (capacity reset also clears the auto-dump
    rate-limit state, so scenarios don't starve each other)."""
    fr.configure(capacity=fr.DEFAULT_CAPACITY, on=True)
    yield
    fr.configure(capacity=fr.DEFAULT_CAPACITY, on=True)


# ----------------------------------------------------------------- ring


class TestRing:
    def test_record_and_read(self):
        fr.record("test.alpha", a=1)
        fr.record("test.beta")
        evs = fr.events()
        kinds = [k for _, k, _ in evs]
        assert kinds == ["test.alpha", "test.beta"]
        assert evs[0][2] == {"a": 1}
        assert evs[1][2] is None
        assert evs[0][0] <= evs[1][0]  # ns timestamps, monotonic

    def test_ring_bound_evicts_oldest(self):
        r = fr.configure(capacity=8)
        for i in range(20):
            fr.record("test.n", i=i)
        evs = r.events()
        assert len(evs) == 8
        assert [e[2]["i"] for e in evs] == list(range(12, 20))
        assert r._dropped == 12

    def test_disabled_records_nothing(self):
        fr.disable()
        fr.record("test.off", x=1)
        fr.record_span("test.span", 0, 1)
        assert fr.events() == []
        fr.enable()
        fr.record("test.on")
        assert len(fr.events()) == 1

    def test_spans_between(self):
        t0 = fr.now_ns()
        fr.record_span("req1.decode", t0, t0 + 1000, trace_id="x.1",
                       tid=1001, tokens=3)
        fr.record("test.point")  # point events never surface as spans
        fr.record_span("early", t0 - 5000, t0 - 4000)
        spans = fr.spans_between(t0 - 100, t0 + 2000)
        assert spans == [("req1.decode", t0, t0 + 1000, 1001, 0)]

    def test_env_capacity_parse(self, monkeypatch):
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "off")
        assert fr._env_capacity() == (False, fr.DEFAULT_CAPACITY)
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "0")
        assert fr._env_capacity()[0] is False
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "128")
        assert fr._env_capacity() == (True, 128)
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER", "bogus")
        assert fr._env_capacity() == (True, fr.DEFAULT_CAPACITY)


# ---------------------------------------------------------------- dumps


class TestDumps:
    def test_dump_writes_perfetto_and_tail(self, tmp_path):
        t = fr.now_ns()
        fr.record("test.kind", a=1)
        fr.record_span("req7.prefill", t, t + 500000, trace_id="p.7",
                       tid=1007)
        path = fr.dump(str(tmp_path / "d"), reason="unit")
        assert path.endswith(".json")
        with open(path) as f:
            d = json.load(f)
        assert d["metadata"]["reason"] == "unit"
        names = {e["name"] for e in d["traceEvents"]}
        assert {"test.kind", "req7.prefill"} <= names
        span = next(e for e in d["traceEvents"]
                    if e["name"] == "req7.prefill")
        assert span["ph"] == "X" and span["dur"] == pytest.approx(500.0)
        assert span["args"]["trace"] == "p.7"
        inst = next(e for e in d["traceEvents"]
                    if e["name"] == "test.kind")
        assert inst["ph"] == "i" and inst["args"] == {"a": 1}
        txt = (tmp_path / "d.txt").read_text()
        assert "reason: unit" in txt
        assert "test.kind a=1" in txt
        assert "span req7.prefill" in txt

    def test_auto_dump_rate_limit_and_counter(self, tmp_path,
                                              monkeypatch):
        from paddle_tpu.profiler import metrics
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        metrics.enable()
        try:
            fr.record("test.crash")
            p1 = fr.auto_dump("unitreason")
            p2 = fr.auto_dump("unitreason")       # inside min interval
            p3 = fr.auto_dump("unitreason2")      # different reason: ok
            assert p1 is not None and os.path.exists(p1)
            assert p2 is None
            assert p3 is not None
            snap = metrics.snapshot()
            assert snap["flightrecorder.dumps{reason=unitreason}"][
                "value"] == 1
            assert snap["flightrecorder.dumps{reason=unitreason2}"][
                "value"] == 1
        finally:
            metrics.disable()

    def test_auto_dump_cap(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        r = fr.recorder()
        r._auto_dumps = fr.MAX_AUTO_DUMPS
        assert fr.auto_dump("capped") is None

    def test_disabled_auto_dump_noop(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        fr.disable()
        assert fr.auto_dump("off") is None
        assert not list(tmp_path.iterdir())

    def test_dump_identity_and_clock_metadata(self, tmp_path,
                                              monkeypatch):
        """ISSUE-15: dumps carry (rank, restart_count, pid) in the
        default FILENAME (N processes share one dump dir without
        clobbering) and the clock mapping (anchors + fleet offset) in
        the metadata (what tools/trace_merge aligns on)."""
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
        monkeypatch.setenv("PADDLE_RESTART_COUNT", "2")
        fr.enable()
        fr.set_clock_offset_ns(12345)
        try:
            fr.record("checkpoint.commit", step=1)
            path = fr.dump(reason="unit")
            name = os.path.basename(path)
            assert name.startswith(
                f"flightrecorder_unit_r3i2_p{os.getpid()}_")
            with open(path) as f:
                d = json.load(f)
            md = d["metadata"]
            assert md["rank"] == 3 and md["restart_count"] == 2
            assert md["clock_offset_ns"] == 12345
            assert isinstance(md["anchor_wall_ns"], int)
            assert isinstance(md["anchor_perf_ns"], int)
            proc = next(e for e in d["traceEvents"]
                        if e["name"] == "process_name")
            assert proc["args"]["name"].startswith("rank3.2 ")
            assert "rank: 3, incarnation: 2" in \
                open(path[:-5] + ".txt").read()
        finally:
            fr.set_clock_offset_ns(0)


class TestEventSchema:
    def test_event_doc_covers_declared_events(self):
        assert set(fr.EVENT_DOC) == set(fr.DECLARED_EVENTS)
        for name, desc in fr.EVENT_DOC.items():
            assert desc and "\n" not in desc, name

    def test_generated_events_doc_is_fresh(self):
        """Tier-1 drift gate: docs/events.md must match what
        tools.metrics_doc renders from the live event schema."""
        from tools.metrics_doc import events_doc_path, render_events
        with open(events_doc_path(), "r", encoding="utf-8") as f:
            committed = f.read()
        assert committed == render_events(), (
            "docs/events.md is stale — regenerate with "
            "`python -m tools.metrics_doc`")


# --------------------------------------------------------------- wiring


class TestWiring:
    def test_retrace_lands_in_recorder_without_monitor(self):
        """jit compiles reach the black box even when the metrics
        registry was never enabled — the post-mortem contract."""
        from paddle_tpu.profiler import metrics
        assert not metrics.is_enabled()

        def _total():
            snap = metrics.snapshot().get("jit.compile.total")
            return snap["value"] if snap else 0

        import paddle_tpu.jit as jit
        before = _total()  # registry history survives disable by design

        @jit.to_static
        def f(x):
            return x * 2

        f(paddle.to_tensor(np.ones((3,), np.float32)))
        compiles = [e for e in fr.events() if e[1] == "jit.compile"]
        assert compiles and compiles[0][2]["cause"] == "first"
        # and the (disabled) metrics registry stayed untouched
        assert _total() == before

    def test_profiler_export_includes_recorder_spans(self, tmp_path):
        """Spans recorded while a Profiler records join its Perfetto
        JSON — sampled request traces and RecordEvent spans share one
        timeline."""
        from paddle_tpu import profiler as P
        prof = P.Profiler(trace_dir=str(tmp_path))
        prof.start()
        t = fr.now_ns()
        fr.record_span("req3.decode", t, t + 100000, trace_id="z.3",
                       tid=1003)
        with P.RecordEvent("host_work"):
            pass
        prof.stop()
        out = tmp_path / "trace.json"
        prof.result.export_chrome_tracing(str(out))
        names = {e["name"] for e in
                 json.load(open(out))["traceEvents"]}
        assert "req3.decode" in names
        assert "host_work" in names

    def test_fit_crash_dumps(self, tmp_path, monkeypatch):
        """An uncaught exception inside Model.fit leaves a fit_crash
        dump with the last dispatched steps in it."""
        monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
        from paddle_tpu import nn, optimizer
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import Callback

        class Bomb(Callback):
            def on_train_batch_end(self, step, logs=None):
                if step >= 1:
                    raise RuntimeError("injected trainer bug")

        paddle.seed(0)
        net = nn.Linear(4, 2)
        m = Model(net)
        m.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                  loss=lambda out, lbl: (out ** 2).mean())
        data = [(np.ones((2, 4), np.float32),
                 np.zeros((2,), np.int64)) for _ in range(4)]
        monkeypatch.setenv("PADDLE_ASYNC_STEPS", "0")
        with pytest.raises(RuntimeError, match="injected trainer bug"):
            m.fit(data, epochs=1, verbose=0, callbacks=[Bomb()])
        dumps = glob.glob(str(tmp_path / "flightrecorder_fit_crash_*"
                              ".json"))
        assert len(dumps) == 1
        d = json.load(open(dumps[0]))
        names = [e["name"] for e in d["traceEvents"]]
        assert "train.step_begin" in names
        assert "fit.crash" in names


# ---------------------------------------------------------------- chaos
# The acceptance scenarios: each failure mode leaves a dump from which
# the in-flight request's trace can be read back.


def _tiny_engine(**kw):
    from paddle_tpu.inference import Config
    from paddle_tpu.models.gpt import gpt
    from paddle_tpu.serving import ServingEngine
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    spec = [paddle.to_tensor(np.zeros((2, 12), np.int32))]
    cfg = (Config().from_layer(m, spec)
           .enable_generation(max_new_tokens=8, prefill_buckets=(16,),
                              max_batch=2))
    return ServingEngine(cfg, trace_sample=1, **kw)


def _req_spans(dump_path):
    d = json.load(open(dump_path))
    return [e for e in d["traceEvents"]
            if e["ph"] == "X" and e["name"].startswith("req")], d


@pytest.mark.chaos
def test_watchdog_timeout_dumps_inflight_request_spans(tmp_path,
                                                       monkeypatch):
    """A Watchdog expiry while a request is mid-decode produces a dump
    whose trace holds that request's queue-wait/prefill spans — the
    post-mortem shows what the wedged replica was serving."""
    from paddle_tpu.distributed.resilience import (Watchdog,
                                                   WatchdogTimeout)
    monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
    eng = _tiny_engine(poll_every=4)
    h = eng.submit(np.arange(1, 9, dtype=np.int32))
    eng.step()                        # admit: queue_wait+prefill spans
    assert h.status.value == "running"
    with pytest.raises(WatchdogTimeout):
        with Watchdog(timeout=0.2, label="test.stall",
                      dump_stacks=False):
            t0 = time.monotonic()
            while time.monotonic() - t0 < 10:   # stalled host loop
                pass
    dumps = glob.glob(str(tmp_path / "flightrecorder_watchdog_*.json"))
    assert len(dumps) == 1
    spans, d = _req_spans(dumps[0])
    names = {e["name"] for e in spans}
    assert f"req{h.id}.queue_wait" in names
    assert f"req{h.id}.prefill" in names
    assert any(e["name"] == "watchdog.timeout"
               and e["args"]["label"] == "test.stall"
               for e in d["traceEvents"])
    eng.drain()


@pytest.mark.chaos
def test_sigterm_mid_serve_dumps_inflight_request_spans(tmp_path,
                                                        monkeypatch):
    """SIGTERM mid-serve_forever: the preemption dump (written BEFORE
    the drain) carries the spans of the requests that were decoding
    when the signal landed, plus the drain's own begin/end events in a
    follow-up read of the ring."""
    import signal
    from paddle_tpu.distributed.resilience import GracefulShutdown
    from paddle_tpu.utils.fault_injection import KillAfter
    monkeypatch.setenv("PADDLE_FLIGHT_RECORDER_DIR", str(tmp_path))
    eng = _tiny_engine(poll_every=2, drain_timeout_s=60.0)
    rng = np.random.RandomState(1)
    traffic = [rng.randint(0, 512, 4 + i).astype(np.int32)
               for i in range(4)]
    killer = KillAfter(4, signal.SIGTERM)
    with GracefulShutdown(exit_on_save=False):
        handles = eng.serve_forever(iter(traffic),
                                    on_step=lambda e: killer.step())
    assert killer.fired
    assert all(h.status.terminal for h in handles)
    dumps = glob.glob(str(tmp_path /
                          "flightrecorder_preemption_*.json"))
    assert len(dumps) == 1
    spans, d = _req_spans(dumps[0])
    names = [e["name"] for e in d["traceEvents"]]
    assert "serve.preempted" in names
    # the dump happens before the drain, so at least one admitted
    # request's spans are already in the ring
    admitted = [h for h in handles if h.admitted_at is not None]
    assert admitted
    span_names = {e["name"] for e in spans}
    assert any(f"req{h.id}.prefill" in span_names for h in admitted)
    # the ring (post-drain) holds the drain bracket too
    kinds = [k for _, k, _ in fr.events()]
    assert "serve.drain_begin" in kinds and "serve.drain_end" in kinds
