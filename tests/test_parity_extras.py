"""Round-4 parity holes (VERDICT r3 Next #8): edit_distance vs a numpy
DP oracle, ReduceLROnPlateau / TerminateOnNaN / VisualDL callbacks,
and the static.amp namespace mapped onto dynamic AMP."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, optimizer


def _lev(a, b):
    """Textbook O(nm) Levenshtein oracle."""
    n, m = len(a), len(b)
    dp = np.zeros((n + 1, m + 1), np.float64)
    dp[:, 0] = np.arange(n + 1)
    dp[0, :] = np.arange(m + 1)
    for i in range(1, n + 1):
        for j in range(1, m + 1):
            dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                           dp[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return dp[n, m]


class TestEditDistance:
    def test_reference_docstring_example(self):
        inp = paddle.to_tensor(np.array(
            [[1, 2, 3], [4, 5, 6], [4, 4, 4], [1, 1, 1]], np.int64))
        lab = paddle.to_tensor(np.array(
            [[1, 3, 4, 1], [4, 5, 8, 1], [7, 7, 7, 1], [1, 1, 1, 1]],
            np.int64))
        il = paddle.to_tensor(np.array([3, 3, 3, 3], np.int64))
        ll = paddle.to_tensor(np.array([4, 4, 4, 4], np.int64))
        d, n = F.edit_distance(inp, lab, input_length=il,
                               label_length=ll, normalized=False)
        np.testing.assert_allclose(np.asarray(d.data).ravel(),
                                   [3, 2, 4, 1])
        assert float(np.asarray(n.data)[0]) == 4.0

    def test_random_vs_oracle(self):
        rng = np.random.RandomState(0)
        for _ in range(5):
            bsz = 6
            sa, sb = rng.randint(2, 9, 2)
            a = rng.randint(0, 5, (bsz, sa)).astype(np.int64)
            b = rng.randint(0, 5, (bsz, sb)).astype(np.int64)
            la = rng.randint(1, sa + 1, bsz).astype(np.int64)
            lb = rng.randint(1, sb + 1, bsz).astype(np.int64)
            d, _ = F.edit_distance(
                paddle.to_tensor(a), paddle.to_tensor(b),
                input_length=paddle.to_tensor(la),
                label_length=paddle.to_tensor(lb), normalized=False)
            ref = [_lev(a[i, :la[i]], b[i, :lb[i]]) for i in range(bsz)]
            np.testing.assert_allclose(np.asarray(d.data).ravel(), ref)

    def test_normalized_and_ignored_tokens(self):
        a = np.array([[1, 9, 2, 3]], np.int64)
        b = np.array([[1, 2, 9, 4]], np.int64)
        # token 9 removed from both -> [1,2,3] vs [1,2,4] -> dist 1
        d, _ = F.edit_distance(paddle.to_tensor(a), paddle.to_tensor(b),
                               ignored_tokens=[9], normalized=False)
        assert float(np.asarray(d.data).ravel()[0]) == 1.0
        dn, _ = F.edit_distance(paddle.to_tensor(a),
                                paddle.to_tensor(b),
                                ignored_tokens=[9], normalized=True)
        np.testing.assert_allclose(np.asarray(dn.data).ravel()[0],
                                   1.0 / 3.0, rtol=1e-6)


def _toy_model():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
    model = paddle.Model(net)
    opt = optimizer.SGD(learning_rate=0.1,
                        parameters=net.parameters())
    model.prepare(opt, loss=nn.CrossEntropyLoss())
    return model, opt


class _ToyData:
    def __init__(self, n=32, poison=False):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 4).astype(np.float32)
        self.y = rng.randint(0, 2, (n,)).astype(np.int64)
        if poison:
            self.x[:, 0] = np.nan

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class TestCallbacks:
    def test_reduce_lr_on_plateau(self):
        from paddle_tpu.callbacks import ReduceLROnPlateau
        model, opt = _toy_model()
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                               verbose=0, min_delta=10.0)  # never improves
        model.fit(_ToyData(), epochs=4, batch_size=16, verbose=0,
                  callbacks=[cb])
        # patience 1 with an unimprovable metric: lr halves repeatedly
        assert opt.get_lr() < 0.1 / 1.9
        with pytest.raises(ValueError):
            ReduceLROnPlateau(factor=1.5)

    def test_reduce_lr_eval_owns_the_tracker(self):
        # with eval data present the plateau tracker must step once
        # per eval, not once for train + once for eval (double-rate
        # patience consumption was a real bug)
        from paddle_tpu.callbacks import ReduceLROnPlateau
        model, opt = _toy_model()
        cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=3,
                               verbose=0, min_delta=10.0)
        model.fit(_ToyData(), eval_data=_ToyData(), epochs=3,
                  batch_size=16, verbose=0, callbacks=[cb])
        assert cb._eval_mode
        # 3 eval steps with patience 3: first sets best, waits reach 2
        # -> no reduction yet; double-stepping would have reduced
        assert opt.get_lr() == pytest.approx(0.1)

    def test_terminate_on_nan(self):
        from paddle_tpu.callbacks import TerminateOnNaN
        model, _ = _toy_model()
        cb = TerminateOnNaN()
        model.fit(_ToyData(poison=True), epochs=3, batch_size=32,
                  verbose=0, callbacks=[cb])
        assert cb.stopped

    def test_visualdl_writes_scalars(self, tmp_path):
        from paddle_tpu.callbacks import VisualDL
        model, _ = _toy_model()
        cb = VisualDL(log_dir=str(tmp_path / "vdl"))
        model.fit(_ToyData(), epochs=1, batch_size=16, verbose=0,
                  callbacks=[cb])
        rows = [json.loads(l) for l in
                open(os.path.join(str(tmp_path / "vdl"),
                                  "scalars.jsonl"))]
        assert rows and all({"tag", "step", "value"} <= set(r) for r
                            in rows)
        assert any(r["tag"] == "train/loss" for r in rows)


class TestStaticAmp:
    def test_decorate_trains(self):
        from paddle_tpu.static import amp as samp
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(),
                            nn.Linear(16, 1))
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=net.parameters())
        dec = samp.decorate(opt, init_loss_scaling=8.0,
                            use_dynamic_loss_scaling=True)
        rng = np.random.RandomState(1)
        x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 1).astype(np.float32))
        losses = []
        for _ in range(5):
            with dec.amp_guard():
                from paddle_tpu.amp.auto_cast import is_autocast_enabled
                assert is_autocast_enabled()
                out = net(x)
                loss = ((out - y) ** 2).astype("float32").mean()
            dec.minimize(loss)
            losses.append(float(np.asarray(loss.data)))
        assert losses[-1] < losses[0]

    def test_namespace_surface(self):
        from paddle_tpu.static import amp as samp
        for name in ("decorate", "AutoMixedPrecisionLists",
                     "CustomOpLists", "fp16_guard",
                     "cast_model_to_fp16", "cast_parameters_to_fp16",
                     "bf16"):
            assert hasattr(samp, name), name
        # bf16 sub-namespace names (reference static/amp/bf16)
        for name in ("decorate_bf16", "cast_model_to_bf16",
                     "cast_parameters_to_bf16", "bf16_guard",
                     "AutoMixedPrecisionListsBF16"):
            assert hasattr(samp.bf16, name), name
        net = nn.Linear(4, 4)
        samp.cast_model_to_fp16(net)
        assert str(net.weight.dtype).endswith("bfloat16")
        with samp.fp16_guard():
            from paddle_tpu.amp.auto_cast import is_autocast_enabled
            assert is_autocast_enabled()
