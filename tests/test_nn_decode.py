"""nn/decode.py coverage: BeamSearchDecoder initialize/step protocol and
an end-to-end tiny-cell dynamic_decode run checked against a numpy
reference beam search (including the gather_tree backtrace).

Reference analog: the reference's beam-search decoder unit tests
(test_rnn_decode_api.py); these were missing here entirely.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.decode import BeamSearchDecoder, dynamic_decode

VOCAB = 6
HID = VOCAB  # the toy cells emit their hidden state as logits


class ScriptedCell(nn.Layer):
    """Emits a fixed logits row per step (ignores inputs); the state
    carries a per-beam tag so parent reordering is observable."""

    def __init__(self, script):
        super().__init__()
        self.script = [np.asarray(row, np.float32) for row in script]
        self.t = 0

    def forward(self, inputs, states):
        b = inputs.shape[0]
        row = self.script[min(self.t, len(self.script) - 1)]
        self.t += 1
        logits = np.broadcast_to(row, (b, VOCAB)).copy()
        return Tensor(logits), states


class LinearTanhCell(nn.Layer):
    """h' = tanh(E[token] + h @ W); logits = h' @ O — enough nonlinearity
    that beams genuinely diverge."""

    def __init__(self, seed=0):
        super().__init__()
        rng = np.random.RandomState(seed)
        self.E = rng.randn(VOCAB, HID).astype(np.float32)
        self.W = (rng.randn(HID, HID) * 0.5).astype(np.float32)
        self.O = (rng.randn(HID, VOCAB) * 1.5).astype(np.float32)

    def forward(self, tokens, states):
        h = states.numpy() if isinstance(states, Tensor) else np.asarray(states)
        tok = np.asarray(tokens.numpy()).astype(np.int64).reshape(-1)
        h2 = np.tanh(self.E[tok] + h @ self.W).astype(np.float32)
        return Tensor(h2 @ self.O), Tensor(h2)


# ----------------------------------------------------------- protocol


def test_initialize_protocol():
    dec = BeamSearchDecoder(ScriptedCell([np.zeros(VOCAB)]),
                            start_token=1, end_token=2, beam_size=3)
    init_state = Tensor(np.zeros((2, HID), np.float32))  # batch 2
    tokens, states, (log_probs, finished) = dec.initialize(init_state)
    assert tokens.shape == (6,)  # batch * beam
    assert np.all(np.asarray(tokens) == 1)
    assert np.asarray(states).shape == (6, HID)
    assert log_probs.shape == (2, 3)
    # beam 0 live, the rest start at -inf-ish so step 1 expands the root
    np.testing.assert_array_equal(np.asarray(log_probs[:, 0]), 0.0)
    assert np.all(np.asarray(log_probs[:, 1:]) <= -1e8)
    assert not np.asarray(finished).any()


def test_step_topk_and_parent_reorder():
    # step 1 expands only the root beam; step 2's scripted logits make
    # exact top-k selection predictable
    script = [
        [0.0, 3.0, 0.0, 2.0, 1.0, 0.0],   # root: picks 1, 3, 4
        [0.0, 0.0, 0.0, 0.0, 0.0, 5.0],   # every beam: 5 dominates
    ]
    dec = BeamSearchDecoder(ScriptedCell(script), start_token=0,
                            end_token=VOCAB - 1 - 4, beam_size=3)
    # use end_token=1? keep it un-hit: end_token must not be in top picks
    dec.end_token = 0
    init = Tensor(np.arange(1 * HID, dtype=np.float32).reshape(1, HID))
    tokens, states, beam_state = dec.initialize(init)
    tokens, parent, states, (lp, fin) = dec.step(0, tokens, states,
                                                 beam_state)
    np.testing.assert_array_equal(np.asarray(tokens), [1, 3, 4])
    np.testing.assert_array_equal(np.asarray(parent), [[0, 0, 0]])
    # scores are the root's log-softmax of the scripted row
    row = np.asarray(script[0], np.float64)
    lsm = row - np.log(np.exp(row).sum())
    np.testing.assert_allclose(np.sort(np.asarray(lp[0]))[::-1],
                               np.sort(lsm[[1, 3, 4]])[::-1], rtol=1e-5)
    # step 2: all beams pick token 5; ranking preserves beam order
    tokens, parent, states, (lp2, fin2) = dec.step(1, tokens, states,
                                                   (lp, fin))
    np.testing.assert_array_equal(np.asarray(tokens), [5, 5, 5])
    np.testing.assert_array_equal(np.asarray(parent), [[0, 1, 2]])
    assert not np.asarray(fin2).any()


def test_finished_beam_extends_with_end_token_at_no_cost():
    end = 2
    script = [
        [0.0, 1.0, 5.0, 0.5, 0.0, 0.0],   # root: end_token 2 wins
        [9.0, 0.0, 0.0, 0.0, 0.0, 0.0],   # finished beam must IGNORE this
    ]
    dec = BeamSearchDecoder(ScriptedCell(script), start_token=0,
                            end_token=end, beam_size=2)
    init = Tensor(np.zeros((1, HID), np.float32))
    tokens, states, bs = dec.initialize(init)
    tokens, parent, states, (lp1, fin1) = dec.step(0, tokens, states, bs)
    assert np.asarray(fin1)[0, 0]  # best beam ended
    best_before = float(np.asarray(lp1)[0, 0])
    tokens, parent, states, (lp2, fin2) = dec.step(1, tokens, states,
                                                   (lp1, fin1))
    # the finished beam extended with end_token at UNCHANGED score
    assert int(np.asarray(tokens)[0]) == end
    assert np.isclose(float(np.asarray(lp2)[0, 0]), best_before)
    assert np.asarray(fin2)[0, 0]


def test_state_reordered_by_parent():
    # beams tagged via distinct states; a step whose winners all come
    # from one parent must gather that parent's state everywhere
    script = [
        [0.0, 4.0, 3.0, 0.0, 0.0, 0.0],   # root expands: tokens 1, 2
        # give beam-dependent logits via state? ScriptedCell ignores
        # state, so craft: all beams see the same row — winners 1,2 from
        # whichever beam ranks first (beam 0, higher carry-over score)
        [0.0, 2.0, 1.9, 0.0, 0.0, 0.0],
    ]
    dec = BeamSearchDecoder(ScriptedCell(script), start_token=0,
                            end_token=5, beam_size=2,
                            embedding_fn=None)
    init = Tensor(np.zeros((1, HID), np.float32))
    tokens, states, bs = dec.initialize(init)

    tokens, parent, states, bs = dec.step(0, tokens, states, bs)
    # tag states by beam so the next reorder is visible
    tagged = Tensor(np.stack([np.full(HID, 10.0, np.float32),
                              np.full(HID, 20.0, np.float32)]))
    tokens, parent, states, bs = dec.step(1, tokens, tagged, bs)
    par = np.asarray(parent)[0]
    got = np.asarray(states).reshape(2, HID)[:, 0]
    want = np.where(par == 0, 10.0, 20.0)
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------- e2e


def _numpy_beam_search(cell, start, end, K, B, T):
    """Mirror of BeamSearchDecoder + gather_tree in plain numpy.
    ``cell(tokens, h) -> (logits, h2)`` must be STATELESS — the beam
    reorder below has to reach the state it consumes next step."""
    h = np.zeros((B * K, HID), np.float32)
    tokens = np.full((B * K,), start, np.int64)
    lp = np.where(np.arange(K)[None, :] == 0, 0.0, -1e9) * np.ones((B, 1))
    fin = np.zeros((B, K), bool)
    step_toks, step_pars = [], []
    for _ in range(T):
        logits, h = cell(tokens, h)
        h = np.asarray(h)
        # fp32 log-softmax, matching the decoder's in-graph math (a
        # float64 reference flips near-tied beams)
        logits = logits.astype(np.float32)
        m = logits.max(-1, keepdims=True)
        lsm = (logits - m) - np.log(
            np.sum(np.exp(logits - m), -1, keepdims=True,
                   dtype=np.float32))
        lsm = lsm.astype(np.float32).reshape(B, K, VOCAB)
        end_only = np.full((VOCAB,), -1e9)
        end_only[end] = 0.0
        lsm = np.where(fin[..., None], end_only[None, None, :], lsm)
        total = lp[..., None] + lsm
        flat = total.reshape(B, K * VOCAB)
        top = np.argsort(-flat, axis=1, kind="stable")[:, :K]
        lp = np.take_along_axis(flat, top, axis=1)
        parent = top // VOCAB
        tok = top % VOCAB
        fin = np.take_along_axis(fin, parent, axis=1) | (tok == end)
        # reorder states by parent
        h = h.reshape(B, K, HID)
        h = np.take_along_axis(h, parent[..., None], axis=1)
        h = h.reshape(B * K, HID)
        step_toks.append(tok)
        step_pars.append(parent)
        tokens = tok.reshape(-1)
        if fin.all():
            break
    # gather_tree backtrace
    Tn = len(step_toks)
    beams = np.broadcast_to(np.arange(K), (B, K)).copy()
    out = np.zeros((Tn, B, K), np.int64)
    for t in range(Tn - 1, -1, -1):
        out[t] = np.take_along_axis(step_toks[t], beams, axis=-1)
        beams = np.take_along_axis(step_pars[t], beams, axis=-1)
    return out, lp


def test_dynamic_decode_matches_numpy_reference():
    paddle.seed(0)
    cell = LinearTanhCell(seed=3)
    B, K, T, start, end = 2, 3, 7, 0, 5

    def np_cell(tokens, h):
        logits, h2 = cell(Tensor(np.asarray(tokens, np.int64)),
                          Tensor(h))
        return (np.asarray(logits.numpy()).astype(np.float32),
                np.asarray(h2.numpy()))

    ref_ids, ref_scores = _numpy_beam_search(np_cell, start, end, K, B, T)

    cell2 = LinearTanhCell(seed=3)
    dec = BeamSearchDecoder(cell2, start_token=start, end_token=end,
                            beam_size=K)
    init = Tensor(np.zeros((B, HID), np.float32))
    ids, scores, lengths = dynamic_decode(dec, init, max_step_num=T,
                                          return_length=True)
    got = np.asarray(ids.numpy())            # [B, T', K]
    assert got.shape[0] == B and got.shape[2] == K
    ref_bt = np.transpose(ref_ids, (1, 0, 2))  # [B, T', K]
    assert got.shape == ref_bt.shape
    np.testing.assert_array_equal(got, ref_bt)
    np.testing.assert_allclose(np.asarray(scores.numpy()), ref_scores,
                               rtol=1e-4, atol=1e-4)
    # lengths: first end_token position + 1 (or T)
    full = got
    for b in range(B):
        for k in range(K):
            seq = full[b, :, k]
            hits = np.nonzero(seq == end)[0]
            want = hits[0] + 1 if hits.size else full.shape[1]
            assert int(np.asarray(lengths.numpy())[b, k]) == want


def test_dynamic_decode_time_major_and_stop():
    cell = LinearTanhCell(seed=1)
    dec = BeamSearchDecoder(cell, start_token=0, end_token=5, beam_size=2)
    init = Tensor(np.zeros((1, HID), np.float32))
    ids_tm, _ = dynamic_decode(dec, init, max_step_num=4,
                               output_time_major=True)
    cell2 = LinearTanhCell(seed=1)
    dec2 = BeamSearchDecoder(cell2, start_token=0, end_token=5,
                             beam_size=2)
    ids_bm, _ = dynamic_decode(dec2, init, max_step_num=4)
    np.testing.assert_array_equal(
        np.transpose(np.asarray(ids_tm.numpy()), (1, 0, 2)),
        np.asarray(ids_bm.numpy()))
