"""Fleet serving router (ISSUE 19): the circuit-breaker state machine
(threshold trip, full-jitter backoff bounds, half-open single-probe
semantics, close-on-success), health-scored admission over live
engines, zero-drop drain re-homing, bounded re-routes, deadline
propagation across placements, the ``serve.router.*`` metrics/events,
the ``PADDLE_ROUTER_*`` env knobs, and the ``/router`` telemetry
endpoint. Chaos-grade fault injection (wedged replicas, injected
admission failures, SIGTERM rolling deploys) lives in
test_chaos_router.py."""
import json
import random
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flight_recorder, monitor
from paddle_tpu.core.telemetry_server import TelemetryServer
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.jit.compile_cache import ExecutableStore
from paddle_tpu.models.gpt import gpt
from paddle_tpu.profiler import metrics
from paddle_tpu.serving import (CircuitBreaker, FleetRouter, QueueFull,
                                RequestFailed, RequestParams,
                                RequestStatus, ServingEngine)
from paddle_tpu.serving.router import (BREAKER_CLOSED, BREAKER_HALF_OPEN,
                                       BREAKER_OPEN)


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = gpt("test-tiny")
    m.eval()
    return m


def _spec():
    return [paddle.to_tensor(np.zeros((2, 12), np.int32))]


def _config(m, *, max_new=8, buckets=(16,), max_batch=2, **serving_kw):
    cfg = (Config().from_layer(m, _spec())
           .enable_generation(max_new_tokens=max_new,
                              prefill_buckets=buckets,
                              max_batch=max_batch))
    cfg.enable_serving(**serving_kw)
    return cfg


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    """One ExecutableStore for every engine this module builds: the
    first engine compiles the program set, every sibling deserializes."""
    return ExecutableStore(str(tmp_path_factory.mktemp("router_exe")))


@pytest.fixture(scope="module")
def reference(tiny_gpt):
    pred = create_predictor(_config(tiny_gpt, max_batch=1))
    return lambda p: pred.generate([p], max_new_tokens=8)[0]


def _engine(tiny_gpt, store, **kw):
    kw.setdefault("max_batch", 1)
    kw.setdefault("max_queue", 4)
    return ServingEngine(_config(tiny_gpt, **kw), poll_every=1,
                         executable_store=store)


def _counter(name):
    snap = metrics.snapshot().get(name)
    return int(snap["value"]) if snap else 0


# --------------------------------------------- breaker state machine


class TestCircuitBreaker:
    def _mk(self, **kw):
        self.now = [0.0]
        kw.setdefault("rng", random.Random(7))
        return CircuitBreaker(clock=lambda: self.now[0], **kw)

    def test_opens_at_threshold_only_on_consecutive(self):
        b = self._mk(threshold=3)
        assert b.record_failure() is None
        assert b.record_failure() is None
        assert b.record_success() is False     # streak broken
        assert b.failures == 0
        assert b.record_failure() is None
        assert b.record_failure() is None
        back = b.record_failure()              # third consecutive
        assert back is not None and b.state == BREAKER_OPEN
        assert not b.admissible()

    def test_backoff_full_jitter_bounds(self):
        # every trip draws uniform[0, min(cap, base * 2^trips)): the
        # store-client idiom, so N routers don't re-stampede in step
        base, cap = 0.05, 2.0
        draws = []
        for seed in range(40):
            b = self._mk(threshold=1, base_s=base, cap_s=cap,
                         rng=random.Random(seed))
            trips = 0
            for _ in range(8):
                bound = min(cap, base * (2 ** trips))
                assert b.backoff_bound() == pytest.approx(bound)
                back = b.record_failure()      # closed->open...
                assert 0.0 <= back < bound or (bound == 0 and back == 0)
                draws.append(back)
                trips += 1
                self.now[0] = b.open_until     # serve the backoff
                assert b.admissible()          # ...half-open
                b.begin()                      # probe fails again
        assert len(set(draws)) > 20            # jitter actually varies

    def test_half_open_admits_exactly_one_probe(self):
        b = self._mk(threshold=1, base_s=0.5, cap_s=0.5)
        b.record_failure()
        assert b.state == BREAKER_OPEN
        assert not b.admissible()              # backoff not served
        self.now[0] = b.open_until + 0.001
        assert b.admissible() and b.state == BREAKER_HALF_OPEN
        b.begin()
        assert not b.admissible()              # ONE probe in flight
        assert b.record_success() is True      # the close transition
        assert b.state == BREAKER_CLOSED and b.trips == 0
        assert b.admissible()

    def test_probe_failure_reopens_with_longer_bound(self):
        b = self._mk(threshold=2, base_s=0.1, cap_s=10.0)
        b.record_failure(), b.record_failure()
        assert b.state == BREAKER_OPEN and b.trips == 1
        self.now[0] = b.open_until
        assert b.admissible()
        b.begin()
        back = b.record_failure()              # probe failure: no grace
        assert back is not None
        assert b.state == BREAKER_OPEN and b.trips == 2
        assert b.backoff_bound() == pytest.approx(0.4)  # 0.1 * 2^2

    def test_threshold_validated(self):
        with pytest.raises(ValueError, match="threshold"):
            CircuitBreaker(threshold=0)


# ----------------------------------------------------- env knobs


def test_env_knobs(tiny_gpt, store, monkeypatch):
    eng = _engine(tiny_gpt, store)
    monkeypatch.setenv("PADDLE_ROUTER_MAX_REROUTES", "5")
    monkeypatch.setenv("PADDLE_ROUTER_BREAKER_THRESHOLD", "not-a-number")
    monkeypatch.setenv("PADDLE_ROUTER_BREAKER_BASE_S", "0.25")
    r = FleetRouter([eng])
    assert r.max_reroutes == 5
    assert r.breaker_threshold == 3        # garbage -> default, recorded
    assert r.breaker_base_s == 0.25
    # explicit kwargs beat the environment
    r2 = FleetRouter([eng], max_reroutes=1, breaker_base_s=0.5)
    assert r2.max_reroutes == 1 and r2.breaker_base_s == 0.5
    eng.shutdown()


# ----------------------------------------------------- admission scoring


def test_score_counts_chunked_prefill_backlog():
    """ISSUE-20: a replica grinding through a chunked prefill scores
    below an otherwise-identical idle peer — every outstanding chunk
    steals a scheduler iteration from decode, so the backlog weighs
    exactly like queued requests in the divisor."""
    base = {"ready": True, "free_tokens": 100, "queue_depth": 2}
    busy = dict(base, prefill_chunks_queued=6)
    assert FleetRouter._score(busy) < FleetRouter._score(base)
    assert FleetRouter._score(busy) == \
        FleetRouter._score(dict(base, queue_depth=8))
    # absent / zero field (engine without chunking): score unchanged
    assert FleetRouter._score(dict(base, prefill_chunks_queued=0)) == \
        FleetRouter._score(base)
    assert FleetRouter._score(dict(base, ready=False,
                                   prefill_chunks_queued=6)) == 0.0


# ------------------------------------------------- routing over engines


def test_routes_complete_bitwise(tiny_gpt, store, reference):
    """Traffic through the router completes bitwise-equal to the
    sequential predictor; admissions land on BOTH replicas (the
    queue-depth divisor spreads score ties)."""
    engines = {"a": _engine(tiny_gpt, store), "b": _engine(tiny_gpt, store)}
    router = FleetRouter(engines, seed=0)
    monitor.enable()
    try:
        a0 = _counter("serve.router.admissions")
        rng = np.random.RandomState(2)
        prompts = [rng.randint(0, 512, 3 + i).astype(np.int32)
                   for i in range(4)]
        handles = [router.submit(p) for p in prompts]
        homes = {h.replica for h in handles}
        assert homes == {"a", "b"}
        for h, p in zip(handles, prompts):
            np.testing.assert_array_equal(h.result(timeout=120),
                                          reference(p))
            assert h.status is RequestStatus.COMPLETED
            assert h.done()
        assert router.stats["admissions"] == 4
        assert router.stats["reroutes"] == 0
        assert _counter("serve.router.admissions") - a0 == 4
        assert _counter("serve.router.admissions{replica=a}") > 0
        assert _counter("serve.router.admissions{replica=b}") > 0
    finally:
        monitor.disable()
        router.shutdown()
        for e in engines.values():
            e.shutdown()


def test_drain_rehomes_queued_work(tiny_gpt, store, reference):
    """The zero-drop core: draining a replica REJECTS its queued work
    with the structured "shutdown" reason, and the handles re-home onto
    the survivor — no caller ever sees the drain."""
    engines = {"a": _engine(tiny_gpt, store, max_queue=8),
               "b": _engine(tiny_gpt, store, max_queue=8)}
    router = FleetRouter(engines, seed=0)
    flight_recorder.configure(capacity=256, on=True)
    try:
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 512, 4 + i).astype(np.int32)
                   for i in range(4)]
        handles = [router.submit(p) for p in prompts]
        victim = "a" if any(h.replica == "a" for h in handles) else "b"
        doomed = [h for h in handles if h.replica == victim]
        assert doomed
        router.drain_replica(victim)
        for h, p in zip(handles, prompts):
            np.testing.assert_array_equal(h.result(timeout=120),
                                          reference(p))
        survivor = "b" if victim == "a" else "a"
        for h in doomed:
            assert h.replica == survivor
            assert h.hops and h.hops[-1] == (victim, "shutdown")
        assert router.stats["rehomed"] == len(doomed)
        assert router.stats["reroutes"] >= len(doomed)
        kinds = [(k, f) for _, k, f in flight_recorder.events()
                 if k.startswith("serve.router.")]
        assert any(k == "serve.router.drain" and f["replica"] == victim
                   for k, f in kinds)
        reroutes = [f for k, f in kinds if k == "serve.router.reroute"]
        assert len(reroutes) == len(doomed)
        assert all(f["src"] == victim and f["dst"] == survivor
                   and f["reason"] == "shutdown" for f in reroutes)
    finally:
        flight_recorder.configure(
            capacity=flight_recorder.DEFAULT_CAPACITY, on=True)
        router.shutdown()
        for e in engines.values():
            e.shutdown()


def test_all_replicas_saturated_rejects(tiny_gpt, store):
    """When NO replica can admit, submit() raises QueueFull carrying
    the aggregated reason and the already-terminal handle — the same
    contract the single-engine front door gives its callers."""
    eng = _engine(tiny_gpt, store, max_queue=1)
    router = FleetRouter({"only": eng}, seed=0)
    monitor.enable()
    try:
        first = router.submit([1, 2, 3])       # queue now at its bound
        with pytest.raises(QueueFull) as ei:
            router.submit([4, 5])
        rr = ei.value.request
        assert rr is not None and rr.done()
        assert rr.status is RequestStatus.REJECTED
        with pytest.raises(RequestFailed):
            rr.result(timeout=1)
        assert router.stats["rejected"] == 1
        assert _counter("serve.router.rejected") >= 1
        assert first.result(timeout=120).size == 8
    finally:
        monitor.disable()
        router.shutdown()
        eng.shutdown()


def test_reroute_budget_bounds_rehoming(tiny_gpt, store):
    """max_reroutes=0: a drain rejection surfaces to the caller instead
    of re-homing — the budget is a hard bound."""
    engines = {"a": _engine(tiny_gpt, store), "b": _engine(tiny_gpt, store)}
    router = FleetRouter(engines, max_reroutes=0, seed=0)
    try:
        h = router.submit([1, 2, 3])
        router.drain_replica(h.replica)
        with pytest.raises(RequestFailed, match="shutdown"):
            h.result(timeout=30)
        assert router.stats["rehomed"] == 0
    finally:
        router.shutdown()
        for e in engines.values():
            e.shutdown()


def test_deadline_propagates_remaining_budget(tiny_gpt, store):
    """A re-routed request's deadline is the REMAINING budget from the
    original submit, never a fresh window."""
    eng = _engine(tiny_gpt, store)
    now = [1000.0]
    router = FleetRouter({"a": eng}, clock=lambda: now[0], seed=0)
    try:
        h = router.submit([1, 2, 3], RequestParams(deadline_s=30.0))
        assert h.deadline == pytest.approx(1030.0)
        now[0] += 12.5
        p = router._params_for(h)
        assert p.deadline_s == pytest.approx(17.5)
        now[0] += 40.0                          # budget exhausted
        assert router._params_for(h).deadline_s == 0.0
        assert not router._reroutable(h)        # never re-placed late
        h.result(timeout=120)
    finally:
        router.shutdown()
        eng.shutdown()


def test_half_open_probe_routes_to_recovering_replica(tiny_gpt, store):
    """A half-open replica gets the NEXT request as its single probe
    even when a healthy peer outscores it; the probe's success closes
    the breaker (event + gauge asserted)."""
    engines = {"a": _engine(tiny_gpt, store), "b": _engine(tiny_gpt, store)}
    router = FleetRouter(engines, breaker_threshold=1,
                         breaker_base_s=0.0, breaker_cap_s=0.0, seed=0)
    monitor.enable()
    flight_recorder.configure(capacity=256, on=True)
    try:
        rec = router._replicas["a"]
        with router._lock:
            router._note_failure(rec, "test")
        assert rec.breaker.state == BREAKER_OPEN
        assert router.stats["breaker_trips"] == 1
        assert _counter("serve.router.breaker.trips{replica=a}") == 1
        # zero backoff: immediately admissible as HALF_OPEN probe
        h = router.submit([1, 2, 3])
        assert h.replica == "a"                # probe outranks score
        assert rec.breaker.probe_in_flight
        assert h.result(timeout=120).size == 8
        assert rec.breaker.state == BREAKER_CLOSED
        kinds = [k for _, k, _ in flight_recorder.events()]
        assert "serve.router.breaker_open" in kinds
        assert "serve.router.breaker_probe" in kinds
        assert "serve.router.breaker_close" in kinds
    finally:
        flight_recorder.configure(
            capacity=flight_recorder.DEFAULT_CAPACITY, on=True)
        monitor.disable()
        router.shutdown()
        for e in engines.values():
            e.shutdown()


def test_client_error_not_rerouted(tiny_gpt, store):
    """A prompt no compiled bucket holds is a CLIENT error — identical
    on every replica, so it surfaces immediately instead of burning
    re-routes against a homogeneous fleet."""
    engines = [_engine(tiny_gpt, store), _engine(tiny_gpt, store)]
    router = FleetRouter(engines, seed=0)
    try:
        with pytest.raises(ValueError):
            router.submit(np.arange(100, dtype=np.int32))  # > bucket 16
        assert router.stats["reroutes"] == 0
    finally:
        router.shutdown()
        for e in engines:
            e.shutdown()


# --------------------------------------------------- telemetry surface


def test_router_endpoint(tiny_gpt, store):
    eng = _engine(tiny_gpt, store)
    router = FleetRouter({"a": eng}, seed=0)
    server = TelemetryServer(port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/router", timeout=10) as r:
            assert r.status == 404             # nothing attached yet
    except urllib.error.HTTPError as e:
        assert e.code == 404
    try:
        server.attach_router(router)
        h = router.submit([1, 2, 3])
        with urllib.request.urlopen(f"http://127.0.0.1:{server.port}"
                                    "/router", timeout=10) as r:
            assert r.status == 200
            doc = json.loads(r.read().decode())
        assert doc["submitted"] == 1 and doc["admissions"] == 1
        (row,) = doc["replicas"]
        assert row["name"] == "a"
        assert row["breaker"] == BREAKER_CLOSED
        assert "score" in row and "ready" in row["health"]
        assert doc["breaker"]["threshold"] == router.breaker_threshold
        h.result(timeout=120)
    finally:
        server.stop()
        router.shutdown()
        eng.shutdown()
