"""Model-family tests: ERNIE/BERT pretraining and ViT (SURVEY.md §4:
the reference exercises model fixtures end-to-end in tests/book/-style
train-to-convergence runs; here one optimizer step + finiteness)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer  # noqa: F401


# (fleet.init mesh leakage is handled by conftest's process-global
# _restore_hybrid_mesh autouse fixture)


def test_ernie_forward_and_loss():
    paddle.seed(0)
    from paddle_tpu.models.ernie import ernie
    model = ernie("test-tiny")
    b, s = 2, 16
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 512, (b, s)).astype(np.int32))
    tt = paddle.to_tensor(np.zeros((b, s), dtype=np.int32))
    mask = paddle.to_tensor(np.ones((b, s), dtype=np.int32))
    mlm_logits, sop_logits = model(ids, tt, mask)
    assert tuple(mlm_logits.shape) == (b, s, 512)
    assert tuple(sop_logits.shape) == (b, 2)
    mlm_labels = rng.randint(0, 512, (b, s)).astype(np.int64)
    mlm_labels[:, s // 2:] = -100  # unmasked positions ignored
    loss = model.loss(
        (mlm_logits, sop_logits),
        (paddle.to_tensor(mlm_labels),
         paddle.to_tensor(rng.randint(0, 2, (b,)).astype(np.int64))))
    assert np.isfinite(float(loss))


def test_ernie_padding_mask_blocks_attention():
    """Padded positions must not change non-padded outputs."""
    paddle.seed(0)
    from paddle_tpu.models.ernie import ernie
    model = ernie("test-tiny", dropout=0.0)
    model.eval()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 512, (1, 8)).astype(np.int32)
    mask = np.ones((1, 8), dtype=np.int32)
    mask[0, 6:] = 0
    out1, _ = model.ernie(paddle.to_tensor(ids), None,
                          paddle.to_tensor(mask))
    ids2 = ids.copy()
    ids2[0, 6:] = rng.randint(0, 512, (2,))  # change only padded tokens
    out2, _ = model.ernie(paddle.to_tensor(ids2), None,
                          paddle.to_tensor(mask))
    np.testing.assert_allclose(np.asarray(out1.numpy())[0, :6],
                               np.asarray(out2.numpy())[0, :6],
                               rtol=2e-5, atol=2e-5)


def test_ernie_train_step_decreases_loss():
    paddle.seed(0)
    from paddle_tpu.models.ernie import ernie
    model = ernie("test-tiny")
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 512, (2, 16)).astype(np.int32))
    labels = (paddle.to_tensor(
        rng.randint(0, 512, (2, 16)).astype(np.int64)),
        paddle.to_tensor(rng.randint(0, 2, (2,)).astype(np.int64)))

    def step():
        out = model(ids)
        loss = model.loss(out, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss)

    first = step()
    for _ in range(4):
        last = step()
    assert last < first


def test_ernie_sequence_classification():
    paddle.seed(0)
    from paddle_tpu.models.ernie import (CONFIGS,
                                         ErnieForSequenceClassification)
    model = ErnieForSequenceClassification(CONFIGS["test-tiny"],
                                           num_classes=3)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 512, (2, 8)).astype(np.int32))
    logits = model(ids)
    assert tuple(logits.shape) == (2, 3)


def test_vit_forward_and_step():
    paddle.seed(0)
    from paddle_tpu.models.vit import vit
    model = vit("test-tiny")
    x = paddle.to_tensor(np.random.RandomState(0).randn(
        2, 3, 32, 32).astype(np.float32))
    logits = model(x)
    assert tuple(logits.shape) == (2, 10)
    labels = paddle.to_tensor(np.array([1, 7], dtype=np.int64))
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())

    def step():
        loss = nn.functional.cross_entropy(model(x), labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return float(loss)

    first = step()
    for _ in range(3):
        last = step()
    assert last < first


def test_ernie_distributed_step_tuple_labels():
    """Pytree (tuple) labels must flow through DistributedTrainStep —
    regression for the _unwrap/_wrap top-level-only marshalling."""
    paddle.seed(0)
    from paddle_tpu.distributed import fleet
    from paddle_tpu.models.ernie import ernie
    strategy = fleet.DistributedStrategy(
        hybrid_configs={"mp_degree": 2})  # dp inferred to fill devices
    fleet.init(strategy=strategy)
    model = ernie("test-tiny")
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=model.parameters())
    step = fleet.DistributedTrainStep(
        model, opt, lambda out, lab: model.loss(out, lab))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 512, (4, 16)).astype(np.int32))
    labels = (paddle.to_tensor(
        rng.randint(0, 512, (4, 16)).astype(np.int64)),
        paddle.to_tensor(rng.randint(0, 2, (4,)).astype(np.int64)))
    loss = step(ids, labels)
    assert np.isfinite(float(loss))


def test_ernie_state_dict_roundtrip(tmp_path):
    paddle.seed(0)
    from paddle_tpu.models.ernie import ernie
    model = ernie("test-tiny")
    path = str(tmp_path / "ernie.pdparams")
    paddle.save(model.state_dict(), path)
    model2 = ernie("test-tiny")
    model2.set_state_dict(paddle.load(path))
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 512, (1, 8)).astype(np.int32))
    model.eval(), model2.eval()
    a, _ = model(ids)
    b, _ = model2(ids)
    np.testing.assert_allclose(np.asarray(a.numpy()),
                               np.asarray(b.numpy()), rtol=1e-6)


@pytest.mark.parametrize("chunk", [7, 32])
def test_fused_lm_loss_matches_plain(chunk):
    """Chunked fused LM-head+CE == plain logits+CE (the HBM fix for
    long-seq configs; BASELINE.md r2). Also trains through TrainStep.
    chunk=7 exercises the remat scan; chunk=32 >= seq-1 exercises the
    r4 single-chunk save-logits fast path."""
    from paddle_tpu.models.gpt import gpt
    paddle.seed(0)
    plain = gpt("test-tiny")
    plain.eval()
    paddle.seed(0)
    fused = gpt("test-tiny", fused_lm_loss=True, lm_loss_chunk=chunk)
    fused.eval()
    ids = np.random.RandomState(0).randint(0, 512, (2, 19)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))
    l_plain = float(plain.loss(plain(x), y))
    l_fused = float(fused.loss(fused(x), y))
    assert abs(l_plain - l_fused) < 2e-3, (l_plain, l_fused)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=fused.parameters())
    step = paddle.jit.TrainStep(fused, opt,
                                lambda out, lab: fused.loss(out, lab))
    l0 = float(step(x, y))
    for _ in range(3):
        ln = float(step(x, y))
    assert ln < l0


@pytest.mark.parametrize("chunk", [8, 16])
def test_fused_lm_loss_head_gradient_matches_plain(chunk):
    """Regression: the fused path must propagate the LM-head/wte weight
    gradient (it was captured as a constant and silently dropped).
    chunk=8 is the remat scan, chunk=16 the single-chunk fast path."""
    from paddle_tpu.models.gpt import gpt
    ids = np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))

    def wte_grad(fused):
        paddle.seed(0)
        m = gpt("test-tiny", fused_lm_loss=fused, lm_loss_chunk=chunk)
        m.eval()
        loss = m.loss(m(x), y)
        loss.backward()
        return np.asarray(m.gpt.embed.wte.weight.grad.numpy())

    g_plain = wte_grad(False)
    g_fused = wte_grad(True)
    np.testing.assert_allclose(g_fused, g_plain, rtol=1e-3, atol=1e-5)


def test_fused_lm_loss_budget_override_forces_remat(monkeypatch):
    """The save-logits budget gate must actually steer the path: an
    over-budget config takes the remat scan (jax.checkpoint fires), an
    in-budget one takes the fast path (no checkpoint) — and both match
    the plain path numerically (loss AND head gradient)."""
    import jax as _jax
    from paddle_tpu.models.gpt import gpt
    ids = np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32)
    x = paddle.to_tensor(ids)
    y = paddle.to_tensor(ids.astype(np.int64))

    ckpt_calls = []
    orig_ckpt = _jax.checkpoint

    def spy(fn, *a, **kw):
        ckpt_calls.append(fn)
        return orig_ckpt(fn, *a, **kw)

    monkeypatch.setattr(_jax, "checkpoint", spy)

    def run(fused, **kw):
        paddle.seed(0)
        m = gpt("test-tiny", fused_lm_loss=fused, **kw)
        m.eval()
        loss = m.loss(m(x), y)
        loss.backward()
        return float(loss), np.asarray(m.gpt.embed.wte.weight.grad.numpy())

    l_plain, g_plain = run(False)

    ckpt_calls.clear()
    l_gated, g_gated = run(True, lm_loss_chunk=16,
                           lm_loss_save_logits_budget=1)
    assert ckpt_calls, "over-budget config must take the remat scan"
    assert abs(l_plain - l_gated) < 2e-3, (l_plain, l_gated)
    np.testing.assert_allclose(g_gated, g_plain, rtol=1e-3, atol=1e-5)

    ckpt_calls.clear()
    l_fast, g_fast = run(True, lm_loss_chunk=16)  # default budget: fits
    assert not ckpt_calls, "in-budget config must skip the remat scan"
    assert abs(l_plain - l_fast) < 2e-3, (l_plain, l_fast)
    np.testing.assert_allclose(g_fast, g_plain, rtol=1e-3, atol=1e-5)


def test_fused_lm_loss_pipeline_loss_fn_still_works():
    # gpt_pipe builds loss_fn with self=None; the fused branch must not
    # dereference cfg on None
    from paddle_tpu.models.gpt import GPTForCausalLM
    logits = paddle.randn([2, 8, 16])
    labels = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 16, (2, 8)).astype(np.int64))
    val = GPTForCausalLM.loss(None, logits, labels)
    assert np.isfinite(float(val))


@pytest.mark.slow  # ~8s on CPU; GPT fused-LM-loss parity stays tier-1
def test_ernie_fused_mlm_loss_matches_plain():
    """Gathered-position fused MLM == plain dense MLM loss AND grads
    (BASELINE config #3 head optimization)."""
    from paddle_tpu.models.ernie import ernie
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (2, 32)).astype(np.int32)
    mlm = np.full((2, 32), -100, np.int64)
    pos = rng.choice(32, 6, replace=False)
    mlm[:, pos] = ids[:, pos]
    x = paddle.to_tensor(ids)
    y = (paddle.to_tensor(mlm),
         paddle.to_tensor(rng.randint(0, 2, (2,)).astype(np.int64)))

    def run(fused):
        paddle.seed(0)
        m = ernie("test-tiny", fused_mlm_loss=fused, max_predictions=16)
        m.eval()
        loss = m.loss(m(x), y)
        loss.backward()
        return float(loss), np.asarray(
            m.ernie.embeddings.word_embeddings.weight.grad.numpy())

    lp, gp = run(False)
    lf, gf = run(True)
    assert abs(lp - lf) < 2e-3
    np.testing.assert_allclose(gf, gp, rtol=1e-3, atol=1e-5)
    # trains through TrainStep too
    paddle.seed(0)
    m = ernie("test-tiny", fused_mlm_loss=True, max_predictions=16)
    opt = optimizer.AdamW(learning_rate=1e-3,
                          parameters=m.parameters())
    step = paddle.jit.TrainStep(m, opt,
                                lambda out, lab: m.loss(out, lab))
    l0 = float(step(x, y))
    for _ in range(3):
        ln = float(step(x, y))
    assert ln < l0


@pytest.mark.slow  # ~4s; fused-resnet parity suite stays tier-1
def test_resnet_nhwc_and_s2d_parity():
    """data_format=NHWC and the space-to-depth stem are numerically
    equal to the NCHW reference path (same state_dict)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.resnet import resnet18
    paddle.seed(0)
    m1 = resnet18(num_classes=6)
    m2 = resnet18(num_classes=6, data_format="NHWC",
                  stem_space_to_depth=True)
    m2.set_state_dict(m1.state_dict())
    m1.eval()
    m2.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 64, 64).astype("float32"))
    np.testing.assert_allclose(np.asarray(m1(x).data),
                               np.asarray(m2(x).data),
                               rtol=2e-3, atol=2e-3)


def test_fuse_conv_bn_eval_parity():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.resnet import resnet18
    from paddle_tpu.nn.utils import fuse_conv_bn
    paddle.seed(0)
    m = resnet18(num_classes=5)
    m.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 3, 32, 32).astype("float32"))
    for _ in range(2):
        m(x)  # populate running stats
    m.eval()
    ref = np.asarray(m(x).data)
    fuse_conv_bn(m)
    got = np.asarray(m(x).data)
    # tolerance covers the CPU backend's relaxed conv precision; at
    # jax_default_matmul_precision=highest the max diff is 2.4e-6
    np.testing.assert_allclose(got, ref, rtol=5e-3, atol=5e-3)


def test_same_dtype_astype_keeps_tape():
    """float->float astype must stay on the autograd tape (the AMP
    `logits.astype("float32")` pattern); int casts detach."""
    import numpy as np
    import paddle_tpu as paddle
    w = paddle.Parameter(np.ones((2,), np.float32))
    z = (w * 2.0).astype("float32")
    z.sum().backward()
    np.testing.assert_allclose(np.asarray(w.grad.data), [2.0, 2.0])
    w2 = paddle.Parameter(np.ones((2,), np.float32))
    zb = w2.astype("bfloat16").astype("float32") * 3
    zb.sum().backward()
    np.testing.assert_allclose(np.asarray(w2.grad.data), [3.0, 3.0])
    assert paddle.cast(w, "int32").stop_gradient
    assert w.astype("bool").stop_gradient


def test_fuse_conv_bn_s2d_and_state_dict_roundtrip():
    """Folding must stay correct through the space-to-depth stem (the
    folded bias rides the repacked conv) and round-trip state_dict."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.resnet import resnet18
    from paddle_tpu.nn.utils import fuse_conv_bn
    paddle.seed(0)
    m = resnet18(num_classes=5, data_format="NHWC",
                 stem_space_to_depth=True)
    m.train()
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 3, 32, 32).astype("float32"))
    for _ in range(2):
        m(x)
    m.eval()
    ref = np.asarray(m(x).data)
    fuse_conv_bn(m)
    got = np.asarray(m(x).data)
    np.testing.assert_allclose(got, ref, rtol=5e-3, atol=5e-3)
    sd = m.state_dict()
    assert "conv1.bias" in sd  # folded bias is a registered parameter
    m2 = resnet18(num_classes=5, data_format="NHWC",
                  stem_space_to_depth=True)
    fuse_conv_bn(m2)  # create the bias slots, then load
    m2.set_state_dict(sd)
    m2.eval()
    np.testing.assert_allclose(np.asarray(m2(x).data), got,
                               rtol=1e-5, atol=1e-5)
